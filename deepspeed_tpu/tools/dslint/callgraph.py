"""Project-wide call graph over stdlib ``ast`` — the interprocedural
substrate DS002 (host-sync taint), DS009 (offline purity) and
``dslint --changed`` share.

Same discipline as the rest of dslint: stdlib-only, no imports of the
code under analysis, built once per run from the already-parsed
``FileContext`` trees and memoized per source snapshot (the lint suite
re-lints the whole package several times per session; the graph is paid
for once).

Resolution strategy (deliberately conservative — precision where the
codebase's idioms make it cheap, and *no* finding is ever produced from
a guess):

  * module functions & imports    bare names resolve through the file's
                                  own defs, then ``import``/``from``
                                  aliases into project modules
  * self/cls method calls         ``self.m()`` resolves within the
                                  enclosing class (bases included when
                                  they resolve in-project)
  * class-attr-bound callables    ``self.x = ClassName(...)`` (any
                                  method), ``self.x = some_func``,
                                  annotated params assigned to attrs
                                  (``def __init__(self, e: "T")`` +
                                  ``self.e = e``), and class-level
                                  ``x: T`` annotations type the receiver
  * local variables               ``x = ClassName(...)``, annotated
                                  locals/params
  * return types                  functions returning ``ClassName(...)``,
                                  a typed name, or carrying a return
                                  annotation propagate the receiver type
                                  through call chains (``get_tracer().
                                  instant(...)``)
  * protocols                     ``with`` resolves ``__enter__``/
                                  ``__exit__`` of the context's type;
                                  ``len``/``next``/``iter``/``bool`` on a
                                  typed value resolve the dunder;
                                  property *reads* on a typed receiver
                                  resolve the getter
  * references                    a bare function/method used as a value
                                  (``Thread(target=self._worker)``,
                                  callbacks, ``getattr(x, "name")`` with
                                  a literal name) adds an edge — thread
                                  entry points stay inside the taint
  * nested defs                   an enclosing function gets an edge to
                                  every def nested in it (closures built
                                  on a hot path run on it)
  * fallback                     a method call on an *untyped* receiver
                                  resolves by unique method name across
                                  all project classes (up to
                                  ``_FALLBACK_CAP`` candidates — linking
                                  all of them over-approximates, which is
                                  safe for taint); beyond the cap the
                                  call is recorded as *unresolved* and
                                  degrades to a statistic, never a
                                  finding
"""

import ast
import builtins
import os
from typing import Dict, Iterable, List, Optional, Set, Tuple

__all__ = ["CallGraph", "FuncInfo", "ClassInfo", "build_graph",
           "get_callgraph", "own_body_nodes"]

_BUILTINS = frozenset(dir(builtins))

#: attribute calls on an untyped receiver resolve by method name when at
#: most this many project classes define the method
_FALLBACK_CAP = 3

#: builtin -> dunder protocol resolution on a typed argument
_PROTOCOL_BUILTINS = {"len": "__len__", "next": "__next__",
                      "iter": "__iter__", "bool": "__bool__",
                      "repr": "__repr__", "str": "__str__"}

#: method names on an *untyped* receiver that are overwhelmingly
#: dict/list/set/str/file traffic — treating them as project calls would
#: need a typed receiver anyway, so they resolve-external instead of
#: polluting the unresolved statistics
_STDLIB_METHODS = frozenset((
    "get", "items", "keys", "values", "append", "extend", "pop",
    "popitem", "setdefault", "update", "add", "discard", "remove",
    "clear", "copy", "sort", "reverse", "insert", "count", "index",
    "split", "rsplit", "join", "strip", "lstrip", "rstrip",
    "startswith", "endswith", "format", "encode", "decode", "lower",
    "upper", "replace", "lstat", "read", "readline", "write", "close",
    "flush", "seek", "item", "tolist", "astype", "reshape", "get_nowait",
    "put_nowait", "put", "acquire", "release", "wait", "notify",
    "notify_all", "set", "is_set", "total_seconds", "isoformat",
    "hexdigest", "digest", "groups", "group", "match", "search",
    "findall", "sub", "most_common", "popleft", "appendleft",
))

#: container accessor calls whose result carries the receiver's
#: (element-flattened) types through — ``self._handles.values()`` yields
#: whatever ``Dict[int, ReplicaHandle]`` flattened to
_CONTAINER_PASSTHROUGH = frozenset(
    ("values", "get", "pop", "copy", "setdefault"))

_FUNC_NODES = (ast.FunctionDef, ast.AsyncFunctionDef)


def own_body_nodes(fn: ast.AST) -> Iterable[ast.AST]:
    """Walk a function's body EXCLUDING nested function/class subtrees
    (each nested def is its own graph node; scanning it under the parent
    would double-report its sinks)."""
    stack = list(ast.iter_child_nodes(fn))
    while stack:
        node = stack.pop()
        if isinstance(node, _FUNC_NODES + (ast.ClassDef,)):
            continue
        yield node
        stack.extend(ast.iter_child_nodes(node))


class FuncInfo:
    __slots__ = ("key", "relpath", "qualname", "node", "cls")

    def __init__(self, key, relpath, qualname, node, cls):
        self.key = key                  # "relpath::qualname"
        self.relpath = relpath
        self.qualname = qualname        # "Class.method" / "func" / "f.inner"
        self.node = node
        self.cls = cls                  # enclosing ClassInfo key or None

    def __repr__(self):
        return f"<fn {self.key}>"


class ClassInfo:
    __slots__ = ("key", "relpath", "qualname", "node", "bases",
                 "methods", "attr_types", "attr_funcs", "properties")

    def __init__(self, key, relpath, qualname, node):
        self.key = key
        self.relpath = relpath
        self.qualname = qualname
        self.node = node
        self.bases: List[ast.expr] = list(node.bases)
        self.methods: Dict[str, str] = {}       # name -> func key
        self.attr_types: Dict[str, Set[str]] = {}   # self.x -> class keys
        self.attr_funcs: Dict[str, Set[str]] = {}   # self.x -> func keys
        self.properties: Set[str] = set()


class _Module:
    __slots__ = ("relpath", "modname", "tree", "imports", "functions",
                 "classes", "global_types", "global_funcs",
                 "internal_imports", "external_imports", "import_lines")

    def __init__(self, relpath, modname, tree):
        self.relpath = relpath
        self.modname = modname          # "deepspeed_tpu.runtime.engine"
        self.tree = tree
        # alias -> ("module", modname) | ("symbol", modname, name)
        self.imports: Dict[str, tuple] = {}
        self.functions: Dict[str, str] = {}     # top-level name -> func key
        self.classes: Dict[str, str] = {}       # top-level name -> class key
        self.global_types: Dict[str, Set[str]] = {}
        self.global_funcs: Dict[str, str] = {}
        self.internal_imports: Set[str] = set()     # module-level, project
        self.external_imports: Set[str] = set()     # top-level ext names
        self.import_lines: Dict[str, int] = {}      # target relpath -> line


class CallGraph:
    """Functions, call/reference edges, and the module import graph."""

    def __init__(self):
        self.functions: Dict[str, FuncInfo] = {}
        self.classes: Dict[str, ClassInfo] = {}
        self.modules: Dict[str, _Module] = {}       # relpath -> _Module
        self.edges: Dict[str, Set[str]] = {}
        self.edge_lines: Dict[Tuple[str, str], int] = {}
        # caller key -> [(line, text)] — dynamic calls that degrade to
        # statistics (NEVER findings)
        self.unresolved: Dict[str, List[Tuple[int, str]]] = {}
        self._reverse: Optional[Dict[str, Set[str]]] = None

    # -- structure ------------------------------------------------------
    def add_edge(self, caller: str, callee: str, line: int):
        if callee == caller:
            pass                        # self-recursion is still an edge
        self.edges.setdefault(caller, set()).add(callee)
        self.edge_lines.setdefault((caller, callee), line)
        self._reverse = None

    def callees(self, key: str) -> Set[str]:
        return self.edges.get(key, set())

    def reverse(self) -> Dict[str, Set[str]]:
        if self._reverse is None:
            rev: Dict[str, Set[str]] = {}
            for caller, outs in self.edges.items():
                for callee in outs:
                    rev.setdefault(callee, set()).add(caller)
            self._reverse = rev
        return self._reverse

    def resolve(self, path_suffix: str, qualname: str) -> Optional[str]:
        """Function key for (repo-path-suffix, qualname), or None."""
        for key, info in self.functions.items():
            if info.qualname == qualname and _path_matches(
                    info.relpath, path_suffix):
                return key
        return None

    def reachable_from(self, roots: Iterable[str],
                       prune: Iterable[str] = ()) -> Dict[str, Optional[str]]:
        """BFS closure over call edges: reached key -> predecessor key
        (None for roots). ``prune`` keys are reached but not expanded."""
        prune = set(prune)
        pred: Dict[str, Optional[str]] = {}
        queue = []
        for r in roots:
            if r not in pred:
                pred[r] = None
                queue.append(r)
        while queue:
            cur = queue.pop(0)
            if cur in prune:
                continue
            for nxt in sorted(self.edges.get(cur, ())):
                if nxt not in pred:
                    pred[nxt] = cur
                    queue.append(nxt)
        return pred

    def path_to(self, pred: Dict[str, Optional[str]], key: str) -> List[str]:
        out = [key]
        seen = {key}
        while pred.get(out[-1]) is not None:
            nxt = pred[out[-1]]
            if nxt in seen:
                break
            out.append(nxt)
            seen.add(nxt)
        return list(reversed(out))

    def stats(self) -> Dict[str, int]:
        return {
            "functions": len(self.functions),
            "classes": len(self.classes),
            "modules": len(self.modules),
            "edges": sum(len(v) for v in self.edges.values()),
            "unresolved_calls": sum(len(v)
                                    for v in self.unresolved.values()),
        }


def _path_matches(relpath: str, suffix: str) -> bool:
    relpath = relpath.replace(os.sep, "/")
    return relpath == suffix or relpath.endswith("/" + suffix)


# ----------------------------------------------------------------------
# builder
# ----------------------------------------------------------------------
def _module_name(relpath: str) -> str:
    p = relpath.replace(os.sep, "/")
    if p.endswith("/__init__.py"):
        p = p[: -len("/__init__.py")]
    elif p.endswith(".py"):
        p = p[:-3]
    return p.replace("/", ".")


class _Builder:
    def __init__(self, files):
        # files: iterable of (relpath, tree)
        self.g = CallGraph()
        self.by_modname: Dict[str, str] = {}        # modname -> relpath
        self.files = list(files)
        # method name -> class keys defining it (fallback resolution)
        self.method_index: Dict[str, List[str]] = {}
        self.class_by_name: Dict[str, List[str]] = {}
        self._return_types: Dict[str, Set[str]] = {}
        self._in_progress: Set[str] = set()

    # -- phase 1: index -------------------------------------------------
    def index(self):
        for relpath, tree in self.files:
            mod = _Module(relpath, _module_name(relpath), tree)
            self.g.modules[relpath] = mod
            self.by_modname[mod.modname] = relpath
            self._index_scope(mod, tree, prefix="", cls=None)
        for mod in self.g.modules.values():
            self._index_imports(mod)
        for cls in self.g.classes.values():
            for name in cls.methods:
                self.method_index.setdefault(name, []).append(cls.key)
            self.class_by_name.setdefault(
                cls.qualname.rsplit(".", 1)[-1], []).append(cls.key)

    def _index_scope(self, mod: _Module, node: ast.AST, prefix: str,
                     cls: Optional[ClassInfo]):
        for child in ast.iter_child_nodes(node):
            if isinstance(child, _FUNC_NODES):
                qn = f"{prefix}.{child.name}" if prefix else child.name
                key = f"{mod.relpath}::{qn}"
                info = FuncInfo(key, mod.relpath, qn, child,
                                cls.key if cls is not None else None)
                self.g.functions[key] = info
                if cls is not None:
                    cls.methods.setdefault(child.name, key)
                    if any(isinstance(d, ast.Name) and d.id == "property"
                           or isinstance(d, ast.Attribute)
                           and d.attr in ("getter", "setter", "deleter")
                           for d in child.decorator_list):
                        cls.properties.add(child.name)
                elif not prefix:
                    mod.functions.setdefault(child.name, key)
                self._index_scope(mod, child, qn, cls=None)
            elif isinstance(child, ast.ClassDef):
                qn = f"{prefix}.{child.name}" if prefix else child.name
                key = f"{mod.relpath}::{qn}"
                cinfo = ClassInfo(key, mod.relpath, qn, child)
                self.g.classes[key] = cinfo
                if not prefix:
                    mod.classes.setdefault(child.name, key)
                self._index_scope(mod, child, qn, cls=cinfo)
            else:
                self._index_scope(mod, child, prefix, cls)

    # -- phase 2: imports ----------------------------------------------
    def _index_imports(self, mod: _Module):
        pkg_parts = mod.modname.split(".")

        def note_internal(modname: str, lineno: int):
            rel = self.by_modname.get(modname)
            if rel is None:
                # "from a.b import name" where a.b is a package dir
                rel = self.by_modname.get(modname + ".__init__")
            if rel is not None:
                mod.internal_imports.add(rel)
                mod.import_lines.setdefault(rel, lineno)
                return True
            return False

        for node in self._module_level_stmts(mod.tree):
            if isinstance(node, ast.Import):
                for a in node.names:
                    mod.imports[a.asname or a.name.split(".")[0]] = \
                        ("module", a.name)
                    if not note_internal(a.name, node.lineno):
                        mod.external_imports.add(a.name.split(".")[0])
            elif isinstance(node, ast.ImportFrom):
                base = node.module or ""
                if node.level:          # relative import
                    anchor = pkg_parts[: len(pkg_parts) - node.level + (
                        1 if mod.relpath.endswith("__init__.py") else 0)]
                    base = ".".join(anchor + ([base] if base else []))
                for a in node.names:
                    dotted = f"{base}.{a.name}" if base else a.name
                    if dotted in self.by_modname:
                        mod.imports[a.asname or a.name] = ("module", dotted)
                        note_internal(dotted, node.lineno)
                    else:
                        mod.imports[a.asname or a.name] = \
                            ("symbol", base, a.name)
                        if not note_internal(base, node.lineno):
                            if base:
                                mod.external_imports.add(base.split(".")[0])
        self._index_lazy_imports(mod, pkg_parts)

    def _index_lazy_imports(self, mod: _Module, pkg_parts):
        """Imports inside function bodies register ALIASES only (so calls
        through closures resolve — ``make_sync_fn`` imports the comm
        facade lazily) — the import *graph* used by DS009 stays strictly
        module-level: a lazy import is exactly the idiom that keeps a
        module offline-pure."""
        top = {id(n) for n in self._module_level_stmts(mod.tree)}
        for node in ast.walk(mod.tree):
            if id(node) in top:
                continue
            if isinstance(node, ast.Import):
                for a in node.names:
                    mod.imports.setdefault(
                        a.asname or a.name.split(".")[0], ("module", a.name))
            elif isinstance(node, ast.ImportFrom):
                base = node.module or ""
                if node.level:
                    anchor = pkg_parts[: len(pkg_parts) - node.level + (
                        1 if mod.relpath.endswith("__init__.py") else 0)]
                    base = ".".join(anchor + ([base] if base else []))
                for a in node.names:
                    dotted = f"{base}.{a.name}" if base else a.name
                    if dotted in self.by_modname:
                        mod.imports.setdefault(
                            a.asname or a.name, ("module", dotted))
                    else:
                        mod.imports.setdefault(
                            a.asname or a.name, ("symbol", base, a.name))

    def _module_level_stmts(self, tree: ast.Module):
        """Module-level statements, descending into top-level ``try``/
        ``if`` (ImportError guards) but skipping ``TYPE_CHECKING`` blocks
        and all function/class bodies — import-graph purity is about what
        executes at import time."""
        stack: List[ast.stmt] = list(tree.body)
        while stack:
            node = stack.pop(0)
            if isinstance(node, _FUNC_NODES + (ast.ClassDef,)):
                continue
            if isinstance(node, ast.If):
                if "TYPE_CHECKING" in ast.dump(node.test):
                    continue
                stack = node.body + node.orelse + stack
                continue
            if isinstance(node, ast.Try):
                stack = (node.body + [s for h in node.handlers
                                      for s in h.body]
                         + node.orelse + node.finalbody + stack)
                continue
            yield node

    # -- phase 3: types -------------------------------------------------
    def infer_types(self):
        for mod in self.g.modules.values():
            for node in self._module_level_stmts(mod.tree):
                self._note_global_assign(mod, node)
        for cls in self.g.classes.values():
            self._infer_class_attrs(cls)

    def _note_global_assign(self, mod: _Module, node: ast.stmt):
        if isinstance(node, ast.Assign) and len(node.targets) == 1 \
                and isinstance(node.targets[0], ast.Name):
            name = node.targets[0].id
            types = self._constructed_types(mod, node.value)
            if types:
                mod.global_types.setdefault(name, set()).update(types)
            fn = self._value_function(mod, node.value)
            if fn:
                mod.global_funcs[name] = fn
        elif isinstance(node, ast.AnnAssign) \
                and isinstance(node.target, ast.Name):
            types = self._annotation_types(mod, node.annotation)
            if types:
                mod.global_types.setdefault(
                    node.target.id, set()).update(types)

    def _infer_class_attrs(self, cls: ClassInfo):
        mod = self.g.modules[cls.relpath]
        for stmt in cls.node.body:          # class-level annotations
            if isinstance(stmt, ast.AnnAssign) \
                    and isinstance(stmt.target, ast.Name):
                types = self._annotation_types(mod, stmt.annotation)
                if types:
                    cls.attr_types.setdefault(
                        stmt.target.id, set()).update(types)
        for mkey in cls.methods.values():
            fn = self.g.functions[mkey].node
            params = self._param_annotations(mod, fn)
            for node in own_body_nodes(fn):
                if isinstance(node, ast.AnnAssign):
                    attr = _self_attr(node.target)
                    if attr is not None:
                        types = self._annotation_types(mod, node.annotation)
                        if types:
                            cls.attr_types.setdefault(
                                attr, set()).update(types)
                    continue
                if not isinstance(node, ast.Assign):
                    continue
                for tgt in node.targets:
                    attr = _self_attr(tgt)
                    if attr is None:
                        continue
                    types = self._constructed_types(mod, node.value)
                    if not types and isinstance(node.value, ast.Name):
                        types = params.get(node.value.id, set())
                    if types:
                        cls.attr_types.setdefault(attr, set()).update(types)
                    f = self._value_function(mod, node.value,
                                             cls_for_self=cls)
                    if f:
                        cls.attr_funcs.setdefault(attr, set()).add(f)

    def _param_annotations(self, mod: _Module, fn) -> Dict[str, Set[str]]:
        out: Dict[str, Set[str]] = {}
        args = list(fn.args.posonlyargs) + list(fn.args.args) \
            + list(fn.args.kwonlyargs)
        for a in args:
            if a.annotation is not None:
                types = self._annotation_types(mod, a.annotation)
                if types:
                    out[a.arg] = types
        return out

    def _annotation_types(self, mod: _Module, ann: ast.expr) -> Set[str]:
        """Class keys named by an annotation: Name/Attribute, string
        forward refs, ``Optional[T]``/``Union[...]``/``T | U``."""
        if ann is None:
            return set()
        if isinstance(ann, ast.Constant) and isinstance(ann.value, str):
            try:
                ann = ast.parse(ann.value, mode="eval").body
            except SyntaxError:
                return set()
        if isinstance(ann, ast.Subscript):      # Optional[T], Union[...]
            inner = ann.slice
            parts = inner.elts if isinstance(inner, ast.Tuple) else [inner]
            out: Set[str] = set()
            for p in parts:
                out |= self._annotation_types(mod, p)
            return out
        if isinstance(ann, ast.BinOp) and isinstance(ann.op, ast.BitOr):
            return (self._annotation_types(mod, ann.left)
                    | self._annotation_types(mod, ann.right))
        name = _dotted(ann)
        if not name or name in ("None", "Optional", "Any"):
            return set()
        ck = self._resolve_class_name(mod, name)
        return {ck} if ck else set()

    def _resolve_class_name(self, mod: _Module, dotted: str
                            ) -> Optional[str]:
        head, _, rest = dotted.partition(".")
        if not rest and head in mod.classes:
            return mod.classes[head]
        imp = mod.imports.get(head)
        if imp is not None:
            if imp[0] == "module" and rest:
                target = self.g.modules.get(self.by_modname.get(imp[1], ""))
                if target is not None:
                    return target.classes.get(rest.split(".")[0])
            elif imp[0] == "symbol" and not rest:
                target = self.g.modules.get(self.by_modname.get(imp[1], ""))
                if target is not None:
                    return target.classes.get(imp[2])
        if not rest:                    # unique class name project-wide
            cands = self.class_by_name.get(head, [])
            if len(cands) == 1:
                return cands[0]
        return None

    def _constructed_types(self, mod: _Module, value: ast.expr) -> Set[str]:
        """Class keys constructed by ``value`` (``ClassName(...)`` /
        ``module.ClassName(...)``), or the return types of a resolvable
        project call (``watch_jit(...)`` -> CompileWatched)."""
        if not isinstance(value, ast.Call):
            if isinstance(value, ast.Name):
                return set(mod.global_types.get(value.id, set()))
            return set()
        name = _dotted(value.func)
        if name:
            ck = self._resolve_class_name(mod, name)
            if ck:
                return {ck}
        targets, _ = self._call_targets(mod, value, scope=None)
        out: Set[str] = set()
        for t in targets or ():
            out |= self.return_types(t)
        return out

    def _value_function(self, mod: _Module, value: ast.expr,
                        cls_for_self: Optional[ClassInfo] = None
                        ) -> Optional[str]:
        if isinstance(value, ast.Name):
            return mod.functions.get(value.id) or self._imported_function(
                mod, value.id)
        attr = _self_attr(value)
        if attr and cls_for_self is not None:
            mk = cls_for_self.methods.get(attr)
            if mk:
                return mk
        return None

    def _imported_function(self, mod: _Module, name: str) -> Optional[str]:
        imp = mod.imports.get(name)
        if imp is None or imp[0] != "symbol":
            return None
        target = self.g.modules.get(self.by_modname.get(imp[1], ""))
        if target is None:
            return None
        return target.functions.get(imp[2])

    # -- return types ---------------------------------------------------
    def return_types(self, fkey: str) -> Set[str]:
        if fkey in self._return_types:
            return self._return_types[fkey]
        if fkey in self._in_progress:       # cycle: give up quietly
            return set()
        self._in_progress.add(fkey)
        try:
            info = self.g.functions.get(fkey)
            if info is None:
                return set()
            mod = self.g.modules[info.relpath]
            out: Set[str] = set()
            if info.node.returns is not None:
                out |= self._annotation_types(mod, info.node.returns)
            for node in own_body_nodes(info.node):
                if isinstance(node, ast.Return) and node.value is not None:
                    out |= self._constructed_types(mod, node.value)
                    if isinstance(node.value, ast.Name) \
                            and node.value.id == "self" and info.cls:
                        out.add(info.cls)
            self._return_types[fkey] = out
            return out
        finally:
            self._in_progress.discard(fkey)

    # -- phase 4: edges -------------------------------------------------
    def build_edges(self):
        for fkey, info in list(self.g.functions.items()):
            self._edges_of(info)

    class _Scope:
        __slots__ = ("func", "cls", "locals", "enclosing")

        def __init__(self, func, cls, locals_, enclosing):
            self.func = func
            self.cls = cls
            self.locals = locals_           # name -> class keys
            self.enclosing = enclosing      # name -> func key (nested defs)

    def _edges_of(self, info: FuncInfo):
        mod = self.g.modules[info.relpath]
        cls = self.g.classes.get(info.cls) if info.cls else None
        locals_: Dict[str, Set[str]] = dict(
            self._param_annotations(mod, info.node))
        enclosing: Dict[str, str] = {}
        for child in ast.iter_child_nodes(info.node):
            if isinstance(child, _FUNC_NODES):
                nested = f"{info.key}.{child.name}"
                if nested in self.g.functions:
                    enclosing[child.name] = nested
                    # a closure built on a hot path runs on it
                    self.g.add_edge(info.key, nested, child.lineno)
        scope = self._Scope(info, cls, locals_, enclosing)
        # forward pass: assignments type locals as they appear
        for node in _own_body_preorder(info.node):
            if isinstance(node, ast.Assign) and len(node.targets) == 1 \
                    and isinstance(node.targets[0], ast.Name):
                t = self._constructed_types_scoped(mod, scope, node.value)
                if t:
                    locals_.setdefault(node.targets[0].id, set()).update(t)
            elif isinstance(node, ast.AnnAssign) \
                    and isinstance(node.target, ast.Name):
                t = self._annotation_types(mod, node.annotation)
                if t:
                    locals_.setdefault(node.target.id, set()).update(t)
            elif isinstance(node, (ast.For, ast.AsyncFor)):
                self._type_loop_target(mod, scope, node.target, node.iter)
            elif isinstance(node, (ast.ListComp, ast.SetComp,
                                   ast.GeneratorExp, ast.DictComp)):
                # preorder yields the comp before its elt, so generator
                # targets are typed before the element expression is seen
                for gen in node.generators:
                    self._type_loop_target(mod, scope, gen.target, gen.iter)
            elif isinstance(node, ast.Call):
                self._note_call(mod, scope, node)
                self._note_reference_args(mod, scope, node)
            elif isinstance(node, (ast.With, ast.AsyncWith)):
                self._note_with(mod, scope, node)
            elif isinstance(node, ast.Attribute) \
                    and isinstance(node.ctx, ast.Load):
                self._note_property_read(mod, scope, node)

    def _type_loop_target(self, mod, scope, target, iter_expr):
        """``for h in self._handles.values()`` types ``h`` from the
        (element-flattened) container; ``for k, v in d.items()`` types the
        value slot."""
        if isinstance(target, ast.Name):
            t = self._expr_types(mod, scope, iter_expr)
            if t:
                scope.locals.setdefault(target.id, set()).update(t)
        elif isinstance(target, ast.Tuple) and len(target.elts) == 2 \
                and isinstance(target.elts[1], ast.Name) \
                and isinstance(iter_expr, ast.Call) \
                and isinstance(iter_expr.func, ast.Attribute) \
                and iter_expr.func.attr == "items":
            t = self._expr_types(mod, scope, iter_expr.func.value)
            if t:
                scope.locals.setdefault(target.elts[1].id, set()).update(t)

    def _note_call(self, mod, scope, call: ast.Call):
        targets, resolved = self._call_targets(mod, call, scope)
        if targets:
            for t in targets:
                self.g.add_edge(scope.func.key, t, call.lineno)
        elif not resolved:
            self.g.unresolved.setdefault(scope.func.key, []).append(
                (call.lineno, _dotted(call.func) or "<dynamic>"))

    def _note_reference_args(self, mod, scope, call: ast.Call):
        """Function/method references passed as values: Thread targets,
        callbacks, ``getattr(x, "literal")``."""
        name = _dotted(call.func)
        if name == "getattr" and len(call.args) >= 2 \
                and isinstance(call.args[1], ast.Constant) \
                and isinstance(call.args[1].value, str):
            self._reference_by_name(mod, scope, call.args[0],
                                    call.args[1].value, call.lineno)
        values = list(call.args) + [kw.value for kw in call.keywords]
        for v in values:
            fk = self._reference_target(mod, scope, v)
            if fk:
                self.g.add_edge(scope.func.key, fk, call.lineno)

    def _reference_target(self, mod, scope, expr) -> Optional[str]:
        if isinstance(expr, ast.Name):
            if expr.id in scope.enclosing:
                return scope.enclosing[expr.id]
            return mod.functions.get(expr.id) \
                or self._imported_function(mod, expr.id)
        attr = _self_attr(expr)
        if attr and scope.cls is not None:
            mk = scope.cls.methods.get(attr)
            if mk and attr not in scope.cls.properties:
                return mk
        return None

    def _reference_by_name(self, mod, scope, receiver, name, lineno):
        for ck in self._expr_types(mod, scope, receiver) \
                or self._fallback_classes(name):
            cinfo = self.g.classes.get(ck)
            if cinfo is not None:
                mk = self._lookup_method(cinfo, name)
                if mk:
                    self.g.add_edge(scope.func.key, mk, lineno)

    def _note_with(self, mod, scope, node):
        for item in node.items:
            cexpr = item.context_expr
            types: Set[str] = set()
            if isinstance(cexpr, ast.Call):
                targets, _ = self._call_targets(mod, cexpr, scope)
                for t in targets or ():
                    types |= self.return_types(t)
            types |= self._expr_types(mod, scope, cexpr)
            for ck in types:
                cinfo = self.g.classes.get(ck)
                if cinfo is None:
                    continue
                for dunder in ("__enter__", "__exit__"):
                    mk = self._lookup_method(cinfo, dunder)
                    if mk:
                        self.g.add_edge(scope.func.key, mk, node.lineno)

    def _note_property_read(self, mod, scope, node: ast.Attribute):
        for ck in self._expr_types(mod, scope, node.value):
            cinfo = self.g.classes.get(ck)
            if cinfo is not None and node.attr in cinfo.properties:
                mk = cinfo.methods.get(node.attr)
                if mk:
                    self.g.add_edge(scope.func.key, mk, node.lineno)

    # -- call resolution ------------------------------------------------
    def _call_targets(self, mod, call: ast.Call, scope
                      ) -> Tuple[Optional[Set[str]], bool]:
        """(targets, resolved): resolved=True when we understood the call
        even if it leads outside the project (stdlib/jax/builtin)."""
        func = call.func
        if isinstance(func, ast.Name):
            name = func.id
            if scope is not None and name in scope.enclosing:
                return {scope.enclosing[name]}, True
            if name in mod.functions:
                return {mod.functions[name]}, True
            if name in mod.global_funcs:
                return {mod.global_funcs[name]}, True
            if name in mod.classes:
                return self._ctor_targets(mod.classes[name]), True
            imp_fn = self._imported_function(mod, name)
            if imp_fn:
                return {imp_fn}, True
            ck = self._resolve_class_name(mod, name)
            if ck:
                return self._ctor_targets(ck), True
            if name in _PROTOCOL_BUILTINS and call.args and scope is not None:
                types = self._expr_types(mod, scope, call.args[0])
                out = set()
                for t in types:
                    cinfo = self.g.classes.get(t)
                    mk = cinfo and self._lookup_method(
                        cinfo, _PROTOCOL_BUILTINS[name])
                    if mk:
                        out.add(mk)
                return (out or None), True
            if name in _BUILTINS:
                return None, True
            if name in mod.imports:         # imported external symbol
                return None, True
            return None, False              # injected callable: dynamic
        if isinstance(func, ast.Attribute):
            return self._attr_call_targets(mod, call, func, scope)
        if isinstance(func, ast.Call):      # curried: f(...)(...) — the
            return None, True               # inner call got its own edge
        return None, True                   # subscripts, lambdas, ...

    def _attr_call_targets(self, mod, call, func: ast.Attribute, scope
                           ) -> Tuple[Optional[Set[str]], bool]:
        attr = func.attr
        recv = func.value
        # module-qualified: guard.note_comm_op(...), np.asarray(...)
        dotted = _dotted(recv)
        if dotted:
            head = dotted.split(".")[0]
            imp = mod.imports.get(head)
            if imp is not None and imp[0] == "module":
                modname = imp[1] if dotted == head \
                    else ".".join([imp[1]] + dotted.split(".")[1:])
                target_rel = self.by_modname.get(modname)
                if target_rel is not None:
                    tmod = self.g.modules[target_rel]
                    if attr in tmod.functions:
                        return {tmod.functions[attr]}, True
                    if attr in tmod.classes:
                        return self._ctor_targets(tmod.classes[attr]), True
                    return None, True       # project module, unknown attr
                project_tops = {m.split(".")[0] for m in self.by_modname}
                if imp[1].split(".")[0] not in project_tops:
                    return None, True       # external module call
        # typed receiver
        types = self._expr_types(mod, scope, recv) if scope is not None \
            else set()
        if types:
            out = set()
            for ck in types:
                cinfo = self.g.classes.get(ck)
                if cinfo is None:
                    continue
                mk = self._lookup_method(cinfo, attr)
                if mk:
                    out.add(mk)
                    continue
                # callable-object attribute: ``self.fn = watch_jit(...)``
                # calls CompileWatched.__call__; ``self.cb = func`` calls
                # the bound function
                out |= cinfo.attr_funcs.get(attr, set())
                for tk in cinfo.attr_types.get(attr, set()):
                    tinfo = self.g.classes.get(tk)
                    mk2 = tinfo and self._lookup_method(tinfo, "__call__")
                    if mk2:
                        out.add(mk2)
            if out:
                return out, True
            return None, True           # typed, but method not in project
        # untyped receiver: stdlib container/str traffic is not a project
        # call — resolve-external rather than degrade to a warning
        if attr in _STDLIB_METHODS:
            return None, True
        # unique-ish method name across project classes
        cands = self.method_index.get(attr, [])
        if 1 <= len(cands) <= _FALLBACK_CAP:
            out = set()
            for ck in cands:
                mk = self.g.classes[ck].methods.get(attr)
                if mk:
                    out.add(mk)
            return out, True
        if not cands:
            return None, True           # clearly not a project method
        return None, False              # ambiguous: degrade to a warning

    def _ctor_targets(self, class_key: str) -> Optional[Set[str]]:
        cinfo = self.g.classes.get(class_key)
        if cinfo is None:
            return None
        mk = self._lookup_method(cinfo, "__init__")
        return {mk} if mk else None

    def _lookup_method(self, cinfo: ClassInfo, name: str,
                       depth: int = 0) -> Optional[str]:
        mk = cinfo.methods.get(name)
        if mk or depth > 4:
            return mk
        mod = self.g.modules[cinfo.relpath]
        for base in cinfo.bases:
            bname = _dotted(base)
            if not bname:
                continue
            bk = self._resolve_class_name(mod, bname)
            if bk and bk != cinfo.key:
                mk = self._lookup_method(self.g.classes[bk], name,
                                         depth + 1)
                if mk:
                    return mk
        return None

    def _expr_types(self, mod, scope, expr) -> Set[str]:
        if scope is None:
            return set()
        if isinstance(expr, ast.Name):
            if expr.id in ("self", "cls") and scope.cls is not None:
                return {scope.cls.key}
            return set(scope.locals.get(expr.id, set())) \
                or set(mod.global_types.get(expr.id, set()))
        if isinstance(expr, ast.Attribute):
            attr = _self_attr(expr)
            if attr and scope.cls is not None:
                return set(scope.cls.attr_types.get(attr, set()))
            # x.y where x is typed: y's annotation/attr types
            recv_types = self._expr_types(mod, scope, expr.value)
            out: Set[str] = set()
            for ck in recv_types:
                cinfo = self.g.classes.get(ck)
                if cinfo is not None:
                    out |= cinfo.attr_types.get(expr.attr, set())
            return out
        if isinstance(expr, ast.Call):
            # container accessors pass the receiver's (element-flattened)
            # types through: ``self._handles.values()`` yields whatever
            # ``Dict[int, ReplicaHandle]`` flattened to
            if isinstance(expr.func, ast.Attribute) \
                    and expr.func.attr in _CONTAINER_PASSTHROUGH:
                inner = self._expr_types(mod, scope, expr.func.value)
                if inner:
                    return inner
            targets, _ = self._call_targets(mod, expr, scope)
            out = set()
            for t in targets or ():
                out |= self.return_types(t)
            # direct construction: T() has type T
            name = _dotted(expr.func)
            if name:
                ck = self._resolve_class_name(mod, name)
                if ck:
                    out.add(ck)
            return out
        if isinstance(expr, ast.Subscript):     # d[k] on a typed container
            return self._expr_types(mod, scope, expr.value)
        return set()

    def _constructed_types_scoped(self, mod, scope, value) -> Set[str]:
        t = self._expr_types(mod, scope, value) if isinstance(
            value, (ast.Call, ast.Name, ast.Attribute)) else set()
        return t

    def _fallback_classes(self, method_name: str) -> List[str]:
        cands = self.method_index.get(method_name, [])
        return cands if 1 <= len(cands) <= _FALLBACK_CAP else []


def _dotted(node) -> Optional[str]:
    parts = []
    while isinstance(node, ast.Attribute):
        parts.append(node.attr)
        node = node.value
    if isinstance(node, ast.Name):
        parts.append(node.id)
        return ".".join(reversed(parts))
    return None


def _self_attr(node) -> Optional[str]:
    if isinstance(node, ast.Attribute) and isinstance(node.value, ast.Name) \
            and node.value.id in ("self", "cls"):
        return node.attr
    return None


def _own_body_preorder(fn):
    """Pre-order walk of a function's own body (nested defs/classes
    skipped) so assignment-based local typing sees defs before uses in
    straight-line code."""
    stack = list(reversed(list(ast.iter_child_nodes(fn))))
    while stack:
        node = stack.pop()
        if isinstance(node, _FUNC_NODES + (ast.ClassDef,)):
            continue
        yield node
        stack.extend(reversed(list(ast.iter_child_nodes(node))))


# ----------------------------------------------------------------------
# entry points + per-session memo
# ----------------------------------------------------------------------
def build_graph(files: Iterable[Tuple[str, ast.AST]]) -> CallGraph:
    """Build from (relpath, parsed-tree) pairs."""
    b = _Builder(files)
    b.index()
    b.infer_types()
    b.build_edges()
    return b.g


_CACHE: Dict[tuple, CallGraph] = {}
_CACHE_MAX = 4


def get_callgraph(project) -> CallGraph:
    """The call graph for a ``ProjectContext`` — built once per source
    snapshot and shared by every rule in the run (and across runs in one
    test session: the lint suite re-lints the package several times)."""
    cached = getattr(project, "_dslint_callgraph", None)
    if cached is not None:
        return cached
    key = tuple(sorted((f.relpath, len(f.source), hash(f.source))
                       for f in project.files))
    graph = _CACHE.get(key)
    if graph is None:
        graph = build_graph((f.relpath, f.tree) for f in project.files)
        if len(_CACHE) >= _CACHE_MAX:
            _CACHE.pop(next(iter(_CACHE)))
        _CACHE[key] = graph
    project._dslint_callgraph = graph
    return graph


def build_graph_from_sources(entries: Iterable[Tuple[str, str]]) -> CallGraph:
    """Build from (relpath, source-text) pairs, through the same snapshot
    cache ``get_callgraph`` uses — env_report, the test-session fixture,
    and the rules all pay for ONE build per source snapshot as long as
    their relpaths agree (repo-relative, forward slashes)."""
    entries = list(entries)
    key = tuple(sorted((rel, len(src), hash(src)) for rel, src in entries))
    graph = _CACHE.get(key)
    if graph is None:
        graph = build_graph((rel, ast.parse(src)) for rel, src in entries)
        if len(_CACHE) >= _CACHE_MAX:
            _CACHE.pop(next(iter(_CACHE)))
        _CACHE[key] = graph
    return graph
