"""Experiment monitoring fan-out.

Reference analog: ``deepspeed/monitor/monitor.py:13,30`` (``Monitor`` ABC +
``MonitorMaster`` fanning (tag, value, step) events to TensorBoard / WandB / CSV /
Comet, rank-0 only). CSV and TensorBoard backends here; wandb gated on import.
"""

import csv
import os
from typing import List, Tuple

import jax

from deepspeed_tpu.utils.logging import logger

Event = Tuple[str, float, int]


class Monitor:
    def __init__(self, config):
        self.enabled = False

    def write_events(self, events: List[Event]):
        raise NotImplementedError


class CSVMonitor(Monitor):
    """reference: monitor/csv_monitor.py — one csv per tag."""

    def __init__(self, csv_config):
        self.enabled = csv_config.enabled and jax.process_index() == 0
        self.output_path = csv_config.output_path or "./csv_monitor"
        self.job_name = csv_config.job_name
        self._files = {}

    def _path_for(self, tag: str) -> str:
        d = os.path.join(self.output_path, self.job_name)
        os.makedirs(d, exist_ok=True)
        return os.path.join(d, tag.replace("/", "_") + ".csv")

    def write_events(self, events: List[Event]):
        if not self.enabled:
            return
        for tag, value, step in events:
            p = self._path_for(tag)
            new = not os.path.exists(p)
            with open(p, "a", newline="") as f:
                w = csv.writer(f)
                if new:
                    w.writerow(["step", tag])
                w.writerow([step, value])


class TensorBoardMonitor(Monitor):
    def __init__(self, tb_config):
        self.enabled = False
        if not (tb_config.enabled and jax.process_index() == 0):
            return
        try:
            from torch.utils.tensorboard import SummaryWriter
            log_dir = os.path.join(tb_config.output_path or "./runs", tb_config.job_name)
            self.writer = SummaryWriter(log_dir=log_dir)
            self.enabled = True
        except Exception as e:
            logger.warning(f"tensorboard unavailable: {e}")

    def write_events(self, events: List[Event]):
        if not self.enabled:
            return
        for tag, value, step in events:
            self.writer.add_scalar(tag, value, step)
        self.writer.flush()


class WandbMonitor(Monitor):
    def __init__(self, wb_config):
        self.enabled = False
        if not (wb_config.enabled and jax.process_index() == 0):
            return
        try:
            import wandb
            wandb.init(project=wb_config.project, group=wb_config.group,
                       entity=wb_config.team)
            self._wandb = wandb
            self.enabled = True
        except Exception as e:
            logger.warning(f"wandb unavailable: {e}")

    def write_events(self, events: List[Event]):
        if not self.enabled:
            return
        for tag, value, step in events:
            self._wandb.log({tag: value}, step=step)


class CometMonitor(Monitor):
    """reference: monitor/comet.py (CometMonitor — rank-0 comet_ml experiment,
    metrics logged at samples_log_interval)."""

    def __init__(self, comet_config):
        self.enabled = False
        if not (comet_config.enabled and jax.process_index() == 0):
            return
        try:
            import comet_ml
            self._experiment = comet_ml.start(
                api_key=comet_config.api_key,
                project=comet_config.project,
                workspace=comet_config.workspace,
                experiment_key=comet_config.experiment_key,
                mode=comet_config.mode,
                online=comet_config.online)
            if comet_config.experiment_name:
                self._experiment.set_name(comet_config.experiment_name)
            self._interval = max(1, comet_config.samples_log_interval)
            self.enabled = True
        except Exception as e:
            logger.warning(f"comet_ml unavailable: {e}")

    @property
    def experiment(self):
        return self._experiment

    def write_events(self, events: List[Event]):
        if not self.enabled:
            return
        for tag, value, step in events:
            if step % self._interval == 0:
                self._experiment.log_metric(tag, value, step=step)


class MonitorMaster(Monitor):
    """reference: monitor/monitor.py:30."""

    def __init__(self, config):
        self.backends = [
            CSVMonitor(config.csv_monitor),
            TensorBoardMonitor(config.tensorboard),
            WandbMonitor(config.wandb),
            CometMonitor(config.comet),
        ]
        self.enabled = any(b.enabled for b in self.backends)

    def write_events(self, events: List[Event]):
        # normalize once for every backend: producers hand numpy/jax scalars
        # (e.g. the engine's async metric drain) as readily as floats, and a
        # device array here would make each backend force its own transfer
        events = [(tag, float(value), int(step)) for tag, value, step in events]
        for b in self.backends:
            if b.enabled:
                b.write_events(events)

    # ---- events sink (tracer instant-events) -----------------------------
    def write_instant(self, name: str, step: int):
        """One tracer instant-event (guard trip, chaos injection, watchdog
        flag) as a unit-valued gauge under ``Events/`` — so the rare events
        land in TensorBoard/CSV on the same step axis as the metrics they
        explain. This is the hook ``Tracer.attach_sink`` takes."""
        self.write_events([(f"Events/{name}", 1.0, int(step))])
