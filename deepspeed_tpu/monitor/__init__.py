from deepspeed_tpu.monitor.monitor import (CometMonitor, CSVMonitor, Event,
                                           Monitor, MonitorMaster,
                                           TensorBoardMonitor, WandbMonitor)

__all__ = [
    "CometMonitor",
    "CSVMonitor",
    "Event",
    "Monitor",
    "MonitorMaster",
    "TensorBoardMonitor",
    "WandbMonitor",
]
