"""commguard — timeout-bounded collectives and wedge-proof initialization.

The repo's own trajectory is the bug report this module closes: BENCH
r02–r05 all wedged at TPU device discovery with no timeout and no
diagnosis, and a single dead peer turns every eager collective into an
infinite hang the step-level resilience layer (PR 2) cannot see because it
lives *below* the engine. Reference engines treat communicator hang as a
first-class recoverable event (torch NCCL ``timeout=`` + coordinated
abort; elastic-training lineage in PAPERS.md); this is the TPU-native
equivalent.

Scope — what CAN be bounded on TPU:

- **Eager host-driven ops** (checkpoint scatter, ``device_broadcast``,
  debugging collectives) and **initialization** (``jax.distributed``
  rendezvous, PJRT device discovery). These block the calling Python
  thread in native code, so the guard runs them on a watched worker
  thread and the *caller* keeps a deadline: a wedge becomes a
  ``CommWedgeError`` carrying the dstrace comm-span tail instead of a
  silent forever-hang. The abandoned worker thread is daemonic — the
  process is about to coordinated-abort anyway (that is the recovery
  contract, see ``FaultTolerantRunner``).
- Collectives **inside jit** are XLA ops scheduled by the compiler; no
  host-side deadline can exist there. Their health is covered from the
  side instead: the facade's trace-time ``_record`` notes every comm op
  into the active heartbeat (``note_comm_op``), so the membership view
  carries "last-completed comm op" per worker and a wedged device shows
  up as a stalled op sequence + stale heartbeat.

Outcome taxonomy (every guarded call is classified, never just raised):

  ok        completed inside the deadline
  timeout   wedged past the deadline -> ``CommWedgeError``
  transient retryable init failure (connection refused/reset, UNAVAILABLE,
            DEADLINE_EXCEEDED, ...) -> exponential-backoff retry
  fatal     anything else -> ``CommInitError`` / re-raise immediately

Chaos: a ``ChaosMonkey`` with comm knobs (``DSTPU_CHAOS_COMM_*``) injects
deterministic delay/wedge faults into guarded ops so the whole
detect → classify → abort → autosave → resume loop is drillable on CPU.
"""

import enum
import itertools
import os
import threading
import time
from typing import Any, Callable, Dict, List, Optional

from pydantic import model_validator

from deepspeed_tpu.config.config_utils import DeepSpeedTPUConfigModel
from deepspeed_tpu.telemetry.tracer import get_tracer
from deepspeed_tpu.utils.logging import logger

#: worker exit status meaning "a comm fault was detected and handled"
#: (classified abort after autosave). Distinct from preemption signals and
#: from crash codes so the elastic agent's restart accounting can treat
#: comm faults like preemptions (free relaunch) instead of crashes
#: (budgeted). 75 = BSD EX_TEMPFAIL: "temporary failure, retry".
COMM_FAULT_EXIT_CODE = 75

#: env overrides for the init path (set by the elastic agent from the
#: "comm_guard" config group so relaunched workers' rendezvous honors the
#: configured budget; 0 deadline disables bounding)
INIT_DEADLINE_ENV = "DSTPU_COMM_INIT_DEADLINE_S"
INIT_RETRIES_ENV = "DSTPU_COMM_INIT_RETRIES"
INIT_BACKOFF_ENV = "DSTPU_COMM_INIT_BACKOFF_S"


class CommOutcome(enum.Enum):
    OK = "ok"
    TIMEOUT = "timeout"
    TRANSIENT = "transient"
    FATAL = "fatal"


class CommGuardConfig(DeepSpeedTPUConfigModel):
    """The ``"comm_guard"`` config group (see ``config/constants.py``)."""
    enabled: bool = False
    # deadline for one eager guarded collective
    op_deadline_s: float = 60.0
    # deadline for init/rendezvous/device discovery (0 = unbounded)
    init_deadline_s: float = 300.0
    # exponential-backoff retry budget for TRANSIENT init failures
    init_retries: int = 3
    init_backoff_s: float = 1.0
    # distributed-health heartbeat (consumed by resilience/membership.py)
    heartbeat_interval_s: float = 1.0
    # a peer whose heartbeat is older than this is LOST
    lost_after_s: float = 10.0
    # where per-rank heartbeat files land ("" -> DSTPU_MEMBERSHIP_DIR or
    # ./membership under the cwd)
    membership_dir: str = ""
    # straggler detection: a rank is an outlier when its per-op duration
    # exceeds median * factor AND the excess exceeds min_s
    straggler_factor: float = 3.0
    straggler_min_s: float = 0.0
    # trailing dstrace slice attached to CommWedgeError
    trace_tail_s: float = 30.0

    @model_validator(mode="after")
    def _check(self):
        if self.op_deadline_s <= 0:
            raise ValueError("op_deadline_s must be > 0")
        if self.init_deadline_s < 0:
            raise ValueError("init_deadline_s must be >= 0 (0 = unbounded)")
        if self.straggler_factor <= 1.0:
            raise ValueError("straggler_factor must be > 1.0")
        if self.heartbeat_interval_s <= 0:
            raise ValueError("heartbeat_interval_s must be > 0")
        if self.lost_after_s <= self.heartbeat_interval_s:
            raise ValueError("lost_after_s must exceed heartbeat_interval_s")
        return self


# ---------------------------------------------------------------------------
# fault taxonomy
# ---------------------------------------------------------------------------
class CommFaultError(RuntimeError):
    """Base class for classified comm faults. Carries the op name, the
    classified outcome, and elapsed time — everything the coordinated
    recovery path and the exit-code classification need."""

    def __init__(self, msg: str, op: str, outcome: CommOutcome,
                 elapsed_s: float = 0.0):
        super().__init__(msg)
        self.op = op
        self.outcome = outcome
        self.elapsed_s = elapsed_s


class CommWedgeError(CommFaultError):
    """A guarded op ran past its deadline — the BENCH r02–r05 failure,
    mechanized. ``comm_tail`` is the trailing slice of dstrace comm events
    (op/bytes/world per entry) so the error itself says what the
    communicator was doing when it wedged."""

    def __init__(self, msg: str, op: str, elapsed_s: float,
                 comm_tail: Optional[List[dict]] = None):
        super().__init__(msg, op, CommOutcome.TIMEOUT, elapsed_s)
        self.comm_tail = comm_tail or []

    def __str__(self):
        base = super().__str__()
        if not self.comm_tail:
            return base
        last = self.comm_tail[-3:]
        ops = ", ".join(e.get("name", "?") for e in last)
        return f"{base} [comm tail ({len(self.comm_tail)} events): ... {ops}]"


class CommInitError(CommFaultError):
    """Initialization / rendezvous / device discovery failed after the
    retry budget (TRANSIENT exhausted) or immediately (FATAL)."""

    def __init__(self, msg: str, op: str, outcome: CommOutcome,
                 attempts: int = 1, cause: Optional[BaseException] = None):
        super().__init__(msg, op, outcome)
        self.attempts = attempts
        self.__cause__ = cause


class CommPeerLostError(CommFaultError):
    """The membership view declared a peer dead (stale heartbeat)."""

    def __init__(self, msg: str, ranks):
        super().__init__(msg, "membership", CommOutcome.FATAL)
        self.ranks = tuple(ranks)


#: exception-text markers meaning "the fabric/control plane hiccuped —
#: retry with backoff" (gRPC status names the TPU runtime surfaces, plus
#: the socket-level spellings)
_TRANSIENT_MARKERS = (
    "unavailable", "deadline_exceeded", "deadline exceeded", "aborted",
    "connection refused", "connection reset", "connection closed",
    "broken pipe", "temporarily", "try again", "resource_exhausted",
    "failed to connect", "socket closed", "timed out",
)
#: markers meaning "credentials, not connectivity" — never retried
_AUTH_MARKERS = ("permission denied", "permission_denied", "unauthenticated",
                 "forbidden", "credential", "authentication", "oauth")


def classify_exception(exc: BaseException) -> CommOutcome:
    """TRANSIENT iff the error text (or type) says the control plane may
    recover; auth and everything else are FATAL — retrying a credential
    failure just burns the deadline."""
    text = f"{type(exc).__name__}: {exc}".lower()
    if any(m in text for m in _AUTH_MARKERS):
        return CommOutcome.FATAL
    if isinstance(exc, (ConnectionError, TimeoutError)):
        return CommOutcome.TRANSIENT
    if any(m in text for m in _TRANSIENT_MARKERS):
        return CommOutcome.TRANSIENT
    return CommOutcome.FATAL


def comm_trace_tail(tail_s: float = 30.0) -> List[dict]:
    """The trailing ``tail_s`` of dstrace comm events as plain dicts —
    what CommWedgeError embeds so a wedge diagnosis never requires the
    full trace dump."""
    tracer = get_tracer()
    if not tracer.enabled:
        return []
    out = []
    for eid, name, cat, ph, ts, dur, tid, args in tracer.tail(tail_s):
        if cat != "comm" and not name.startswith("comm/"):
            continue
        out.append({"name": name, "ph": ph, "ts": ts, "dur_s": dur,
                    "args": dict(args) if args else {}})
    return out


# ---------------------------------------------------------------------------
# comm-op sequence numbers (the cross-rank join key)
# ---------------------------------------------------------------------------
#: process-wide monotonic comm-op counter. SPMD programs record collectives
#: in the SAME order on every rank (trace-time for jit ops, call order for
#: eager guarded ops), so the k-th recorded op on rank 0 IS the k-th on
#: rank 3 — ``op_seq`` stamped into every comm span/instant is what
#: ``dstpu trace merge`` joins per-rank timelines on. itertools.count is
#: GIL-atomic: allocation never locks the hot path.
_op_seq = itertools.count(1)


def next_op_seq() -> int:
    """Allocate the next comm-op sequence number (registered DS002 hot
    path: one C-level counter increment, never a host sync)."""
    return next(_op_seq)


# ---------------------------------------------------------------------------
# comm-op listener (membership's "last-completed comm op" feed)
# ---------------------------------------------------------------------------
_comm_listener: Optional[Callable[[str], None]] = None


def set_comm_op_listener(fn: Optional[Callable[[str], None]]) -> None:
    """Install the active heartbeat's ``note_op`` (one listener; the
    heartbeat un-installs itself on stop via ``clear_comm_op_listener``)."""
    global _comm_listener
    _comm_listener = fn


def clear_comm_op_listener(fn: Callable[[str], None]) -> None:
    """Uninstall ``fn`` only if it is still the active listener — a stopped
    heartbeat must never sever a newer heartbeat's feed (overlapping
    lifetimes: rolling runner replacement, training + serving in one
    process). Equality, not identity: each ``obj.method`` access builds a
    fresh bound-method object, and ``==`` is what compares the underlying
    (instance, function) pair."""
    global _comm_listener
    if _comm_listener == fn:
        _comm_listener = None


def note_comm_op(op_name: str) -> None:
    """Called by the collective facade for every recorded comm op (trace
    time under jit, per call when eager). Registered DS002 hot path: one
    attribute read + one Python call, never a host sync."""
    lis = _comm_listener
    if lis is not None:
        lis(op_name)


# ---------------------------------------------------------------------------
# active guard (the facade's eager ops route through it automatically)
# ---------------------------------------------------------------------------
_active_guard: Optional["CommGuard"] = None


def set_active_guard(guard: Optional["CommGuard"]) -> None:
    """Install the process-wide guard (the ``FaultTolerantRunner`` does this
    when the ``"comm_guard"`` group is enabled). While installed, the comm
    facade's eager host-driven ops (``device_broadcast``) run deadline-
    bounded without any caller change — the chaos comm drill works against
    an unmodified training script."""
    global _active_guard
    _active_guard = guard


def get_active_guard() -> Optional["CommGuard"]:
    return _active_guard


def clear_active_guard(guard: "CommGuard") -> None:
    """Uninstall ``guard`` only if it is still the active one (overlapping
    runner lifetimes must not strip a newer runner's guard)."""
    global _active_guard
    if _active_guard is guard:
        _active_guard = None


def guarded(op: str, fn: Callable[[], Any],
            deadline_s: Optional[float] = None) -> Any:
    """Run one eager comm op under the active guard, or inline when no
    guard is installed (zero-overhead default: one global read)."""
    g = _active_guard
    if g is None:
        return fn()
    return g.run(op, fn, deadline_s=deadline_s)


# ---------------------------------------------------------------------------
# deadline-bounded execution
# ---------------------------------------------------------------------------
def _run_with_deadline(fn: Callable[[], Any], deadline_s: float,
                       name: str) -> Dict[str, Any]:
    """Run ``fn`` on a daemon worker thread; wait up to ``deadline_s``.

    Returns ``{"done": bool, "value": ..., "error": ...}``. On timeout the
    worker is abandoned (it is stuck in native code no Python mechanism can
    unwind — that is the whole point); the caller raises and the
    coordinated-recovery contract tears the process down.
    """
    box: Dict[str, Any] = {}
    done = threading.Event()

    def _target():
        try:
            box["value"] = fn()
        except BaseException as e:   # noqa: BLE001 — classified by caller
            box["error"] = e
        finally:
            done.set()

    t = threading.Thread(target=_target, daemon=True,
                         name=f"dstpu-commguard-{name}")
    t.start()
    box["done"] = done.wait(deadline_s)
    return box


def bounded_init(fn: Callable[[], Any], *, name: str = "init",
                 deadline_s: float = 300.0, retries: int = 3,
                 backoff_s: float = 1.0,
                 classify: Callable[[BaseException], CommOutcome]
                 = classify_exception,
                 trace_tail_s: float = 30.0) -> Any:
    """Run an init/rendezvous/discovery callable under a deadline with
    exponential-backoff retry for TRANSIENT failures.

    - wedge (no return inside ``deadline_s``) -> ``CommWedgeError``
      immediately: a wedged native init poisons the backend, retrying
      in-process would just stack abandoned threads;
    - TRANSIENT exception -> retry up to ``retries`` times, sleeping
      ``backoff_s * 2^(attempt-1)`` between attempts;
    - FATAL exception -> ``CommInitError`` at once.

    ``deadline_s <= 0`` runs ``fn`` inline (unbounded, for callers that
    explicitly opt out, e.g. DSTPU_COMM_INIT_DEADLINE_S=0).
    """
    tracer = get_tracer()
    attempts = 0
    while True:
        attempts += 1
        t0 = time.monotonic()
        with tracer.span(f"comm/init/{name}", cat="comm", attempt=attempts):
            if deadline_s and deadline_s > 0:
                box = _run_with_deadline(fn, deadline_s, name)
            else:
                # inline (unbounded opt-out): catch Exception only —
                # KeyboardInterrupt/SystemExit must keep their meaning (the
                # runner's preemption contract), not become a FATAL init
                # failure. The threaded path is immune: interrupts land on
                # the main thread's done.wait(), not in the worker.
                try:
                    box = {"done": True, "value": fn()}
                except Exception as e:
                    box = {"done": True, "error": e}
        elapsed = time.monotonic() - t0
        if not box["done"]:
            tracer.instant("comm/init_wedge", cat="comm", op=name,
                           deadline_s=deadline_s)
            raise CommWedgeError(
                f"{name}: initialization exceeded {deadline_s:.0f}s deadline "
                f"(wedged in native init; attempt {attempts})",
                op=name, elapsed_s=elapsed,
                comm_tail=comm_trace_tail(trace_tail_s))
        if "error" not in box:
            return box.get("value")
        exc = box["error"]
        outcome = classify(exc)
        if outcome is CommOutcome.TRANSIENT and attempts <= retries:
            sleep = backoff_s * 2 ** (attempts - 1)
            tracer.instant("comm/init_retry", cat="comm", op=name,
                           attempt=attempts, backoff_s=round(sleep, 3))
            logger.warning(f"commguard: {name} transient init failure "
                           f"(attempt {attempts}/{retries + 1}): {exc!r}; "
                           f"retrying in {sleep:.1f}s")
            time.sleep(sleep)
            continue
        kind = "transient (retry budget exhausted)" \
            if outcome is CommOutcome.TRANSIENT else "fatal"
        raise CommInitError(
            f"{name}: initialization failed ({kind}) after {attempts} "
            f"attempt(s): {exc!r}",
            op=name, outcome=outcome, attempts=attempts, cause=exc)


class CommGuard:
    """Deadline-bounds eager collectives and classifies every outcome.

    ``run(op, fn)`` executes ``fn`` on a watched worker thread; a return
    inside ``op_deadline_s`` is OK (duration fed to the straggler window
    and the heartbeat), a chaos delay is OK-but-slow, and a wedge raises
    ``CommWedgeError`` with the dstrace comm tail attached. Counters are
    plain ints (single guarded-caller discipline: eager ops are host-driven
    and rare) exposed for deterministic tests and env reports.
    """

    def __init__(self, config: Optional[CommGuardConfig] = None,
                 chaos=None):
        self.cfg = config or CommGuardConfig(enabled=True)
        # duck-typed ChaosMonkey (avoids a comm -> resilience import cycle):
        # anything with .comm_fault(op, call_index) -> None|"delay"|"wedge"
        self.chaos = chaos
        self.counters: Dict[str, int] = {o.value: 0 for o in CommOutcome}
        self._calls = 0                    # guarded-op call index (chaos key)

    # ------------------------------------------------------------------
    def run(self, op: str, fn: Callable[[], Any],
            deadline_s: Optional[float] = None) -> Any:
        """One guarded eager op. Raises ``CommWedgeError`` on deadline,
        re-raises (classified, counted) on failure."""
        deadline = deadline_s if deadline_s is not None \
            else self.cfg.op_deadline_s
        call_idx = self._calls
        self._calls += 1
        tracer = get_tracer()
        # allocated at ENTRY so the k-th guarded op carries the same seq on
        # every rank even when one of them wedges mid-op
        op_seq = next_op_seq()
        fault = self.chaos.comm_fault(op, call_idx) \
            if self.chaos is not None else None
        run_fn = fn
        if fault == "wedge":
            # the injected wedge IS a never-returning native call as far as
            # the guard can tell: the worker sleeps far past any deadline
            run_fn = self._wedged(op, deadline)
        elif fault == "delay":
            run_fn = self._delayed(op, fn)
        t0 = time.monotonic()
        with tracer.span(f"comm/guarded/{op}", cat="comm", call=call_idx,
                         op_seq=op_seq, deadline_s=deadline):
            box = _run_with_deadline(run_fn, deadline, op)
        elapsed = time.monotonic() - t0
        if not box["done"]:
            self.counters["timeout"] += 1
            tracer.instant("comm/wedge", cat="comm", op=op,
                           deadline_s=deadline)
            raise CommWedgeError(
                f"collective '{op}' exceeded {deadline:.1f}s deadline "
                f"(wedged; call #{call_idx})",
                op=op, elapsed_s=elapsed,
                comm_tail=comm_trace_tail(self.cfg.trace_tail_s))
        if "error" in box:
            exc = box["error"]
            outcome = classify_exception(exc)
            self.counters[outcome.value] += 1
            tracer.instant("comm/op_failed", cat="comm", op=op,
                           outcome=outcome.value)
            raise exc
        self.counters["ok"] += 1
        note_comm_op(op)
        return box.get("value")

    # ------------------------------------------------------------------
    def _wedged(self, op: str, deadline: float) -> Callable[[], None]:
        def _hang():
            # bounded far past the deadline (not infinite) so the abandoned
            # daemon thread eventually exits in long-lived test processes
            time.sleep(max(deadline, 0.1) * 100)
        return _hang

    def _delayed(self, op: str, fn: Callable[[], Any]) -> Callable[[], Any]:
        delay = getattr(self.chaos.config, "comm_delay_s", 0.0)

        def _slow():
            time.sleep(delay)
            return fn()
        return _slow
