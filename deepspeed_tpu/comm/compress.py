"""comm/compress — quantized error-feedback collectives + bucketed overlap.

The first-class wire-compression layer (ROADMAP item 3): EQuARX-style
quantized all-reduce / reduce-scatter (arxiv 2506.17615 — int8/fp8 codes with
per-chunk fp32 scales and persistent error feedback) usable on ANY mesh axis
or axis tuple, plus a gradient-bucket scheduler that issues one collective
per filled bucket instead of a single fused end-of-backward reduction — the
T3 lesson (arxiv 2401.16677): the win comes from *fine-grained*
compute/collective overlap, which per-bucket collectives hand to XLA's
latency-hiding scheduler.

This module is THE single quantize/dequantize + error-feedback
implementation: the qgZ gradient path (``runtime/zero/qgz.quantized_grad_sync``)
and the engine's ``comm_compression`` bucket sync are both thin adapters over
it, and every collective it issues is routed through the ``comm.comm`` facade
so commguard ``_record``, the heartbeat, and dstrace comm spans see the op
with BOTH ``bytes`` (logical payload) and ``wire_bytes`` (codes + scales)
args — the deterministic counters the plan rollups and tests assert on.

Accounting convention (shared with the facade): ``bytes`` is the logical
payload volume of ONE phase (what the uncompressed op would move — the same
convention the fp32 facade ops use; the ring-traffic multiple lives in the
busbw factor, never in the byte counters). ``wire_bytes`` is the same
payload in the wire dtype plus the fp32 per-chunk scales:

    wire_payload_bytes(n) = n * wire_itemsize + 4 * ceil(n / chunk)

so for fp32 inputs at the default chunk the compression ratio is
``4 / (1 + 4/chunk)`` ≈ 3.94x — the ≥3.5x acceptance floor with margin.

Error feedback (1-bit-Adam / EQuARX lineage, cf. ``comm/compressed.py``):
each participant keeps a *worker* residual (its local compression error,
full payload size) and a *server* residual (the error of re-quantizing its
reduced chunk for the regather hop). Residuals are added before quantizing
and replaced with the fresh quantization error every step, so the bias of
any single step is repaid on the next — the running mean converges to the
exact reduction. State is per-bucket, device-resident, threaded through the
engine's optimizer state (``CommCompressState``) so it checkpoints and
rides the mesh-portable resume path.

Module-level imports are jax-free (the ``comm/guard.py`` idiom) so the
config group parses on jax-less hosts; jax loads lazily at build/trace time.
"""

import math
from typing import Any, Dict, List, NamedTuple, Optional, Sequence, Tuple

from pydantic import model_validator

from deepspeed_tpu.config.config_utils import DeepSpeedTPUConfigModel
# the synthetic Perfetto track the per-bucket ``comm/overlap`` spans ride
# (authoritative constant in telemetry/tracer.py, jax-free like this module)
from deepspeed_tpu.telemetry.tracer import COMM_OVERLAP_TID  # noqa: F401
from deepspeed_tpu.utils.logging import logger

#: elements per fp32 scale group ("per-chunk scales"). 256 keeps scale
#: overhead at 4/256 = 1.6% of the code bytes.
DEFAULT_CHUNK = 256

#: wire dtype name -> (jnp dtype factory name, clip max, itemsize). The jnp
#: dtype is resolved lazily (this module must import jax-free).
WIRE_DTYPES: Dict[str, Tuple[str, float, int]] = {
    "int8": ("int8", 127.0, 1),
    "fp8": ("float8_e4m3fn", 448.0, 1),
}



class CommCompressionConfig(DeepSpeedTPUConfigModel):
    """The ``"comm_compression"`` config group (default OFF = today's exact
    semantics: no extra state, no new collectives, bit-identical steps)."""
    enabled: bool = False
    # int8 | fp8 (e4m3) codes on the wire; scales are always fp32 per chunk
    wire_dtype: str = "int8"
    # elements per scale group
    chunk: int = DEFAULT_CHUNK
    # persistent per-tensor worker+server residuals (EQuARX error feedback);
    # disabling drops the state and accepts the per-step quantization bias
    error_feedback: bool = True
    # gradient bytes per reduction bucket (accumulation dtype); each filled
    # bucket issues its own quantized collective during backward
    bucket_bytes: int = 4 << 20
    # False collapses the scheduler to ONE fused bucket (compression without
    # the per-bucket overlap structure)
    overlap: bool = True
    # leaves below this many elements reduce in full precision (norm scales
    # and biases are bandwidth-irrelevant and the most quantization-
    # sensitive — same rationale as qgZ's MIN_QUANT_SIZE)
    min_size: int = 2048

    @model_validator(mode="after")
    def _check(self):
        if self.wire_dtype not in WIRE_DTYPES:
            raise ValueError(f"comm_compression.wire_dtype must be one of "
                             f"{sorted(WIRE_DTYPES)}, got {self.wire_dtype!r}")
        if self.chunk < 8:
            raise ValueError(f"comm_compression.chunk must be >= 8, "
                             f"got {self.chunk}")
        if self.bucket_bytes < 1:
            raise ValueError(f"comm_compression.bucket_bytes must be >= 1, "
                             f"got {self.bucket_bytes}")
        if self.min_size < 0:
            raise ValueError(f"comm_compression.min_size must be >= 0, "
                             f"got {self.min_size}")
        return self


# ---------------------------------------------------------------------------
# analytic wire-byte accounting (pure ints — shared by the facade recording,
# the plan proposal table's standalone copy, and the tests' exact asserts)
# ---------------------------------------------------------------------------
def wire_itemsize(wire_dtype: str) -> int:
    return WIRE_DTYPES[wire_dtype][2]


def padded_elems(n: int, world: int, chunk: int = DEFAULT_CHUNK) -> int:
    """Flat element count padded so every participant's shard is whole
    chunks: the smallest multiple of ``world * chunk`` >= n."""
    align = world * chunk
    return ((n + align - 1) // align) * align


def wire_payload_bytes(n_elems: int, wire_dtype: str = "int8",
                       chunk: int = DEFAULT_CHUNK) -> int:
    """Bytes on the wire for ONE phase moving ``n_elems``: codes plus the
    fp32 per-chunk scales."""
    return n_elems * wire_itemsize(wire_dtype) + 4 * math.ceil(n_elems / chunk)


def all_reduce_wire_bytes(n: int, world: int, wire_dtype: str = "int8",
                          chunk: int = DEFAULT_CHUNK) -> int:
    """Single-payload wire volume of the quantized all-reduce (same
    convention as the facade's ``bytes``: one phase's payload; the
    exchange+regather ring multiple lives in the busbw factor)."""
    return wire_payload_bytes(padded_elems(n, world, chunk), wire_dtype, chunk)


def reduce_scatter_wire_bytes(n: int, world: int, wire_dtype: str = "int8",
                              chunk: int = DEFAULT_CHUNK) -> int:
    return wire_payload_bytes(padded_elems(n, world, chunk), wire_dtype, chunk)


# ---------------------------------------------------------------------------
# the codec (runs at trace time inside shard_map — registered DS002 hot path:
# pure jnp, never a host sync)
# ---------------------------------------------------------------------------
def _wire_jnp(wire_dtype: str):
    import jax.numpy as jnp
    name, clip, _ = WIRE_DTYPES[wire_dtype]
    return getattr(jnp, name), clip


def quantize_wire(x, wire_dtype: str = "int8", chunk: int = DEFAULT_CHUNK):
    """Flat fp array [n] (n divisible by chunk) -> (codes [n] in the wire
    dtype, fp32 scales [n/chunk]). Symmetric per-chunk absmax scaling."""
    import jax.numpy as jnp
    dt, clip = _wire_jnp(wire_dtype)
    xc = x.astype(jnp.float32).reshape(-1, chunk)
    absmax = jnp.max(jnp.abs(xc), axis=-1, keepdims=True)
    scale = jnp.maximum(absmax / clip, 1e-12)
    if wire_dtype == "int8":
        codes = jnp.clip(jnp.round(xc / scale), -clip, clip).astype(dt)
    else:
        codes = jnp.clip(xc / scale, -clip, clip).astype(dt)
    return codes.reshape(-1), scale.reshape(-1)


def dequantize_wire(codes, scales, chunk: int = DEFAULT_CHUNK):
    """Inverse of ``quantize_wire`` -> flat fp32 [n]."""
    import jax.numpy as jnp
    return (codes.reshape(-1, chunk).astype(jnp.float32)
            * scales.reshape(-1, 1)).reshape(-1)


def ef_step(x, error, wire_dtype: str = "int8", chunk: int = DEFAULT_CHUNK):
    """One error-feedback compression step: compensate with the residual,
    quantize, and record the fresh compression error.

    Returns ``(codes, scales, new_error)`` with the invariant
    ``new_error == (x + error) - dequantize(codes, scales)`` exactly.
    ``error=None`` (feedback off) behaves as a zero residual and returns
    ``new_error=None``."""
    comp = x if error is None else x + error
    codes, scales = quantize_wire(comp, wire_dtype, chunk)
    new_error = None if error is None \
        else comp - dequantize_wire(codes, scales, chunk)
    return codes, scales, new_error


# ---------------------------------------------------------------------------
# in-shard_map collective impls (manual over ``axes``)
# ---------------------------------------------------------------------------
def axis_world(axes: Sequence[str]) -> int:
    """Static participant count of the axis group (trace-time constant
    inside shard_map)."""
    from jax import lax
    w = 1
    for ax in axes:
        w = w * lax.psum(1, ax)
    return w


def _exchange(x2d, axis: str):
    """All-to-all a [w, m] array over ONE mesh axis: row j of the result is
    the chunk peer j sent. Multi-axis groups compose this per axis in the
    hierarchical loops of ``reduce_scatter_impl`` / ``all_reduce_impl``."""
    from jax import lax
    return lax.all_to_all(x2d, axis, split_axis=0, concat_axis=0,
                          tiled=False)


def _regather(x, axis: str):
    """All-gather local shards over ONE mesh axis, ordered to match
    ``_exchange``'s participant numbering."""
    from jax import lax
    return lax.all_gather(x, axis, axis=0, tiled=True)


def reduce_scatter_impl(x, axes: Sequence[str], wire_dtype: str = "int8",
                        chunk: int = DEFAULT_CHUNK, worker_error=None,
                        mean: bool = True):
    """Quantized reduce-scatter of a flat [n] payload over ``axes`` (call
    inside shard_map manual over at least ``axes``): error-feedback
    compress, exchange int8/fp8 chunks + scales, dequant-reduce on the
    receiver (the reference ``all_to_all_quant_reduce`` /
    ``quant_reduce.cu`` scheme, generalized to any axis group).

    Multi-axis groups reduce HIERARCHICALLY, innermost axis first (``axes``
    arrive outermost-first, the mesh convention): the full payload rides
    only the innermost/fast hop and each outer/slow hop carries the
    already-reduced 1/w shard — the qgZ intra-node-then-inter-node
    structure. Error feedback applies at the first (full-payload)
    quantization; outer hops re-quantize their shard without a residual,
    exactly like the pre-existing ``quantized_psum``.

    Returns ``(local_sum_or_mean [n_pad / W], new_worker_error [n_pad])``.
    """
    import jax
    import jax.numpy as jnp
    from jax import lax
    w = axis_world(axes)
    n = x.size
    n_pad = padded_elems(n, w, chunk)
    flat = x.astype(jnp.float32).reshape(-1)
    if n_pad != n:
        flat = jnp.concatenate(
            [flat, jnp.zeros((n_pad - n,), jnp.float32)])
    shard = flat
    new_worker_error = None
    for i, ax in enumerate(reversed(tuple(axes))):   # innermost first
        if i == 0:
            codes, scales, new_worker_error = ef_step(
                shard, worker_error, wire_dtype, chunk)
        else:
            codes, scales, _ = ef_step(shard, None, wire_dtype, chunk)
        wk = lax.psum(1, ax)
        cx = _exchange(codes.reshape(wk, -1), ax)
        sx = _exchange(scales.reshape(wk, -1), ax)
        deq = jax.vmap(lambda c, s: dequantize_wire(c, s, chunk))(cx, sx)
        shard = deq.sum(0)
    if mean:
        shard = shard / w
    return shard, new_worker_error


def all_reduce_impl(x, axes: Sequence[str], wire_dtype: str = "int8",
                    chunk: int = DEFAULT_CHUNK, worker_error=None,
                    server_error=None, mean: bool = True):
    """Quantized all-reduce over ``axes``: hierarchical reduce-scatter
    (worker error feedback on the first hop), re-quantize the reduced
    shard ONCE (server error feedback), and regather the codes + scales
    axis by axis in LIFO order — gathering is pure concatenation, so the
    regather needs no further quantization and every hop stays int8/fp8.
    Returns ``(full [n_pad], new_worker_error, new_server_error)`` —
    slice to ``x.size`` if the exact shape matters."""
    shard, new_worker_error = reduce_scatter_impl(
        x, axes, wire_dtype, chunk, worker_error=worker_error, mean=mean)
    codes, scales, new_server_error = ef_step(shard, server_error,
                                              wire_dtype, chunk)
    for ax in tuple(axes):     # inverts the reversed-order scatter (LIFO)
        codes = _regather(codes, ax)
        scales = _regather(scales, ax)
    out = dequantize_wire(codes, scales, chunk)
    return out, new_worker_error, new_server_error


# ---------------------------------------------------------------------------
# error-feedback state (threaded through the engine's optimizer state)
# ---------------------------------------------------------------------------
class TensorEF(NamedTuple):
    """Per-bucket error-feedback residuals. Leading dim = the axis-group
    world W (each participant owns its row — sharded over the replica axes,
    so the state is one global array that checkpoints and reshards like any
    optimizer moment): ``worker`` [W, n_pad] is the local compression
    error, ``server`` [W, n_pad / W] the regather re-quantization error."""
    worker: Any
    server: Any


class CommCompressState(NamedTuple):
    """Optimizer-state wrapper carrying the error-feedback residuals next
    to the real optax state: ``inner`` is whatever the wrapped optimizer
    keeps, ``error_feedback`` a tuple of per-bucket ``TensorEF``. Saved and
    restored as ordinary optimizer state by the checkpoint engine. Across
    a replica-world change the residuals are ADOPTED, not reset: both
    resume paths (direct row-prefix restore and the structure-changed
    mining fallback) re-spread the surviving participants' mean via
    ``reshard_error_feedback`` — mean-preserving, so the correction mass
    the next reduction repays is unchanged; only an unrecognizable bucket
    plan (different model/config) falls back to fresh zeros, with the
    moments preserved either way — never a crash."""
    inner: Any
    error_feedback: Tuple[TensorEF, ...]


def with_error_feedback(tx, ef_init_fn):
    """Wrap an optax ``GradientTransformation`` so its state is a
    ``CommCompressState``: the optimizer half updates normally against
    ``inner``; the residual half passes through untouched (the engine's
    compiled step swaps fresh residuals in at the gradient-sync boundary,
    gated on overflow exactly like the moments)."""
    import optax

    def init(params):
        return CommCompressState(inner=tx.init(params),
                                 error_feedback=ef_init_fn())

    def update(updates, state, params=None):
        upd, new_inner = tx.update(updates, state.inner, params)
        return upd, CommCompressState(inner=new_inner,
                                      error_feedback=state.error_feedback)

    return optax.GradientTransformation(init, update)


def reshard_error_feedback(ef: TensorEF, new_world: int,
                           surviving: Optional[int] = None,
                           xp=None) -> TensorEF:
    """THE mesh-portable residual reshard rule (both checkpoint adoption
    paths call this — never a local copy): the mean over the surviving old
    participants is the correction mass the next reduction would have
    repaid, so giving every NEW participant that mean preserves it exactly
    (mean over the new group == mean over the survivors). Server shards
    are per-participant chunks of the payload: a changed world changes the
    chunking, so only the worker residual transfers and the server
    residual restarts at zero (one regather hop of bias).

    ``surviving`` restricts the mean to the leading rows (the row-prefix a
    direct cross-world restore preserves); ``xp`` selects the array module
    — default jax.numpy (device path), the checkpoint's host-mining path
    passes numpy so nothing materializes on one device."""
    if xp is None:
        import jax.numpy as xp
    worker = ef.worker
    rows = int(worker.shape[0]) if surviving is None else int(surviving)
    mean = xp.mean(worker[:max(rows, 1)], axis=0, keepdims=True)
    n_pad = int(worker.shape[1])
    new_worker = xp.repeat(mean.astype(xp.float32), new_world, axis=0)
    server = xp.zeros((new_world, n_pad // new_world), xp.float32) \
        if n_pad % new_world == 0 else xp.zeros((new_world, 0), xp.float32)
    return TensorEF(worker=new_worker, server=server)


# ---------------------------------------------------------------------------
# gradient-bucket scheduler
# ---------------------------------------------------------------------------
class Bucket(NamedTuple):
    index: int
    paths: Tuple[str, ...]
    sizes: Tuple[int, ...]          # flat element count per leaf
    shapes: Tuple[Tuple[int, ...], ...]
    n: int                          # total elements (unpadded)
    n_pad: int                      # padded to world * chunk
    logical_bytes: int              # UNPADDED accumulation-dtype payload —
    #                                 what the dense reduction would move
    #                                 (matches the facade's recorded bytes)
    wire_bytes: int                 # codes + scales payload (padded)


def plan_buckets(leaves: List[Tuple[str, Tuple[int, ...]]], world: int,
                 cfg: CommCompressionConfig,
                 itemsize: int = 4) -> List[Bucket]:
    """Deterministic bucket partition of the quantized leaves (already
    filtered by the caller): greedy fill in tree-flatten order (the order
    backward produces gradients) closing a bucket once it holds
    ``bucket_bytes``; ``overlap=False`` collapses to ONE fused bucket."""
    buckets: List[Bucket] = []
    cur: List[Tuple[str, Tuple[int, ...]]] = []
    cur_bytes = 0

    def close():
        if not cur:
            return
        sizes = tuple(int(math.prod(s)) if s else 1 for _, s in cur)
        n = sum(sizes)
        n_pad = padded_elems(n, world, cfg.chunk)
        buckets.append(Bucket(
            index=len(buckets),
            paths=tuple(p for p, _ in cur),
            sizes=sizes,
            shapes=tuple(tuple(s) for _, s in cur),
            n=n, n_pad=n_pad,
            logical_bytes=n * itemsize,
            wire_bytes=wire_payload_bytes(n_pad, cfg.wire_dtype, cfg.chunk)))
        cur.clear()

    for path, shape in leaves:
        size = int(math.prod(shape)) if shape else 1
        cur.append((path, tuple(shape)))
        cur_bytes += size * itemsize
        if cfg.overlap and cur_bytes >= cfg.bucket_bytes:
            close()
            cur_bytes = 0
    close()
    return buckets


class GradCompressor:
    """The engine-facing half: owns the bucket plan, the error-feedback
    state layout, and the manual-region sync function. Built once per
    engine from the parameter tree (the plan is a pure function of the
    model + config, so a checkpoint resumed with the same config restores
    residuals leaf-for-leaf)."""

    def __init__(self, cfg: CommCompressionConfig, axes: Sequence[str],
                 mesh):
        self.cfg = cfg
        self.axes = tuple(axes)
        self.world = 1
        for ax in self.axes:
            self.world *= int(mesh.shape[ax])
        self.buckets: List[Bucket] = []
        self._skipped: Tuple[str, ...] = ()

    # -- planning (host-side, build time) --------------------------------
    def build(self, params, itemsize: int = 4,
              exclude_paths: Sequence[str] = ()) -> "GradCompressor":
        import jax
        import numpy as np
        from deepspeed_tpu.utils.tree import tree_path_str
        excluded = set(exclude_paths)
        quantized, skipped = [], []
        for path, leaf in jax.tree_util.tree_flatten_with_path(params)[0]:
            p = tree_path_str(path)
            shape = tuple(np.shape(leaf))
            size = int(np.size(leaf))
            dt = np.dtype(getattr(leaf, "dtype", np.float32))
            if (p in excluded or size < self.cfg.min_size
                    or not np.issubdtype(dt, np.floating)):
                skipped.append(p)
                continue
            quantized.append((p, shape))
        self.buckets = plan_buckets(quantized, self.world, self.cfg,
                                    itemsize=itemsize)
        self._skipped = tuple(skipped)
        logger.info(
            "comm_compression: %d bucket(s) over axes %s (world %d, "
            "wire=%s chunk=%d, %d leaves quantized / %d full-precision); "
            "logical %.2f MB -> wire %.2f MB per reduction",
            len(self.buckets), self.axes, self.world, self.cfg.wire_dtype,
            self.cfg.chunk, sum(len(b.paths) for b in self.buckets),
            len(skipped),
            sum(b.logical_bytes for b in self.buckets) / 1e6,
            sum(b.wire_bytes for b in self.buckets) / 1e6)
        return self

    def bucket_summaries(self) -> List[Dict[str, Any]]:
        """Per-bucket metadata for the ``comm/overlap`` spans and tests."""
        return [{"index": b.index, "leaves": len(b.paths), "n": b.n,
                 "n_pad": b.n_pad, "bytes": b.logical_bytes,
                 "wire_bytes": b.wire_bytes} for b in self.buckets]

    # -- error-feedback state layout -------------------------------------
    def ef_enabled(self) -> bool:
        return bool(self.cfg.error_feedback and self.buckets)

    def zero_error_feedback(self) -> Tuple[TensorEF, ...]:
        """Fresh residuals (call under jit with the matching out_shardings
        so zeros materialize sharded)."""
        import jax.numpy as jnp
        if not self.ef_enabled():
            return ()
        return tuple(
            TensorEF(worker=jnp.zeros((self.world, b.n_pad), jnp.float32),
                     server=jnp.zeros((self.world, b.n_pad // self.world),
                                      jnp.float32))
            for b in self.buckets)

    def _axes_entry(self):
        return self.axes[0] if len(self.axes) == 1 else self.axes

    def ef_partition_specs(self):
        """shard_map specs for the EF tree: manual over the replica axes on
        the participant dim (each worker sees its own [1, n] row)."""
        from jax.sharding import PartitionSpec as P
        if not self.ef_enabled():
            return ()
        spec = P(self._axes_entry())
        return tuple(TensorEF(worker=spec, server=spec) for _ in self.buckets)

    def error_feedback_shardings(self, mesh):
        from jax.sharding import NamedSharding, PartitionSpec as P
        if not self.ef_enabled():
            return ()
        s = NamedSharding(mesh, P(self._axes_entry()))
        return tuple(TensorEF(worker=s, server=s) for _ in self.buckets)

    # -- the manual-region sync (trace time; DS002 hot path) -------------
    def make_sync_fn(self, fallback_leaf_sync=None):
        """Build ``sync_fn(grads, batch, ef) -> (reduced_grads, new_ef)``
        for ``wrap_grads_phase``: per bucket, concatenate the member leaves
        flat, run ONE facade-recorded quantized all-reduce (error feedback
        threaded), and split back. Leaves outside every bucket fall back to
        ``fallback_leaf_sync(path, grad, batch)`` (default: full-precision
        pmean — the engine passes its sparse-embedding composite here)."""
        import jax
        import jax.numpy as jnp
        from deepspeed_tpu.comm.comm import quantized_all_reduce
        from deepspeed_tpu.utils.tree import tree_path_str

        cfg, axes, buckets = self.cfg, self.axes, self.buckets
        path_to_bucket: Dict[str, Tuple[int, int]] = {}
        for b in buckets:
            for i, p in enumerate(b.paths):
                path_to_bucket[p] = (b.index, i)

        def default_fallback(path, g, batch):
            return jax.lax.pmean(g, axes)

        fallback = fallback_leaf_sync or default_fallback

        def sync_fn(grads, batch, ef):
            flat = {tree_path_str(p): (p, g) for p, g in
                    jax.tree_util.tree_flatten_with_path(grads)[0]}
            reduced: Dict[str, Any] = {}
            new_ef: List[Optional[TensorEF]] = [None] * len(buckets)
            for b in buckets:
                parts = [flat[p][1] for p in b.paths]
                # keep the ACCUMULATION dtype on the payload the facade
                # records: the logical bytes must be what the dense
                # reduction would have moved (2n for bf16 accumulation,
                # not an fp32-inflated 4n) — the impl casts to fp32
                # internally for the quantize math either way
                dt = jnp.result_type(*(x.dtype for x in parts))
                payload = jnp.concatenate(
                    [x.astype(dt).reshape(-1) for x in parts])
                bucket_ef = ef[b.index] if ef else None
                # each participant's EF row rides in with a leading
                # singleton (the manual shard of the [W, n] state)
                err = None
                if bucket_ef is not None:
                    err = TensorEF(worker=bucket_ef.worker[0],
                                   server=bucket_ef.server[0])
                out, err_out = quantized_all_reduce(
                    payload, axes, wire_dtype=cfg.wire_dtype,
                    chunk=cfg.chunk, error=err)
                if bucket_ef is not None and err_out is not None:
                    new_ef[b.index] = TensorEF(
                        worker=err_out.worker[None],
                        server=err_out.server[None])
                off = 0
                for p, size, shape in zip(b.paths, b.sizes, b.shapes):
                    g = flat[p][1]
                    reduced[p] = out[off:off + size].reshape(shape) \
                        .astype(g.dtype)
                    off += size
            for p, (path, g) in flat.items():
                if p not in reduced:
                    reduced[p] = fallback(path, g, batch)

            out_grads = jax.tree_util.tree_map_with_path(
                lambda path, _: reduced[tree_path_str(path)], grads)
            ef_out = tuple(new_ef) if ef else ()
            return out_grads, ef_out

        return sync_fn
