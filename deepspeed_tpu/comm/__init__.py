from deepspeed_tpu.comm.comm import (
    ReduceOp,
    all_gather,
    all_reduce,
    all_to_all,
    barrier,
    broadcast_one_to_all,
    device_broadcast,
    ppermute,
    reduce_scatter,
)  # noqa: F401
from deepspeed_tpu.comm.comms_logging import CommsLogger, get_comms_logger
from deepspeed_tpu.comm.mesh import (
    BATCH_AXES,
    MESH_AXES,
    batch_sharding,
    create_mesh,
    get_data_parallel_world_size,
    get_expert_parallel_world_size,
    get_global_mesh,
    get_model_parallel_world_size,
    get_pipe_parallel_world_size,
    get_seq_data_parallel_world_size,
    get_sequence_parallel_world_size,
    init_distributed,
    replicated,
    set_global_mesh,
)

__all__ = [
    "ReduceOp", "all_reduce", "all_gather", "reduce_scatter", "all_to_all", "ppermute",
    "broadcast_one_to_all", "barrier", "device_broadcast", "CommsLogger",
    "get_comms_logger", "MESH_AXES", "BATCH_AXES", "create_mesh", "batch_sharding",
    "replicated", "init_distributed", "get_global_mesh", "set_global_mesh",
    "get_data_parallel_world_size", "get_seq_data_parallel_world_size",
    "get_model_parallel_world_size", "get_expert_parallel_world_size",
    "get_sequence_parallel_world_size", "get_pipe_parallel_world_size",
]
