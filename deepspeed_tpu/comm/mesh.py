"""Device-mesh factory — the TPU-native process-group layer.

Reference analog: ``deepspeed/utils/groups.py`` (dp/mp/ep/sp group factories,
``_create_expert_and_data_parallel:117``, SP accessors ``:472-524``) and
``comm.init_distributed`` / ``initialize_mesh_device`` (``deepspeed/comm/comm.py:619,603``).

On TPU, process groups are *named mesh axes* of one ``jax.sharding.Mesh``:

    axes (outer→inner): ('pipe', 'data', 'fsdp_out', 'fsdp', 'expert',
                         'sequence', 'tensor')

- ``data``     — pure data parallelism (batch sharding, grad all-reduce)
- ``fsdp``     — ZeRO/FSDP parameter+optimizer sharding (reference ZeRO's dp partition)
- ``tensor``   — tensor (Megatron-style) model parallelism; innermost so its
                 collectives ride the fastest ICI links
- ``sequence`` — Ulysses/context parallelism over the sequence dimension
- ``expert``   — MoE expert parallelism (all_to_all dispatch axis)
- ``pipe``     — pipeline stages; outermost so stages map onto distinct ICI
                 sub-slices (or onto DCN slices in multi-slice)

The combined (data × fsdp × sequence) extent is the "seq-dp" world that the reference's
ZeRO runs over (``runtime/engine.py:1190 seq_data_parallel_group``).

Multi-slice: axes named in ``MeshConfig.dcn_axes`` are laid out across slices
(DCN) using ``jax.experimental.mesh_utils.create_hybrid_device_mesh``.
"""

import os
from typing import Dict, List, Optional, Sequence, Tuple

import jax
import numpy as np
from jax.sharding import Mesh, NamedSharding, PartitionSpec

from deepspeed_tpu.config.config import MeshConfig
from deepspeed_tpu.utils.logging import log_dist, logger

# Canonical axis order, outermost (slowest, DCN-friendly) first. ``fsdp_out`` is
# the hierarchical-sharding replica axis (size 1 unless MiCS / ZeRO++ hpZ splits
# the ZeRO world): MiCS shards params over the inner ``fsdp`` sub-axis and
# replicates across ``fsdp_out`` (reference runtime/zero/mics.py:64); hpZ keeps
# the secondary compute shard on ``fsdp`` so per-layer gathers stay node-local
# (reference partition_parameters.py:1664 _partition_param_sec).
MESH_AXES: Tuple[str, ...] = ("pipe", "data", "fsdp_out", "fsdp", "expert",
                              "sequence", "tensor")

# Axes over which a replicated batch is split (DP world for batch-size math).
BATCH_AXES: Tuple[str, ...] = ("data", "fsdp_out", "fsdp")

# The full ZeRO sharding world (what stage 1-3 partition over).
FSDP_AXES: Tuple[str, ...] = ("fsdp_out", "fsdp")

_global_mesh: Optional[Mesh] = None


def resolve_axis_sizes(cfg: MeshConfig, n_devices: int) -> Dict[str, int]:
    """Fill the single -1 axis with the remaining device count; validate product."""
    sizes = {
        "pipe": cfg.pipe, "data": cfg.data,
        "fsdp_out": getattr(cfg, "fsdp_outer", 1), "fsdp": cfg.fsdp,
        "expert": cfg.expert, "sequence": cfg.sequence, "tensor": cfg.tensor,
    }
    unknown = [k for k, v in sizes.items() if v == -1]
    if len(unknown) > 1:
        raise ValueError(f"at most one mesh axis may be -1, got {unknown}")
    known = int(np.prod([v for v in sizes.values() if v != -1]))
    if unknown:
        if n_devices % known != 0:
            raise ValueError(
                f"device count {n_devices} not divisible by fixed axes product {known}")
        sizes[unknown[0]] = n_devices // known
    total = int(np.prod(list(sizes.values())))
    if total != n_devices:
        raise ValueError(
            f"mesh axes product {total} != device count {n_devices} (sizes={sizes})")
    return sizes


def create_mesh(cfg: Optional[MeshConfig] = None,
                devices: Optional[Sequence] = None) -> Mesh:
    """Build the named-axis mesh. ``devices`` defaults to all global devices."""
    cfg = cfg or MeshConfig()
    devices = list(devices) if devices is not None else jax.devices()
    sizes = resolve_axis_sizes(cfg, len(devices))
    shape = tuple(sizes[a] for a in MESH_AXES)

    dcn_axes = list(cfg.dcn_axes or [])
    if dcn_axes:
        from jax.experimental import mesh_utils
        ici_shape = tuple(1 if a in dcn_axes else sizes[a] for a in MESH_AXES)
        dcn_shape = tuple(sizes[a] if a in dcn_axes else 1 for a in MESH_AXES)
        try:
            device_array = mesh_utils.create_hybrid_device_mesh(
                ici_shape, dcn_shape, devices=devices)
        except Exception as e:  # single-slice / CPU: no slice_index attribute
            logger.warning(f"hybrid mesh unavailable ({e}); falling back to flat mesh")
            device_array = np.asarray(devices).reshape(shape)
    else:
        try:
            from jax.experimental import mesh_utils
            device_array = mesh_utils.create_device_mesh(shape, devices=devices)
        except Exception:
            device_array = np.asarray(devices).reshape(shape)

    mesh = Mesh(device_array, MESH_AXES)
    log_dist(f"created mesh {dict(zip(MESH_AXES, shape))} over {len(devices)} devices",
             ranks=[0])
    return mesh


def get_global_mesh() -> Optional[Mesh]:
    return _global_mesh


def set_global_mesh(mesh: Mesh) -> None:
    global _global_mesh
    _global_mesh = mesh


# --- world-size accessors (reference: utils/groups.py get_*_world_size) -----

def axis_size(mesh: Mesh, axis: str) -> int:
    return mesh.shape[axis]


def get_data_parallel_world_size(mesh: Mesh) -> int:
    """DP world for batch math = data × fsdp_out × fsdp (ZeRO shards inside DP).
    Tolerates user-built meshes that omit the optional fsdp_out axis."""
    return int(np.prod([mesh.shape.get(a, 1) for a in BATCH_AXES]))


def get_seq_data_parallel_world_size(mesh: Mesh) -> int:
    """reference engine.py:1190: ZeRO runs over the seq×dp group under SP."""
    return get_data_parallel_world_size(mesh) * mesh.shape["sequence"]


def get_model_parallel_world_size(mesh: Mesh) -> int:
    return mesh.shape["tensor"]

def get_expert_parallel_world_size(mesh: Mesh) -> int:
    return mesh.shape["expert"]

def get_sequence_parallel_world_size(mesh: Mesh) -> int:
    return mesh.shape["sequence"]

def get_pipe_parallel_world_size(mesh: Mesh) -> int:
    return mesh.shape["pipe"]


def batch_axes(mesh: Mesh) -> Tuple[str, ...]:
    """The DP axes present in this mesh — tolerates hand-built meshes that omit
    the optional ``fsdp_out`` axis (NamedSharding rejects unknown axis names)."""
    return tuple(a for a in BATCH_AXES if a in mesh.shape)


def batch_sharding(mesh: Mesh) -> NamedSharding:
    """Sharding for a [batch, ...] array: batch split over the DP axes."""
    return NamedSharding(mesh, PartitionSpec(batch_axes(mesh)))


def replicated(mesh: Mesh) -> NamedSharding:
    return NamedSharding(mesh, PartitionSpec())


def discover_cluster_env() -> dict:
    """Rendezvous discovery chain (reference: ``comm/comm.py:619``
    init_distributed env:// + ``mpi_discovery:688`` + the AML/AWS-SM env
    patching ``:744,:776``): DSTPU_* > torch-style RANK/WORLD_SIZE/MASTER_ADDR
    > OpenMPI OMPI_COMM_WORLD_* > SLURM_*. Returns possibly-empty kwargs for
    ``jax.distributed.initialize``."""
    env = os.environ
    out = {}
    # DSTPU_* vars are independent (any launcher may set a subset)
    if "DSTPU_NUM_PROCESSES" in env:
        out["num_processes"] = int(env["DSTPU_NUM_PROCESSES"])
    if "DSTPU_PROCESS_ID" in env:
        out["process_id"] = int(env["DSTPU_PROCESS_ID"])
    if env.get("DSTPU_COORDINATOR_ADDRESS"):
        out["coordinator_address"] = env["DSTPU_COORDINATOR_ADDRESS"]
    if out:
        return out
    # torch-style: the full triple is only ever set together by a launcher, so
    # requiring all three avoids hijacking unrelated runs
    if "WORLD_SIZE" in env and "RANK" in env and env.get("MASTER_ADDR"):
        return {"num_processes": int(env["WORLD_SIZE"]),
                "process_id": int(env["RANK"]),
                "coordinator_address":
                    f"{env['MASTER_ADDR']}:{env.get('MASTER_PORT', '29500')}"}
    # MPI/SLURM allocations leak their env into interactive shells (a bare
    # python under sbatch sees SLURM_NTASKS), so these are opt-in — the analog
    # of the reference's auto_mpi_discovery arg (comm/comm.py:619)
    if env.get("DSTPU_AUTO_MPI_DISCOVERY") != "1":
        return {}
    if "OMPI_COMM_WORLD_SIZE" in env:             # mpirun (mpi_discovery)
        out["num_processes"] = int(env["OMPI_COMM_WORLD_SIZE"])
        out["process_id"] = int(env["OMPI_COMM_WORLD_RANK"])
        if env.get("MASTER_ADDR"):
            out["coordinator_address"] = \
                f"{env['MASTER_ADDR']}:{env.get('MASTER_PORT', '29500')}"
        else:
            # mpirun sets no MASTER_ADDR; the reference bcasts rank 0's IP
            # over MPI (comm.py:688 mpi_discovery) — same here when mpi4py
            # is present, else the user must export MASTER_ADDR
            host = None
            try:
                from mpi4py import MPI
                host = MPI.COMM_WORLD.bcast(_non_loopback_ip(), root=0)
            except Exception as e:   # degrade, never crash startup
                logger.warning(f"OMPI discovery failed ({e})")
            if host:
                out["coordinator_address"] = \
                    f"{host}:{env.get('MASTER_PORT', '29500')}"
            else:
                logger.warning(
                    "OMPI discovery: cannot derive the coordinator address; "
                    "export MASTER_ADDR to rendezvous")
    elif "SLURM_NTASKS" in env and "SLURM_PROCID" in env:   # srun
        out["num_processes"] = int(env["SLURM_NTASKS"])
        out["process_id"] = int(env["SLURM_PROCID"])
        nodelist = env.get("SLURM_STEP_NODELIST", env.get("SLURM_NODELIST", ""))
        head = _slurm_head_node(nodelist)
        if head:
            out["coordinator_address"] = \
                f"{head}:{env.get('MASTER_PORT', '29500')}"
    return out


def _non_loopback_ip() -> str:
    """This host's outbound-interface IP (reference mpi_discovery uses
    ``hostname -I``'s first entry for the same reason:
    gethostbyname(gethostname()) is 127.0.1.1 on stock Debian images)."""
    import socket
    s = socket.socket(socket.AF_INET, socket.SOCK_DGRAM)
    try:
        s.connect(("8.8.8.8", 80))   # no traffic sent; routes the socket
        return s.getsockname()[0]
    except OSError:
        ip = socket.gethostbyname(socket.gethostname())
        return "" if ip.startswith("127.") else ip
    finally:
        s.close()


def _slurm_head_node(nodelist: str) -> str:
    """First hostname of a SLURM nodelist. Handles hyphenated prefixes and
    bracket ranges: ``tpu-pod-node[1-4,7]`` -> ``tpu-pod-node1``."""
    import re
    first = nodelist.split(",")[0].strip()
    m = re.match(r"^([^\[]+)\[(\d+)", first)
    if m:
        return m.group(1) + m.group(2)
    return first


def init_distributed(coordinator_address: Optional[str] = None,
                     num_processes: Optional[int] = None,
                     process_id: Optional[int] = None,
                     deadline_s: Optional[float] = None,
                     retries: Optional[int] = None,
                     backoff_s: Optional[float] = None) -> None:
    """Multi-host bring-up (reference: comm.init_distributed env:// rendezvous,
    comm/comm.py:619). On TPU pods JAX auto-discovers peers from the TPU metadata;
    explicit args support DCN/CPU clusters; env discovery covers torchrun/MPI/
    SLURM launches (``discover_cluster_env``). No-op when single-process.

    The rendezvous is WEDGE-PROOF: it runs under ``comm.guard.bounded_init``
    — a deadline (``deadline_s``, default 300s, env override
    ``DSTPU_COMM_INIT_DEADLINE_S``, 0 = unbounded) turns a hung coordinator
    into a ``CommWedgeError`` instead of an infinite hang, and TRANSIENT
    failures (coordinator not up yet, connection refused/reset) are retried
    with exponential backoff instead of crashing the worker the platform
    just relaunched a second before its peers."""
    disc = discover_cluster_env()
    if num_processes is None:
        num_processes = disc.get("num_processes", 1)
    if coordinator_address is None:
        coordinator_address = disc.get("coordinator_address")
    if process_id is None:
        process_id = disc.get("process_id")
    if num_processes <= 1 and coordinator_address is None:
        return
    kwargs = {}
    if coordinator_address:
        kwargs["coordinator_address"] = coordinator_address
    if num_processes:
        kwargs["num_processes"] = num_processes
    if process_id is not None:
        kwargs["process_id"] = process_id

    from deepspeed_tpu.comm.guard import (INIT_BACKOFF_ENV, INIT_DEADLINE_ENV,
                                          INIT_RETRIES_ENV, bounded_init)

    def _env(name, cast, default):
        try:
            return cast(os.environ.get(name, default))
        except ValueError:
            return cast(default)

    # explicit args win; else the DSTPU_COMM_INIT_* env (exported by the
    # elastic agent from the "comm_guard" config group) configures the
    # rendezvous budget for relaunched workers
    if deadline_s is None:
        deadline_s = _env(INIT_DEADLINE_ENV, float, 300.0)
    if retries is None:
        retries = _env(INIT_RETRIES_ENV, int, 3)
    if backoff_s is None:
        backoff_s = _env(INIT_BACKOFF_ENV, float, 1.0)
    bounded_init(lambda: jax.distributed.initialize(**kwargs),
                 name="jax_distributed", deadline_s=deadline_s,
                 retries=retries, backoff_s=backoff_s)
    # stamp the dstrace process-identity header at rendezvous: every trace
    # this worker dumps from here on carries rank/world, the join key
    # ``dstpu trace merge`` aligns per-rank timelines by
    from deepspeed_tpu.telemetry.tracer import get_tracer
    get_tracer().set_process_identity(jax.process_index(),
                                      jax.process_count())
    log_dist(f"jax.distributed initialized: {jax.process_count()} processes", ranks=[0])
