"""Error-feedback sign-compressed collectives (1-bit compression).

Reference analog: ``deepspeed/runtime/comm/compressed.py`` (``CompressedBackend.
compressed_allreduce`` — the NCCL/MPI variants in ``runtime/comm/{nccl,mpi}.py``
implement the same two-phase algorithm with cupy/mpi4py packbits). Algorithm
(1-bit Adam, arXiv:2102.02888):

1. worker compensates its tensor with its error buffer, compresses to
   ``sign × scale`` (scale = ‖x‖₂/√n), records the new compression error;
2. signs are exchanged chunk-wise (all-to-all) + scales allgathered; each worker
   averages its chunk across workers ("server" role), compensates with the
   server error buffer, compresses again;
3. compressed server chunks are allgathered so every worker ends with the full
   averaged tensor.

TPU-native shape: a pure function over a named mesh axis usable inside
``shard_map`` — ``lax.all_to_all``/``all_gather`` ride ICI/DCN, signs travel as
packed uint8 bitmaps (32× smaller than f32, matching the reference's cupy
packbits wire format).
"""

from typing import Optional, Tuple

import jax
import jax.numpy as jnp
from jax import lax


def pack_signs(bits: jnp.ndarray) -> jnp.ndarray:
    """{0,1} int array [m] (m % 8 == 0) -> uint8 [m/8] bitmap (LSB-first)."""
    b = bits.reshape(-1, 8).astype(jnp.uint8)
    weights = (jnp.uint8(1) << jnp.arange(8, dtype=jnp.uint8))
    return (b * weights).sum(-1).astype(jnp.uint8)


def unpack_signs(packed: jnp.ndarray, m: int) -> jnp.ndarray:
    """uint8 bitmap -> ±1 float32 [m]."""
    bits = (packed[:, None] >> jnp.arange(8, dtype=jnp.uint8)[None, :]) & 1
    return bits.reshape(-1)[:m].astype(jnp.float32) * 2.0 - 1.0


def _compress(x: jnp.ndarray, error: jnp.ndarray
              ) -> Tuple[jnp.ndarray, jnp.ndarray, jnp.ndarray]:
    """Error-feedback 1-bit compression: returns (scale, sign_bits, new_error).
    sign convention matches the reference (x >= 0 → +1)."""
    comp = x + error
    n = comp.size
    scale = jnp.linalg.norm(comp) / jnp.sqrt(jnp.float32(n))
    signs = (comp >= 0).astype(jnp.float32) * 2.0 - 1.0
    new_error = comp - scale * signs
    return scale, (comp >= 0).astype(jnp.uint8), new_error


def compress_local(x: jnp.ndarray, error: jnp.ndarray
                   ) -> Tuple[jnp.ndarray, jnp.ndarray]:
    """Single-party compression (the degenerate world-size-1 path): returns the
    decompressed value and the new error buffer."""
    scale, bits, new_error = _compress(x, error)
    return scale * (bits.astype(jnp.float32) * 2.0 - 1.0), new_error


def compressed_allreduce(x: jnp.ndarray,
                         worker_error: jnp.ndarray,
                         server_error: jnp.ndarray,
                         axis_name: Optional[str]
                         ) -> Tuple[jnp.ndarray, jnp.ndarray, jnp.ndarray]:
    """1-bit-compressed mean-allreduce over ``axis_name`` (call inside shard_map).

    ``x``/``worker_error``: flat [n], n divisible by 8·W;
    ``server_error``: flat [n/W]. Returns (mean_estimate [n], new_worker_error,
    new_server_error).
    """
    if axis_name is None:
        out, new_we = compress_local(x, worker_error)
        return out, new_we, server_error
    w = lax.psum(1, axis_name)
    n = x.size
    # phase 1: compress locally, exchange sign chunks + scales
    scale, bits, new_worker_error = _compress(x, worker_error)
    packed = pack_signs(bits).reshape(w, -1)          # [W, n/W/8] uint8
    recv = lax.all_to_all(packed, axis_name, split_axis=0, concat_axis=0)
    scales = lax.all_gather(scale, axis_name)         # [W]
    chunk = n // w
    peer_signs = jax.vmap(lambda p: unpack_signs(p, chunk))(recv)  # [W, n/W]
    # "server" reduce: mean of peers' compressed chunks + error feedback
    server_chunk = (peer_signs * scales[:, None]).mean(0) + server_error
    s_scale = jnp.linalg.norm(server_chunk) / jnp.sqrt(jnp.float32(chunk))
    s_bits = (server_chunk >= 0).astype(jnp.uint8)
    s_signs = s_bits.astype(jnp.float32) * 2.0 - 1.0
    new_server_error = server_chunk - s_scale * s_signs
    # phase 2: allgather compressed server chunks
    packed_s = pack_signs(s_bits)
    all_packed = lax.all_gather(packed_s, axis_name)  # [W, n/W/8]
    all_scales = lax.all_gather(s_scale, axis_name)   # [W]
    all_signs = jax.vmap(lambda p: unpack_signs(p, chunk))(all_packed)
    out = (all_signs * all_scales[:, None]).reshape(n)
    return out, new_worker_error, new_server_error


def error_buffer_shapes(n: int, world_size: int) -> Tuple[int, int]:
    """(padded_n, server_chunk) for a flat tensor of ``n`` elements: padded so
    chunks are byte-aligned per worker."""
    align = 8 * world_size
    padded = ((n + align - 1) // align) * align
    return padded, padded // world_size
