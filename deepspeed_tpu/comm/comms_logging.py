"""Communication volume / bandwidth accounting.

Reference analog: ``deepspeed/utils/comms_logging.py:67`` (``CommsLogger``) and
``calc_bw_log:34`` (alg/bus bandwidth). Two recording modes:

- ``record_traced``: called at **trace time** from the collective facade — per-op
  message sizes and world sizes are static under XLA, so totals are exact analytic
  communication volume per compiled step.
- ``timed``: context manager for eager host-driven ops — wall-clock latency plus
  alg/bus bandwidth like the reference's ``timed_op``.
"""

import time
from collections import defaultdict
from contextlib import contextmanager
from typing import Dict, List, Optional, Tuple

from deepspeed_tpu.telemetry.tracer import get_tracer
from deepspeed_tpu.utils.logging import logger


#: canonical op-name -> op-kind registry. Classification is an EXACT lookup
#: on this table (plus the explicit ``kind`` the facade passes for new ops),
#: never a substring match — "quantized_all_reduce" must take the allreduce
#: busbw factor because the table says so, and an op whose NAME merely
#: contains "all_reduce" must not silently inherit the 2(n-1)/n factor.
OP_KINDS = {
    "all_reduce": "all_reduce",
    "quantized_all_reduce": "all_reduce",
    "all_gather": "all_gather",
    "sparse_all_gather": "all_gather",
    "reduce_scatter": "reduce_scatter",
    "quantized_reduce_scatter": "reduce_scatter",
    "all_to_all": "all_to_all",
    "quantized_all_to_all": "all_to_all",
    "ppermute": "ppermute",
    "broadcast": "broadcast",
    "device_broadcast": "broadcast",
    "barrier": "barrier",
}

#: busbw = algbw * factor(n) per op kind (reference calc_bw_log ring factors)
_RING_FACTORS = {
    "all_reduce": lambda n: 2 * (n - 1) / n,
    "all_gather": lambda n: (n - 1) / n,
    "reduce_scatter": lambda n: (n - 1) / n,
    "all_to_all": lambda n: (n - 1) / n,
}


def canonical_op_kind(op_name: str, kind: str = None) -> str:
    """The op's canonical kind: an explicit ``kind`` wins, else the exact
    registry entry, else ``"other"`` (busbw == algbw)."""
    if kind:
        return kind
    return OP_KINDS.get(op_name, "other")


def calc_bw(op_name: str, size_bytes: int, duration_s: float, world: int,
            kind: str = None):
    """Algorithm vs bus bandwidth (reference: comms_logging.py:34 calc_bw_log).

    busbw scales algbw by the ring-collective traffic factor: allreduce 2(n-1)/n,
    allgather/reduce_scatter/all_to_all (n-1)/n — selected by the CANONICAL
    op kind (``canonical_op_kind``), an exact lookup, so compressed /
    quantized op names can never misclassify the factor.

    Degenerate inputs are guarded, not propagated: a zero/negative duration
    (clock granularity on a fast op) or a negative size yields (0, 0)
    instead of inf/garbage, and ``world <= 1`` reports busbw == algbw — the
    ring factor would otherwise multiply a single-member op down to a 0
    busbw that reads as "link dead" on a dashboard.
    """
    if duration_s <= 0 or size_bytes < 0:
        return 0.0, 0.0
    algbw = size_bytes / duration_s
    n = max(world, 1)
    if n == 1:
        return algbw, algbw     # no inter-member traffic to scale by
    factor = _RING_FACTORS.get(canonical_op_kind(op_name, kind))
    busbw = algbw * factor(n) if factor else algbw
    return algbw, busbw


def emit_comm_instant(op_name: str, nbytes: int, world: int,
                      wire_bytes: int = None, kind: str = None,
                      op_seq: int = None) -> None:
    """Trace-time analytic comm record: an instant event (no runtime duration
    exists under XLA scheduling) carrying op/bytes/wire_bytes/world. THE
    single emission point — both ``CommsLogger.record_traced`` and the
    collective facade's logger-off path route through here so the trace args
    can never drift. ``wire_bytes`` defaults to the logical ``bytes`` (an
    uncompressed op is its own wire format); compressed collectives pass
    the codes+scales payload so dstrace / ``dstpu plan`` rollups can report
    the compression ratio deterministically. ``op_seq`` is the commguard
    sequence number — the cross-rank join key ``dstpu trace merge`` matches
    the k-th collective on rank 0 to the k-th on rank 3 by."""
    tracer = get_tracer()
    if tracer.enabled:
        args = {"bytes": int(nbytes),
                "wire_bytes": int(nbytes if wire_bytes is None
                                  else wire_bytes),
                "kind": canonical_op_kind(op_name, kind),
                "world": int(world)}
        if op_seq is not None:
            args["op_seq"] = int(op_seq)
        tracer.instant(f"comm/{op_name}", cat="comm", **args)


class CommsLogger:
    def __init__(self):
        self.enabled = False
        self.verbose = False
        self.prof_all = True
        self.prof_ops = []
        # op -> {count, total_bytes, wire_bytes}
        self.traced: Dict[str, Dict[str, float]] = defaultdict(
            lambda: {"count": 0, "bytes": 0, "wire_bytes": 0})
        # op -> list of (bytes, seconds, world, wire_bytes)
        self.timed_records: Dict[str, list] = defaultdict(list)

    def configure(self, enabled: bool = True, verbose: bool = False,
                  prof_all: bool = True, prof_ops=None):
        self.enabled = enabled
        self.verbose = verbose
        self.prof_all = prof_all
        self.prof_ops = prof_ops or []

    def record_traced(self, op_name: str, nbytes: int, world: int,
                      wire_bytes: int = None, kind: str = None,
                      op_seq: int = None):
        rec = self.traced[op_name]
        rec["count"] += 1
        rec["bytes"] += nbytes
        rec["wire_bytes"] += nbytes if wire_bytes is None else wire_bytes
        emit_comm_instant(op_name, nbytes, world, wire_bytes=wire_bytes,
                          kind=kind, op_seq=op_seq)
        if self.verbose:
            logger.info(f"[comms][trace] {op_name}: {nbytes / 1e6:.2f} MB over {world} members")

    @contextmanager
    def timed(self, op_name: str, nbytes: int, world: int,
              wire_bytes: int = None, kind: str = None, op_seq: int = None):
        tracer = get_tracer()
        if not (self.enabled or tracer.enabled):
            yield
            return
        wire = nbytes if wire_bytes is None else wire_bytes
        start = time.time()
        yield
        dur = time.time() - start
        algbw, busbw = calc_bw(op_name, nbytes, dur, world, kind=kind)
        if tracer.enabled:
            extra = {} if op_seq is None else {"op_seq": int(op_seq)}
            tracer.complete(f"comm/{op_name}", dur, cat="comm",
                            bytes=int(nbytes), wire_bytes=int(wire),
                            kind=canonical_op_kind(op_name, kind),
                            world=int(world),
                            algbw_gbps=algbw / 1e9, busbw_gbps=busbw / 1e9,
                            **extra)
        if not self.enabled:
            return
        self.timed_records[op_name].append((nbytes, dur, world, wire))
        if self.verbose:
            logger.info(f"[comms] {op_name}: {nbytes / 1e6:.2f} MB in {dur * 1e3:.2f} ms | "
                        f"algbw {algbw / 1e9:.2f} GB/s busbw {busbw / 1e9:.2f} GB/s")

    def log_summary(self, show_straggler: bool = False):
        """reference: dist.log_summary (comm/comm.py:422)."""
        lines = ["Communication summary:"]
        for op, rec in sorted(self.traced.items()):
            lines.append(f"  [traced] {op}: {int(rec['count'])} calls, "
                         f"{rec['bytes'] / 1e9:.3f} GB total")
        for op, recs in sorted(self.timed_records.items()):
            tot_b = sum(r[0] for r in recs)
            tot_t = sum(r[1] for r in recs)
            algbw, busbw = calc_bw(op, tot_b, tot_t, recs[-1][2] if recs else 1)
            lines.append(f"  [timed]  {op}: {len(recs)} calls, {tot_b / 1e9:.3f} GB, "
                         f"{tot_t * 1e3:.1f} ms, algbw {algbw / 1e9:.2f} GB/s")
        logger.info("\n".join(lines))
        return lines

    def per_op_totals(self) -> Dict[str, Dict[str, float]]:
        """Merged per-op volume/time totals across both recording modes —
        the summary ``env_report`` and tests consume without parsing log
        lines: ``{op: {count, bytes, wire_bytes, seconds}}`` (seconds only
        for eager timed ops; traced ops are scheduled by XLA). The
        compression ratio of an op is ``bytes / wire_bytes`` — equal when
        nothing on that op compresses."""
        out: Dict[str, Dict[str, float]] = {}
        for op, rec in self.traced.items():
            out[op] = {"count": int(rec["count"]),
                       "bytes": float(rec["bytes"]),
                       "wire_bytes": float(rec["wire_bytes"]),
                       "seconds": 0.0}
        for op, recs in self.timed_records.items():
            e = out.setdefault(op, {"count": 0, "bytes": 0.0,
                                    "wire_bytes": 0.0, "seconds": 0.0})
            e["count"] += len(recs)
            e["bytes"] += float(sum(r[0] for r in recs))
            e["wire_bytes"] += float(sum(
                r[3] if len(r) > 3 else r[0] for r in recs))
            e["seconds"] += float(sum(r[1] for r in recs))
        return out

    def env_report_rows(self) -> List[Tuple[str, str]]:
        """(key, value) rows for the ``dstpu_report`` environment report —
        per-op volume with the wire column, plus ONE comm-compression
        status row summarizing whether any op this process recorded moved
        fewer wire than logical bytes."""
        totals = self.per_op_totals()
        if not totals:
            return [("comms ops", "none recorded in this process"),
                    ("comm compression",
                     "no compressed ops recorded (enable the "
                     "comm_compression config group)")]
        rows = []
        logical_total = wire_total = 0.0
        for op, t in sorted(totals.items()):
            val = f"{int(t['count'])} calls, {t['bytes'] / 1e6:.2f} MB"
            if t["wire_bytes"] < t["bytes"]:
                ratio = t["bytes"] / max(t["wire_bytes"], 1.0)
                val += (f" -> {t['wire_bytes'] / 1e6:.2f} MB wire "
                        f"({ratio:.2f}x)")
            if t["seconds"] > 0:
                # volume/duration only: bus bandwidth needs the per-op world
                # size, which totals deliberately do not aggregate over
                val += (f", {t['seconds'] * 1e3:.1f} ms, "
                        f"{t['bytes'] / t['seconds'] / 1e9:.2f} GB/s")
            rows.append((f"comms[{op}]", val))
            logical_total += t["bytes"]
            wire_total += t["wire_bytes"]
        if wire_total < logical_total:
            rows.append(("comm compression",
                         f"active: {logical_total / 1e6:.2f} MB logical -> "
                         f"{wire_total / 1e6:.2f} MB wire "
                         f"({logical_total / max(wire_total, 1.0):.2f}x)"))
        else:
            rows.append(("comm compression",
                         "no compressed ops recorded (enable the "
                         "comm_compression config group)"))
        return rows

    def reset(self):
        self.traced.clear()
        self.timed_records.clear()


_comms_logger: Optional[CommsLogger] = None


def get_comms_logger() -> CommsLogger:
    global _comms_logger
    if _comms_logger is None:
        _comms_logger = CommsLogger()
    return _comms_logger
