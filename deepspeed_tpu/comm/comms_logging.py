"""Communication volume / bandwidth accounting.

Reference analog: ``deepspeed/utils/comms_logging.py:67`` (``CommsLogger``) and
``calc_bw_log:34`` (alg/bus bandwidth). Two recording modes:

- ``record_traced``: called at **trace time** from the collective facade — per-op
  message sizes and world sizes are static under XLA, so totals are exact analytic
  communication volume per compiled step.
- ``timed``: context manager for eager host-driven ops — wall-clock latency plus
  alg/bus bandwidth like the reference's ``timed_op``.
"""

import time
from collections import defaultdict
from contextlib import contextmanager
from typing import Dict, Optional

from deepspeed_tpu.utils.logging import logger


def calc_bw(op_name: str, size_bytes: int, duration_s: float, world: int):
    """Algorithm vs bus bandwidth (reference: comms_logging.py:34 calc_bw_log).

    busbw scales algbw by the ring-collective traffic factor: allreduce 2(n-1)/n,
    allgather/reduce_scatter/all_to_all (n-1)/n.
    """
    if duration_s <= 0:
        return 0.0, 0.0
    algbw = size_bytes / duration_s
    n = max(world, 1)
    if "all_reduce" in op_name:
        busbw = algbw * (2 * (n - 1) / n)
    elif any(k in op_name for k in ("all_gather", "reduce_scatter", "all_to_all")):
        busbw = algbw * ((n - 1) / n)
    else:
        busbw = algbw
    return algbw, busbw


class CommsLogger:
    def __init__(self):
        self.enabled = False
        self.verbose = False
        self.prof_all = True
        self.prof_ops = []
        # op -> {count, total_bytes}
        self.traced: Dict[str, Dict[str, float]] = defaultdict(lambda: {"count": 0, "bytes": 0})
        # op -> list of (bytes, seconds, world)
        self.timed_records: Dict[str, list] = defaultdict(list)

    def configure(self, enabled: bool = True, verbose: bool = False,
                  prof_all: bool = True, prof_ops=None):
        self.enabled = enabled
        self.verbose = verbose
        self.prof_all = prof_all
        self.prof_ops = prof_ops or []

    def record_traced(self, op_name: str, nbytes: int, world: int):
        rec = self.traced[op_name]
        rec["count"] += 1
        rec["bytes"] += nbytes
        if self.verbose:
            logger.info(f"[comms][trace] {op_name}: {nbytes / 1e6:.2f} MB over {world} members")

    @contextmanager
    def timed(self, op_name: str, nbytes: int, world: int):
        if not self.enabled:
            yield
            return
        start = time.time()
        yield
        dur = time.time() - start
        self.timed_records[op_name].append((nbytes, dur, world))
        if self.verbose:
            algbw, busbw = calc_bw(op_name, nbytes, dur, world)
            logger.info(f"[comms] {op_name}: {nbytes / 1e6:.2f} MB in {dur * 1e3:.2f} ms | "
                        f"algbw {algbw / 1e9:.2f} GB/s busbw {busbw / 1e9:.2f} GB/s")

    def log_summary(self, show_straggler: bool = False):
        """reference: dist.log_summary (comm/comm.py:422)."""
        lines = ["Communication summary:"]
        for op, rec in sorted(self.traced.items()):
            lines.append(f"  [traced] {op}: {int(rec['count'])} calls, "
                         f"{rec['bytes'] / 1e9:.3f} GB total")
        for op, recs in sorted(self.timed_records.items()):
            tot_b = sum(r[0] for r in recs)
            tot_t = sum(r[1] for r in recs)
            algbw, busbw = calc_bw(op, tot_b, tot_t, recs[-1][2] if recs else 1)
            lines.append(f"  [timed]  {op}: {len(recs)} calls, {tot_b / 1e9:.3f} GB, "
                         f"{tot_t * 1e3:.1f} ms, algbw {algbw / 1e9:.2f} GB/s")
        logger.info("\n".join(lines))
        return lines

    def reset(self):
        self.traced.clear()
        self.timed_records.clear()


_comms_logger: Optional[CommsLogger] = None


def get_comms_logger() -> CommsLogger:
    global _comms_logger
    if _comms_logger is None:
        _comms_logger = CommsLogger()
    return _comms_logger
