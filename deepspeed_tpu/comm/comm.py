"""Instrumented collective facade.

Reference analog: ``deepspeed/comm/comm.py`` — every collective wrapped by ``timed_op``
(:101) feeding a ``CommsLogger`` with latency + alg/bus bandwidth; plus capability
shims and group bookkeeping.

On TPU, collectives inside ``jit`` are XLA ops scheduled by the compiler — wrapping
them with host-side timers would be meaningless (and would break tracing). The facade
therefore has two personalities:

1. **Inside jit / shard_map** (the hot path): thin, trace-safe wrappers over
   ``jax.lax`` collectives (``psum``, ``all_gather``, ``psum_scatter``,
   ``all_to_all``, ``ppermute``) that also record *analytic* byte counts into the
   comms logger at trace time — per-op volume is static under XLA, so bandwidth
   accounting is exact without runtime probes.
2. **Outside jit** (eager, host-driven — e.g. checkpoint scatter, debugging):
   device-level ops executed immediately and wall-clock timed, mirroring
   ``timed_op`` behavior.

The reduce ops mirror ``deepspeed/comm/reduce_op.py``.
"""

import enum
from typing import Optional

import jax
import jax.numpy as jnp
from jax.sharding import Mesh, NamedSharding, PartitionSpec

from deepspeed_tpu.comm.comms_logging import emit_comm_instant, get_comms_logger
from deepspeed_tpu.comm.guard import guarded, next_op_seq, note_comm_op
from deepspeed_tpu.telemetry.tracer import get_tracer


class ReduceOp(enum.Enum):
    SUM = 0
    PRODUCT = 1
    MIN = 2
    MAX = 3
    AVG = 4


def _nbytes(x) -> int:
    return int(jnp.size(x)) * jnp.dtype(x.dtype).itemsize


def _axis_size(axis_name) -> int:
    return jax.lax.axis_size(axis_name)


def _record(op_name: str, x, axis_name, world: Optional[int] = None,
            nbytes: Optional[int] = None, wire_bytes: Optional[int] = None,
            kind: Optional[str] = None):
    # membership feed: the active heartbeat carries "last-completed comm op"
    # per worker (one attribute read when no heartbeat is running).
    # ``nbytes`` overrides the logical payload (default: x's bytes);
    # ``wire_bytes`` is what actually rides the wire (default: == nbytes —
    # uncompressed ops are their own wire format); ``kind`` is the canonical
    # op kind for exact busbw classification (comms_logging.OP_KINDS).
    note_comm_op(op_name)
    logger_ = get_comms_logger()
    tracer = get_tracer()
    if not (logger_.enabled or tracer.enabled):
        return
    # op_seq: the cross-rank join key — SPMD records collectives in the
    # same order on every rank, so the k-th recorded op matches across
    # ranks (allocated only when someone will actually record it, keeping
    # the sequence aligned with what the trace carries)
    op_seq = next_op_seq()
    try:
        world = world or _axis_size(axis_name)
    except Exception:
        world = world or 1
    if nbytes is None:
        nbytes = _nbytes(x)
    if logger_.enabled:
        logger_.record_traced(op_name, nbytes, world,
                              wire_bytes=wire_bytes, kind=kind,
                              op_seq=op_seq)  # also traces
    else:
        # tracing without the comms logger: emit the trace-time instant
        # through the shared helper, skip the volume-accounting tables
        emit_comm_instant(op_name, nbytes, world, wire_bytes=wire_bytes,
                          kind=kind, op_seq=op_seq)


# --- trace-safe collectives (usable under jit/shard_map with named axes) ----

def all_reduce(x, axis_name, op: ReduceOp = ReduceOp.SUM):
    """reference: comm.py all_reduce → NCCL allreduce; here lax.p* over a mesh axis."""
    _record("all_reduce", x, axis_name)
    if op in (ReduceOp.SUM, ReduceOp.AVG):
        out = jax.lax.psum(x, axis_name)
        if op == ReduceOp.AVG:
            out = out / _axis_size(axis_name)
        return out
    if op == ReduceOp.MAX:
        return jax.lax.pmax(x, axis_name)
    if op == ReduceOp.MIN:
        return jax.lax.pmin(x, axis_name)
    raise NotImplementedError(f"reduce op {op}")


def all_gather(x, axis_name, axis: int = 0, tiled: bool = True):
    """reference: all_gather_into_tensor (comm/torch.py:238)."""
    _record("all_gather", x, axis_name)
    return jax.lax.all_gather(x, axis_name, axis=axis, tiled=tiled)


def reduce_scatter(x, axis_name, scatter_dimension: int = 0, tiled: bool = True):
    """reference: reduce_scatter_fn (comm.py:246) / reduce_scatter_coalesced."""
    _record("reduce_scatter", x, axis_name)
    return jax.lax.psum_scatter(x, axis_name, scatter_dimension=scatter_dimension,
                                tiled=tiled)


def all_to_all(x, axis_name, split_axis: int, concat_axis: int, tiled: bool = True):
    """reference: single_all_to_all (sequence/layer.py:153), MoE dispatch."""
    _record("all_to_all", x, axis_name)
    return jax.lax.all_to_all(x, axis_name, split_axis=split_axis,
                              concat_axis=concat_axis, tiled=tiled)


def ppermute(x, axis_name, perm):
    """Ring shift — the building block for ring attention / pipeline p2p
    (reference analog: pipe/p2p.py send/recv pairs)."""
    _record("ppermute", x, axis_name, world=len(perm) if perm else 1)
    return jax.lax.ppermute(x, axis_name, perm)


def broadcast_one_to_all(x, axis_name, root: int = 0):
    """reference: comm.py broadcast. SPMD: select root's value on every member."""
    _record("broadcast", x, axis_name)
    idx = jax.lax.axis_index(axis_name)
    masked = jnp.where(idx == root, x, jnp.zeros_like(x))
    return jax.lax.psum(masked, axis_name)


def barrier(axis_name):
    """reference: dist.barrier. Under SPMD a psum of a scalar is a full barrier."""
    return jax.lax.psum(jnp.ones(()), axis_name)


# --- quantized collectives (comm/compress.py math, facade-recorded) --------

def _axes_tuple(axis_name) -> tuple:
    return (axis_name,) if isinstance(axis_name, str) else tuple(axis_name)


def quantized_all_reduce(x, axis_name, op: ReduceOp = ReduceOp.AVG,
                         wire_dtype: str = "int8", chunk: Optional[int] = None,
                         error=None):
    """EQuARX-style all-reduce with int8/fp8 codes + per-chunk fp32 scales
    on the wire (comm/compress.py — reduce-scatter, server re-quantize,
    regather). ``axis_name`` may be one mesh axis or a tuple; call inside
    shard_map manual over those axes. ``x`` is flat [n]; ``error`` an
    optional ``compress.TensorEF`` (worker [n_pad], server [n_pad/W]) —
    the error-feedback residuals this call compensates with and refreshes.

    Returns ``(out [n_pad], new_error)`` (slice to n if exact shape
    matters; ``new_error`` is None when ``error`` is). Recorded through
    ``_record`` with BOTH ``bytes`` (logical payload) and ``wire_bytes``
    so commguard, the heartbeat, and dstrace see the compressed op."""
    from deepspeed_tpu.comm import compress
    if op not in (ReduceOp.SUM, ReduceOp.AVG):
        raise NotImplementedError(f"quantized reduce op {op}")
    axes = _axes_tuple(axis_name)
    chunk = compress.DEFAULT_CHUNK if chunk is None else chunk
    world = compress.axis_world(axes)
    _record("quantized_all_reduce", x, axes, world=world,
            wire_bytes=compress.all_reduce_wire_bytes(
                int(jnp.size(x)), world, wire_dtype, chunk),
            kind="all_reduce")
    out, w_err, s_err = compress.all_reduce_impl(
        x, axes, wire_dtype, chunk,
        worker_error=None if error is None else error.worker,
        server_error=None if error is None else error.server,
        mean=(op == ReduceOp.AVG))
    new_error = None if error is None else compress.TensorEF(
        worker=w_err, server=s_err)
    return out, new_error


def quantized_reduce_scatter(x, axis_name, op: ReduceOp = ReduceOp.AVG,
                             wire_dtype: str = "int8",
                             chunk: Optional[int] = None, error=None):
    """Quantized reduce-scatter (the first phase of the all-reduce): flat
    [n] in, this participant's reduced shard [n_pad / W] out. ``error`` is
    the worker residual [n_pad] (or None). Returns ``(shard, new_error)``.
    Facade-recorded with logical + wire bytes like every collective."""
    from deepspeed_tpu.comm import compress
    if op not in (ReduceOp.SUM, ReduceOp.AVG):
        raise NotImplementedError(f"quantized reduce op {op}")
    axes = _axes_tuple(axis_name)
    chunk = compress.DEFAULT_CHUNK if chunk is None else chunk
    world = compress.axis_world(axes)
    _record("quantized_reduce_scatter", x, axes, world=world,
            wire_bytes=compress.reduce_scatter_wire_bytes(
                int(jnp.size(x)), world, wire_dtype, chunk),
            kind="reduce_scatter")
    return compress.reduce_scatter_impl(
        x, axes, wire_dtype, chunk, worker_error=error,
        mean=(op == ReduceOp.AVG))


# --- eager (outside-jit) helpers -------------------------------------------

def device_broadcast(x, mesh: Mesh):
    """Replicate a host array to every device (reference: _broadcast_model
    engine.py:1101 — params replicated from rank 0).

    Eager and host-driven, so it runs under the active ``CommGuard`` when a
    ``FaultTolerantRunner`` with the ``"comm_guard"`` group is live: a sick
    device/fabric becomes a ``CommWedgeError`` inside ``op_deadline_s``
    instead of blocking this thread forever (no guard installed -> plain
    inline call, one global read of overhead)."""
    return guarded("device_broadcast",
                   lambda: jax.device_put(x, NamedSharding(mesh, PartitionSpec())))
