"""Config keys and defaults.

Reference analog: ``deepspeed/runtime/constants.py`` (457 LoC of key/default pairs).
Only the keys meaningful on TPU are kept; CUDA-only knobs are accepted (and ignored
with a warning) for drop-in config compatibility.
"""

TRAIN_BATCH_SIZE = "train_batch_size"
TRAIN_MICRO_BATCH_SIZE_PER_GPU = "train_micro_batch_size_per_gpu"
GRADIENT_ACCUMULATION_STEPS = "gradient_accumulation_steps"

OPTIMIZER = "optimizer"
SCHEDULER = "scheduler"

FP16 = "fp16"
BF16 = "bf16"
ZERO_OPTIMIZATION = "zero_optimization"
GRADIENT_CLIPPING = "gradient_clipping"
PRESCALE_GRADIENTS = "prescale_gradients"
GRADIENT_PREDIVIDE_FACTOR = "gradient_predivide_factor"
STEPS_PER_PRINT = "steps_per_print"
WALL_CLOCK_BREAKDOWN = "wall_clock_breakdown"
MESH = "mesh"
ACTIVATION_CHECKPOINTING = "activation_checkpointing"
FLOPS_PROFILER = "flops_profiler"
MONITOR_TENSORBOARD = "tensorboard"
MONITOR_CSV = "csv_monitor"
MONITOR_WANDB = "wandb"
MONITOR_COMET = "comet"
COMMS_LOGGER = "comms_logger"
DATA_EFFICIENCY = "data_efficiency"
CURRICULUM_LEARNING = "curriculum_learning"
ELASTICITY = "elasticity"
COMPRESSION_TRAINING = "compression_training"
AUTOTUNING = "autotuning"
CHECKPOINT = "checkpoint"
DATA_TYPES = "data_types"                 # reference: constants.py:426
GRAD_ACCUM_DTYPE = "grad_accum_dtype"     # reference: constants.py:427
PROGRESSIVE_LAYER_DROP = "progressive_layer_drop"
EIGENVALUE = "eigenvalue"
SPARSE_GRADIENTS = "sparse_gradients"
DUMP_STATE = "dump_state"
# legacy spelling of the bf16 group accepted for drop-in compatibility
# (reference: BFLOAT16_CONFIG_LEGACY, constants.py:132)
BF16_LEGACY = "bfloat16"
# TPU-native keys — no reference analog
ASYNC_PIPELINE = "async_pipeline"   # latency-hiding step pipeline group
RESILIENCE = "resilience"           # fault-tolerance group (guards/autosave)
COMM_GUARD = "comm_guard"           # comm fault-tolerance group (deadlines/
#                                     heartbeat/membership; comm/guard.py)
COMM_COMPRESSION = "comm_compression"  # quantized error-feedback collectives
#                                     + bucketed backward/reduce-scatter
#                                     overlap (comm/compress.py)
DEBUG_NANS = "debug_nans"           # jax_debug_nans for the compiled step
MEMORY = "memory"                   # dsmem group (ledger preflight + live
#                                     HBM/RSS sampling; telemetry/memory.py)
SERVING = "serving"                 # serving group (admission, degradation
#                                     ladder, host KV offload tier, fault
#                                     isolation; serving/server.py
#                                     ServingConfig.from_ds_config)
FLEET = "fleet"                     # fleet router group (replicas, prefix
#                                     affinity, ladder-aware spill, failover
#                                     retry budget, scale-out thresholds;
#                                     serving/fleet.py
#                                     FleetConfig.from_ds_config)

# elasticity group keys for shrink-to-survive (elasticity/agent.py): the
# agent may re-plan a generation below the launch world when membership
# proves a rank permanently lost, floored at MIN_WORLD_SIZE; REJOIN_GRACE_S
# is how long a lost rank gets to heartbeat again before the shrink commits
ELASTICITY_MIN_WORLD_SIZE = "min_world_size"
ELASTICITY_SHRINK_ON_PEER_LOSS = "shrink_on_peer_loss"
ELASTICITY_REJOIN_GRACE_S = "rejoin_grace_s"

# Defaults (mirroring reference semantics)
STEPS_PER_PRINT_DEFAULT = 10
GRADIENT_CLIPPING_DEFAULT = 0.0
GRADIENT_ACCUMULATION_STEPS_DEFAULT = 1

# Keys from the reference config space that have no TPU meaning; accepted silently.
IGNORED_CUDA_ONLY_KEYS = frozenset({
    "amp",
    "communication_data_type",
    "fp16_master_weights_and_gradients",
    "cuda_aware",
    "use_node_local_storage",
})
