"""Config model base.

Reference analog: ``deepspeed/runtime/config_utils.py`` (``DeepSpeedConfigModel``):
pydantic base with "auto" support and deprecated-field aliasing. We keep the "auto"
sentinel contract — integrations resolve "auto" values before validation.
"""

from typing import Any, Dict

from pydantic import BaseModel, ConfigDict

AUTO = "auto"


class DeepSpeedTPUConfigModel(BaseModel):
    """Base for every sub-config: ignore unknown keys (forward compat), validate on
    assignment, allow "auto" passthrough for annotated fields."""

    model_config = ConfigDict(extra="ignore", validate_assignment=True,
                              arbitrary_types_allowed=True, populate_by_name=True)

    def dict_repr(self) -> Dict[str, Any]:
        return self.model_dump()


def get_scalar_param(d: Dict[str, Any], name: str, default: Any) -> Any:
    return d.get(name, default)
