from deepspeed_tpu.config.config import (
    ActivationCheckpointingConfig,
    BF16Config,
    CheckpointConfig,
    CommsLoggerConfig,
    DeepSpeedTPUConfig,
    ElasticityConfig,
    FP16Config,
    FlopsProfilerConfig,
    MeshConfig,
    OffloadConfig,
    OptimizerConfig,
    SchedulerConfig,
    ZeroConfig,
)

__all__ = [
    "DeepSpeedTPUConfig", "ZeroConfig", "FP16Config", "BF16Config", "OffloadConfig",
    "OptimizerConfig", "SchedulerConfig", "MeshConfig", "ActivationCheckpointingConfig",
    "FlopsProfilerConfig", "CommsLoggerConfig", "CheckpointConfig", "ElasticityConfig",
]
