"""The single-JSON config tree.

Reference analog: ``deepspeed/runtime/config.py:96+`` (``DeepSpeedConfig`` — ~100
accessors, batch-size triple reconciliation ``train_batch_size = micro_batch * gas *
dp_world``) and the per-feature pydantic models (``runtime/zero/config.py``,
``runtime/fp16``, monitor/flops/comms sub-configs). The config *keys* are kept
compatible with the reference JSON space so existing DeepSpeed configs parse; the
semantics are TPU-native (ZeRO stages select sharding policies; offload selects the
host-memory tier; mesh describes the named-axis device mesh).
"""

import json
import os
from typing import Any, Dict, List, Optional, Union

from pydantic import Field, model_validator

from deepspeed_tpu.config.config_utils import DeepSpeedTPUConfigModel
from deepspeed_tpu.config import constants as C
from deepspeed_tpu.utils.logging import logger


class FP16Config(DeepSpeedTPUConfigModel):
    """reference: runtime/fp16/loss_scaler.py + config keys under "fp16"."""
    enabled: bool = False
    loss_scale: float = 0.0  # 0 => dynamic
    initial_scale_power: int = 16
    loss_scale_window: int = 1000
    hysteresis: int = 2
    consecutive_hysteresis: bool = False
    min_loss_scale: float = 1.0

    @property
    def dynamic(self) -> bool:
        return self.loss_scale == 0.0


class BF16Config(DeepSpeedTPUConfigModel):
    enabled: bool = False
    # Keep fp32 master weights + fp32 grad accumulation (reference bf16_optimizer.py:34)
    master_weights: bool = True


class OffloadConfig(DeepSpeedTPUConfigModel):
    """reference: runtime/zero/offload_config.py. device: none|cpu (host DRAM)|nvme."""
    device: str = "none"
    nvme_path: Optional[str] = None
    buffer_count: int = 4
    pin_memory: bool = True
    pipeline_read: bool = True
    pipeline_write: bool = True
    ratio: float = 1.0  # Twin-Flow partial offload (engine.py:757 zero_partial_offload)
    # offload_param streaming granularity: transformer blocks per streamed
    # group (larger = fewer, bigger H2D transfers but more HBM per group)
    layers_per_group: int = 1
    # nvme tier: swap the fp32 MASTERS too (full ZeRO-Infinity — reference
    # swaps the flat fp32 param shard alongside the moments); False keeps
    # masters pinned in host RAM (moments-only swap)
    swap_masters: bool = True


class ZeroConfig(DeepSpeedTPUConfigModel):
    """reference: runtime/zero/config.py (DeepSpeedZeroConfig).

    On TPU the stages are sharding policies over the ``fsdp`` mesh axis:
      stage 0 — pure DP: params+opt replicated, batch sharded over data axis
      stage 1 — optimizer states sharded (weight-update sharding)
      stage 2 — + gradients reduce-scattered into the shard (in SPMD this is the same
                sharding spec as stage 1; XLA emits reduce-scatter automatically)
      stage 3 — + parameters sharded; XLA inserts allgathers per use (FSDP)
    """
    stage: int = 0
    contiguous_gradients: bool = True
    reduce_scatter: bool = True
    reduce_bucket_size: int = int(5e8)
    allgather_bucket_size: int = int(5e8)
    overlap_comm: bool = True
    offload_param: OffloadConfig = Field(default_factory=OffloadConfig)
    offload_optimizer: OffloadConfig = Field(default_factory=OffloadConfig)
    sub_group_size: int = int(1e9)
    # ZeRO++ knobs (reference: zero_hpz_partition_size config.py:283, qwZ/qgZ :287,:299)
    zero_hpz_partition_size: int = 1
    zero_quantized_weights: bool = False
    zero_quantized_gradients: bool = False
    # MiCS (reference: runtime/zero/mics.py): shard within a group, replicate across
    mics_shard_size: int = -1
    mics_hierarchical_params_gather: bool = False
    # stage-1/2 elastic checkpoint compat flag
    elastic_checkpoint: bool = False
    gather_16bit_weights_on_model_save: bool = True

    @model_validator(mode="after")
    def _check(self):
        if self.stage not in (0, 1, 2, 3):
            raise ValueError(f"zero stage must be 0-3, got {self.stage}")
        return self


class DataTypesConfig(DeepSpeedTPUConfigModel):
    """reference: "data_types" config group (runtime/config.py:901) — the dtype
    gradients are accumulated in across microbatches. None keeps the default
    (fp32, matching the reference's bf16_optimizer fp32 accumulation); "bf16"
    halves the gas scan-carry HBM footprint at a small accumulation-precision
    cost (the final unscale/clip/update still run in fp32)."""
    grad_accum_dtype: Optional[str] = None

    @model_validator(mode="after")
    def _check(self):
        if self.grad_accum_dtype not in (None, "fp32", "bf16", "fp16"):
            raise ValueError(
                f"{C.GRAD_ACCUM_DTYPE} must be fp32|bf16|fp16, "
                f"got {self.grad_accum_dtype}")
        return self


class OptimizerConfig(DeepSpeedTPUConfigModel):
    type: str = "adamw"
    params: Dict[str, Any] = Field(default_factory=dict)


class SchedulerConfig(DeepSpeedTPUConfigModel):
    type: Optional[str] = None
    params: Dict[str, Any] = Field(default_factory=dict)


class MeshConfig(DeepSpeedTPUConfigModel):
    """TPU-native addition: named-axis device mesh (data, fsdp, tensor, sequence,
    expert, pipe). -1 on at most one axis means "fill with remaining devices".
    The reference expresses the same information via mpu / groups.py world sizes."""
    data: int = -1
    fsdp: int = 1
    # hierarchical-sharding replica factor (MiCS / ZeRO++ hpZ): the ZeRO world is
    # fsdp_outer x fsdp, with the inner fsdp axis holding the shard group
    fsdp_outer: int = 1
    tensor: int = 1
    sequence: int = 1
    expert: int = 1
    pipe: int = 1
    # axes that ride DCN (multi-slice) rather than ICI; outermost first
    dcn_axes: list = Field(default_factory=list)


class ActivationCheckpointingConfig(DeepSpeedTPUConfigModel):
    """reference: runtime/activation_checkpointing/checkpointing.py. On TPU this maps
    to jax.checkpoint policies instead of autograd recomputation wrappers."""
    partition_activations: bool = False
    cpu_checkpointing: bool = False
    contiguous_memory_optimization: bool = False
    number_checkpoints: Optional[int] = None
    synchronize_checkpoint_boundary: bool = False
    profile: bool = False
    # TPU-native: name of the remat policy (see runtime/activation_checkpointing.py)
    policy: str = "nothing_saveable"
    # values tagged via checkpoint_name() that named save/offload policies act on
    saved_names: List[str] = ["block_out", "attn_out", "mlp_out"]


class FlopsProfilerConfig(DeepSpeedTPUConfigModel):
    enabled: bool = False
    profile_step: int = 1
    module_depth: int = -1
    top_modules: int = 1
    detailed: bool = True
    output_file: Optional[str] = None


class CommsLoggerConfig(DeepSpeedTPUConfigModel):
    enabled: bool = False
    verbose: bool = False
    prof_all: bool = True
    debug: bool = False
    prof_ops: list = Field(default_factory=list)


class TensorBoardConfig(DeepSpeedTPUConfigModel):
    enabled: bool = False
    output_path: str = ""
    job_name: str = "DeepSpeedTPUJob"


class CSVConfig(DeepSpeedTPUConfigModel):
    enabled: bool = False
    output_path: str = ""
    job_name: str = "DeepSpeedTPUJob"


class WandbConfig(DeepSpeedTPUConfigModel):
    enabled: bool = False
    group: Optional[str] = None
    team: Optional[str] = None
    project: str = "deepspeed_tpu"


class CometConfig(DeepSpeedTPUConfigModel):
    """reference: monitor/config.py CometConfig (monitor/comet.py)."""
    enabled: bool = False
    samples_log_interval: int = 100
    project: Optional[str] = None
    workspace: Optional[str] = None
    api_key: Optional[str] = None
    experiment_name: Optional[str] = None
    experiment_key: Optional[str] = None
    online: Optional[bool] = None
    mode: Optional[str] = None


class CheckpointConfig(DeepSpeedTPUConfigModel):
    """reference: checkpoint keys + runtime/checkpoint_engine. use_node_local_storage
    etc. are CUDA-cluster knobs; TPU uses a single logical sharded checkpoint."""
    tag_validation: str = "Warn"
    load_universal: bool = False
    use_node_local_storage: bool = False
    parallel_write_pipeline: bool = False
    async_save: bool = False


class CurriculumLegacyConfig(DeepSpeedTPUConfigModel):
    """Legacy top-level "curriculum_learning" key (reference: runtime/config.py
    curriculum_params_legacy) — seqlen curriculum driven by the engine."""
    enabled: bool = False
    curriculum_type: str = "seqlen"
    min_difficulty: int = 1
    max_difficulty: int = 1
    schedule_type: str = "fixed_linear"
    schedule_config: Dict[str, Any] = Field(default_factory=dict)


class DataEfficiencyConfig(DeepSpeedTPUConfigModel):
    """reference: runtime/data_pipeline/config.py (get_data_efficiency_config).
    ``data_sampling.curriculum_learning.curriculum_metrics`` maps metric name →
    scheduler config; ``data_routing.random_ltd`` configures token dropping."""
    enabled: bool = False
    seed: int = 1234
    data_sampling: Dict[str, Any] = Field(default_factory=dict)
    data_routing: Dict[str, Any] = Field(default_factory=dict)

    @property
    def curriculum_enabled(self) -> bool:
        return (self.enabled and self.data_sampling.get("enabled", False)
                and self.data_sampling.get("curriculum_learning", {}).get("enabled", False))

    @property
    def curriculum_metrics(self) -> Dict[str, Any]:
        return self.data_sampling.get("curriculum_learning", {}).get("curriculum_metrics", {})

    @property
    def random_ltd_enabled(self) -> bool:
        return (self.enabled and self.data_routing.get("enabled", False)
                and self.data_routing.get("random_ltd", {}).get("enabled", False))

    @property
    def random_ltd(self) -> Dict[str, Any]:
        return self.data_routing.get("random_ltd", {})


class ElasticityConfig(DeepSpeedTPUConfigModel):
    """reference: deepspeed/elasticity/config.py. The shrink-to-survive
    keys (TPU-native, no reference analog) let the elastic agent re-plan a
    generation at the SURVIVING world when membership proves a rank
    permanently lost, instead of relaunch-looping at a world that can
    never assemble again."""
    enabled: bool = False
    max_train_batch_size: int = 2000
    micro_batch_sizes: list = Field(default_factory=lambda: [2, 4, 6])
    min_gpus: int = 1
    max_gpus: int = 10000
    min_time: int = 0
    version: float = 0.2
    ignore_non_elastic_batch_info: bool = False
    prefer_larger_batch: bool = True
    # shrink-to-survive (elasticity/agent.py): relaunch a comm-fault /
    # preemption generation at world - |lost ranks| when membership shows
    # a peer permanently gone ...
    shrink_on_peer_loss: bool = False
    # ... never below this floor (the agent refuses and exits instead) ...
    min_world_size: int = 1
    # ... after giving the lost rank this long to heartbeat again (0 =
    # shrink at the first stale-membership verdict)
    rejoin_grace_s: float = 0.0


class PLDConfig(DeepSpeedTPUConfigModel):
    """reference: progressive_layer_drop config keys (PLD_THETA/PLD_GAMMA)."""
    enabled: bool = False
    theta: float = 0.5
    gamma: float = 0.001


class AsyncPipelineConfig(DeepSpeedTPUConfigModel):
    """Latency-hiding step pipeline (TPU-native; no reference analog — JAX's
    async dispatch makes the host loop the bottleneck the reference never had).

    With ``enabled``, ``train_batch`` returns without touching step outputs:
    they queue on a device-side ring drained with ONE batched ``device_get``
    every ``sync_every`` steps (and at log/checkpoint boundaries or explicit
    ``engine.flush_metrics()``). Host-side consumers (monitor events, the
    resilience StepGuard) observe steps with up to ``sync_every`` steps of
    lag — numerics are bit-identical, only *detection* is deferred.

    ``prefetch`` stages batches (stack + device_put) one step ahead on a
    background thread so host→device transfer of batch N+1 overlaps compute
    of batch N. Disabled by default: the default config preserves per-step
    readback semantics exactly."""
    enabled: bool = False
    # drain the step-output ring every N steps (1 = per-step readback, the
    # synchronous baseline; only honored when enabled)
    sync_every: int = 8
    # double-buffered background batch staging (train_batch(data_iter=...))
    prefetch: bool = False
    # staged batches kept ready ahead of compute (2 = classic double buffer)
    prefetch_depth: int = 2

    @model_validator(mode="after")
    def _check(self):
        if self.sync_every < 1:
            raise ValueError(f"sync_every must be >= 1, got {self.sync_every}")
        if self.prefetch_depth < 1:
            raise ValueError(
                f"prefetch_depth must be >= 1, got {self.prefetch_depth}")
        return self


class MemoryConfig(DeepSpeedTPUConfigModel):
    """dsmem (TPU-native; ``deepspeed_tpu/telemetry/memory.py``): analytic
    memory-plan preflight plus live HBM/RSS watermark sampling into the
    dstrace timeline. With the group absent the engine still samples at
    drain boundaries whenever tracing is on (counter tracks ride every
    ``DSTPU_TRACE`` dump for free); enabling the group adds the analytic
    preflight and the background cadence thread."""
    enabled: bool = False
    # sample at the async drain / sync steps_per_print boundary (points
    # that already host-sync — sampling there adds zero new syncs)
    sample_on_drain: bool = True
    # background sampler thread period in seconds (0 = off); for serve /
    # idle stretches with no drain cadence
    cadence_s: float = 0.0
    # bounded in-memory sample ring (diagnostic bundles embed the tail)
    window: int = 512
    # analytic ledger vs device bytes_limit at engine init:
    # off | warn | refuse (refuse raises MemoryPreflightError)
    preflight: str = "warn"

    @model_validator(mode="after")
    def _check(self):
        if self.preflight not in ("off", "warn", "refuse"):
            raise ValueError(f"memory.preflight must be off|warn|refuse, "
                             f"got {self.preflight!r}")
        if self.cadence_s < 0:
            raise ValueError(f"memory.cadence_s must be >= 0, "
                             f"got {self.cadence_s}")
        return self


class DeepSpeedTPUConfig:
    """Parses the single JSON/dict config (reference: DeepSpeedConfig,
    runtime/config.py). Performs the batch-size triple reconciliation with
    ``dp_world_size`` = size of (data x fsdp) mesh axes."""

    def __init__(self, config: Union[str, Dict[str, Any], None],
                 dp_world_size: Optional[int] = None,
                 apply_elastic_overrides: bool = False):
        if config is None:
            config = {}
        if isinstance(config, str):
            if not os.path.exists(config):
                raise FileNotFoundError(f"DeepSpeed-TPU config not found: {config}")
            with open(config) as f:
                config = json.load(f)
        if not isinstance(config, dict):
            raise TypeError(f"config must be dict or path, got {type(config)}")
        self._raw = dict(config)

        # elastic relaunch overrides: when the agent's shrink preflight
        # escalated the offload ladder it exports the merged override dict
        # as env. Applied ONLY for the training entry point
        # (deepspeed_tpu.initialize passes apply_elastic_overrides=True) —
        # other configs parsed in the same process (autotuning candidates,
        # serving groups, eval engines) must see exactly what they were
        # given, not a silently escalated variant.
        if apply_elastic_overrides:
            from deepspeed_tpu.launcher.constants import ENV_CONFIG_OVERRIDES
            _ov_raw = os.environ.get(ENV_CONFIG_OVERRIDES)
            if _ov_raw:
                try:
                    overrides = json.loads(_ov_raw)
                except ValueError:
                    overrides = None
                if not isinstance(overrides, dict):
                    logger.warning(f"{ENV_CONFIG_OVERRIDES} is not a JSON "
                                   f"object; ignored")
                    overrides = None
                if overrides:
                    from deepspeed_tpu.telemetry.memory import deep_merge
                    import copy
                    self._raw = deep_merge(copy.deepcopy(self._raw),
                                           overrides)
                    logger.info(f"elastic config overrides applied from "
                                f"{ENV_CONFIG_OVERRIDES}: {overrides}")

        for key in list(self._raw):
            if key in C.IGNORED_CUDA_ONLY_KEYS:
                logger.warning(f"config key '{key}' has no TPU equivalent; ignored")

        self.fp16 = FP16Config(**self._raw.get(C.FP16, {}))
        self.bf16 = BF16Config(**self._raw.get(C.BF16, self._raw.get(C.BF16_LEGACY, {})))
        self.zero_config = ZeroConfig(**self._raw.get(C.ZERO_OPTIMIZATION, {}))
        self.optimizer = OptimizerConfig(**self._raw[C.OPTIMIZER]) if C.OPTIMIZER in self._raw else None
        self.scheduler = SchedulerConfig(**self._raw[C.SCHEDULER]) if C.SCHEDULER in self._raw else None
        self.mesh = MeshConfig(**self._raw.get(C.MESH, {}))
        self.activation_checkpointing = ActivationCheckpointingConfig(
            **self._raw.get(C.ACTIVATION_CHECKPOINTING, {}))
        self.flops_profiler = FlopsProfilerConfig(**self._raw.get(C.FLOPS_PROFILER, {}))
        self.comms_logger = CommsLoggerConfig(**self._raw.get(C.COMMS_LOGGER, {}))
        self.tensorboard = TensorBoardConfig(**self._raw.get(C.MONITOR_TENSORBOARD, {}))
        self.csv_monitor = CSVConfig(**self._raw.get(C.MONITOR_CSV, {}))
        self.wandb = WandbConfig(**self._raw.get(C.MONITOR_WANDB, {}))
        self.comet = CometConfig(**self._raw.get(C.MONITOR_COMET, {}))
        self.checkpoint_config = CheckpointConfig(**self._raw.get(C.CHECKPOINT, {}))
        self.elasticity = ElasticityConfig(**self._raw.get(C.ELASTICITY, {}))
        self.curriculum_learning_legacy = CurriculumLegacyConfig(
            **self._raw.get(C.CURRICULUM_LEARNING, {}))
        # compression_training keeps the reference's nested-dict schema verbatim
        # (deepspeed/compression/config.py); parsed lazily by the Compressor
        self.compression_config: Dict[str, Any] = dict(
            self._raw.get(C.COMPRESSION_TRAINING, {}))
        self.data_efficiency = DataEfficiencyConfig(
            **self._raw.get(C.DATA_EFFICIENCY, {}))
        self.data_types = DataTypesConfig(**self._raw.get(C.DATA_TYPES, {}))
        self.async_pipeline = AsyncPipelineConfig(
            **self._raw.get(C.ASYNC_PIPELINE, {}))
        self.memory = MemoryConfig(**self._raw.get(C.MEMORY, {}))
        self.pld = PLDConfig(**self._raw.get(C.PROGRESSIVE_LAYER_DROP, {}))
        # single schema shared with the implementation (no parallel copy to
        # keep in sync): reference get_eigenvalue_config (runtime/config.py:565)
        from deepspeed_tpu.runtime.eigenvalue import EigenvalueConfig
        self.eigenvalue = EigenvalueConfig(**self._raw.get(C.EIGENVALUE, {}))
        # reference: get_sparse_gradients_enabled (runtime/config.py:247)
        self.sparse_gradients_enabled: bool = bool(
            self._raw.get(C.SPARSE_GRADIENTS, False))
        # resilience subsystem (step guards / autosave / watchdog); the engine
        # only arms its device-side guard when the group is explicitly present
        # so default bf16/fp32 NaN propagation semantics are unchanged
        from deepspeed_tpu.resilience.config import ResilienceConfig
        self.resilience = ResilienceConfig(**self._raw.get(C.RESILIENCE, {}))
        self.resilience_explicit: bool = C.RESILIENCE in self._raw
        # comm fault-tolerance (deadline-bounded collectives/init, heartbeat
        # membership, straggler detection); consumed by comm/guard.py and
        # resilience/membership.py — presence of the group enables the guard
        from deepspeed_tpu.comm.guard import CommGuardConfig
        _cg = self._raw.get(C.COMM_GUARD, {})
        self.comm_guard = CommGuardConfig(**{"enabled": C.COMM_GUARD
                                             in self._raw, **_cg})
        # quantized error-feedback collectives + bucketed backward overlap
        # (comm/compress.py); default OFF = today's exact wire + semantics
        from deepspeed_tpu.comm.compress import CommCompressionConfig
        self.comm_compression = CommCompressionConfig(
            **self._raw.get(C.COMM_COMPRESSION, {}))

        self.gradient_clipping: float = float(
            self._raw.get(C.GRADIENT_CLIPPING, C.GRADIENT_CLIPPING_DEFAULT))
        self.prescale_gradients: bool = bool(self._raw.get(C.PRESCALE_GRADIENTS, False))
        self.gradient_predivide_factor: float = float(
            self._raw.get(C.GRADIENT_PREDIVIDE_FACTOR, 1.0))
        self.steps_per_print: int = int(
            self._raw.get(C.STEPS_PER_PRINT, C.STEPS_PER_PRINT_DEFAULT))
        self.wall_clock_breakdown: bool = bool(self._raw.get(C.WALL_CLOCK_BREAKDOWN, False))
        self.dump_state: bool = bool(self._raw.get(C.DUMP_STATE, False))
        # numerical sanitizer (SURVEY §5.2): aborts with a traceback at the
        # first NaN-producing op instead of silently propagating — the
        # jax_debug_nans analog of the reference's CheckOverflow/_has_inf_or_nan
        # guards (with fp16 enabled, prefer the loss-scaler's overflow skip)
        self.debug_nans: bool = bool(self._raw.get(C.DEBUG_NANS, False))

        # --- batch size triple reconciliation (reference: config.py
        #     _configure_train_batch_size / _batch_assertion) ---
        self.train_batch_size: Optional[int] = self._raw.get(C.TRAIN_BATCH_SIZE)
        self.train_micro_batch_size_per_gpu: Optional[int] = self._raw.get(
            C.TRAIN_MICRO_BATCH_SIZE_PER_GPU)
        self.gradient_accumulation_steps: Optional[int] = self._raw.get(
            C.GRADIENT_ACCUMULATION_STEPS)
        if dp_world_size is not None:
            self.resolve_batch_sizes(dp_world_size)

    def resolve_batch_sizes(self, dp_world_size: int) -> None:
        """train_batch = micro_batch * gas * dp_world. Given any two, derive the third;
        given one, assume the others (reference: config.py _set_batch_related_parameters)."""
        tb, mb, gas = (self.train_batch_size, self.train_micro_batch_size_per_gpu,
                       self.gradient_accumulation_steps)
        if tb is not None and mb is not None and gas is not None:
            if tb != mb * gas * dp_world_size:
                raise ValueError(
                    f"train_batch_size {tb} != micro_batch {mb} * gas {gas} * dp {dp_world_size}")
        elif tb is not None and mb is not None:
            gas = tb // (mb * dp_world_size)
            if gas == 0 or tb % (mb * dp_world_size) != 0:
                raise ValueError(
                    f"train_batch_size {tb} not divisible by micro_batch {mb} * dp {dp_world_size}")
        elif tb is not None and gas is not None:
            if tb % (gas * dp_world_size) != 0:
                raise ValueError(
                    f"train_batch_size {tb} not divisible by gas {gas} * dp {dp_world_size}")
            mb = tb // (gas * dp_world_size)
        elif mb is not None and gas is not None:
            tb = mb * gas * dp_world_size
        elif tb is not None:
            gas = C.GRADIENT_ACCUMULATION_STEPS_DEFAULT
            if tb % (gas * dp_world_size) != 0:
                raise ValueError(
                    f"train_batch_size {tb} not divisible by "
                    f"gas {gas} * dp {dp_world_size}")
            mb = tb // (gas * dp_world_size)
        elif mb is not None:
            gas = C.GRADIENT_ACCUMULATION_STEPS_DEFAULT
            tb = mb * gas * dp_world_size
        elif gas is not None:
            # gas alone (reference _set_batch_related_parameters: micro
            # defaults to 1, train batch follows) — the pipeline engine
            # leans on this branch when a config gives only the
            # accumulation depth
            mb = 1
            tb = gas * dp_world_size
        else:
            mb, gas = 1, C.GRADIENT_ACCUMULATION_STEPS_DEFAULT
            tb = mb * gas * dp_world_size
        self.train_batch_size, self.train_micro_batch_size_per_gpu, \
            self.gradient_accumulation_steps = int(tb), int(mb), int(gas)

    # --- convenience accessors (subset of the reference's ~100 get_*) ---
    @property
    def zero_enabled(self) -> bool:
        return self.zero_config.stage > 0

    @property
    def zero_optimization_stage(self) -> int:
        return self.zero_config.stage

    @property
    def precision_dtype(self):
        import jax.numpy as jnp
        if self.bf16.enabled:
            return jnp.bfloat16
        if self.fp16.enabled:
            return jnp.float16
        return jnp.float32

    @property
    def loss_scale(self) -> float:
        return self.fp16.loss_scale if self.fp16.enabled else 1.0

    @property
    def grad_accum_dtype(self):
        """jnp dtype gradients are accumulated in over the gas scan (fp32 unless
        data_types.grad_accum_dtype overrides)."""
        import jax.numpy as jnp
        name = self.data_types.grad_accum_dtype
        return {None: jnp.float32, "fp32": jnp.float32,
                "bf16": jnp.bfloat16, "fp16": jnp.float16}[name]

    def raw(self) -> Dict[str, Any]:
        return dict(self._raw)

    def __repr__(self) -> str:
        return (f"DeepSpeedTPUConfig(train_batch_size={self.train_batch_size}, "
                f"micro_batch={self.train_micro_batch_size_per_gpu}, "
                f"gas={self.gradient_accumulation_steps}, zero_stage={self.zero_config.stage}, "
                f"dtype={'bf16' if self.bf16.enabled else 'fp16' if self.fp16.enabled else 'fp32'})")
