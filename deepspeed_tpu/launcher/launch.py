"""Per-node process spawner (reference: ``deepspeed/launcher/launch.py:133 main()``).

Spawns one Python process per local worker with the DSTPU_* rendezvous env
(consumed by ``deepspeed_tpu.comm.mesh.init_distributed``), fans SIGINT/SIGTERM
out to children, and kills all local workers if any one dies (reference
``terminate_process_tree:119`` + the sig handlers around ``launch.py:160``).

On a TPU host the default is ONE process per node (JAX owns all local chips);
``--nproc_per_node`` overrides for CPU simulation
(with ``JAX_PLATFORMS=cpu`` + ``--xla_force_host_platform_device_count``).
"""

import argparse
import base64
import json
import os
import signal
import subprocess
import sys
import time
from typing import List

from deepspeed_tpu.launcher.constants import (ENV_COORDINATOR, ENV_HOSTNAME,
                                              ENV_LOCAL_RANK,
                                              ENV_NUM_PROCESSES,
                                              ENV_PROCESS_ID)
from deepspeed_tpu.utils.logging import logger


def parse_args(args=None):
    parser = argparse.ArgumentParser(
        description="per-node launcher (internal; invoked by the dstpu runner)")
    parser.add_argument("--world_info", type=str, required=True,
                        help="base64-encoded {hostname: [worker ids]} dict")
    parser.add_argument("--node_rank", type=str, default="0",
                        help="this node's index (int, or %%n/$SLURM_NODEID "
                        "substituted by the fan-out tool)")
    parser.add_argument("--coordinator_addr", type=str, default="127.0.0.1")
    parser.add_argument("--coordinator_port", type=int, default=8476)
    parser.add_argument("--nproc_per_node", type=int, default=None,
                        help="processes on this node (default: from world_info)")
    parser.add_argument("--bind_cores_to_rank", action="store_true",
                        help="pin each local rank to an equal slice of host "
                        "cores via taskset (reference launch.py numactl "
                        "binding — keeps host-side input pipelines and the "
                        "offload-tier CPU optimizer off each other's cores)")
    parser.add_argument("user_script", type=str)
    parser.add_argument("user_args", nargs=argparse.REMAINDER)
    return parser.parse_args(args=args)


def decode_world_info(world_info_b64: str) -> dict:
    return json.loads(base64.urlsafe_b64decode(world_info_b64.encode()).decode())


def build_rank_env(world_info: dict, node_rank: int, local_rank: int,
                   coordinator_addr: str, coordinator_port: int) -> dict:
    """Compute the global process id + rendezvous env for one local worker."""
    hosts = list(world_info.keys())
    procs_before = sum(len(world_info[h]) for h in hosts[:node_rank])
    total = sum(len(v) for v in world_info.values())
    env = dict(os.environ)
    env[ENV_COORDINATOR] = f"{coordinator_addr}:{coordinator_port}"
    env[ENV_NUM_PROCESSES] = str(total)
    env[ENV_PROCESS_ID] = str(procs_before + local_rank)
    env[ENV_LOCAL_RANK] = str(local_rank)
    env[ENV_HOSTNAME] = hosts[node_rank] if node_rank < len(hosts) else "localhost"
    return env


def core_binding_prefix(local_rank: int, nproc: int) -> List[str]:
    """An equal slice of this process's ALLOWED cores per local rank
    (reference ``launch.py`` numactl/core-binding path; ``utils/numa.py``).
    Uses sched_getaffinity, not cpu_count — in a cgroup/cpuset-limited
    container the machine's full core list is not bindable. Empty when cores
    can't be split."""
    try:
        cores = sorted(os.sched_getaffinity(0))
    except AttributeError:       # non-linux: no taskset either — skip binding
        return []
    per = len(cores) // nproc
    if per < 1:
        return []
    mine = cores[local_rank * per:] if local_rank == nproc - 1 \
        else cores[local_rank * per:(local_rank + 1) * per]
    return ["taskset", "-c", ",".join(str(c) for c in mine)]


def main(args=None):
    args = parse_args(args)
    world_info = decode_world_info(args.world_info)
    node_rank = int(str(args.node_rank).lstrip("%n").lstrip("$") or "0") \
        if not str(args.node_rank).isdigit() else int(args.node_rank)
    hosts = list(world_info.keys())
    if node_rank >= len(hosts):
        raise ValueError(f"node_rank {node_rank} out of range for {len(hosts)} hosts")
    local_workers = world_info[hosts[node_rank]]
    nproc = args.nproc_per_node or len(local_workers)
    if nproc != len(local_workers):
        # --nproc_per_node override: homogeneous re-slotting so global ids and
        # the world size stay consistent
        world_info = {h: list(range(nproc)) for h in hosts}

    processes: List[subprocess.Popen] = []
    for local_rank in range(nproc):
        env = build_rank_env(world_info, node_rank, local_rank,
                             args.coordinator_addr, args.coordinator_port)
        cmd = [sys.executable, "-u", args.user_script] + args.user_args
        if args.bind_cores_to_rank:
            cmd = core_binding_prefix(local_rank, nproc) + cmd
        logger.info(f"launching local rank {local_rank}: {' '.join(cmd)}")
        processes.append(subprocess.Popen(cmd, env=env))

    def sig_handler(signum, frame):
        for p in processes:
            if p.poll() is None:
                p.send_signal(signum)
        sys.exit(128 + signum)

    signal.signal(signal.SIGINT, sig_handler)
    signal.signal(signal.SIGTERM, sig_handler)

    # Monitor: if any child exits non-zero, kill the rest (reference launch.py
    # main-loop + terminate_process_tree).
    exit_code = 0
    alive = list(processes)
    while alive:
        for p in list(alive):
            rc = p.poll()
            if rc is None:
                continue
            alive.remove(p)
            if rc != 0:
                logger.error(f"child {p.pid} failed with code {rc}; "
                             "terminating remaining workers")
                exit_code = rc
                for q in alive:
                    if q.poll() is None:
                        q.terminate()
                for q in alive:
                    try:
                        q.wait(timeout=30)
                    except subprocess.TimeoutExpired:
                        q.kill()
                alive = []
                break
        time.sleep(0.5)
    sys.exit(exit_code)


if __name__ == "__main__":
    main()
