"""Pipeline bubble-overhead measurement.

The SPMD 1F1B executor predicates each macro-step's forward and backward
halves with ``lax.cond`` (``one_f_one_b.py``): fill steps run forward-only,
drain steps backward-only, so the bubble is the true 1F1B
``(s-1)/(m+s-1)`` rather than the all-masked lockstep model's
``2(s-1)/(2(s-1)+m)``. This bench A/Bs the two executors at identical
(m, s): ``predicate=True`` vs the masked dead-compute baseline
(``predicate=False``, the pre-predication executor).

On a virtual CPU mesh the "devices" share the host cores, so wall-clock
tracks TOTAL executed work, not the per-step max: masked, each of the
``s`` devices executes a full fwd+bwd in all ``2(s-1)+m`` macro-steps;
predicated, it executes only its ``m`` forwards and ``m`` backwards —
analytic shared-core speedup ``t_masked/t_pred ≈ (2(s-1)+m)/m``. On real
multi-chip hardware (per-step max over stages) the ratio would instead be
``(2(s-1)+m)/(m+s-1)``. Reports measured speedup per m alongside both
analytic bubble models (reference host-1F1B ``(s-1)/(m+s-1)``, deepspeed
schedule.py:189, now matched by this executor).

Usage: ``dstpu_pipe_bench [--stages 4] [--layers 8] [--hidden 256]``.
Prints one JSON line.
"""

import argparse
import json
import time


def main(argv=None):
    p = argparse.ArgumentParser()
    p.add_argument("--stages", type=int, default=4)
    p.add_argument("--layers", type=int, default=8)
    p.add_argument("--hidden", type=int, default=256)
    p.add_argument("--seq", type=int, default=64)
    p.add_argument("--micro-batch", type=int, default=2)
    p.add_argument("--microbatches", type=int, nargs="+", default=[4, 8, 16])
    p.add_argument("--reps", type=int, default=5)
    args = p.parse_args(argv)

    import os
    import sys
    sys.path.insert(0, os.getcwd())
    try:
        from bench_util import bounded_device_discovery
        # bounded-init path: deadline + backoff retries + classified rc and
        # one-line diagnosis (tunnel wedge vs no devices vs auth)
        bounded_device_discovery("dstpu_pipe_bench")
    except ImportError:       # installed outside the repo root
        pass

    import jax
    jax.devices()
    import jax.numpy as jnp
    import numpy as np

    from deepspeed_tpu.comm.mesh import create_mesh, set_global_mesh
    from deepspeed_tpu.config.config import MeshConfig
    from deepspeed_tpu.models.llama import LlamaConfig, LlamaForCausalLM
    from deepspeed_tpu.runtime.pipe.module import llama_pipe_module
    from deepspeed_tpu.runtime.pipe.one_f_one_b import (
        pipeline_train_step_1f1b)
    from deepspeed_tpu.runtime.pipe.schedule import (bubble_fraction,
                                                     lockstep_bubble_fraction,
                                                     num_macro_steps)

    s = args.stages
    n_dev = len(jax.devices())
    if n_dev % s:
        raise SystemExit(f"{n_dev} devices not divisible by {s} stages")
    cfg = LlamaConfig(vocab_size=256, hidden_size=args.hidden,
                      intermediate_size=2 * args.hidden,
                      num_layers=args.layers, num_heads=4, num_kv_heads=4,
                      max_seq_len=args.seq, scan_layers=True,
                      dtype=jnp.float32)
    model = LlamaForCausalLM(cfg)
    rng = np.random.default_rng(0)

    mesh = create_mesh(MeshConfig(pipe=s, data=n_dev // s))
    set_global_mesh(mesh)
    init_toks = rng.integers(0, 256, size=(2, args.seq)).astype(np.int32)
    params = model.init(jax.random.PRNGKey(0),
                        {"input_ids": jnp.asarray(init_toks)})
    mod = llama_pipe_module(cfg, params)

    def make_step(predicate):
        def step(stacked, tied, toks_mb):
            loss, gp, gt = pipeline_train_step_1f1b(
                mod.block_fn, stacked, tied, toks_mb, mod.first_fn,
                mod.last_fn, mesh=mesh, predicate=predicate)
            return loss, jax.tree.map(jnp.sum, (gp, gt))
        return jax.jit(step)

    step_pred, step_mask = make_step(True), make_step(False)

    def timeit(fn, toks_mb):
        out = fn(mod.stacked_params, mod.tied_params, toks_mb)   # compile
        jax.block_until_ready(out)
        best = float("inf")
        for _ in range(args.reps):
            t0 = time.perf_counter()
            jax.block_until_ready(
                fn(mod.stacked_params, mod.tied_params, toks_mb))
            best = min(best, time.perf_counter() - t0)  # min: robust to
        return best                                     # scheduler noise

    points = []
    for m in args.microbatches:
        toks = jnp.asarray(rng.integers(
            0, 256, size=(m, args.micro_batch, args.seq)), jnp.int32)
        t_pred = timeit(step_pred, toks)
        t_mask = timeit(step_mask, toks)
        points.append((m, t_pred, t_mask))

    speedups = [tm / tp for _, tp, tm in points]
    out = {
        "metric": "pipe_predication_speedup",
        "value": round(float(np.median(speedups)), 3),
        "unit": "t_masked/t_predicated at same (m, s); shared-core model "
                "(2(s-1)+m)/m, real-chip model (2(s-1)+m)/(m+s-1)",
        "stages": s,
        "points": [
            {"microbatches": m, "macro_steps": int(num_macro_steps(m, s)),
             "t_predicated_s": round(tp, 4), "t_masked_s": round(tm, 4),
             "speedup": round(tm / tp, 3),
             "model_shared_core": round((2 * (s - 1) + m) / m, 3),
             "model_real_chip": round(
                 (2 * (s - 1) + m) / (m + s - 1), 3),
             "bubble_lockstep": round(lockstep_bubble_fraction(m, s), 3),
             "bubble_host_1f1b": round(bubble_fraction(m, s), 3)}
            for m, tp, tm in points],
    }
    print(json.dumps(out))
    return 0
