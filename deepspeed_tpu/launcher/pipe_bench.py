"""Pipeline bubble-overhead measurement.

The lockstep SPMD executor's cost model says one train step costs
``num_macro_steps(m, s) = 2(s-1) + m`` macro-steps, each a full stage
fwd+bwd on every device (fill/drain steps run masked dead compute), which
makes the bubble overhead ``2(s-1) / (2(s-1) + m)``. On a virtual CPU
mesh wall-clock speedup is meaningless (all "devices" share the host
cores), but the model's testable invariant IS measurable:
``step_time / num_macro_steps`` should be constant across microbatch
counts. This sweep times several m (min over reps, robust to scheduler
noise) and reports the coefficient of variation of the per-macro-step
time, alongside both analytic bubble models (lockstep
``2(s-1)/(2(s-1)+m)`` vs the reference host-1F1B ``(s-1)/(m+s-1)``,
deepspeed schedule.py:189).

Usage: ``dstpu_pipe_bench [--stages 4] [--layers 8] [--hidden 64]``.
Prints one JSON line.
"""

import argparse
import json
import time


def main(argv=None):
    p = argparse.ArgumentParser()
    p.add_argument("--stages", type=int, default=4)
    p.add_argument("--layers", type=int, default=8)
    p.add_argument("--hidden", type=int, default=64)
    p.add_argument("--seq", type=int, default=64)
    p.add_argument("--micro-batch", type=int, default=2)
    p.add_argument("--microbatches", type=int, nargs="+",
                   default=[2, 4, 8, 16])
    p.add_argument("--reps", type=int, default=5)
    args = p.parse_args(argv)

    import os
    import sys
    sys.path.insert(0, os.getcwd())
    try:
        from bench_util import guard_device_discovery
        disarm = guard_device_discovery("dstpu_pipe_bench")
    except ImportError:       # installed outside the repo root
        disarm = lambda: None  # noqa: E731

    import jax
    jax.devices()
    disarm()
    import jax.numpy as jnp
    import numpy as np

    import deepspeed_tpu
    from deepspeed_tpu.comm.mesh import create_mesh, set_global_mesh
    from deepspeed_tpu.config.config import MeshConfig
    from deepspeed_tpu.models.llama import LlamaConfig, LlamaForCausalLM
    from deepspeed_tpu.runtime.pipe.module import llama_pipe_module
    from deepspeed_tpu.runtime.pipe.schedule import (bubble_fraction,
                                                     lockstep_bubble_fraction,
                                                     num_macro_steps)

    s = args.stages
    n_dev = len(jax.devices())
    if n_dev % s:
        raise SystemExit(f"{n_dev} devices not divisible by {s} stages")
    cfg = LlamaConfig(vocab_size=256, hidden_size=args.hidden,
                      intermediate_size=2 * args.hidden,
                      num_layers=args.layers, num_heads=4, num_kv_heads=4,
                      max_seq_len=args.seq, scan_layers=True,
                      dtype=jnp.float32)
    model = LlamaForCausalLM(cfg)
    rng = np.random.default_rng(0)

    mesh = create_mesh(MeshConfig(pipe=s, data=n_dev // s))
    set_global_mesh(mesh)
    init_toks = rng.integers(0, 256, size=(2, args.seq)).astype(np.int32)
    params = model.init(jax.random.PRNGKey(0),
                        {"input_ids": jnp.asarray(init_toks)})
    points = []
    for m in args.microbatches:
        b = m * args.micro_batch
        tokens = rng.integers(0, 256, size=(b, args.seq)).astype(np.int32)
        engine, _, _, _ = deepspeed_tpu.initialize(
            model=llama_pipe_module(cfg, params), mesh=mesh,
            config={"gradient_accumulation_steps": m,
                    "optimizer": {"type": "AdamW", "params": {"lr": 1e-3}}})
        assert engine.micro_batches == m, (engine.micro_batches, m)
        engine.train_batch(tokens)                       # compile
        best = float("inf")
        for _ in range(args.reps):
            t0 = time.perf_counter()
            engine.train_batch(tokens)
            best = min(best, time.perf_counter() - t0)   # min: robust to
        points.append((num_macro_steps(m, s), m, best))  # scheduler noise

    # the cost model: every macro-step costs one stage fwd+bwd, so
    # step_time / macro_steps should be CONSTANT across m — report its
    # spread (cv) as the model-fit metric
    per = np.array([t / k for k, _, t in points], np.float64)
    cv = float(per.std() / per.mean()) if per.mean() else 1.0
    out = {
        "metric": "pipe_macro_step_time_cv",
        "value": round(cv, 4),
        "unit": "std/mean (lower = cost model holds)",
        "stages": s,
        "per_macro_step_s_mean": round(float(per.mean()), 5),
        "points": [
            {"microbatches": m, "macro_steps": int(k),
             "step_s": round(t, 4),
             "per_macro_step_s": round(t / k, 5),
             "bubble_lockstep": round(lockstep_bubble_fraction(m, s), 3),
             "bubble_host_1f1b": round(bubble_fraction(m, s), 3)}
            for k, m, t in points],
    }
    print(json.dumps(out))
    return 0
