"""Multi-node runners — build the command that starts ``launch.py`` on every node.

Reference analog: ``deepspeed/launcher/multinode_runner.py:18-384`` (PDSH/OpenMPI/
MPICH/IMPI/Slurm/MVAPICH runner classes). TPU-native additions: a ``gcloud``
runner that fans out over TPU-VM workers with
``gcloud compute tpus tpu-vm ssh --worker=all``, and a plain ``ssh`` runner with
no pdsh dependency.

Each runner only *constructs* the command (unit-testable without ssh); ``exec``
replaces the current process like the reference does.
"""

import os
import shutil
import subprocess
import sys
from abc import ABC, abstractmethod
from shlex import quote
from typing import Dict, List

from deepspeed_tpu.launcher.constants import (DEFAULT_COORDINATOR_PORT,
                                              EXPORT_ENVS, PDSH_MAX_FAN_OUT)
from deepspeed_tpu.utils.logging import logger


class MultiNodeRunner(ABC):
    """Builds and launches the per-node command (reference multinode_runner.py:18)."""

    def __init__(self, args, world_info_base64: str):
        self.args = args
        self.user_arguments = self.parse_user_args()
        self.user_script = args.user_script
        self.world_info_base64 = world_info_base64
        self.exports: Dict[str, str] = {}

    @abstractmethod
    def get_cmd(self, environment: Dict[str, str],
                active_resources: Dict[str, List[int]]) -> List[str]:
        """Return the shell command to launch on the cluster."""

    def add_export(self, key: str, var: str) -> None:
        self.exports[key.strip()] = var.strip()

    def parse_user_args(self):
        return self.args.user_args

    @property
    def name(self) -> str:
        return self.__class__.__name__

    def backend_exists(self) -> bool:
        return True

    def export_envs_from_environ(self, environment: Dict[str, str]) -> None:
        for var, val in environment.items():
            if any(var.startswith(prefix) for prefix in EXPORT_ENVS):
                self.add_export(var, quote(val))


class PDSHRunner(MultiNodeRunner):
    """pdsh fan-out (reference multinode_runner.py:60 PDSHRunner)."""

    def backend_exists(self) -> bool:
        return shutil.which("pdsh") is not None

    def get_cmd(self, environment, active_resources):
        environment = dict(environment)
        environment["PDSH_RCMD_TYPE"] = "ssh"
        self.export_envs_from_environ(environment)

        active_workers = ",".join(active_resources.keys())
        logger.info(f"Running on the following workers: {active_workers}")

        pdsh_cmd = ["pdsh", "-S", "-f", str(PDSH_MAX_FAN_OUT), "-w", active_workers]
        exports = "".join(f"export {k}={v}; " for k, v in self.exports.items())

        # pdsh runs this on every node; launch.py reads its own node rank from
        # the hostname it sees (%h substitution).
        launch_cmd = [
            exports + f"cd {os.path.abspath('.')};",
            sys.executable, "-u", "-m", "deepspeed_tpu.launcher.launch",
            f"--world_info={self.world_info_base64}",
            "--node_rank=%n",
            f"--coordinator_addr={self.args.coordinator_addr}",
            f"--coordinator_port={self.args.coordinator_port}",
        ]
        if self.args.nproc_per_node is not None:
            launch_cmd.append(f"--nproc_per_node={self.args.nproc_per_node}")
        launch_cmd.append(self.user_script)
        launch_cmd.extend(map(quote, self.user_arguments))
        return pdsh_cmd + [" ".join(launch_cmd)]


class SSHRunner(MultiNodeRunner):
    """Plain-ssh fan-out, one background ssh per node; no pdsh required."""

    def backend_exists(self) -> bool:
        return shutil.which("ssh") is not None

    def get_node_cmd(self, host: str, node_rank: int, environment) -> List[str]:
        self.export_envs_from_environ(environment)
        exports = "".join(f"export {k}={v}; " for k, v in self.exports.items())
        remote = (
            exports + f"cd {os.path.abspath('.')}; "
            f"{sys.executable} -u -m deepspeed_tpu.launcher.launch "
            f"--world_info={self.world_info_base64} "
            f"--node_rank={node_rank} "
            f"--coordinator_addr={self.args.coordinator_addr} "
            f"--coordinator_port={self.args.coordinator_port} "
            + (f"--nproc_per_node={self.args.nproc_per_node} "
               if self.args.nproc_per_node is not None else "")
            + quote(self.user_script) + " "
            + " ".join(map(quote, self.user_arguments)))
        return ["ssh", "-o", "StrictHostKeyChecking=no", host, remote]

    def get_cmd(self, environment, active_resources):
        # Composite: the runner main() iterates get_node_cmd per host instead.
        raise NotImplementedError("SSHRunner launches per-node; use get_node_cmd")


class GcloudTPURunner(MultiNodeRunner):
    """TPU-VM pod fan-out via ``gcloud compute tpus tpu-vm ssh --worker=all``.

    TPU pods have no hostfile: every worker runs the same command and JAX
    discovers peers from TPU metadata, so no world_info/node_rank is injected.
    """

    def backend_exists(self) -> bool:
        return shutil.which("gcloud") is not None

    def get_cmd(self, environment, active_resources):
        self.export_envs_from_environ(environment)
        exports = "".join(f"export {k}={v}; " for k, v in self.exports.items())
        remote = (exports + f"cd {os.path.abspath('.')}; "
                  f"{sys.executable} -u " + quote(self.user_script) + " "
                  + " ".join(map(quote, self.user_arguments)))
        cmd = ["gcloud", "compute", "tpus", "tpu-vm", "ssh",
               self.args.tpu_name, "--worker=all"]
        if self.args.tpu_zone:
            cmd.append(f"--zone={self.args.tpu_zone}")
        cmd.append(f"--command={remote}")
        return cmd


class SlurmRunner(MultiNodeRunner):
    """srun dispatch (reference multinode_runner.py:304 SlurmRunner)."""

    def backend_exists(self) -> bool:
        return shutil.which("srun") is not None

    def get_cmd(self, environment, active_resources):
        self.export_envs_from_environ(environment)
        total_nodes = len(active_resources)
        srun_cmd = ["srun", "-N", str(total_nodes), "--ntasks-per-node=1"]
        if getattr(self.args, "slurm_comment", ""):
            srun_cmd += ["--comment", self.args.slurm_comment]
        exports = ",".join(f"{k}={v}" for k, v in self.exports.items())
        if exports:
            srun_cmd += [f"--export=ALL,{exports}"]
        launch = [sys.executable, "-u", "-m", "deepspeed_tpu.launcher.launch",
                  f"--world_info={self.world_info_base64}",
                  "--node_rank=$SLURM_NODEID",
                  f"--coordinator_addr={self.args.coordinator_addr}",
                  f"--coordinator_port={self.args.coordinator_port}",
                  self.user_script] + list(map(quote, self.user_arguments))
        return srun_cmd + launch


class XpkRunner(MultiNodeRunner):
    """GKE TPU-pod dispatch via ``xpk workload create`` (the batch-scheduler
    path for Cloud TPU multislice — the TPU-pod analog of the reference's
    SLURM runner, multinode_runner.py:303).

    Like GcloudTPURunner, no world_info/node_rank is injected: every worker
    of every slice runs the same command and JAX discovers peers from TPU
    metadata (plus MEGASCALE env for multislice, which xpk sets).
    """

    def backend_exists(self) -> bool:
        return shutil.which("xpk") is not None

    def get_cmd(self, environment, active_resources):
        self.export_envs_from_environ(environment)
        exports = "".join(f"export {k}={v}; " for k, v in self.exports.items())
        remote = (exports + f"{sys.executable} -u "
                  + quote(self.user_script) + " "
                  + " ".join(map(quote, self.user_arguments))).strip()
        cmd = ["xpk", "workload", "create",
               f"--cluster={self.args.xpk_cluster}",
               f"--workload={self.args.xpk_workload}",
               f"--tpu-type={self.args.tpu_type}",
               f"--num-slices={self.args.num_slices}"]
        if self.args.xpk_docker_image:
            cmd.append(f"--docker-image={self.args.xpk_docker_image}")
        if self.args.tpu_zone:
            cmd.append(f"--zone={self.args.tpu_zone}")
        cmd.append(f"--command={remote}")
        return cmd


class MPIRunner(MultiNodeRunner):
    """mpirun dispatch (reference multinode_runner.py:124 OpenMPIRunner).

    One process per host; ranks read OMPI/PMI env to find their process id.
    """

    def backend_exists(self) -> bool:
        return shutil.which("mpirun") is not None

    def get_cmd(self, environment, active_resources):
        self.export_envs_from_environ(environment)
        total_procs = len(active_resources)
        hosts = ",".join(active_resources.keys())
        mpi_cmd = ["mpirun", "-n", str(total_procs), "-host", hosts]
        for k, v in self.exports.items():
            mpi_cmd += ["-x", f"{k}={v}"]
        return mpi_cmd + [sys.executable, "-u", self.user_script] + \
            list(map(quote, self.user_arguments))
