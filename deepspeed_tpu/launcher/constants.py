"""Launcher constants (reference: deepspeed/launcher/constants.py)."""

PDSH_LAUNCHER = "pdsh"
SSH_LAUNCHER = "ssh"
GCLOUD_LAUNCHER = "gcloud"
SLURM_LAUNCHER = "slurm"
MPICH_LAUNCHER = "mpich"
OPENMPI_LAUNCHER = "openmpi"
XPK_LAUNCHER = "xpk"

PDSH_MAX_FAN_OUT = 1024

# Env vars every launched rank receives (consumed by comm.mesh.init_distributed).
ENV_COORDINATOR = "DSTPU_COORDINATOR_ADDRESS"
ENV_NUM_PROCESSES = "DSTPU_NUM_PROCESSES"
ENV_PROCESS_ID = "DSTPU_PROCESS_ID"
ENV_LOCAL_RANK = "DSTPU_LOCAL_RANK"
ENV_HOSTNAME = "DSTPU_HOSTNAME"
# JSON config-override dict the elastic agent exports when a shrink's
# ledger preflight escalated the offload ladder (fewer chips => more bytes
# per chip); DeepSpeedTPUConfig deep-merges it over the worker's raw config
# at parse time, so relaunched workers train at the escalated tier with no
# config-file edit
ENV_CONFIG_OVERRIDES = "DSTPU_ELASTIC_CONFIG_OVERRIDES"

DEFAULT_COORDINATOR_PORT = 8476

# Env vars forwarded from the runner's environment to every node (reference
# forwards NCCL_*/PYTHON* etc, launcher/runner.py EXPORT_ENVS).
EXPORT_ENVS = [
    "JAX_", "XLA_", "LIBTPU_", "TPU_", "PYTHON", "PATH", "LD_LIBRARY",
    "DSTPU_", "HF_", "TRANSFORMERS_",
]
