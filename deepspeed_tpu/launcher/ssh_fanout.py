"""Parallel ssh fanout over a hostfile.

Reference analog: ``bin/ds_ssh`` — reads the DLTS hostfile and runs the given
command on every host (pdsh-style), used for cluster-wide setup/inspection.
Here: threads + ``subprocess ssh`` with per-host prefixed output, the same
hostfile grammar as the launcher (``launcher/runner.py:fetch_hostfile``).
"""

import argparse
import shlex
import subprocess
import sys
import threading

from deepspeed_tpu.launcher.runner import DLTS_HOSTFILE, fetch_hostfile

SSH_OPTS = ["-o", "StrictHostKeyChecking=no", "-o", "PasswordAuthentication=no"]


def parse_args(args=None):
    p = argparse.ArgumentParser(
        description="run a command on every hostfile host (ds_ssh analog)")
    p.add_argument("-H", "--hostfile", default=DLTS_HOSTFILE)
    p.add_argument("--ssh_port", type=int, default=None)
    p.add_argument("command", nargs=argparse.REMAINDER,
                   help="command to run remotely")
    return p.parse_args(args)


def run_on_host(host: str, command, port=None, runner=subprocess.run):
    # one argument = a shell snippet, passed through verbatim so pipes/&&/env
    # expand remotely (ds_ssh behavior); multiple argv words are quoted so
    # boundaries and metacharacters survive the ssh hop
    remote = command[0] if len(command) == 1 else shlex.join(command)
    cmd = ["ssh"] + SSH_OPTS + (["-p", str(port)] if port else []) + \
        [host, remote]
    proc = runner(cmd, capture_output=True, text=True)
    return host, proc.returncode, proc.stdout, proc.stderr


def fanout(hosts, command, port=None, runner=subprocess.run):
    results = {}
    lock = threading.Lock()

    def work(h):
        host, rc, out, err = run_on_host(h, command, port, runner)
        with lock:
            results[host] = (rc, out, err)

    threads = [threading.Thread(target=work, args=(h,)) for h in hosts]
    for t in threads:
        t.start()
    for t in threads:
        t.join()
    return results


def main(args=None):
    a = parse_args(args)
    if not a.command:
        print("usage: dstpu_ssh [-H hostfile] <command...>", file=sys.stderr)
        return 2
    pool = fetch_hostfile(a.hostfile)
    hosts = list(pool) or ["localhost"]
    results = fanout(hosts, a.command, a.ssh_port)
    worst = 0
    for host in hosts:
        rc, out, err = results[host]
        if rc != 0 and worst == 0:
            worst = rc if 0 < rc < 256 else 1  # signal-killed ssh: rc<0 -> 1
        for line in (out or "").splitlines():
            print(f"{host}: {line}")
        for line in (err or "").splitlines():
            print(f"{host}: {line}", file=sys.stderr)
    return worst
