"""``dstpu_io`` — NVMe/SSD async-I/O benchmark (reference: ``bin/ds_io`` →
``deepspeed/nvme/perf_run_sweep.py`` sweeping the csrc/aio engine).

Measures read/write GB/s of the C++ async I/O engine
(``deepspeed_tpu/ops/csrc/aio.cpp``) against a target directory, sweeping
block size and queue depth; prints the best config like ``ds_nvme_tune``.
"""

import argparse
import json
import os
import sys
import tempfile
import time

import numpy as np


def parse_args(args=None):
    p = argparse.ArgumentParser(description="async I/O throughput sweep")
    p.add_argument("--path", default=None, help="target dir (default: tmp)")
    p.add_argument("--size_mb", type=int, default=256, help="file size per trial")
    p.add_argument("--threads", type=int, nargs="+", default=[1, 4, 8])
    p.add_argument("--trials", type=int, default=3)
    p.add_argument("--read_only", action="store_true")
    p.add_argument("--write_only", action="store_true")
    return p.parse_args(args)


def bench_config(path: str, size_mb: int, threads: int, trials: int,
                 do_read=True, do_write=True):
    from deepspeed_tpu.ops.async_io import AsyncIOHandle
    handle = AsyncIOHandle(num_threads=threads)
    nbytes = size_mb << 20
    data = np.random.randint(0, 255, size=nbytes, dtype=np.uint8)
    out = {"threads": threads, "size_mb": size_mb}
    fname = os.path.join(path, f"dstpu_io_{os.getpid()}.bin")
    try:
        if do_write:
            rates = []
            for _ in range(trials):
                t0 = time.perf_counter()
                rid = handle.async_pwrite(data, fname)
                handle.wait(rid)
                rates.append(nbytes / (time.perf_counter() - t0))
            out["write_gbps"] = max(rates) / 1e9
        if do_read:
            if not os.path.exists(fname):
                with open(fname, "wb") as f:
                    f.write(data.tobytes())
            dst = np.empty(nbytes, dtype=np.uint8)
            rates = []
            for _ in range(trials):
                t0 = time.perf_counter()
                rid = handle.async_pread(dst, fname)
                handle.wait(rid)
                rates.append(nbytes / (time.perf_counter() - t0))
            out["read_gbps"] = max(rates) / 1e9
    finally:
        if os.path.exists(fname):
            os.unlink(fname)
    return out


def main(args=None):
    args = parse_args(args)
    path = args.path or tempfile.gettempdir()
    results = []
    for t in args.threads:
        r = bench_config(path, args.size_mb, t, args.trials,
                         do_read=not args.write_only,
                         do_write=not args.read_only)
        results.append(r)
        print(json.dumps(r))
    best = max(results, key=lambda r: r.get("read_gbps", 0) + r.get("write_gbps", 0))
    print(f"best config: {json.dumps(best)}")
    return 0


if __name__ == "__main__":
    sys.exit(main())
