"""NVMe/SSD tuning sweep — find the (threads, block size) that saturates disk.

Reference analog: ``bin/ds_nvme_tune`` + ``deepspeed/nvme/`` (1283 LoC:
``sweep_main`` runs a grid over queue depth / block size / submit mode /
io-parallelism and writes the winning config for ``aio`` JSON blocks).

TPU redesign: the swap engine (``ops/csrc/aio.cpp``) is a pread/pwrite thread
pool, so the tunables are worker threads x request block size; large transfers
are split into block-sized sub-requests at different file offsets so all
workers pull concurrently (the same role as the reference's queue-depth x
block-size grid for libaio). The winner is printed as the ``"aio"`` config
block the offload tier consumes.
"""

import argparse
import json
import os
import sys
import tempfile
import time

import numpy as np


def parse_args(args=None):
    p = argparse.ArgumentParser(description="NVMe tuning sweep (ds_nvme_tune analog)")
    p.add_argument("--nvme_dir", "--path", dest="nvme_dir", default=None,
                   help="directory on the device under test (default: tmp)")
    p.add_argument("--size_mb", type=int, default=512)
    p.add_argument("--threads", type=int, nargs="+", default=[1, 2, 4, 8, 16])
    p.add_argument("--block_mb", type=int, nargs="+", default=[1, 4, 16, 64])
    p.add_argument("--trials", type=int, default=2)
    p.add_argument("--out", default=None, help="write winning config JSON here")
    return p.parse_args(args)


def _run_chunked(handle, arr, path, block_bytes, write: bool) -> float:
    """Submit |arr| as block-sized sub-requests at increasing offsets; return
    seconds to drain them all."""
    n = arr.nbytes
    t0 = time.perf_counter()
    reqs = []
    for off in range(0, n, block_bytes):
        chunk = arr[off:off + block_bytes]
        reqs.append(handle.async_pwrite(chunk, path, offset=off) if write
                    else handle.async_pread(chunk, path, offset=off))
    failed = sum(handle.wait(r) for r in reqs)
    if failed:
        raise IOError(f"{failed}/{len(reqs)} aio requests failed on {path} "
                      f"({'write' if write else 'read'}, block={block_bytes})")
    return time.perf_counter() - t0


def sweep(nvme_dir=None, size_mb=512, threads=(1, 4, 8), block_mb=(1, 16),
          trials=2):
    from deepspeed_tpu.ops.async_io import AsyncIOHandle

    nvme_dir = nvme_dir or tempfile.gettempdir()
    nbytes = size_mb << 20
    data = np.random.randint(0, 255, size=nbytes, dtype=np.uint8)
    dst = np.empty(nbytes, dtype=np.uint8)
    fname = os.path.join(nvme_dir, f"dstpu_nvme_tune_{os.getpid()}.bin")
    results = []
    # pre-size the target so concurrent offset writes never race on creation
    # (the thread-pool fallback opens 'wb' when the file doesn't exist yet)
    with open(fname, "wb") as f:
        f.truncate(nbytes)
    # blocks >= the file are one whole-file request: test that size once
    blocks = sorted({min(b, size_mb) for b in block_mb})
    try:
        for t in threads:
            handle = AsyncIOHandle(num_threads=t)
            for b in blocks:
                bb = b << 20
                w = min(_run_chunked(handle, data, fname, bb, write=True)
                        for _ in range(trials))
                r = min(_run_chunked(handle, dst, fname, bb, write=False)
                        for _ in range(trials))
                results.append({
                    "threads": t, "block_mb": b,
                    "write_gbps": round(nbytes / w / 1e9, 3),
                    "read_gbps": round(nbytes / r / 1e9, 3),
                })
    finally:
        if os.path.exists(fname):
            os.unlink(fname)
    return results


def main(args=None):
    a = parse_args(args)
    results = sweep(a.nvme_dir, a.size_mb, a.threads, a.block_mb, a.trials)
    for row in results:
        print(json.dumps(row))
    best = max(results, key=lambda r: r["read_gbps"] + r["write_gbps"])
    config = {"aio": {
        "thread_count": best["threads"],
        "block_size": best["block_mb"] << 20,
        "single_submit": False, "overlap_events": True,
        "measured_read_gbps": best["read_gbps"],
        "measured_write_gbps": best["write_gbps"],
    }}
    print(json.dumps(config))
    if a.out:
        with open(a.out, "w") as f:
            json.dump(config, f, indent=2)
        print(f"wrote {a.out}", file=sys.stderr)
    return 0
