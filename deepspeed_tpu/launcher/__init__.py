"""Launcher / CLI layer (reference L7: ``deepspeed/launcher/``, ``bin/``)."""

from deepspeed_tpu.launcher.runner import (encode_world_info, fetch_hostfile,
                                           parse_resource_filter)

__all__ = ["fetch_hostfile", "parse_resource_filter", "encode_world_info"]
