"""``dstpu_bench`` — collective micro-benchmark sweep (reference: ``bin/ds_bench``
feeding ``deepspeed/utils/comms_logging.py`` algbw/busbw reporting).

Sweeps message sizes for one collective over a chosen mesh axis and prints
latency, algorithm bandwidth, and bus bandwidth per size (calc_bw_log parity,
``utils/comms_logging.py:34``).
"""

import argparse
import sys
import time

import numpy as np


def parse_args(args=None):
    p = argparse.ArgumentParser(description="collective micro-benchmark sweep")
    p.add_argument("--op", default="all_reduce",
                   choices=["all_reduce", "all_gather", "reduce_scatter",
                            "all_to_all", "ppermute",
                            "quantized_psum", "quantized_all_gather",
                            "quantized_all_to_all"])
    p.add_argument("--axis", default="data", help="mesh axis to benchmark over")
    p.add_argument("--minsize", type=int, default=1 << 12, help="min bytes")
    p.add_argument("--maxsize", type=int, default=1 << 26, help="max bytes")
    p.add_argument("--trials", type=int, default=20)
    p.add_argument("--warmups", type=int, default=5)
    p.add_argument("--dtype", default="bfloat16")
    return p.parse_args(args)


QUANTIZED_OPS = ("quantized_psum", "quantized_all_gather",
                 "quantized_all_to_all")


def run_sweep(op: str, axis: str, minsize: int, maxsize: int, trials: int,
              warmups: int, dtype: str = "bfloat16"):
    import jax
    import jax.numpy as jnp
    from jax.sharding import Mesh, PartitionSpec as P

    from deepspeed_tpu.comm import comm
    from deepspeed_tpu.comm.comms_logging import calc_bw
    from deepspeed_tpu.ops.pallas import quant as _quant

    devices = np.array(jax.devices())
    world = len(devices)
    mesh = Mesh(devices.reshape(world), (axis,))
    jdtype = jnp.dtype(dtype)

    fns = {
        "all_reduce": lambda x: comm.all_reduce(x, axis),
        "all_gather": lambda x: comm.all_gather(x, axis),
        "reduce_scatter": lambda x: comm.reduce_scatter(x, axis),
        "all_to_all": lambda x: comm.all_to_all(x, axis, 0, 0),
        "ppermute": lambda x: comm.ppermute(
            x, axis, [(i, (i + 1) % world) for i in range(world)]),
        # int8-wire collectives (ZeRO++ qgZ / MoE dispatch formats) — same
        # logical reduction with ~4x fewer wire bytes than fp32; comparing
        # these rows against their dense siblings measures the compression
        # win on real ICI/DCN (ops/pallas/quant.py)
        "quantized_psum": lambda x: _quant.quantized_psum(
            x.reshape(world, -1), (axis,)).ravel(),
        "quantized_all_gather": lambda x: _quant.quantized_all_gather(
            x.reshape(world, -1), axis).ravel(),
        "quantized_all_to_all": lambda x: _quant.quantized_all_to_all(
            x.reshape(world, -1), axis).ravel(),
    }
    body = fns[op]

    @jax.jit
    def step(x):
        # out_specs is P(axis) for every op: all_gather's per-shard output is the
        # full gathered array, so its global result is simply world× larger.
        return jax.shard_map(
            lambda v: body(v), mesh=mesh, in_specs=P(axis), out_specs=P(axis),
            # pallas quant kernels need vma checks off; keep the guard for
            # the dense collectives
            check_vma=op not in QUANTIZED_OPS)(x)

    results = []
    size = minsize
    while size <= maxsize:
        # quantized ops reshape each local shard to (world, -1), so the
        # global element count must divide by world^2
        align = world * world if op in QUANTIZED_OPS else world
        n_elem = max(align, size // jdtype.itemsize)
        n_elem -= n_elem % align
        x = jnp.ones((n_elem,), jdtype)
        for _ in range(warmups):
            step(x).block_until_ready()
        t0 = time.perf_counter()
        for _ in range(trials):
            step(x).block_until_ready()
        dt = (time.perf_counter() - t0) / trials
        base_op = {"quantized_psum": "all_reduce",
                   "quantized_all_gather": "all_gather",
                   "quantized_all_to_all": "all_to_all"}.get(op, op)
        algbw, busbw = calc_bw(base_op, n_elem * jdtype.itemsize, dt, world)
        results.append({"op": op, "bytes": n_elem * jdtype.itemsize,
                        "latency_us": dt * 1e6,
                        "algbw_gbps": algbw * 8 / 1e9,
                        "busbw_gbps": busbw * 8 / 1e9})
        size *= 4
    return results


def main(args=None):
    args = parse_args(args)
    rows = run_sweep(args.op, args.axis, args.minsize, args.maxsize,
                     args.trials, args.warmups, args.dtype)
    print(f"{'bytes':>14} {'latency(us)':>14} {'algbw(Gbps)':>12} {'busbw(Gbps)':>12}")
    for r in rows:
        print(f"{r['bytes']:>14} {r['latency_us']:>14.1f} "
              f"{r['algbw_gbps']:>12.2f} {r['busbw_gbps']:>12.2f}")
    return 0


if __name__ == "__main__":
    sys.exit(main())
