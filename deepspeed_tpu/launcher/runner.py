"""``dstpu`` CLI — multi-node dispatch (reference: ``deepspeed/launcher/runner.py:419``).

Flow (mirrors the reference):
hostfile → parse/filter resources (--include/--exclude/--num_nodes) →
base64 world-info → pick a MultiNodeRunner (pdsh/ssh/gcloud/slurm/mpi) →
exec the fan-out command, which runs ``launcher.launch`` on each node.

Single-node (no hostfile, no --tpu_name) short-circuits straight into
``launcher.launch`` locally, like the reference does for world_size==1.
"""

import argparse
import base64
import json
import os
import shutil
import subprocess
import sys
from typing import Dict, List, Optional

from deepspeed_tpu.launcher import multinode_runner as mnr
from deepspeed_tpu.launcher.constants import (DEFAULT_COORDINATOR_PORT,
                                              GCLOUD_LAUNCHER, MPICH_LAUNCHER,
                                              OPENMPI_LAUNCHER, PDSH_LAUNCHER,
                                              SLURM_LAUNCHER, SSH_LAUNCHER,
                                              XPK_LAUNCHER)
from deepspeed_tpu.utils.logging import logger

DLTS_HOSTFILE = "/job/hostfile"


def parse_args(args=None):
    parser = argparse.ArgumentParser(
        description="dstpu distributed launcher",
        formatter_class=argparse.ArgumentDefaultsHelpFormatter)
    parser.add_argument("-H", "--hostfile", type=str, default=DLTS_HOSTFILE,
                        help="hostfile: lines of '<hostname> slots=<n>'")
    parser.add_argument("-i", "--include", type=str, default="",
                        help="nodes/workers to include, e.g. 'host1,host2@0,1'")
    parser.add_argument("-e", "--exclude", type=str, default="",
                        help="nodes/workers to exclude")
    parser.add_argument("--num_nodes", type=int, default=-1)
    parser.add_argument("--num_workers", type=int, default=-1,
                        help="processes per node (-1 = all slots)")
    parser.add_argument("--coordinator_addr", type=str, default=None,
                        help="JAX coordinator address (default: first node)")
    parser.add_argument("--coordinator_port", type=int,
                        default=DEFAULT_COORDINATOR_PORT)
    parser.add_argument("--launcher", type=str, default=PDSH_LAUNCHER,
                        choices=[PDSH_LAUNCHER, SSH_LAUNCHER, GCLOUD_LAUNCHER,
                                 SLURM_LAUNCHER, OPENMPI_LAUNCHER,
                                 MPICH_LAUNCHER, XPK_LAUNCHER])
    parser.add_argument("--xpk_cluster", type=str, default=None,
                        help="GKE cluster name: selects the xpk launcher "
                             "(xpk workload create multislice dispatch)")
    parser.add_argument("--xpk_workload", type=str, default="dstpu-job")
    parser.add_argument("--xpk_docker_image", type=str, default=None)
    parser.add_argument("--tpu_type", type=str, default=None,
                        help="xpk: accelerator type, e.g. v5litepod-256")
    parser.add_argument("--num_slices", type=int, default=1,
                        help="xpk: multislice slice count")
    parser.add_argument("--tpu_name", type=str, default=None,
                        help="TPU-VM pod name (switches to the gcloud runner)")
    parser.add_argument("--tpu_zone", type=str, default=None)
    parser.add_argument("--nproc_per_node", type=int, default=None,
                        help="override local processes per node (CPU simulation)")
    parser.add_argument("--force_multi", action="store_true")
    parser.add_argument("user_script", type=str)
    parser.add_argument("user_args", nargs=argparse.REMAINDER)
    return parser.parse_args(args=args)


def fetch_hostfile(hostfile_path: str) -> Dict[str, int]:
    """Parse '<hostname> slots=<n>' lines (reference runner.py:213 fetch_hostfile)."""
    if not os.path.isfile(hostfile_path):
        return {}
    resource_pool: Dict[str, int] = {}
    with open(hostfile_path) as fd:
        for line in fd:
            line = line.strip()
            if not line or line.startswith("#"):
                continue
            try:
                hostname, slots = line.split()
                _, slot_count = slots.split("=")
                slot_count = int(slot_count)
            except ValueError:
                raise ValueError(f"Hostfile is not formatted correctly: {line!r}")
            if hostname in resource_pool:
                raise ValueError(f"Hostfile contains duplicate hosts: {hostname}")
            resource_pool[hostname] = slot_count
    return resource_pool


def _parse_inclusion_exclusion(resource_pool: Dict[str, int], inclusion: str,
                               exclusion: str) -> Dict[str, List[int]]:
    active: Dict[str, List[int]] = {
        h: list(range(n)) for h, n in resource_pool.items()}
    return parse_resource_filter(active, include_str=inclusion,
                                 exclude_str=exclusion)


def parse_resource_filter(host_info: Dict[str, List[int]], include_str: str = "",
                          exclude_str: str = "") -> Dict[str, List[int]]:
    """Apply --include/--exclude filters of the form
    'host1@0,2;host2' (reference runner.py:293 parse_resource_filter)."""
    if include_str and exclude_str:
        raise ValueError("include_str and exclude_str are mutually exclusive")
    if not include_str and not exclude_str:
        return host_info

    filtered: Dict[str, List[int]] = {}
    spec = include_str or exclude_str
    parsed: Dict[str, Optional[List[int]]] = {}
    for term in spec.split(";"):
        term = term.strip()
        if not term:
            continue
        if "@" in term:
            host, slots = term.split("@")
            parsed[host.strip()] = [int(s) for s in slots.split(",")]
        else:
            parsed[term] = None

    for host, slots in parsed.items():
        if host not in host_info:
            raise ValueError(f"Hostname '{host}' not found in hostfile")
        for s in slots or []:
            if s not in host_info[host]:
                raise ValueError(f"No slot '{s}' specified on host '{host}'")

    if include_str:
        for host, slots in parsed.items():
            filtered[host] = slots if slots is not None else host_info[host]
    else:
        for host, avail in host_info.items():
            if host not in parsed:
                filtered[host] = avail
            elif parsed[host] is not None:
                keep = [s for s in avail if s not in parsed[host]]
                if keep:
                    filtered[host] = keep
    return filtered


def encode_world_info(world_info: Dict[str, List[int]]) -> str:
    return base64.urlsafe_b64encode(
        json.dumps(world_info).encode()).decode()


def main(args=None):
    args = parse_args(args)

    if args.tpu_name:
        args.launcher = GCLOUD_LAUNCHER
    if args.xpk_cluster:
        args.launcher = XPK_LAUNCHER
        if not args.tpu_type:
            raise ValueError("--xpk_cluster requires --tpu_type "
                             "(e.g. v5litepod-256)")

    resource_pool = fetch_hostfile(args.hostfile)
    if not resource_pool and args.launcher not in (GCLOUD_LAUNCHER,
                                                   XPK_LAUNCHER):
        # Single-node: run launch.py locally, one process (JAX owns local chips).
        world_info = {"localhost": [0]}
        cmd = [sys.executable, "-u", "-m", "deepspeed_tpu.launcher.launch",
               f"--world_info={encode_world_info(world_info)}",
               "--node_rank=0",
               f"--coordinator_addr=127.0.0.1",
               f"--coordinator_port={args.coordinator_port}"]
        if args.nproc_per_node is not None:
            cmd.append(f"--nproc_per_node={args.nproc_per_node}")
        cmd += [args.user_script] + args.user_args
        logger.info(f"single-node launch: {' '.join(cmd)}")
        result = subprocess.Popen(cmd)
        result.wait()
        sys.exit(result.returncode)

    active_resources = _parse_inclusion_exclusion(
        resource_pool, args.include, args.exclude)
    if args.num_nodes > 0:
        active_resources = dict(list(active_resources.items())[:args.num_nodes])
    if args.num_workers > 0:
        active_resources = {h: w[:args.num_workers]
                            for h, w in active_resources.items()}

    if args.coordinator_addr is None and active_resources:
        args.coordinator_addr = list(active_resources.keys())[0]

    world_info_b64 = encode_world_info(active_resources)

    runner_cls = {
        PDSH_LAUNCHER: mnr.PDSHRunner,
        SSH_LAUNCHER: mnr.SSHRunner,
        GCLOUD_LAUNCHER: mnr.GcloudTPURunner,
        SLURM_LAUNCHER: mnr.SlurmRunner,
        OPENMPI_LAUNCHER: mnr.MPIRunner,
        MPICH_LAUNCHER: mnr.MPIRunner,
        XPK_LAUNCHER: mnr.XpkRunner,
    }[args.launcher]
    runner = runner_cls(args, world_info_b64)
    if not runner.backend_exists():
        raise RuntimeError(f"launcher backend '{args.launcher}' not available "
                           f"(binary missing on PATH)")

    env = dict(os.environ)
    if isinstance(runner, mnr.SSHRunner):
        procs = []
        for rank, host in enumerate(active_resources):
            procs.append(subprocess.Popen(
                runner.get_node_cmd(host, rank, env)))
        rc = 0
        for p in procs:
            p.wait()
            rc = rc or p.returncode
        sys.exit(rc)

    cmd = runner.get_cmd(env, active_resources)
    logger.info(f"cmd = {' '.join(cmd)}")
    result = subprocess.Popen(cmd, env=env)
    result.wait()
    sys.exit(result.returncode)


if __name__ == "__main__":
    main()
