"""Top-k gating + expert-parallel MoE layer.

Reference analog: ``deepspeed/moe/sharded_moe.py`` — ``TopKGate`` (:449) with
top1/top2/topk gating (:183,:290,:374), capacity, load-balancing aux loss; and
``MOELayer`` (:533): einsum dispatch -> all-to-all -> local experts -> all-to-all ->
combine. Expert groups come from ``utils/groups.py:117``.

TPU-native: GShard-style dense dispatch/combine einsums with the experts dimension
sharded over the ``expert`` mesh axis — XLA emits exactly the all-to-all pair the
reference performs by hand, fused with the dispatch einsums. Static capacity keeps
every shape compile-time constant (no ragged dispatch under jit).
"""

import dataclasses
from typing import Any, Optional, Tuple

import flax.linen as nn
import jax
import jax.numpy as jnp
import numpy as np
from jax.sharding import PartitionSpec

from deepspeed_tpu.models.llama import shard_activation


@dataclasses.dataclass(frozen=True)
class MoEConfig:
    num_experts: int = 8
    top_k: int = 2
    capacity_factor: float = 1.25
    eval_capacity_factor: float = 2.0
    min_capacity: int = 4
    noisy_gate_policy: Optional[str] = None     # None | "RSample" | "Jitter"
    drop_tokens: bool = True
    use_rts: bool = True                        # random token selection tie-break
    aux_loss_weight: float = 0.01
    router_z_loss_weight: float = 0.001
    # renormalize the kept top-k gate probs to sum to 1 (GShard/Mixtral
    # behavior). HF Qwen2-MoE defaults this OFF (norm_topk_prob=False in
    # Qwen1.5-MoE configs) — raw softmax probs weight the combine directly.
    norm_topk_prob: bool = True
    # int8 wire format for the dispatch/combine collectives (EQuARX-style;
    # cf. reference _AllToAll dispatch, sharded_moe.py:533 + ZeRO++ wire
    # quantization): the token->expert reduction and the expert->token
    # combine run in manual shard_map regions over the batch / expert axes
    # with quantized_psum — 4x less ICI/DCN traffic than fp32 dispatch, 2x
    # vs the default bf16 (plus fp32 per-row scales); straight-through grads
    quantized_dispatch: bool = False
    dtype: Any = jnp.bfloat16


def _capacity(num_tokens: int, num_experts: int, capacity_factor: float,
              min_capacity: int) -> int:
    cap = int(np.ceil(num_tokens / num_experts * capacity_factor))
    return max(cap, min_capacity)


def top_k_gating(logits, cfg: MoEConfig, capacity: int, rng=None,
                 train: bool = True):
    """Returns (dispatch [T,E,C] bool, combine [T,E,C] float, aux_loss, z_loss).

    reference: top2gating sharded_moe.py:290 — softmax over experts, top-k choice,
    position-in-expert via cumsum, tokens beyond capacity dropped; aux loss =
    E * mean(gate_frac) . mean(token_frac) (switch/gshard load-balancing loss).
    """
    t, e = logits.shape
    if train and cfg.noisy_gate_policy == "RSample" and rng is not None:
        logits = logits + jax.random.normal(rng, logits.shape) / e
    probs = jax.nn.softmax(logits.astype(jnp.float32), axis=-1)

    topk_probs, topk_idx = jax.lax.top_k(probs, cfg.top_k)        # [T, K]

    # aux losses computed on the full softmax (reference: l_aux on gates1)
    top1_onehot = jax.nn.one_hot(topk_idx[:, 0], e, dtype=jnp.float32)
    me = jnp.mean(probs, axis=0)
    ce = jnp.mean(top1_onehot, axis=0)
    aux_loss = jnp.sum(me * ce) * e * cfg.aux_loss_weight
    z_loss = jnp.mean(jax.scipy.special.logsumexp(
        logits.astype(jnp.float32), axis=-1) ** 2) * cfg.router_z_loss_weight

    # position of each (token, k) within its expert: cumsum over flattened choices
    # in k-major order so k=0 choices win capacity slots first (reference: gates1
    # positions computed before masking gates2 locations)
    onehot = jax.nn.one_hot(topk_idx, e, dtype=jnp.int32)          # [T, K, E]
    flat = onehot.transpose(1, 0, 2).reshape(cfg.top_k * t, e)     # k-major
    pos_flat = jnp.cumsum(flat, axis=0) - flat                     # [K*T, E]
    pos = pos_flat.reshape(cfg.top_k, t, e).transpose(1, 0, 2)     # [T, K, E]
    pos_in_expert = jnp.sum(pos * onehot, axis=-1)                 # [T, K]
    keep = pos_in_expert < capacity                                # drop overflow

    # normalize kept top-k probs (reference: denom_s = gates1_s + gates2_s);
    # skipped when norm_topk_prob is off (HF Qwen2-MoE semantics)
    kept_probs = topk_probs * keep
    if cfg.norm_topk_prob:
        denom = jnp.maximum(jnp.sum(kept_probs, axis=-1, keepdims=True), 1e-9)
        norm_probs = kept_probs / denom
    else:
        norm_probs = kept_probs

    cap_onehot = jax.nn.one_hot(jnp.where(keep, pos_in_expert, capacity),
                                capacity, dtype=jnp.float32)       # [T, K, C]
    expert_onehot = onehot.astype(jnp.float32)                     # [T, K, E]
    combine = jnp.einsum("tk,tke,tkc->tec", norm_probs, expert_onehot, cap_onehot)
    dispatch = combine > 0
    return dispatch, combine, aux_loss, z_loss


class TopKGate(nn.Module):
    """Router (reference: TopKGate sharded_moe.py:449). fp32 gate weights."""
    cfg: MoEConfig

    @nn.compact
    def __call__(self, x, train: bool = True):
        t = x.shape[0]
        cf = self.cfg.capacity_factor if train else self.cfg.eval_capacity_factor
        capacity = _capacity(t * self.cfg.top_k, self.cfg.num_experts, cf,
                             self.cfg.min_capacity)
        logits = nn.Dense(self.cfg.num_experts, use_bias=False, dtype=jnp.float32,
                          param_dtype=jnp.float32, name="wg")(x.astype(jnp.float32))
        rng = self.make_rng("gating") if (train and self.cfg.noisy_gate_policy) else None
        return top_k_gating(logits, self.cfg, capacity, rng=rng, train=train)


class Experts(nn.Module):
    """E parallel SwiGLU expert MLPs, parameters stacked on a leading experts dim
    (reference: moe/experts.py — a ModuleList; here one vmapped dense stack so the
    expert dim shards over the ``expert`` mesh axis)."""
    num_experts: int
    hidden_size: int
    intermediate_size: int
    dtype: Any = jnp.bfloat16

    @nn.compact
    def __call__(self, x):  # x: [E, C, D]
        e, c, d = x.shape
        init = nn.initializers.lecun_normal()
        w_gate = self.param("w_gate", init, (self.num_experts, d, self.intermediate_size),
                            jnp.float32)
        w_up = self.param("w_up", init, (self.num_experts, d, self.intermediate_size),
                          jnp.float32)
        w_down = self.param("w_down", init,
                            (self.num_experts, self.intermediate_size, d), jnp.float32)
        x = x.astype(self.dtype)
        g = jnp.einsum("ecd,edf->ecf", x, w_gate.astype(self.dtype))
        u = jnp.einsum("ecd,edf->ecf", x, w_up.astype(self.dtype))
        h = nn.silu(g) * u
        return jnp.einsum("ecf,efd->ecd", h, w_down.astype(self.dtype))


def _quantized_wire_axes(mesh):
    """Axes for the int8 MoE collectives, filtered to what is still automatic
    in the surrounding context (the qgZ gradient phase may already hold the
    data axis manual): (token-reduction axes, expert axis active)."""
    from deepspeed_tpu.comm import mesh as mesh_lib
    manual = set()
    try:
        manual = set(jax.sharding.get_abstract_mesh().manual_axes)
    except AttributeError:
        pass
    tok = tuple(a for a in mesh_lib.batch_axes(mesh)
                if mesh.shape.get(a, 1) > 1 and a not in manual)
    ep = mesh.shape.get("expert", 1) > 1 and "expert" not in manual
    return tok, ep


def _region_mesh(mesh):
    """Mesh to hand a nested shard_map: inside a partial-manual region
    (e.g. the qgZ gradient phase) jax requires the *context* abstract mesh
    (whose outer axes are already Manual), not the concrete one."""
    try:
        am = jax.sharding.get_abstract_mesh()
        if getattr(am, "manual_axes", ()):
            return am
    except AttributeError:
        pass
    return mesh


def _quantized_dispatch_sum(mesh, tok_axes, dispatch, tokens):
    """Token->expert dispatch with int8 on the wire. The SPMD dispatch
    einsum contracts over the token dim, whose shards live on the batch
    axes — the cross-device sum of the per-shard [E,C,D] partials is the
    dispatch collective (reference: _AllToAll before experts,
    sharded_moe.py:533). Here each shard computes its partial locally in a
    manual region and the partials reduce via ``quantized_psum``."""
    from deepspeed_tpu.ops.pallas.quant import quantized_psum

    def body(dm, tk):
        part = jnp.einsum("tec,td->ecd", dm, tk)
        e, c, dd = part.shape
        flat = quantized_psum(part.reshape(e * c, dd), tok_axes)
        return flat.reshape(e, c, dd)

    return jax.shard_map(
        body, mesh=_region_mesh(mesh),
        in_specs=(PartitionSpec(tok_axes), PartitionSpec(tok_axes)),
        out_specs=PartitionSpec(),
        axis_names=frozenset(tok_axes), check_vma=False)(dispatch, tokens)


def _quantized_combine_sum(mesh, combine, expert_out):
    """Expert->token combine with int8 on the wire: each expert shard
    computes its partial [T,D] from its local experts, partials reduce over
    the expert axis via ``quantized_psum`` (the reverse _AllToAll)."""
    from deepspeed_tpu.ops.pallas.quant import quantized_psum

    def body(cm, eo):
        part = jnp.einsum("tec,ecd->td", cm, eo)
        return quantized_psum(part, ("expert",))

    return jax.shard_map(
        body, mesh=_region_mesh(mesh),
        in_specs=(PartitionSpec(None, "expert"), PartitionSpec("expert")),
        out_specs=PartitionSpec(),
        axis_names=frozenset({"expert"}), check_vma=False)(combine, expert_out)


class MOELayer(nn.Module):
    """Dispatch -> experts -> combine (reference: MOELayer sharded_moe.py:533)."""
    cfg: MoEConfig
    hidden_size: int
    intermediate_size: int

    @nn.compact
    def __call__(self, x, train: bool = True):
        """x: [B, S, D] -> ([B, S, D], aux_loss)."""
        b, s, d = x.shape
        tokens = x.reshape(b * s, d)
        dispatch, combine, aux_loss, z_loss = TopKGate(self.cfg, name="gate")(
            tokens, train=train)
        tok_axes, ep_on = (), False
        if self.cfg.quantized_dispatch:
            from deepspeed_tpu.comm import mesh as mesh_lib
            mesh = mesh_lib.get_global_mesh()
            if mesh is not None:
                tok_axes, ep_on = _quantized_wire_axes(mesh)
        # [T,E,C] x [T,D] -> [E,C,D]; experts dim rides the expert mesh axis:
        # XLA inserts the token collective here (reference: _AllToAll before
        # experts) — int8-wire via the manual region when configured
        if tok_axes:
            dispatched = _quantized_dispatch_sum(
                mesh, tok_axes, dispatch.astype(x.dtype), tokens)
        else:
            dispatched = jnp.einsum("tec,td->ecd", dispatch.astype(x.dtype),
                                    tokens)
        dispatched = shard_activation(dispatched, ("expert", None, None))
        expert_out = Experts(self.cfg.num_experts, self.hidden_size,
                             self.intermediate_size, self.cfg.dtype,
                             name="experts")(dispatched)
        expert_out = shard_activation(expert_out, ("expert", None, None))
        if ep_on:
            out = _quantized_combine_sum(mesh, combine.astype(x.dtype),
                                         expert_out)
        else:
            out = jnp.einsum("tec,ecd->td", combine.astype(x.dtype), expert_out)
        return out.reshape(b, s, d), aux_loss + z_loss


def moe_tensor_rules(path, leaf) -> Optional[PartitionSpec]:
    """Expert-parallel sharding: stacked expert weights shard their leading
    experts dim over the ``expert`` mesh axis (reference: expert params live in
    expert-parallel groups, utils/groups.py:117)."""
    name = "/".join(str(getattr(k, "key", getattr(k, "idx", k))) for k in path)
    ndim = np.ndim(leaf)
    if "experts/" in name and ndim == 3:
        return PartitionSpec("expert", None, None)
    return None
