"""Compile-event ledger — every XLA compile becomes a trace instant.

PRs 10 and 13 each re-learned the same lesson by hand: a mid-measurement
XLA compile stalls the serve tick (or the timed bench window) for seconds
and silently poisons every counter and latency number downstream — the
fix was always "warm the exact shapes first", re-discovered per drill.
This module mechanizes the discipline:

- ``watch_jit(fn, name)`` wraps a jitted callable. Every dispatch probes
  the jit cache size before/after (one C-level int read — never a host
  sync; the wrapper is a registered DS002 hot path): when the cache grew,
  THIS call traced+compiled, and an ``xla/compile`` instant is emitted
  carrying the fn qualname, the abstract shape signature of the call, and
  the wall ms the dispatch took (trace+lower+compile all block dispatch,
  so the first-call wall time IS the compile cost).
- ``compiles_total()`` is the process-wide counter benches mark before
  their timed window and diff after: ``compiles_during_measurement`` in
  the proof set, asserted ZERO after warmup — the "warm the exact shapes
  first" rule as a machine-checked invariant instead of tribal knowledge.

The signature builder runs ONLY on the compile (slow) path and describes
arguments duck-typed (``.shape``/``.dtype`` attribute reads, never a
materialization), so the ledger itself can never add a transfer.
Stdlib-only at module level — importable from any hot-path file.
"""

import threading
import time
from typing import Any, Callable, Optional

from deepspeed_tpu.telemetry.tracer import get_tracer

COMPILE_INSTANT = "xla/compile"

#: cap on rendered signature length (a 100-layer param tree would bloat
#: every compile instant; the head + leaf count identifies the shape set)
_SIG_MAX_LEAVES = 12

_lock = threading.Lock()
_total = 0


def compiles_total() -> int:
    """XLA compiles observed by watched dispatch sites so far in this
    process. Benches snapshot it before the timed window; the diff is
    ``compiles_during_measurement``."""
    with _lock:
        return _total


def _describe(x: Any) -> Optional[str]:
    """One leaf's abstract signature — attribute reads only, no
    materialization (``f32[8,128]`` idiom)."""
    shape = getattr(x, "shape", None)
    if shape is None:
        if isinstance(x, (int, float, bool)):
            return type(x).__name__
        return None
    dtype = getattr(x, "dtype", None)
    dname = getattr(dtype, "name", str(dtype)) if dtype is not None else "?"
    return f"{dname}[{','.join(str(d) for d in shape)}]"


def _walk(obj: Any, out: list) -> int:
    """Collect up to ``_SIG_MAX_LEAVES`` rendered leaf descriptions into
    ``out`` but COUNT every leaf (cheap attribute reads) — the tail count
    in the signature must be the tree's true size, not the render cap."""
    desc = _describe(obj)
    if desc is not None:
        if len(out) < _SIG_MAX_LEAVES:
            out.append(desc)
        return 1
    n = 0
    if isinstance(obj, dict):
        for k in sorted(obj, key=str):
            n += _walk(obj[k], out)
    elif isinstance(obj, (list, tuple)):
        for v in obj:
            n += _walk(v, out)
    # other leaves (None, configs, rng keys without .shape) add nothing
    return n


def signature_of(args: tuple, kwargs: dict) -> str:
    """Abstract shape signature of one call — the compile cache key's
    human-readable shadow. Computed ONLY on the compile path."""
    leaves: list = []
    total = _walk(args, leaves) + _walk(kwargs, leaves)
    if total > len(leaves):
        return ",".join(leaves) + f",...({total} leaves)"
    return ",".join(leaves)


def record_compile(name: str, signature: str, wall_s: float) -> None:
    """Count + trace one observed compile (the slow path — the compile
    itself just took orders of magnitude longer than this bookkeeping)."""
    global _total
    with _lock:
        _total += 1
    get_tracer().instant(COMPILE_INSTANT, cat="compile", fn=name,
                         signature=signature,
                         wall_ms=round(wall_s * 1e3, 3))


class CompileWatched:
    """Transparent wrapper over a jitted callable: dispatch passes
    straight through; a jit-cache growth marks the call as a compile and
    emits the ``xla/compile`` instant. Attribute access (``.lower``,
    ``.clear_cache``...) delegates to the wrapped function."""
    __slots__ = ("_fn", "_name", "_probe")

    def __init__(self, fn: Callable, name: str):
        self._fn = fn
        self._name = name
        # jax.jit functions expose the compiled-signature cache size; a
        # callable without it (plain python fn, exotic jax version) is
        # passed through unwatched rather than broken
        self._probe = getattr(fn, "_cache_size", None)

    def __call__(self, *args, **kwargs):
        probe = self._probe
        if probe is None:
            return self._fn(*args, **kwargs)
        before = probe()
        t0 = time.monotonic()
        out = self._fn(*args, **kwargs)
        if probe() > before:
            record_compile(self._name, signature_of(args, kwargs),
                           time.monotonic() - t0)
        return out

    def __getattr__(self, item):
        return getattr(self._fn, item)


def watch_jit(fn: Callable, name: str) -> CompileWatched:
    """Wrap a jitted callable so its compiles land in the ledger. The
    contract every engine/serving jit dispatch site follows: the wrapper
    is shape-transparent (same args, same return, donation semantics
    untouched) and adds one int probe per dispatch."""
    return CompileWatched(fn, name)
