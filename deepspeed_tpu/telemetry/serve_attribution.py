"""``dstpu plan --serve`` — serving-tick attribution and siege-knob planning.

The serving analog of ``attribution.py`` (the DeepCompile loop of PR 7,
arxiv 2504.09983, applied to the serve tick): replay a bench_serve /
``DSTPU_TRACE`` dump and explain where every serving tick went, then turn
the dominant pressure signal into ONE executable serving-config override
with a machine-checkable counter prediction the bench can re-run and judge
(the ZeRO-Offload-style host-tier economics of arxiv 2101.06840, tuned per
traffic mix instead of per engineer):

1. **Tick attribution** — every ``serve/tick`` window (the retro-span the
   serve loop emits around each working tick; older dumps fall back to the
   raw ``serve/engine_step`` spans) is decomposed into *exclusive* stages
   on the serve-loop track — admission, prefill, decode, demote, promote,
   drain, residual — by the same priority interval sweep as the training
   planner, so the per-tick ledger provably sums to the window
   (``residual`` is the exact remainder; over-attribution surfaces as
   ``tie_out_error``, bounded by the clock-skew tolerance).
2. **Joins** — the per-request retro-spans (``serve/queued`` /
   ``serve/prefill`` / ``serve/decode``) roll up to p50/p99 TTFT/TPOT per
   degradation-ladder level; the ``serve/*`` + ``mem/*`` counter tracks
   (KV bytes, prefix cache, tier state) report last/max/p95/p99 per
   series; the instant families (``serve/ladder``, ``serve/kv_demote``,
   ``serve/kv_recalibrate``, ``serve/prefix_evict``, backpressure kinds)
   are counted so a whole siege episode reads from one report.
3. **Regression ledger** — ``serve_plan_baseline.json`` (dslint/plan
   ratchet idiom): per-stage per-tick quantiles, workload-scoped by trace
   basename; regression -> exit 1, improvements surface as stale entries
   expired only via ``--write-baseline``.
4. **Proposals** — a rule table maps the dominant pressure signal to ONE
   serving-config override (raise ``kv_demote_watermark`` when demote
   churn starves decode; raise ``host_kv_budget_bytes`` when sheds happen
   with idle host budget; raise ``prefix_cache_max_blocks`` when the hit
   ratio is low under eviction pressure; widen ``ladder_hysteresis`` when
   brownout flaps) carrying a deterministic counter prediction
   (``{counter, op, value}``) that ``autotuning.serve_verify`` re-executes
   against the same seeded bench_serve preset and judges EXACTLY,
   persisting verdicts under ``plan.serve_verifications`` in
   ``autotuning_results.json``.

Offline-only, by contract: stdlib-only at module level and file-loadable
standalone (``bin/dstpu plan --serve`` works on jax-less hosts), listed in
``tools/dslint/hotpath.py`` ``OFFLINE_ONLY_MODULES`` — no registered hot
path may import it, and it never imports jax.
"""

import argparse
import json
import os
import sys
from typing import Any, Dict, List, Optional, Tuple


def _load_trace_names():
    """File-load ``telemetry/names.py`` from the sibling path — never a
    package import: this module loads standalone on jax-less hosts. The
    stage table's NAMES live in the registry, so renaming a serve emitter
    is a DS007 finding instead of silently reattributing to residual."""
    import importlib.util
    mod = sys.modules.get("dstpu_trace_names")
    if mod is None:
        path = os.path.join(os.path.dirname(os.path.abspath(__file__)),
                            "names.py")
        spec = importlib.util.spec_from_file_location(
            "dstpu_trace_names", path)
        mod = importlib.util.module_from_spec(spec)
        spec.loader.exec_module(mod)
        sys.modules["dstpu_trace_names"] = mod
    return mod


_NAMES = _load_trace_names()

EXIT_OK = 0
EXIT_REGRESSION = 1
EXIT_UNREADABLE = 2

SERVE_PLAN_VERSION = 1
SERVE_PLAN_BASELINE_VERSION = 1
SERVE_PLAN_BASELINE_NAME = "serve_plan_baseline.json"
SERVE_PLAN_ARTIFACT_ENV = "DSTPU_SERVE_PLAN_ARTIFACT"
DEFAULT_SERVE_PLAN_ARTIFACT = "serve_plan.json"

#: stage keys, in ledger/report order. ``residual`` is always last: the
#: remainder of the tick the sweep could not attribute (ladder/reconcile/
#: gauge bookkeeping, engine host work outside the prefill/decode kernels).
STAGES = ("admission", "prefill", "decode", "demote", "promote", "drain",
          "residual")

#: exclusive-sweep priority — at any instant the HIGHEST-priority covering
#: span owns the time. The page movers (demote/promote) outrank the step
#: phases, the step phases outrank request settling, and admission is the
#: outermost attributable catch-all. ``serve/engine_step`` is NOT a stage:
#: its prefill/decode interior attributes, the rest is residual.
_PRIORITY = {"demote": 6, "promote": 5, "prefill": 4, "decode": 3,
             "drain": 2, "admission": 1}

#: per-window tie-out tolerance, same contract as attribution.py: stage
#: sums may exceed the tick window by at most this fraction (sub-ms clock
#: skew between the retro tick window and the stage spans inside it).
TIE_OUT_TOLERANCE = 0.05

_TICK_NAME = _NAMES.SERVE_TICK_NAME

#: span name -> exclusive stage key: the names come from the
#: registry (one declaration, DS007-enforced); the sweep
#: priorities stay here next to the sweep
_STAGE_OF = dict(_NAMES.SERVE_STAGE_OF)

#: ServingConfig defaults the proposal rules fall back to when the input
#: is a bare trace with no bench_serve provenance (a literal, NOT an
#: import: this module loads standalone by contract; tests pin the copies
#: against serving.server.ServingConfig)
SERVING_DEFAULTS = {
    "max_queue_depth": 64,
    "kv_high_watermark": 0.95,
    "kv_offload_enabled": False,
    "host_kv_budget_bytes": 256 << 20,
    "kv_demote_watermark": 0.90,
    "kv_demote_watermark_brownout": 0.60,
    "prefix_cache_enabled": False,
    "prefix_cache_max_blocks": 0,
    "brownout_pressure": 0.85,
    "shed_pressure": 0.97,
    "ladder_hysteresis": 0.10,
    "ladder_cooldown_ticks": 20,
    "scheduler": {"prefill_chunk_tokens": 0, "role_split": False,
                  "handoff_quantize": "none"},
}


class PlanError(Exception):
    """Unreadable/empty input — maps to CLI exit code 2."""


# ---------------------------------------------------------------------------
# event loading / normalization (standalone copies — see module docstring)
# ---------------------------------------------------------------------------
class Ev:
    """One normalized trace event (Chrome-trace microsecond clock)."""
    __slots__ = ("name", "cat", "ph", "ts", "dur", "tid", "args")

    def __init__(self, name, cat, ph, ts, dur, tid, args):
        self.name = name
        self.cat = cat
        self.ph = ph
        self.ts = float(ts)
        self.dur = float(dur)
        self.tid = tid
        self.args = args or {}

    @property
    def end(self) -> float:
        return self.ts + self.dur


def events_from_chrome(obj: Any) -> List[Ev]:
    """Normalize a Chrome-trace object (dict with ``traceEvents`` or a bare
    event list) into ``Ev`` records; metadata ("M") events are dropped."""
    if isinstance(obj, dict):
        raw = obj.get("traceEvents")
        if raw is None:
            raise PlanError("not a Chrome trace: no 'traceEvents' key")
    elif isinstance(obj, list):
        raw = obj
    else:
        raise PlanError(f"not a Chrome trace: top-level {type(obj).__name__}")
    out = []
    for e in raw:
        if not isinstance(e, dict) or e.get("ph") == "M":
            continue
        try:
            out.append(Ev(e.get("name", "?"), e.get("cat", ""), e.get("ph"),
                          float(e.get("ts", 0.0)), float(e.get("dur", 0.0)),
                          e.get("tid"), e.get("args")))
        except (TypeError, ValueError):
            continue   # malformed row: skip, never die mid-replay
    return out


def quantile(sorted_vals: List[float], q: float) -> float:
    """Exact sample quantile, the repo-wide rule (``tracer._quantile`` /
    ``attribution.quantile``): value at index ``min(int(q*n), n-1)``.
    Deliberately a local copy, NOT an import — standalone-load contract;
    tests/test_serve_plan.py pins the copies equal."""
    if not sorted_vals:
        return 0.0
    return sorted_vals[min(int(q * len(sorted_vals)), len(sorted_vals) - 1)]


def load_input(path: str) -> Tuple[List[Ev], Dict[str, Any]]:
    """Load a serve-plan input: either a raw dstrace Chrome dump, or a
    bench_serve report JSON whose ``provenance.trace_path`` locates the
    dump (relative paths resolve against the report's directory). Returns
    ``(events, meta)`` where meta carries trace_path / provenance /
    bench_counters / prefix for the joins and the proposal rules."""
    try:
        with open(path) as f:
            obj = json.load(f)
    except (OSError, ValueError) as e:
        raise PlanError(f"cannot read {path}: {e}") from e
    meta: Dict[str, Any] = {"input": path, "trace_path": path,
                            "provenance": None, "bench_counters": None,
                            "prefix": None}
    if isinstance(obj, dict) and "traceEvents" in obj:
        return events_from_chrome(obj), meta
    if isinstance(obj, dict) and ("provenance" in obj or "counters" in obj):
        prov = obj.get("provenance") or {}
        trace_path = prov.get("trace_path")
        if not trace_path:
            raise PlanError(
                f"bench_serve report {path} has no provenance.trace_path — "
                "re-run bench_serve with --trace (or DSTPU_TRACE) so the "
                "plan can locate the dump")
        if not os.path.isabs(trace_path):
            trace_path = os.path.join(os.path.dirname(os.path.abspath(path)),
                                      trace_path)
        if not os.path.exists(trace_path):
            raise PlanError(f"trace {trace_path} (from {path} provenance) "
                            "does not exist")
        try:
            with open(trace_path) as f:
                trace_obj = json.load(f)
        except (OSError, ValueError) as e:
            raise PlanError(f"cannot read trace {trace_path}: {e}") from e
        meta.update(trace_path=trace_path, provenance=prov,
                    bench_counters=obj.get("counters"),
                    prefix=obj.get("prefix"))
        return events_from_chrome(trace_obj), meta
    raise PlanError(f"{path} is neither a Chrome trace nor a bench_serve "
                    "report (no traceEvents / provenance)")


# ---------------------------------------------------------------------------
# tick windows + exclusive sweep
# ---------------------------------------------------------------------------
def tick_windows(events: List[Ev]) -> Tuple[List[Dict[str, Any]], str]:
    """The tick windows to attribute. ``serve/tick`` retro-spans (one per
    working serve tick) are the primary anchor; dumps from before the tick
    span existed fall back to the raw ``serve/engine_step`` spans (the
    ledger then misses admission/drain work outside the step — noted via
    the returned mode)."""
    ticks = sorted((e for e in events
                    if e.ph == "X" and e.name == _TICK_NAME),
                   key=lambda e: e.ts)
    if ticks:
        return [{"start_us": e.ts, "end_us": e.end,
                 "tick": e.args.get("tick")} for e in ticks], "tick"
    steps = sorted((e for e in events
                    if e.ph == "X" and e.name == "serve/engine_step"),
                   key=lambda e: e.ts)
    if not steps:
        raise PlanError("no serving tick spans in trace (serve/tick and "
                        "serve/engine_step both absent) — was the server "
                        "run traced with DSTPU_TRACE?")
    return [{"start_us": e.ts, "end_us": e.end, "tick": None}
            for e in steps], "engine_step"


def main_track(events: List[Ev]) -> Optional[Any]:
    """The tid that emits the tick spans — the serve loop's track."""
    counts: Dict[Any, int] = {}
    for e in events:
        if e.ph == "X" and e.name in (_TICK_NAME, "serve/engine_step"):
            counts[e.tid] = counts.get(e.tid, 0) + 1
    if not counts:
        return None
    return max(sorted(counts, key=str), key=counts.get)


def _exclusive_sweep(intervals: List[Tuple[float, float, str]],
                     w0: float, w1: float) -> Dict[str, float]:
    """Exclusive per-stage time over [w0, w1]: at every instant the
    highest-priority covering interval owns it. Intervals are pre-clipped."""
    out = {s: 0.0 for s in STAGES if s != "residual"}
    if not intervals:
        return out
    pts = sorted({w0, w1, *(i[0] for i in intervals),
                  *(i[1] for i in intervals)})
    for a, b in zip(pts, pts[1:]):
        if b <= a:
            continue
        mid = (a + b) / 2.0
        best = None
        for s, e, stage in intervals:
            if s <= mid < e and (best is None
                                 or _PRIORITY[stage] > _PRIORITY[best]):
                best = stage
        if best is not None:
            out[best] += b - a
    return out


def _union(intervals: List[Tuple[float, float]]) -> float:
    total, cur_s, cur_e = 0.0, None, None
    for s, e in sorted(intervals):
        if cur_e is None or s > cur_e:
            if cur_e is not None:
                total += cur_e - cur_s
            cur_s, cur_e = s, e
        else:
            cur_e = max(cur_e, e)
    if cur_e is not None:
        total += cur_e - cur_s
    return total


# ---------------------------------------------------------------------------
# joins: request latency / counter tracks / instant families
# ---------------------------------------------------------------------------
def request_latency(events: List[Ev]) -> Dict[str, Any]:
    """p50/p99 TTFT/TPOT per degradation-ladder level, rebuilt from the
    per-request retro-spans exactly as bench_serve does (TTFT = queued.dur
    + prefill.dur; TPOT = decode.dur / (tokens - 1)); the ``level`` arg is
    the ladder level the request was admitted under."""
    queued: Dict[Any, Tuple[float, str]] = {}
    prefill: Dict[Any, float] = {}
    decode: Dict[Any, Tuple[float, int]] = {}
    for e in events:
        if e.ph != "X" or "uid" not in e.args:
            continue
        uid = e.args["uid"]
        if e.name == "serve/queued":
            queued[uid] = (e.dur, str(e.args.get("level", "unknown")))
        elif e.name == "serve/prefill":
            prefill[uid] = e.dur
        elif e.name == "serve/decode":
            decode[uid] = (e.dur, int(e.args.get("tokens", 0) or 0))
    per_level: Dict[str, Dict[str, List[float]]] = {}
    for uid, dur in prefill.items():
        if uid not in queued:
            continue
        qdur, level = queued[uid]
        bucket = per_level.setdefault(level, {"ttft_us": [], "tpot_us": []})
        bucket["ttft_us"].append(qdur + dur)
        if uid in decode:
            ddur, tokens = decode[uid]
            if tokens > 1:
                bucket["tpot_us"].append(ddur / (tokens - 1))
    out: Dict[str, Any] = {"levels": {}, "requests": len(prefill)}
    all_ttft: List[float] = []
    all_tpot: List[float] = []
    for level in sorted(per_level):
        b = per_level[level]
        row: Dict[str, Any] = {"count": len(b["ttft_us"])}
        for key, vals in (("ttft", b["ttft_us"]), ("tpot", b["tpot_us"])):
            vals.sort()
            row[f"{key}_p50_ms"] = round(quantile(vals, 0.5) / 1e3, 4)
            row[f"{key}_p99_ms"] = round(quantile(vals, 0.99) / 1e3, 4)
        out["levels"][level] = row
        all_ttft.extend(b["ttft_us"])
        all_tpot.extend(b["tpot_us"])
    all_ttft.sort()
    all_tpot.sort()
    out["ttft_p50_ms"] = round(quantile(all_ttft, 0.5) / 1e3, 4)
    out["ttft_p99_ms"] = round(quantile(all_ttft, 0.99) / 1e3, 4)
    out["tpot_p50_ms"] = round(quantile(all_tpot, 0.5) / 1e3, 4)
    out["tpot_p99_ms"] = round(quantile(all_tpot, 0.99) / 1e3, 4)
    return out


def counter_tracks(events: List[Ev]) -> Dict[str, Dict[str, Dict[str, Any]]]:
    """The ``serve/*`` + ``mem/*`` counter tracks rolled up per series:
    last/max/p95/p99/count — the read side of the KV-bytes, prefix-cache,
    tier-state and dsmem HBM tracks (same stats ``Tracer.counter_series``
    now reports live)."""
    series: Dict[str, Dict[str, List[float]]] = {}
    for e in events:
        if e.ph != "C" or not e.args:
            continue
        if not (e.name.startswith("serve/") or e.name.startswith("mem/")):
            continue
        bucket = series.setdefault(e.name, {})
        for key, val in e.args.items():
            try:
                v = float(val)
            except (TypeError, ValueError):
                continue
            bucket.setdefault(key, []).append(v)
    out: Dict[str, Dict[str, Dict[str, Any]]] = {}
    for name in sorted(series):
        out[name] = {}
        for key in sorted(series[name]):
            vals = series[name][key]
            last = vals[-1]
            vals = sorted(vals)
            out[name][key] = {"last": last, "max": vals[-1],
                              "p95": quantile(vals, 0.95),
                              "p99": quantile(vals, 0.99),
                              "count": len(vals)}
    return out


def instant_families(events: List[Ev]) -> Dict[str, Any]:
    """Counts of the serve instant families plus the structured details a
    siege episode reconstructs from: ladder edges keyed ``frm->to``,
    backpressure by kind, demotion/promotion/recalibration/eviction
    volume."""
    counts: Dict[str, int] = {}
    ladder: Dict[str, int] = {}
    backpressure: Dict[str, int] = {}
    demoted_bytes = promoted_bytes = evicted_blocks = 0
    for e in events:
        if e.ph != "i" or not e.name.startswith("serve/"):
            continue
        counts[e.name] = counts.get(e.name, 0) + 1
        if e.name == "serve/ladder":
            key = f"{e.args.get('frm')}->{e.args.get('to')}"
            ladder[key] = ladder.get(key, 0) + 1
        elif e.name == "serve/backpressure":
            kind = str(e.args.get("kind", "?"))
            backpressure[kind] = backpressure.get(kind, 0) + 1
        elif e.name == "serve/kv_demote":
            demoted_bytes += int(e.args.get("bytes", 0) or 0)
        elif e.name == "serve/kv_promote":
            promoted_bytes += int(e.args.get("bytes", 0) or 0)
        elif e.name == "serve/prefix_evict":
            evicted_blocks += int(e.args.get("blocks", 0) or 0)
    return {"counts": dict(sorted(counts.items())),
            "ladder_edges": dict(sorted(ladder.items())),
            "backpressure": dict(sorted(backpressure.items())),
            "demoted_bytes": demoted_bytes,
            "promoted_bytes": promoted_bytes,
            "prefix_evicted_blocks": evicted_blocks}


# ---------------------------------------------------------------------------
# attribution
# ---------------------------------------------------------------------------
def attribute_serve(events: List[Ev], source: str = "<events>",
                    meta: Optional[Dict[str, Any]] = None) -> Dict[str, Any]:
    """Replay a serving trace into the serve-plan report: per-tick
    exclusive stage ledger (ties out to each tick window within
    ``TIE_OUT_TOLERANCE``), aggregate per-tick quantiles, the request/
    counter/instant joins, observed config, and proposals."""
    meta = meta or {}
    windows, window_mode = tick_windows(events)
    track = main_track(events)
    spans = [e for e in events if e.ph == "X"]
    ledger = []
    for i, w in enumerate(windows):
        w0, w1 = w["start_us"], w["end_us"]
        on_track, off_track = [], []
        for e in spans:
            st = _STAGE_OF.get(e.name)
            if st is None or e.end <= w0 or e.ts >= w1:
                continue
            clipped = (max(e.ts, w0), min(e.end, w1))
            if track is None or e.tid == track:
                on_track.append((clipped[0], clipped[1], st))
            else:
                off_track.append((clipped[0], clipped[1], st))
        excl = _exclusive_sweep(on_track, w0, w1)
        dur = w1 - w0
        attributed = sum(excl.values())
        residual = dur - attributed
        overlapped: Dict[str, float] = {}
        for st in set(s for _, _, s in off_track):
            overlapped[st] = _union([(a, b) for a, b, s in off_track
                                     if s == st])
        stages_us = {s: excl.get(s, 0.0) for s in STAGES if s != "residual"}
        stages_us["residual"] = max(residual, 0.0)
        ledger.append({
            "index": i,
            "tick": w["tick"],
            "start_us": round(w0, 3),
            "dur_us": round(dur, 3),
            "stages_us": {k: round(v, 3) for k, v in stages_us.items()},
            "overlapped_us": {k: round(v, 3)
                              for k, v in sorted(overlapped.items())},
            # tie-out proof: attributed time never exceeds the window
            # beyond clock skew; residual is the exact remainder
            "tie_out_error": round(max(attributed - dur, 0.0)
                                   / dur if dur > 0 else 0.0, 6),
        })
    total_us = sum(w["dur_us"] for w in ledger) or 1.0
    aggregate: Dict[str, Dict[str, float]] = {}
    for s in STAGES:
        per_tick_ms = sorted(w["stages_us"][s] / 1e3 for w in ledger)
        total_stage = sum(w["stages_us"][s] for w in ledger)
        aggregate[s] = {
            "total_ms": round(total_stage / 1e3, 3),
            "share": round(total_stage / total_us, 4),
            "mean_tick_ms": round(sum(per_tick_ms) / len(per_tick_ms), 4),
            "p50_tick_ms": round(quantile(per_tick_ms, 0.5), 4),
            "p95_tick_ms": round(quantile(per_tick_ms, 0.95), 4),
            "p99_tick_ms": round(quantile(per_tick_ms, 0.99), 4),
        }
    cfg = dict(SERVING_DEFAULTS)
    prov = meta.get("provenance") or {}
    for key, val in (prov.get("serving_config") or {}).items():
        cfg[key] = val
    report = {
        "version": SERVE_PLAN_VERSION,
        "source": source,
        "trace": meta.get("trace_path", source),
        "window_mode": window_mode,
        "windows": ledger,
        "ticks_total": len(ledger),
        "window_ms_total": round(total_us / 1e3, 3),
        "tick_ms_p50": round(quantile(
            sorted(w["dur_us"] / 1e3 for w in ledger), 0.5), 4),
        "aggregate": aggregate,
        "requests": request_latency(events),
        "counters": counter_tracks(events),
        "instants": instant_families(events),
        "config_observed": cfg,
        "provenance": prov or None,
        "bench_counters": meta.get("bench_counters"),
        "prefix": meta.get("prefix"),
    }
    report["proposals"] = propose_serve(report)
    return report


# ---------------------------------------------------------------------------
# proposals: dominant pressure signal -> ONE serving-config override
# ---------------------------------------------------------------------------
def _signals(report: Dict[str, Any]) -> Dict[str, Any]:
    """The deterministic counter signals the rule table fires on —
    bench_serve's counter proof set when the input was a report, else the
    equivalents rebuilt from the trace's instants/counter tracks."""
    bench = report.get("bench_counters") or {}
    inst = report.get("instants", {})
    tracks = report.get("counters", {})
    cfg = report.get("config_observed", {})
    sheds = bench.get("sheds")
    if sheds is None:
        sheds = inst.get("backpressure", {}).get("shed", 0)
    brownouts = bench.get("brownout_entries")
    if brownouts is None:
        brownouts = sum(n for key, n in inst.get("ladder_edges", {}).items()
                        if key.endswith("->brownout"))
    demoted_bytes = bench.get("demoted_bytes")
    if demoted_bytes is None:
        demoted_bytes = inst.get("demoted_bytes", 0)
    demotions = bench.get("demotions")
    if demotions is None:
        demotions = inst.get("counts", {}).get("serve/kv_demote", 0)
    evictions = bench.get("prefix_evictions")
    if evictions is None:
        evictions = inst.get("prefix_evicted_blocks", 0)
    prefix = report.get("prefix") or {}
    hit_ratio = prefix.get("prefix_hit_ratio")
    host_frac_max = None
    budget = cfg.get("host_kv_budget_bytes") or 0
    host_track = tracks.get("serve/kv_tier", {}).get("host_bytes")
    if host_track is not None and budget > 0:
        host_frac_max = round(host_track["max"] / budget, 4)
    # scheduler proof set (report["scheduler"], mirrored into the bench
    # counters): the worst tick's prefill tokens — the exact quantity the
    # chunk cap bounds by construction
    sched = report.get("scheduler") or {}
    max_prefill = bench.get("max_prefill_tokens_per_tick")
    if max_prefill is None:
        max_prefill = sched.get("max_prefill_tokens_per_tick")
    return {"sheds": int(sheds or 0),
            "brownout_entries": int(brownouts or 0),
            "demotions": int(demotions or 0),
            "demoted_bytes": int(demoted_bytes or 0),
            "prefix_evictions": int(evictions or 0),
            "prefix_hit_ratio": hit_ratio,
            "host_frac_max": host_frac_max,
            "max_prefill_tokens_per_tick": int(max_prefill or 0)}


def propose_serve(report: Dict[str, Any]) -> List[Dict[str, Any]]:
    """The serving rule table: each entry maps a dominant pressure signal
    to ONE executable serving-config override plus an exact counter
    prediction (``{counter, op, value}`` judged against the re-run's
    bench_serve counters by ``autotuning.serve_verify``). Deterministic:
    ordered by score, ties by rule id."""
    agg = report["aggregate"]
    cfg = report["config_observed"]
    sig = _signals(report)
    props: List[Dict[str, Any]] = []

    churn_share = round(agg["demote"]["share"] + agg["promote"]["share"], 4)
    cur_wm = float(cfg.get("kv_demote_watermark", 0.90))
    if sig["demotions"] > 0 and churn_share >= 0.05 and cur_wm < 0.95:
        # decode starved by demote churn: the tick spends more time moving
        # pages than the load justifies — demote later. The step is
        # deliberately LARGE (+0.25): demotion volume responds to the line
        # with real but bounded run-to-run jitter, and a verifiable
        # prediction needs effect size well above that noise (a +0.05
        # nudge would flip verdicts on scheduler timing, not on the knob).
        new_wm = round(min(cur_wm + 0.25, 0.95), 2)
        props.append({
            "id": "raise_kv_demote_watermark",
            "signal": "demote_churn",
            "score": churn_share,
            "knob": "kv_demote_watermark",
            "overrides": {"serving": {"kv_demote_watermark": new_wm}},
            "reason": f"demote+promote churn is {churn_share:.0%} of tick "
                      f"time ({sig['demotions']} demotions, "
                      f"{sig['demoted_bytes']} bytes) at watermark "
                      f"{cur_wm}: the tier thrashes pages instead of "
                      "decoding — demote later",
            "predicted": {"counter": "demoted_bytes", "op": "<=",
                          "value": sig["demoted_bytes"],
                          "baseline": sig["demoted_bytes"],
                          "unit": "bytes"},
        })
    host_frac = sig["host_frac_max"]
    if cfg.get("kv_offload_enabled") and sig["sheds"] > 0 \
            and host_frac is not None and host_frac < 0.5:
        # shedding while the host tier sits half-idle: the overflow valve
        # exists but is sized too small to absorb this traffic mix
        cur_budget = int(cfg.get("host_kv_budget_bytes", 256 << 20))
        props.append({
            "id": "raise_host_kv_budget_bytes",
            "signal": "sheds_with_idle_host_budget",
            "score": round(min(sig["sheds"], 20) / 20.0, 4),
            "knob": "host_kv_budget_bytes",
            "overrides": {"serving": {"host_kv_budget_bytes":
                                      cur_budget * 2}},
            "reason": f"{sig['sheds']} sheds while the host KV tier peaked "
                      f"at {host_frac:.0%} of its budget: overload is "
                      "degrading to 429 with headroom left — double the "
                      "host budget so pressure degrades to slower first",
            "predicted": {"counter": "sheds", "op": "<=",
                          "value": max(sig["sheds"] - 1, 0),
                          "baseline": sig["sheds"],
                          "unit": "requests"},
        })
    cur_cap = int(cfg.get("prefix_cache_max_blocks", 0) or 0)
    hit = sig["prefix_hit_ratio"]
    if cfg.get("prefix_cache_enabled") and cur_cap > 0 \
            and sig["prefix_evictions"] > 0 and (hit is None or hit < 0.6):
        # the soft cap trims reusable pages the traffic mix would have hit:
        # a bigger cap can only evict fewer blocks under the same seeded
        # load (the exact prediction); the hit ratio rises with it
        hit_txt = "unknown" if hit is None else f"{hit:.0%}"
        props.append({
            "id": "raise_prefix_cache_max_blocks",
            "signal": "low_hit_ratio_with_eviction_pressure",
            "score": round(1.0 - (hit if hit is not None else 0.5), 4),
            "knob": "prefix_cache_max_blocks",
            "overrides": {"serving": {"prefix_cache_max_blocks":
                                      cur_cap * 2}},
            "reason": f"prefix-cache hit ratio {hit_txt} with "
                      f"{sig['prefix_evictions']} blocks evicted at cap "
                      f"{cur_cap}: the cap trims pages the mix would have "
                      "reused — double it",
            "predicted": {"counter": "prefix_evictions", "op": "<=",
                          "value": sig["prefix_evictions"],
                          "baseline": sig["prefix_evictions"],
                          "unit": "blocks",
                          "hit_ratio_baseline": hit},
        })
    sched_cfg = dict(cfg.get("scheduler") or {})
    cur_chunk = int(sched_cfg.get("prefill_chunk_tokens", 0) or 0)
    maxp = sig["max_prefill_tokens_per_tick"]
    prefill_share = agg["prefill"]["share"]
    if maxp > 0 and prefill_share >= 0.35 and agg["decode"]["share"] > 0 \
            and (cur_chunk == 0 or maxp > cur_chunk // 2):
        # decode-first starvation: prefill dominates the tick while decodes
        # wait behind it (the p99 prefill tick IS the TPOT spike a long
        # prompt causes) — cap chunked prefill at half the observed worst
        # tick. KV-block-aligned (16-token pages in the bench geometry) so
        # capped boundaries stay on page granularity; the planner then
        # bounds every tick's prefill tokens by the cap BY CONSTRUCTION,
        # which is exactly the predicted counter bound the re-run judges.
        new_cap = max(maxp // 2 - (maxp // 2) % 16, 16)
        props.append({
            "id": "prefill_chunk_tokens",
            "signal": "prefill_dominates_with_decodes_waiting",
            "score": round(prefill_share, 4),
            "knob": "scheduler.prefill_chunk_tokens",
            "overrides": {"serving": {"scheduler":
                                      {"prefill_chunk_tokens": new_cap}}},
            "reason": f"prefill holds {prefill_share:.0%} of tick time "
                      f"(p99 prefill tick "
                      f"{agg['prefill']['p99_tick_ms']:.2f} ms) with "
                      f"decodes in flight and a worst tick of {maxp} "
                      f"prefill tokens: decode latency is serialized "
                      f"behind long prompts — cap chunked prefill at "
                      f"{new_cap} tokens/tick",
            "predicted": {"counter": "max_prefill_tokens_per_tick",
                          "op": "<=", "value": new_cap,
                          "baseline": maxp,
                          "unit": "tokens"},
        })
    cur_hyst = float(cfg.get("ladder_hysteresis", 0.10))
    if sig["brownout_entries"] >= 2 and cur_hyst < 0.30:
        # brownout flapping: the ladder re-enters brownout on pressure
        # jitter — widen the descent band so one episode stays one episode
        new_hyst = round(min(cur_hyst * 2, 0.30), 3)
        props.append({
            "id": "widen_ladder_hysteresis",
            "signal": "brownout_flapping",
            "score": round(min(sig["brownout_entries"], 10) / 10.0, 4),
            "knob": "ladder_hysteresis",
            "overrides": {"serving": {"ladder_hysteresis": new_hyst}},
            "reason": f"{sig['brownout_entries']} brownout entries in one "
                      f"run at hysteresis {cur_hyst}: the ladder flaps on "
                      "pressure jitter — widen the descent band to "
                      f"{new_hyst}",
            "predicted": {"counter": "brownout_entries", "op": "<=",
                          "value": sig["brownout_entries"],
                          "baseline": sig["brownout_entries"],
                          "unit": "entries"},
        })
    props.sort(key=lambda p: (-p["score"], p["id"]))
    return props


# ---------------------------------------------------------------------------
# regression baseline (dslint/plan ratchet idiom)
# ---------------------------------------------------------------------------
def load_serve_plan_baseline(path: str) -> dict:
    with open(path) as f:
        data = json.load(f)
    if data.get("version") != SERVE_PLAN_BASELINE_VERSION:
        raise ValueError(f"unsupported serve plan baseline version "
                         f"{data.get('version')!r} in {path} "
                         f"(expected {SERVE_PLAN_BASELINE_VERSION})")
    return data


def find_serve_plan_baseline(start: str) -> Optional[str]:
    """Walk up from ``start`` looking for the checked-in baseline (same
    discovery rule as dslint's / plan's)."""
    d = os.path.abspath(start)
    if os.path.isfile(d):
        d = os.path.dirname(d)
    while True:
        cand = os.path.join(d, SERVE_PLAN_BASELINE_NAME)
        if os.path.exists(cand):
            return cand
        parent = os.path.dirname(d)
        if parent == d:
            return None
        d = parent


def write_serve_plan_baseline(path: str, report: Dict[str, Any],
                              tolerance: float = 2.0,
                              min_abs_ms: float = 0.05) -> dict:
    """Record the report's per-stage tick quantiles as the new baseline,
    workload-scoped by the TRACE basename (same contract as
    ``plan_baseline.json``: discovered baselines only judge traces of
    their own workload)."""
    data = {
        "version": SERVE_PLAN_BASELINE_VERSION,
        "workload": os.path.basename(str(report.get("trace", ""))),
        "tolerance": float(tolerance),
        "min_abs_ms": float(min_abs_ms),
        "entries": {
            s: {"p50_tick_ms": report["aggregate"][s]["p50_tick_ms"],
                "p95_tick_ms": report["aggregate"][s]["p95_tick_ms"]}
            for s in STAGES},
    }
    with open(path, "w") as f:
        json.dump(data, f, indent=2, sort_keys=True)
        f.write("\n")
    return data


def check_baseline(report: Dict[str, Any], baseline: dict,
                   tolerance: Optional[float] = None
                   ) -> Tuple[List[dict], List[dict]]:
    """(regressions, stale) — the plan ratchet: a stage REGRESSES when its
    current per-tick quantile exceeds baseline * tolerance AND the
    absolute floor; an improved entry is STALE and expires only via
    ``--write-baseline``."""
    tol = float(tolerance if tolerance is not None
                else baseline.get("tolerance", 2.0))
    floor = float(baseline.get("min_abs_ms", 0.05))
    regressions, stale = [], []
    for stage, entry in sorted(baseline.get("entries", {}).items()):
        agg = report["aggregate"].get(stage)
        if agg is None:
            continue
        for metric in ("p50_tick_ms", "p95_tick_ms"):
            base = float(entry.get(metric, 0.0))
            cur = float(agg[metric])
            row = {"stage": stage, "metric": metric, "baseline_ms": base,
                   "current_ms": cur,
                   "ratio": round(cur / base, 3) if base > 0 else None}
            if cur > base * tol and (cur - base) > floor:
                regressions.append(row)
            elif base > cur * tol and (base - cur) > floor:
                stale.append(row)
    return regressions, stale


# ---------------------------------------------------------------------------
# rendering + CLI
# ---------------------------------------------------------------------------
def render(report: Dict[str, Any], top_windows: int = 8) -> str:
    out = []
    out.append(f"dstpu plan --serve — {report['source']}")
    prov = report.get("provenance") or {}
    preset = prov.get("preset", "?")
    out.append(f"preset={preset} seed={prov.get('seed', '?')} | "
               f"{report['ticks_total']} ticks, "
               f"{report['window_ms_total']:.1f} ms traced tick time, "
               f"p50 tick {report['tick_ms_p50']:.3f} ms "
               f"(windows: {report['window_mode']})")
    out.append("")
    hdr = f"{'win':>4} {'tick':>6} {'ms':>9}"
    for s in STAGES:
        hdr += f" {s[:8]:>9}"
    out.append(hdr + "   tie-out")
    out.append("-" * len(hdr))
    for w in report["windows"][:top_windows]:
        tick = w["tick"] if w["tick"] is not None else "-"
        row = f"{w['index']:>4} {tick:>6} {w['dur_us'] / 1e3:>9.3f}"
        for s in STAGES:
            row += f" {w['stages_us'][s] / 1e3:>9.3f}"
        row += f"   {w['tie_out_error'] * 100:.2f}%"
        out.append(row)
    if len(report["windows"]) > top_windows:
        out.append(f"... {len(report['windows']) - top_windows} more "
                   "windows (--top N)")
    out.append("")
    out.append(f"{'stage':<10} {'share':>7} {'p50/tick':>10} "
               f"{'p95/tick':>10} {'p99/tick':>10}")
    out.append("-" * 51)
    for s in STAGES:
        a = report["aggregate"][s]
        out.append(f"{s:<10} {a['share'] * 100:>6.1f}% "
                   f"{a['p50_tick_ms']:>9.3f}ms {a['p95_tick_ms']:>9.3f}ms "
                   f"{a['p99_tick_ms']:>9.3f}ms")
    req = report.get("requests", {})
    if req.get("levels"):
        out.append("")
        out.append("request latency from retro-spans (per ladder level)")
        for level, r in req["levels"].items():
            out.append(f"  {level:<10} n={r['count']:<5} "
                       f"ttft p50/p99 {r['ttft_p50_ms']:.2f}/"
                       f"{r['ttft_p99_ms']:.2f} ms  tpot p50/p99 "
                       f"{r['tpot_p50_ms']:.3f}/{r['tpot_p99_ms']:.3f} ms")
    inst = report.get("instants", {})
    if inst.get("ladder_edges") or inst.get("backpressure"):
        out.append("")
        out.append(f"ladder edges: {inst.get('ladder_edges')}  "
                   f"backpressure: {inst.get('backpressure')}")
    out.append("")
    if report["proposals"]:
        out.append("proposals (dominant pressure -> serving override):")
        for p in report["proposals"]:
            out.append(f"  [{p['id']}] {p['reason']}")
            out.append(f"      overrides: {json.dumps(p['overrides'])}")
            pred = p["predicted"]
            out.append(f"      predicted: {pred['counter']} {pred['op']} "
                       f"{pred['value']} {pred.get('unit', '')} (verify "
                       "with dstpu_bench_serve --verify-plan)")
    else:
        out.append("proposals: none — no pressure signal clears its rule "
                   "(the knobs fit this traffic mix)")
    return "\n".join(out)


def analyze_serve_path(path: str) -> Dict[str, Any]:
    """Load + attribute in one call (the API the tests, env_report and
    verify runner use). ``path`` is a trace dump or bench_serve report."""
    events, meta = load_input(path)
    return attribute_serve(events, source=path, meta=meta)


def main(argv=None) -> int:
    parser = argparse.ArgumentParser(
        prog="dstpu plan --serve",
        description="serving-tick attribution, siege-knob regression "
                    "ledger, and proposal generation (input: a dstrace "
                    "dump or a bench_serve report with provenance)")
    parser.add_argument("input", help="dstrace Chrome-trace dump or "
                                      "bench_serve report JSON")
    parser.add_argument("--baseline", default=None,
                        help=f"baseline path (default: walk up from the "
                             f"trace for {SERVE_PLAN_BASELINE_NAME})")
    parser.add_argument("--write-baseline", action="store_true",
                        help="record this report as the new baseline "
                             "(ratchet: also how stale entries expire)")
    parser.add_argument("--tolerance", type=float, default=None,
                        help="regression factor vs baseline (default: the "
                             "factor stored in the baseline)")
    parser.add_argument("--out", default=None,
                        help="write the full plan artifact JSON here "
                             f"(env_report reads ${SERVE_PLAN_ARTIFACT_ENV} "
                             f"or ./{DEFAULT_SERVE_PLAN_ARTIFACT})")
    parser.add_argument("--json", action="store_true",
                        help="print the report as JSON instead of a table")
    parser.add_argument("--top", type=int, default=8,
                        help="ledger windows to show (default 8)")
    args = parser.parse_args(argv)

    try:
        report = analyze_serve_path(args.input)
    except PlanError as e:
        print(f"dstpu plan --serve: {e}", file=sys.stderr)
        return EXIT_UNREADABLE

    # discovery anchors at the TRACE path (workload scoping, same contract
    # as plan_baseline.json); pass --baseline to compare across workloads
    trace_path = report["trace"]
    bl_path = args.baseline or find_serve_plan_baseline(trace_path)
    regressions, stale = [], []
    effective_tol = args.tolerance if args.tolerance is not None else 2.0
    trace_workload = os.path.basename(trace_path)
    if args.write_baseline:
        trace_dir = os.path.dirname(os.path.abspath(trace_path))
        target = bl_path or os.path.join(trace_dir,
                                         SERVE_PLAN_BASELINE_NAME)
        if args.baseline is None and os.path.exists(target):
            try:    # never clobber a DISCOVERED baseline of another
                existing_wl = load_serve_plan_baseline(target) \
                    .get("workload")
            except (OSError, ValueError):
                existing_wl = None
            if existing_wl and existing_wl != trace_workload:
                redirected = os.path.join(trace_dir,
                                          SERVE_PLAN_BASELINE_NAME)
                if os.path.abspath(redirected) == os.path.abspath(target):
                    print(f"# refusing --write-baseline: {target} ratchets "
                          f"workload {existing_wl!r} — pass --baseline "
                          "PATH to overwrite it deliberately",
                          file=sys.stderr)
                    target = None
                else:
                    print(f"# note: {target} ratchets workload "
                          f"{existing_wl!r} — starting this workload's "
                          f"baseline at {redirected} instead",
                          file=sys.stderr)
                    target = redirected
        if target is not None:
            if args.tolerance is None and os.path.exists(target):
                try:    # ratchet rewrite: keep the factor the team chose
                    effective_tol = float(load_serve_plan_baseline(target)
                                          .get("tolerance", 2.0))
                except (OSError, ValueError):
                    pass
            write_serve_plan_baseline(target, report,
                                      tolerance=effective_tol)
            print(f"# serve plan baseline written -> {target}",
                  file=sys.stderr)
        bl_path = target
    elif bl_path:
        try:
            baseline = load_serve_plan_baseline(bl_path)
        except (OSError, ValueError) as e:
            print(f"dstpu plan --serve: bad baseline {bl_path}: {e}",
                  file=sys.stderr)
            return EXIT_UNREADABLE
        bl_workload = baseline.get("workload")
        if args.baseline is None and bl_workload \
                and bl_workload != trace_workload:
            print(f"# note: discovered baseline {bl_path} is for workload "
                  f"{bl_workload!r}, not {trace_workload!r} — comparison "
                  "skipped (pass --baseline to compare anyway, or "
                  "--write-baseline to start ratcheting this workload)",
                  file=sys.stderr)
            bl_path = None
        else:
            regressions, stale = check_baseline(report, baseline,
                                                tolerance=args.tolerance)
            effective_tol = args.tolerance if args.tolerance is not None \
                else float(baseline.get("tolerance", 2.0))
    report["baseline"] = {"path": bl_path, "regressions": regressions,
                          "stale": stale}

    # the tie-out contract is CHECKED, not assumed (attribution.py idiom)
    violations = [w["index"] for w in report["windows"]
                  if w["tie_out_error"] > TIE_OUT_TOLERANCE]
    report["tie_out_violations"] = violations
    for idx in violations:
        w = report["windows"][idx]
        print(f"WARNING: tick window {idx} over-attributes "
              f"{w['tie_out_error'] * 100:.1f}% of its span "
              f"(> {TIE_OUT_TOLERANCE * 100:.0f}% tolerance) — "
              "overlapping or skewed spans; treat its ledger row as "
              "suspect", file=sys.stderr)

    if args.out:
        with open(args.out, "w") as f:
            json.dump(report, f, indent=2)
            f.write("\n")
    if args.json:
        print(json.dumps(report, indent=2))
    else:
        print(render(report, top_windows=args.top))
        for r in regressions:
            print(f"REGRESSION: {r['stage']} {r['metric']} "
                  f"{r['baseline_ms']:.3f} -> {r['current_ms']:.3f} ms "
                  f"({r['ratio']}x, tolerance {effective_tol}x) vs "
                  f"{bl_path}", file=sys.stderr)
        for r in stale:
            print(f"stale baseline entry (improved): {r['stage']} "
                  f"{r['metric']} {r['baseline_ms']:.3f} -> "
                  f"{r['current_ms']:.3f} ms — re-run with "
                  f"--write-baseline to ratchet", file=sys.stderr)
    return EXIT_REGRESSION if regressions else EXIT_OK


if __name__ == "__main__":
    sys.exit(main())
