"""dstrace — always-on structured span tracing.

The telemetry substrate that unifies the repo's five observability islands
(timer registry, CommsLogger, monitor fan-out, serving metrics, resilience
diagnostics) into ONE host-clock event stream: bounded ring buffer of span /
instant events with monotonic ids and explicit step / request correlation
keys, exported as Chrome-trace JSON (Perfetto-loadable) plus an in-process
summary API.

Design constraints (all load-bearing):

- **Never a host sync.** Emission reads ``time.monotonic()`` and appends a
  tuple — no jax calls, no ``float()`` on device arrays, no transfers. The
  emit helpers are registered DS002 hot paths, so the linter *proves* the
  tracer cannot regrow a sync (``tools/dslint/hotpath.py``).
- **Lock-free emit.** ``deque.append`` and ``itertools.count.__next__`` are
  GIL-atomic; the only lock guards export/reconfiguration. Producers on the
  serve loop, prefetch worker, watchdog monitor, and main thread never
  contend.
- **Signal-safe instants.** ``instant(..., fanout=False)`` does nothing but
  an append — no I/O, no locks, no allocation beyond one tuple — so the
  resilience SIGTERM handler can leave a breadcrumb (DS005-clean).
- **Bounded memory.** The ring holds ``capacity`` events (oldest evicted);
  a long-running server traces forever at a fixed footprint, and the
  resilience diagnostic bundles embed ``tail(seconds)`` slices of it.

Activation: ``configure_tracing(enabled=True)``, or set ``DSTPU_TRACE=path``
in the environment — tracing starts at first use and the trace is dumped to
``path`` at interpreter exit (plus wherever ``engine.dump_trace`` is called).
"""

import atexit
import collections
import itertools
import json
import os
import socket
import threading
import time
from typing import Any, Callable, Dict, List, Optional, Tuple

from deepspeed_tpu.utils.logging import logger

TRACE_ENV = "DSTPU_TRACE"
TRACE_CAPACITY_ENV = "DSTPU_TRACE_CAPACITY"
DEFAULT_CAPACITY = 65536

#: env fallbacks for the process-identity header (``set_process_identity``
#: is the programmatic form — ``comm.mesh.init_distributed`` stamps it at
#: rendezvous, which covers every MULTICHIP worker; the env form covers
#: launchers that know the rank before the process does)
TRACE_RANK_ENV = "DSTPU_TRACE_RANK"
TRACE_WORLD_ENV = "DSTPU_TRACE_WORLD"

#: synthetic tid range for per-request serving tracks — renders one Perfetto
#: track per request uid. Real thread idents are pointer-sized (far above
#: this window), so [BASE, BASE + SPAN) never collides with a live thread.
REQUEST_TID_BASE = 1_000_000
REQUEST_TID_SPAN = 10_000_000

#: synthetic track for the comm-compression ``comm/overlap`` bucket spans
#: (below the request window; same no-collision argument). Its own track is
#: the contract ``dstpu plan`` relies on: off-main-track spans attribute as
#: overlapped work — the prefetch-worker treatment — never as step cost.
COMM_OVERLAP_TID = 900_000


def request_tid(uid: int) -> int:
    """Synthetic per-request track id (stable for a given uid)."""
    return REQUEST_TID_BASE + (int(uid) % REQUEST_TID_SPAN)

# event tuple layout: (eid, name, cat, ph, ts_s, dur_s, tid, args_or_None)
_EID, _NAME, _CAT, _PH, _TS, _DUR, _TID, _ARGS = range(8)


def _quantile(sorted_vals, q: float) -> float:
    """Exact sample quantile over pre-sorted values — the repo-wide rule
    (serving ``_LatencyStat.quantile`` / ``attribution.quantile``): the
    value at index ``min(int(q*n), n-1)``. The step-time attribution of
    ``dstpu plan`` consumes these, so the rule must not drift per caller."""
    if not sorted_vals:
        return 0.0
    return sorted_vals[min(int(q * len(sorted_vals)), len(sorted_vals) - 1)]


class _NoopSpan:
    """Shared do-nothing context — THE fast path when tracing is off (one
    attribute read + one identity return per ``span()`` call)."""
    __slots__ = ()

    def __enter__(self):
        return self

    def __exit__(self, exc_type, exc, tb):
        return False


_NOOP_SPAN = _NoopSpan()


class _Span:
    """A live span: enter stamps t0, exit appends one complete ("X") event.
    Nesting falls out of Chrome-trace semantics — same-thread spans nest by
    ts/dur containment, which the with-statement guarantees."""
    __slots__ = ("_tracer", "_name", "_cat", "_args", "_t0")

    def __init__(self, tracer, name, cat, args):
        self._tracer = tracer
        self._name = name
        self._cat = cat
        self._args = args
        self._t0 = 0.0

    def __enter__(self):
        self._t0 = time.monotonic()
        return self

    def __exit__(self, exc_type, exc, tb):
        t0 = self._t0
        self._tracer._emit(self._name, self._cat, "X", t0,
                           time.monotonic() - t0,
                           threading.get_ident(), self._args)
        return False


class Tracer:
    """Thread-safe bounded span tracer with Chrome-trace export."""

    def __init__(self, capacity: int = DEFAULT_CAPACITY):
        self.enabled = False
        self._events: collections.deque = collections.deque(
            maxlen=max(int(capacity), 16))
        self._ids = itertools.count(1)        # monotonic event ids
        self._epoch = time.monotonic()        # export ts origin
        self._lock = threading.Lock()         # export/config only, never emit
        self._cleared = 0                     # events discarded by clear()
        self._sink: Optional[Callable[[str, int], None]] = None
        # process identity for cross-rank merge (``dstpu trace merge``):
        # rank/world default from env, re-stampable at rendezvous time
        try:
            self._rank = int(os.environ.get(TRACE_RANK_ENV, 0))
            self._world = int(os.environ.get(TRACE_WORLD_ENV, 1))
        except ValueError:
            self._rank, self._world = 0, 1

    # ------------------------------------------------------------------
    # configuration
    # ------------------------------------------------------------------
    def configure(self, enabled: Optional[bool] = None,
                  capacity: Optional[int] = None) -> "Tracer":
        with self._lock:
            if capacity is not None and capacity != self._events.maxlen:
                old = self._events
                new = collections.deque(old, maxlen=max(int(capacity), 16))
                self._events = new
                # emit is lock-free by design, so a producer may have
                # appended to the old deque between the copy and the swap —
                # carry those over (the remaining loss window is a single
                # concurrent emit's attribute-load-to-append gap)
                last = max((e[_EID] for e in new), default=0)
                new.extend(e for e in list(old) if e[_EID] > last)
            if enabled is not None:
                self.enabled = bool(enabled)
        return self

    @property
    def capacity(self) -> int:
        return self._events.maxlen

    def set_process_identity(self, rank: int, world: int) -> None:
        """Stamp this process's rank/world into every future dump header
        (``comm.mesh.init_distributed`` calls this at rendezvous — config
        time, never the hot path). The header is what ``dstpu trace merge``
        joins per-rank dumps on; without it a dump merges as rank 0 of 1."""
        self._rank = int(rank)
        self._world = int(world)

    def process_identity(self) -> Dict[str, Any]:
        """The dump header: who emitted this trace and a FRESH monotonic↔
        wall anchor pair (same instant, both clocks) so a merger can place
        this dump's monotonic timeline on the shared wall clock. Stamped at
        dump time — anchors age badly; a dump-time pair bounds NTP drift to
        the run's tail, not its whole life."""
        return {
            "rank": self._rank,
            "world": self._world,
            "hostname": socket.gethostname(),
            "pid": os.getpid(),
            # one anchor pair, read back-to-back: wall_s - monotonic_s maps
            # any event ts (epoch-relative monotonic) onto the wall clock
            "monotonic_s": time.monotonic(),
            "wall_s": time.time(),
            "epoch_monotonic_s": self._epoch,
        }

    def clear(self) -> None:
        with self._lock:
            # cleared events are not ring evictions: account for them so
            # dropped() stays exact across clear()
            self._cleared += len(self._events)
            self._events.clear()

    def attach_sink(self, fn: Callable[[str, int], None]) -> None:
        """Attach the instant-event fan-out hook (``fn(name, step)``) — the
        monitor's ``events`` sink, so guard trips / chaos injections land in
        TensorBoard/CSV alongside gauges. One sink; last attach wins."""
        self._sink = fn

    def detach_sink(self) -> None:
        self._sink = None

    # ------------------------------------------------------------------
    # emission (registered DS002 hot path: must never host-sync)
    # ------------------------------------------------------------------
    def _emit(self, name, cat, ph, ts, dur, tid, args) -> None:
        self._events.append(
            (next(self._ids), name, cat, ph, ts, dur, tid, args))

    def span(self, name: str, cat: str = "host", **args):
        """``with tracer.span("engine/dispatch", step=n): ...`` — a complete
        event on the current thread. Returns a shared no-op context when
        tracing is off (the fast path every instrumented call site relies
        on)."""
        if not self.enabled:
            return _NOOP_SPAN
        return _Span(self, name, cat, args or None)

    def instant(self, name: str, cat: str = "event",
                step: Optional[int] = None, fanout: bool = True,
                tid: Optional[int] = None, **args) -> None:
        """A zero-duration marker (guard trip, chaos injection, preemption
        signal). ``step`` is the correlation key; when present and
        ``fanout`` is True the attached monitor sink also receives it.
        ``fanout=False`` is the signal-handler-safe form: append only, no
        sink, no I/O, no locks. ``tid`` overrides the track (per-request
        serving tracks)."""
        if not self.enabled:
            return
        if step is not None:
            args["step"] = step
        self._emit(name, cat, "i", time.monotonic(), 0.0,
                   tid if tid is not None else threading.get_ident(),
                   args or None)
        sink = self._sink
        if fanout and sink is not None and step is not None:
            try:
                sink(name, step)
            except Exception:
                logger.exception("dstrace: instant sink failed")

    def counter(self, name: str, cat: str = "mem",
                tid: Optional[int] = None, **series) -> None:
        """A Chrome-trace counter sample (``"ph":"C"``): ``series`` maps
        series label -> numeric value, rendered by Perfetto as a stacked
        counter track time-aligned with the spans (the dsmem HBM/RSS/KV
        watermark tracks). Same hot-path contract as ``instant``: one
        append, no locks, no I/O, no device touch."""
        if not self.enabled or not series:
            return
        self._emit(name, cat, "C", time.monotonic(), 0.0,
                   tid if tid is not None else threading.get_ident(),
                   series)

    def complete(self, name: str, dur_s: float, cat: str = "host",
                 end_ts: Optional[float] = None, tid: Optional[int] = None,
                 **args) -> None:
        """Record a span retroactively from a measured duration (the async
        drain's reconciled step window, serving request phases rebuilt from
        lifecycle timestamps). ``end_ts`` is on the tracer clock
        (``time.monotonic``); defaults to now. ``tid`` overrides the track
        (per-request serving tracks use ``REQUEST_TID_BASE + uid``)."""
        if not self.enabled:
            return
        if dur_s < 0.0:
            dur_s = 0.0
        end = time.monotonic() if end_ts is None else end_ts
        self._emit(name, cat, "X", end - dur_s, dur_s,
                   tid if tid is not None else threading.get_ident(),
                   args or None)

    # ------------------------------------------------------------------
    # read side
    # ------------------------------------------------------------------
    def events_snapshot(self) -> List[Tuple]:
        with self._lock:
            return list(self._events)

    def tail(self, seconds: float) -> List[Tuple]:
        """Events whose END falls inside the last ``seconds`` — the slice
        resilience diagnostic bundles embed ("what happened in the 30s
        before the guard quarantined")."""
        cutoff = time.monotonic() - max(float(seconds), 0.0)
        return [e for e in self.events_snapshot()
                if (e[_TS] + e[_DUR]) >= cutoff]

    def dropped(self) -> int:
        """Events evicted from the ring so far (monotonic ids make the count
        exact: last id minus retained length minus clear()ed events)."""
        snap = self.events_snapshot()
        if not snap:
            return 0
        last = max(e[_EID] for e in snap)
        return max(0, last - len(snap) - self._cleared)

    # ------------------------------------------------------------------
    # export
    # ------------------------------------------------------------------
    def to_chrome(self, events: Optional[List[Tuple]] = None) -> Dict[str, Any]:
        """Chrome-trace/Perfetto JSON object format. Span events are "X"
        (complete) with microsecond ts/dur relative to the tracer epoch;
        instants are "i"; thread-name metadata rides along so Perfetto
        tracks are labeled."""
        if events is None:
            events = self.events_snapshot()
        pid = os.getpid()
        thread_names = {t.ident: t.name for t in threading.enumerate()}
        trace_events: List[Dict[str, Any]] = []
        seen_tids: Dict[int, str] = {}
        for eid, name, cat, ph, ts, dur, tid, args in events:
            tid = int(tid)
            if tid not in seen_tids:
                if tid in thread_names:
                    seen_tids[tid] = thread_names[tid]
                elif tid == COMM_OVERLAP_TID:
                    seen_tids[tid] = "comm-overlap"
                elif REQUEST_TID_BASE <= tid < REQUEST_TID_BASE + \
                        REQUEST_TID_SPAN:
                    seen_tids[tid] = f"request-{tid - REQUEST_TID_BASE}"
                else:
                    seen_tids[tid] = f"thread-{tid}"   # exited thread
            ev: Dict[str, Any] = {
                "name": name, "cat": cat, "ph": ph, "pid": pid, "tid": tid,
                "ts": round((ts - self._epoch) * 1e6, 3),
            }
            if ph == "X":
                ev["dur"] = round(dur * 1e6, 3)
            elif ph == "i":
                ev["s"] = "t"          # thread-scoped instant
            if ph == "C":
                # counter events: args ARE the series values (adding the
                # event id would draw a bogus monotonically-rising series)
                ev["args"] = dict(args) if args else {}
            else:
                ev["args"] = dict(args, id=eid) if args else {"id": eid}
            trace_events.append(ev)
        identity = self.process_identity()
        proc_label = "deepspeed_tpu" if identity["world"] <= 1 else \
            f"deepspeed_tpu rank{identity['rank']}/{identity['world']}"
        meta = [{"name": "process_name", "ph": "M", "pid": pid,
                 "args": {"name": proc_label}}]
        meta.extend({"name": "thread_name", "ph": "M", "pid": pid,
                     "tid": tid, "args": {"name": label}}
                    for tid, label in sorted(seen_tids.items()))
        return {
            "traceEvents": meta + trace_events,
            "displayTimeUnit": "ms",
            "otherData": {
                "clock": "monotonic",
                "events": len(events),
                "dropped": self.dropped(),
                "capacity": self._events.maxlen,
                # the cross-rank join key: which process this dump is, and
                # the clock anchor that places it on the shared wall clock
                "process": identity,
            },
        }

    def export_chrome(self, path: Optional[str] = None,
                      tail_s: Optional[float] = None) -> Dict[str, Any]:
        """Build (and optionally write) the Chrome-trace dump. ``tail_s``
        restricts it to the trailing slice — the diagnostic-bundle form."""
        events = self.tail(tail_s) if tail_s is not None else None
        trace = self.to_chrome(events)
        if path:
            d = os.path.dirname(os.path.abspath(path))
            if d:
                os.makedirs(d, exist_ok=True)
            with open(path, "w") as f:
                # args may hold numpy scalars etc. — stringify, never die
                json.dump(trace, f, default=str)
        return trace

    # ------------------------------------------------------------------
    # aggregation
    # ------------------------------------------------------------------
    def summary(self, prefix: Optional[str] = None) -> Dict[str, Dict[str, float]]:
        """Per-span-name aggregate over the ring's complete events:
        count / total_s / mean_s / max_s / p50_s / p95_s / p99_s.
        ``prefix`` filters span names (e.g. ``"serve/"``; a tuple of
        prefixes matches any — ``str.startswith`` semantics)."""
        buckets: Dict[str, List[float]] = {}
        for e in self.events_snapshot():
            if e[_PH] != "X":
                continue
            name = e[_NAME]
            if prefix and not name.startswith(prefix):
                continue
            buckets.setdefault(name, []).append(e[_DUR])
        out: Dict[str, Dict[str, float]] = {}
        for name, durs in buckets.items():
            durs.sort()
            n = len(durs)
            out[name] = {
                "count": n,
                "total_s": sum(durs),
                "mean_s": sum(durs) / n,
                "max_s": durs[-1],
                "p50_s": _quantile(durs, 0.5),
                "p95_s": _quantile(durs, 0.95),
                "p99_s": _quantile(durs, 0.99),
            }
        return out

    def instant_counts(self, prefix: Optional[str] = None) -> Dict[str, int]:
        out: Dict[str, int] = {}
        for e in self.events_snapshot():
            if e[_PH] != "i":
                continue
            name = e[_NAME]
            if prefix and not name.startswith(prefix):
                continue
            out[name] = out.get(name, 0) + 1
        return out

    def counter_series(self, prefix: Optional[str] = None
                       ) -> Dict[str, Dict[str, Dict[str, float]]]:
        """Per-counter per-series aggregate over the ring's "C" events:
        ``{counter: {series: {"last", "max", "p95", "p99", "count"}}}`` —
        the read side of the dsmem HBM/RSS/KV tracks (events are
        id-ordered, so "last" is the newest sample; p95/p99 follow the
        shared exact-quantile rule ``_quantile``, same as the serve-plan
        replay's standalone copy, so KV/prefix counter tracks report tails
        rather than just last/max)."""
        values: Dict[str, Dict[str, List[float]]] = {}
        for e in sorted(self.events_snapshot(), key=lambda e: e[_EID]):
            if e[_PH] != "C" or not e[_ARGS]:
                continue
            name = e[_NAME]
            if prefix and not name.startswith(prefix):
                continue
            bucket = values.setdefault(name, {})
            for series, value in e[_ARGS].items():
                try:
                    v = float(value)
                except (TypeError, ValueError):
                    continue
                bucket.setdefault(series, []).append(v)
        out: Dict[str, Dict[str, Dict[str, float]]] = {}
        for name, bucket in values.items():
            rows = out.setdefault(name, {})
            for series, vals in bucket.items():
                last = vals[-1]
                vals.sort()
                rows[series] = {"last": last, "max": vals[-1],
                                "p95": _quantile(vals, 0.95),
                                "p99": _quantile(vals, 0.99),
                                "count": len(vals)}
        return out

    def prometheus_lines(self, prefix: Optional[str] = None) -> List[str]:
        """Prometheus exposition of the span aggregates plus counter-track
        gauges (the serving ``/metrics`` endpoint appends these for
        ``serve/*`` and ``mem/*``)."""
        lines: List[str] = []
        summ = self.summary(prefix=prefix)
        if summ:
            lines += ["# HELP dstpu_trace_span_seconds tracer span durations",
                      "# TYPE dstpu_trace_span_seconds summary"]
            for name in sorted(summ):
                s = summ[name]
                for q, key in ((0.5, "p50_s"), (0.95, "p95_s"),
                               (0.99, "p99_s")):
                    lines.append(f'dstpu_trace_span_seconds{{span="{name}",'
                                 f'quantile="{q}"}} {s[key]:.9g}')
                lines.append(f'dstpu_trace_span_seconds_sum{{span="{name}"}} '
                             f'{s["total_s"]:.9g}')
                lines.append(
                    f'dstpu_trace_span_seconds_count{{span="{name}"}} '
                    f'{int(s["count"])}')
        counters = self.counter_series(prefix=prefix)
        if counters:
            lines += ["# HELP dstpu_trace_counter tracer counter tracks "
                      "(last/peak per series)",
                      "# TYPE dstpu_trace_counter gauge"]
            for name in sorted(counters):
                for series in sorted(counters[name]):
                    s = counters[name][series]
                    for stat in ("last", "max", "p95", "p99"):
                        lines.append(
                            f'dstpu_trace_counter{{counter="{name}",'
                            f'series="{series}",stat="{stat}"}} '
                            f'{s[stat]:.9g}')
        return lines


# ---------------------------------------------------------------------------
# process-global tracer
# ---------------------------------------------------------------------------
_tracer: Optional[Tracer] = None
_tracer_guard = threading.Lock()


def _dump_at_exit(tracer: Tracer, path: str) -> None:
    try:
        tracer.export_chrome(path)
        logger.info(f"dstrace: trace written -> {path} "
                    f"(load in https://ui.perfetto.dev)")
    except Exception:
        logger.exception("dstrace: atexit trace dump failed")


def get_tracer() -> Tracer:
    """THE process tracer every instrumented subsystem shares. First call
    honors ``DSTPU_TRACE=path`` (enable + dump at exit) and
    ``DSTPU_TRACE_CAPACITY``."""
    global _tracer
    t = _tracer
    if t is not None:
        return t
    with _tracer_guard:
        if _tracer is None:
            try:
                cap = int(os.environ.get(TRACE_CAPACITY_ENV,
                                         DEFAULT_CAPACITY))
            except ValueError:
                cap = DEFAULT_CAPACITY
            t = Tracer(capacity=cap)
            path = os.environ.get(TRACE_ENV)
            if path:
                t.enabled = True
                atexit.register(_dump_at_exit, t, path)
                logger.info(f"dstrace: tracing enabled ({TRACE_ENV}); dump "
                            f"at exit -> {path}")
            _tracer = t
        return _tracer


def configure_tracing(enabled: Optional[bool] = None,
                      capacity: Optional[int] = None) -> Tracer:
    """Convenience front door: ``configure_tracing(enabled=True)``."""
    return get_tracer().configure(enabled=enabled, capacity=capacity)
