"""``dstpu plan`` — trace-driven step-time attribution and config planning.

The read side of dstrace: PR 5 made every subsystem emit correlated spans
and PR 6 attached comm health to them, but nothing could yet *replay* a
trace and say where a step's time went or what config change would buy it
back. This module closes that loop (DeepCompile-style profile-guided
planning, arxiv 2504.09983):

1. **Attribution** — each training step window (the ``engine/
   steps_reconciled`` retro-spans in async mode, synthesized dispatch runs
   in sync mode) is decomposed into *exclusive* stages on the main track:
   dispatch-gap, drain/host-sync, h2d staging, comm, checkpoint I/O,
   inline prefetch, and an unattributed residual. Exclusivity comes from a
   priority interval sweep (innermost span wins), so the per-window ledger
   provably ties out: ``sum(stages) + residual == window`` by
   construction, and ``sum(stages) <= window`` within a small clock-skew
   tolerance is asserted rather than assumed.
2. **Ledger + aggregates** — per-window stage times normalized to
   per-step milliseconds, with p50/p95/p99 across windows and share of
   total traced step time. Comm spans roll up bytes/algbw/busbw per op
   and world size.
3. **Regression ledger** — ``plan_baseline.json`` (same ratchet idiom as
   dslint's baseline): per-stage per-step quantiles are recorded once,
   regressions beyond a tolerance factor fail the CLI with exit code 1 —
   a deterministic "drain time grew 2x" tripwire on hosts where
   wall-clock A/B is noise. Improvements surface as *stale* entries so
   the baseline ratchets down via ``--write-baseline``.
4. **Proposals** — a rule table maps dominant stages to concrete config
   overrides ({sync_every, prefetch, gas, micro_batch, zero_stage,
   offload tier}) with a machine-checkable predicted win;
   ``Autotuner(plan=...)`` executes exactly these and verifies the
   prediction against the resulting trace (autotuning/autotuner.py).

Offline-only, by contract: this module never imports jax and never runs
on a hot path — ``tools/dslint/hotpath.py`` lists it in
``OFFLINE_ONLY_MODULES`` and tests/test_plan.py proves no registered
hot-path file can reach it.
"""

import argparse
import json
import math
import os
import sys
from typing import Any, Dict, List, Optional, Tuple

def _load_trace_names():
    """File-load ``telemetry/names.py`` from the sibling path — never a
    package import: this module loads standalone on jax-less hosts. The
    registry is the ONE declaration of the span names this sweep
    attributes; dslint DS007 keeps the emitters in agreement with it."""
    import importlib.util
    mod = sys.modules.get("dstpu_trace_names")
    if mod is None:
        path = os.path.join(os.path.dirname(os.path.abspath(__file__)),
                            "names.py")
        spec = importlib.util.spec_from_file_location(
            "dstpu_trace_names", path)
        mod = importlib.util.module_from_spec(spec)
        spec.loader.exec_module(mod)
        sys.modules["dstpu_trace_names"] = mod
    return mod


_NAMES = _load_trace_names()

EXIT_OK = 0
EXIT_REGRESSION = 1
EXIT_UNREADABLE = 2

PLAN_VERSION = 1
PLAN_BASELINE_VERSION = 1
PLAN_BASELINE_NAME = "plan_baseline.json"
PLAN_ARTIFACT_ENV = "DSTPU_PLAN_ARTIFACT"
DEFAULT_PLAN_ARTIFACT = "plan.json"

#: stage keys, in ledger/report order. ``residual`` is always last: it is
#: the remainder of the window the sweep could not attribute (device-bound
#: compute in sync mode, untraced host work in async mode).
STAGES = ("dispatch", "drain", "h2d", "comm", "ckpt", "prefetch", "residual")

#: exclusive-sweep priority — at any instant the HIGHEST-priority covering
#: span owns the time, which resolves nesting (drain inside ckpt/save goes
#: to drain; comm/h2d inside a dispatch span goes to h2d). dispatch is the
#: outermost catch-all of the attributable stages.
_PRIORITY = {"drain": 6, "h2d": 5, "comm": 4, "ckpt": 3, "prefetch": 2,
             "dispatch": 1}

#: per-window tie-out tolerance: exclusive stage sums may exceed the
#: reconciled window by at most this fraction (the reconciled retro-span is
#: stamped from ``time.time()`` deltas while spans use ``time.monotonic()``
#: — sub-ms skew, never 5%).
TIE_OUT_TOLERANCE = 0.05

#: canonical names/prefixes from the registry (the emit side is pinned to
#: the same file by DS007, so a rename can't silently empty a stage)
_DISPATCH_NAMES = tuple(_NAMES.TRAIN_DISPATCH_NAMES)
_RECONCILE_NAME = _NAMES.TRAIN_RECONCILE_NAME
_DRAIN_NAME = _NAMES.TRAIN_DRAIN_NAME
_H2D_NAME = _NAMES.COMM_H2D_NAME
_OVERLAP_NAME = _NAMES.COMM_OVERLAP_NAME
_COMM_PREFIX = _NAMES.COMM_PREFIX
_CKPT_PREFIX = _NAMES.CKPT_PREFIX
_PREFETCH_PREFIX = _NAMES.PREFETCH_PREFIX

#: sync-mode window synthesis splits at inter-dispatch gaps larger than
#: ``median gap x FACTOR`` (with an absolute floor so a uniform sub-ms
#: loop never fragments): gaps that big are pauses BETWEEN training
#: phases, not step cost.
SYNC_SPLIT_GAP_FACTOR = 10.0
SYNC_SPLIT_GAP_MIN_US = 1_000.0


class PlanError(Exception):
    """Unreadable/empty trace input — maps to CLI exit code 2."""


# ---------------------------------------------------------------------------
# event loading / normalization
# ---------------------------------------------------------------------------
class Ev:
    """One normalized trace event (Chrome-trace microsecond clock).
    ``pid`` carries the rank of a merged cross-rank dump (``dstpu trace
    merge`` keys each source dump's events by pid = rank)."""
    __slots__ = ("name", "cat", "ph", "ts", "dur", "tid", "args", "pid")

    def __init__(self, name, cat, ph, ts, dur, tid, args, pid=None):
        self.name = name
        self.cat = cat
        self.ph = ph
        self.ts = float(ts)
        self.dur = float(dur)
        self.tid = tid
        self.args = args or {}
        self.pid = pid

    @property
    def end(self) -> float:
        return self.ts + self.dur


def events_from_chrome(obj: Any) -> List[Ev]:
    """Normalize a Chrome-trace object (dict with ``traceEvents`` or a bare
    event list) into ``Ev`` records; metadata ("M") events are dropped."""
    if isinstance(obj, dict):
        raw = obj.get("traceEvents")
        if raw is None:
            raise PlanError("not a Chrome trace: no 'traceEvents' key")
    elif isinstance(obj, list):
        raw = obj
    else:
        raise PlanError(f"not a Chrome trace: top-level {type(obj).__name__}")
    out = []
    for e in raw:
        if not isinstance(e, dict) or e.get("ph") == "M":
            continue
        try:
            out.append(Ev(e.get("name", "?"), e.get("cat", ""), e.get("ph"),
                          float(e.get("ts", 0.0)), float(e.get("dur", 0.0)),
                          e.get("tid"), e.get("args"), pid=e.get("pid")))
        except (TypeError, ValueError):
            continue   # malformed row: skip, never die mid-replay
    return out


def load_events(path: str) -> List[Ev]:
    """Load + normalize a dstrace Chrome-trace JSON dump."""
    try:
        with open(path) as f:
            obj = json.load(f)
    except (OSError, ValueError) as e:
        raise PlanError(f"cannot read trace {path}: {e}") from e
    return events_from_chrome(obj)


def events_from_tracer(tracer) -> List[Ev]:
    """Normalize the live tracer ring (``get_tracer()``) — the in-process
    replay path the Autotuner's verification uses."""
    return events_from_chrome(tracer.to_chrome())


def quantile(sorted_vals: List[float], q: float) -> float:
    """Exact sample quantile, same rule everywhere in the repo (serving
    ``_LatencyStat.quantile`` / ``Tracer.summary``): value at index
    ``min(int(q*n), n-1)`` of the sorted samples. Deliberately a local
    copy, NOT an import: this module must load standalone via
    ``bin/dstpu plan``'s file loader on jax-less hosts, so it may import
    nothing from the package; tests/test_plan.py pins the copies equal."""
    if not sorted_vals:
        return 0.0
    return sorted_vals[min(int(q * len(sorted_vals)), len(sorted_vals) - 1)]


# ---------------------------------------------------------------------------
# stage classification + step windows
# ---------------------------------------------------------------------------
def stage_of(name: str, cat: str) -> Optional[str]:
    if name == _DRAIN_NAME:
        return "drain"
    if name == _H2D_NAME:
        return "h2d"
    if name.startswith(_CKPT_PREFIX):
        return "ckpt"
    if name.startswith(_PREFETCH_PREFIX):
        return "prefetch"
    if cat == "comm" or name.startswith(_COMM_PREFIX):
        return "comm"
    if name in _DISPATCH_NAMES:
        return "dispatch"
    return None


def main_track(events: List[Ev]) -> Optional[Any]:
    """The tid that emits the dispatch spans — the train loop's track."""
    counts: Dict[Any, int] = {}
    for e in events:
        if e.ph == "X" and e.name in _DISPATCH_NAMES:
            counts[e.tid] = counts.get(e.tid, 0) + 1
    if not counts:
        return None
    return max(sorted(counts, key=str), key=counts.get)


def step_windows(events: List[Ev]) -> Tuple[List[Dict[str, Any]], str]:
    """The step windows to attribute, plus the trace's mode.

    Async traces carry ``engine/steps_reconciled`` retro-spans: each IS a
    window (the TRUE step time of its drained steps — dispatch spans only
    show launch cost). Sync traces have no reconciled spans; each contiguous
    run of dispatch spans is synthesized into one window (first dispatch
    start -> last dispatch end), so inter-step host work still attributes.
    """
    rec = sorted((e for e in events if e.ph == "X"
                  and e.name == _RECONCILE_NAME),
                 key=lambda e: e.ts)
    if rec:
        wins = []
        for e in rec:
            steps = int(e.args.get("steps", 1) or 1)
            wins.append({"start_us": e.ts, "end_us": e.end, "steps": steps,
                         "last_step": e.args.get("last_step")})
        return wins, "async"
    disp = sorted((e for e in events if e.ph == "X"
                   and e.name in _DISPATCH_NAMES), key=lambda e: e.ts)
    if not disp:
        raise PlanError("no step spans in trace (engine/steps_reconciled, "
                        "engine/dispatch, engine/train_step all absent) — "
                        "was the run traced with DSTPU_TRACE?")
    # contiguous runs only: an inter-dispatch gap much larger than the
    # loop's typical cadence (an eval phase, a pause, untraced work between
    # loops) starts a NEW window, so the idle time never inflates any
    # window's residual or the per-step quantiles the baseline ratchets
    gaps = sorted(max(b.ts - a.end, 0.0) for a, b in zip(disp, disp[1:]))
    med_gap = gaps[len(gaps) // 2] if gaps else 0.0
    cut = max(med_gap * SYNC_SPLIT_GAP_FACTOR, SYNC_SPLIT_GAP_MIN_US)
    runs = [[disp[0]]]
    for prev, cur in zip(disp, disp[1:]):
        if cur.ts - prev.end > cut:
            runs.append([])
        runs[-1].append(cur)
    return [{"start_us": r[0].ts, "end_us": r[-1].end, "steps": len(r),
             "last_step": r[-1].args.get("step")} for r in runs], "sync"


def _exclusive_sweep(intervals: List[Tuple[float, float, str]],
                     w0: float, w1: float) -> Dict[str, float]:
    """Exclusive per-stage time over [w0, w1]: at every instant the
    highest-priority covering interval owns it. Intervals are pre-clipped.
    O(points x intervals) — windows hold tens of spans, not thousands."""
    out = {s: 0.0 for s in STAGES if s != "residual"}
    if not intervals:
        return out
    pts = sorted({w0, w1, *(i[0] for i in intervals),
                  *(i[1] for i in intervals)})
    for a, b in zip(pts, pts[1:]):
        if b <= a:
            continue
        mid = (a + b) / 2.0
        best = None
        for s, e, stage in intervals:
            if s <= mid < e and (best is None
                                 or _PRIORITY[stage] > _PRIORITY[best]):
                best = stage
        if best is not None:
            out[best] += b - a
    return out


def _union(intervals: List[Tuple[float, float]]) -> float:
    total, cur_s, cur_e = 0.0, None, None
    for s, e in sorted(intervals):
        if cur_e is None or s > cur_e:
            if cur_e is not None:
                total += cur_e - cur_s
            cur_s, cur_e = s, e
        else:
            cur_e = max(cur_e, e)
    if cur_e is not None:
        total += cur_e - cur_s
    return total


# ---------------------------------------------------------------------------
# attribution
# ---------------------------------------------------------------------------
def attribute(events: List[Ev], source: str = "<events>",
              merged_ranks: Optional[Dict[Any, int]] = None
              ) -> Dict[str, Any]:
    """Replay a trace into the plan report: per-window exclusive stage
    ledger (ties out to the window within ``TIE_OUT_TOLERANCE``), aggregate
    per-step quantiles, comm rollups, observed config, and proposals.

    ``merged_ranks`` (pid -> rank, from a ``dstpu trace merge`` dump)
    switches to the cross-rank form: the top-level ledger attributes the
    REFERENCE rank's timeline (mixing N ranks' dispatch spans into one
    window sweep would attribute nothing meaningful) and a ``cross_rank``
    section carries every rank's per-stage ledger plus the cross-rank
    variance — which stage's cost diverges across ranks is exactly the
    load-imbalance signal the skew ledger's waits trace back to."""
    if merged_ranks and len(set(merged_ranks.values())) > 1:
        return _attribute_merged(events, source, merged_ranks)
    windows, mode = step_windows(events)
    track = main_track(events)
    spans = [e for e in events if e.ph == "X"]
    ledger = []
    for i, w in enumerate(windows):
        w0, w1 = w["start_us"], w["end_us"]
        on_track, off_track = [], []
        for e in spans:
            if e.name == _RECONCILE_NAME:
                continue
            st = stage_of(e.name, e.cat)
            if st is None or e.end <= w0 or e.ts >= w1:
                continue
            clipped = (max(e.ts, w0), min(e.end, w1))
            if track is None or e.tid == track:
                on_track.append((clipped[0], clipped[1], st))
            else:
                off_track.append((clipped[0], clipped[1], st))
        excl = _exclusive_sweep(on_track, w0, w1)
        dur = w1 - w0
        attributed = sum(excl.values())
        residual = dur - attributed
        # overlapped (informational, NOT in the exclusive sum): work other
        # threads did under this window — the prefetch worker's staging is
        # the latency hiding working as designed, not step cost
        overlapped: Dict[str, float] = {}
        for st in set(s for _, _, s in off_track):
            overlapped[st] = _union([(a, b) for a, b, s in off_track
                                     if s == st])
        stages_us = {s: excl.get(s, 0.0) for s in STAGES if s != "residual"}
        stages_us["residual"] = max(residual, 0.0)
        ledger.append({
            "index": i,
            "start_us": round(w0, 3),
            "dur_us": round(dur, 3),
            "steps": w["steps"],
            "last_step": w["last_step"],
            "stages_us": {k: round(v, 3) for k, v in stages_us.items()},
            "overlapped_us": {k: round(v, 3)
                              for k, v in sorted(overlapped.items())},
            # tie-out proof: attributed time never exceeds the window
            # beyond clock skew; residual is the exact remainder
            "tie_out_error": round(max(attributed - dur, 0.0)
                                   / dur if dur > 0 else 0.0, 6),
        })
    total_us = sum(w["dur_us"] for w in ledger) or 1.0
    steps_total = sum(w["steps"] for w in ledger)
    aggregate: Dict[str, Dict[str, float]] = {}
    for s in STAGES:
        per_step_ms = sorted((w["stages_us"][s] / w["steps"]) / 1e3
                             for w in ledger)
        total_stage = sum(w["stages_us"][s] for w in ledger)
        aggregate[s] = {
            "total_ms": round(total_stage / 1e3, 3),
            "share": round(total_stage / total_us, 4),
            "mean_step_ms": round(sum(per_step_ms) / len(per_step_ms), 4),
            "p50_step_ms": round(quantile(per_step_ms, 0.5), 4),
            "p95_step_ms": round(quantile(per_step_ms, 0.95), 4),
            "p99_step_ms": round(quantile(per_step_ms, 0.99), 4),
        }
    report = {
        "version": PLAN_VERSION,
        "source": source,
        "mode": mode,
        "windows": ledger,
        "steps_total": steps_total,
        "window_ms_total": round(total_us / 1e3, 3),
        "step_ms_p50": round(quantile(
            sorted(w["dur_us"] / w["steps"] / 1e3 for w in ledger), 0.5), 4),
        "aggregate": aggregate,
        "comm": comm_rollup(events),
        "comm_overlap": comm_overlap_rollup(ledger),
        "config_observed": observed_config(events, windows, mode),
        "memory": memory_observed(events),
    }
    report["proposals"] = propose(report)
    return report


def _attribute_merged(events: List[Ev], source: str,
                      merged_ranks: Dict[Any, int]) -> Dict[str, Any]:
    """The cross-rank form of ``attribute``: reference-rank ledger +
    per-rank stage ledgers + per-stage cross-rank variance."""
    by_rank: Dict[int, List[Ev]] = {}
    for e in events:
        rank = merged_ranks.get(e.pid)
        if rank is not None:
            by_rank.setdefault(rank, []).append(e)
    # ONE attribution pass per rank; the reference (top-level) ledger is
    # the lowest rank that actually carries step spans — a serving-only
    # rank 0 must not kill the whole replay
    reps: Dict[int, Dict[str, Any]] = {}
    for rank in sorted(by_rank):
        try:
            reps[rank] = attribute(by_rank[rank],
                                   source=f"{source}#rank{rank}")
        except PlanError:
            continue          # a rank with no step spans (serving-only...)
    if not reps:
        raise PlanError(f"no rank in {source} carries step spans "
                        "(engine/steps_reconciled, engine/dispatch, "
                        "engine/train_step all absent on every rank) — "
                        "use `dstpu plan --cross-rank` for comm-only "
                        "merged dumps")
    ref = min(reps)
    report = dict(reps[ref])
    report["source"] = source
    per_rank: Dict[str, Any] = {}
    stage_p50s: Dict[str, Dict[int, float]] = {s: {} for s in STAGES}
    for rank, rep in sorted(reps.items()):
        per_rank[str(rank)] = {
            "steps_total": rep["steps_total"],
            "step_ms_p50": rep["step_ms_p50"],
            "stages": {s: {"p50_step_ms": rep["aggregate"][s]["p50_step_ms"],
                           "share": rep["aggregate"][s]["share"]}
                       for s in STAGES},
        }
        for s in STAGES:
            stage_p50s[s][rank] = rep["aggregate"][s]["p50_step_ms"]
    variance: Dict[str, Any] = {}
    for s in STAGES:
        vals = stage_p50s[s]
        if len(vals) < 2:
            continue
        lo_rank = min(sorted(vals), key=lambda r: vals[r])
        hi_rank = max(sorted(vals), key=lambda r: vals[r])
        variance[s] = {
            "p50_step_ms_min": vals[lo_rank],
            "p50_step_ms_max": vals[hi_rank],
            "spread_ms": round(vals[hi_rank] - vals[lo_rank], 4),
            "slowest_rank": hi_rank,
        }
    report["cross_rank"] = {
        "ranks": sorted(by_rank),
        "reference_rank": ref,
        "per_rank": per_rank,
        "variance": variance,
    }
    return report


def comm_rollup(events: List[Ev]) -> Dict[str, Dict[str, Any]]:
    """Per-op comm volume/bandwidth rollup over the whole trace, keyed
    ``op@world`` (world size is the mesh-axis span the collective ran
    over). Spans carry measured algbw/busbw; in-jit instants carry only
    analytic bytes — both count toward volume. ``wire_bytes`` defaults to
    the logical bytes for pre-compression traces; compressed collectives
    record the codes+scales payload, so ``compression`` = logical/wire is
    exactly the wire saving the comm_compression group bought."""
    out: Dict[str, Dict[str, Any]] = {}
    for e in events:
        if not (e.cat == "comm" or e.name.startswith(_COMM_PREFIX)):
            continue
        # h2d is staging (its own stage), comm/overlap the analytic
        # schedule track — neither is collective volume
        if e.name in (_H2D_NAME, _OVERLAP_NAME) or "bytes" not in e.args:
            continue
        op = e.name[len(_COMM_PREFIX):] \
        if e.name.startswith(_COMM_PREFIX) else e.name
        world = e.args.get("world", 1)
        key = f"{op}@{world}"
        rec = out.setdefault(key, {"op": op, "world": world,
                                   "kind": e.args.get("kind")
                                   or _OP_KIND_FALLBACK.get(op),
                                   "count": 0,
                                   "bytes": 0, "wire_bytes": 0, "timed": 0,
                                   "algbw_gbps_sum": 0.0,
                                   "busbw_gbps_sum": 0.0})
        rec["count"] += 1
        nbytes = int(e.args.get("bytes", 0) or 0)
        rec["bytes"] += nbytes
        rec["wire_bytes"] += int(e.args.get("wire_bytes", nbytes) or 0)
        if e.ph == "X" and "algbw_gbps" in e.args:
            rec["timed"] += 1
            rec["algbw_gbps_sum"] += float(e.args["algbw_gbps"])
            rec["busbw_gbps_sum"] += float(e.args["busbw_gbps"])
    for rec in out.values():
        n = rec.pop("timed")
        rec["algbw_gbps_mean"] = round(rec.pop("algbw_gbps_sum") / n, 3) \
            if n else None
        rec["busbw_gbps_mean"] = round(rec.pop("busbw_gbps_sum") / n, 3) \
            if n else None
        rec["compression"] = round(rec["bytes"] / rec["wire_bytes"], 3) \
            if rec["wire_bytes"] else None
    return dict(sorted(out.items()))


def comm_overlap_rollup(ledger: List[Dict[str, Any]]) -> Dict[str, Any]:
    """Comm overlap attribution from the per-window ledger: the
    ``comm/overlap`` track rides its own synthetic tid, so its
    window-clipped time lands in each window's ``overlapped_us["comm"]``
    (the prefetch-worker treatment). ``overlap_fraction`` =
    comm/overlap-track time ∩ step windows / total comm time (overlapped +
    on-main-track exclusive).

    Reading it: in a fully-jit training trace every collective is an
    instant (XLA schedules it inside the dispatched step), so on-track
    comm is 0 and the fraction reads 1.0 — the truthful statement that
    ALL comm rides inside the step. It becomes a tuning signal when
    eager main-track comm exists (checkpoint scatter, host-driven
    broadcasts): time those ops spend blocking the main track pulls the
    fraction below 1. The knob-sensitive counters for the bucket schedule
    itself are the per-bucket wire bytes and span count in the rollup."""
    overlap_us = sum(w["overlapped_us"].get("comm", 0.0) for w in ledger)
    on_track_us = sum(w["stages_us"].get("comm", 0.0) for w in ledger)
    total = overlap_us + on_track_us
    return {
        "overlap_us": round(overlap_us, 3),
        "on_track_us": round(on_track_us, 3),
        "total_comm_us": round(total, 3),
        "overlap_fraction": round(overlap_us / total, 4) if total else None,
    }


#: dsmem counter names (must match telemetry/memory.py — a literal, not an
#: import: this module loads standalone by contract)
_MEM_IN_USE = _NAMES.HBM_IN_USE_COUNTER
_MEM_PEAK = _NAMES.HBM_PEAK_COUNTER
_MEM_LIMIT = _NAMES.HBM_LIMIT_COUNTER


def memory_observed(events: List[Ev]) -> Optional[Dict[str, Any]]:
    """The dsmem HBM counter tracks, rolled up per device: peak bytes in
    use, the device limit, and the headroom fraction — the memory input to
    the proposal rule table (a trace that carries memory counters makes
    its own case for raising micro_batch or escalating the offload
    tier). None when the trace has no memory tracks (untraced or a
    backend without allocator stats)."""
    devices: Dict[str, Dict[str, float]] = {}
    for e in events:
        if e.ph != "C" or not e.args:
            continue
        if e.name not in (_MEM_IN_USE, _MEM_PEAK, _MEM_LIMIT):
            continue
        for dev, val in e.args.items():
            try:
                v = float(val)
            except (TypeError, ValueError):
                continue
            d = devices.setdefault(dev, {"peak_bytes_in_use": 0.0,
                                         "bytes_limit": 0.0})
            if e.name == _MEM_LIMIT:
                d["bytes_limit"] = max(d["bytes_limit"], v)
            else:          # in-use samples fold into the observed peak too
                d["peak_bytes_in_use"] = max(d["peak_bytes_in_use"], v)
    if not devices:
        return None
    out: Dict[str, Any] = {"devices": {}}
    headrooms = []
    for dev, d in sorted(devices.items()):
        row = {"peak_bytes_in_use": int(d["peak_bytes_in_use"]),
               "bytes_limit": int(d["bytes_limit"]),
               "headroom_frac": None}
        if d["bytes_limit"] > 0:
            row["headroom_frac"] = round(
                1.0 - d["peak_bytes_in_use"] / d["bytes_limit"], 4)
            headrooms.append(row["headroom_frac"])
        out["devices"][dev] = row
    out["min_headroom_frac"] = min(headrooms) if headrooms else None
    return out


def observed_config(events: List[Ev], windows: List[Dict[str, Any]],
                    mode: str) -> Dict[str, Any]:
    """The async-pipeline config the trace itself reveals — what `plan`
    proposes *against* (never trusts a config file that may have drifted
    from the run)."""
    drains = [e for e in events if e.ph == "X" and e.name == _DRAIN_NAME]
    sync_every = None
    if mode == "async" and drains:
        per = [int(e.args.get("steps", 0) or 0) for e in drains]
        per = [p for p in per if p > 0]
        if per:
            sync_every = max(per)   # flushes shorten windows; cadence = max
    prefetch = any(e.name.startswith(_PREFETCH_PREFIX) for e in events)
    return {"mode": mode, "sync_every": sync_every, "prefetch": prefetch,
            "transfers_observed": len(drains) if mode == "async" else
            sum(w["steps"] for w in windows)}


# ---------------------------------------------------------------------------
# proposals: dominant stage -> config override with a predicted win
# ---------------------------------------------------------------------------
#: minimum share of traced step time a stage needs before its rule fires
_SHARE_FLOOR = {"dispatch": 0.25, "drain": 0.20, "h2d": 0.15, "comm": 0.20,
                "ckpt": 0.15, "prefetch": 0.15, "residual": 0.60}

#: comm-compression wire model for the proposal prediction. Deliberately a
#: local copy of ``comm.compress.wire_payload_bytes`` at the default int8 /
#: chunk=256 config, NOT an import (standalone-load contract — this module
#: must file-load on jax-less hosts); tests pin the copies equal.
_WIRE_CHUNK = 256

#: the op kinds the comm_compression layer can actually compress (gradient
#: reduction family); param all-gathers and MoE dispatch are NOT on this
#: list — proposing compression against their volume would predict savings
#: the knob cannot deliver. Literal (standalone-load contract; pre-`kind`
#: traces classify by these exact names).
_COMPRESSIBLE_KINDS = ("all_reduce", "reduce_scatter")
_OP_KIND_FALLBACK = {"all_reduce": "all_reduce",
                     "reduce_scatter": "reduce_scatter"}


def _predicted_wire_bytes(logical_bytes: int, itemsize: int = 4) -> int:
    n = logical_bytes // itemsize
    return n + 4 * math.ceil(n / _WIRE_CHUNK)


def propose(report: Dict[str, Any]) -> List[Dict[str, Any]]:
    """The rule table: each entry maps a dominant stage to ONE concrete
    config override plus a prediction the Autotuner can execute and verify
    (docs/observability.md carries the prose version). Deterministic:
    ordered by stage share, ties by rule id."""
    agg = report["aggregate"]
    cfg = report["config_observed"]
    steps = max(report["steps_total"], 1)
    props: List[Dict[str, Any]] = []

    def share(stage):
        return agg[stage]["share"]

    # sync-mode per-step readback -> async pipeline. Prediction: readback
    # transfers drop N -> ceil(N / sync_every), countable as engine/drain
    # spans in the verifying run's trace.
    if cfg["mode"] == "sync" and share("dispatch") >= _SHARE_FLOOR["dispatch"]:
        se = 8
        props.append({
            "id": "enable_async_pipeline",
            "stage": "dispatch",
            "share": share("dispatch"),
            "knob": "sync_every",
            "overrides": {"async_pipeline": {"enabled": True,
                                             "sync_every": se}},
            "reason": f"sync-mode dispatch is {share('dispatch'):.0%} of "
                      "step time: per-step readback serializes host and "
                      "device — defer it behind the async ring",
            "predicted": {
                "metric": "readback_transfers",
                "sync_every": se,
                "baseline_sync_every": 1,     # sync: a transfer per step
                "per_steps": steps,
                "current": steps,
                "proposed": math.ceil(steps / se),
            },
        })
    # async but draining too often -> double the cadence. Same countable
    # prediction, halved transfers.
    elif cfg["mode"] == "async" and share("drain") >= _SHARE_FLOOR["drain"] \
            and (cfg["sync_every"] or 1) < 64:
        cur = max(int(cfg["sync_every"] or 1), 1)
        se = cur * 2
        props.append({
            "id": "raise_sync_every",
            "stage": "drain",
            "share": share("drain"),
            "knob": "sync_every",
            "overrides": {"async_pipeline": {"enabled": True,
                                             "sync_every": se}},
            "reason": f"drain/host-sync is {share('drain'):.0%} of step "
                      f"time at sync_every={cur}: halve the drain count",
            "predicted": {
                "metric": "readback_transfers",
                "sync_every": se,
                "baseline_sync_every": cur,
                "per_steps": steps,
                "current": math.ceil(steps / cur),
                "proposed": math.ceil(steps / se),
            },
        })
    if share("h2d") >= _SHARE_FLOOR["h2d"] and not cfg["prefetch"]:
        props.append({
            "id": "enable_prefetch",
            "stage": "h2d",
            "share": share("h2d"),
            "knob": "prefetch",
            "overrides": {"async_pipeline": {"enabled": True,
                                             "prefetch": True}},
            "reason": f"inline h2d staging is {share('h2d'):.0%} of step "
                      "time with no prefetch worker in the trace: stage "
                      "batch N+1 during batch N's compute",
            "predicted": {
                "metric": "h2d_off_main_track",
                "current_main_track_ms": agg["h2d"]["total_ms"],
                "proposed_main_track_ms": 0.0,
            },
        })
    if share("prefetch") >= _SHARE_FLOOR["prefetch"]:
        props.append({
            "id": "raise_prefetch_depth",
            "stage": "prefetch",
            "share": share("prefetch"),
            "knob": "prefetch_depth",
            "overrides": {"async_pipeline": {"enabled": True,
                                             "prefetch": True,
                                             "prefetch_depth": 4}},
            "reason": f"main-track prefetch stall is "
                      f"{share('prefetch'):.0%} of step time: the worker "
                      "can't stay ahead — deepen the staging buffer",
            "predicted": {"metric": "prefetch_stall_share",
                          "current": share("prefetch"), "proposed": 0.0},
        })
    if share("comm") >= _SHARE_FLOOR["comm"]:
        roll = report.get("comm") or {}
        # only the gradient-reduction family is compressible: the proposal
        # predicts against THAT volume, and never fires when the dominant
        # comm is param gathers / dispatch the knob cannot touch
        comp_rows = [r for r in roll.values()
                     if r.get("kind") in _COMPRESSIBLE_KINDS]
        logical = sum(int(r.get("bytes", 0)) for r in comp_rows)
        wire = sum(int(r.get("wire_bytes", r.get("bytes", 0)))
                   for r in comp_rows)
        if comp_rows and wire >= logical and logical > 0:
            # dominant comm stage with NOTHING compressed on the wire:
            # enable the comm_compression group. The prediction is an
            # analytic FLOOR on the verifying run's wire-byte counter
            # (int8 codes + fp32 per-chunk scales over the total volume as
            # one payload; per-call padding to world*chunk adds a bounded
            # overhead on top — the formula is compress.wire_payload_bytes,
            # copied here by the standalone-load contract and pinned equal
            # by tests).
            props.append({
                "id": "enable_comm_compression",
                "stage": "comm",
                "share": share("comm"),
                "knob": "comm_compression",
                "overrides": {"comm_compression": {"enabled": True}},
                "reason": f"comm is {share('comm'):.0%} of step time and "
                          "every collective moves full-width bytes: "
                          "quantize the wire (int8 codes + per-chunk "
                          "scales, error feedback keeps numerics)",
                "predicted": {
                    "metric": "wire_bytes",
                    "current": logical,
                    "proposed": _predicted_wire_bytes(logical),
                    # advisory floor: per-call padding to world*chunk means
                    # the observed counter lands at or slightly above this
                    "bound": "floor",
                },
            })
        else:
            props.append({
                "id": "raise_gas",
                "stage": "comm",
                "share": share("comm"),
                "knob": "gas",
                "overrides": {"gradient_accumulation_steps": 2},
                "reason": f"comm is {share('comm'):.0%} of step time: "
                          "accumulate more microbatches per optimizer sync "
                          "so each gradient reduction amortizes over more "
                          "tokens",
                "predicted": {"metric": "comm_ops_per_sample",
                              "current": 1.0, "proposed": 0.5},
            })
    if share("ckpt") >= _SHARE_FLOOR["ckpt"]:
        props.append({
            "id": "relax_ckpt_cadence",
            "stage": "ckpt",
            "share": share("ckpt"),
            "knob": "checkpoint_cadence",
            "overrides": {},    # advisory: cadence lives in the runner
            "reason": f"checkpoint I/O is {share('ckpt'):.0%} of step "
                      "time: halve the save cadence (or move saves to the "
                      "host-RAM tier) — resilience costs a bounded replay, "
                      "not every step",
            "predicted": {"metric": "ckpt_share",
                          "current": share("ckpt"),
                          "proposed": share("ckpt") / 2},
        })
    mem = report.get("memory") or {}
    headroom = mem.get("min_headroom_frac")
    if share("residual") >= _SHARE_FLOOR["residual"] \
            and cfg["mode"] == "sync" \
            and (headroom is None or headroom >= 0.10):
        # the dsmem counter tracks turn "toward the HBM ceiling" from a
        # guess into a number; under 10% observed headroom the rule yields
        # to raise_offload_tier below instead of proposing an OOM
        head_txt = "" if headroom is None else (
            f" (dsmem observed {headroom:.0%} HBM headroom)")
        props.append({
            "id": "raise_micro_batch",
            "stage": "residual",
            "share": share("residual"),
            "knob": "micro_batch",
            "overrides": {},    # advisory: the absolute mbs is model-bound
            "reason": f"unattributed residual is {share('residual'):.0%} "
                      "of a sync-mode window: the step is device-bound — "
                      "raise micro_batch toward the HBM ceiling"
                      f"{head_txt}, or drop zero_stage / the offload tier "
                      "if state headroom allows (run the Autotuner sweep)",
            "predicted": {"metric": "mfu", "current": None,
                          "proposed": None,
                          "hbm_headroom_frac": headroom},
        })
    if headroom is not None and headroom < 0.05:
        # memory, not time, is the binding constraint: the run finished
        # within 5% of the device limit — the next perturbation (longer
        # seq, one more request, a fragmentation spike) is an OOM. Escalate
        # the offload ladder one rung (`dstpu mem --preflight` on the
        # config names the exact tier).
        props.append({
            "id": "raise_offload_tier",
            "stage": "memory",
            "share": round(1.0 - headroom, 4),
            "knob": "offload_optimizer",
            "overrides": {"zero_optimization": {
                "offload_optimizer": {"device": "cpu"}}},
            "reason": f"observed HBM peak is within {headroom:.1%} of the "
                      "device limit: offload optimizer state to host RAM "
                      "before the next run OOMs (verify the exact tier "
                      "with `dstpu mem --preflight`)",
            "predicted": {"metric": "hbm_headroom_frac",
                          "current": headroom, "proposed": None},
        })
    props.sort(key=lambda p: (-p["share"], p["id"]))
    return props


# ---------------------------------------------------------------------------
# regression baseline (dslint ratchet idiom)
# ---------------------------------------------------------------------------
def load_plan_baseline(path: str) -> dict:
    with open(path) as f:
        data = json.load(f)
    if data.get("version") != PLAN_BASELINE_VERSION:
        raise ValueError(f"unsupported plan baseline version "
                         f"{data.get('version')!r} in {path} "
                         f"(expected {PLAN_BASELINE_VERSION})")
    return data


def find_plan_baseline(start: str) -> Optional[str]:
    """Walk up from ``start`` looking for the checked-in plan baseline
    (same discovery rule as dslint's)."""
    d = os.path.abspath(start)
    if os.path.isfile(d):
        d = os.path.dirname(d)
    while True:
        cand = os.path.join(d, PLAN_BASELINE_NAME)
        if os.path.exists(cand):
            return cand
        parent = os.path.dirname(d)
        if parent == d:
            return None
        d = parent


def write_plan_baseline(path: str, report: Dict[str, Any],
                        tolerance: float = 2.0,
                        min_abs_ms: float = 0.05) -> dict:
    """Record the report's per-stage quantiles as the new baseline. The
    ``workload`` tag (the trace's basename) scopes DISCOVERED baselines:
    auto-discovery only compares traces of the same workload, so a real
    run's trace saved inside the repo never gets judged against the
    micro-fixture baseline (explicit ``--baseline`` always compares)."""
    data = {
        "version": PLAN_BASELINE_VERSION,
        "workload": os.path.basename(str(report.get("source", ""))),
        "tolerance": float(tolerance),
        "min_abs_ms": float(min_abs_ms),
        "entries": {
            s: {"p50_step_ms": report["aggregate"][s]["p50_step_ms"],
                "p95_step_ms": report["aggregate"][s]["p95_step_ms"]}
            for s in STAGES},
    }
    with open(path, "w") as f:
        json.dump(data, f, indent=2, sort_keys=True)
        f.write("\n")
    return data


def check_baseline(report: Dict[str, Any], baseline: dict,
                   tolerance: Optional[float] = None
                   ) -> Tuple[List[dict], List[dict]]:
    """(regressions, stale). A stage REGRESSES when its current p50
    per-step ms exceeds baseline * tolerance AND by more than the absolute
    floor (sub-floor stages are noise, not signal, on a 2-core host). A
    baseline entry is STALE when the stage improved past the same margin —
    expire it with ``--write-baseline`` so the win is locked in (the dslint
    ratchet: fixed findings must not silently shield a future regression).
    ``tolerance`` overrides the factor stored in the baseline (the CLI's
    ``--tolerance``).
    """
    tol = float(tolerance if tolerance is not None
                else baseline.get("tolerance", 2.0))
    floor = float(baseline.get("min_abs_ms", 0.05))
    regressions, stale = [], []
    for stage, entry in sorted(baseline.get("entries", {}).items()):
        agg = report["aggregate"].get(stage)
        if agg is None:
            continue
        for metric in ("p50_step_ms", "p95_step_ms"):
            base = float(entry.get(metric, 0.0))
            cur = float(agg[metric])
            row = {"stage": stage, "metric": metric, "baseline_ms": base,
                   "current_ms": cur,
                   "ratio": round(cur / base, 3) if base > 0 else None}
            if cur > base * tol and (cur - base) > floor:
                regressions.append(row)
            elif base > cur * tol and (base - cur) > floor:
                stale.append(row)
    return regressions, stale


# ---------------------------------------------------------------------------
# rendering + CLI
# ---------------------------------------------------------------------------
def render(report: Dict[str, Any], top_windows: int = 8) -> str:
    out = []
    cfg = report["config_observed"]
    out.append(f"dstpu plan — {report['source']}")
    out.append(f"mode={cfg['mode']} sync_every={cfg['sync_every']} "
               f"prefetch={cfg['prefetch']} | "
               f"{report['steps_total']} steps over "
               f"{len(report['windows'])} windows, "
               f"{report['window_ms_total']:.1f} ms traced step time, "
               f"p50 step {report['step_ms_p50']:.3f} ms")
    out.append("")
    hdr = f"{'win':>4} {'steps':>5} {'ms':>9}"
    for s in STAGES:
        hdr += f" {s[:8]:>9}"
    out.append(hdr + "   tie-out")
    out.append("-" * len(hdr))
    for w in report["windows"][:top_windows]:
        row = f"{w['index']:>4} {w['steps']:>5} {w['dur_us'] / 1e3:>9.2f}"
        for s in STAGES:
            row += f" {w['stages_us'][s] / 1e3:>9.3f}"
        row += f"   {w['tie_out_error'] * 100:.2f}%"
        out.append(row)
    if len(report["windows"]) > top_windows:
        out.append(f"... {len(report['windows']) - top_windows} more "
                   "windows (--top N)")
    out.append("")
    out.append(f"{'stage':<10} {'share':>7} {'p50/step':>10} {'p95/step':>10}"
               f" {'p99/step':>10}")
    out.append("-" * 51)
    for s in STAGES:
        a = report["aggregate"][s]
        out.append(f"{s:<10} {a['share'] * 100:>6.1f}% "
                   f"{a['p50_step_ms']:>9.3f}ms {a['p95_step_ms']:>9.3f}ms "
                   f"{a['p99_step_ms']:>9.3f}ms")
    if report["comm"]:
        out.append("")
        out.append("comm rollup (op@world: count, MB logical -> MB wire, "
                   "mean algbw/busbw GB/s)")
        for key, r in report["comm"].items():
            bw = "analytic (in-jit)" if r["algbw_gbps_mean"] is None else \
                f"{r['algbw_gbps_mean']:.2f}/{r['busbw_gbps_mean']:.2f}"
            wire = f"{r.get('wire_bytes', r['bytes']) / 1e6:>9.2f}"
            comp = r.get("compression")
            comp_txt = f" ({comp:.2f}x)" if comp and comp > 1.0 else ""
            out.append(f"  {key:<28} {r['count']:>6} {r['bytes'] / 1e6:>9.2f}"
                       f" -> {wire}{comp_txt} {bw}")
        co = report.get("comm_overlap") or {}
        if co.get("overlap_fraction") is not None:
            out.append(f"  comm overlap: {co['overlap_us'] / 1e3:.3f}ms of "
                       f"{co['total_comm_us'] / 1e3:.3f}ms comm overlapped "
                       f"({co['overlap_fraction']:.0%})")
    if report.get("memory"):
        out.append("")
        out.append("memory (dsmem counter tracks: peak in-use / limit / "
                   "headroom)")
        for dev, d in report["memory"]["devices"].items():
            head = "-" if d["headroom_frac"] is None \
                else f"{d['headroom_frac'] * 100:.1f}%"
            out.append(f"  {dev:<28} {d['peak_bytes_in_use'] / 1e9:>7.2f}GB"
                       f" {d['bytes_limit'] / 1e9:>7.2f}GB {head:>7}")
    out.append("")
    if report["proposals"]:
        out.append("proposals (dominant stage -> config override):")
        for p in report["proposals"]:
            out.append(f"  [{p['id']}] {p['reason']}")
            if p["overrides"]:
                out.append(f"      overrides: {json.dumps(p['overrides'])}")
            pred = p["predicted"]
            if pred.get("metric") == "readback_transfers":
                out.append(f"      predicted: {pred['current']} -> "
                           f"{pred['proposed']} readback transfers per "
                           f"{pred['per_steps']} steps (verify with "
                           f"Autotuner(plan=...))")
    else:
        out.append("proposals: none — no stage clears its share floor "
                   "(the step spends its time on attributed, already-"
                   "pipelined work)")
    return "\n".join(out)


def analyze_path(trace_path: str) -> Dict[str, Any]:
    """Load + attribute in one call (the API tests and env_report use).
    A merged cross-rank dump (``dstpu trace merge`` output, detected by
    its ``otherData.crossrank`` block) gets the per-rank ledger form."""
    try:
        with open(trace_path) as f:
            obj = json.load(f)
    except (OSError, ValueError) as e:
        raise PlanError(f"cannot read trace {trace_path}: {e}") from e
    merged_ranks = None
    if isinstance(obj, dict):
        cr = (obj.get("otherData") or {}).get("crossrank")
        if cr and cr.get("ranks"):
            # merge contract: each source dump's events carry pid == rank
            merged_ranks = {int(r): int(r) for r in cr["ranks"]}
    return attribute(events_from_chrome(obj), source=trace_path,
                     merged_ranks=merged_ranks)


def main(argv=None) -> int:
    parser = argparse.ArgumentParser(
        prog="dstpu plan",
        description="trace-driven step-time attribution, regression ledger "
                    "and profile-guided config proposals (produce a trace "
                    "with DSTPU_TRACE=trace.json or engine.dump_trace)")
    parser.add_argument("trace", help="dstrace Chrome-trace JSON dump")
    parser.add_argument("--baseline", default=None,
                        help=f"plan baseline path (default: walk up from "
                             f"the trace for {PLAN_BASELINE_NAME})")
    parser.add_argument("--write-baseline", action="store_true",
                        help="record this report as the new baseline "
                             "(ratchet: also how stale entries expire)")
    parser.add_argument("--tolerance", type=float, default=None,
                        help="regression factor vs baseline (default: the "
                             "factor stored in the baseline, 2.0 when "
                             "writing a fresh one)")
    parser.add_argument("--out", default=None,
                        help="write the full plan artifact JSON here "
                             f"(env_report reads ${PLAN_ARTIFACT_ENV} or "
                             f"./{DEFAULT_PLAN_ARTIFACT})")
    parser.add_argument("--json", action="store_true",
                        help="print the report as JSON instead of a table")
    parser.add_argument("--top", type=int, default=8,
                        help="ledger windows to show (default 8)")
    args = parser.parse_args(argv)

    try:
        report = analyze_path(args.trace)
    except PlanError as e:
        print(f"dstpu plan: {e}", file=sys.stderr)
        return EXIT_UNREADABLE

    # discovery anchors at the TRACE path (dslint walks up from the linted
    # files, same idea): a trace outside the repo is a different workload —
    # comparing it against the checked-in fixture baseline would flag
    # meaningless "regressions"; pass --baseline to compare anyway
    bl_path = args.baseline or find_plan_baseline(args.trace)
    regressions, stale = [], []
    effective_tol = args.tolerance if args.tolerance is not None else 2.0
    if args.write_baseline:
        trace_dir = os.path.dirname(os.path.abspath(args.trace))
        target = bl_path or os.path.join(trace_dir, PLAN_BASELINE_NAME)
        if args.baseline is None and os.path.exists(target):
            try:    # never clobber a DISCOVERED baseline of another
                existing_wl = load_plan_baseline(target).get("workload")
            except (OSError, ValueError):
                existing_wl = None
            if existing_wl and existing_wl != os.path.basename(args.trace):
                redirected = os.path.join(trace_dir, PLAN_BASELINE_NAME)
                if os.path.abspath(redirected) == os.path.abspath(target):
                    # nowhere safe to redirect: the other workload's
                    # baseline lives right next to this trace
                    print(f"# refusing --write-baseline: {target} "
                          f"ratchets workload {existing_wl!r} — pass "
                          "--baseline PATH to overwrite it deliberately "
                          "or to name a new file", file=sys.stderr)
                    target = None
                else:
                    print(f"# note: {target} ratchets workload "
                          f"{existing_wl!r} — starting this workload's "
                          f"baseline at {redirected} instead (pass "
                          "--baseline to overwrite deliberately)",
                          file=sys.stderr)
                    target = redirected
        if target is not None:
            if args.tolerance is None and os.path.exists(target):
                try:    # ratchet rewrite: keep the factor the team chose
                    effective_tol = float(load_plan_baseline(target)
                                          .get("tolerance", 2.0))
                except (OSError, ValueError):
                    pass
            write_plan_baseline(target, report, tolerance=effective_tol)
            print(f"# plan baseline written -> {target}", file=sys.stderr)
        bl_path = target
    elif bl_path:
        try:
            baseline = load_plan_baseline(bl_path)
        except (OSError, ValueError) as e:
            print(f"dstpu plan: bad baseline {bl_path}: {e}",
                  file=sys.stderr)
            return EXIT_UNREADABLE
        bl_workload = baseline.get("workload")
        trace_workload = os.path.basename(args.trace)
        if args.baseline is None and bl_workload \
                and bl_workload != trace_workload:
            # discovered, different workload: its quantiles say nothing
            # about this trace — note it instead of fabricating a verdict
            print(f"# note: discovered baseline {bl_path} is for workload "
                  f"{bl_workload!r}, not {trace_workload!r} — comparison "
                  "skipped (pass --baseline to compare anyway, or "
                  "--write-baseline to start ratcheting this workload)",
                  file=sys.stderr)
            bl_path = None
        else:
            regressions, stale = check_baseline(report, baseline,
                                                tolerance=args.tolerance)
            effective_tol = args.tolerance if args.tolerance is not None \
                else float(baseline.get("tolerance", 2.0))
    report["baseline"] = {"path": bl_path, "regressions": regressions,
                          "stale": stale}

    # the tie-out contract is CHECKED, not assumed: over-attribution past
    # the clock-skew tolerance marks a window's ledger row untrustworthy
    # (overlapping duplicate spans, clock skew) — warned on stderr in every
    # output mode and carried in the artifact
    violations = [w["index"] for w in report["windows"]
                  if w["tie_out_error"] > TIE_OUT_TOLERANCE]
    report["tie_out_violations"] = violations
    for idx in violations:
        w = report["windows"][idx]
        print(f"WARNING: window {idx} over-attributes "
              f"{w['tie_out_error'] * 100:.1f}% of its span "
              f"(> {TIE_OUT_TOLERANCE * 100:.0f}% tolerance) — "
              "overlapping or skewed spans; treat its ledger row as "
              "suspect", file=sys.stderr)

    if args.out:
        with open(args.out, "w") as f:
            json.dump(report, f, indent=2)
            f.write("\n")
    if args.json:
        print(json.dumps(report, indent=2))
    else:
        print(render(report, top_windows=args.top))
        for r in regressions:
            print(f"REGRESSION: {r['stage']} {r['metric']} "
                  f"{r['baseline_ms']:.3f} -> {r['current_ms']:.3f} ms "
                  f"({r['ratio']}x, tolerance "
                  f"{effective_tol}x) vs {bl_path}", file=sys.stderr)
        for r in stale:
            print(f"stale baseline entry (improved): {r['stage']} "
                  f"{r['metric']} {r['baseline_ms']:.3f} -> "
                  f"{r['current_ms']:.3f} ms — re-run with "
                  f"--write-baseline to ratchet", file=sys.stderr)
    return EXIT_REGRESSION if regressions else EXIT_OK


if __name__ == "__main__":
    sys.exit(main())
