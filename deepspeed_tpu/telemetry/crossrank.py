"""dstrace-mp — cross-rank trace merge and collective-skew attribution.

The multi-process half of the observability story: every layer so far
(dstrace PR 5, ``dstpu plan`` PR 7, dsmem PR 8, serve-plan PR 13) replays
ONE process's ring. A MULTICHIP run dumps N isolated rings and nobody can
see *which rank made the collective slow* — the canonical multi-chip
diagnostic (DeepSpeed comms logger's straggler view, T3-style per-rank
barrier-wait decomposition, arxiv 2401.16677). This module closes it:

1. **Merge** (``bin/dstpu trace merge r0.json r1.json ...``) — joins
   per-rank dstrace dumps into ONE Chrome-trace/Perfetto timeline with
   per-rank track groups (pid = rank). Clocks are aligned by the dumps'
   monotonic↔wall anchor pairs (the process-identity header the tracer
   stamps at dump time) when present, else by **matched-collective offset
   estimation**: the k-th recorded collective carries the same ``op_seq``
   on every rank (SPMD records in program order), so the median pairwise
   completion-time delta over the op_seq join IS the clock offset (under
   blocking semantics collectives complete together). Either way
   the post-alignment median delta is reported as the **residual skew**
   per rank — the error bar on every cross-rank duration read off the
   merged timeline.

   The matched-collective aligner's documented failure mode: a rank that
   is *systematically* late at every collective (a persistently slow
   rank) is indistinguishable from a clock offset — the median absorbs
   it, and the skew ledger under-reports that rank's lateness. Wall
   anchors (same host, or NTP-disciplined hosts) do not have this
   failure, which is why they win when present and why
   ``residual_skew_us`` is always published: a large residual under
   wall-anchor alignment is real systematic skew, not clock error.

2. **Namespacing** — event ids and tids are only process-unique, and the
   tracer's synthetic tracks (``COMM_OVERLAP_TID``, per-uid request
   tracks) use small fixed integers that WOULD collide across ranks. The
   merge namespaces both as ``(rank << 40) | (id & (2**40 - 1))`` so no
   two ranks' events can alias, and prefixes every thread label with
   ``r<rank>/``.

3. **Skew ledger** (``bin/dstpu plan --cross-rank merged.json``) — for
   every matched collective op@seq: per-rank **arrival** time (span END =
   when the rank's own contribution to the op completed — a rank that got
   to the op late ends late, and a rank whose op itself ran slow ends
   late; both are the lateness everyone else pays for), ``wait =
   last_arrival − own_arrival`` (what every earlier rank burned blocking
   on the last one), per-rank wait totals + p50/p99, and the dominant
   straggler (the rank that *caused* the most wait) per window and
   overall — tied out against ``StragglerDetector`` verdicts in the
   MULTICHIP drill. A checked-in workload-scoped
   ``crossrank_baseline.json`` ratchets each rank's share of caused wait
   (dslint/plan idiom: regression exit 1, stale expiry only via
   ``--write-baseline``).

Offline-only, by contract: stdlib-only at module level, file-loadable by
``bin/dstpu`` on jax-less hosts, listed in ``OFFLINE_ONLY_MODULES``
(tools/dslint/hotpath.py) — it replays whole dumps and must never ride a
hot path.
"""

import argparse
import json
import os
import sys
from typing import Any, Dict, List, Optional, Tuple


def _load_trace_names():
    """File-load ``telemetry/names.py`` from the sibling path — never a
    package import: this module loads standalone on jax-less hosts (the
    DS007 registry is the one declaration of the comm-span namespace the
    skew ledger joins on)."""
    import importlib.util
    mod = sys.modules.get("dstpu_trace_names")
    if mod is None:
        path = os.path.join(os.path.dirname(os.path.abspath(__file__)),
                            "names.py")
        spec = importlib.util.spec_from_file_location(
            "dstpu_trace_names", path)
        mod = importlib.util.module_from_spec(spec)
        spec.loader.exec_module(mod)
        sys.modules["dstpu_trace_names"] = mod
    return mod


_COMM_PREFIX = _load_trace_names().COMM_PREFIX

EXIT_OK = 0
EXIT_REGRESSION = 1
EXIT_UNREADABLE = 2

CROSSRANK_VERSION = 1
CROSSRANK_BASELINE_VERSION = 1
CROSSRANK_BASELINE_NAME = "crossrank_baseline.json"
CROSSRANK_ARTIFACT_ENV = "DSTPU_CROSSRANK_ARTIFACT"
DEFAULT_CROSSRANK_ARTIFACT = "crossrank.json"
DEFAULT_MERGED_NAME = "merged_trace.json"

#: id/tid namespacing at merge time: rank in the high bits, the original
#: (process-local) id masked into the low 40. 2**40 monotonic event ids is
#: far beyond any ring's lifetime, and masking a pointer-sized thread ident
#: keeps its distinguishing low bits while the rank field guarantees two
#: RANKS can never alias (the raw idents themselves routinely coincide
#: across processes — every glibc MainThread lands at a similar address).
RANK_SHIFT = 40
RANK_ID_MASK = (1 << RANK_SHIFT) - 1

#: windowing for "dominant straggler per window": collectives separated by
#: a gap larger than max(10x the median inter-collective gap, 1ms) belong
#: to different phases (same split rule as attribution's sync-window
#: synthesis — pauses between phases must not fuse windows)
WINDOW_SPLIT_GAP_FACTOR = 10.0
WINDOW_SPLIT_GAP_MIN_US = 1_000.0

#: per-window tie-out: no rank can wait longer than the window it waited
#: in — a violation means the clock alignment (or the op_seq join) is
#: garbage and the ledger row is untrustworthy
TIE_OUT_TOLERANCE = 0.05


class CrossRankError(Exception):
    """Unreadable/unmergeable input — maps to CLI exit code 2."""


def quantile(sorted_vals: List[float], q: float) -> float:
    """Exact sample quantile, the repo-wide rule (``tracer._quantile`` /
    ``attribution.quantile``): value at ``min(int(q*n), n-1)``. A local
    copy by the standalone-load contract (this module imports nothing from
    the package); tests pin the copies equal."""
    if not sorted_vals:
        return 0.0
    return sorted_vals[min(int(q * len(sorted_vals)), len(sorted_vals) - 1)]


# ---------------------------------------------------------------------------
# dump loading + identity
# ---------------------------------------------------------------------------
def load_dump(path: str) -> dict:
    try:
        with open(path) as f:
            obj = json.load(f)
    except (OSError, ValueError) as e:
        raise CrossRankError(f"cannot read trace {path}: {e}") from e
    if not isinstance(obj, dict) or "traceEvents" not in obj:
        raise CrossRankError(f"{path}: not a Chrome trace (no traceEvents)")
    return obj


def dump_identity(obj: dict, fallback_rank: int) -> Dict[str, Any]:
    """The process-identity header (``Tracer.process_identity``) of one
    dump, defaulted for pre-header dumps: rank falls back to the dump's
    POSITION in the merge argument list (stable, documented), anchors to
    None (matched-collective alignment takes over)."""
    proc = (obj.get("otherData") or {}).get("process") or {}
    return {
        "rank": int(proc.get("rank", fallback_rank)),
        "world": int(proc.get("world", 0) or 0),
        "hostname": proc.get("hostname", "?"),
        "pid": int(proc.get("pid", 0) or 0),
        "wall_s": proc.get("wall_s"),
        "monotonic_s": proc.get("monotonic_s"),
        "epoch_monotonic_s": proc.get("epoch_monotonic_s"),
    }


def _wall_base_us(ident: Dict[str, Any]) -> Optional[float]:
    """Wall-clock time (us) at the dump's trace epoch (ts == 0), from the
    header's monotonic↔wall anchor pair — or None for pre-header dumps."""
    if ident["wall_s"] is None or ident["monotonic_s"] is None \
            or ident["epoch_monotonic_s"] is None:
        return None
    return (float(ident["wall_s"])
            - (float(ident["monotonic_s"])
               - float(ident["epoch_monotonic_s"]))) * 1e6


def _is_comm(e: dict) -> bool:
    return e.get("cat") == "comm" \
        or str(e.get("name", "")).startswith(_COMM_PREFIX)


def _comm_span_arrivals(events: List[dict]) -> Dict[int, float]:
    """op_seq -> span END ts (us) over one dump's COMPLETE comm spans —
    the join the offset estimator and the skew ledger both run on.

    The END is the rank's **arrival** at the collective's sync point: the
    instant its own contribution finished (a rank that got to the op late
    ends late; a rank whose fabric/op is slow also ends late — both are
    the lateness everyone else pays for). Under truly blocking semantics
    exits align, which is exactly why matched END times are the classic
    clock-offset estimator. In-jit collectives are trace-time instants
    (no runtime duration exists under XLA scheduling) and never join."""
    out: Dict[int, float] = {}
    for e in events:
        if e.get("ph") != "X" or not _is_comm(e):
            continue
        args = e.get("args") or {}
        if "op_seq" not in args:
            continue
        seq = int(args["op_seq"])
        if seq not in out:        # first occurrence wins (seq is unique)
            out[seq] = float(e.get("ts", 0.0)) + float(e.get("dur", 0.0))
    return out


# ---------------------------------------------------------------------------
# merge
# ---------------------------------------------------------------------------
def merge_traces(paths: List[str]) -> dict:
    """Merge per-rank dstrace dumps into ONE plan-loadable Chrome trace.

    Per-rank track groups: each source dump becomes its own Chrome
    ``pid`` (= rank), labeled ``rank N (hostname, pid P)``, with every
    thread re-labeled ``r<N>/<label>``. Clock alignment: wall anchors
    when every dump has a header, else matched-collective median offset
    vs the reference rank; residual per-rank skew is measured after
    alignment either way and published in ``otherData.crossrank``.
    """
    if not paths:
        raise CrossRankError("nothing to merge (no trace paths)")
    dumps = []
    for i, path in enumerate(paths):
        obj = load_dump(path)
        events = [e for e in obj["traceEvents"] if isinstance(e, dict)]
        dumps.append({"path": path, "obj": obj, "events": events,
                      "ident": dump_identity(obj, fallback_rank=i)})
    # rank uniqueness: duplicate headers (two dumps from the same rank, or
    # pre-header dumps defaulting to 0) fall back to argument position
    ranks = [d["ident"]["rank"] for d in dumps]
    if len(set(ranks)) != len(ranks):
        for i, d in enumerate(dumps):
            d["ident"]["rank"] = i
        rank_note = "duplicate rank headers: ranks reassigned by position"
    else:
        rank_note = None
    dumps.sort(key=lambda d: d["ident"]["rank"])
    ref = dumps[0]
    ref_rank = ref["ident"]["rank"]

    wall_bases = {d["ident"]["rank"]: _wall_base_us(d["ident"])
                  for d in dumps}
    use_wall = all(b is not None for b in wall_bases.values())
    ref_starts = _comm_span_arrivals(ref["events"])

    offsets: Dict[int, float] = {}
    residual: Dict[int, Optional[float]] = {}
    joined: Dict[int, int] = {}
    unaligned: List[int] = []
    for d in dumps:
        rank = d["ident"]["rank"]
        arrivals = _comm_span_arrivals(d["events"]) if rank != ref_rank \
            else ref_starts
        join = arrivals.keys() & ref_starts.keys()
        joined[rank] = len(join)
        if use_wall:
            offsets[rank] = wall_bases[rank] - wall_bases[ref_rank]
        elif rank == ref_rank:
            offsets[rank] = 0.0
        elif join:
            # the median matched-collective completion delta IS the clock
            # offset (robust to the minority of genuinely-late ops); see
            # the module docstring for the systematic-skew caveat
            deltas = sorted(arrivals[s] - ref_starts[s] for s in join)
            offsets[rank] = -quantile(deltas, 0.5)
        else:
            # no anchors AND no matched spans: this rank's timeline is
            # UNALIGNED — say so loudly instead of presenting an
            # arbitrary epoch offset as a perfect (residual 0) alignment
            offsets[rank] = 0.0
            unaligned.append(rank)
        # residual skew: the median aligned completion delta that REMAINS
        # — under wall anchors this is real systematic lateness; under
        # matched-collective alignment it is ~0 by construction; None for
        # an unaligned rank (there is no error bar to report)
        if rank == ref_rank:
            residual[rank] = 0.0
        elif rank in unaligned:
            residual[rank] = None
        else:
            aligned = sorted((arrivals[s] + offsets[rank])
                             - (ref_starts[s] + offsets[ref_rank])
                             for s in join)
            residual[rank] = quantile(aligned, 0.5)

    merged_events: List[dict] = []
    total = 0
    for d in dumps:
        rank = d["ident"]["rank"]
        off = offsets[rank]
        labels = {e.get("tid"): (e.get("args") or {}).get("name", "")
                  for e in d["events"]
                  if e.get("ph") == "M" and e.get("name") == "thread_name"}
        merged_events.append({
            "name": "process_name", "ph": "M", "pid": rank,
            "args": {"name": f"rank {rank} ({d['ident']['hostname']}, "
                             f"pid {d['ident']['pid']})"}})
        seen_tids: Dict[int, int] = {}
        for e in d["events"]:
            if e.get("ph") == "M":
                continue
            raw_tid = int(e.get("tid", 0))
            ns_tid = seen_tids.get(raw_tid)
            if ns_tid is None:
                ns_tid = (rank << RANK_SHIFT) | (raw_tid & RANK_ID_MASK)
                seen_tids[raw_tid] = ns_tid
                label = labels.get(raw_tid) or f"thread-{raw_tid}"
                merged_events.append({
                    "name": "thread_name", "ph": "M", "pid": rank,
                    "tid": ns_tid, "args": {"name": f"r{rank}/{label}"}})
            out = dict(e)
            out["pid"] = rank
            out["tid"] = ns_tid
            out["ts"] = round(float(e.get("ts", 0.0)) + off, 3)
            args = e.get("args")
            if isinstance(args, dict) and e.get("ph") != "C":
                args = dict(args)
                if "id" in args:
                    try:
                        args["id"] = (rank << RANK_SHIFT) | \
                            (int(args["id"]) & RANK_ID_MASK)
                    except (TypeError, ValueError):
                        pass
                if _is_comm(e):
                    args["rank"] = rank   # StragglerDetector.ingest_spans
                out["args"] = args        # + the skew ledger key off this
            merged_events.append(out)
            total += 1

    max_residual = max((abs(v) for v in residual.values()
                        if v is not None), default=0.0)
    if use_wall:
        alignment = "wall_anchor"
    elif len(unaligned) == len(dumps) - 1 and len(dumps) > 1:
        alignment = "none"        # nothing aligned anything
    else:
        alignment = "matched_collectives"
    crossrank = {
        "ranks": [d["ident"]["rank"] for d in dumps],
        "reference_rank": ref_rank,
        "alignment": alignment,
        "offsets_us": {str(r): round(v, 3) for r, v in offsets.items()},
        "residual_skew_us": {str(r): (round(v, 3) if v is not None
                                      else None)
                             for r, v in residual.items()},
        "max_residual_skew_us": round(max_residual, 3),
        "matched_collectives": {str(r): n for r, n in joined.items()},
        "sources": {str(d["ident"]["rank"]):
                    {"path": os.path.basename(d["path"]),
                     "hostname": d["ident"]["hostname"],
                     "pid": d["ident"]["pid"],
                     "world": d["ident"]["world"]} for d in dumps},
    }
    if unaligned:
        crossrank["unaligned_ranks"] = unaligned
    if rank_note:
        crossrank["note"] = rank_note
    return {
        "traceEvents": merged_events,
        "displayTimeUnit": "ms",
        "otherData": {
            "clock": "monotonic",
            "events": total,
            "crossrank": crossrank,
        },
    }


# ---------------------------------------------------------------------------
# matched collectives + skew ledger
# ---------------------------------------------------------------------------
def _matched_with_mismatches(obj: dict
                             ) -> Tuple[Dict[int, Dict[str, Any]], int]:
    events = obj.get("traceEvents") or []
    by_seq: Dict[int, Dict[str, Any]] = {}
    mismatches = 0
    for e in events:
        if not isinstance(e, dict) or e.get("ph") != "X" or not _is_comm(e):
            continue
        args = e.get("args") or {}
        if "op_seq" not in args or "rank" not in args:
            continue
        seq, rank = int(args["op_seq"]), int(args["rank"])
        ts, dur = float(e.get("ts", 0.0)), float(e.get("dur", 0.0))
        rec = by_seq.setdefault(seq, {"op": e.get("name"), "ranks": {}})
        if rec["op"] != e.get("name"):
            rec["mismatch"] = True
            continue
        if rank not in rec["ranks"]:      # seq unique per rank: first wins
            rec["ranks"][rank] = {"start_us": ts, "end_us": ts + dur,
                                  "dur_us": dur}
    out = {}
    for seq, rec in by_seq.items():
        if rec.pop("mismatch", False):
            mismatches += 1
            continue
        if len(rec["ranks"]) >= 2:
            out[seq] = rec
    return dict(sorted(out.items())), mismatches


def matched_collectives(obj: dict) -> Dict[int, Dict[str, Any]]:
    """``{op_seq: {"op": name, "ranks": {rank: {"start_us", "end_us",
    "dur_us"}}}}`` over a MERGED dump's complete comm spans — the ledger's
    input, exposed so tests can feed the same durations straight into a
    ``StragglerDetector``. Seqs whose op NAME disagrees across ranks are
    dropped (a misaligned join must not fabricate waits)."""
    return _matched_with_mismatches(obj)[0]


def attribute_crossrank(obj: dict, source: str = "<merged>"
                        ) -> Dict[str, Any]:
    """Replay a merged dump into the collective-skew ledger.

    Per matched op@seq: per-rank **arrival** (span END — when the rank's
    contribution to the collective completed), ``wait = last_arrival −
    own_arrival`` (the time every earlier rank burned blocking on the
    last one; the last arrival waits 0 and is the collective's
    **straggler**). Windows split at large inter-collective gaps; each
    window reports per-rank waited/caused totals, its dominant straggler,
    and a tie-out check (no rank waits longer than the window —
    violations mean the alignment or the join is broken, and the row is
    flagged, not trusted)."""
    cr = (obj.get("otherData") or {}).get("crossrank") or {}
    matched, mismatches = _matched_with_mismatches(obj)
    ranks = sorted({r for rec in matched.values() for r in rec["ranks"]})
    if not ranks and cr.get("ranks"):
        ranks = sorted(int(r) for r in cr["ranks"])

    collectives = []
    for seq, rec in matched.items():
        arrivals = {r: v["end_us"] for r, v in rec["ranks"].items()}
        last = max(arrivals.values())
        straggler = max(sorted(arrivals), key=lambda r: arrivals[r])
        waits = {r: last - a for r, a in arrivals.items()}
        collectives.append({
            "seq": seq,
            "op": rec["op"],
            "arrivals_us": {str(r): round(a, 3)
                            for r, a in sorted(arrivals.items())},
            "waits_us": {str(r): round(w, 3)
                         for r, w in sorted(waits.items())},
            "straggler": straggler,
            "wait_total_us": round(sum(waits.values()), 3),
        })
    collectives.sort(key=lambda c: min(
        float(v) for v in c["arrivals_us"].values()))

    # windowing on first-arrival times (attribution's gap-split rule)
    windows: List[Dict[str, Any]] = []
    if collectives:
        firsts = [min(float(v) for v in c["arrivals_us"].values())
                  for c in collectives]
        gaps = sorted(max(b - a, 0.0) for a, b in zip(firsts, firsts[1:]))
        med_gap = gaps[len(gaps) // 2] if gaps else 0.0
        cut = max(med_gap * WINDOW_SPLIT_GAP_FACTOR, WINDOW_SPLIT_GAP_MIN_US)
        runs: List[List[int]] = [[0]]
        for i in range(1, len(collectives)):
            if firsts[i] - firsts[i - 1] > cut:
                runs.append([])
            runs[-1].append(i)
        for run in runs:
            sub = [collectives[i] for i in run]
            w0 = min(min(float(v) for v in c["arrivals_us"].values())
                     for c in sub)
            w1 = max(max(float(v) for v in c["arrivals_us"].values())
                     for c in sub)
            waited = {r: 0.0 for r in ranks}
            caused = {r: 0.0 for r in ranks}
            for c in sub:
                for r_str, w in c["waits_us"].items():
                    waited[int(r_str)] = waited.get(int(r_str), 0.0) + w
                caused[c["straggler"]] = caused.get(c["straggler"], 0.0) \
                    + c["wait_total_us"]
            dur = w1 - w0
            worst = max(waited.values(), default=0.0)
            windows.append({
                "start_us": round(w0, 3),
                "dur_us": round(dur, 3),
                "collectives": len(sub),
                "waited_us": {str(r): round(v, 3)
                              for r, v in sorted(waited.items())},
                "caused_us": {str(r): round(v, 3)
                              for r, v in sorted(caused.items())},
                "dominant_straggler": max(
                    sorted(caused), key=lambda r: caused[r]) if sub else None,
                # no rank can wait longer than the window it waited in
                "tie_out_error": round(max(worst - dur, 0.0) / dur, 6)
                if dur > 0 else 0.0,
            })

    per_rank: Dict[str, Dict[str, float]] = {}
    total_caused = sum(c["wait_total_us"] for c in collectives) or 0.0
    for r in ranks:
        own_waits = sorted(float(c["waits_us"].get(str(r), 0.0))
                           for c in collectives)
        caused_us = sum(c["wait_total_us"] for c in collectives
                        if c["straggler"] == r)
        straggled = sum(1 for c in collectives if c["straggler"] == r)
        per_rank[str(r)] = {
            "waited_us": round(sum(own_waits), 3),
            "caused_us": round(caused_us, 3),
            "wait_share": round(caused_us / total_caused, 4)
            if total_caused > 0 else 0.0,
            "straggled": straggled,
            "wait_p50_us": round(quantile(own_waits, 0.5), 3),
            "wait_p99_us": round(quantile(own_waits, 0.99), 3),
        }
    dominant = None
    if per_rank and total_caused > 0:
        dominant = int(max(sorted(per_rank),
                           key=lambda r: per_rank[r]["caused_us"]))
    return {
        "version": CROSSRANK_VERSION,
        "source": source,
        "ranks": ranks,
        "alignment": cr.get("alignment"),
        "reference_rank": cr.get("reference_rank"),
        "residual_skew_us": cr.get("residual_skew_us", {}),
        "max_residual_skew_us": cr.get("max_residual_skew_us", 0.0),
        "unaligned_ranks": cr.get("unaligned_ranks", []),
        "matched": len(collectives),
        "seq_mismatches": mismatches,
        "collectives": collectives,
        "windows": windows,
        "per_rank": per_rank,
        "wait_total_us": round(total_caused, 3),
        "dominant_straggler": dominant,
    }


def analyze_crossrank_path(path: str) -> Dict[str, Any]:
    """Load + attribute a merged dump in one call (env_report / tests)."""
    return attribute_crossrank(load_dump(path), source=path)


# ---------------------------------------------------------------------------
# regression baseline (dslint/plan ratchet idiom)
# ---------------------------------------------------------------------------
def load_crossrank_baseline(path: str) -> dict:
    with open(path) as f:
        data = json.load(f)
    if data.get("version") != CROSSRANK_BASELINE_VERSION:
        raise ValueError(f"unsupported crossrank baseline version "
                         f"{data.get('version')!r} in {path} "
                         f"(expected {CROSSRANK_BASELINE_VERSION})")
    return data


def find_crossrank_baseline(start: str) -> Optional[str]:
    """Walk up from ``start`` for the checked-in baseline (dslint rule)."""
    d = os.path.abspath(start)
    if os.path.isfile(d):
        d = os.path.dirname(d)
    while True:
        cand = os.path.join(d, CROSSRANK_BASELINE_NAME)
        if os.path.exists(cand):
            return cand
        parent = os.path.dirname(d)
        if parent == d:
            return None
        d = parent


def write_crossrank_baseline(path: str, report: Dict[str, Any],
                             tolerance: float = 2.0,
                             min_abs_share: float = 0.10,
                             min_abs_ms: float = 0.05) -> dict:
    """Record each rank's caused-wait share + p99 own-wait as the new
    baseline, workload-scoped by the merged trace's basename (discovered
    baselines only judge traces of the same workload)."""
    data = {
        "version": CROSSRANK_BASELINE_VERSION,
        "workload": os.path.basename(str(report.get("source", ""))),
        "tolerance": float(tolerance),
        "min_abs_share": float(min_abs_share),
        "min_abs_ms": float(min_abs_ms),
        "entries": {
            r: {"wait_share": rec["wait_share"],
                "wait_p99_ms": round(rec["wait_p99_us"] / 1e3, 4)}
            for r, rec in sorted(report.get("per_rank", {}).items())},
    }
    with open(path, "w") as f:
        json.dump(data, f, indent=2, sort_keys=True)
        f.write("\n")
    return data


def check_crossrank_baseline(report: Dict[str, Any], baseline: dict,
                             tolerance: Optional[float] = None
                             ) -> Tuple[List[dict], List[dict]]:
    """(regressions, stale). A rank REGRESSES when its caused-wait share
    (or p99 own-wait) exceeds baseline * tolerance AND by more than the
    absolute floor; improvements past the same margin are STALE entries
    that must expire via ``--write-baseline`` (the ratchet)."""
    tol = float(tolerance if tolerance is not None
                else baseline.get("tolerance", 2.0))
    share_floor = float(baseline.get("min_abs_share", 0.10))
    ms_floor = float(baseline.get("min_abs_ms", 0.05))
    regressions, stale = [], []
    for rank, entry in sorted(baseline.get("entries", {}).items()):
        cur_rec = report.get("per_rank", {}).get(rank)
        if cur_rec is None:
            continue
        for metric, floor, cur in (
                ("wait_share", share_floor, cur_rec["wait_share"]),
                ("wait_p99_ms", ms_floor, cur_rec["wait_p99_us"] / 1e3)):
            base = float(entry.get(metric, 0.0))
            row = {"rank": rank, "metric": metric,
                   "baseline": round(base, 4), "current": round(cur, 4),
                   "ratio": round(cur / base, 3) if base > 0 else None}
            if cur > base * tol and (cur - base) > floor:
                regressions.append(row)
            elif base > cur * tol and (base - cur) > floor:
                stale.append(row)
    return regressions, stale


# ---------------------------------------------------------------------------
# rendering + CLIs
# ---------------------------------------------------------------------------
def render(report: Dict[str, Any], top: int = 10) -> str:
    out = []
    out.append(f"dstpu plan --cross-rank — {report['source']}")
    res = report.get("residual_skew_us") or {}
    out.append(f"ranks {report['ranks']} | alignment "
               f"{report.get('alignment') or 'unknown'} (reference rank "
               f"{report.get('reference_rank')}), max residual skew "
               f"{report.get('max_residual_skew_us', 0.0):.1f}us | "
               f"{report['matched']} matched collectives"
               + (f", {report['seq_mismatches']} seq mismatches dropped"
                  if report.get("seq_mismatches") else ""))
    out.append("")
    out.append(f"{'rank':>5} {'waited ms':>10} {'caused ms':>10} "
               f"{'share':>7} {'straggled':>10} {'p50 wait':>10} "
               f"{'p99 wait':>10} {'resid us':>9}")
    out.append("-" * 78)
    for r, rec in sorted(report.get("per_rank", {}).items(),
                         key=lambda kv: int(kv[0])):
        out.append(f"{r:>5} {rec['waited_us'] / 1e3:>10.3f} "
                   f"{rec['caused_us'] / 1e3:>10.3f} "
                   f"{rec['wait_share'] * 100:>6.1f}% "
                   f"{rec['straggled']:>10} "
                   f"{rec['wait_p50_us'] / 1e3:>9.3f}ms "
                   f"{rec['wait_p99_us'] / 1e3:>9.3f}ms "
                   f"{float(res.get(r) or 0.0):>9.1f}")
    if report.get("dominant_straggler") is not None:
        dom = report["dominant_straggler"]
        caused_ms = report["per_rank"][str(dom)]["caused_us"] / 1e3
        out.append("")
        out.append(f"dominant straggler: rank {dom} (caused "
                   f"{caused_ms:.3f}ms of "
                   f"{report['wait_total_us'] / 1e3:.3f}ms total wait)")
    if report.get("windows"):
        out.append("")
        out.append(f"{'window':>7} {'ms':>9} {'collectives':>12} "
                   f"{'dominant':>9}   tie-out")
        out.append("-" * 48)
        for i, w in enumerate(report["windows"][:top]):
            out.append(f"{i:>7} {w['dur_us'] / 1e3:>9.2f} "
                       f"{w['collectives']:>12} "
                       f"{str(w['dominant_straggler']):>9}   "
                       f"{w['tie_out_error'] * 100:.2f}%")
        if len(report["windows"]) > top:
            out.append(f"... {len(report['windows']) - top} more windows")
    worst = sorted(report.get("collectives", []),
                   key=lambda c: -c["wait_total_us"])[:top]
    if worst:
        out.append("")
        out.append("worst collectives (op@seq: total wait, straggler)")
        for c in worst:
            out.append(f"  {c['op']}@{c['seq']:<6} "
                       f"{c['wait_total_us'] / 1e3:>9.3f}ms  "
                       f"rank {c['straggler']}")
    return "\n".join(out)


def merge_main(argv=None) -> int:
    parser = argparse.ArgumentParser(
        prog="dstpu trace merge",
        description="merge per-rank dstrace dumps into one Perfetto "
                    "timeline with per-rank track groups and aligned "
                    "clocks (feeds `dstpu plan --cross-rank`)")
    parser.add_argument("traces", nargs="+",
                        help="per-rank Chrome-trace JSON dumps "
                             "(DSTPU_TRACE output, one per rank)")
    parser.add_argument("--out", default=DEFAULT_MERGED_NAME,
                        help=f"merged trace path "
                             f"(default ./{DEFAULT_MERGED_NAME})")
    parser.add_argument("--json", action="store_true",
                        help="print the crossrank summary as JSON")
    args = parser.parse_args(argv)
    try:
        merged = merge_traces(args.traces)
    except CrossRankError as e:
        print(f"dstpu trace merge: {e}", file=sys.stderr)
        return EXIT_UNREADABLE
    with open(args.out, "w") as f:
        json.dump(merged, f)
    cr = merged["otherData"]["crossrank"]
    if cr.get("unaligned_ranks"):
        print(f"WARNING: ranks {cr['unaligned_ranks']} have no clock "
              "anchors AND no matched collectives — their timelines are "
              "UNALIGNED (epoch-relative only); cross-rank deltas "
              "involving them are meaningless", file=sys.stderr)
    if args.json:
        print(json.dumps(cr, indent=2))
    else:
        print(f"# merged {len(args.traces)} dumps -> {args.out} "
              f"(ranks {cr['ranks']}, alignment {cr['alignment']}, "
              f"max residual skew {cr['max_residual_skew_us']:.1f}us, "
              f"load in https://ui.perfetto.dev)")
    return EXIT_OK


def main(argv=None) -> int:
    parser = argparse.ArgumentParser(
        prog="dstpu plan --cross-rank",
        description="collective-skew attribution over a merged cross-rank "
                    "dstrace dump (produce one with `dstpu trace merge "
                    "r0.json r1.json ...`)")
    parser.add_argument("trace", help="merged Chrome-trace JSON")
    parser.add_argument("--baseline", default=None,
                        help=f"crossrank baseline path (default: walk up "
                             f"from the trace for {CROSSRANK_BASELINE_NAME})")
    parser.add_argument("--write-baseline", action="store_true",
                        help="record this report as the new baseline "
                             "(ratchet: also how stale entries expire)")
    parser.add_argument("--tolerance", type=float, default=None,
                        help="regression factor vs baseline (default: the "
                             "stored factor, 2.0 when writing fresh)")
    parser.add_argument("--out", default=None,
                        help="write the full artifact JSON here "
                             f"(env_report reads ${CROSSRANK_ARTIFACT_ENV} "
                             f"or ./{DEFAULT_CROSSRANK_ARTIFACT})")
    parser.add_argument("--json", action="store_true",
                        help="print the report as JSON instead of a table")
    parser.add_argument("--top", type=int, default=10,
                        help="windows / worst collectives to show")
    args = parser.parse_args(argv)

    try:
        report = analyze_crossrank_path(args.trace)
    except CrossRankError as e:
        print(f"dstpu plan --cross-rank: {e}", file=sys.stderr)
        return EXIT_UNREADABLE

    # baseline discovery anchors at the TRACE path (plan/dslint rule): a
    # merged dump outside the repo is a different workload
    bl_path = args.baseline or find_crossrank_baseline(args.trace)
    regressions, stale = [], []
    effective_tol = args.tolerance if args.tolerance is not None else 2.0
    if args.write_baseline:
        trace_dir = os.path.dirname(os.path.abspath(args.trace))
        target = bl_path or os.path.join(trace_dir, CROSSRANK_BASELINE_NAME)
        if args.baseline is None and os.path.exists(target):
            try:    # never clobber a DISCOVERED other-workload baseline
                existing_wl = load_crossrank_baseline(target).get("workload")
            except (OSError, ValueError):
                existing_wl = None
            if existing_wl and existing_wl != os.path.basename(args.trace):
                redirected = os.path.join(trace_dir, CROSSRANK_BASELINE_NAME)
                if os.path.abspath(redirected) == os.path.abspath(target):
                    print(f"# refusing --write-baseline: {target} ratchets "
                          f"workload {existing_wl!r} — pass --baseline PATH "
                          "to overwrite deliberately", file=sys.stderr)
                    target = None
                else:
                    print(f"# note: {target} ratchets workload "
                          f"{existing_wl!r} — starting this workload's "
                          f"baseline at {redirected} instead",
                          file=sys.stderr)
                    target = redirected
        if target is not None:
            if args.tolerance is None and os.path.exists(target):
                try:    # ratchet rewrite keeps the stored factor
                    effective_tol = float(load_crossrank_baseline(target)
                                          .get("tolerance", 2.0))
                except (OSError, ValueError):
                    pass
            write_crossrank_baseline(target, report,
                                     tolerance=effective_tol)
            print(f"# crossrank baseline written -> {target}",
                  file=sys.stderr)
        bl_path = target
    elif bl_path:
        try:
            baseline = load_crossrank_baseline(bl_path)
        except (OSError, ValueError) as e:
            print(f"dstpu plan --cross-rank: bad baseline {bl_path}: {e}",
                  file=sys.stderr)
            return EXIT_UNREADABLE
        bl_workload = baseline.get("workload")
        trace_workload = os.path.basename(args.trace)
        if args.baseline is None and bl_workload \
                and bl_workload != trace_workload:
            print(f"# note: discovered baseline {bl_path} is for workload "
                  f"{bl_workload!r}, not {trace_workload!r} — comparison "
                  "skipped (pass --baseline to compare anyway)",
                  file=sys.stderr)
            bl_path = None
        else:
            regressions, stale = check_crossrank_baseline(
                report, baseline, tolerance=args.tolerance)
            effective_tol = args.tolerance if args.tolerance is not None \
                else float(baseline.get("tolerance", 2.0))
    report["baseline"] = {"path": bl_path, "regressions": regressions,
                          "stale": stale}

    violations = [i for i, w in enumerate(report["windows"])
                  if w["tie_out_error"] > TIE_OUT_TOLERANCE]
    report["tie_out_violations"] = violations
    for idx in violations:
        w = report["windows"][idx]
        print(f"WARNING: window {idx} has a rank waiting "
              f"{w['tie_out_error'] * 100:.1f}% longer than the window "
              f"(> {TIE_OUT_TOLERANCE * 100:.0f}% tolerance) — broken "
              "clock alignment or op_seq join; treat its row as suspect",
              file=sys.stderr)

    if args.out:
        with open(args.out, "w") as f:
            json.dump(report, f, indent=2)
            f.write("\n")
    if args.json:
        print(json.dumps(report, indent=2))
    else:
        print(render(report, top=args.top))
        for r in regressions:
            print(f"REGRESSION: rank {r['rank']} {r['metric']} "
                  f"{r['baseline']} -> {r['current']} ({r['ratio']}x, "
                  f"tolerance {effective_tol}x) vs {bl_path}",
                  file=sys.stderr)
        for r in stale:
            print(f"stale baseline entry (improved): rank {r['rank']} "
                  f"{r['metric']} {r['baseline']} -> {r['current']} — "
                  "re-run with --write-baseline to ratchet",
                  file=sys.stderr)
    return EXIT_REGRESSION if regressions else EXIT_OK


if __name__ == "__main__":
    sys.exit(main())
