"""dsmem — analytic memory ledger, live HBM watermark tracks, OOM forensics.

The memory axis of observability, built on the dstrace idioms (PR 5/7):
deterministic numbers as proof, checked-in ratchet baselines, dslint-proven
hot-path cleanliness. Three parts:

1. **MemoryLedger** — an analytic, jax-free memory *plan* computed from
   engine config + mesh: per-component byte accounting (params / grads /
   optimizer state by dtype, zero_stage and offload tier; activation-
   checkpoint working set; KV-cache pages) with per-phase expected
   watermarks (``init`` / ``first_step`` / ``steady`` / ``ckpt``). The
   reference ``estimate_zero*_model_states_mem_needs`` APIs are reproduced
   on top of it.
2. **MemorySampler** — live device HBM stats (``Device.memory_stats()``:
   bytes_in_use / peak / limit) plus host RSS, read strictly OFF the hot
   path (the engine's step-boundary drain hook and an optional background
   cadence thread) and emitted as Chrome-trace **counter** events
   (``"ph":"C"``) into the dstrace ring — Perfetto shows HBM/RSS tracks
   time-aligned with the dispatch/drain/comm spans. Registered in
   ``tools/dslint/hotpath.py`` so the linter *proves* sampling never adds
   a host sync to the train/serve paths.
3. **Tie-out + forensics** — the mem report artifact compares plan vs
   observed watermarks per phase against a checked-in, workload-scoped
   ``mem_baseline.json`` (the dslint/plan ratchet contract: regression →
   exit 1, improvements expired only via ``--write-baseline``); an
   analytic *preflight* refuses/warns when the plan exceeds
   ``bytes_limit`` and suggests the next offload tier; and the OOM
   handlers in the engine and ``FaultTolerantRunner`` turn a
   RESOURCE_EXHAUSTED into a diagnostic bundle embedding the ledger, the
   last N memory samples, per-phase deltas, and the trace tail.

Module-level contract: **stdlib-only imports** (mirroring attribution.py)
so ``bin/dstpu mem`` can file-load this module on jax-less hosts and the
ledger math is replayable anywhere. The sampler late-imports jax inside
its collection helpers and takes the tracer as a constructor argument —
nothing at import time touches the device runtime.
"""

import argparse
import collections
import json
import logging
import os
import sys
import threading
import time
from typing import Any, Callable, Dict, List, Optional, Tuple

logger = logging.getLogger("deepspeed_tpu")

EXIT_OK = 0
EXIT_REGRESSION = 1
EXIT_UNREADABLE = 2

MEM_REPORT_VERSION = 1
MEM_BASELINE_VERSION = 1
MEM_BASELINE_NAME = "mem_baseline.json"

#: ledger/observation phases, in lifecycle order. ``first_step`` exists as
#: a separate observation bucket because the first step carries compile
#: workspace the analytic plan does not model; the *plan* values for
#: first_step and steady are identical by construction.
PHASES = ("init", "first_step", "steady", "ckpt")

#: counter-event names the sampler emits (and attribution/plan consumes)
HBM_IN_USE_COUNTER = "mem/hbm_bytes_in_use"
HBM_PEAK_COUNTER = "mem/hbm_peak_bytes"
HBM_LIMIT_COUNTER = "mem/hbm_bytes_limit"
HOST_RSS_COUNTER = "mem/host_rss_bytes"
KV_BYTES_COUNTER = "serve/kv_bytes"

_DTYPE_BYTES = {
    None: 4, "fp32": 4, "float32": 4, "fp16": 2, "float16": 2,
    "bf16": 2, "bfloat16": 2, "fp8": 1, "float8_e4m3fn": 1, "int8": 1,
}

#: saved-activation working set per layer, as a multiple of one
#: [micro_batch, seq, hidden] activation in compute dtype. Derived from the
#: docs/memory_plan.md arithmetic (q + k,v + gate,up + wo/down saves ≈ 7
#: hidden-sized tensors per layer for the dot-saving policies on a llama
#: block); boundaries-only policies save one.
_REMAT_POLICY_FACTOR = {
    "nothing_saveable": 1.0,
    "checkpoint_dots": 7.0,
    "dots_saveable": 7.0,
    "dots_with_no_batch_dims_saveable": 7.0,
    "everything_saveable": 12.0,
    "save_named": 3.0,
    "offload_dots_to_host": 7.0,       # same saves, host tier (see ledger)
}


class MemoryPreflightError(RuntimeError):
    """The analytic plan cannot fit the device (``memory.preflight:
    refuse``): raised at engine init, with the next offload tier in the
    message, instead of dying minutes later in XLA."""


def _dtype_bytes(name) -> int:
    if isinstance(name, int):
        return name
    return _DTYPE_BYTES.get(str(name).lower() if name is not None else None,
                            4)


def is_oom_message(msg: str) -> bool:
    """OOM classification shared by the engine handler, the resilience
    runner, and the autotuner (previously three drifting string matches)."""
    if not msg:
        return False
    low = msg.lower()
    return "resource_exhausted" in low or "out of memory" in low \
        or "out of host memory" in low


def is_oom_error(exc: BaseException) -> bool:
    return is_oom_message(str(exc))


# ---------------------------------------------------------------------------
# part 1: the analytic ledger
# ---------------------------------------------------------------------------
class MemoryLedger:
    """Analytic per-device memory plan from config-shaped inputs.

    All sizes are **bytes per device**. ``zero_world`` is the ZeRO sharding
    world (the ``fsdp * fsdp_outer`` mesh span); replicated state divides
    by 1, sharded state by ``zero_world`` per the configured stage:

      stage 0: params + grads + optimizer state replicated
      stage 1: optimizer state sharded
      stage 2: + gradient accumulation buffer sharded
      stage 3: + parameters sharded

    Offload tiers move bytes to the host column: ``offload_optimizer``
    moves ``ratio`` of the optimizer state (Twin-Flow partial offload),
    ``offload_param`` moves the fp32 masters to host and leaves only the
    streamed layer-group working set in HBM.

    Activation/logits terms need shape hints (``micro_batch`` / ``seq_len``
    / ``hidden_size`` / ``num_layers`` / ``vocab_size``); without them
    those components are 0 and ``notes`` records the omission — model
    states (the preflight's dominant term) never need shapes.
    """

    def __init__(self, num_params: int,
                 zero_stage: int = 0,
                 zero_world: int = 1,
                 compute_dtype: str = "bf16",
                 master_dtype: Optional[str] = "fp32",
                 optimizer_moments: int = 2,
                 grad_accum_dtype: Optional[str] = None,
                 offload_optimizer: str = "none",
                 offload_optimizer_ratio: float = 1.0,
                 offload_param: str = "none",
                 layers_per_group: int = 1,
                 num_layers: Optional[int] = None,
                 micro_batch: Optional[int] = None,
                 seq_len: Optional[int] = None,
                 hidden_size: Optional[int] = None,
                 vocab_size: Optional[int] = None,
                 remat_policy: str = "nothing_saveable",
                 loss_chunked: bool = False,
                 gather_on_save: bool = True,
                 kv_bytes: int = 0):
        self.num_params = int(num_params)
        self.zero_stage = int(zero_stage)
        self.zero_world = max(int(zero_world), 1)
        self.compute_dtype = compute_dtype
        self.master_dtype = master_dtype
        self.optimizer_moments = int(optimizer_moments)
        self.grad_accum_dtype = grad_accum_dtype
        self.offload_optimizer = offload_optimizer
        self.offload_optimizer_ratio = min(max(
            float(offload_optimizer_ratio), 0.0), 1.0)
        self.offload_param = offload_param
        self.layers_per_group = max(int(layers_per_group), 1)
        self.num_layers = num_layers
        self.micro_batch = micro_batch
        self.seq_len = seq_len
        self.hidden_size = hidden_size
        self.vocab_size = vocab_size
        self.remat_policy = remat_policy
        self.loss_chunked = bool(loss_chunked)
        self.gather_on_save = bool(gather_on_save)
        self.kv_bytes = int(kv_bytes)
        self.notes: List[str] = []

    # -- component accounting ----------------------------------------------
    def components(self) -> Dict[str, Dict[str, int]]:
        """``{component: {"hbm_bytes", "host_bytes"}}`` — the itemized plan.
        Components: params, masters, opt_state, grads, activations, logits,
        kv_cache."""
        p = self.num_params
        zw = self.zero_world
        comp = _dtype_bytes(self.compute_dtype)
        out: Dict[str, Dict[str, int]] = {}
        self.notes = []

        param_shard = zw if self.zero_stage >= 3 else 1
        if self.offload_param != "none":
            # masters pinned/streamed from the host tier; HBM holds only the
            # streamed layer-group working set (compute dtype)
            if self.num_layers:
                hbm_params = comp * p * self.layers_per_group \
                    // self.num_layers
            else:
                hbm_params = 0
                self.notes.append(
                    "offload_param without num_layers: streamed HBM "
                    "working set unknown, planned as 0")
            out["params"] = {"hbm_bytes": hbm_params, "host_bytes": 0}
            out["masters"] = {"hbm_bytes": 0, "host_bytes": 4 * p}
        else:
            # the dense path keeps fp32 masters resident (compute-dtype
            # casts are transient); fp32 compute folds masters into params
            if self.master_dtype is None or comp == 4:
                out["params"] = {"hbm_bytes": 4 * p // param_shard,
                                 "host_bytes": 0}
                out["masters"] = {"hbm_bytes": 0, "host_bytes": 0}
            else:
                out["params"] = {
                    "hbm_bytes":
                        _dtype_bytes(self.master_dtype) * p // param_shard,
                    "host_bytes": 0}
                out["masters"] = {"hbm_bytes": 0, "host_bytes": 0}

        opt_bytes = self.optimizer_moments * 4 * p \
            // (zw if self.zero_stage >= 1 else 1)
        if self.offload_optimizer != "none":
            host_share = int(opt_bytes * self.offload_optimizer_ratio)
            out["opt_state"] = {"hbm_bytes": opt_bytes - host_share,
                                "host_bytes": host_share}
        else:
            out["opt_state"] = {"hbm_bytes": opt_bytes, "host_bytes": 0}

        grad_bytes = _dtype_bytes(self.grad_accum_dtype) * p \
            // (zw if self.zero_stage >= 2 else 1)
        if self.offload_optimizer != "none" or self.offload_param != "none":
            # host-optimizer paths accumulate grads host-side per group
            out["grads"] = {"hbm_bytes": 0, "host_bytes": grad_bytes}
        else:
            out["grads"] = {"hbm_bytes": grad_bytes, "host_bytes": 0}

        act = {"hbm_bytes": 0, "host_bytes": 0}
        if self.micro_batch and self.seq_len and self.hidden_size \
                and self.num_layers:
            factor = _REMAT_POLICY_FACTOR.get(self.remat_policy, 1.0)
            per_layer = int(factor * self.micro_batch * self.seq_len
                            * self.hidden_size * comp)
            total = per_layer * self.num_layers
            if self.remat_policy == "offload_dots_to_host":
                act = {"hbm_bytes": per_layer, "host_bytes": total}
            else:
                act = {"hbm_bytes": total, "host_bytes": 0}
        else:
            self.notes.append("activation shapes not provided: "
                              "activations planned as 0")
        out["activations"] = act

        logits = 0
        if self.micro_batch and self.seq_len and self.vocab_size \
                and not self.loss_chunked:
            # the log_softmax chain materializes fp32 logits + exp temps
            logits = 2 * 4 * self.micro_batch * self.seq_len \
                * self.vocab_size
        out["logits"] = {"hbm_bytes": logits, "host_bytes": 0}
        out["kv_cache"] = {"hbm_bytes": self.kv_bytes, "host_bytes": 0}
        return out

    # -- phase watermarks ---------------------------------------------------
    def phase_bytes(self) -> Dict[str, Dict[str, int]]:
        """Expected per-phase watermarks, ``{phase: {"hbm_bytes",
        "host_bytes"}}``. ``init`` is model state only; ``first_step`` and
        ``steady`` add the per-step working set (identical by plan — the
        observed first_step additionally carries compile workspace, which
        is why they are separate *observation* buckets); ``ckpt`` adds the
        stage-3 save-time gather buffer."""
        c = self.components()

        def tot(names, col):
            return sum(c[n][col] for n in names)

        model_state = ("params", "masters", "opt_state")
        working = ("grads", "activations", "logits", "kv_cache")
        init_hbm = tot(model_state, "hbm_bytes")
        init_host = tot(model_state, "host_bytes")
        step_hbm = init_hbm + tot(working, "hbm_bytes")
        step_host = init_host + tot(working, "host_bytes")
        gather = 0
        if self.zero_stage >= 3 and self.gather_on_save \
                and self.offload_param == "none":
            gather = _dtype_bytes(self.compute_dtype) * self.num_params
        return {
            "init": {"hbm_bytes": init_hbm, "host_bytes": init_host},
            "first_step": {"hbm_bytes": step_hbm, "host_bytes": step_host},
            "steady": {"hbm_bytes": step_hbm, "host_bytes": step_host},
            "ckpt": {"hbm_bytes": step_hbm + gather,
                     "host_bytes": step_host},
        }

    def max_hbm_bytes(self) -> int:
        return max(v["hbm_bytes"] for v in self.phase_bytes().values())

    def to_dict(self) -> Dict[str, Any]:
        comps = self.components()     # also refreshes notes
        return {
            "inputs": {
                "num_params": self.num_params,
                "zero_stage": self.zero_stage,
                "zero_world": self.zero_world,
                "compute_dtype": str(self.compute_dtype),
                "grad_accum_dtype": self.grad_accum_dtype,
                "optimizer_moments": self.optimizer_moments,
                "offload_optimizer": self.offload_optimizer,
                "offload_optimizer_ratio": self.offload_optimizer_ratio,
                "offload_param": self.offload_param,
                "remat_policy": self.remat_policy,
                "micro_batch": self.micro_batch,
                "seq_len": self.seq_len,
                "hidden_size": self.hidden_size,
                "num_layers": self.num_layers,
                "vocab_size": self.vocab_size,
                "kv_bytes": self.kv_bytes,
            },
            "components": comps,
            "phases": self.phase_bytes(),
            "notes": list(self.notes),
        }

    # -- construction from the single-JSON config ---------------------------
    @classmethod
    def from_config(cls, raw: Dict[str, Any], num_params: int,
                    mesh_shape: Optional[Dict[str, int]] = None,
                    **shape_hints) -> "MemoryLedger":
        """Build the plan from a raw ds-config dict (stdlib-only: reads the
        JSON keys directly, never the pydantic tree). ``mesh_shape`` is the
        named-axis mesh (``dict(mesh.shape)``); the ZeRO world is its
        ``fsdp * fsdp_out`` span."""
        zc = raw.get("zero_optimization", {}) or {}
        opt_off = zc.get("offload_optimizer", {}) or {}
        par_off = zc.get("offload_param", {}) or {}
        mesh_shape = mesh_shape or raw.get("mesh", {}) or {}
        zw = int(mesh_shape.get("fsdp", 1) or 1) \
            * int(mesh_shape.get("fsdp_out",
                                 mesh_shape.get("fsdp_outer", 1)) or 1)
        if raw.get("bf16", raw.get("bfloat16", {})).get("enabled"):
            compute = "bf16"
        elif raw.get("fp16", {}).get("enabled"):
            compute = "fp16"
        else:
            compute = "fp32"
        opt_type = (raw.get("optimizer", {}) or {}).get("type", "adamw")
        moments = 1 if str(opt_type).lower() in ("sgd", "momentum") else 2
        ac = raw.get("activation_checkpointing", {}) or {}
        hints = dict(
            micro_batch=raw.get("train_micro_batch_size_per_gpu"),
            remat_policy=ac.get("policy", "nothing_saveable"),
            loss_chunked=bool(raw.get("loss_chunk_size", 0)),
        )
        hints.update(shape_hints)
        return cls(
            num_params=num_params,
            zero_stage=int(zc.get("stage", 0) or 0),
            zero_world=zw,
            compute_dtype=compute,
            optimizer_moments=moments,
            grad_accum_dtype=(raw.get("data_types", {}) or {}
                              ).get("grad_accum_dtype"),
            offload_optimizer=opt_off.get("device", "none") or "none",
            # dslint: disable=DS002 -- config-dict scalar, not an array
            offload_optimizer_ratio=float(opt_off.get("ratio", 1.0) or 1.0),
            offload_param=par_off.get("device", "none") or "none",
            layers_per_group=int(par_off.get("layers_per_group", 1) or 1),
            gather_on_save=bool(zc.get("gather_16bit_weights_on_model_save",
                                       True)),
            **hints)


# -- reference estimator APIs (deepspeed.runtime.zero.stage_1_and_2 /
#    stage3 ``estimate_zero*_model_states_mem_needs``) ----------------------
def estimate_zero2_model_states_mem_needs(
        total_params: int, num_gpus_per_node: int = 1, num_nodes: int = 1,
        cpu_offload: bool = True,
        additional_buffer_factor: float = 1.5) -> Tuple[int, int]:
    """Reference-shaped ZeRO-2 estimator: returns ``(device_bytes,
    host_bytes)`` per device. With offload the device keeps only the
    fp16/bf16 params (2 bytes/param) and the host carries masters + Adam
    moments (+ the reference's buffer factor); without it the device adds
    the 16-bytes/param optimizer block sharded over the world."""
    world = max(num_gpus_per_node * num_nodes, 1)
    p = int(total_params)
    if cpu_offload:
        gpu = 2 * p
        cpu = int(p * max(4 * world, 16) * additional_buffer_factor)
    else:
        gpu = 4 * p + 16 * p // world
        cpu = int(p * 4 * num_gpus_per_node * additional_buffer_factor)
    return gpu, cpu


def estimate_zero3_model_states_mem_needs(
        total_params: int, largest_layer_params: int = 0,
        num_gpus_per_node: int = 1, num_nodes: int = 1,
        cpu_offload: bool = True, cpu_offload_params: bool = False,
        additional_buffer_factor: float = 1.5) -> Tuple[int, int]:
    """Reference-shaped ZeRO-3 estimator (``(device_bytes, host_bytes)``):
    stage 3 shards everything, so the device floor is the largest layer's
    gathered params; offload tiers move the 16-18 bytes/param state host-
    side."""
    world = max(num_gpus_per_node * num_nodes, 1)
    p = int(total_params)
    largest = 4 * int(largest_layer_params)
    if cpu_offload:
        if cpu_offload_params:
            gpu = largest
            cpu = int(p * max(4 * world, 18 // max(num_nodes, 1))
                      * additional_buffer_factor)
        else:
            gpu = largest + 2 * p // world
            cpu = int(p * max(4 * world, 16 // max(num_nodes, 1))
                      * additional_buffer_factor)
    else:
        gpu = largest + 18 * p // world
        cpu = int(4 * largest_layer_params * num_gpus_per_node
                  * additional_buffer_factor)
    return gpu, cpu


# ---------------------------------------------------------------------------
# part 2: the live sampler
# ---------------------------------------------------------------------------
class MemorySampler:
    """Bounded-window device-HBM + host-RSS sampler feeding the dstrace
    ring as Chrome-trace counter tracks.

    Strictly off the hot path: the engine calls ``on_drain`` at the async
    ring's designated drain (the step boundary that already host-syncs)
    and at ``steps_per_print`` boundaries in sync mode; ``start()`` adds a
    background cadence thread for long idle/serve stretches. Both entry
    points are DS002-registered (``tools/dslint/hotpath.py``) so the
    linter proves sampling never grows a device sync — collection is
    allocator-stat dict reads and one ``/proc`` line, never a transfer.

    On backends without allocator stats (CPU: ``memory_stats() is None``)
    the device series are empty and host RSS still tracks."""

    def __init__(self, tracer=None, window: int = 512,
                 devices_fn: Optional[Callable[[], List[Any]]] = None):
        self._tracer = tracer
        #: deque append/iteration is GIL-atomic — the cadence thread and the
        #: drain hook never contend on a lock for the common path
        self.samples: collections.deque = collections.deque(
            maxlen=max(int(window), 8))
        self.phase = "init"
        self._lock = threading.Lock()          # phase_peaks merges only
        self._phase_peaks: Dict[str, Dict[str, int]] = {}
        self._devices_fn = devices_fn
        self._thread: Optional[threading.Thread] = None
        self._stop = threading.Event()
        self._page_size = 4096
        try:
            self._page_size = os.sysconf("SC_PAGE_SIZE")
        except (ValueError, OSError, AttributeError):
            pass

    # -- collection (registered hot path: must never device-sync) ----------
    def _collect(self) -> Dict[str, Any]:
        devices: Dict[str, Dict[str, int]] = {}
        try:
            if self._devices_fn is not None:
                devs = self._devices_fn()
            else:
                import jax                      # late: module stays jax-free
                devs = jax.local_devices()
            for d in devs:
                stats = d.memory_stats()
                if stats:
                    devices[str(d)] = {
                        "bytes_in_use": int(stats.get("bytes_in_use", 0)),
                        "peak_bytes_in_use":
                            int(stats.get("peak_bytes_in_use", 0)),
                        "bytes_limit": int(stats.get("bytes_limit", 0)),
                    }
        except Exception:                       # stats are best-effort
            pass
        rss = 0
        try:
            with open(f"/proc/{os.getpid()}/statm") as f:
                rss = int(f.read().split()[1]) * self._page_size
        except (OSError, ValueError, IndexError):
            pass
        return {"ts": time.time(), "phase": self.phase,
                "devices": devices, "host_rss_bytes": rss}

    def sample(self, step: Optional[int] = None,
               phase: Optional[str] = None) -> Dict[str, Any]:
        """One observation: collect, fold into the per-phase watermarks,
        and emit counter events (when a tracer is attached and enabled)."""
        if phase is not None:
            self.phase = phase
        s = self._collect()
        if step is not None:
            s["step"] = int(step)
        self.samples.append(s)
        with self._lock:
            peaks = self._phase_peaks.setdefault(
                s["phase"], {"hbm_bytes_in_use": 0, "hbm_peak_bytes": 0,
                             "host_rss_bytes": 0, "samples": 0})
            peaks["samples"] += 1
            for d in s["devices"].values():
                if d["bytes_in_use"] > peaks["hbm_bytes_in_use"]:
                    peaks["hbm_bytes_in_use"] = d["bytes_in_use"]
                if d["peak_bytes_in_use"] > peaks["hbm_peak_bytes"]:
                    peaks["hbm_peak_bytes"] = d["peak_bytes_in_use"]
            if s["host_rss_bytes"] > peaks["host_rss_bytes"]:
                peaks["host_rss_bytes"] = s["host_rss_bytes"]
        tr = self._tracer
        if tr is not None and tr.enabled:
            if s["devices"]:
                tr.counter(HBM_IN_USE_COUNTER, cat="mem",
                           **{k: v["bytes_in_use"]
                              for k, v in s["devices"].items()})
                tr.counter(HBM_PEAK_COUNTER, cat="mem",
                           **{k: v["peak_bytes_in_use"]
                              for k, v in s["devices"].items()})
                tr.counter(HBM_LIMIT_COUNTER, cat="mem",
                           **{k: v["bytes_limit"]
                              for k, v in s["devices"].items()})
            if s["host_rss_bytes"]:
                tr.counter(HOST_RSS_COUNTER, cat="mem",
                           rss=s["host_rss_bytes"])
        return s

    def seen(self, phase: str) -> bool:
        """Whether ``phase`` has at least one observation (dict membership
        — GIL-atomic, safe from the hot path): the engine's sync-mode hook
        samples each phase's FIRST step even off the print boundary, so
        short runs still populate every lifecycle bucket."""
        return phase in self._phase_peaks

    def on_drain(self, step: Optional[int] = None) -> None:
        """The engine's step-boundary hook (called from the designated
        drain / the sync-mode print boundary — points that already pay a
        host sync, so sampling here adds zero new synchronization)."""
        self.sample(step=step)

    # -- background cadence -------------------------------------------------
    def start(self, cadence_s: float) -> "MemorySampler":
        if self._thread is not None:
            return self
        cadence_s = max(float(cadence_s), 0.05)
        self._stop.clear()

        def _loop():
            while not self._stop.wait(cadence_s):
                try:
                    self.sample()
                except Exception:
                    logger.exception("dsmem: background sample failed")

        self._thread = threading.Thread(target=_loop, name="dstpu-mem",
                                        daemon=True)
        self._thread.start()
        return self

    def stop(self) -> None:
        self._stop.set()
        t = self._thread
        if t is not None:
            t.join(timeout=2.0)
            self._thread = None

    # -- read side -----------------------------------------------------------
    def watermarks(self) -> Dict[str, Dict[str, int]]:
        with self._lock:
            return {k: dict(v) for k, v in self._phase_peaks.items()}

    def tail(self, n: int = 32) -> List[Dict[str, Any]]:
        return list(self.samples)[-max(int(n), 0):]

    def bytes_limit(self) -> int:
        """Largest per-device ``bytes_limit`` seen (0 when the backend has
        no allocator stats)."""
        limit = 0
        for s in self.samples:
            for d in s["devices"].values():
                if d["bytes_limit"] > limit:
                    limit = d["bytes_limit"]
        return limit

    def report(self, ledger: Optional[MemoryLedger] = None,
               source: str = "<live>") -> Dict[str, Any]:
        """The mem report artifact ``dstpu mem`` consumes: plan (when a
        ledger is given) + observed per-phase watermarks + latest device
        stats."""
        last_devices: Dict[str, Dict[str, int]] = {}
        for s in self.samples:
            if s["devices"]:
                last_devices = s["devices"]
        return {
            "version": MEM_REPORT_VERSION,
            "source": source,
            "bytes_limit": self.bytes_limit(),
            "plan": ledger.to_dict() if ledger is not None else None,
            "observed": {"phases": self.watermarks(),
                         "num_samples": len(self.samples)},
            "devices": last_devices,
        }

    def export(self, path: str, ledger: Optional[MemoryLedger] = None,
               source: Optional[str] = None) -> Dict[str, Any]:
        rep = self.report(ledger=ledger,
                          source=source or os.path.basename(path))
        d = os.path.dirname(os.path.abspath(path))
        if d:
            os.makedirs(d, exist_ok=True)
        with open(path, "w") as f:
            json.dump(rep, f, indent=2, sort_keys=True)
            f.write("\n")
        return rep


# ---------------------------------------------------------------------------
# part 3a: plan-vs-observed tie-out
# ---------------------------------------------------------------------------
def tie_out(report: Dict[str, Any]) -> List[Dict[str, Any]]:
    """Per-phase plan-vs-observed rows. ``delta_frac`` is observed/plan - 1
    (positive = the plan under-estimated). Rows without both sides carry
    None deltas — informational, never a verdict (the ratchet baseline is
    the deterministic gate)."""
    plan = (report.get("plan") or {}).get("phases", {})
    observed = (report.get("observed") or {}).get("phases", {})
    rows = []
    for phase in PHASES:
        p = plan.get(phase, {}).get("hbm_bytes")
        o = observed.get(phase, {}).get("hbm_peak_bytes")
        if o in (None, 0):
            o = observed.get(phase, {}).get("hbm_bytes_in_use")
        delta = None
        if p and o:
            delta = round(o / p - 1.0, 4)
        rows.append({"phase": phase, "plan_hbm_bytes": p,
                     "observed_hbm_bytes": o, "delta_frac": delta,
                     "observed_host_rss_bytes":
                         observed.get(phase, {}).get("host_rss_bytes")})
    return rows


# ---------------------------------------------------------------------------
# part 3b: the ratchet baseline (dslint/plan idiom)
# ---------------------------------------------------------------------------
#: baseline metrics per phase — device watermark and host RSS watermark
_BASELINE_METRICS = ("hbm_peak_bytes", "host_rss_bytes")


def load_mem_baseline(path: str) -> dict:
    with open(path) as f:
        data = json.load(f)
    if data.get("version") != MEM_BASELINE_VERSION:
        raise ValueError(f"unsupported mem baseline version "
                         f"{data.get('version')!r} in {path} "
                         f"(expected {MEM_BASELINE_VERSION})")
    return data


def find_mem_baseline(start: str) -> Optional[str]:
    """Walk up from ``start`` for the checked-in baseline (dslint/plan
    discovery rule — anchored at the artifact, never the cwd)."""
    d = os.path.abspath(start)
    if os.path.isfile(d):
        d = os.path.dirname(d)
    while True:
        cand = os.path.join(d, MEM_BASELINE_NAME)
        if os.path.exists(cand):
            return cand
        parent = os.path.dirname(d)
        if parent == d:
            return None
        d = parent


def write_mem_baseline(path: str, report: Dict[str, Any],
                       tolerance: float = 1.25,
                       min_abs_bytes: int = 1 << 20) -> dict:
    """Record the report's observed per-phase watermarks as the baseline.
    ``workload`` (the report's source basename) scopes discovered
    baselines exactly like the plan ledger's."""
    phases = (report.get("observed") or {}).get("phases", {})
    data = {
        "version": MEM_BASELINE_VERSION,
        "workload": os.path.basename(str(report.get("source", ""))),
        "tolerance": float(tolerance),
        "min_abs_bytes": int(min_abs_bytes),
        "entries": {
            phase: {m: int(phases[phase].get(m, 0))
                    for m in _BASELINE_METRICS}
            for phase in PHASES if phase in phases},
    }
    with open(path, "w") as f:
        json.dump(data, f, indent=2, sort_keys=True)
        f.write("\n")
    return data


def check_mem_baseline(report: Dict[str, Any], baseline: dict,
                       tolerance: Optional[float] = None
                       ) -> Tuple[List[dict], List[dict]]:
    """``(regressions, stale)``. A phase REGRESSES when its observed
    watermark exceeds baseline * tolerance AND by more than the absolute
    floor; it is STALE when it improved past the same margin (expire via
    ``--write-baseline`` — the ratchet)."""
    tol = float(tolerance if tolerance is not None
                else baseline.get("tolerance", 1.25))
    floor = int(baseline.get("min_abs_bytes", 1 << 20))
    phases = (report.get("observed") or {}).get("phases", {})
    regressions, stale = [], []
    for phase, entry in sorted(baseline.get("entries", {}).items()):
        obs = phases.get(phase)
        if obs is None:
            continue
        for metric in _BASELINE_METRICS:
            base = int(entry.get(metric, 0))
            cur = int(obs.get(metric, 0))
            row = {"phase": phase, "metric": metric,
                   "baseline_bytes": base, "current_bytes": cur,
                   "ratio": round(cur / base, 3) if base > 0 else None}
            if cur > base * tol and (cur - base) > floor:
                regressions.append(row)
            elif base > cur * tol and (base - cur) > floor:
                stale.append(row)
    return regressions, stale


# ---------------------------------------------------------------------------
# part 3c: preflight
# ---------------------------------------------------------------------------
#: the offload escalation ladder preflight suggests from, in order: each
#: entry is (predicate over ledger, suggestion text, config override)
def next_offload_tier(ledger: MemoryLedger) -> Optional[Dict[str, Any]]:
    """The next rung of the offload ladder for a plan that does not fit:
    shard harder first (free), then optimizer offload, then param offload,
    then NVMe — the ZeRO-Offload escalation order."""
    if ledger.zero_stage < 1 and ledger.zero_world > 1:
        return {"suggestion": "shard optimizer state over the fsdp axis "
                              "(free HBM, no host traffic)",
                "overrides": {"zero_optimization": {"stage": 1}}}
    if ledger.zero_stage < 3 and ledger.zero_world > 1:
        return {"suggestion": f"raise zero_stage {ledger.zero_stage} -> 3 "
                              "(shard params + grads over the fsdp axis)",
                "overrides": {"zero_optimization": {"stage": 3}}}
    if ledger.offload_optimizer == "none":
        return {"suggestion": "offload optimizer state to host RAM "
                              "(ZeRO-Offload tier: frees "
                              f"{ledger.optimizer_moments * 4}"
                              " bytes/param of HBM)",
                "overrides": {"zero_optimization": {
                    "offload_optimizer": {"device": "cpu"}}}}
    if ledger.offload_param == "none":
        return {"suggestion": "stream params from host RAM "
                              "(offload_param: cpu — ZeRO-Infinity tier)",
                "overrides": {"zero_optimization": {
                    "offload_param": {"device": "cpu"}}}}
    if "nvme" not in (ledger.offload_optimizer, ledger.offload_param):
        return {"suggestion": "swap masters+moments to NVMe "
                              "(offload_*.device: nvme)",
                "overrides": {"zero_optimization": {
                    "offload_optimizer": {"device": "nvme"}}}}
    return None


def deep_merge(dst: Dict[str, Any], src: Dict[str, Any]) -> Dict[str, Any]:
    """Recursive dict merge (``src`` wins) — the shape config overrides
    ride in (``next_offload_tier``'s nested ``overrides`` dicts)."""
    for k, v in src.items():
        if isinstance(v, dict) and isinstance(dst.get(k), dict):
            deep_merge(dst[k], v)
        else:
            dst[k] = v
    return dst


def plan_world_config(raw: Dict[str, Any], num_params: int, world_chips: int,
                      bytes_limit: int, max_rungs: int = 8) -> Dict[str, Any]:
    """Re-plan a training config for a DIFFERENT chip count — the
    shrink-aware relauncher's preflight (all stdlib + analytic, no devices
    touched). Builds the per-chip ledger at ``world_chips``, and while the
    plan does not fit ``bytes_limit``, escalates the offload ladder
    (``next_offload_tier``: stage 1 -> 3 -> optimizer offload -> param
    offload -> nvme) by merging each rung's overrides into a config copy.

    The candidate mesh is the data/fsdp world scaled to ``world_chips``
    (explicit tensor/expert/sequence/pipe extents in ``raw["mesh"]`` are
    preserved and divided out of the dp/fsdp span) — placement derives
    from mesh + memory plan, not a hand-edited table.

    Returns ``{"config", "overrides", "escalations", "verdict", "ledger"}``:
    ``overrides`` is the single merged dict a relauncher exports to
    workers; ``verdict`` is the final ``preflight`` result (``fits`` False
    means even the full ladder cannot fit — the caller's refuse/warn
    policy decides what happens next)."""
    import copy
    cfg = copy.deepcopy(raw or {})
    model_axes = {a: int((raw.get("mesh", {}) or {}).get(a, 1) or 1)
                  for a in ("pipe", "tensor", "expert", "sequence")}
    model_world = 1
    for v in model_axes.values():
        model_world *= max(v, 1)
    zero_world = max(1, int(world_chips) // model_world)
    effective_chips = zero_world * model_world
    notes = []
    if effective_chips != int(world_chips):
        # a chip count that does not divide the model-parallel extent
        # cannot build the mesh at all — plan the (conservative: fewer
        # chips = more bytes/chip) divisible floor, and SAY so rather than
        # silently pricing a world that will not launch
        notes.append(
            f"world_chips {world_chips} not divisible by the model-parallel "
            f"extent {model_world} ({model_axes}); planned for "
            f"{effective_chips} chips — launching {world_chips} will fail "
            f"mesh construction")
    mesh_shape = dict(model_axes)
    mesh_shape.update({"data": 1, "fsdp_out": 1, "fsdp": zero_world})

    overrides: Dict[str, Any] = {}
    escalations = []
    ledger = MemoryLedger.from_config(cfg, num_params=num_params,
                                      mesh_shape=mesh_shape)
    verdict = preflight(ledger, bytes_limit)
    while bytes_limit and not verdict["fits"] and len(escalations) < max_rungs:
        rung = verdict.get("suggestion") or next_offload_tier(ledger)
        if rung is None:
            break
        deep_merge(cfg, rung["overrides"])
        deep_merge(overrides, rung["overrides"])
        escalations.append(rung["suggestion"])
        ledger = MemoryLedger.from_config(cfg, num_params=num_params,
                                          mesh_shape=mesh_shape)
        verdict = preflight(ledger, bytes_limit)
    return {"config": cfg, "overrides": overrides,
            "escalations": escalations, "verdict": verdict,
            "ledger": ledger.to_dict(), "mesh_shape": mesh_shape,
            "world_chips": int(world_chips),
            "world_chips_effective": effective_chips, "notes": notes}


def plan_from_provenance(prov: Dict[str, Any], world_workers: int,
                         default_config: Optional[Dict[str, Any]] = None
                         ) -> Optional[Dict[str, Any]]:
    """``plan_world_config`` driven by a checkpoint's ``ds_meta.json``
    provenance block — the ONE derivation (num_params, recorded HBM limit,
    chips-per-worker from the saved world) shared by the elastic agent's
    shrink preflight and ``dstpu_ckpt inspect --compat``, so the CLI's
    verdict can never diverge from what the agent actually launches.
    Returns None when the provenance carries no param count to plan from."""
    num_params = ((prov or {}).get("params") or {}).get("count", 0)
    if not num_params:
        return None
    bytes_limit = (prov.get("ledger") or {}).get("bytes_limit", 0)
    raw = prov.get("config") or default_config or {}
    return plan_world_config(
        raw, num_params=num_params,
        world_chips=int(world_workers) * provenance_chips_per_worker(prov),
        bytes_limit=bytes_limit)


def provenance_chips_per_worker(prov: Dict[str, Any]) -> int:
    """Chips one worker of this checkpoint's topology drives. For a
    multi-process save it is device_count / process_count; for a
    single-process save (no worker concept) it is 1 — a target ``world``
    then reads naturally as a CHIP count."""
    saved = (prov or {}).get("world") or {}
    pc = max(1, int(saved.get("process_count", 1)))
    if pc <= 1:
        return 1
    return max(1, int(saved.get("device_count", 1)) // pc)


def preflight(ledger: MemoryLedger, bytes_limit: int,
              headroom_frac: float = 0.05) -> Dict[str, Any]:
    """Plan vs device limit, before any allocation: ``fits`` is the hard
    verdict, ``tight`` flags under-headroom plans, ``suggestion`` is the
    next offload tier when the plan must shrink."""
    phases = ledger.phase_bytes()
    worst_phase = max(PHASES, key=lambda ph: phases[ph]["hbm_bytes"])
    need = phases[worst_phase]["hbm_bytes"]
    out: Dict[str, Any] = {
        "bytes_limit": int(bytes_limit),
        "required_bytes": need,
        "worst_phase": worst_phase,
        "fits": not bytes_limit or need <= bytes_limit,
        "tight": bool(bytes_limit)
        and need > bytes_limit * (1.0 - headroom_frac)
        and need <= bytes_limit,
        "suggestion": None,
    }
    if bytes_limit and (not out["fits"] or out["tight"]):
        out["suggestion"] = next_offload_tier(ledger)
    return out


# ---------------------------------------------------------------------------
# CLI — ``bin/dstpu mem``
# ---------------------------------------------------------------------------
def _fmt_bytes(n: Optional[int]) -> str:
    if n is None:
        return "-"
    n = int(n)
    for unit in ("B", "KB", "MB", "GB", "TB"):
        if abs(n) < 1024 or unit == "TB":
            return f"{n:.2f}{unit}" if unit != "B" else f"{n}B"
        n /= 1024.0
    return str(n)


def render_report(report: Dict[str, Any]) -> str:
    out = [f"dstpu mem — {report.get('source')}"]
    limit = report.get("bytes_limit") or 0
    out.append(f"bytes_limit: {_fmt_bytes(limit) if limit else 'unknown'}")
    out.append("")
    out.append(f"{'phase':<12} {'plan HBM':>12} {'observed HBM':>14} "
               f"{'delta':>8} {'host RSS':>12}")
    out.append("-" * 62)
    for row in tie_out(report):
        delta = "-" if row["delta_frac"] is None \
            else f"{row['delta_frac'] * 100:+.1f}%"
        out.append(f"{row['phase']:<12} "
                   f"{_fmt_bytes(row['plan_hbm_bytes']):>12} "
                   f"{_fmt_bytes(row['observed_hbm_bytes']):>14} "
                   f"{delta:>8} "
                   f"{_fmt_bytes(row['observed_host_rss_bytes']):>12}")
    plan = report.get("plan")
    if plan:
        out.append("")
        out.append("plan components (HBM / host):")
        for name, c in plan.get("components", {}).items():
            out.append(f"  {name:<14} {_fmt_bytes(c['hbm_bytes']):>12} "
                       f"{_fmt_bytes(c['host_bytes']):>12}")
        for note in plan.get("notes", []):
            out.append(f"  note: {note}")
    return "\n".join(out)


def _load_report(path: str) -> Dict[str, Any]:
    try:
        with open(path) as f:
            data = json.load(f)
    except (OSError, ValueError) as e:
        raise ValueError(f"unreadable mem report {path}: {e}")
    if not isinstance(data, dict) or "observed" not in data:
        raise ValueError(f"not a mem report (no 'observed' section): {path}")
    return data


def main(argv=None) -> int:
    parser = argparse.ArgumentParser(
        prog="dstpu mem",
        description="memory ledger tie-out, watermark ratchet, and "
                    "analytic preflight (artifact: engine."
                    "dump_memory_report / MemorySampler.export)")
    parser.add_argument("artifact", nargs="?", default=None,
                        help="mem report JSON (plan + observed watermarks)")
    parser.add_argument("--preflight", metavar="CONFIG",
                        help="analytic-only mode: build the ledger from "
                             "this ds-config JSON and check it against "
                             "--bytes-limit (exit 1 when it cannot fit)")
    parser.add_argument("--params", type=int, default=0,
                        help="model parameter count for --preflight")
    parser.add_argument("--bytes-limit", type=int, default=0,
                        help="per-device HBM limit for --preflight "
                             "(default: the artifact's recorded limit)")
    parser.add_argument("--baseline", default=None,
                        help=f"mem baseline path (default: walk up from "
                             f"the artifact for {MEM_BASELINE_NAME})")
    parser.add_argument("--write-baseline", action="store_true",
                        help="record this report's watermarks as the new "
                             "baseline (ratchet: how stale entries expire)")
    parser.add_argument("--tolerance", type=float, default=None,
                        help="regression factor vs baseline (default: the "
                             "factor stored in the baseline, 1.25 fresh)")
    parser.add_argument("--json", action="store_true",
                        help="print the report (+ verdicts) as JSON")
    args = parser.parse_args(argv)

    if args.preflight:
        return _preflight_main(args)
    if not args.artifact:
        parser.error("an artifact path (or --preflight CONFIG) is required")

    try:
        report = _load_report(args.artifact)
    except ValueError as e:
        print(f"dstpu mem: {e}", file=sys.stderr)
        return EXIT_UNREADABLE

    bl_path = args.baseline or find_mem_baseline(args.artifact)
    regressions, stale = [], []
    effective_tol = args.tolerance if args.tolerance is not None else 1.25
    if args.write_baseline:
        target = bl_path or os.path.join(
            os.path.dirname(os.path.abspath(args.artifact)),
            MEM_BASELINE_NAME)
        if args.tolerance is None and os.path.exists(target):
            try:      # ratchet rewrite keeps the stored factor
                effective_tol = float(load_mem_baseline(target)
                                      .get("tolerance", 1.25))
            except (OSError, ValueError):
                pass
        write_mem_baseline(target, report, tolerance=effective_tol)
        print(f"# mem baseline written -> {target}", file=sys.stderr)
        bl_path = target
    elif bl_path:
        try:
            baseline = load_mem_baseline(bl_path)
        except (OSError, ValueError) as e:
            print(f"dstpu mem: bad baseline {bl_path}: {e}", file=sys.stderr)
            return EXIT_UNREADABLE
        bl_workload = baseline.get("workload")
        workload = os.path.basename(str(report.get("source", "")))
        if args.baseline is None and bl_workload \
                and bl_workload != workload:
            # discovered baseline of ANOTHER workload: its watermarks say
            # nothing about this run — note, don't fabricate a verdict
            print(f"# note: discovered baseline {bl_path} is for workload "
                  f"{bl_workload!r}, not {workload!r} — comparison skipped "
                  "(pass --baseline to compare anyway, or --write-baseline "
                  "to start ratcheting this workload)", file=sys.stderr)
            bl_path = None
        else:
            regressions, stale = check_mem_baseline(
                report, baseline, tolerance=args.tolerance)
            effective_tol = args.tolerance if args.tolerance is not None \
                else float(baseline.get("tolerance", 1.25))
    report["baseline"] = {"path": bl_path, "regressions": regressions,
                          "stale": stale}

    # informational preflight against the recorded limit: a plan that no
    # longer fits the device it ran on deserves a loud line even when the
    # ratchet is quiet
    plan_pf = None
    if report.get("plan") and (args.bytes_limit
                               or report.get("bytes_limit")):
        inputs = report["plan"].get("inputs", {})
        phases = report["plan"].get("phases", {})
        limit = args.bytes_limit or report["bytes_limit"]
        need = max((v.get("hbm_bytes", 0) for v in phases.values()),
                   default=0)
        plan_pf = {"bytes_limit": limit, "required_bytes": need,
                   "fits": need <= limit}
        report["preflight"] = plan_pf
        if not plan_pf["fits"]:
            print(f"WARNING: plan needs {_fmt_bytes(need)} HBM but the "
                  f"device limit is {_fmt_bytes(limit)} — run "
                  f"`dstpu mem --preflight` on the config for the next "
                  f"offload tier (inputs: {json.dumps(inputs)})",
                  file=sys.stderr)

    if args.json:
        print(json.dumps(report, indent=2))
    else:
        print(render_report(report))
        for r in regressions:
            ratio = "new watermark" if r["ratio"] is None \
                else f"{r['ratio']}x"
            print(f"REGRESSION: {r['phase']} {r['metric']} "
                  f"{_fmt_bytes(r['baseline_bytes'])} -> "
                  f"{_fmt_bytes(r['current_bytes'])} "
                  f"({ratio}, tolerance {effective_tol}x) "
                  f"vs {bl_path}", file=sys.stderr)
        for r in stale:
            print(f"stale baseline entry (improved): {r['phase']} "
                  f"{r['metric']} {_fmt_bytes(r['baseline_bytes'])} -> "
                  f"{_fmt_bytes(r['current_bytes'])} — re-run with "
                  "--write-baseline to ratchet", file=sys.stderr)
    return EXIT_REGRESSION if regressions else EXIT_OK


def _preflight_main(args) -> int:
    try:
        with open(args.preflight) as f:
            raw = json.load(f)
    except (OSError, ValueError) as e:
        print(f"dstpu mem: unreadable config {args.preflight}: {e}",
              file=sys.stderr)
        return EXIT_UNREADABLE
    ledger = MemoryLedger.from_config(raw, num_params=args.params)
    verdict = preflight(ledger, args.bytes_limit)
    out = {"ledger": ledger.to_dict(), "preflight": verdict}
    if args.json:
        print(json.dumps(out, indent=2))
    else:
        print(f"plan: {_fmt_bytes(verdict['required_bytes'])} HBM at the "
              f"'{verdict['worst_phase']}' watermark"
              + (f" vs limit {_fmt_bytes(verdict['bytes_limit'])}"
                 if verdict["bytes_limit"] else " (no --bytes-limit given)"))
        if not verdict["fits"]:
            print("verdict: DOES NOT FIT", file=sys.stderr)
        elif verdict["tight"]:
            print("verdict: fits, but under 5% headroom", file=sys.stderr)
        else:
            print("verdict: fits")
        sug = verdict.get("suggestion")
        if sug:
            print(f"suggestion: {sug['suggestion']}\n  overrides: "
                  f"{json.dumps(sug['overrides'])}", file=sys.stderr)
    return EXIT_OK if verdict["fits"] else EXIT_REGRESSION


if __name__ == "__main__":
    sys.exit(main())
