"""dstpu reqtrace — per-request timeline stitching across the fleet.

The request-scoped half of the cross-process observability story.
``crossrank`` answers "which RANK made the collective slow" by joining
per-rank rings on ``op_seq``; this module answers "where did REQUEST X's
latency go" by joining the router's and every replica's rings on the
fleet-wide **trace id** (minted at the router, propagated via the
``X-Dstpu-Trace`` header / ``trace_id`` body field, stamped on every
``req/*`` span — see ``telemetry/names.py``).

Per trace id, the stitched timeline holds:

* the router's ``req/wall`` **envelope** — the router-observed wall time
  from route entry to the terminal verdict, the denominator every other
  number is stated against;
* per-replica **visit chains** — ``req/queue`` -> ``req/prefill`` ->
  ``req/decode`` retro-spans (shared monotonic edges, so the chain sum
  is exact within each process), grouped by source process;
* **router-attributed gaps** — ``req/reroute`` spans covering failover
  backoffs, the link between a dying replica's chain and its
  survivor's;
* **recovered ledgers** — a replica that died mid-request never emitted
  its retro-spans, but its flight-recorder dump (``serving.server
  .flight_dump``) carries the in-flight ``describe()`` ledgers; those
  fold in as duration-only ``recovered`` entries so the killed attempt
  is visible, not vanished.

**Tie-out invariant** (the crossrank discipline applied per request):
phase + reroute span time must fit inside the wall envelope without
overlap — ``tie_out_error = (span_sum − covered_inside_wall) /
wall_dur``. In a clean stitch the spans nest disjointly inside the
envelope and the error is ~0; clock misalignment or a broken trace-id
join pushes spans outside the envelope (or on top of each other) and
the error grows past ``TIE_OUT_TOLERANCE`` — the row is flagged, not
trusted. ``req/handoff`` is deliberately OUTSIDE the conservation sum:
it sub-spans the prefill->decode boundary inside time the phase spans
already cover (including it would double-count by construction).

Clock alignment reuses crossrank's wall-anchor rule: each dump's
process-identity header pins monotonic ts to wall time; dumps without a
header fold in unaligned (offset 0) and are flagged — their spans still
group by trace id, but their tie-out rows are suspect by definition.

Offline-only, by contract: stdlib-only, file-loadable on jax-less hosts
(sibling-load idiom for ``names.py``/``crossrank.py``), listed in
``OFFLINE_ONLY_MODULES`` — it replays whole dumps and must never ride a
hot path.
"""

import argparse
import json
import os
import sys
from typing import Any, Dict, List, Optional, Tuple


def _load_sibling(mod_name: str, filename: str):
    """File-load a sibling telemetry module — never a package import:
    this module loads standalone on jax-less hosts (crossrank's
    ``_load_trace_names`` idiom, generalized)."""
    import importlib.util
    mod = sys.modules.get(mod_name)
    if mod is None:
        path = os.path.join(os.path.dirname(os.path.abspath(__file__)),
                            filename)
        spec = importlib.util.spec_from_file_location(mod_name, path)
        mod = importlib.util.module_from_spec(spec)
        spec.loader.exec_module(mod)
        sys.modules[mod_name] = mod
    return mod


_names = _load_sibling("dstpu_trace_names", "names.py")
_crossrank = _load_sibling("dstpu_crossrank", "crossrank.py")

REQ_PREFIX = _names.REQ_PREFIX
REQ_TRACE_ARG = _names.REQ_TRACE_ARG
REQ_WALL_NAME = _names.REQ_WALL_NAME
REQ_HANDOFF_NAME = _names.REQ_HANDOFF_NAME
REQ_REROUTE_NAME = _names.REQ_REROUTE_NAME
REQ_STAGE_OF = _names.REQ_STAGE_OF

EXIT_OK = 0
EXIT_REGRESSION = 1
EXIT_UNREADABLE = 2

REQTRACE_VERSION = 1
REQTRACE_ARTIFACT_ENV = "DSTPU_REQTRACE_ARTIFACT"
DEFAULT_REQTRACE_ARTIFACT = "reqtrace.json"

#: per-trace tie-out: phase+reroute span time that does not fit inside
#: the wall envelope without overlap, as a fraction of the envelope —
#: the same 5% alignment-sanity bar crossrank's windows use
TIE_OUT_TOLERANCE = 0.05

#: the conservation sum's members: phase chains + router-attributed
#: gaps. req/wall is the denominator, req/handoff is a sub-span of time
#: the phases already cover (counting it would double-book).
_CONSERVED = frozenset(n for n in REQ_STAGE_OF if n != REQ_HANDOFF_NAME)


class ReqTraceError(Exception):
    """Unreadable/unstitchable input — maps to CLI exit code 2."""


# ---------------------------------------------------------------------------
# dump loading
# ---------------------------------------------------------------------------
def _load_source(path: str, index: int) -> Dict[str, Any]:
    """One dump -> {path, kind, ident, base_us, events, flight}. A flight
    dump is an ordinary Chrome trace whose ``otherData.flight`` carries
    the dying process's in-flight request ledgers."""
    try:
        obj = _crossrank.load_dump(path)
    except _crossrank.CrossRankError as e:
        raise ReqTraceError(str(e)) from e
    ident = _crossrank.dump_identity(obj, fallback_rank=index)
    flight = (obj.get("otherData") or {}).get("flight")
    return {
        "path": path,
        "kind": "flight" if isinstance(flight, dict) else "ring",
        "ident": ident,
        "base_us": _crossrank._wall_base_us(ident),
        "events": [e for e in obj.get("traceEvents", ())
                   if isinstance(e, dict)],
        "flight": flight if isinstance(flight, dict) else None,
    }


def _req_spans(src: Dict[str, Any], src_idx: int
               ) -> Tuple[List[Dict[str, Any]], int]:
    """Extract one source's ``req/*`` complete spans on the shared wall
    axis. Returns ``(spans, malformed)`` — a req/ span without a trace_id
    arg cannot join anything and counts as malformed (an orphan by
    construction)."""
    base = src["base_us"]
    spans: List[Dict[str, Any]] = []
    malformed = 0
    for e in src["events"]:
        name = str(e.get("name", ""))
        if e.get("ph") != "X" or not name.startswith(REQ_PREFIX):
            continue
        args = e.get("args") or {}
        trace_id = args.get(REQ_TRACE_ARG)
        if trace_id is None:
            malformed += 1
            continue
        ts = float(e.get("ts", 0.0))
        dur = max(float(e.get("dur", 0.0)), 0.0)
        start = (base + ts) if base is not None else ts
        spans.append({
            "trace_id": str(trace_id),
            "name": name,
            "source": src_idx,
            "aligned": base is not None,
            "start_us": start,
            "end_us": start + dur,
            "dur_us": dur,
            "args": {k: v for k, v in args.items() if k != REQ_TRACE_ARG},
        })
    return spans, malformed


def _flight_ledgers(src: Dict[str, Any], src_idx: int
                    ) -> List[Dict[str, Any]]:
    """The duration-only recovered entries from one flight dump's
    in-flight/queued request ledgers (``Request.describe()`` dicts)."""
    out: List[Dict[str, Any]] = []
    flight = src["flight"] or {}
    for state_key in ("inflight", "queued"):
        for entry in flight.get(state_key) or ():
            if not isinstance(entry, dict):
                continue
            trace_id = entry.get("trace_id")
            if trace_id is None:
                continue
            out.append({
                "trace_id": str(trace_id),
                "source": src_idx,
                "replica_id": flight.get("replica_id"),
                "reason": flight.get("reason"),
                "was": state_key,
                "state": entry.get("state"),
                "generated_tokens": entry.get("generated_tokens", 0),
                "queue_wait_s": entry.get("queue_wait_s"),
                "ttft_s": entry.get("ttft_s"),
            })
    return out


# ---------------------------------------------------------------------------
# stitching
# ---------------------------------------------------------------------------
def _covered_us(intervals: List[Tuple[float, float]],
                lo: float, hi: float) -> float:
    """Length of the union of ``intervals`` clipped to ``[lo, hi]`` — the
    sweep the tie-out compares raw span time against (overlap and
    out-of-envelope time both vanish from the union but not the sum)."""
    clipped = sorted((max(a, lo), min(b, hi)) for a, b in intervals
                     if min(b, hi) > max(a, lo))
    total, cur_a, cur_b = 0.0, None, None
    for a, b in clipped:
        if cur_b is None or a > cur_b:
            if cur_b is not None:
                total += cur_b - cur_a
            cur_a, cur_b = a, b
        else:
            cur_b = max(cur_b, b)
    if cur_b is not None:
        total += cur_b - cur_a
    return total


def stitch_requests(paths: List[str]) -> Dict[str, Any]:
    """Stitch per-process dstrace dumps (router + replicas + recovered
    flight dumps) into per-request timelines keyed by trace id.

    Every trace id with a ``req/wall`` envelope becomes a request row:
    per-source visit chains, reroute links, recovered flight ledgers,
    unattributed gap, and the tie-out verdict. Spans whose trace id has
    no envelope anywhere are **orphans** — counted loudly (an orphan is
    either a dropped router dump or a propagation bug), never silently
    merged away."""
    if not paths:
        raise ReqTraceError("nothing to stitch (no trace paths)")
    sources = [_load_source(p, i) for i, p in enumerate(paths)]

    all_spans: List[Dict[str, Any]] = []
    malformed = 0
    for i, src in enumerate(sources):
        spans, bad = _req_spans(src, i)
        all_spans.extend(spans)
        malformed += bad
    recovered: List[Dict[str, Any]] = []
    for i, src in enumerate(sources):
        if src["flight"] is not None:
            recovered.extend(_flight_ledgers(src, i))

    by_trace: Dict[str, List[Dict[str, Any]]] = {}
    for s in all_spans:
        by_trace.setdefault(s["trace_id"], []).append(s)
    rec_by_trace: Dict[str, List[Dict[str, Any]]] = {}
    for r in recovered:
        rec_by_trace.setdefault(r["trace_id"], []).append(r)

    traces: Dict[str, Dict[str, Any]] = {}
    orphan_spans = malformed
    orphan_traces: List[str] = []
    violations: List[str] = []
    max_err = 0.0
    for trace_id in sorted(set(by_trace) | set(rec_by_trace)):
        spans = sorted(by_trace.get(trace_id, ()),
                       key=lambda s: (s["start_us"], s["name"]))
        recs = rec_by_trace.get(trace_id, [])
        walls = [s for s in spans if s["name"] == REQ_WALL_NAME]
        if not walls:
            # no envelope anywhere: every span of this trace is an orphan
            orphan_spans += len(spans)
            orphan_traces.append(trace_id)
            traces[trace_id] = {"wall": None, "spans": spans,
                                "recovered": recs, "orphan": True}
            continue
        wall = walls[0]
        w0, w1 = wall["start_us"], wall["end_us"]
        wall_dur = max(wall["dur_us"], 0.0)
        phases = [s for s in spans
                  if s["name"] in _CONSERVED and s is not wall]
        # per-source visit chains, ordered by first span start — "which
        # replicas served this request, in what order". Reroute spans are
        # router-side gap attribution, not a replica visit.
        chain_spans = [s for s in phases if s["name"] != REQ_REROUTE_NAME]
        visit_order: List[int] = []
        for s in chain_spans:
            if s["source"] not in visit_order:
                visit_order.append(s["source"])
        visits = []
        for src_idx in visit_order:
            chain = [s for s in chain_spans if s["source"] == src_idx]
            visits.append({
                "source": src_idx,
                "pid": sources[src_idx]["ident"]["pid"],
                "stages": [REQ_STAGE_OF.get(s["name"]) for s in chain],
                "start_us": min(s["start_us"] for s in chain),
                "end_us": max(s["end_us"] for s in chain),
                "span_sum_us": sum(s["dur_us"] for s in chain),
            })
        span_sum = sum(s["dur_us"] for s in phases)
        aligned = all(s["aligned"] for s in spans)
        covered = _covered_us([(s["start_us"], s["end_us"])
                               for s in phases], w0, w1)
        # the conservation check: span time that did NOT land inside the
        # envelope as disjoint coverage is overflow — misalignment or a
        # broken join, never real latency
        overflow = max(span_sum - covered, 0.0)
        tie_out_error = (overflow / wall_dur) if wall_dur > 0 else 0.0
        gap_us = max(wall_dur - covered, 0.0)
        reroutes = sum(1 for s in spans if s["name"] == "req/reroute")
        row = {
            "wall": {"start_us": round(w0, 3), "end_us": round(w1, 3),
                     "dur_us": round(wall_dur, 3),
                     "outcome": wall["args"].get("outcome"),
                     "uid": wall["args"].get("uid"),
                     "source": wall["source"]},
            "spans": spans,
            "visits": visits,
            "recovered": recs,
            "reroutes": reroutes,
            "span_sum_us": round(span_sum, 3),
            "covered_us": round(covered, 3),
            "gap_us": round(gap_us, 3),
            "tie_out_error": round(tie_out_error, 6),
            "aligned": aligned,
            "flight_recovered": bool(recs),
            "orphan": False,
        }
        traces[trace_id] = row
        if tie_out_error > TIE_OUT_TOLERANCE:
            violations.append(trace_id)
        max_err = max(max_err, tie_out_error)

    unaligned_sources = [i for i, s in enumerate(sources)
                         if s["base_us"] is None]
    stitched = [t for t, row in traces.items() if not row["orphan"]]
    return {
        "version": REQTRACE_VERSION,
        "sources": [{
            "path": os.path.basename(s["path"]),
            "kind": s["kind"],
            "pid": s["ident"]["pid"],
            "hostname": s["ident"]["hostname"],
            "aligned": s["base_us"] is not None,
            "flight_reason": (s["flight"] or {}).get("reason")
            if s["flight"] else None,
        } for s in sources],
        "alignment": ("wall_anchor" if not unaligned_sources
                      else ("none" if len(unaligned_sources) == len(sources)
                            else "partial")),
        "unaligned_sources": unaligned_sources,
        "traces": traces,
        "requests_stitched": len(stitched),
        "orphan_spans": orphan_spans,
        "orphan_traces": orphan_traces,
        "recovered_requests": len(recovered),
        "flight_dumps": sum(1 for s in sources if s["kind"] == "flight"),
        "tie_out_violations": violations,
        "max_tie_out_error": round(max_err, 6),
    }


# ---------------------------------------------------------------------------
# rendering + CLI
# ---------------------------------------------------------------------------
def render(report: Dict[str, Any], top: int = 20) -> str:
    out = []
    out.append("dstpu reqtrace — per-request fleet timelines")
    out.append(f"{len(report['sources'])} sources "
               f"({report['flight_dumps']} flight dumps) | alignment "
               f"{report['alignment']} | {report['requests_stitched']} "
               f"requests stitched, {report['orphan_spans']} orphan spans, "
               f"{report['recovered_requests']} recovered from flight "
               f"dumps | max tie-out error "
               f"{report['max_tie_out_error'] * 100:.2f}%")
    out.append("")
    out.append(f"{'trace id':<22} {'wall ms':>9} {'visits':>7} "
               f"{'reroutes':>9} {'gap ms':>8} {'tie-out':>8}  flags")
    out.append("-" * 78)
    rows = [(t, r) for t, r in report["traces"].items() if not r["orphan"]]
    rows.sort(key=lambda kv: -(kv[1]["wall"]["dur_us"]))
    for trace_id, r in rows[:top]:
        flags = []
        if r["flight_recovered"]:
            flags.append("flight")
        if not r["aligned"]:
            flags.append("UNALIGNED")
        if r["tie_out_error"] > TIE_OUT_TOLERANCE:
            flags.append("TIE-OUT")
        out.append(f"{trace_id:<22} {r['wall']['dur_us'] / 1e3:>9.3f} "
                   f"{len(r['visits']):>7} {r['reroutes']:>9} "
                   f"{r['gap_us'] / 1e3:>8.3f} "
                   f"{r['tie_out_error'] * 100:>7.2f}%  "
                   f"{','.join(flags) or '-'}")
    if len(rows) > top:
        out.append(f"... {len(rows) - top} more requests")
    if report["orphan_traces"]:
        out.append("")
        out.append(f"orphan traces (spans but no req/wall envelope): "
                   f"{report['orphan_traces'][:10]}"
                   + (" ..." if len(report["orphan_traces"]) > 10 else ""))
    return "\n".join(out)


def main(argv=None) -> int:
    parser = argparse.ArgumentParser(
        prog="dstpu reqtrace",
        description="stitch router + replica dstrace dumps (and recovered "
                    "flight-recorder dumps) into per-request timelines "
                    "joined on the fleet trace id, with the span/wall "
                    "tie-out check")
    parser.add_argument("traces", nargs="+",
                        help="per-process Chrome-trace JSON dumps (router "
                             "ring, replica rings, flight dumps)")
    parser.add_argument("--out", default=None,
                        help="write the full artifact JSON here "
                             f"(env_report reads ${REQTRACE_ARTIFACT_ENV} "
                             f"or ./{DEFAULT_REQTRACE_ARTIFACT})")
    parser.add_argument("--json", action="store_true",
                        help="print the report as JSON instead of a table")
    parser.add_argument("--top", type=int, default=20,
                        help="requests to show (slowest first)")
    args = parser.parse_args(argv)
    try:
        report = stitch_requests(args.traces)
    except ReqTraceError as e:
        print(f"dstpu reqtrace: {e}", file=sys.stderr)
        return EXIT_UNREADABLE
    if args.out:
        with open(args.out, "w") as f:
            json.dump(report, f, indent=2)
            f.write("\n")
    if args.json:
        print(json.dumps(report, indent=2))
    else:
        print(render(report, top=args.top))
    for trace_id in report["tie_out_violations"]:
        err = report["traces"][trace_id]["tie_out_error"]
        print(f"WARNING: trace {trace_id} spans overflow the wall "
              f"envelope by {err * 100:.1f}% "
              f"(> {TIE_OUT_TOLERANCE * 100:.0f}% tolerance) — broken "
              "clock alignment or trace-id join; treat its row as suspect",
              file=sys.stderr)
    return EXIT_REGRESSION if report["tie_out_violations"] else EXIT_OK


if __name__ == "__main__":
    sys.exit(main())
