"""``dstpu_trace`` — top-spans text report from a dstrace dump.

Reads a Chrome-trace JSON written by ``engine.dump_trace`` / ``DSTPU_TRACE``
and renders the aggregate view an oncall wants before opening Perfetto:
per-span-name count / total / mean / max / share of traced wall time, plus
instant-event counts (guard trips, chaos injections, preemption signals).
"""

import argparse
import json
import sys
from collections import defaultdict
from typing import Dict, List, Tuple

#: step spans that anchor --step-range slicing to wall time
_STEP_SPAN_NAMES = ("engine/dispatch", "engine/train_step")


def load_events(path: str) -> List[dict]:
    with open(path) as f:
        trace = json.load(f)
    events = trace["traceEvents"] if isinstance(trace, dict) else trace
    return [e for e in events if isinstance(e, dict)]


def track_names(events: List[dict]) -> Dict[int, str]:
    """tid -> label from the thread_name metadata rows the dump carries."""
    return {e.get("tid"): e.get("args", {}).get("name", "")
            for e in events
            if e.get("ph") == "M" and e.get("name") == "thread_name"}


def filter_track(events: List[dict], track: str) -> List[dict]:
    """Keep one Perfetto track: ``track`` matches the thread label
    (``MainThread``, ``prefetch``, ``request-7``, ...) or a raw tid.
    Metadata rows ride along so the slice stays labeled."""
    names = track_names(events)
    keep = {tid for tid, label in names.items() if label == track}
    if not keep and track.lstrip("-").isdigit():
        keep = {int(track)}
    if not keep:
        known = sorted(set(names.values()))
        raise ValueError(f"no track named {track!r} in trace "
                         f"(known: {known})")
    return [e for e in events
            if e.get("ph") == "M" or e.get("tid") in keep]


def step_time_bounds(events: List[dict],
                     lo_step: int, hi_step: int) -> Tuple[float, float]:
    """Wall-time window [lo, hi] (trace us) covering steps lo..hi: from
    the first dispatch of step ``lo_step`` to the last dispatch end of
    step ``hi_step``, extended through any reconciled drain window whose
    step range intersects — so the slice keeps the drain/h2d/comm spans
    that carry no per-step arg but belong to those steps."""
    lo = hi = None
    for e in events:
        if e.get("ph") != "X":
            continue
        name, args = e.get("name"), e.get("args", {})
        ts, dur = float(e.get("ts", 0)), float(e.get("dur", 0))
        if name in _STEP_SPAN_NAMES and "step" in args:
            s = int(args["step"])
            if lo_step <= s <= hi_step:
                lo = ts if lo is None else min(lo, ts)
                hi = ts + dur if hi is None else max(hi, ts + dur)
    if lo is None:
        raise ValueError(f"no step spans in [{lo_step}:{hi_step}] "
                         "(engine/dispatch carries the step arg)")
    for e in events:    # extend through intersecting reconciled windows
        if e.get("ph") != "X" or e.get("name") != "engine/steps_reconciled":
            continue
        args = e.get("args", {})
        last = args.get("last_step")
        steps = args.get("steps")
        if last is None or steps is None:
            continue
        first = int(last) - int(steps) + 1
        if first <= hi_step and int(last) >= lo_step:
            hi = max(hi, float(e.get("ts", 0)) + float(e.get("dur", 0)))
            lo = min(lo, float(e.get("ts", 0)))
    return lo, hi


def rank_pids(events: List[dict]) -> Dict[int, int]:
    """pid -> rank from a MERGED dump's process metadata (``dstpu trace
    merge`` labels every source dump ``rank N (host, pid P)`` and keys its
    events by pid = rank)."""
    out: Dict[int, int] = {}
    for e in events:
        if e.get("ph") != "M" or e.get("name") != "process_name":
            continue
        label = (e.get("args") or {}).get("name", "")
        if label.startswith("rank "):
            try:
                out[e.get("pid")] = int(label.split()[1])
            except (ValueError, IndexError):
                continue
    return out


def filter_rank(events: List[dict], rank: int) -> List[dict]:
    """``--rank N`` — one rank's story out of a merged cross-rank dump:
    every event on that rank's tracks PLUS the *matched* collective spans
    of the other ranks (same ``op_seq``), so the slice still shows who the
    rank was waiting on. Stays plan-loadable Chrome JSON."""
    rank = int(rank)
    pids = {pid for pid, r in rank_pids(events).items() if r == rank}
    if not pids:
        known = sorted(set(rank_pids(events).values()))
        raise ValueError(f"no rank {rank} in trace (merged ranks: {known}; "
                         "produce a merged dump with `dstpu trace merge`)")

    def _comm_seq(e):
        if e.get("ph") != "X":
            return None
        name = e.get("name", "")
        if e.get("cat") != "comm" and not name.startswith("comm/"):
            return None
        return (e.get("args") or {}).get("op_seq")

    own_seqs = {_comm_seq(e) for e in events
                if e.get("pid") in pids and _comm_seq(e) is not None}
    out = []
    for e in events:
        if e.get("ph") == "M":
            # keep every rank's process label (matched spans from other
            # ranks still group under a named track) but only THIS rank's
            # thread labels — the other ranks' threads are out of scope
            if e.get("name") == "process_name" or e.get("pid") in pids:
                out.append(e)
            continue
        if e.get("pid") in pids:
            out.append(e)
            continue
        seq = _comm_seq(e)
        if seq is not None and seq in own_seqs:
            out.append(e)      # the matched half of this rank's collectives
    return out


def filter_request(events: List[dict], uid: int) -> List[dict]:
    """``--request UID`` — one serving request's story: its queued/
    prefill/decode retro-spans (the synthetic ``request-UID`` track plus
    any span/instant carrying its ``uid`` arg) AND every serve-category
    event intersecting the request's wall-time window, so the serve ticks,
    demote/promote copies, and ladder edges that shaped its latency ride
    along. The slice stays plan-loadable Chrome JSON (feeds ``dstpu plan
    --serve`` / bug reports)."""
    uid = int(uid)
    names = track_names(events)
    req_tids = {tid for tid, label in names.items()
                if label == f"request-{uid}"}
    other_req_tids = {tid for tid, label in names.items()
                      if label.startswith("request-")
                      and tid not in req_tids}

    def _is_request(e):
        args = e.get("args") or {}
        return args.get("uid") == uid or e.get("tid") in req_tids

    req_events = [e for e in events
                  if e.get("ph") != "M" and _is_request(e)]
    if not req_events:
        known = sorted({(e.get("args") or {}).get("uid")
                        for e in events
                        if e.get("ph") != "M"
                        and (e.get("args") or {}).get("uid") is not None})
        raise ValueError(f"no events for request uid {uid} in trace "
                         f"(uids present: {known[:20]}"
                         f"{'...' if len(known) > 20 else ''})")
    lo = min(float(e.get("ts", 0)) for e in req_events)
    hi = max(float(e.get("ts", 0)) + float(e.get("dur", 0))
             for e in req_events)
    out = []
    for e in events:
        if e.get("ph") == "M":
            out.append(e)
            continue
        if _is_request(e):
            out.append(e)
            continue
        name = e.get("name", "")
        if not (e.get("cat") == "serve" or name.startswith("serve/")):
            continue
        # serve-LOOP context only: another request's synthetic track is
        # that request's story, not this one's — the loop-track ticks and
        # demote/promote copies (whichever uid they moved) ride along
        if e.get("tid") in other_req_tids:
            continue
        ts = float(e.get("ts", 0))
        if ts + float(e.get("dur", 0)) >= lo and ts <= hi:
            out.append(e)
    return out


def filter_step_range(events: List[dict], spec: str) -> List[dict]:
    """``--step-range A:B`` — keep every event intersecting the wall-time
    window those steps occupied (NOT just events carrying a step arg: the
    drain/h2d/comm spans of those steps have none)."""
    try:
        a, _, b = spec.partition(":")
        lo_step, hi_step = int(a), int(b if b else a)
    except ValueError:
        raise ValueError(f"--step-range wants A:B (got {spec!r})")
    lo, hi = step_time_bounds(events, lo_step, hi_step)
    out = []
    for e in events:
        if e.get("ph") == "M":
            out.append(e)
            continue
        ts = float(e.get("ts", 0))
        end = ts + float(e.get("dur", 0))
        if end >= lo and ts <= hi:
            out.append(e)
    return out


def write_slice(path: str, events: List[dict]):
    """Write a filtered event set back out as Chrome-trace JSON — the
    sliced dump feeds ``dstpu plan`` or a bug report without shipping the
    whole ring."""
    obj = {"traceEvents": events, "displayTimeUnit": "ms",
           "otherData": {"sliced": True, "events": len(events)}}
    with open(path, "w") as f:
        json.dump(obj, f, default=str)


def aggregate(events: List[dict], cat: str = None):
    """(span_rows, instant_rows, wall_us). Span rows are per-name
    aggregates of "X" events; wall is the end-to-end traced interval."""
    spans: Dict[str, List[float]] = defaultdict(list)
    instants: Dict[str, int] = defaultdict(int)
    lo, hi = None, None
    for e in events:
        ph = e.get("ph")
        if cat and e.get("cat") != cat:
            continue
        if ph == "X":
            ts, dur = float(e.get("ts", 0)), float(e.get("dur", 0))
            spans[e.get("name", "?")].append(dur)
            lo = ts if lo is None else min(lo, ts)
            hi = ts + dur if hi is None else max(hi, ts + dur)
        elif ph == "i":
            instants[e.get("name", "?")] += 1
            ts = float(e.get("ts", 0))
            lo = ts if lo is None else min(lo, ts)
            hi = ts if hi is None else max(hi, ts)
    wall = (hi - lo) if (lo is not None and hi is not None) else 0.0
    rows = []
    for name, durs in spans.items():
        total = sum(durs)
        rows.append({"name": name, "count": len(durs), "total_us": total,
                     "mean_us": total / len(durs), "max_us": max(durs),
                     "share": (total / wall) if wall > 0 else 0.0})
    rows.sort(key=lambda r: r["total_us"], reverse=True)
    return rows, dict(instants), wall


def render(rows, instants, wall_us: float, top: int = 20) -> str:
    out = []
    out.append(f"traced wall time: {wall_us / 1e3:.2f} ms")
    out.append("")
    out.append(f"{'span':<36} {'count':>7} {'total ms':>10} "
               f"{'mean ms':>9} {'max ms':>9} {'% wall':>7}")
    out.append("-" * 82)
    for r in rows[:top]:
        out.append(f"{r['name']:<36} {r['count']:>7} "
                   f"{r['total_us'] / 1e3:>10.2f} {r['mean_us'] / 1e3:>9.3f} "
                   f"{r['max_us'] / 1e3:>9.3f} {r['share'] * 100:>6.1f}%")
    if len(rows) > top:
        out.append(f"... {len(rows) - top} more span names (--top N)")
    if instants:
        out.append("")
        out.append(f"{'instant event':<46} {'count':>7}")
        out.append("-" * 54)
        for name in sorted(instants, key=instants.get, reverse=True):
            out.append(f"{name:<46} {instants[name]:>7}")
    return "\n".join(out)


def main(argv=None) -> int:
    parser = argparse.ArgumentParser(
        prog="dstpu_trace",
        description="top-spans report from a dstrace Chrome-trace dump "
                    "(produce one with DSTPU_TRACE=trace.json or "
                    "engine.dump_trace)")
    parser.add_argument("trace", help="Chrome-trace JSON file")
    parser.add_argument("--top", type=int, default=20,
                        help="span names to show (default 20)")
    parser.add_argument("--cat", default=None,
                        help="restrict to one category "
                             "(train/comm/serve/ckpt/data/resilience)")
    parser.add_argument("--json", action="store_true",
                        help="machine-readable aggregate instead of a table")
    parser.add_argument("--step-range", default=None, metavar="A:B",
                        help="slice to the wall-time window steps A..B "
                             "occupied (keeps their drain/h2d/comm spans)")
    parser.add_argument("--track", default=None, metavar="NAME",
                        help="slice to one Perfetto track by thread label "
                             "(e.g. MainThread, request-7) or raw tid")
    parser.add_argument("--rank", default=None, metavar="N", type=int,
                        help="slice a merged cross-rank dump to one rank's "
                             "tracks plus its matched collective spans "
                             "(produce one with `dstpu trace merge`)")
    parser.add_argument("--request", default=None, metavar="UID", type=int,
                        help="slice to one serving request: its retro-"
                             "spans plus intersecting serve ticks / "
                             "demote / promote spans")
    parser.add_argument("--out", default=None, metavar="PATH",
                        help="write the sliced events as Chrome-trace JSON "
                             "(feeds `dstpu plan` / bug reports)")
    args = parser.parse_args(argv)
    try:
        events = load_events(args.trace)
    except (OSError, ValueError, KeyError) as e:
        print(f"dstpu_trace: cannot read {args.trace}: {e}", file=sys.stderr)
        return 2
    try:
        if args.rank is not None:
            events = filter_rank(events, args.rank)
        if args.step_range:
            events = filter_step_range(events, args.step_range)
        if args.request is not None:
            events = filter_request(events, args.request)
        if args.track:
            events = filter_track(events, args.track)
    except ValueError as e:
        print(f"dstpu_trace: {e}", file=sys.stderr)
        return 2
    if args.out:
        write_slice(args.out, events)
        print(f"# sliced trace ({len(events)} events) -> {args.out}",
              file=sys.stderr)
    rows, instants, wall = aggregate(events, cat=args.cat)
    if args.json:
        print(json.dumps({"wall_us": wall, "spans": rows,
                          "instants": instants}, indent=2))
    else:
        print(render(rows, instants, wall, top=args.top))
    return 0


if __name__ == "__main__":
    sys.exit(main())
