"""``dstpu_trace`` — top-spans text report from a dstrace dump.

Reads a Chrome-trace JSON written by ``engine.dump_trace`` / ``DSTPU_TRACE``
and renders the aggregate view an oncall wants before opening Perfetto:
per-span-name count / total / mean / max / share of traced wall time, plus
instant-event counts (guard trips, chaos injections, preemption signals).
"""

import argparse
import json
import sys
from collections import defaultdict
from typing import Dict, List


def load_events(path: str) -> List[dict]:
    with open(path) as f:
        trace = json.load(f)
    events = trace["traceEvents"] if isinstance(trace, dict) else trace
    return [e for e in events if isinstance(e, dict)]


def aggregate(events: List[dict], cat: str = None):
    """(span_rows, instant_rows, wall_us). Span rows are per-name
    aggregates of "X" events; wall is the end-to-end traced interval."""
    spans: Dict[str, List[float]] = defaultdict(list)
    instants: Dict[str, int] = defaultdict(int)
    lo, hi = None, None
    for e in events:
        ph = e.get("ph")
        if cat and e.get("cat") != cat:
            continue
        if ph == "X":
            ts, dur = float(e.get("ts", 0)), float(e.get("dur", 0))
            spans[e.get("name", "?")].append(dur)
            lo = ts if lo is None else min(lo, ts)
            hi = ts + dur if hi is None else max(hi, ts + dur)
        elif ph == "i":
            instants[e.get("name", "?")] += 1
            ts = float(e.get("ts", 0))
            lo = ts if lo is None else min(lo, ts)
            hi = ts if hi is None else max(hi, ts)
    wall = (hi - lo) if (lo is not None and hi is not None) else 0.0
    rows = []
    for name, durs in spans.items():
        total = sum(durs)
        rows.append({"name": name, "count": len(durs), "total_us": total,
                     "mean_us": total / len(durs), "max_us": max(durs),
                     "share": (total / wall) if wall > 0 else 0.0})
    rows.sort(key=lambda r: r["total_us"], reverse=True)
    return rows, dict(instants), wall


def render(rows, instants, wall_us: float, top: int = 20) -> str:
    out = []
    out.append(f"traced wall time: {wall_us / 1e3:.2f} ms")
    out.append("")
    out.append(f"{'span':<36} {'count':>7} {'total ms':>10} "
               f"{'mean ms':>9} {'max ms':>9} {'% wall':>7}")
    out.append("-" * 82)
    for r in rows[:top]:
        out.append(f"{r['name']:<36} {r['count']:>7} "
                   f"{r['total_us'] / 1e3:>10.2f} {r['mean_us'] / 1e3:>9.3f} "
                   f"{r['max_us'] / 1e3:>9.3f} {r['share'] * 100:>6.1f}%")
    if len(rows) > top:
        out.append(f"... {len(rows) - top} more span names (--top N)")
    if instants:
        out.append("")
        out.append(f"{'instant event':<46} {'count':>7}")
        out.append("-" * 54)
        for name in sorted(instants, key=instants.get, reverse=True):
            out.append(f"{name:<46} {instants[name]:>7}")
    return "\n".join(out)


def main(argv=None) -> int:
    parser = argparse.ArgumentParser(
        prog="dstpu_trace",
        description="top-spans report from a dstrace Chrome-trace dump "
                    "(produce one with DSTPU_TRACE=trace.json or "
                    "engine.dump_trace)")
    parser.add_argument("trace", help="Chrome-trace JSON file")
    parser.add_argument("--top", type=int, default=20,
                        help="span names to show (default 20)")
    parser.add_argument("--cat", default=None,
                        help="restrict to one category "
                             "(train/comm/serve/ckpt/data/resilience)")
    parser.add_argument("--json", action="store_true",
                        help="machine-readable aggregate instead of a table")
    args = parser.parse_args(argv)
    try:
        events = load_events(args.trace)
    except (OSError, ValueError, KeyError) as e:
        print(f"dstpu_trace: cannot read {args.trace}: {e}", file=sys.stderr)
        return 2
    rows, instants, wall = aggregate(events, cat=args.cat)
    if args.json:
        print(json.dumps({"wall_us": wall, "spans": rows,
                          "instants": instants}, indent=2))
    else:
        print(render(rows, instants, wall, top=args.top))
    return 0


if __name__ == "__main__":
    sys.exit(main())
