"""deepspeed_tpu.telemetry — dstrace structured tracing.

One low-overhead span tracer unifying train, serving, comm, and resilience
telemetry (see ``docs/observability.md``). Import surface::

    from deepspeed_tpu.telemetry import get_tracer, configure_tracing
    configure_tracing(enabled=True)
    with get_tracer().span("my/phase", step=7):
        ...
    engine.dump_trace("trace.json")        # -> ui.perfetto.dev
"""

from deepspeed_tpu.telemetry.tracer import (DEFAULT_CAPACITY,
                                            REQUEST_TID_BASE, TRACE_ENV,
                                            Tracer, configure_tracing,
                                            get_tracer, request_tid)

__all__ = ["Tracer", "get_tracer", "configure_tracing", "TRACE_ENV",
           "DEFAULT_CAPACITY", "REQUEST_TID_BASE", "request_tid"]
