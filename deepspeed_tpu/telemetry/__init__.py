"""deepspeed_tpu.telemetry — dstrace structured tracing.

One low-overhead span tracer unifying train, serving, comm, and resilience
telemetry (see ``docs/observability.md``). Import surface::

    from deepspeed_tpu.telemetry import get_tracer, configure_tracing
    configure_tracing(enabled=True)
    with get_tracer().span("my/phase", step=7):
        ...
    engine.dump_trace("trace.json")        # -> ui.perfetto.dev
"""

from deepspeed_tpu.telemetry.tracer import (DEFAULT_CAPACITY,
                                            REQUEST_TID_BASE, TRACE_ENV,
                                            Tracer, configure_tracing,
                                            get_tracer, request_tid)
__all__ = ["Tracer", "get_tracer", "configure_tracing", "TRACE_ENV",
           "DEFAULT_CAPACITY", "REQUEST_TID_BASE", "request_tid",
           "analyze_path", "attribute", "events_from_tracer", "load_events",
           "analyze_serve_path", "attribute_serve", "propose_serve",
           "MemoryLedger", "MemorySampler", "is_oom_error",
           "estimate_zero2_model_states_mem_needs",
           "estimate_zero3_model_states_mem_needs",
           "merge_traces", "attribute_crossrank", "analyze_crossrank_path",
           "matched_collectives"]

#: offline trace replay (``dstpu plan``) — re-exported LAZILY (PEP 562):
#: every hot-path file imports this package for ``get_tracer``, and the
#: OFFLINE_ONLY_MODULES contract (tools/dslint/hotpath.py) says no hot
#: path may reach attribution, transitively included — so the module loads
#: only when someone actually asks for the replay API.
_ATTRIBUTION_EXPORTS = ("analyze_path", "attribute", "events_from_tracer",
                        "load_events")

#: serving-tick replay (``dstpu plan --serve``) — same lazy contract as
#: attribution: serve_attribution is OFFLINE_ONLY, so the hot-path import
#: chain must never load it transitively
_SERVE_PLAN_EXPORTS = ("analyze_serve_path", "attribute_serve",
                       "propose_serve")

#: dsmem (memory ledger + sampler + OOM classification) — also lazy: the
#: module is stdlib-only but pulling it into every ``get_tracer`` importer
#: would be pure dead weight on the hot-path import chain
_MEMORY_EXPORTS = ("MemoryLedger", "MemorySampler", "is_oom_error",
                   "estimate_zero2_model_states_mem_needs",
                   "estimate_zero3_model_states_mem_needs")

#: cross-rank merge + skew ledger (``dstpu trace merge`` / ``dstpu plan
#: --cross-rank``) — OFFLINE_ONLY like attribution: the hot-path import
#: chain must never load it transitively
_CROSSRANK_EXPORTS = ("merge_traces", "attribute_crossrank",
                      "analyze_crossrank_path", "matched_collectives")


def __getattr__(name):
    if name in _ATTRIBUTION_EXPORTS:
        from deepspeed_tpu.telemetry import attribution
        return getattr(attribution, name)
    if name in _SERVE_PLAN_EXPORTS:
        from deepspeed_tpu.telemetry import serve_attribution
        return getattr(serve_attribution, name)
    if name in _MEMORY_EXPORTS:
        from deepspeed_tpu.telemetry import memory
        return getattr(memory, name)
    if name in _CROSSRANK_EXPORTS:
        from deepspeed_tpu.telemetry import crossrank
        return getattr(crossrank, name)
    raise AttributeError(
        f"module {__name__!r} has no attribute {name!r}")
