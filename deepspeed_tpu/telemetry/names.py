"""Canonical trace-name registry — THE one place a span/instant/counter
name is declared.

Until dslint v2 the emitters (engine, server, fleet, chaos, comm guard)
and the offline consumers (``attribution.py`` / ``serve_attribution.py``
/ ``crossrank.py`` stage tables, the plan rules, the bench gates) agreed
on names one hand-written test at a time — renaming an emitted span
silently dropped it out of the exclusive-stage ledgers and every
downstream share went to ``residual``. Now:

* every name a ``Tracer.span/instant/counter/complete`` call emits as a
  literal MUST appear in :data:`TRACE_NAMES` (rule **DS007**; dynamic
  f-string names must start with a :data:`DYNAMIC_PREFIXES` entry), and
* the offline stage tables derive their name constants FROM this module,

so a rename that touches only one side is a lint finding, not a silent
attribution hole.

Contract: this module is **stdlib-only pure data** and must stay loadable
standalone (``importlib`` file-load, no package import) — the offline
consumers run on jax-less hosts and load it from the sibling path under
``sys.modules["dstpu_trace_names"]``.

Adding a name: add the ``name -> (kinds,)`` entry here (kinds from
``span``/``instant``/``counter``/``complete``), emit it, and — if an
offline sweep should attribute it — extend the relevant stage constant
below. ``python bin/dslint deepspeed_tpu`` confirms both sides agree.
"""

from typing import Dict, Tuple

#: every literal trace name the package emits, mapped to the event kinds
#: it may be emitted as. DS007 flags an emitted literal that is missing
#: here, and a registered name emitted as an unregistered kind.
TRACE_NAMES: Dict[str, Tuple[str, ...]] = {
    # -- training engine ---------------------------------------------------
    "engine/train_step": ("span",),
    "engine/dispatch": ("span",),
    "engine/drain": ("span",),              # DispatchRing's drain span
    "engine/steps_reconciled": ("complete",),
    "engine/overflow_step": ("instant",),
    "comm/h2d": ("span",),
    "comm/overlap": ("complete",),
    "ckpt/save": ("span",),
    "ckpt/load": ("span",),
    "prefetch/next": ("span",),
    "prefetch/stage": ("span",),
    "xla/compile": ("instant",),
    # -- memory telemetry --------------------------------------------------
    "mem/oom": ("instant",),
    "mem/see_memory_usage": ("instant",),
    "mem/hbm_bytes_in_use": ("counter",),
    "mem/hbm_peak_bytes": ("counter",),
    "mem/hbm_bytes_limit": ("counter",),
    "mem/host_rss_bytes": ("counter",),
    # -- collective guard / membership ------------------------------------
    "comm/init_retry": ("instant",),
    "comm/init_wedge": ("instant",),
    "comm/op_failed": ("instant",),
    "comm/wedge": ("instant",),
    "comm/straggler": ("instant",),
    # -- resilience --------------------------------------------------------
    "resilience/bad_step": ("instant",),
    "resilience/lr_backoff": ("instant",),
    "resilience/quarantine": ("instant",),
    "resilience/comm_fault": ("instant",),
    "resilience/preempt_signal": ("instant",),
    "resilience/watchdog_flag": ("instant",),
    # -- chaos drills ------------------------------------------------------
    "chaos/stall": ("complete",),
    "chaos/serve_slow_tick": ("complete",),
    "chaos/ckpt_io_fail": ("instant",),
    "chaos/comm_delay": ("instant",),
    "chaos/comm_wedge": ("instant",),
    "chaos/die": ("instant",),
    "chaos/nan": ("instant",),
    "chaos/oom": ("instant",),
    "chaos/replica_kill": ("instant",),
    "chaos/serve_kv_pressure": ("instant",),
    "chaos/serve_poison": ("instant",),
    # -- elasticity --------------------------------------------------------
    "elastic/peer_lost": ("instant",),
    "elastic/regrow": ("instant",),
    "elastic/shrink_refused": ("instant",),
    "elastic/shrink_planned": ("instant",),
    "elastic/reshard": ("instant",),
    # -- serving tick ------------------------------------------------------
    "serve/tick": ("complete",),
    "serve/engine_step": ("span",),
    "serve/admit": ("span",),
    "serve/demote": ("span",),
    "serve/promote": ("span",),
    "serve/drain": ("span",),
    "serve/step_prefill": ("complete",),
    "serve/step_decode": ("complete",),
    "serve/prefill_chunk": ("complete",),
    "serve/queued": ("complete",),
    "serve/prefill": ("complete",),
    "serve/decode": ("complete",),
    "serve/kv_bytes": ("counter",),
    "serve/tick_stage_share": ("counter",),
    "serve/kv_tier": ("counter",),
    "serve/prefix_cache": ("counter",),
    "serve/backpressure": ("instant",),
    "serve/degraded": ("instant",),
    "serve/evicted": ("instant",),
    "serve/kv_demote": ("instant",),
    "serve/kv_promote": ("instant",),
    "serve/kv_recalibrate": ("instant",),
    "serve/kv_drift": ("instant",),
    "serve/ladder": ("instant",),
    "serve/prefix_evict": ("instant",),
    "serve/prefix_handoff_adopt": ("instant",),
    "serve/prefix_handoff_export": ("instant",),
    "serve/quarantine": ("instant",),
    "serve/recovered": ("instant",),
    "serve/step_fault": ("instant",),
    "serve/flight_dump": ("instant",),
    # -- per-request tracing (trace_id-scoped; reqtrace.py stitches) -------
    "req/queue": ("complete",),
    "req/prefill": ("complete",),
    "req/decode": ("complete",),
    "req/handoff": ("complete",),
    "req/reroute": ("complete",),
    "req/wall": ("complete",),
    # -- disaggregated prefill/decode -------------------------------------
    "disagg/tick": ("complete",),
    "disagg/handoff": ("instant",),
    # -- fleet router ------------------------------------------------------
    "fleet/poll_tick": ("span",),
    "fleet/rotation": ("counter",),
    "fleet/load": ("counter",),
    "fleet/handoff": ("instant",),
    "fleet/out_of_rotation": ("instant",),
    "fleet/replica_lost": ("instant",),
    "fleet/replica_relaunched": ("instant",),
    "fleet/request_lost": ("instant",),
    "fleet/reroute": ("instant",),
    "fleet/retire": ("instant",),
    "fleet/scale_out": ("instant",),
    "fleet/spill": ("instant",),
    "fleet/flight_recovered": ("instant",),
}

#: f-string names are allowed when their literal head starts with one of
#: these (per-op comm records, per-state request transitions); everything
#: else dynamic is a DS007 finding. Literal names never get prefix
#: leniency — they must be registered above.
DYNAMIC_PREFIXES: Tuple[str, ...] = ("comm/", "serve/")

# ---------------------------------------------------------------------------
# canonical constants the offline stage tables consume (attribution.py /
# serve_attribution.py / crossrank.py file-load this module standalone)
# ---------------------------------------------------------------------------
TRAIN_DISPATCH_NAMES: Tuple[str, ...] = ("engine/dispatch",
                                         "engine/train_step")
TRAIN_RECONCILE_NAME = "engine/steps_reconciled"
TRAIN_DRAIN_NAME = "engine/drain"
COMM_H2D_NAME = "comm/h2d"
COMM_OVERLAP_NAME = "comm/overlap"
COMM_PREFIX = "comm/"
CKPT_PREFIX = "ckpt/"
PREFETCH_PREFIX = "prefetch/"

HBM_IN_USE_COUNTER = "mem/hbm_bytes_in_use"
HBM_PEAK_COUNTER = "mem/hbm_peak_bytes"
HBM_LIMIT_COUNTER = "mem/hbm_bytes_limit"

SERVE_TICK_NAME = "serve/tick"

#: serving stage table: span name -> exclusive-sweep stage key. The
#: ``serve_attribution`` priorities live next to the sweep; the NAMES
#: live here so renaming an emitter trips DS007 instead of silently
#: reattributing the stage to residual.
SERVE_STAGE_OF: Dict[str, str] = {
    "serve/admit": "admission",
    "serve/step_prefill": "prefill",
    # per-chunk sub-spans nest inside step_prefill when chunked prefill
    # is on — same stage, so the exclusive sweep still ties out
    "serve/prefill_chunk": "prefill",
    "serve/step_decode": "decode",
    "serve/demote": "demote",
    "serve/promote": "promote",
    "serve/drain": "drain",
}

#: per-request tracing namespace (reqtrace.py file-loads this module
#: standalone, same contract as the tables above). Spans carrying a
#: ``trace_id`` arg under REQ_PREFIX are the stitch join; REQ_STAGE_OF
#: maps each lifecycle span to its timeline stage; REQ_WALL_NAME is the
#: router-side envelope every replica-side span must fit inside (the
#: tie-out denominator); REQ_TRACE_ARG is the one arg key the join uses.
REQ_PREFIX = "req/"
REQ_TRACE_ARG = "trace_id"
REQ_WALL_NAME = "req/wall"
REQ_REROUTE_NAME = "req/reroute"
REQ_HANDOFF_NAME = "req/handoff"
REQ_STAGE_OF: Dict[str, str] = {
    "req/queue": "queue",
    "req/prefill": "prefill",
    "req/decode": "decode",
    "req/handoff": "handoff",
    "req/reroute": "reroute",
}
