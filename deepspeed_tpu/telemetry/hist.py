"""dstpu hist — deterministic fixed-log-bucket latency histograms.

The SLO layer's measurement primitive: a histogram whose bucket bounds
are EXACT powers of two (``2.0**e`` seconds) so the bucket a value lands
in is a pure function of the value — no adaptive resizing, no
quantile-sketch randomness, no platform-dependent rounding. Two
properties the serving tests lean on:

* **bit-identical cross-platform** — IEEE-754 represents powers of two
  exactly, so ``bucket_index(v)`` gives the same answer on every host
  and the golden-bucket tests can pin exact counts;
* **mergeable** — same-bounds histograms add counterwise, so per-replica
  histograms fold into fleet-wide ones without approximation error
  (the same reason Prometheus's histogram type is cumulative-bucket).

The default span ``2**-20 s .. 2**6 s`` (~1 us .. 64 s) covers every
serving latency this repo measures (queue wait, TTFT, TPOT, KV handoff);
values beyond the top bound land in the implicit ``+Inf`` bucket, never
dropped. No wall-clock anywhere in this module: callers feed it
monotonic-stamp differences or TickLedger ceil-div units, which is what
keeps the histogram tests deterministic.

Offline-friendly by construction (stdlib only, never imports jax) but
NOT offline-only: ``serving/metrics.py`` feeds histograms on the serve
path's bookkeeping side (stdlib float/int work — no host sync).
"""

from typing import Dict, Iterable, List, Optional, Sequence, Tuple

#: default bucket span: 2**-20 s (~0.95 us) .. 2**6 s (64 s), one bucket
#: per power of two — 27 finite bounds + the implicit +Inf bucket
DEFAULT_LOW_EXP = -20
DEFAULT_HIGH_EXP = 6


def log2_bounds(low_exp: int = DEFAULT_LOW_EXP,
                high_exp: int = DEFAULT_HIGH_EXP) -> Tuple[float, ...]:
    """Upper bucket bounds ``2.0**e`` for ``e`` in ``[low_exp, high_exp]``
    — each IEEE-754-exact, so the bounds (and therefore every bucket
    verdict) are identical on every platform."""
    if high_exp < low_exp:
        raise ValueError(f"empty bound span [{low_exp}, {high_exp}]")
    return tuple(2.0 ** e for e in range(low_exp, high_exp + 1))


class LogHistogram:
    """Fixed-bound histogram with Prometheus-histogram semantics: a value
    lands in the first bucket whose upper bound is ``>= value`` (le-
    inclusive, the Prometheus ``le`` contract), or in ``+Inf`` past the
    top bound. Tracks exact ``count`` and ``sum`` alongside the bucket
    counters so conservation identities (bucket total == observations ==
    completed requests) are checkable, not approximate."""

    __slots__ = ("bounds", "counts", "inf_count", "count", "sum")

    def __init__(self, bounds: Optional[Sequence[float]] = None):
        self.bounds: Tuple[float, ...] = tuple(
            bounds if bounds is not None else log2_bounds())
        if list(self.bounds) != sorted(self.bounds) or len(
                set(self.bounds)) != len(self.bounds):
            raise ValueError("bounds must be strictly increasing")
        self.counts: List[int] = [0] * len(self.bounds)
        self.inf_count = 0
        self.count = 0
        self.sum = 0.0

    def bucket_index(self, value: float) -> int:
        """Index of the bucket ``value`` lands in; ``len(bounds)`` means
        the +Inf bucket. Linear scan: the bound list is ~27 entries and
        observation sits on bookkeeping paths, not hot loops."""
        v = float(value)
        for i, b in enumerate(self.bounds):
            if v <= b:
                return i
        return len(self.bounds)

    def observe(self, value: float) -> None:
        v = float(value)
        i = self.bucket_index(v)
        if i < len(self.counts):
            self.counts[i] += 1
        else:
            self.inf_count += 1
        self.count += 1
        self.sum += v

    def observe_many(self, values: Iterable[float]) -> None:
        for v in values:
            self.observe(v)

    def merge(self, other: "LogHistogram") -> None:
        """Counterwise fold of a same-bounds histogram (per-replica ->
        fleet-wide). Differing bounds are a programming error, not data."""
        if other.bounds != self.bounds:
            raise ValueError("cannot merge histograms with different bounds")
        for i, c in enumerate(other.counts):
            self.counts[i] += c
        self.inf_count += other.inf_count
        self.count += other.count
        self.sum += other.sum

    def quantile(self, q: float) -> float:
        """Upper-bound quantile estimate: the upper edge of the bucket
        holding the q-th observation (``min(int(q*n), n-1)`` rank, the
        repo-wide exact-quantile rule applied to bucket ranks). +Inf-
        bucket hits report the top finite bound — a floor, clearly
        saturated, never a fabricated value."""
        if self.count <= 0:
            return 0.0
        rank = min(int(q * self.count), self.count - 1)
        seen = 0
        for i, c in enumerate(self.counts):
            seen += c
            if rank < seen:
                return self.bounds[i]
        return self.bounds[-1] if self.bounds else 0.0

    def snapshot(self) -> Dict[str, object]:
        """JSON-able state: finite-bucket counts, +Inf count, exact
        count/sum — the bench_serve proof-set row."""
        return {"bounds": list(self.bounds), "counts": list(self.counts),
                "inf_count": self.inf_count, "count": self.count,
                "sum": self.sum}

    @classmethod
    def from_snapshot(cls, snap: Dict[str, object]) -> "LogHistogram":
        h = cls(bounds=snap.get("bounds") or log2_bounds())
        counts = list(snap.get("counts") or ())
        if len(counts) != len(h.counts):
            raise ValueError("snapshot counts do not match bounds")
        h.counts = [int(c) for c in counts]
        h.inf_count = int(snap.get("inf_count", 0))
        h.count = int(snap.get("count", 0))
        h.sum = float(snap.get("sum", 0.0))
        return h

    def delta_from(self, earlier: "LogHistogram") -> "LogHistogram":
        """This histogram minus an earlier same-bounds snapshot — the
        bench_serve warmed-run discipline (measure the measured window,
        not the warmup)."""
        if earlier.bounds != self.bounds:
            raise ValueError("cannot diff histograms with different bounds")
        out = LogHistogram(bounds=self.bounds)
        out.counts = [max(a - b, 0) for a, b in zip(self.counts,
                                                    earlier.counts)]
        out.inf_count = max(self.inf_count - earlier.inf_count, 0)
        out.count = max(self.count - earlier.count, 0)
        out.sum = self.sum - earlier.sum
        return out


def format_le(bound: float) -> str:
    """The ``le`` label text for one bound: ``repr`` of the float, which
    for powers of two is the exact shortest decimal — deterministic
    across platforms (goldens pin it)."""
    return repr(float(bound))


#: the one namespace this module may emit TYPE metadata for — the
#: emission site below carries it inline, so DS008 sees a static prefix
#: claim (`dstpu_req_*` belongs to this function alone) instead of an
#: anything-goes `f"# TYPE {name}"`.
FAMILY_NAMESPACE = "dstpu_req_"


def prometheus_histogram_lines(family: str, hist: LogHistogram,
                               help_text: str = "") -> List[str]:
    """Render ONE histogram as a DS008-clean Prometheus exposition block:
    exactly one ``# TYPE`` (and optional ``# HELP``) line per family,
    cumulative ``_bucket`` rows ending in ``+Inf``, then ``_sum`` and
    ``_count``. ``family`` must live inside ``dstpu_req_*`` — this
    function is the single TYPE emission site for that namespace, which
    is what makes duplicate-metadata collisions impossible by
    construction (dslint DS008's prefix-claim discipline)."""
    if not family.startswith(FAMILY_NAMESPACE):
        raise ValueError(
            f"histogram family {family!r} outside the {FAMILY_NAMESPACE}* "
            f"namespace this emission site owns")
    key = family[len(FAMILY_NAMESPACE):]
    lines: List[str] = []
    if help_text:
        lines.append(f"# HELP {family} {help_text}")
    lines.append(f"# TYPE dstpu_req_{key} histogram")
    cum = 0
    for bound, c in zip(hist.bounds, hist.counts):
        cum += c
        lines.append(f'{family}_bucket{{le="{format_le(bound)}"}} {cum}')
    cum += hist.inf_count
    lines.append(f'{family}_bucket{{le="+Inf"}} {cum}')
    lines.append(f"{family}_sum {hist.sum}")
    lines.append(f"{family}_count {hist.count}")
    return lines
