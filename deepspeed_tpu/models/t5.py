"""T5 encoder-decoder family (relative position bias, RMS norms, no biases).

Reference analog: the T5 injection policy (``module_inject`` t5 container) —
the reference serves T5 via v1 kernel injection; here the family is a full
training model plus a jitted greedy decode. Covers v1.0 (ReLU FFN, tied
head) and v1.1/flan (gated-GELU FFN, untied head) via config knobs.

Architecture notes (verified against HF T5):
- T5LayerNorm == RMSNorm (no mean subtraction, no bias), pre-norm blocks.
- Attention has NO scaling by 1/sqrt(d) (folded into init) and no biases.
- Relative position bias: bucketed (bidirectional for the encoder, causal
  buckets for the decoder), learned per head, owned by layer 0 of each stack
  and shared by the rest; cross-attention has none.
- Tied head multiplies by d_model**-0.5 before the shared embedding.
"""

import dataclasses
from functools import partial
from typing import Any, Optional

import flax.linen as nn
import jax
import jax.numpy as jnp
import numpy as np

from deepspeed_tpu.models.llama import (
    BATCH_AXES, HEADS_AXIS, SEQ_AXIS, RMSNorm, shard_activation)


@dataclasses.dataclass(frozen=True)
class T5Config:
    vocab_size: int = 32128
    d_model: int = 512
    d_kv: int = 64
    d_ff: int = 2048
    num_layers: int = 6          # encoder layers (decoder matches)
    num_decoder_layers: Optional[int] = None
    num_heads: int = 8
    relative_attention_num_buckets: int = 32
    relative_attention_max_distance: int = 128
    layer_norm_eps: float = 1e-6
    gated_act: bool = False      # v1.1/flan: GEGLU; v1.0: ReLU
    tie_word_embeddings: bool = True
    dtype: Any = jnp.bfloat16

    @property
    def n_dec_(self) -> int:
        return self.num_decoder_layers or self.num_layers


TINY_T5 = T5Config(vocab_size=512, d_model=64, d_kv=16, d_ff=128,
                   num_layers=2, num_heads=4, dtype=jnp.float32)
TINY_T5_V11 = dataclasses.replace(TINY_T5, gated_act=True,
                                  tie_word_embeddings=False)


def relative_position_bucket(rel_pos, bidirectional: bool, num_buckets: int,
                             max_distance: int):
    """HF T5 bucketing: half the buckets exact, half log-spaced to
    max_distance (t5 semantics; symmetric halves when bidirectional)."""
    ret = 0
    n = -rel_pos
    if bidirectional:
        num_buckets //= 2
        ret += (n < 0).astype(jnp.int32) * num_buckets
        n = jnp.abs(n)
    else:
        n = jnp.maximum(n, 0)
    max_exact = num_buckets // 2
    is_small = n < max_exact
    large = max_exact + (
        jnp.log(n.astype(jnp.float32) / max_exact + 1e-6)
        / np.log(max_distance / max_exact) * (num_buckets - max_exact)
    ).astype(jnp.int32)
    large = jnp.minimum(large, num_buckets - 1)
    return ret + jnp.where(is_small, n, large)


# T5LayerNorm IS RMSNorm (no mean subtraction, no bias) — reuse llama's
_T5RMSNorm = RMSNorm

class _T5Attention(nn.Module):
    """Unscaled multi-head attention with optional relative-position bias and
    masking. ``kv`` defaults to ``x`` (self-attention)."""
    cfg: T5Config
    has_rel_bias: bool = False
    bidirectional: bool = True

    @nn.compact
    def __call__(self, x, kv=None, mask=None, bias=None):
        cfg = self.cfg
        kv = x if kv is None else kv
        dense = partial(nn.DenseGeneral, use_bias=False, dtype=cfg.dtype,
                        param_dtype=jnp.float32)
        q = dense(features=(cfg.num_heads, cfg.d_kv), name="q")(x)
        k = dense(features=(cfg.num_heads, cfg.d_kv), name="k")(kv)
        v = dense(features=(cfg.num_heads, cfg.d_kv), name="v")(kv)
        q = shard_activation(q, (BATCH_AXES, SEQ_AXIS, HEADS_AXIS, None))
        scores = jnp.einsum("bqhd,bkhd->bhqk", q, k)   # NO 1/sqrt(d) in T5
        if self.has_rel_bias:
            table = self.param(
                "rel_bias", nn.initializers.normal(1.0),
                (cfg.relative_attention_num_buckets, cfg.num_heads),
                jnp.float32)
            qlen, klen = x.shape[1], kv.shape[1]
            rel = jnp.arange(klen)[None, :] - jnp.arange(qlen)[:, None]
            buckets = relative_position_bucket(
                rel, self.bidirectional, cfg.relative_attention_num_buckets,
                cfg.relative_attention_max_distance)
            bias = table[buckets].transpose(2, 0, 1)[None]  # [1, H, Q, K]
        if bias is not None:
            scores = scores + bias.astype(scores.dtype)
        if mask is not None:
            scores = jnp.where(mask, scores, jnp.finfo(jnp.float32).min)
        probs = jax.nn.softmax(scores.astype(jnp.float32), -1) \
            .astype(cfg.dtype)
        ctx = jnp.einsum("bhqk,bkhd->bqhd", probs, v)
        out = nn.DenseGeneral(features=cfg.d_model, axis=(-2, -1),
                              use_bias=False, dtype=cfg.dtype,
                              param_dtype=jnp.float32, name="o")(ctx)
        # re-exported so sibling layers reuse layer 0's bias (T5 sharing)
        return out, (bias if self.has_rel_bias else None)


class _T5FFN(nn.Module):
    cfg: T5Config

    @nn.compact
    def __call__(self, x):
        cfg = self.cfg
        dense = partial(nn.Dense, use_bias=False, dtype=cfg.dtype,
                        param_dtype=jnp.float32)
        if cfg.gated_act:
            g = jax.nn.gelu(dense(cfg.d_ff, name="wi_0")(x))
            h = g * dense(cfg.d_ff, name="wi_1")(x)
        else:
            h = jax.nn.relu(dense(cfg.d_ff, name="wi")(x))
        return dense(cfg.d_model, name="wo")(h)


class _T5Block(nn.Module):
    cfg: T5Config
    is_decoder: bool = False
    has_rel_bias: bool = False

    @nn.compact
    def __call__(self, x, enc=None, self_mask=None, cross_mask=None,
                 rel_bias=None):
        cfg = self.cfg
        norm = partial(_T5RMSNorm, eps=cfg.layer_norm_eps, dtype=cfg.dtype)
        h = norm(name="ln_self")(x)
        attn, bias_out = _T5Attention(
            cfg, has_rel_bias=self.has_rel_bias,
            bidirectional=not self.is_decoder, name="self_attn")(
                h, mask=self_mask, bias=rel_bias)
        x = x + attn
        if self.is_decoder:
            h = norm(name="ln_cross")(x)
            cross, _ = _T5Attention(cfg, name="cross_attn")(
                h, kv=enc, mask=cross_mask)
            x = x + cross
        h = norm(name="ln_ffn")(x)
        x = x + _T5FFN(cfg, name="ffn")(h)
        return shard_activation(x, (BATCH_AXES, SEQ_AXIS, None)), bias_out


class T5Model(nn.Module):
    """Encoder-decoder backbone -> decoder logits [B, T, V]."""
    cfg: T5Config

    @nn.compact
    def __call__(self, input_ids, decoder_input_ids, enc_mask=None):
        cfg = self.cfg
        embed = nn.Embed(cfg.vocab_size, cfg.d_model, dtype=cfg.dtype,
                         param_dtype=jnp.float32, name="shared")

        # ---- encoder ----
        x = embed(input_ids)
        key_mask = None if enc_mask is None else \
            enc_mask[:, None, None, :].astype(bool)
        bias = None
        for i in range(cfg.num_layers):
            x, b = _T5Block(cfg, has_rel_bias=(i == 0),
                            name=f"enc_layer_{i}")(
                x, self_mask=key_mask, rel_bias=bias)
            bias = b if b is not None else bias
        enc = _T5RMSNorm(cfg.layer_norm_eps, cfg.dtype,
                         name="enc_final_norm")(x)

        # ---- decoder ----
        t = decoder_input_ids.shape[1]
        causal = jnp.tril(jnp.ones((t, t), bool))[None, None]
        y = embed(decoder_input_ids)
        bias = None
        for i in range(cfg.n_dec_):
            y, b = _T5Block(cfg, is_decoder=True, has_rel_bias=(i == 0),
                            name=f"dec_layer_{i}")(
                y, enc=enc, self_mask=causal, cross_mask=key_mask,
                rel_bias=bias)
            bias = b if b is not None else bias
        y = _T5RMSNorm(cfg.layer_norm_eps, cfg.dtype, name="dec_final_norm")(y)

        if cfg.tie_word_embeddings:
            y = y * (cfg.d_model ** -0.5)
            return embed.attend(y).astype(jnp.float32)
        kernel = self.param("lm_head", nn.initializers.lecun_normal(),
                            (cfg.d_model, cfg.vocab_size), jnp.float32)
        return y.astype(jnp.float32) @ kernel


class T5ForConditionalGeneration(nn.Module):
    """batch: {"input_ids", "labels", optional "attention_mask",
    "decoder_input_ids"} -> mean teacher-forcing CE (labels -100 ignored).
    decoder inputs default to labels shifted right with pad=0 start token."""
    cfg: T5Config

    def setup(self):
        self.model = T5Model(self.cfg)

    @property
    def config(self):
        return self.cfg

    def logits(self, batch):
        labels = batch["labels"]
        dec_in = batch.get("decoder_input_ids")
        if dec_in is None:
            dec_in = jnp.pad(labels, ((0, 0), (1, 0)))[:, :-1]
            dec_in = jnp.maximum(dec_in, 0)    # -100 ignore -> pad id 0
        return self.model(batch["input_ids"], dec_in,
                          enc_mask=batch.get("attention_mask"))

    def __call__(self, batch):
        labels = batch["labels"]
        logits = self.logits(batch)
        mask = (labels >= 0).astype(jnp.float32)
        safe = jnp.maximum(labels, 0)
        logp = jax.nn.log_softmax(logits, -1)
        ll = jnp.take_along_axis(logp, safe[..., None], -1)[..., 0]
        return -jnp.sum(ll * mask) / jnp.maximum(jnp.sum(mask), 1.0)

    def generate_greedy(self, params, input_ids, max_new_tokens=16,
                        enc_mask=None):
        """Simple greedy seq2seq decode: the decoder length grows per step,
        so every step retraces (fine for demos/eval; a production loop would
        pad the decoder to max length and reuse one compiled step — the paged
        v2 path is decoder-only by design)."""
        b = input_ids.shape[0]
        dec = jnp.zeros((b, 1), jnp.int32)
        for _ in range(max_new_tokens):
            logits = self.apply({"params": params}, input_ids, dec,
                                enc_mask=enc_mask,
                                method=lambda m, i, d, enc_mask: m.model(
                                    i, d, enc_mask=enc_mask))
            nxt = jnp.argmax(logits[:, -1], -1).astype(jnp.int32)
            dec = jnp.concatenate([dec, nxt[:, None]], axis=1)
        return dec[:, 1:]


def t5_tensor_rules(path, leaf):
    """TP rules (reference t5 policy: q/k/v/wi column, o/wo row). The shared
    embedding shards its hidden dim, so the tied ``attend`` head contracts
    over the sharded axis (row-parallel psum) like the other tied-head
    families here."""
    from jax.sharding import PartitionSpec
    names = [str(getattr(k, "key", getattr(k, "idx", k))) for k in path]
    if "shared" in names or "lm_head" in names:
        return PartitionSpec(None, "tensor")
    if names[-1] != "kernel":
        return None
    if any(n in names for n in ("q", "k", "v")):
        return PartitionSpec(None, "tensor", None)
    if "o" in names:
        return PartitionSpec("tensor", None, None)
    if any(n in names for n in ("wi", "wi_0", "wi_1")):
        return PartitionSpec(None, "tensor")
    if "wo" in names:
        return PartitionSpec("tensor", None)
    return None


def convert_hf_t5(hf_state, cfg: T5Config):
    """HF T5 naming -> our tree (q/k/v/o Linear weights transpose into
    DenseGeneral kernels; rel-bias tables live on layer 0 of each stack)."""
    def get(name):
        v = hf_state[name]
        return np.asarray(v.detach().cpu().numpy() if hasattr(v, "detach") else v)

    d, h, dk = cfg.d_model, cfg.num_heads, cfg.d_kv
    tree = {"shared": {"embedding": get("shared.weight")},
            "enc_final_norm": {"scale": get("encoder.final_layer_norm.weight")},
            "dec_final_norm": {"scale": get("decoder.final_layer_norm.weight")}}
    if not cfg.tie_word_embeddings:
        tree["lm_head"] = get("lm_head.weight").T

    def attn(prefix, has_bias, bias_name):
        out = {
            "q": {"kernel": get(prefix + "q.weight").T.reshape(d, h, dk)},
            "k": {"kernel": get(prefix + "k.weight").T.reshape(d, h, dk)},
            "v": {"kernel": get(prefix + "v.weight").T.reshape(d, h, dk)},
            "o": {"kernel": get(prefix + "o.weight").T.reshape(h, dk, d)},
        }
        if has_bias:
            out["rel_bias"] = get(prefix + bias_name)
        return out

    for stack, n, dec in (("encoder", cfg.num_layers, False),
                          ("decoder", cfg.n_dec_, True)):
        for i in range(n):
            p = f"{stack}.block.{i}.layer."
            name = f"{'dec' if dec else 'enc'}_layer_{i}"
            layer = {
                "ln_self": {"scale": get(p + "0.layer_norm.weight")},
                "self_attn": attn(p + "0.SelfAttention.", i == 0,
                                  "relative_attention_bias.weight"),
            }
            ff_idx = 2 if dec else 1
            if dec:
                layer["ln_cross"] = {"scale": get(p + "1.layer_norm.weight")}
                layer["cross_attn"] = attn(p + "1.EncDecAttention.", False, "")
            layer["ln_ffn"] = {"scale": get(p + f"{ff_idx}.layer_norm.weight")}
            ffn = {}
            if cfg.gated_act:
                ffn["wi_0"] = {"kernel": get(p + f"{ff_idx}.DenseReluDense.wi_0.weight").T}
                ffn["wi_1"] = {"kernel": get(p + f"{ff_idx}.DenseReluDense.wi_1.weight").T}
            else:
                ffn["wi"] = {"kernel": get(p + f"{ff_idx}.DenseReluDense.wi.weight").T}
            ffn["wo"] = {"kernel": get(p + f"{ff_idx}.DenseReluDense.wo.weight").T}
            layer["ffn"] = ffn
            tree[name] = layer
    return {"model": tree}
