"""Simple fixture models.

Reference analog: ``tests/unit/simple_model.py`` (``SimpleModel``, random
dataloaders) — the standard unit-test fixture, kept in the package so examples and
benchmarks share it.
"""

from typing import Any, Optional, Sequence

import flax.linen as nn
import jax
import jax.numpy as jnp
import numpy as np


class SimpleModel(nn.Module):
    """MLP over dict batches {"x": [B, D], "y": [B]} returning mean cross-entropy.

    Mirrors the reference SimpleModel's role: smallest thing with >1 layer that
    exercises sharding, precision, and the optimizer.
    """
    hidden_dim: int = 64
    num_layers: int = 2
    num_classes: int = 10

    @nn.compact
    def __call__(self, batch):
        x = batch["x"].astype(jnp.float32) if batch["x"].dtype == jnp.float64 \
            else batch["x"]
        for _ in range(self.num_layers):
            x = nn.Dense(self.hidden_dim)(x)
            x = nn.relu(x)
        logits = nn.Dense(self.num_classes)(x)
        labels = jax.nn.one_hot(batch["y"], self.num_classes, dtype=logits.dtype)
        loss = -jnp.sum(labels * jax.nn.log_softmax(logits, axis=-1), axis=-1)
        return jnp.mean(loss)


class SimpleCNN(nn.Module):
    """Tiny CNN for the cifar10-style end-to-end slice (BASELINE config 1)."""
    num_classes: int = 10

    @nn.compact
    def __call__(self, batch):
        x = batch["x"]
        x = nn.Conv(16, (3, 3))(x)
        x = nn.relu(x)
        x = nn.avg_pool(x, (2, 2), strides=(2, 2))
        x = nn.Conv(32, (3, 3))(x)
        x = nn.relu(x)
        x = nn.avg_pool(x, (2, 2), strides=(2, 2))
        x = x.reshape((x.shape[0], -1))
        x = nn.Dense(128)(x)
        x = nn.relu(x)
        logits = nn.Dense(self.num_classes)(x)
        labels = jax.nn.one_hot(batch["y"], self.num_classes, dtype=logits.dtype)
        return jnp.mean(-jnp.sum(labels * jax.nn.log_softmax(logits, -1), -1))


def random_dataset(n: int, input_dim: int = 32, num_classes: int = 10,
                   seed: int = 0) -> Sequence[Any]:
    rng = np.random.default_rng(seed)
    xs = rng.normal(size=(n, input_dim)).astype(np.float32)
    ys = rng.integers(0, num_classes, size=(n,)).astype(np.int32)
    return [{"x": xs[i], "y": ys[i]} for i in range(n)]


def random_batch(batch_size: int, input_dim: int = 32, num_classes: int = 10,
                 seed: int = 0, gas: Optional[int] = None):
    rng = np.random.default_rng(seed)
    shape = (gas, batch_size) if gas else (batch_size,)
    return {
        "x": rng.normal(size=shape + (input_dim,)).astype(np.float32),
        "y": rng.integers(0, num_classes, size=shape).astype(np.int32),
    }
