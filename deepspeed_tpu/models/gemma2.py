"""Gemma-2 causal LM — the sandwich-norm / alternating-window gemma family.

Reference analog: the v2 engine's gemma coverage stops at gemma-1
(``inference/v2/model_implementations``); gemma-2's block differs enough to
be its own family (this was an explicitly-flagged gap): four RMS norms per
block (post-attention and post-feedforward applied to the SUBLAYER OUTPUT
before the residual add), attention-logit soft-capping, a decoupled
``query_pre_attn_scalar`` attention scale, and alternating
sliding/full-window attention per layer (even layers sliding). Shares the
gemma conventions already in-tree: (1+scale) zero-centered RMS norms,
sqrt(hidden) embedding normalizer, gelu-tanh gated MLP, tied head with
final-logit soft-capping.
"""

import dataclasses
from functools import partial
from typing import Any, Optional

import flax.linen as nn
import jax
import jax.numpy as jnp
import numpy as np
from jax.sharding import PartitionSpec

from deepspeed_tpu.models.llama import (BATCH_AXES, HEADS_AXIS, SEQ_AXIS,
                                        RMSNorm, _xla_attention,
                                        apply_rope, llama_tensor_rules,
                                        rope_freqs, shard_activation)

@dataclasses.dataclass(frozen=True)
class Gemma2Config:
    vocab_size: int = 256000
    hidden_size: int = 2304
    intermediate_size: int = 9216
    num_layers: int = 26
    num_heads: int = 8
    num_kv_heads: int = 4
    head_dim: int = 256
    max_seq_len: int = 8192
    rope_theta: float = 10000.0
    rms_norm_eps: float = 1e-6
    query_pre_attn_scalar: float = 256.0
    attn_logit_softcap: Optional[float] = 50.0
    final_logit_softcap: Optional[float] = 30.0
    sliding_window: int = 4096
    dtype: Any = jnp.bfloat16

    def is_sliding(self, layer_idx: int) -> bool:
        # HF layer_types: sliding_attention on even indices, full on odd
        return layer_idx % 2 == 0


TINY_GEMMA2 = Gemma2Config(
    vocab_size=512, hidden_size=64, intermediate_size=128, num_layers=4,
    num_heads=4, num_kv_heads=2, head_dim=16, max_seq_len=128,
    sliding_window=8, attn_logit_softcap=50.0, final_logit_softcap=30.0,
    # != head_dim so the serving path's folded scale is a real factor
    query_pre_attn_scalar=32.0)


class Gemma2Attention(nn.Module):
    cfg: Gemma2Config
    sliding: bool

    @nn.compact
    def __call__(self, x, positions):
        cfg = self.cfg
        d = cfg.head_dim
        dense = partial(nn.DenseGeneral, use_bias=False, dtype=cfg.dtype,
                        param_dtype=jnp.float32)
        q = dense(features=(cfg.num_heads, d), name="wq")(x)
        k = dense(features=(cfg.num_kv_heads, d), name="wk")(x)
        v = dense(features=(cfg.num_kv_heads, d), name="wv")(x)
        q = shard_activation(q, (BATCH_AXES, SEQ_AXIS, HEADS_AXIS, None))
        k = shard_activation(k, (BATCH_AXES, SEQ_AXIS, HEADS_AXIS, None))
        v = shard_activation(v, (BATCH_AXES, SEQ_AXIS, HEADS_AXIS, None))
        cos, sin = rope_freqs(d, cfg.max_seq_len, cfg.rope_theta)
        q = apply_rope(q, jnp.asarray(cos), jnp.asarray(sin), positions)
        k = apply_rope(k, jnp.asarray(cos), jnp.asarray(sin), positions)
        out = _xla_attention(
            q, k, v, causal=True,
            window=cfg.sliding_window if self.sliding else None,
            scale=cfg.query_pre_attn_scalar ** -0.5,
            softcap=cfg.attn_logit_softcap)
        out = shard_activation(out, (BATCH_AXES, SEQ_AXIS, HEADS_AXIS, None))
        return nn.DenseGeneral(features=cfg.hidden_size, axis=(-2, -1),
                               use_bias=False, dtype=cfg.dtype,
                               param_dtype=jnp.float32, name="wo")(out)


class Gemma2MLP(nn.Module):
    cfg: Gemma2Config

    @nn.compact
    def __call__(self, x):
        cfg = self.cfg
        dense = partial(nn.Dense, use_bias=False, dtype=cfg.dtype,
                        param_dtype=jnp.float32)
        g = nn.gelu(dense(cfg.intermediate_size, name="w_gate")(x),
                    approximate=True)
        u = dense(cfg.intermediate_size, name="w_up")(x)
        h = shard_activation(g * u, (BATCH_AXES, SEQ_AXIS, HEADS_AXIS))
        return dense(cfg.hidden_size, name="w_down")(h)


class Gemma2Block(nn.Module):
    """Sandwich norms: the post-attention / post-feedforward norms apply to
    the sublayer OUTPUT before the residual add (gemma-2's signature)."""
    cfg: Gemma2Config
    layer_idx: int

    @nn.compact
    def __call__(self, x, positions):
        cfg = self.cfg
        norm = partial(RMSNorm, cfg.rms_norm_eps, cfg.dtype,
                       scale_offset=True)
        h = Gemma2Attention(cfg, cfg.is_sliding(self.layer_idx),
                            name="attn")(norm(name="attn_norm")(x), positions)
        x = x + norm(name="post_attn_norm")(h)
        h2 = Gemma2MLP(cfg, name="mlp")(norm(name="pre_ffw_norm")(x))
        x = x + norm(name="post_ffw_norm")(h2)
        return shard_activation(x, (BATCH_AXES, SEQ_AXIS, None))


class Gemma2ForCausalLM(nn.Module):
    """batch {"input_ids": [B,S]} -> mean next-token CE (tied head with
    final-logit soft-capping)."""
    cfg: Gemma2Config

    @nn.compact
    def _backbone(self, input_ids):
        cfg = self.cfg
        positions = jnp.broadcast_to(jnp.arange(input_ids.shape[1]),
                                     input_ids.shape)
        embed = nn.Embed(cfg.vocab_size, cfg.hidden_size, dtype=cfg.dtype,
                         param_dtype=jnp.float32, name="embed")
        x = embed(input_ids)
        x = x * jnp.sqrt(jnp.asarray(cfg.hidden_size,
                                     jnp.float32)).astype(x.dtype)
        for i in range(cfg.num_layers):
            x = Gemma2Block(cfg, i, name=f"layer_{i}")(x, positions)
        x = RMSNorm(cfg.rms_norm_eps, cfg.dtype, scale_offset=True,
                    name="final_norm")(x)
        from deepspeed_tpu.models.llama import softcap_logits
        return softcap_logits(embed.attend(x).astype(jnp.float32),
                              cfg.final_logit_softcap)

    @property
    def config(self):
        return self.cfg

    def __call__(self, batch):
        input_ids = batch["input_ids"]
        logits = self._backbone(input_ids)
        labels = input_ids[:, 1:]
        logp = jax.nn.log_softmax(logits[:, :-1], axis=-1)
        ll = jnp.take_along_axis(logp, labels[..., None], axis=-1)[..., 0]
        return -jnp.mean(ll)

    def logits(self, batch):
        return self._backbone(batch["input_ids"])


def gemma2_tensor_rules(path, leaf) -> Optional[PartitionSpec]:
    """Same projection names as the llama family -> llama's TP rules apply."""
    return llama_tensor_rules(path, leaf)


# ---------------------------------------------------------------------------
# HF interop
# ---------------------------------------------------------------------------
def gemma2_config_from_hf(hf: dict) -> Gemma2Config:
    if hf.get("model_type", "gemma2") != "gemma2":
        raise ValueError(f"not a gemma2 config: {hf.get('model_type')!r}")
    return Gemma2Config(
        vocab_size=hf["vocab_size"],
        hidden_size=hf["hidden_size"],
        intermediate_size=hf["intermediate_size"],
        num_layers=hf["num_hidden_layers"],
        num_heads=hf["num_attention_heads"],
        num_kv_heads=hf.get("num_key_value_heads",
                            hf["num_attention_heads"]),
        head_dim=hf.get("head_dim", 256),
        max_seq_len=hf.get("max_position_embeddings", 8192),
        rope_theta=hf.get("rope_theta", 10000.0),
        rms_norm_eps=hf.get("rms_norm_eps", 1e-6),
        query_pre_attn_scalar=float(hf.get("query_pre_attn_scalar", 256)),
        attn_logit_softcap=hf.get("attn_logit_softcapping", 50.0),
        final_logit_softcap=hf.get("final_logit_softcapping", 30.0),
        sliding_window=hf.get("sliding_window", 4096))


def convert_hf_gemma2(hf_state, cfg: Gemma2Config):
    """Map an HF Gemma2 state dict into the Gemma2ForCausalLM tree (tied
    head; HF stores gemma norm weights as the zero-centered offset, same as
    our scale_offset convention, so norms map through directly)."""
    from deepspeed_tpu.models.families import _t as t
    from deepspeed_tpu.models.families import attn_tree_from_weights, hf_get

    def get(name):
        return hf_get(hf_state, name)

    d, h, hkv, dh = (cfg.hidden_size, cfg.num_heads, cfg.num_kv_heads,
                     cfg.head_dim)
    tree = {"embed": {"embedding": get("model.embed_tokens.weight")},
            "final_norm": {"scale": get("model.norm.weight")}}
    for i in range(cfg.num_layers):
        p = f"model.layers.{i}."
        tree[f"layer_{i}"] = {
            "attn_norm": {"scale": get(p + "input_layernorm.weight")},
            "post_attn_norm": {"scale":
                               get(p + "post_attention_layernorm.weight")},
            "pre_ffw_norm": {"scale":
                             get(p + "pre_feedforward_layernorm.weight")},
            "post_ffw_norm": {"scale":
                              get(p + "post_feedforward_layernorm.weight")},
            "attn": attn_tree_from_weights(
                get(p + "self_attn.q_proj.weight"),
                get(p + "self_attn.k_proj.weight"),
                get(p + "self_attn.v_proj.weight"),
                get(p + "self_attn.o_proj.weight"), d, h, hkv, dh),
            "mlp": {
                "w_gate": {"kernel": t(get(p + "mlp.gate_proj.weight"))},
                "w_up": {"kernel": t(get(p + "mlp.up_proj.weight"))},
                "w_down": {"kernel": t(get(p + "mlp.down_proj.weight"))},
            },
        }
    return tree
