"""BERT encoder family (bidirectional attention, post-LN, MLM head).

Reference analog: the BERT container (``module_inject/containers/bert.py``),
the vendored regression BERT (``tests/unit/modeling.py``), and the compression
suite's standard target (``deepspeed/compression`` examples train BERT). The
training kernel suite (``csrc/transformer/``, ``DeepSpeedTransformerLayer``)
was likewise built around BERT-style post-LN blocks.

Architecture: word + learned position + token-type embeddings with LayerNorm;
post-LN encoder blocks (attn -> add&LN -> GELU FFN -> add&LN); MLM head
(transform dense + GELU + LN, decoder tied to the embedding table + output
bias). Attention is bidirectional (``causal=False``) with an optional padding
mask via ``attention_mask``.
"""

import dataclasses
from functools import partial
from typing import Any, Optional

import flax.linen as nn
import jax
import jax.numpy as jnp
import numpy as np

from deepspeed_tpu.models.llama import (
    BATCH_AXES, HEADS_AXIS, SEQ_AXIS, shard_activation)

MLM_IGNORE_INDEX = -100


@dataclasses.dataclass(frozen=True)
class BertConfig:
    vocab_size: int = 30522
    hidden_size: int = 768
    intermediate_size: int = 3072
    num_layers: int = 12
    num_heads: int = 12
    max_position_embeddings: int = 512
    type_vocab_size: int = 2
    layer_norm_eps: float = 1e-12
    dtype: Any = jnp.float32

    @property
    def head_dim_(self) -> int:
        return self.hidden_size // self.num_heads


TINY_BERT = BertConfig(vocab_size=512, hidden_size=128, intermediate_size=256,
                       num_layers=2, num_heads=4, max_position_embeddings=128)


def _bidirectional_attention(q, k, v, attention_mask):
    """[B,S,H,d] attention without causal masking; ``attention_mask`` [B,S]
    (1 = attend, 0 = padding) masks keys."""
    d = q.shape[-1]
    scores = jnp.einsum("bqhd,bkhd->bhqk", q, k) / jnp.sqrt(
        jnp.asarray(d, jnp.float32)).astype(q.dtype)
    if attention_mask is not None:
        bias = jnp.where(attention_mask[:, None, None, :].astype(bool),
                         0.0, jnp.finfo(jnp.float32).min)
        scores = scores + bias.astype(scores.dtype)
    probs = jax.nn.softmax(scores.astype(jnp.float32), axis=-1).astype(q.dtype)
    return jnp.einsum("bhqk,bkhd->bqhd", probs, v)


class BertBlock(nn.Module):
    cfg: BertConfig

    @nn.compact
    def __call__(self, x, attention_mask=None):
        cfg = self.cfg
        d = cfg.head_dim_
        dense = partial(nn.DenseGeneral, use_bias=True, dtype=cfg.dtype,
                        param_dtype=jnp.float32)
        q = dense(features=(cfg.num_heads, d), name="wq")(x)
        k = dense(features=(cfg.num_heads, d), name="wk")(x)
        v = dense(features=(cfg.num_heads, d), name="wv")(x)
        q = shard_activation(q, (BATCH_AXES, SEQ_AXIS, HEADS_AXIS, None))
        attn = _bidirectional_attention(q, k, v, attention_mask)
        attn_out = nn.DenseGeneral(features=cfg.hidden_size, axis=(-2, -1),
                                   use_bias=True, dtype=cfg.dtype,
                                   param_dtype=jnp.float32, name="wo")(attn)
        x = nn.LayerNorm(epsilon=cfg.layer_norm_eps, dtype=cfg.dtype,
                         name="attn_ln")(x + attn_out)          # post-LN
        m = nn.Dense(cfg.intermediate_size, use_bias=True, dtype=cfg.dtype,
                     param_dtype=jnp.float32, name="fc1")(x)
        m = jax.nn.gelu(m)
        m = nn.Dense(cfg.hidden_size, use_bias=True, dtype=cfg.dtype,
                     param_dtype=jnp.float32, name="fc2")(m)
        x = nn.LayerNorm(epsilon=cfg.layer_norm_eps, dtype=cfg.dtype,
                         name="mlp_ln")(x + m)
        return shard_activation(x, (BATCH_AXES, SEQ_AXIS, None))


class BertModel(nn.Module):
    cfg: BertConfig

    @nn.compact
    def __call__(self, input_ids, token_type_ids=None, attention_mask=None,
                 positions=None):
        cfg = self.cfg
        if positions is None:
            positions = jnp.broadcast_to(jnp.arange(input_ids.shape[1]),
                                         input_ids.shape)
        if token_type_ids is None:
            token_type_ids = jnp.zeros_like(input_ids)
        embed = nn.Embed(cfg.vocab_size, cfg.hidden_size, dtype=cfg.dtype,
                         param_dtype=jnp.float32, name="embed")
        x = embed(input_ids)
        x = x + self.param("pos_embed", nn.initializers.normal(0.02),
                           (cfg.max_position_embeddings, cfg.hidden_size),
                           jnp.float32)[positions].astype(cfg.dtype)
        x = x + nn.Embed(cfg.type_vocab_size, cfg.hidden_size, dtype=cfg.dtype,
                         param_dtype=jnp.float32, name="type_embed")(token_type_ids)
        x = nn.LayerNorm(epsilon=cfg.layer_norm_eps, dtype=cfg.dtype,
                         name="embed_ln")(x)
        x = shard_activation(x, (BATCH_AXES, SEQ_AXIS, None))
        for i in range(cfg.num_layers):
            x = BertBlock(cfg, name=f"layer_{i}")(x, attention_mask)
        return x, embed


class BertForMaskedLM(nn.Module):
    """batch: {"input_ids", "labels" (MLM targets, -100 = unmasked),
    optional "token_type_ids"/"attention_mask"} -> mean MLM loss.
    ``logits(batch)`` returns [B, S, V] for evaluation."""
    cfg: BertConfig

    def setup(self):
        self.model = BertModel(self.cfg)
        self.mlm_dense = nn.Dense(self.cfg.hidden_size, dtype=self.cfg.dtype,
                                  param_dtype=jnp.float32, name="mlm_dense")
        self.mlm_ln = nn.LayerNorm(epsilon=self.cfg.layer_norm_eps,
                                   dtype=self.cfg.dtype, name="mlm_ln")
        self.mlm_bias = self.param("mlm_bias", nn.initializers.zeros,
                                   (self.cfg.vocab_size,), jnp.float32)

    @property
    def config(self):
        return self.cfg

    def _logits(self, batch):
        x, embed = self.model(batch["input_ids"],
                              batch.get("token_type_ids"),
                              batch.get("attention_mask"))
        h = jax.nn.gelu(self.mlm_dense(x))
        h = self.mlm_ln(h)
        return embed.attend(h).astype(jnp.float32) + self.mlm_bias  # tied

    def logits(self, batch):
        return self._logits(batch)

    def __call__(self, batch):
        logits = self._logits(batch)
        labels = batch.get("labels")
        if labels is None:   # engine warmup / perplexity eval: all positions
            labels = batch["input_ids"]
        mask = (labels != MLM_IGNORE_INDEX).astype(jnp.float32)
        safe = jnp.maximum(labels, 0)
        logp = jax.nn.log_softmax(logits, axis=-1)
        ll = jnp.take_along_axis(logp, safe[..., None], axis=-1)[..., 0]
        return -jnp.sum(ll * mask) / jnp.maximum(jnp.sum(mask), 1.0)


def bert_tensor_rules(path, leaf):
    from jax.sharding import PartitionSpec
    names = [str(getattr(k, "key", getattr(k, "idx", k))) for k in path]
    if "embed" in names or "type_embed" in names:
        return PartitionSpec(None, "tensor")
    if any(n in names for n in ("wq", "wk", "wv")) and names[-1] == "kernel":
        return PartitionSpec(None, "tensor", None)
    if "wo" in names and names[-1] == "kernel":
        return PartitionSpec("tensor", None, None)
    if "fc1" in names and names[-1] == "kernel":
        return PartitionSpec(None, "tensor")
    if "fc2" in names and names[-1] == "kernel":
        return PartitionSpec("tensor", None)
    return None


def mlm_mask_batch(input_ids: np.ndarray, rng: np.random.Generator,
                   mask_token_id: int, vocab_size: int,
                   mask_prob: float = 0.15):
    """Standard BERT masking: select mask_prob positions as targets; of those
    80% -> [MASK], 10% -> random token, 10% -> unchanged."""
    input_ids = np.array(input_ids, copy=True)
    labels = np.full_like(input_ids, MLM_IGNORE_INDEX)
    sel = rng.random(input_ids.shape) < mask_prob
    labels[sel] = input_ids[sel]
    roll = rng.random(input_ids.shape)
    input_ids[sel & (roll < 0.8)] = mask_token_id
    rand = sel & (roll >= 0.8) & (roll < 0.9)
    input_ids[rand] = rng.integers(0, vocab_size, size=int(rand.sum()))
    return {"input_ids": input_ids, "labels": labels}
