"""Model-family registry: arch presets + HF-checkpoint weight mappers.

Reference analog: ``deepspeed/inference/v2/model_implementations/`` — per-arch
mappers (llama_v2, mistral, mixtral, qwen, qwen_v2, phi, phi3, falcon, opt) that
translate a HuggingFace checkpoint into the engine's layer containers.

TPU shape: mistral / qwen2 / phi3 ARE the llama computation graph with knobs
(sliding window, qkv bias, fused projections), so they map onto ``LlamaConfig``
+ ``LlamaForCausalLM`` and get training, ZeRO/TP/SP sharding, AND the FastGen
paged decode for free. ``convert_hf_state_dict`` translates HF parameter naming
(torch ``[out, in]`` linears, fused qkv/gate_up for phi3) into our flax tree
(``[in, out]`` kernels, DenseGeneral ``[D, H, dh]`` attention projections).

Falcon (parallel attn+mlp block, LayerNorm, MQA) and OPT (learned positions,
LayerNorm, GELU) have genuinely different blocks — see ``models/falcon.py`` and
``models/opt.py``.
"""

import dataclasses
from typing import Any, Callable, Dict

import jax.numpy as jnp
import numpy as np

from deepspeed_tpu.models.llama import LlamaConfig

# ---------------------------------------------------------------------------
# Presets (public architecture configs)
# ---------------------------------------------------------------------------

MISTRAL_7B = LlamaConfig(
    vocab_size=32000, hidden_size=4096, intermediate_size=14336, num_layers=32,
    num_heads=32, num_kv_heads=8, max_seq_len=32768, rope_theta=10000.0,
    sliding_window=4096)

QWEN2_7B = LlamaConfig(
    vocab_size=152064, hidden_size=3584, intermediate_size=18944, num_layers=28,
    num_heads=28, num_kv_heads=4, max_seq_len=32768, rope_theta=1000000.0,
    attention_bias=True)

PHI3_MINI = LlamaConfig(
    vocab_size=32064, hidden_size=3072, intermediate_size=8192, num_layers=32,
    num_heads=32, num_kv_heads=32, max_seq_len=4096, rope_theta=10000.0)

# gemma (v1) is a llama variant: gelu_tanh gated MLP, (1+scale) norms, sqrt(d)
# embedding normalizer, tied head, head_dim decoupled from hidden/heads.
# gemma2 (sandwich norms, attention-logit softcapping, alternating
# sliding/full windows) is its own family: models/gemma2.py.
GEMMA_2B = LlamaConfig(
    vocab_size=256000, hidden_size=2048, intermediate_size=16384, num_layers=18,
    num_heads=8, num_kv_heads=1, head_dim=256, max_seq_len=8192,
    rope_theta=10000.0, rms_norm_eps=1e-6, tie_embeddings=True,
    hidden_act="gelu_tanh", rms_scale_offset=True, scale_embeddings=True)


def config_from_hf(hf_config: Dict[str, Any]) -> LlamaConfig:
    """Build a LlamaConfig from a HF config dict for any llama-family arch
    (reference: engine_factory reads the HF config to pick a policy)."""
    mt = hf_config.get("model_type", "llama")
    if mt not in ("llama", "mistral", "qwen2", "phi3", "gemma"):
        raise ValueError(f"not a llama-family arch: {mt!r} "
                         "(falcon/opt have their own model classes)")
    return LlamaConfig(
        vocab_size=hf_config["vocab_size"],
        hidden_size=hf_config["hidden_size"],
        intermediate_size=hf_config["intermediate_size"],
        num_layers=hf_config["num_hidden_layers"],
        num_heads=hf_config["num_attention_heads"],
        num_kv_heads=hf_config.get("num_key_value_heads",
                                   hf_config["num_attention_heads"]),
        max_seq_len=hf_config.get("max_position_embeddings", 4096),
        rope_theta=hf_config.get("rope_theta", 10000.0),
        rms_norm_eps=hf_config.get("rms_norm_eps", 1e-5),
        tie_embeddings=hf_config.get("tie_word_embeddings", False),
        attention_bias=(mt == "qwen2") or hf_config.get("attention_bias", False),
        sliding_window=hf_config.get("sliding_window")
        if mt == "mistral" else None,
        head_dim=hf_config.get("head_dim"),
        hidden_act="gelu_tanh" if mt == "gemma" else "silu",
        rms_scale_offset=(mt == "gemma"),
        scale_embeddings=(mt == "gemma"),
    )


# ---------------------------------------------------------------------------
# HF -> flax-tree weight conversion
# ---------------------------------------------------------------------------

def _t(w) -> np.ndarray:
    return np.asarray(w).T


def hf_get(state, name) -> np.ndarray:
    """Fetch one tensor from an HF state dict as numpy (torch tensors are
    detached/CPU'd; bf16 upcast to fp32 first since numpy has no bfloat16).
    Shared by every family converter."""
    v = state[name]
    if hasattr(v, "detach"):
        v = v.detach().cpu()
        if str(v.dtype) == "torch.bfloat16":
            v = v.float()
        return v.numpy()
    return np.asarray(v)


def attn_tree_from_weights(wq, wk, wv, wo, d, h, hkv, dh,
                           bq=None, bk=None, bv=None):
    """HF [out, in] projection weights -> the LlamaAttention param subtree
    (DenseGeneral kernels [D, heads, dh] / wo [h, dh, D], biases [heads, dh]).
    Single source of the attention layout mapping, shared by every
    llama-family converter (incl. qwen2-moe)."""
    attn = {
        "wq": {"kernel": _t(wq).reshape(d, h, dh)},
        "wk": {"kernel": _t(wk).reshape(d, hkv, dh)},
        "wv": {"kernel": _t(wv).reshape(d, hkv, dh)},
        "wo": {"kernel": _t(wo).reshape(h, dh, d)},
    }
    if bq is not None:
        attn["wq"]["bias"] = np.asarray(bq).reshape(h, dh)
        attn["wk"]["bias"] = np.asarray(bk).reshape(hkv, dh)
        attn["wv"]["bias"] = np.asarray(bv).reshape(hkv, dh)
    return attn


def convert_hf_state_dict(hf_state: Dict[str, Any], cfg: LlamaConfig,
                          model_type: str = "llama") -> Dict[str, Any]:
    """Map a HF state dict (numpy/torch tensors keyed 'model.layers.0.…') into
    the LlamaForCausalLM param tree. Handles phi3's fused ``qkv_proj`` /
    ``gate_up_proj`` (reference: phi3 containers split fused tensors) and
    qwen2's qkv biases."""
    def get(name):
        return hf_get(hf_state, name)

    d, h, hkv, dh = cfg.hidden_size, cfg.num_heads, cfg.num_kv_heads, cfg.head_dim_
    tree: Dict[str, Any] = {"model": {}}
    m = tree["model"]
    m["embed"] = {"embedding": get("model.embed_tokens.weight")}
    m["final_norm"] = {"scale": get("model.norm.weight")}
    if not cfg.tie_embeddings:
        m["lm_head"] = {"kernel": _t(get("lm_head.weight"))}

    for i in range(cfg.num_layers):
        p = f"model.layers.{i}."
        layer: Dict[str, Any] = {}
        layer["attn_norm"] = {"scale": get(p + "input_layernorm.weight")}
        layer["mlp_norm"] = {"scale": get(p + "post_attention_layernorm.weight")}

        if model_type == "phi3":
            qkv = get(p + "self_attn.qkv_proj.weight")     # [(h+2hkv)*dh, D]
            wq, wk, wv = np.split(qkv, [h * dh, (h + hkv) * dh], axis=0)
        else:
            wq = get(p + "self_attn.q_proj.weight")
            wk = get(p + "self_attn.k_proj.weight")
            wv = get(p + "self_attn.v_proj.weight")
        biases = {}
        if cfg.attention_bias:
            biases = dict(bq=get(p + "self_attn.q_proj.bias"),
                          bk=get(p + "self_attn.k_proj.bias"),
                          bv=get(p + "self_attn.v_proj.bias"))
        layer["attn"] = attn_tree_from_weights(
            wq, wk, wv, get(p + "self_attn.o_proj.weight"),
            d, h, hkv, dh, **biases)

        if model_type == "phi3":
            gu = get(p + "mlp.gate_up_proj.weight")        # [2I, D]
            wg, wu = np.split(gu, 2, axis=0)
        else:
            wg = get(p + "mlp.gate_proj.weight")
            wu = get(p + "mlp.up_proj.weight")
        layer["mlp"] = {
            "w_gate": {"kernel": _t(wg)},
            "w_up": {"kernel": _t(wu)},
            "w_down": {"kernel": _t(get(p + "mlp.down_proj.weight"))},
        }
        m[f"layer_{i}"] = layer
    return tree


def export_hf_state_dict(params: Dict[str, Any], cfg: LlamaConfig) -> Dict[str, np.ndarray]:
    """Inverse mapping (our tree -> HF naming), for checkpoint interchange."""
    d, h, hkv, dh = cfg.hidden_size, cfg.num_heads, cfg.num_kv_heads, cfg.head_dim_
    m = params["model"]
    out: Dict[str, np.ndarray] = {
        "model.embed_tokens.weight": np.asarray(m["embed"]["embedding"]),
        "model.norm.weight": np.asarray(m["final_norm"]["scale"]),
    }
    if "lm_head" in m:
        out["lm_head.weight"] = _t(np.asarray(m["lm_head"]["kernel"]))
    for i in range(cfg.num_layers):
        lp = m[f"layer_{i}"]
        p = f"model.layers.{i}."
        out[p + "input_layernorm.weight"] = np.asarray(lp["attn_norm"]["scale"])
        out[p + "post_attention_layernorm.weight"] = np.asarray(lp["mlp_norm"]["scale"])
        out[p + "self_attn.q_proj.weight"] = _t(
            np.asarray(lp["attn"]["wq"]["kernel"]).reshape(d, h * dh))
        out[p + "self_attn.k_proj.weight"] = _t(
            np.asarray(lp["attn"]["wk"]["kernel"]).reshape(d, hkv * dh))
        out[p + "self_attn.v_proj.weight"] = _t(
            np.asarray(lp["attn"]["wv"]["kernel"]).reshape(d, hkv * dh))
        for nm, key in (("q", "wq"), ("k", "wk"), ("v", "wv")):
            if "bias" in lp["attn"][key]:
                out[p + f"self_attn.{nm}_proj.bias"] = \
                    np.asarray(lp["attn"][key]["bias"]).reshape(-1)
        out[p + "self_attn.o_proj.weight"] = _t(
            np.asarray(lp["attn"]["wo"]["kernel"]).reshape(h * dh, d))
        out[p + "mlp.gate_proj.weight"] = _t(np.asarray(lp["mlp"]["w_gate"]["kernel"]))
        out[p + "mlp.up_proj.weight"] = _t(np.asarray(lp["mlp"]["w_up"]["kernel"]))
        out[p + "mlp.down_proj.weight"] = _t(np.asarray(lp["mlp"]["w_down"]["kernel"]))
    return out
