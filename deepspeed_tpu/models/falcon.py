"""Falcon model family (parallel attention+MLP block, multi-query attention).

Reference analog: ``deepspeed/inference/v2/model_implementations/falcon`` and
the falcon container in ``module_inject/containers``. Architecture (Falcon-7B):
one shared LayerNorm feeding BOTH attention and MLP in parallel
(``parallel_attn`` + ``new_decoder_architecture=False``); multi-query attention
(1 KV head); rotary embeddings; GELU MLP; tied embeddings.
"""

import dataclasses
from functools import partial
from typing import Any, Optional

import flax.linen as nn
import jax
import jax.numpy as jnp
import numpy as np

from deepspeed_tpu.models.llama import (
    BATCH_AXES, SEQ_AXIS, HEADS_AXIS, _dispatch_attention, apply_rope,
    rope_freqs, shard_activation)


@dataclasses.dataclass(frozen=True)
class FalconConfig:
    vocab_size: int = 65024
    hidden_size: int = 4544
    num_layers: int = 32
    num_heads: int = 71
    num_kv_heads: int = 1          # multi-query
    max_seq_len: int = 2048
    rope_theta: float = 10000.0
    layer_norm_eps: float = 1e-5
    dtype: Any = jnp.bfloat16
    attention_backend: str = "xla"
    # HF falcon 40B/180B checkpoints interleave the fused qkv per KV group
    # (new_decoder_architecture=True in the HF config); 7B multi_query packs
    # q rows then k then v sequentially
    new_decoder_architecture: bool = False

    @property
    def head_dim_(self) -> int:
        return self.hidden_size // self.num_heads


TINY_FALCON = FalconConfig(vocab_size=512, hidden_size=128, num_layers=2,
                           num_heads=4, num_kv_heads=1, max_seq_len=256,
                           dtype=jnp.float32)


class FalconBlock(nn.Module):
    """Parallel residual: x + attn(ln(x)) + mlp(ln(x)). Falcon-7B
    (``parallel_attn``) shares one LayerNorm between the branches;
    new_decoder_architecture (40B/180B) has per-branch norms ln_attn/ln_mlp."""
    cfg: FalconConfig

    @nn.compact
    def __call__(self, x, positions):
        cfg = self.cfg
        d = cfg.head_dim_
        if cfg.new_decoder_architecture:
            h = nn.LayerNorm(epsilon=cfg.layer_norm_eps, dtype=cfg.dtype,
                             name="ln_attn")(x)
            h_mlp = nn.LayerNorm(epsilon=cfg.layer_norm_eps, dtype=cfg.dtype,
                                 name="ln_mlp")(x)
        else:
            h = nn.LayerNorm(epsilon=cfg.layer_norm_eps, dtype=cfg.dtype,
                             name="input_ln")(x)
            h_mlp = h

        dense = partial(nn.DenseGeneral, use_bias=False, dtype=cfg.dtype,
                        param_dtype=jnp.float32)
        q = dense(features=(cfg.num_heads, d), name="wq")(h)
        k = dense(features=(cfg.num_kv_heads, d), name="wk")(h)
        v = dense(features=(cfg.num_kv_heads, d), name="wv")(h)
        q = shard_activation(q, (BATCH_AXES, SEQ_AXIS, HEADS_AXIS, None))
        cos, sin = rope_freqs(d, cfg.max_seq_len, cfg.rope_theta)
        cos, sin = jnp.asarray(cos), jnp.asarray(sin)
        q = apply_rope(q, cos, sin, positions)
        k = apply_rope(k, cos, sin, positions)
        attn = _dispatch_attention(cfg.attention_backend, q, k, v, causal=True)
        attn_out = nn.DenseGeneral(features=cfg.hidden_size, axis=(-2, -1),
                                   use_bias=False, dtype=cfg.dtype,
                                   param_dtype=jnp.float32, name="wo")(attn)

        mlp = nn.Dense(4 * cfg.hidden_size, use_bias=False, dtype=cfg.dtype,
                       param_dtype=jnp.float32, name="mlp_up")(h_mlp)
        mlp = nn.gelu(mlp)
        mlp_out = nn.Dense(cfg.hidden_size, use_bias=False, dtype=cfg.dtype,
                           param_dtype=jnp.float32, name="mlp_down")(mlp)
        # parallel residual sum
        return shard_activation(x + attn_out + mlp_out,
                                (BATCH_AXES, SEQ_AXIS, None))


class FalconModel(nn.Module):
    cfg: FalconConfig

    @nn.compact
    def __call__(self, input_ids, positions=None):
        cfg = self.cfg
        if positions is None:
            positions = jnp.arange(input_ids.shape[1])[None, :]
        x = nn.Embed(cfg.vocab_size, cfg.hidden_size, dtype=cfg.dtype,
                     param_dtype=jnp.float32, name="embed")(input_ids)
        for i in range(cfg.num_layers):
            x = FalconBlock(cfg, name=f"layer_{i}")(x, positions)
        x = nn.LayerNorm(epsilon=cfg.layer_norm_eps, dtype=cfg.dtype,
                         name="final_ln")(x)
        # tied embeddings (falcon ties lm_head to word embeddings)
        embed = self.variables["params"]["embed"]["embedding"]
        return x.astype(jnp.float32) @ embed.astype(jnp.float32).T


class FalconForCausalLM(nn.Module):
    """Batch dict {"input_ids": [B,S]} -> mean next-token cross-entropy (same
    contract as LlamaForCausalLM)."""
    cfg: FalconConfig

    def setup(self):
        self.model = FalconModel(self.cfg)

    @property
    def config(self):
        return self.cfg

    def __call__(self, batch):
        input_ids = batch["input_ids"]
        logits = self.model(input_ids, positions=batch.get("positions"))
        labels = input_ids[:, 1:]
        logp = jax.nn.log_softmax(logits[:, :-1].astype(jnp.float32), axis=-1)
        ll = jnp.take_along_axis(logp, labels[..., None], axis=-1)[..., 0]
        return -jnp.mean(ll)

    def logits(self, batch):
        return self.model(batch["input_ids"],
                          positions=batch.get("positions"))


def falcon_tensor_rules(path, leaf):
    """TP sharding rules (AutoTP analog) for Falcon params."""
    from jax.sharding import PartitionSpec
    names = [str(getattr(k, "key", getattr(k, "idx", k))) for k in path]
    if "embed" in names:
        return PartitionSpec(None, "tensor")
    if "wq" in names:
        return PartitionSpec(None, "tensor", None)
    if any(n in names for n in ("wk", "wv")):
        # MQA: a single KV head cannot shard across tensor ranks — replicate
        # (the reference AutoTP replicates undersized kv projections too)
        return PartitionSpec()
    if "wo" in names:
        return PartitionSpec("tensor", None, None)
    if "mlp_up" in names:
        return PartitionSpec(None, "tensor")
    if "mlp_down" in names:
        return PartitionSpec("tensor", None)
    return None


def _split_falcon_qkv(qkv, cfg: "FalconConfig"):
    """Split HF falcon's fused query_key_value rows into (wq, wk, wv).

    HF layouts (transformers FalconAttention._split_heads):
    - new_decoder_architecture (40B/180B): rows interleave per KV group —
      [hkv groups × (h/hkv q-heads, 1 k-head, 1 v-head) × dh];
    - multi_query (hkv=1, Falcon-7B): sequential q|k|v rows;
    - old MHA (hkv=h, falcon-rw): per-head interleaved [q_i, k_i, v_i] — the
      grouped layout with group size 1, NOT a sequential split.
    """
    h, hkv, dh = cfg.num_heads, cfg.num_kv_heads, cfg.head_dim_
    if cfg.new_decoder_architecture or hkv == h:
        g = h // hkv
        grouped = qkv.reshape(hkv, g + 2, dh, qkv.shape[1])
        wq = grouped[:, :g].reshape(h * dh, qkv.shape[1])
        wk = grouped[:, g].reshape(hkv * dh, qkv.shape[1])
        wv = grouped[:, g + 1].reshape(hkv * dh, qkv.shape[1])
        return wq, wk, wv
    if hkv != 1:
        raise ValueError(
            f"sequential falcon qkv split is only valid for multi_query "
            f"(hkv=1); got num_kv_heads={hkv}, num_heads={h}. Grouped "
            f"checkpoints must set new_decoder_architecture=True.")
    return np.split(qkv, [h * dh, (h + hkv) * dh], axis=0)


def convert_hf_falcon(hf_state, cfg: FalconConfig):
    """HF falcon naming -> our tree: fused query_key_value [(H+2Hkv)*dh, D]
    split into wq/wk/wv (per-KV-group interleaved for new_decoder_architecture);
    dense_h_to_4h/dense_4h_to_h -> mlp_up/mlp_down."""
    def get(name):
        v = hf_state[name]
        return np.asarray(v.detach().cpu().numpy() if hasattr(v, "detach") else v)

    d, h, hkv, dh = cfg.hidden_size, cfg.num_heads, cfg.num_kv_heads, cfg.head_dim_
    tree = {"embed": {"embedding": get("transformer.word_embeddings.weight")},
            "final_ln": {"scale": get("transformer.ln_f.weight"),
                         "bias": get("transformer.ln_f.bias")}}
    for i in range(cfg.num_layers):
        p = f"transformer.h.{i}."
        qkv = get(p + "self_attention.query_key_value.weight")
        wq, wk, wv = _split_falcon_qkv(qkv, cfg)
        if cfg.new_decoder_architecture:
            norms = {"ln_attn": {"scale": get(p + "ln_attn.weight"),
                                 "bias": get(p + "ln_attn.bias")},
                     "ln_mlp": {"scale": get(p + "ln_mlp.weight"),
                                "bias": get(p + "ln_mlp.bias")}}
        else:
            norms = {"input_ln": {"scale": get(p + "input_layernorm.weight"),
                                  "bias": get(p + "input_layernorm.bias")}}
        tree[f"layer_{i}"] = {
            **norms,
            "wq": {"kernel": wq.T.reshape(d, h, dh)},
            "wk": {"kernel": wk.T.reshape(d, hkv, dh)},
            "wv": {"kernel": wv.T.reshape(d, hkv, dh)},
            "wo": {"kernel": get(p + "self_attention.dense.weight").T
                   .reshape(h, dh, d)},
            "mlp_up": {"kernel": get(p + "mlp.dense_h_to_4h.weight").T},
            "mlp_down": {"kernel": get(p + "mlp.dense_4h_to_h.weight").T},
        }
    return {"model": tree}
