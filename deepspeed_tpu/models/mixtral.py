"""Mixtral-style MoE causal LM.

Reference analog: the MoE model path (``deepspeed/moe/layer.py:17`` MoE wraps a
dense block's MLP) + inference v2's ``qwen_v2_moe``/mixtral implementations. Here a
Llama backbone whose MLP is an expert-parallel MOELayer; aux (load-balance + router
z) losses are threaded functionally through the blocks into the LM loss, as the
reference accumulates them via MoE param groups.
"""

import dataclasses
from typing import Any, Optional

import flax.linen as nn
import jax
import jax.numpy as jnp
import numpy as np
from jax.sharding import PartitionSpec

from deepspeed_tpu.models.llama import (
    BATCH_AXES,
    SEQ_AXIS,
    LlamaAttention,
    LlamaConfig,
    RMSNorm,
    llama_tensor_rules,
    shard_activation,
)
from deepspeed_tpu.moe.sharded_moe import MOELayer, MoEConfig, moe_tensor_rules


@dataclasses.dataclass(frozen=True)
class MixtralConfig:
    base: LlamaConfig = LlamaConfig(
        vocab_size=32000, hidden_size=4096, intermediate_size=14336,
        num_layers=32, num_heads=32, num_kv_heads=8)
    moe: MoEConfig = MoEConfig(num_experts=8, top_k=2)


TINY_MIXTRAL = MixtralConfig(
    base=LlamaConfig(vocab_size=512, hidden_size=64, intermediate_size=128,
                     num_layers=2, num_heads=4, num_kv_heads=2, max_seq_len=128),
    moe=MoEConfig(num_experts=4, top_k=2, dtype=jnp.bfloat16))

MIXTRAL_8X7B = MixtralConfig(
    base=LlamaConfig(vocab_size=32000, hidden_size=4096, intermediate_size=14336,
                     num_layers=32, num_heads=32, num_kv_heads=8,
                     rope_theta=1000000.0),
    moe=MoEConfig(num_experts=8, top_k=2))


class MixtralBlock(nn.Module):
    cfg: MixtralConfig

    @nn.compact
    def __call__(self, x, positions, train: bool = True):
        base = self.cfg.base
        h = x + LlamaAttention(base, name="attn")(
            RMSNorm(base.rms_norm_eps, base.dtype, name="attn_norm")(x), positions)
        moe_out, aux = MOELayer(self.cfg.moe, base.hidden_size,
                                base.intermediate_size, name="moe")(
            RMSNorm(base.rms_norm_eps, base.dtype, name="mlp_norm")(h), train=train)
        out = h + moe_out
        return shard_activation(out, (BATCH_AXES, SEQ_AXIS, None)), aux


class MixtralForCausalLM(nn.Module):
    """batch {"input_ids": [B,S]} -> LM loss + weighted MoE aux losses."""
    cfg: MixtralConfig

    @nn.compact
    def _backbone(self, input_ids, train: bool = True):
        base = self.cfg.base
        positions = jnp.broadcast_to(jnp.arange(input_ids.shape[1]), input_ids.shape)
        embed = nn.Embed(base.vocab_size, base.hidden_size, dtype=base.dtype,
                         param_dtype=jnp.float32, name="embed")
        x = embed(input_ids)
        aux_total = jnp.float32(0.0)
        for i in range(base.num_layers):
            x, aux = MixtralBlock(self.cfg, name=f"layer_{i}")(x, positions, train)
            aux_total = aux_total + aux
        x = RMSNorm(base.rms_norm_eps, base.dtype, name="final_norm")(x)
        logits = nn.Dense(base.vocab_size, use_bias=False, dtype=jnp.float32,
                          param_dtype=jnp.float32, name="lm_head")(x)
        return logits, aux_total

    def __call__(self, batch, train: bool = True):
        input_ids = batch["input_ids"]
        logits, aux_total = self._backbone(input_ids, train)
        labels = input_ids[:, 1:]
        logits = logits[:, :-1]
        logp = jax.nn.log_softmax(logits.astype(jnp.float32), axis=-1)
        ll = jnp.take_along_axis(logp, labels[..., None], axis=-1)[..., 0]
        return -jnp.mean(ll) + aux_total

    def logits(self, batch):
        logits, _ = self._backbone(batch["input_ids"], train=False)
        return logits


def mixtral_tensor_rules(path, leaf) -> Optional[PartitionSpec]:
    """Compose attention TP rules with expert-parallel rules."""
    spec = moe_tensor_rules(path, leaf)
    if spec is not None:
        return spec
    return llama_tensor_rules(path, leaf)


# ---------------------------------------------------------------------------
# HF interop (reference: inference v2 mixtral containers/policy load HF
# Mixtral checkpoints; here the config + state-dict mappers)
# ---------------------------------------------------------------------------
def mixtral_config_from_hf(hf: dict) -> MixtralConfig:
    """Build a MixtralConfig from an HF ``MixtralConfig`` dict. HF Mixtral
    renormalizes the kept top-k routing weights (our ``norm_topk_prob=True``
    default)."""
    mt = hf.get("model_type", "mixtral")
    if mt != "mixtral" or "num_local_experts" not in hf:
        raise ValueError(f"not a Mixtral config (model_type={mt!r}); dense "
                         "llama-family archs go through families."
                         "config_from_hf")
    base = LlamaConfig(
        vocab_size=hf["vocab_size"],
        hidden_size=hf["hidden_size"],
        intermediate_size=hf["intermediate_size"],
        num_layers=hf["num_hidden_layers"],
        num_heads=hf["num_attention_heads"],
        num_kv_heads=hf.get("num_key_value_heads",
                            hf["num_attention_heads"]),
        max_seq_len=hf.get("max_position_embeddings", 4096),
        rope_theta=hf.get("rope_theta", 1e6),
        rms_norm_eps=hf.get("rms_norm_eps", 1e-5),
        sliding_window=hf.get("sliding_window"),
        head_dim=hf.get("head_dim"),
    )
    moe = MoEConfig(
        num_experts=hf["num_local_experts"],
        top_k=hf.get("num_experts_per_tok", 2),
        aux_loss_weight=hf.get("router_aux_loss_coef", 0.001),
        router_z_loss_weight=0.0,   # HF Mixtral has no router z-loss
    )
    return MixtralConfig(base=base, moe=moe)


def convert_hf_mixtral(hf_state, cfg: MixtralConfig):
    """Map an HF Mixtral state dict into the MixtralForCausalLM tree.
    HF expert naming: ``block_sparse_moe.experts.{e}.w1`` (gate, [I, D]),
    ``w2`` (down, [D, I]), ``w3`` (up, [I, D]); router
    ``block_sparse_moe.gate`` [E, D]. Attention mapping shared with the
    llama-family converter (families.attn_tree_from_weights)."""
    from deepspeed_tpu.models.families import _t as t
    from deepspeed_tpu.models.families import hf_get
    from deepspeed_tpu.models.families import attn_tree_from_weights

    def get(name):
        return hf_get(hf_state, name)

    base = cfg.base
    d, h, hkv, dh = (base.hidden_size, base.num_heads, base.num_kv_heads,
                     base.head_dim_)
    e = cfg.moe.num_experts
    tree = {"embed": {"embedding": get("model.embed_tokens.weight")},
            "final_norm": {"scale": get("model.norm.weight")},
            "lm_head": {"kernel": t(get("lm_head.weight"))}}
    for i in range(base.num_layers):
        p = f"model.layers.{i}."
        ep = p + "block_sparse_moe.experts."
        tree[f"layer_{i}"] = {
            "attn_norm": {"scale": get(p + "input_layernorm.weight")},
            "mlp_norm": {"scale": get(p + "post_attention_layernorm.weight")},
            "attn": attn_tree_from_weights(
                get(p + "self_attn.q_proj.weight"),
                get(p + "self_attn.k_proj.weight"),
                get(p + "self_attn.v_proj.weight"),
                get(p + "self_attn.o_proj.weight"), d, h, hkv, dh),
            "moe": {
                "gate": {"wg": {"kernel":
                                t(get(p + "block_sparse_moe.gate.weight"))}},
                "experts": {
                    "w_gate": np.stack([t(get(f"{ep}{j}.w1.weight"))
                                        for j in range(e)]),
                    "w_up": np.stack([t(get(f"{ep}{j}.w3.weight"))
                                      for j in range(e)]),
                    "w_down": np.stack([t(get(f"{ep}{j}.w2.weight"))
                                        for j in range(e)]),
                },
            },
        }
    return tree
