"""Mixtral-style MoE causal LM.

Reference analog: the MoE model path (``deepspeed/moe/layer.py:17`` MoE wraps a
dense block's MLP) + inference v2's ``qwen_v2_moe``/mixtral implementations. Here a
Llama backbone whose MLP is an expert-parallel MOELayer; aux (load-balance + router
z) losses are threaded functionally through the blocks into the LM loss, as the
reference accumulates them via MoE param groups.
"""

import dataclasses
from typing import Any, Optional

import flax.linen as nn
import jax
import jax.numpy as jnp
import numpy as np
from jax.sharding import PartitionSpec

from deepspeed_tpu.models.llama import (
    BATCH_AXES,
    SEQ_AXIS,
    LlamaAttention,
    LlamaConfig,
    RMSNorm,
    llama_tensor_rules,
    shard_activation,
)
from deepspeed_tpu.moe.sharded_moe import MOELayer, MoEConfig, moe_tensor_rules


@dataclasses.dataclass(frozen=True)
class MixtralConfig:
    base: LlamaConfig = LlamaConfig(
        vocab_size=32000, hidden_size=4096, intermediate_size=14336,
        num_layers=32, num_heads=32, num_kv_heads=8)
    moe: MoEConfig = MoEConfig(num_experts=8, top_k=2)


TINY_MIXTRAL = MixtralConfig(
    base=LlamaConfig(vocab_size=512, hidden_size=64, intermediate_size=128,
                     num_layers=2, num_heads=4, num_kv_heads=2, max_seq_len=128),
    moe=MoEConfig(num_experts=4, top_k=2, dtype=jnp.bfloat16))

MIXTRAL_8X7B = MixtralConfig(
    base=LlamaConfig(vocab_size=32000, hidden_size=4096, intermediate_size=14336,
                     num_layers=32, num_heads=32, num_kv_heads=8,
                     rope_theta=1000000.0),
    moe=MoEConfig(num_experts=8, top_k=2))


class MixtralBlock(nn.Module):
    cfg: MixtralConfig

    @nn.compact
    def __call__(self, x, positions, train: bool = True):
        base = self.cfg.base
        h = x + LlamaAttention(base, name="attn")(
            RMSNorm(base.rms_norm_eps, base.dtype, name="attn_norm")(x), positions)
        moe_out, aux = MOELayer(self.cfg.moe, base.hidden_size,
                                base.intermediate_size, name="moe")(
            RMSNorm(base.rms_norm_eps, base.dtype, name="mlp_norm")(h), train=train)
        out = h + moe_out
        return shard_activation(out, (BATCH_AXES, SEQ_AXIS, None)), aux


class MixtralForCausalLM(nn.Module):
    """batch {"input_ids": [B,S]} -> LM loss + weighted MoE aux losses."""
    cfg: MixtralConfig

    @nn.compact
    def _backbone(self, input_ids, train: bool = True):
        base = self.cfg.base
        positions = jnp.broadcast_to(jnp.arange(input_ids.shape[1]), input_ids.shape)
        embed = nn.Embed(base.vocab_size, base.hidden_size, dtype=base.dtype,
                         param_dtype=jnp.float32, name="embed")
        x = embed(input_ids)
        aux_total = jnp.float32(0.0)
        for i in range(base.num_layers):
            x, aux = MixtralBlock(self.cfg, name=f"layer_{i}")(x, positions, train)
            aux_total = aux_total + aux
        x = RMSNorm(base.rms_norm_eps, base.dtype, name="final_norm")(x)
        logits = nn.Dense(base.vocab_size, use_bias=False, dtype=jnp.float32,
                          param_dtype=jnp.float32, name="lm_head")(x)
        return logits, aux_total

    def __call__(self, batch, train: bool = True):
        input_ids = batch["input_ids"]
        logits, aux_total = self._backbone(input_ids, train)
        labels = input_ids[:, 1:]
        logits = logits[:, :-1]
        logp = jax.nn.log_softmax(logits.astype(jnp.float32), axis=-1)
        ll = jnp.take_along_axis(logp, labels[..., None], axis=-1)[..., 0]
        return -jnp.mean(ll) + aux_total

    def logits(self, batch):
        logits, _ = self._backbone(batch["input_ids"], train=False)
        return logits


def mixtral_tensor_rules(path, leaf) -> Optional[PartitionSpec]:
    """Compose attention TP rules with expert-parallel rules."""
    spec = moe_tensor_rules(path, leaf)
    if spec is not None:
        return spec
    return llama_tensor_rules(path, leaf)
