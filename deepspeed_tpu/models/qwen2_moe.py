"""Qwen2-MoE causal LM (Mixtral-style experts + a gated shared expert).

Reference analog: ``inference/v2/model_implementations/qwen_v2_moe`` — the
arch is a qwen2 backbone (attention bias) whose MLP is top-k routed experts
PLUS a dense "shared expert" applied to every token, scaled by a per-token
sigmoid gate (``shared_expert_gate``). Built on the same expert-parallel
MOELayer as Mixtral; the shared expert is an ordinary TP-sharded MLP.
"""

import dataclasses
from typing import Any, Optional

import flax.linen as nn
import jax
import numpy as np
import jax.numpy as jnp
from jax.sharding import PartitionSpec

from deepspeed_tpu.models.llama import (
    BATCH_AXES, SEQ_AXIS, LlamaAttention, LlamaConfig, RMSNorm,
    llama_tensor_rules, shard_activation)
from deepspeed_tpu.moe.sharded_moe import MOELayer, MoEConfig, moe_tensor_rules


@dataclasses.dataclass(frozen=True)
class Qwen2MoEConfig:
    base: LlamaConfig = LlamaConfig(
        vocab_size=151936, hidden_size=2048, intermediate_size=5632,
        num_layers=24, num_heads=16, num_kv_heads=16, attention_bias=True,
        rope_theta=1000000.0)
    # norm_topk_prob=False: HF Qwen2MoeConfig defaults it off for
    # Qwen1.5-MoE — combine weights are the raw softmax top-k probs
    moe: MoEConfig = MoEConfig(num_experts=60, top_k=4, norm_topk_prob=False)
    moe_intermediate_size: int = 1408
    shared_expert_intermediate_size: int = 5632


TINY_QWEN2_MOE = Qwen2MoEConfig(
    base=LlamaConfig(vocab_size=512, hidden_size=64, intermediate_size=128,
                     num_layers=2, num_heads=4, num_kv_heads=4,
                     attention_bias=True, max_seq_len=128),
    moe=MoEConfig(num_experts=4, top_k=2, norm_topk_prob=False,
                  dtype=jnp.bfloat16),
    moe_intermediate_size=32,
    shared_expert_intermediate_size=128,
)


class _SharedExpert(nn.Module):
    """Dense SwiGLU MLP over all tokens, output scaled by a per-token
    sigmoid gate (HF Qwen2MoeSparseMoeBlock.shared_expert[_gate])."""
    cfg: Qwen2MoEConfig

    @nn.compact
    def __call__(self, x):
        base = self.cfg.base
        dense = lambda f, n: nn.Dense(f, use_bias=False, dtype=base.dtype,
                                      param_dtype=jnp.float32, name=n)
        g = jax.nn.silu(dense(self.cfg.shared_expert_intermediate_size,
                              "w_gate")(x))
        u = dense(self.cfg.shared_expert_intermediate_size, "w_up")(x)
        out = dense(base.hidden_size, "w_down")(g * u)
        gate = nn.Dense(1, use_bias=False, dtype=base.dtype,
                        param_dtype=jnp.float32, name="gate")(x)
        return out * jax.nn.sigmoid(gate.astype(jnp.float32)).astype(out.dtype)


class Qwen2MoEBlock(nn.Module):
    cfg: Qwen2MoEConfig

    @nn.compact
    def __call__(self, x, positions, train: bool = True):
        base = self.cfg.base
        h = x + LlamaAttention(base, name="attn")(
            RMSNorm(base.rms_norm_eps, base.dtype, name="attn_norm")(x),
            positions)
        inp = RMSNorm(base.rms_norm_eps, base.dtype, name="mlp_norm")(h)
        moe_out, aux = MOELayer(self.cfg.moe, base.hidden_size,
                                self.cfg.moe_intermediate_size, name="moe")(
            inp, train=train)
        shared = _SharedExpert(self.cfg, name="shared_expert")(inp)
        out = h + moe_out + shared
        return shard_activation(out, (BATCH_AXES, SEQ_AXIS, None)), aux


class Qwen2MoEForCausalLM(nn.Module):
    """batch {"input_ids": [B,S]} -> LM loss + weighted MoE aux losses."""
    cfg: Qwen2MoEConfig

    @nn.compact
    def _backbone(self, input_ids, train: bool = True):
        base = self.cfg.base
        positions = jnp.broadcast_to(jnp.arange(input_ids.shape[1]),
                                     input_ids.shape)
        x = nn.Embed(base.vocab_size, base.hidden_size, dtype=base.dtype,
                     param_dtype=jnp.float32, name="embed")(input_ids)
        aux_total = jnp.float32(0.0)
        for i in range(base.num_layers):
            x, aux = Qwen2MoEBlock(self.cfg, name=f"layer_{i}")(
                x, positions, train)
            aux_total = aux_total + aux
        x = RMSNorm(base.rms_norm_eps, base.dtype, name="final_norm")(x)
        logits = nn.Dense(base.vocab_size, use_bias=False, dtype=jnp.float32,
                          param_dtype=jnp.float32, name="lm_head")(x)
        return logits, aux_total

    @property
    def config(self):
        return self.cfg

    def __call__(self, batch, train: bool = True):
        input_ids = batch["input_ids"]
        logits, aux_total = self._backbone(input_ids, train)
        labels = input_ids[:, 1:]
        logp = jax.nn.log_softmax(logits[:, :-1].astype(jnp.float32), axis=-1)
        ll = jnp.take_along_axis(logp, labels[..., None], axis=-1)[..., 0]
        return -jnp.mean(ll) + aux_total

    def logits(self, batch):
        logits, _ = self._backbone(batch["input_ids"], train=False)
        return logits


def qwen2_moe_tensor_rules(path, leaf) -> Optional[PartitionSpec]:
    """Expert rules + qwen2 attention/MLP rules. The shared expert's
    w_gate/w_up/w_down fall through to llama's MLP substring rules (column/
    column/row); its scalar sigmoid gate matches nothing and replicates."""
    spec = moe_tensor_rules(path, leaf)
    if spec is not None:
        return spec
    return llama_tensor_rules(path, leaf)


# ---------------------------------------------------------------------------
# HF interop (reference: qwen_v2_moe container/policy — the engine loads HF
# Qwen2Moe checkpoints; here config + state-dict mappers)
# ---------------------------------------------------------------------------
def qwen2_moe_config_from_hf(hf: dict) -> Qwen2MoEConfig:
    """Build a Qwen2MoEConfig from an HF ``Qwen2MoeConfig`` dict. Only the
    uniform-sparse layout is supported (every layer a sparse MoE block —
    ``decoder_sparse_step=1``, no ``mlp_only_layers``)."""
    if hf.get("decoder_sparse_step", 1) != 1 or hf.get("mlp_only_layers"):
        raise ValueError("only uniformly sparse Qwen2-MoE layouts are "
                         "supported (decoder_sparse_step=1, no "
                         "mlp_only_layers)")
    base = LlamaConfig(
        vocab_size=hf["vocab_size"],
        hidden_size=hf["hidden_size"],
        intermediate_size=hf.get("intermediate_size", 4 * hf["hidden_size"]),
        num_layers=hf["num_hidden_layers"],
        num_heads=hf["num_attention_heads"],
        num_kv_heads=hf.get("num_key_value_heads",
                            hf["num_attention_heads"]),
        max_seq_len=hf.get("max_position_embeddings", 4096),
        rope_theta=hf.get("rope_theta", 10000.0),
        rms_norm_eps=hf.get("rms_norm_eps", 1e-6),
        attention_bias=True,
    )
    moe = MoEConfig(
        num_experts=hf["num_experts"],
        top_k=hf.get("num_experts_per_tok", 4),
        norm_topk_prob=hf.get("norm_topk_prob", False),
        aux_loss_weight=hf.get("router_aux_loss_coef", 0.001),
    )
    return Qwen2MoEConfig(
        base=base, moe=moe,
        moe_intermediate_size=hf.get("moe_intermediate_size", 1408),
        shared_expert_intermediate_size=hf.get(
            "shared_expert_intermediate_size", 5632))


def convert_hf_qwen2_moe(hf_state, cfg: Qwen2MoEConfig):
    """Map an HF Qwen2Moe state dict into the Qwen2MoEForCausalLM tree
    (stacked expert weights [E, ...] for the expert-sharded Experts module).
    Attention mapping is shared with the llama-family converter
    (families.attn_tree_from_weights)."""
    from deepspeed_tpu.models.families import _t as t
    from deepspeed_tpu.models.families import hf_get
    from deepspeed_tpu.models.families import attn_tree_from_weights

    def get(name):
        return hf_get(hf_state, name)

    base = cfg.base
    d, h, hkv, dh = (base.hidden_size, base.num_heads, base.num_kv_heads,
                     base.head_dim_)
    e = cfg.moe.num_experts
    tree = {"embed": {"embedding": get("model.embed_tokens.weight")},
            "final_norm": {"scale": get("model.norm.weight")},
            "lm_head": {"kernel": t(get("lm_head.weight"))}}
    for i in range(base.num_layers):
        p = f"model.layers.{i}."
        attn = attn_tree_from_weights(
            get(p + "self_attn.q_proj.weight"),
            get(p + "self_attn.k_proj.weight"),
            get(p + "self_attn.v_proj.weight"),
            get(p + "self_attn.o_proj.weight"),
            d, h, hkv, dh,
            bq=get(p + "self_attn.q_proj.bias"),
            bk=get(p + "self_attn.k_proj.bias"),
            bv=get(p + "self_attn.v_proj.bias"))
        experts = {
            "w_gate": np.stack([t(get(p + f"mlp.experts.{j}.gate_proj.weight"))
                                for j in range(e)]),
            "w_up": np.stack([t(get(p + f"mlp.experts.{j}.up_proj.weight"))
                              for j in range(e)]),
            "w_down": np.stack([t(get(p + f"mlp.experts.{j}.down_proj.weight"))
                                for j in range(e)]),
        }
        tree[f"layer_{i}"] = {
            "attn_norm": {"scale": get(p + "input_layernorm.weight")},
            "mlp_norm": {"scale": get(p + "post_attention_layernorm.weight")},
            "attn": attn,
            "moe": {"gate": {"wg": {"kernel": t(get(p + "mlp.gate.weight"))}},
                    "experts": experts},
            "shared_expert": {
                "w_gate": {"kernel":
                           t(get(p + "mlp.shared_expert.gate_proj.weight"))},
                "w_up": {"kernel":
                         t(get(p + "mlp.shared_expert.up_proj.weight"))},
                "w_down": {"kernel":
                           t(get(p + "mlp.shared_expert.down_proj.weight"))},
                "gate": {"kernel":
                         t(get(p + "mlp.shared_expert_gate.weight"))},
            },
        }
    return tree
