"""OPT model family (learned positional embeddings, pre-LayerNorm, ReLU MLP).

Reference analog: ``deepspeed/inference/v2/model_implementations/opt`` and the
OPT container in ``module_inject/containers``. Architecture: learned position
embeddings with OPT's +2 offset convention, pre-norm decoder blocks, biased
projections, ReLU MLP, final LayerNorm, tied lm_head.
"""

import dataclasses
from functools import partial
from typing import Any

import flax.linen as nn
import jax
import jax.numpy as jnp
import numpy as np

from deepspeed_tpu.models.llama import (
    BATCH_AXES, SEQ_AXIS, HEADS_AXIS, _dispatch_attention, shard_activation)


@dataclasses.dataclass(frozen=True)
class OPTConfig:
    vocab_size: int = 50272
    hidden_size: int = 2048
    ffn_dim: int = 8192
    num_layers: int = 24
    num_heads: int = 32
    max_seq_len: int = 2048
    layer_norm_eps: float = 1e-5
    dtype: Any = jnp.bfloat16
    attention_backend: str = "xla"

    @property
    def head_dim_(self) -> int:
        return self.hidden_size // self.num_heads


TINY_OPT = OPTConfig(vocab_size=512, hidden_size=128, ffn_dim=256, num_layers=2,
                     num_heads=4, max_seq_len=128, dtype=jnp.float32)

# OPT's learned position table is offset by 2 (padding-token legacy)
OPT_POSITION_OFFSET = 2


class OPTBlock(nn.Module):
    cfg: OPTConfig

    @nn.compact
    def __call__(self, x):
        cfg = self.cfg
        d = cfg.head_dim_
        h = nn.LayerNorm(epsilon=cfg.layer_norm_eps, dtype=cfg.dtype,
                         name="attn_ln")(x)
        dense = partial(nn.DenseGeneral, use_bias=True, dtype=cfg.dtype,
                        param_dtype=jnp.float32)
        q = dense(features=(cfg.num_heads, d), name="wq")(h)
        k = dense(features=(cfg.num_heads, d), name="wk")(h)
        v = dense(features=(cfg.num_heads, d), name="wv")(h)
        q = shard_activation(q, (BATCH_AXES, SEQ_AXIS, HEADS_AXIS, None))
        attn = _dispatch_attention(cfg.attention_backend, q, k, v, causal=True)
        x = x + nn.DenseGeneral(features=cfg.hidden_size, axis=(-2, -1),
                                use_bias=True, dtype=cfg.dtype,
                                param_dtype=jnp.float32, name="wo")(attn)
        h2 = nn.LayerNorm(epsilon=cfg.layer_norm_eps, dtype=cfg.dtype,
                          name="mlp_ln")(x)
        m = nn.Dense(cfg.ffn_dim, use_bias=True, dtype=cfg.dtype,
                     param_dtype=jnp.float32, name="fc1")(h2)
        m = nn.relu(m)
        x = x + nn.Dense(cfg.hidden_size, use_bias=True, dtype=cfg.dtype,
                         param_dtype=jnp.float32, name="fc2")(m)
        return shard_activation(x, (BATCH_AXES, SEQ_AXIS, None))


class OPTModel(nn.Module):
    cfg: OPTConfig

    @nn.compact
    def __call__(self, input_ids, positions=None):
        cfg = self.cfg
        if positions is None:
            positions = jnp.arange(input_ids.shape[1])[None, :]
        x = nn.Embed(cfg.vocab_size, cfg.hidden_size, dtype=cfg.dtype,
                     param_dtype=jnp.float32, name="embed")(input_ids)
        pos_table = self.param("pos_embed", nn.initializers.normal(0.02),
                               (cfg.max_seq_len + OPT_POSITION_OFFSET,
                                cfg.hidden_size), jnp.float32)
        x = x + pos_table[positions + OPT_POSITION_OFFSET].astype(cfg.dtype)
        for i in range(cfg.num_layers):
            x = OPTBlock(cfg, name=f"layer_{i}")(x)
        x = nn.LayerNorm(epsilon=cfg.layer_norm_eps, dtype=cfg.dtype,
                         name="final_ln")(x)
        embed = self.variables["params"]["embed"]["embedding"]
        return x.astype(jnp.float32) @ embed.astype(jnp.float32).T


class OPTForCausalLM(nn.Module):
    cfg: OPTConfig

    def setup(self):
        self.model = OPTModel(self.cfg)

    @property
    def config(self):
        return self.cfg

    def __call__(self, batch):
        input_ids = batch["input_ids"]
        logits = self.model(input_ids, positions=batch.get("positions"))
        labels = input_ids[:, 1:]
        logp = jax.nn.log_softmax(logits[:, :-1].astype(jnp.float32), axis=-1)
        ll = jnp.take_along_axis(logp, labels[..., None], axis=-1)[..., 0]
        return -jnp.mean(ll)

    def logits(self, batch):
        return self.model(batch["input_ids"],
                          positions=batch.get("positions"))


def opt_tensor_rules(path, leaf):
    """TP sharding rules for OPT params."""
    from jax.sharding import PartitionSpec
    names = [str(getattr(k, "key", getattr(k, "idx", k))) for k in path]
    if "embed" in names or "pos_embed" in names:
        return PartitionSpec(None, "tensor")
    if any(n in names for n in ("wq", "wk", "wv")) and names[-1] == "kernel":
        return PartitionSpec(None, "tensor", None)
    if "wo" in names and names[-1] == "kernel":
        return PartitionSpec("tensor", None, None)
    if "fc1" in names and names[-1] == "kernel":
        return PartitionSpec(None, "tensor")
    if "fc2" in names and names[-1] == "kernel":
        return PartitionSpec("tensor", None)
    return None


def convert_hf_opt(hf_state, cfg: OPTConfig):
    """HF OPT naming -> our tree (q/k/v/out_proj with biases, fc1/fc2,
    embed_positions includes the +2 offset rows)."""
    def get(name):
        v = hf_state[name]
        return np.asarray(v.detach().cpu().numpy() if hasattr(v, "detach") else v)

    d, h, dh = cfg.hidden_size, cfg.num_heads, cfg.head_dim_
    pfx = "model.decoder."
    tree = {
        "embed": {"embedding": get(pfx + "embed_tokens.weight")},
        "pos_embed": get(pfx + "embed_positions.weight"),
        "final_ln": {"scale": get(pfx + "final_layer_norm.weight"),
                     "bias": get(pfx + "final_layer_norm.bias")},
    }
    for i in range(cfg.num_layers):
        p = f"{pfx}layers.{i}."
        tree[f"layer_{i}"] = {
            "attn_ln": {"scale": get(p + "self_attn_layer_norm.weight"),
                        "bias": get(p + "self_attn_layer_norm.bias")},
            "mlp_ln": {"scale": get(p + "final_layer_norm.weight"),
                       "bias": get(p + "final_layer_norm.bias")},
            "wq": {"kernel": get(p + "self_attn.q_proj.weight").T.reshape(d, h, dh),
                   "bias": get(p + "self_attn.q_proj.bias").reshape(h, dh)},
            "wk": {"kernel": get(p + "self_attn.k_proj.weight").T.reshape(d, h, dh),
                   "bias": get(p + "self_attn.k_proj.bias").reshape(h, dh)},
            "wv": {"kernel": get(p + "self_attn.v_proj.weight").T.reshape(d, h, dh),
                   "bias": get(p + "self_attn.v_proj.bias").reshape(h, dh)},
            "wo": {"kernel": get(p + "self_attn.out_proj.weight").T.reshape(h, dh, d),
                   "bias": get(p + "self_attn.out_proj.bias")},
            "fc1": {"kernel": get(p + "fc1.weight").T, "bias": get(p + "fc1.bias")},
            "fc2": {"kernel": get(p + "fc2.weight").T, "bias": get(p + "fc2.bias")},
        }
    return {"model": tree}
