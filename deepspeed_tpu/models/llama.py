"""Llama-family causal LM — the flagship training model.

Reference analog: the reference has no in-tree Llama *training* model (it wraps HF
modules), but its inference stack ships per-arch implementations
(``deepspeed/inference/v2/model_implementations/llama_v2``,
``module_inject/containers/llama.py``). Here the model is first-class and TPU-native:

- pure flax, bf16-friendly; matmuls land on the MXU
- Megatron-style tensor parallelism expressed as *sharding rules*
  (``llama_tensor_rules``), not module surgery — the AutoTP analog
  (``module_inject/auto_tp.py:189``) for our own model zoo
- activation sharding constraints on the (batch, sequence, heads) axes so XLA lays
  collectives on the right mesh axes
- pluggable attention backend: "xla" (fused by the compiler), "flash" (Pallas),
  "ulysses" (all-to-all SP, reference ``sequence/layer.py:271``), "ring"
  (blockwise CP — the reference gap noted in SURVEY.md §2.2)
- optional ``lax.scan`` over layers (fast compiles at depth) + jax.checkpoint remat
  policies (reference ``runtime/activation_checkpointing``)
"""

import dataclasses
from functools import lru_cache, partial
from typing import Any, Callable, Optional, Tuple

import flax.linen as nn
import jax
import jax.numpy as jnp
import numpy as np
from jax.sharding import PartitionSpec

# logical activation axes -> mesh axes
from deepspeed_tpu.comm.mesh import BATCH_AXES  # ("data", "fsdp_out", "fsdp")
SEQ_AXIS = "sequence"
HEADS_AXIS = "tensor"


@dataclasses.dataclass(frozen=True)
class LlamaConfig:
    vocab_size: int = 32000
    hidden_size: int = 4096
    intermediate_size: int = 14336
    num_layers: int = 32
    num_heads: int = 32
    num_kv_heads: int = 8
    head_dim: Optional[int] = None
    max_seq_len: int = 8192
    rope_theta: float = 500000.0
    rms_norm_eps: float = 1e-5
    tie_embeddings: bool = False
    dtype: Any = jnp.bfloat16
    attention_backend: str = "xla"     # xla | flash | ulysses | ring
    remat: bool = False
    remat_policy: str = "nothing_saveable"
    scan_layers: bool = False
    logits_soft_cap: Optional[float] = None
    # fuse the lm-head matmul with softmax-CE per token-chunk so the fp32
    # [B*S, V] logits tensor never materializes (see
    # sequence/cross_entropy.py:chunked_cross_entropy). None = dense loss.
    loss_chunk_size: Optional[int] = None
    # unroll the chunk loop instead of scan(checkpoint) — the scan structure
    # is suspected of pathological XLA:TPU compile times when nested in the
    # engine's gas scan
    loss_chunk_unroll: bool = False
    # llama-family arch knobs (mistral/qwen2/phi3 are llama variants):
    attention_bias: bool = False          # qwen2: bias on q/k/v projections
    sliding_window: Optional[int] = None  # mistral: attend to last W tokens only
    # gemma-family knobs (gemma/gemma2 are llama variants too):
    hidden_act: str = "silu"              # gemma: "gelu_tanh" gated MLP
    rms_scale_offset: bool = False        # gemma norm: y * (1 + scale)
    scale_embeddings: bool = False        # gemma: embed output * sqrt(hidden)

    @property
    def head_dim_(self) -> int:
        return self.head_dim or self.hidden_size // self.num_heads


# Model presets (public architecture configs)
LLAMA3_8B = LlamaConfig(vocab_size=128256, hidden_size=4096, intermediate_size=14336,
                        num_layers=32, num_heads=32, num_kv_heads=8)
LLAMA3_70B = LlamaConfig(vocab_size=128256, hidden_size=8192, intermediate_size=28672,
                         num_layers=80, num_heads=64, num_kv_heads=8)
LLAMA2_7B = LlamaConfig(vocab_size=32000, hidden_size=4096, intermediate_size=11008,
                        num_layers=32, num_heads=32, num_kv_heads=32, rope_theta=10000.0)
TINY_LLAMA = LlamaConfig(vocab_size=512, hidden_size=128, intermediate_size=256,
                         num_layers=2, num_heads=4, num_kv_heads=2, max_seq_len=256)


def shard_activation(x, spec: Tuple):
    """with_sharding_constraint filtered to the active mesh's axis names
    (hand-built meshes may lack canonical axes, e.g. fsdp_out); degrades to
    no-op outside a mesh context."""
    from deepspeed_tpu.comm import mesh as mesh_lib
    mesh = mesh_lib.get_global_mesh()
    if mesh is not None:
        names = set(mesh.axis_names)
        # inside a partial-manual shard_map (e.g. the qgZ int8-wire gradient
        # phase) the manual axes are already local — a constraint naming them
        # would be rejected; keep constraining the still-automatic axes
        try:
            names -= set(jax.sharding.get_abstract_mesh().manual_axes)
        except AttributeError:  # older jax without AbstractMesh.manual_axes
            pass

        def filt(entry):
            if isinstance(entry, (tuple, list)):
                kept = tuple(a for a in entry if a in names)
                return kept if kept else None
            return entry if entry in names else None
        spec = tuple(filt(e) for e in spec)
        if all(e is None for e in spec):
            # nothing survived filtering (fully non-canonical mesh): an
            # all-None spec would force replication, not act as a no-op
            return x
    try:
        return jax.lax.with_sharding_constraint(x, PartitionSpec(*spec))
    except Exception:
        return x


class RMSNorm(nn.Module):
    """RMS norm in fp32 accumulation (reference kernel: csrc rms_norm.cu — here a
    single XLA fusion)."""
    eps: float = 1e-5
    dtype: Any = jnp.bfloat16
    # gemma convention: weights stored as an offset from 1 (zero-init),
    # applied as y * (1 + scale)
    scale_offset: bool = False

    @nn.compact
    def __call__(self, x):
        orig_dtype = x.dtype
        x32 = x.astype(jnp.float32)
        var = jnp.mean(jnp.square(x32), axis=-1, keepdims=True)
        y = x32 * jax.lax.rsqrt(var + self.eps)
        init = nn.initializers.zeros if self.scale_offset else nn.initializers.ones
        scale = self.param("scale", init, (x.shape[-1],), jnp.float32)
        if self.scale_offset:
            scale = scale + 1.0
        return (y * scale).astype(orig_dtype)


@lru_cache(maxsize=32)
def rope_freqs(head_dim: int, max_len: int, theta: float) -> Tuple[np.ndarray, np.ndarray]:
    # cached: serving policies call this per layer per trace; the cache also
    # keeps the returned ndarrays identical objects so tracers embed one
    # constant instead of num_layers copies
    inv = 1.0 / (theta ** (np.arange(0, head_dim, 2, dtype=np.float64) / head_dim))
    t = np.arange(max_len, dtype=np.float64)
    freqs = np.outer(t, inv)
    return np.cos(freqs).astype(np.float32), np.sin(freqs).astype(np.float32)


def apply_rope(x, cos, sin, positions):
    """x: [B, S, H, D]; positions: [B, S] (reference kernel: apply_rotary_pos_emb.cu)."""
    cos_p = cos[positions][:, :, None, :]   # [B, S, 1, D/2]
    sin_p = sin[positions][:, :, None, :]
    x1, x2 = jnp.split(x.astype(jnp.float32), 2, axis=-1)
    out = jnp.concatenate([x1 * cos_p - x2 * sin_p, x2 * cos_p + x1 * sin_p], axis=-1)
    return out.astype(x.dtype)


def softcap_logits(x, cap):
    """tanh soft-capping (gemma2): identity when cap is falsy. The single
    definition shared by training attention, serving paths, and heads."""
    return cap * jnp.tanh(x / cap) if cap else x


def _xla_attention(q, k, v, causal: bool = True, segment_ids=None, window=None,
                   scale=None, softcap=None):
    """Plain attention; XLA fuses softmax chain. q,k,v: [B, S, H, D] / kv
    [B, S, Hkv, D]. ``window`` adds mistral-style sliding-window masking
    (token t attends to (t-window, t]); ``scale`` overrides 1/sqrt(d)
    (gemma2 query_pre_attn_scalar); ``softcap`` tanh-caps the raw logits
    before masking (gemma2 attn_logit_softcapping)."""
    b, sq, h, d = q.shape
    hkv = k.shape[2]
    if hkv != h:
        rep = h // hkv
        k = jnp.repeat(k, rep, axis=2)
        v = jnp.repeat(v, rep, axis=2)
    scores = jnp.einsum("bqhd,bkhd->bhqk", q, k).astype(jnp.float32) * \
        (scale if scale is not None else 1.0 / np.sqrt(d))
    scores = softcap_logits(scores, softcap)
    sk = k.shape[1]
    if causal or window is not None:
        qpos = jnp.arange(sq)[:, None] + (sk - sq)
        kpos = jnp.arange(sk)[None, :]
        mask = qpos >= kpos
        if window is not None:
            mask &= kpos > qpos - window
        scores = jnp.where(mask[None, None], scores, -1e30)
    if segment_ids is not None:
        seg_mask = segment_ids[:, :, None] == segment_ids[:, None, :]
        scores = jnp.where(seg_mask[:, None], scores, -1e30)
    probs = jax.nn.softmax(scores, axis=-1).astype(q.dtype)
    return jnp.einsum("bhqk,bkhd->bqhd", probs, v)


def _dispatch_attention(backend: str, q, k, v, causal=True, segment_ids=None,
                        mesh=None, window=None):
    if window is not None and backend != "flash":
        # sliding window: explicit mask on the XLA path (the SP backends
        # don't support it; the flash kernel does, with block skipping)
        return _xla_attention(q, k, v, causal, segment_ids, window=window)
    if backend == "xla":
        return _xla_attention(q, k, v, causal, segment_ids)
    if backend == "flash":
        from deepspeed_tpu.ops.pallas.flash_attention import flash_attention_auto
        return flash_attention_auto(q, k, v, causal=causal, window=window,
                                    segment_ids=segment_ids)
    if backend == "ulysses":
        from deepspeed_tpu.sequence.ulysses import ulysses_attention
        return ulysses_attention(q, k, v, causal=causal,
                                 segment_ids=segment_ids)
    if backend == "ring":
        if segment_ids is not None and jax.default_backend() != "tpu":
            # the jnp ring body has no segment carry; only the flash ring
            # (TPU) masks packed sequences — never silently drop the mask
            raise NotImplementedError(
                "packed-sequence segment_ids with the ring backend need "
                "the flash ring (TPU); on CPU use 'ulysses'/'flash'/'xla'")
        from deepspeed_tpu.sequence.ring import ring_attention
        return ring_attention(q, k, v, causal=causal,
                              segment_ids=segment_ids)
    raise ValueError(f"unknown attention backend '{backend}'")


class LlamaAttention(nn.Module):
    cfg: LlamaConfig

    @nn.compact
    def __call__(self, x, positions, segment_ids=None):
        cfg = self.cfg
        d = cfg.head_dim_
        dense = partial(nn.DenseGeneral, use_bias=cfg.attention_bias,
                        dtype=cfg.dtype, param_dtype=jnp.float32)
        q = dense(features=(cfg.num_heads, d), name="wq")(x)
        k = dense(features=(cfg.num_kv_heads, d), name="wk")(x)
        v = dense(features=(cfg.num_kv_heads, d), name="wv")(x)
        q = shard_activation(q, (BATCH_AXES, SEQ_AXIS, HEADS_AXIS, None))
        k = shard_activation(k, (BATCH_AXES, SEQ_AXIS, HEADS_AXIS, None))
        v = shard_activation(v, (BATCH_AXES, SEQ_AXIS, HEADS_AXIS, None))

        cos, sin = rope_freqs(d, cfg.max_seq_len, cfg.rope_theta)
        cos, sin = jnp.asarray(cos), jnp.asarray(sin)
        q = apply_rope(q, cos, sin, positions)
        k = apply_rope(k, cos, sin, positions)

        out = _dispatch_attention(cfg.attention_backend, q, k, v, causal=True,
                                  segment_ids=segment_ids,
                                  window=cfg.sliding_window)
        out = shard_activation(out, (BATCH_AXES, SEQ_AXIS, HEADS_AXIS, None))
        return nn.DenseGeneral(features=cfg.hidden_size, axis=(-2, -1), use_bias=False,
                               dtype=cfg.dtype, param_dtype=jnp.float32, name="wo")(out)


class LlamaMLP(nn.Module):
    cfg: LlamaConfig

    @nn.compact
    def __call__(self, x):
        cfg = self.cfg
        dense = partial(nn.Dense, use_bias=False, dtype=cfg.dtype,
                        param_dtype=jnp.float32)
        gate = dense(cfg.intermediate_size, name="w_gate")(x)
        up = dense(cfg.intermediate_size, name="w_up")(x)
        if cfg.hidden_act == "silu":
            act = nn.silu
        elif cfg.hidden_act == "gelu_tanh":            # gemma
            act = lambda v: nn.gelu(v, approximate=True)
        else:
            raise ValueError(f"unsupported hidden_act {cfg.hidden_act!r} "
                             "(silu | gelu_tanh)")
        h = act(gate) * up
        h = shard_activation(h, (BATCH_AXES, SEQ_AXIS, HEADS_AXIS))
        return dense(cfg.hidden_size, name="w_down")(h)


class LlamaBlock(nn.Module):
    cfg: LlamaConfig

    @nn.compact
    def __call__(self, x, positions, segment_ids=None):
        cfg = self.cfg
        h = x + LlamaAttention(cfg, name="attn")(
            RMSNorm(cfg.rms_norm_eps, cfg.dtype,
                    scale_offset=cfg.rms_scale_offset, name="attn_norm")(x),
            positions, segment_ids)
        out = h + LlamaMLP(cfg, name="mlp")(
            RMSNorm(cfg.rms_norm_eps, cfg.dtype,
                    scale_offset=cfg.rms_scale_offset, name="mlp_norm")(h))
        return shard_activation(out, (BATCH_AXES, SEQ_AXIS, None))


REMAT_POLICIES = {
    "nothing_saveable": jax.checkpoint_policies.nothing_saveable,
    "everything_saveable": jax.checkpoint_policies.everything_saveable,
    "dots_saveable": jax.checkpoint_policies.dots_saveable,
    "dots_with_no_batch_dims_saveable":
        jax.checkpoint_policies.dots_with_no_batch_dims_saveable,
    # saved matmul outputs stream to host RAM instead of staying in HBM
    # (~3.4GB of qkv+gate/up saves per 697M mb=4 step — the r01 OOM dump's
    # dominant allocations); XLA schedules the DMAs around the compute
    "offload_dots_to_host":
        jax.checkpoint_policies.offload_dot_with_no_batch_dims(
            offload_src="device", offload_dst="pinned_host"),
}


class LMHead(nn.Module):
    """Unembedding projection with the kernel exposed as an attribute so the
    chunked-loss path can scan over it (same param path/init as the nn.Dense it
    replaces: ``lm_head/kernel``, fp32 master, lecun-normal)."""
    hidden_size: int
    vocab_size: int
    dtype: Any = jnp.bfloat16

    def setup(self):
        self.kernel = self.param("kernel", nn.initializers.lecun_normal(),
                                 (self.hidden_size, self.vocab_size), jnp.float32)

    def __call__(self, x):
        return jnp.dot(x.astype(self.dtype), self.kernel.astype(self.dtype))


class LlamaModel(nn.Module):
    """Backbone: embed -> N blocks -> final norm. Call with token ids [B, S].
    ``return_hidden=True`` skips the unembed matmul and returns
    ``(hidden [B,S,H], head weights)`` for the chunked-CE loss path (head
    weights are ``embedding [V,H]`` when tied, else ``kernel [H,V]``)."""
    cfg: LlamaConfig

    @nn.compact
    def __call__(self, input_ids, positions=None, segment_ids=None,
                 return_hidden=False):
        cfg = self.cfg
        if positions is None:
            positions = jnp.broadcast_to(jnp.arange(input_ids.shape[1]),
                                         input_ids.shape)
        embed = nn.Embed(cfg.vocab_size, cfg.hidden_size, dtype=cfg.dtype,
                         param_dtype=jnp.float32, name="embed")
        x = embed(input_ids)
        if cfg.scale_embeddings:          # gemma: normalizer on the embed output
            x = x * jnp.sqrt(jnp.asarray(cfg.hidden_size, jnp.float32)).astype(x.dtype)
        x = shard_activation(x, (BATCH_AXES, SEQ_AXIS, None))

        block_cls = LlamaBlock
        if cfg.remat:
            block_cls = nn.remat(
                LlamaBlock, policy=REMAT_POLICIES[cfg.remat_policy],
                prevent_cse=not cfg.scan_layers, static_argnums=())

        if cfg.scan_layers:
            x, _ = nn.scan(
                lambda mdl, carry, _: (mdl(carry, positions, segment_ids), None),
                variable_axes={"params": 0},
                split_rngs={"params": True, "dropout": True},
                length=cfg.num_layers,
                metadata_params={nn.PARTITION_NAME: "layers"},
            )(block_cls(cfg, name="layers"), x, None)
        else:
            for i in range(cfg.num_layers):
                x = block_cls(cfg, name=f"layer_{i}")(x, positions, segment_ids)

        x = RMSNorm(cfg.rms_norm_eps, cfg.dtype,
                    scale_offset=cfg.rms_scale_offset, name="final_norm")(x)
        # head matmul in compute dtype (bf16 on the MXU, fp32 accumulation);
        # downstream softmax casts to fp32 — an fp32 head matmul is ~8x slower
        if cfg.tie_embeddings:
            if return_hidden:
                return x, embed.embedding
            logits = embed.attend(x)
        else:
            head = LMHead(cfg.hidden_size, cfg.vocab_size, cfg.dtype,
                          name="lm_head")
            if return_hidden:
                return x, head.kernel
            logits = head(x)
        logits = logits.astype(jnp.float32)
        if cfg.logits_soft_cap:
            logits = cfg.logits_soft_cap * jnp.tanh(logits / cfg.logits_soft_cap)
        return logits


class LlamaForCausalLM(nn.Module):
    """Training entry: batch dict {"input_ids": [B,S]} (+ optional "labels",
    "segment_ids", "positions", "loss_mask") -> mean next-token cross-entropy."""
    cfg: LlamaConfig

    def setup(self):
        self.model = LlamaModel(self.cfg)

    def __call__(self, batch):
        input_ids = batch["input_ids"]
        if self.cfg.loss_chunk_size:
            return self._chunked_loss(batch)
        logits = self.model(input_ids,
                            positions=batch.get("positions"),
                            segment_ids=batch.get("segment_ids"))
        labels = batch.get("labels")
        if labels is None:
            labels = input_ids[:, 1:]
            logits = logits[:, :-1]
            mask = batch.get("loss_mask")
            mask = mask[:, 1:] if mask is not None else jnp.ones_like(labels)
        else:
            mask = batch.get("loss_mask", jnp.ones_like(labels))
        logp = jax.nn.log_softmax(logits.astype(jnp.float32), axis=-1)
        ll = jnp.take_along_axis(logp, labels[..., None], axis=-1)[..., 0]
        mask = mask.astype(jnp.float32)
        return -jnp.sum(ll * mask) / jnp.maximum(jnp.sum(mask), 1.0)

    def _chunked_loss(self, batch):
        """Same loss as the dense path, via chunked head-matmul + CE fusion.
        Labels/mask are aligned to all S positions (last position masked out in
        the next-token case) so chunk shapes stay static."""
        from deepspeed_tpu.sequence.cross_entropy import chunked_cross_entropy

        input_ids = batch["input_ids"]
        labels = batch.get("labels")
        if labels is None:
            labels = jnp.pad(input_ids[:, 1:], ((0, 0), (0, 1)))
            mask = batch.get("loss_mask")
            mask = mask[:, 1:] if mask is not None else \
                jnp.ones_like(input_ids[:, 1:])
            mask = jnp.pad(mask, ((0, 0), (0, 1)))
        else:
            mask = batch.get("loss_mask", jnp.ones_like(labels))
        hidden, head = self.model(input_ids,
                                  positions=batch.get("positions"),
                                  segment_ids=batch.get("segment_ids"),
                                  return_hidden=True)
        kw = {"embedding": head} if self.cfg.tie_embeddings else {"kernel": head}
        return chunked_cross_entropy(
            hidden, labels, mask, chunk_size=self.cfg.loss_chunk_size,
            soft_cap=self.cfg.logits_soft_cap, compute_dtype=self.cfg.dtype,
            unroll=self.cfg.loss_chunk_unroll, **kw)

    def logits(self, batch):
        return self.model(batch["input_ids"], positions=batch.get("positions"),
                          segment_ids=batch.get("segment_ids"))


def llama_tensor_rules(path, leaf) -> Optional[PartitionSpec]:
    """Megatron-style TP sharding rules keyed on parameter paths — the AutoTP
    analog (reference module_inject/auto_tp.py:189: column-shard qkv/up, row-shard
    o/down, vocab-shard embeddings).

    Returned specs leave dims free for the fsdp axis to occupy (stage 3 layers on
    a different dim via build_param_shardings).
    """
    name = "/".join(str(getattr(k, "key", getattr(k, "idx", k))) for k in path)
    ndim = np.ndim(leaf)
    if "wq/kernel" in name or "wk/kernel" in name or "wv/kernel" in name:
        # [embed, heads, head_dim] -> shard heads
        return PartitionSpec(*([None] * (ndim - 2)), "tensor", None)
    if "wo/kernel" in name:
        # [heads, head_dim, embed] -> shard heads (input-parallel => psum output)
        return PartitionSpec("tensor", *([None] * (ndim - 1)))
    if "w_gate/kernel" in name or "w_up/kernel" in name:
        return PartitionSpec(*([None] * (ndim - 1)), "tensor")
    if "w_down/kernel" in name:
        return PartitionSpec(*([None] * (ndim - 2)), "tensor", None)
    if "embed/embedding" in name:
        return PartitionSpec("tensor", *([None] * (ndim - 1)))
    if "lm_head/kernel" in name:
        return PartitionSpec(*([None] * (ndim - 1)), "tensor")
    return None


def make_llama(cfg: LlamaConfig = TINY_LLAMA):
    return LlamaForCausalLM(cfg)


def random_tokens(batch_size: int, seq_len: int, vocab_size: int = 512,
                  seed: int = 0, gas: Optional[int] = None):
    rng = np.random.default_rng(seed)
    shape = (gas, batch_size, seq_len) if gas else (batch_size, seq_len)
    return {"input_ids": rng.integers(0, vocab_size, size=shape).astype(np.int32)}
