"""BLOOM model family (ALiBi attention, fused QKV, LayerNorms, tied head).

Reference analog: the BLOOM container (``module_inject/containers/bloom.py``)
and v1 inference policy (ALiBi handled inside the softmax kernel,
``csrc/transformer/inference/csrc/softmax.cu`` alibi variant). Architecture:
word embeddings + embedding LayerNorm, pre-LN blocks with fused
query_key_value (per-head [q|k|v] interleave), ALiBi position bias (no
rope/learned positions), GELU MLP, final LayerNorm, tied lm_head.

TPU redesign of ALiBi: instead of a bias-aware softmax kernel, the bias
``slope_h * (j - i)`` is folded into the dot product by augmenting the head
dim with two columns (hi/lo position split so the bias stays exact in a bf16
KV cache — see ``alibi_augment``), with a ``sqrt(d+2)/sqrt(d)`` factor
compensating the kernel's ``1/sqrt(head_dim)`` scale. Per-row constants
(``-slope*i``) vanish under softmax, so scores are exactly ALiBi — and every
attention backend (XLA, Pallas flash, ring, Ulysses, paged serving) supports
BLOOM with zero kernel changes.
"""

import dataclasses
import math
from functools import partial
from typing import Any

import flax.linen as nn
import jax
import jax.numpy as jnp
import numpy as np

from deepspeed_tpu.models.llama import (
    BATCH_AXES, HEADS_AXIS, SEQ_AXIS, _dispatch_attention, shard_activation)


@dataclasses.dataclass(frozen=True)
class BloomConfig:
    vocab_size: int = 250880
    hidden_size: int = 4096
    num_layers: int = 30
    num_heads: int = 32
    max_seq_len: int = 2048
    layer_norm_eps: float = 1e-5
    dtype: Any = jnp.bfloat16
    attention_backend: str = "xla"

    @property
    def head_dim_(self) -> int:
        return self.hidden_size // self.num_heads


TINY_BLOOM = BloomConfig(vocab_size=512, hidden_size=128, num_layers=2,
                         num_heads=4, max_seq_len=128, dtype=jnp.float32)


def alibi_slopes(num_heads: int) -> np.ndarray:
    """Per-head ALiBi slopes (geometric in 2^(-8/n), interpolated for
    non-power-of-two head counts — the published ALiBi recipe)."""
    def pow2_slopes(n):
        start = 2.0 ** (-8.0 / n)
        return [start ** (i + 1) for i in range(n)]

    if math.log2(num_heads).is_integer():
        return np.asarray(pow2_slopes(num_heads), np.float32)
    closest = 2 ** math.floor(math.log2(num_heads))
    extra = pow2_slopes(2 * closest)[0::2][:num_heads - closest]
    return np.asarray(pow2_slopes(closest) + extra, np.float32)


# pos = ALIBI_POS_SPLIT*hi + lo; hi and lo are small integers that stay exact
# in bf16 (mantissa 8 bits), so the *position* columns carry no rounding to
# 32k context even with a bf16 KV cache — a single absolute-position column
# would round above position 256 in bf16. The query-side slope columns are
# still cast to the compute dtype, so in bf16 the bias keeps the ~0.4%
# relative rounding of the slope itself (position-independent, benign).
ALIBI_POS_SPLIT = 128


def alibi_augment(q, k, v, slopes, positions):
    """Fold ALiBi into (q, k, v) by two extra head-dim columns (module
    docstring). q/k/v: [..., H, d] (batched [B,S,H,d] or token-major [T,H,d]);
    ``positions``: matching leading shape, absolute key positions. The bias
    ``slope*pos`` is decomposed as ``(slope*SPLIT)*hi + slope*lo`` with
    ``hi = pos // SPLIT, lo = pos % SPLIT``. Returns the augmented
    [..., H, d+2] triple; slice the output ``[..., :d]`` after attention."""
    d = q.shape[-1]
    h = q.shape[-2]
    s = jnp.sqrt(jnp.asarray(d + 2, jnp.float32) / d).astype(q.dtype)
    kscale = np.sqrt(d + 2)
    lead = (1,) * (q.ndim - 2)
    sl32 = slopes.astype(jnp.float32)
    q_cols = jnp.broadcast_to(
        jnp.stack([sl32 * ALIBI_POS_SPLIT * kscale, sl32 * kscale],
                  axis=-1).astype(q.dtype).reshape(lead + (h, 2)),
        q.shape[:-1] + (2,))
    pos = positions.astype(jnp.int32)
    k_cols = jnp.broadcast_to(
        jnp.stack([(pos // ALIBI_POS_SPLIT).astype(q.dtype),
                   (pos % ALIBI_POS_SPLIT).astype(q.dtype)],
                  axis=-1)[..., None, :], k.shape[:-1] + (2,))
    q_a = jnp.concatenate([q * s, q_cols], axis=-1)
    k_a = jnp.concatenate([k, k_cols], axis=-1)
    v_a = jnp.concatenate([v, jnp.zeros_like(v[..., :2])], axis=-1)
    return q_a, k_a, v_a


class BloomBlock(nn.Module):
    cfg: BloomConfig

    @nn.compact
    def __call__(self, x, positions):
        cfg = self.cfg
        d = cfg.head_dim_
        h = nn.LayerNorm(epsilon=cfg.layer_norm_eps, dtype=cfg.dtype,
                         name="input_ln")(x)
        dense = partial(nn.DenseGeneral, use_bias=True, dtype=cfg.dtype,
                        param_dtype=jnp.float32)
        q = dense(features=(cfg.num_heads, d), name="wq")(h)
        k = dense(features=(cfg.num_heads, d), name="wk")(h)
        v = dense(features=(cfg.num_heads, d), name="wv")(h)
        q = shard_activation(q, (BATCH_AXES, SEQ_AXIS, HEADS_AXIS, None))
        slopes = jnp.asarray(alibi_slopes(cfg.num_heads))
        q, k, v = alibi_augment(q, k, v, slopes, positions)
        attn = _dispatch_attention(cfg.attention_backend, q, k, v,
                                   causal=True)[..., :d]
        x = x + nn.DenseGeneral(features=cfg.hidden_size, axis=(-2, -1),
                                use_bias=True, dtype=cfg.dtype,
                                param_dtype=jnp.float32, name="wo")(attn)
        h2 = nn.LayerNorm(epsilon=cfg.layer_norm_eps, dtype=cfg.dtype,
                          name="post_ln")(x)
        m = nn.Dense(4 * cfg.hidden_size, use_bias=True, dtype=cfg.dtype,
                     param_dtype=jnp.float32, name="mlp_up")(h2)
        m = jax.nn.gelu(m)
        x = x + nn.Dense(cfg.hidden_size, use_bias=True, dtype=cfg.dtype,
                         param_dtype=jnp.float32, name="mlp_down")(m)
        return shard_activation(x, (BATCH_AXES, SEQ_AXIS, None))


class BloomModel(nn.Module):
    cfg: BloomConfig

    @nn.compact
    def __call__(self, input_ids, positions=None):
        cfg = self.cfg
        if positions is None:
            positions = jnp.broadcast_to(jnp.arange(input_ids.shape[1]),
                                         input_ids.shape)
        x = nn.Embed(cfg.vocab_size, cfg.hidden_size, dtype=cfg.dtype,
                     param_dtype=jnp.float32, name="embed")(input_ids)
        x = nn.LayerNorm(epsilon=cfg.layer_norm_eps, dtype=cfg.dtype,
                         name="embed_ln")(x)
        x = shard_activation(x, (BATCH_AXES, SEQ_AXIS, None))
        for i in range(cfg.num_layers):
            x = BloomBlock(cfg, name=f"layer_{i}")(x, positions)
        x = nn.LayerNorm(epsilon=cfg.layer_norm_eps, dtype=cfg.dtype,
                         name="final_ln")(x)
        embed = self.variables["params"]["embed"]["embedding"]
        return x.astype(jnp.float32) @ embed.astype(jnp.float32).T  # tied


class BloomForCausalLM(nn.Module):
    cfg: BloomConfig

    def setup(self):
        self.model = BloomModel(self.cfg)

    @property
    def config(self):
        return self.cfg

    def __call__(self, batch):
        input_ids = batch["input_ids"]
        logits = self.model(input_ids, positions=batch.get("positions"))
        labels = input_ids[:, 1:]
        logp = jax.nn.log_softmax(logits[:, :-1].astype(jnp.float32), axis=-1)
        ll = jnp.take_along_axis(logp, labels[..., None], axis=-1)[..., 0]
        return -jnp.mean(ll)

    def logits(self, batch):
        return self.model(batch["input_ids"],
                          positions=batch.get("positions"))


def bloom_tensor_rules(path, leaf):
    """TP sharding rules (reference container: qkv column-, dense row-parallel;
    ALiBi slopes are per-head so head sharding composes)."""
    from jax.sharding import PartitionSpec
    names = [str(getattr(k, "key", getattr(k, "idx", k))) for k in path]
    if "embed" in names:
        return PartitionSpec(None, "tensor")
    if any(n in names for n in ("wq", "wk", "wv")) and names[-1] == "kernel":
        return PartitionSpec(None, "tensor", None)
    if "wo" in names and names[-1] == "kernel":
        return PartitionSpec("tensor", None, None)
    if "mlp_up" in names and names[-1] == "kernel":
        return PartitionSpec(None, "tensor")
    if "mlp_down" in names and names[-1] == "kernel":
        return PartitionSpec("tensor", None)
    return None


def convert_hf_bloom(hf_state, cfg: BloomConfig):
    """HF BLOOM naming -> our tree. HF fuses query_key_value rows as
    ``[h, 3, d]`` per-head interleave (the layout the reference's
    fusedqkv_utils splits, ``module_inject/fusedqkv_utils.py``)."""
    def get(name):
        v = hf_state[name]
        return np.asarray(v.detach().cpu().numpy() if hasattr(v, "detach") else v)

    dmodel, h, d = cfg.hidden_size, cfg.num_heads, cfg.head_dim_
    pfx = "transformer."
    tree = {
        "embed": {"embedding": get(pfx + "word_embeddings.weight")},
        "embed_ln": {"scale": get(pfx + "word_embeddings_layernorm.weight"),
                     "bias": get(pfx + "word_embeddings_layernorm.bias")},
        "final_ln": {"scale": get(pfx + "ln_f.weight"),
                     "bias": get(pfx + "ln_f.bias")},
    }
    for i in range(cfg.num_layers):
        p = f"{pfx}h.{i}."
        qkv_w = get(p + "self_attention.query_key_value.weight")  # [3hd, D]
        qkv_b = get(p + "self_attention.query_key_value.bias")    # [3hd]
        w = qkv_w.reshape(h, 3, d, dmodel)
        b = qkv_b.reshape(h, 3, d)
        tree[f"layer_{i}"] = {
            "input_ln": {"scale": get(p + "input_layernorm.weight"),
                         "bias": get(p + "input_layernorm.bias")},
            "post_ln": {"scale": get(p + "post_attention_layernorm.weight"),
                        "bias": get(p + "post_attention_layernorm.bias")},
            "wq": {"kernel": w[:, 0].transpose(2, 0, 1), "bias": b[:, 0]},
            "wk": {"kernel": w[:, 1].transpose(2, 0, 1), "bias": b[:, 1]},
            "wv": {"kernel": w[:, 2].transpose(2, 0, 1), "bias": b[:, 2]},
            "wo": {"kernel": get(p + "self_attention.dense.weight")
                   .T.reshape(h, d, dmodel),
                   "bias": get(p + "self_attention.dense.bias")},
            "mlp_up": {"kernel": get(p + "mlp.dense_h_to_4h.weight").T,
                       "bias": get(p + "mlp.dense_h_to_4h.bias")},
            "mlp_down": {"kernel": get(p + "mlp.dense_4h_to_h.weight").T,
                         "bias": get(p + "mlp.dense_4h_to_h.bias")},
        }
    return {"model": tree}
