"""Unified HF-checkpoint ingestion — the engine_factory analog.

Reference analog: ``deepspeed/inference/v2/engine_factory.py`` (reads the HF
config, picks the arch policy, maps the checkpoint into engine containers).
Here: ``from_hf_checkpoint(hf_config, state_dict)`` dispatches on
``model_type`` to the per-family config mapper + weight converter and returns
``(model, cfg, params)`` ready for training (``deepspeed_tpu.initialize``),
serving (``InferenceEngineV2``), or ZeRO-Inference.
"""

from typing import Any, Dict, Tuple

LLAMA_FAMILY = ("llama", "mistral", "qwen2", "phi3", "gemma")


def _falcon_config(hf: Dict[str, Any]):
    from deepspeed_tpu.models.falcon import FalconConfig
    if hf.get("alibi") or hf.get("parallel_attn", True) is False:
        # falcon-rw variants: ALiBi positions / sequential attn+mlp — a
        # different block than the rotary parallel-attn FalconForCausalLM
        raise ValueError("unsupported falcon variant (alibi or "
                         "non-parallel attention, e.g. falcon-rw); only the "
                         "rotary parallel-attn layout is supported")
    heads = hf["num_attention_heads"]
    if hf.get("new_decoder_architecture"):
        kv = hf.get("num_kv_heads", hf.get("n_head_kv"))
        if kv is None:
            raise ValueError("new_decoder_architecture falcon config is "
                             "missing num_kv_heads / n_head_kv")
    elif hf.get("multi_query", True):
        kv = 1
    else:
        kv = heads
    return FalconConfig(
        vocab_size=hf["vocab_size"], hidden_size=hf["hidden_size"],
        num_layers=hf["num_hidden_layers"], num_heads=heads, num_kv_heads=kv,
        max_seq_len=hf.get("max_position_embeddings", 2048),
        rope_theta=hf.get("rope_theta", 10000.0),
        layer_norm_eps=hf.get("layer_norm_epsilon", 1e-5),
        new_decoder_architecture=bool(hf.get("new_decoder_architecture")))


def _opt_config(hf: Dict[str, Any]):
    from deepspeed_tpu.models.opt import OPTConfig
    if hf.get("word_embed_proj_dim", hf["hidden_size"]) != hf["hidden_size"]:
        raise ValueError("unsupported OPT variant: word_embed_proj_dim != "
                         "hidden_size (opt-350m style project_in/out)")
    if hf.get("do_layer_norm_before", True) is False:
        raise ValueError("unsupported OPT variant: post-LN "
                         "(do_layer_norm_before=false)")
    return OPTConfig(
        vocab_size=hf["vocab_size"], hidden_size=hf["hidden_size"],
        ffn_dim=hf["ffn_dim"], num_layers=hf["num_hidden_layers"],
        num_heads=hf["num_attention_heads"],
        max_seq_len=hf.get("max_position_embeddings", 2048))


def _bloom_config(hf: Dict[str, Any]):
    from deepspeed_tpu.models.bloom import BloomConfig
    return BloomConfig(
        vocab_size=hf["vocab_size"],
        hidden_size=hf.get("hidden_size", hf.get("n_embed")),
        num_layers=hf.get("num_hidden_layers", hf.get("n_layer")),
        num_heads=hf.get("num_attention_heads", hf.get("n_head")),
        layer_norm_eps=hf.get("layer_norm_epsilon", 1e-5))


def _gpt2_config(hf: Dict[str, Any]):
    from deepspeed_tpu.models.gpt2 import GPT2Config
    return GPT2Config(
        vocab_size=hf["vocab_size"], hidden_size=hf["n_embd"],
        num_layers=hf["n_layer"], num_heads=hf["n_head"],
        max_seq_len=hf.get("n_positions", 1024),
        layer_norm_eps=hf.get("layer_norm_epsilon", 1e-5))


def _gpt_neox_config(hf: Dict[str, Any]):
    from deepspeed_tpu.models.gpt_neox import GPTNeoXConfig
    return GPTNeoXConfig(
        vocab_size=hf["vocab_size"], hidden_size=hf["hidden_size"],
        intermediate_size=hf["intermediate_size"],
        num_layers=hf["num_hidden_layers"],
        num_heads=hf["num_attention_heads"],
        max_seq_len=hf.get("max_position_embeddings", 2048),
        rotary_pct=hf.get("rotary_pct", 0.25),
        rope_theta=hf.get("rotary_emb_base", 10000.0),
        layer_norm_eps=hf.get("layer_norm_eps", 1e-5),
        parallel_residual=hf.get("use_parallel_residual", True))


def _t5_config(hf: Dict[str, Any]):
    from deepspeed_tpu.models.t5 import T5Config
    ff = hf.get("feed_forward_proj", "relu")
    return T5Config(
        vocab_size=hf["vocab_size"], d_model=hf["d_model"],
        d_kv=hf.get("d_kv", 64), d_ff=hf["d_ff"],
        num_layers=hf["num_layers"],
        num_decoder_layers=hf.get("num_decoder_layers"),
        num_heads=hf["num_heads"],
        relative_attention_num_buckets=hf.get(
            "relative_attention_num_buckets", 32),
        relative_attention_max_distance=hf.get(
            "relative_attention_max_distance", 128),
        layer_norm_eps=hf.get("layer_norm_epsilon", 1e-6),
        gated_act=ff.startswith("gated"),
        tie_word_embeddings=hf.get("tie_word_embeddings", True))


def _llama_family_entry(mt):
    def build():
        from deepspeed_tpu.models.families import (config_from_hf,
                                                   convert_hf_state_dict)
        from deepspeed_tpu.models.llama import LlamaForCausalLM
        return (config_from_hf, LlamaForCausalLM,
                lambda st, cfg: convert_hf_state_dict(st, cfg,
                                                      model_type=mt))
    return build


def _family_entry(mod_name, config_attr, model_attr, convert_attr):
    def build():
        import importlib
        mod = importlib.import_module(f"deepspeed_tpu.models.{mod_name}")
        config_fn = getattr(mod, config_attr) if isinstance(config_attr, str) \
            else config_attr
        return (config_fn, getattr(mod, model_attr),
                getattr(mod, convert_attr))
    return build


# model_type -> thunk building (config_fn, model_ctor, convert_fn); only the
# requested family's module is imported
_REGISTRY = {
    "mixtral": _family_entry("mixtral", "mixtral_config_from_hf",
                             "MixtralForCausalLM", "convert_hf_mixtral"),
    "qwen2_moe": _family_entry("qwen2_moe", "qwen2_moe_config_from_hf",
                               "Qwen2MoEForCausalLM", "convert_hf_qwen2_moe"),
    "falcon": _family_entry("falcon", _falcon_config, "FalconForCausalLM",
                            "convert_hf_falcon"),
    "opt": _family_entry("opt", _opt_config, "OPTForCausalLM",
                         "convert_hf_opt"),
    "bloom": _family_entry("bloom", _bloom_config, "BloomForCausalLM",
                           "convert_hf_bloom"),
    "gpt2": _family_entry("gpt2", _gpt2_config, "GPT2ForCausalLM",
                          "convert_hf_gpt2"),
    "gpt_neox": _family_entry("gpt_neox", _gpt_neox_config,
                              "GPTNeoXForCausalLM", "convert_hf_gpt_neox"),
    "t5": _family_entry("t5", _t5_config, "T5ForConditionalGeneration",
                        "convert_hf_t5"),
    "gemma2": _family_entry("gemma2", "gemma2_config_from_hf",
                            "Gemma2ForCausalLM", "convert_hf_gemma2"),
    **{mt: _llama_family_entry(mt) for mt in LLAMA_FAMILY},
}


def supported_model_types() -> Tuple[str, ...]:
    return tuple(sorted(_REGISTRY))


def from_hf_checkpoint(hf_config: Dict[str, Any], state_dict=None):
    """(hf config dict, optional state dict) -> (model, cfg, params).
    ``params`` is None when no state dict is given (config-only use).
    Raises on unknown ``model_type`` with the supported list."""
    mt = hf_config.get("model_type")
    if mt not in _REGISTRY:
        raise ValueError(
            f"unsupported model_type {mt!r}; supported: "
            f"{', '.join(sorted(_REGISTRY))}")
    config_fn, model_ctor, convert_fn = _REGISTRY[mt]()
    cfg = config_fn(hf_config)
    model = model_ctor(cfg)
    params = convert_fn(state_dict, cfg) if state_dict is not None else None
    return model, cfg, params
