"""GPT-NeoX / GPT-J model family (partial rotary, parallel residual).

Reference analog: the gptneox/gptj containers
(``module_inject/containers/{gptneox,gptj}.py``) and their v1 inference
policies. Architecture knobs covering both archs:

- ``rotary_pct``: rotary applied to the first ``pct`` of each head dim
  (NeoX default 0.25; GPT-J uses a fixed ``rotary_dim``, expressed as a pct)
- ``parallel_residual``: ``x + attn(ln1(x)) + mlp(ln2(x))`` (NeoX
  ``use_parallel_residual`` / GPT-J's single-LN parallel block)
- untied ``embed_out`` lm head (NeoX) — unlike gpt2/bloom
"""

import dataclasses
from functools import partial
from typing import Any

import flax.linen as nn
import jax
import jax.numpy as jnp
import numpy as np

from deepspeed_tpu.models.llama import (
    BATCH_AXES, HEADS_AXIS, SEQ_AXIS, _dispatch_attention, rope_freqs,
    shard_activation)


@dataclasses.dataclass(frozen=True)
class GPTNeoXConfig:
    vocab_size: int = 50432
    hidden_size: int = 4096
    intermediate_size: int = 16384
    num_layers: int = 32
    num_heads: int = 32
    max_seq_len: int = 2048
    rotary_pct: float = 0.25
    rope_theta: float = 10000.0
    layer_norm_eps: float = 1e-5
    parallel_residual: bool = True
    dtype: Any = jnp.bfloat16
    attention_backend: str = "xla"

    @property
    def head_dim_(self) -> int:
        return self.hidden_size // self.num_heads

    @property
    def rotary_dim_(self) -> int:
        # even size, like NeoX's int(head_dim * rotary_pct)
        return (int(self.head_dim_ * self.rotary_pct) // 2) * 2


TINY_NEOX = GPTNeoXConfig(vocab_size=512, hidden_size=128,
                          intermediate_size=256, num_layers=2, num_heads=4,
                          max_seq_len=128, dtype=jnp.float32)

# GPT-J-style preset: fixed rotary_dim=64 on head_dim 256 -> pct 0.25,
# parallel residual with one shared LN is approximated by parallel_residual
GPTJ_6B = GPTNeoXConfig(vocab_size=50400, hidden_size=4096,
                        intermediate_size=16384, num_layers=28, num_heads=16,
                        rotary_pct=64 / 256, parallel_residual=True)


def apply_partial_rotary(x, positions, rot_dim, theta, max_seq_len):
    """Rotate the first ``rot_dim`` of each head dim; pass the rest through
    (NeoX rotary_pct semantics). x: [..., H, d]; positions broadcastable to
    the leading dims."""
    if rot_dim <= 0:
        return x
    cos, sin = rope_freqs(rot_dim, max_seq_len, theta)
    cos = jnp.asarray(cos)[positions][..., None, :]   # [..., 1, rot/2]
    sin = jnp.asarray(sin)[positions][..., None, :]
    rot, rest = x[..., :rot_dim], x[..., rot_dim:]
    r1, r2 = jnp.split(rot.astype(jnp.float32), 2, axis=-1)
    rot = jnp.concatenate([r1 * cos - r2 * sin, r2 * cos + r1 * sin], -1)
    return jnp.concatenate([rot.astype(x.dtype), rest], axis=-1)


class GPTNeoXBlock(nn.Module):
    cfg: GPTNeoXConfig

    @nn.compact
    def __call__(self, x, positions):
        cfg = self.cfg
        d = cfg.head_dim_
        h = nn.LayerNorm(epsilon=cfg.layer_norm_eps, dtype=cfg.dtype,
                         name="input_ln")(x)
        dense = partial(nn.DenseGeneral, use_bias=True, dtype=cfg.dtype,
                        param_dtype=jnp.float32)
        q = dense(features=(cfg.num_heads, d), name="wq")(h)
        k = dense(features=(cfg.num_heads, d), name="wk")(h)
        v = dense(features=(cfg.num_heads, d), name="wv")(h)
        q = shard_activation(q, (BATCH_AXES, SEQ_AXIS, HEADS_AXIS, None))
        q = apply_partial_rotary(q, positions, cfg.rotary_dim_, cfg.rope_theta,
                                 cfg.max_seq_len)
        k = apply_partial_rotary(k, positions, cfg.rotary_dim_, cfg.rope_theta,
                                 cfg.max_seq_len)
        attn = _dispatch_attention(cfg.attention_backend, q, k, v, causal=True)
        attn_out = nn.DenseGeneral(features=cfg.hidden_size, axis=(-2, -1),
                                   use_bias=True, dtype=cfg.dtype,
                                   param_dtype=jnp.float32, name="wo")(attn)
        h2_src = x if cfg.parallel_residual else x + attn_out
        h2 = nn.LayerNorm(epsilon=cfg.layer_norm_eps, dtype=cfg.dtype,
                          name="post_ln")(h2_src)
        m = nn.Dense(cfg.intermediate_size, use_bias=True, dtype=cfg.dtype,
                     param_dtype=jnp.float32, name="mlp_up")(h2)
        m = jax.nn.gelu(m)
        mlp_out = nn.Dense(cfg.hidden_size, use_bias=True, dtype=cfg.dtype,
                           param_dtype=jnp.float32, name="mlp_down")(m)
        if cfg.parallel_residual:
            x = x + attn_out + mlp_out
        else:
            x = h2_src + mlp_out
        return shard_activation(x, (BATCH_AXES, SEQ_AXIS, None))


class GPTNeoXModel(nn.Module):
    cfg: GPTNeoXConfig

    @nn.compact
    def __call__(self, input_ids, positions=None):
        cfg = self.cfg
        if positions is None:
            positions = jnp.broadcast_to(jnp.arange(input_ids.shape[1]),
                                         input_ids.shape)
        x = nn.Embed(cfg.vocab_size, cfg.hidden_size, dtype=cfg.dtype,
                     param_dtype=jnp.float32, name="embed")(input_ids)
        x = shard_activation(x, (BATCH_AXES, SEQ_AXIS, None))
        for i in range(cfg.num_layers):
            x = GPTNeoXBlock(cfg, name=f"layer_{i}")(x, positions)
        x = nn.LayerNorm(epsilon=cfg.layer_norm_eps, dtype=cfg.dtype,
                         name="final_ln")(x)
        kernel = self.param("embed_out", nn.initializers.lecun_normal(),
                            (cfg.hidden_size, cfg.vocab_size), jnp.float32)
        return x.astype(jnp.float32) @ kernel  # untied NeoX head


class GPTNeoXForCausalLM(nn.Module):
    cfg: GPTNeoXConfig

    def setup(self):
        self.model = GPTNeoXModel(self.cfg)

    @property
    def config(self):
        return self.cfg

    def __call__(self, batch):
        input_ids = batch["input_ids"]
        logits = self.model(input_ids, positions=batch.get("positions"))
        labels = input_ids[:, 1:]
        logp = jax.nn.log_softmax(logits[:, :-1].astype(jnp.float32), axis=-1)
        ll = jnp.take_along_axis(logp, labels[..., None], axis=-1)[..., 0]
        return -jnp.mean(ll)

    def logits(self, batch):
        return self.model(batch["input_ids"],
                          positions=batch.get("positions"))


def gpt_neox_tensor_rules(path, leaf):
    from jax.sharding import PartitionSpec
    names = [str(getattr(k, "key", getattr(k, "idx", k))) for k in path]
    if "embed" in names or "embed_out" in names:
        return PartitionSpec(None, "tensor")
    if any(n in names for n in ("wq", "wk", "wv")) and names[-1] == "kernel":
        return PartitionSpec(None, "tensor", None)
    if "wo" in names and names[-1] == "kernel":
        return PartitionSpec("tensor", None, None)
    if "mlp_up" in names and names[-1] == "kernel":
        return PartitionSpec(None, "tensor")
    if "mlp_down" in names and names[-1] == "kernel":
        return PartitionSpec("tensor", None)
    return None


def convert_hf_gpt_neox(hf_state, cfg: GPTNeoXConfig):
    """HF GPT-NeoX naming -> our tree. HF fuses query_key_value rows as
    ``[h, 3, d]`` per-head interleave (same layout fusedqkv_utils splits for
    bloom/neox)."""
    def get(name):
        v = hf_state[name]
        return np.asarray(v.detach().cpu().numpy() if hasattr(v, "detach") else v)

    dmodel, h, d = cfg.hidden_size, cfg.num_heads, cfg.head_dim_
    pfx = "gpt_neox."
    tree = {
        "embed": {"embedding": get(pfx + "embed_in.weight")},
        "final_ln": {"scale": get(pfx + "final_layer_norm.weight"),
                     "bias": get(pfx + "final_layer_norm.bias")},
        "embed_out": get("embed_out.weight").T,
    }
    for i in range(cfg.num_layers):
        p = f"{pfx}layers.{i}."
        w = get(p + "attention.query_key_value.weight").reshape(h, 3, d, dmodel)
        b = get(p + "attention.query_key_value.bias").reshape(h, 3, d)
        tree[f"layer_{i}"] = {
            "input_ln": {"scale": get(p + "input_layernorm.weight"),
                         "bias": get(p + "input_layernorm.bias")},
            "post_ln": {"scale": get(p + "post_attention_layernorm.weight"),
                        "bias": get(p + "post_attention_layernorm.bias")},
            "wq": {"kernel": w[:, 0].transpose(2, 0, 1), "bias": b[:, 0]},
            "wk": {"kernel": w[:, 1].transpose(2, 0, 1), "bias": b[:, 1]},
            "wv": {"kernel": w[:, 2].transpose(2, 0, 1), "bias": b[:, 2]},
            "wo": {"kernel": get(p + "attention.dense.weight")
                   .T.reshape(h, d, dmodel),
                   "bias": get(p + "attention.dense.bias")},
            "mlp_up": {"kernel": get(p + "mlp.dense_h_to_4h.weight").T,
                       "bias": get(p + "mlp.dense_h_to_4h.bias")},
            "mlp_down": {"kernel": get(p + "mlp.dense_4h_to_h.weight").T,
                         "bias": get(p + "mlp.dense_4h_to_h.bias")},
        }
    return {"model": tree}
