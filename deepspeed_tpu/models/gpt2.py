"""GPT-2 model family (learned positions, pre-LN, fused c_attn, tied head).

Reference analog: the megatron/gpt2-style containers
(``module_inject/containers/megatron_gpt.py``, ``distil_bert.py`` sibling) and
HFGPT2LayerPolicy (``module_inject/containers/gpt2.py``). Architecture: wte +
wpe embeddings, pre-LN blocks (ln_1 -> attn -> residual; ln_2 -> GELU MLP ->
residual), final ln_f, head tied to wte. HF stores Conv1D weights as
``[in, out]`` (already kernel-oriented — no transpose in the converter,
unlike Linear-based archs).
"""

import dataclasses
from functools import partial
from typing import Any

import flax.linen as nn
import jax
import jax.numpy as jnp
import numpy as np

from deepspeed_tpu.models.llama import (
    BATCH_AXES, HEADS_AXIS, SEQ_AXIS, _dispatch_attention, shard_activation)


@dataclasses.dataclass(frozen=True)
class GPT2Config:
    vocab_size: int = 50257
    hidden_size: int = 768
    num_layers: int = 12
    num_heads: int = 12
    max_seq_len: int = 1024
    layer_norm_eps: float = 1e-5
    dtype: Any = jnp.bfloat16
    attention_backend: str = "xla"

    @property
    def head_dim_(self) -> int:
        return self.hidden_size // self.num_heads


TINY_GPT2 = GPT2Config(vocab_size=512, hidden_size=128, num_layers=2,
                       num_heads=4, max_seq_len=128, dtype=jnp.float32)


class GPT2Block(nn.Module):
    cfg: GPT2Config

    @nn.compact
    def __call__(self, x):
        cfg = self.cfg
        d = cfg.head_dim_
        h = nn.LayerNorm(epsilon=cfg.layer_norm_eps, dtype=cfg.dtype,
                         name="ln_1")(x)
        dense = partial(nn.DenseGeneral, use_bias=True, dtype=cfg.dtype,
                        param_dtype=jnp.float32)
        q = dense(features=(cfg.num_heads, d), name="wq")(h)
        k = dense(features=(cfg.num_heads, d), name="wk")(h)
        v = dense(features=(cfg.num_heads, d), name="wv")(h)
        q = shard_activation(q, (BATCH_AXES, SEQ_AXIS, HEADS_AXIS, None))
        attn = _dispatch_attention(cfg.attention_backend, q, k, v, causal=True)
        x = x + nn.DenseGeneral(features=cfg.hidden_size, axis=(-2, -1),
                                use_bias=True, dtype=cfg.dtype,
                                param_dtype=jnp.float32, name="wo")(attn)
        h2 = nn.LayerNorm(epsilon=cfg.layer_norm_eps, dtype=cfg.dtype,
                          name="ln_2")(x)
        m = nn.Dense(4 * cfg.hidden_size, use_bias=True, dtype=cfg.dtype,
                     param_dtype=jnp.float32, name="mlp_up")(h2)
        m = jax.nn.gelu(m)
        x = x + nn.Dense(cfg.hidden_size, use_bias=True, dtype=cfg.dtype,
                         param_dtype=jnp.float32, name="mlp_down")(m)
        return shard_activation(x, (BATCH_AXES, SEQ_AXIS, None))


class GPT2Model(nn.Module):
    cfg: GPT2Config

    @nn.compact
    def __call__(self, input_ids, positions=None):
        cfg = self.cfg
        if positions is None:
            positions = jnp.broadcast_to(jnp.arange(input_ids.shape[1]),
                                         input_ids.shape)
        embed = nn.Embed(cfg.vocab_size, cfg.hidden_size, dtype=cfg.dtype,
                         param_dtype=jnp.float32, name="embed")
        x = embed(input_ids)
        x = x + self.param("pos_embed", nn.initializers.normal(0.02),
                           (cfg.max_seq_len, cfg.hidden_size),
                           jnp.float32)[positions].astype(cfg.dtype)
        x = shard_activation(x, (BATCH_AXES, SEQ_AXIS, None))
        for i in range(cfg.num_layers):
            x = GPT2Block(cfg, name=f"layer_{i}")(x)
        x = nn.LayerNorm(epsilon=cfg.layer_norm_eps, dtype=cfg.dtype,
                         name="final_ln")(x)
        return x.astype(jnp.float32) @ \
            embed.embedding.astype(jnp.float32).T   # tied wte head


class GPT2ForCausalLM(nn.Module):
    cfg: GPT2Config

    def setup(self):
        self.model = GPT2Model(self.cfg)

    @property
    def config(self):
        return self.cfg

    def __call__(self, batch):
        input_ids = batch["input_ids"]
        logits = self.model(input_ids, positions=batch.get("positions"))
        labels = input_ids[:, 1:]
        logp = jax.nn.log_softmax(logits[:, :-1].astype(jnp.float32), axis=-1)
        ll = jnp.take_along_axis(logp, labels[..., None], axis=-1)[..., 0]
        return -jnp.mean(ll)

    def logits(self, batch):
        return self.model(batch["input_ids"],
                          positions=batch.get("positions"))


def gpt2_tensor_rules(path, leaf):
    from jax.sharding import PartitionSpec
    names = [str(getattr(k, "key", getattr(k, "idx", k))) for k in path]
    if "embed" in names or "pos_embed" in names:
        return PartitionSpec(None, "tensor")
    if any(n in names for n in ("wq", "wk", "wv")) and names[-1] == "kernel":
        return PartitionSpec(None, "tensor", None)
    if "wo" in names and names[-1] == "kernel":
        return PartitionSpec("tensor", None, None)
    if "mlp_up" in names and names[-1] == "kernel":
        return PartitionSpec(None, "tensor")
    if "mlp_down" in names and names[-1] == "kernel":
        return PartitionSpec("tensor", None)
    return None


def convert_hf_gpt2(hf_state, cfg: GPT2Config):
    """HF GPT-2 naming -> our tree. c_attn fuses q|k|v COLUMNS of a Conv1D
    ``[D, 3D]`` (sequential split, not per-head interleave — the layout
    fusedqkv_utils calls 'glmtype' sequential)."""
    # GPT2LMHeadModel prefixes the backbone with 'transformer.'; bare
    # GPT2Model dicts don't — accept both
    pfx = "transformer." if any(k.startswith("transformer.")
                                for k in hf_state) else ""

    def get(name):
        v = hf_state[pfx + name]
        return np.asarray(v.detach().cpu().numpy() if hasattr(v, "detach") else v)

    dmodel, h, d = cfg.hidden_size, cfg.num_heads, cfg.head_dim_
    tree = {
        "embed": {"embedding": get("wte.weight")},
        "pos_embed": get("wpe.weight"),
        "final_ln": {"scale": get("ln_f.weight"), "bias": get("ln_f.bias")},
    }
    for i in range(cfg.num_layers):
        p = f"h.{i}."
        ca_w = get(p + "attn.c_attn.weight")          # [D, 3D] Conv1D
        ca_b = get(p + "attn.c_attn.bias")            # [3D]
        qw, kw, vw = np.split(ca_w, 3, axis=1)
        qb, kb, vb = np.split(ca_b, 3)
        tree[f"layer_{i}"] = {
            "ln_1": {"scale": get(p + "ln_1.weight"), "bias": get(p + "ln_1.bias")},
            "ln_2": {"scale": get(p + "ln_2.weight"), "bias": get(p + "ln_2.bias")},
            "wq": {"kernel": qw.reshape(dmodel, h, d), "bias": qb.reshape(h, d)},
            "wk": {"kernel": kw.reshape(dmodel, h, d), "bias": kb.reshape(h, d)},
            "wv": {"kernel": vw.reshape(dmodel, h, d), "bias": vb.reshape(h, d)},
            "wo": {"kernel": get(p + "attn.c_proj.weight").reshape(h, d, dmodel),
                   "bias": get(p + "attn.c_proj.bias")},
            "mlp_up": {"kernel": get(p + "mlp.c_fc.weight"),
                       "bias": get(p + "mlp.c_fc.bias")},
            "mlp_down": {"kernel": get(p + "mlp.c_proj.weight"),
                         "bias": get(p + "mlp.c_proj.bias")},
        }
    return {"model": tree}
