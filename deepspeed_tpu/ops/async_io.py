"""Async file I/O handle over the native thread-pool engine.

Reference analog: ``csrc/aio/py_lib/py_ds_aio.cpp`` (``aio_handle``) + the
``deepspeed/ops/aio`` wrapper — submit pread/pwrite of tensors against NVMe,
poll/wait completion. Python fallback uses a ThreadPoolExecutor.
"""

import ctypes
import os
from concurrent.futures import Future, ThreadPoolExecutor
from typing import Dict

import numpy as np

from deepspeed_tpu.utils.logging import warning_once


class AsyncIOHandle:
    """reference: aio_handle(block_size, queue_depth, single_submit,
    overlap_events, num_threads) — here only num_threads is meaningful."""

    def __init__(self, num_threads: int = 8):
        self.num_threads = num_threads
        self._lib = None
        self._h = None
        self._pool = None
        self._futures: Dict[int, Future] = {}
        self._next_id = 1
        try:
            from deepspeed_tpu.ops.op_builder import get_op
            lib = get_op("aio")
            lib.aio_create.restype = ctypes.c_void_p
            lib.aio_create.argtypes = [ctypes.c_int]
            lib.aio_destroy.argtypes = [ctypes.c_void_p]
            for fn in (lib.aio_pread, lib.aio_pwrite):
                fn.restype = ctypes.c_int64
                fn.argtypes = [ctypes.c_void_p, ctypes.c_char_p, ctypes.c_void_p,
                               ctypes.c_int64, ctypes.c_int64]
            lib.aio_wait.argtypes = [ctypes.c_void_p, ctypes.c_int64]
            lib.aio_is_done.argtypes = [ctypes.c_void_p, ctypes.c_int64]
            lib.aio_drain.argtypes = [ctypes.c_void_p]
            self._lib = lib
            self._h = lib.aio_create(num_threads)
        except Exception as e:
            warning_once(f"aio native op unavailable ({e}); thread-pool fallback")
            self._pool = ThreadPoolExecutor(max_workers=num_threads)

    def __del__(self):
        try:
            if self._lib is not None and self._h:
                self._lib.aio_destroy(self._h)
            if self._pool is not None:
                self._pool.shutdown(wait=False)
        except Exception:
            pass

    @staticmethod
    def _buf(a: np.ndarray):
        return a.ctypes.data_as(ctypes.c_void_p)

    def async_pwrite(self, array: np.ndarray, path: str, offset: int = 0) -> int:
        assert array.flags["C_CONTIGUOUS"]
        if self._lib is not None:
            return self._lib.aio_pwrite(self._h, path.encode(), self._buf(array),
                                        array.nbytes, offset)
        def work(data=array, p=path, off=offset):
            with open(p, "r+b" if os.path.exists(p) else "wb") as f:
                f.seek(off)
                f.write(data.tobytes())
        rid = self._next_id; self._next_id += 1
        self._futures[rid] = self._pool.submit(work)
        return rid

    def async_pread(self, array: np.ndarray, path: str, offset: int = 0) -> int:
        assert array.flags["C_CONTIGUOUS"]
        if self._lib is not None:
            return self._lib.aio_pread(self._h, path.encode(), self._buf(array),
                                       array.nbytes, offset)
        def work(data=array, p=path, off=offset):
            with open(p, "rb") as f:
                f.seek(off)
                raw = f.read(data.nbytes)
            data.ravel()[:] = np.frombuffer(raw, dtype=data.dtype)
        rid = self._next_id; self._next_id += 1
        self._futures[rid] = self._pool.submit(work)
        return rid

    def wait(self, request_id: int) -> int:
        """Block until the request completes; 0 = success, 1 = THIS request failed."""
        if self._lib is not None:
            return self._lib.aio_wait(self._h, request_id)
        fut = self._futures.pop(request_id)
        try:
            fut.result()
        except Exception:
            return 1
        return 0

    def is_done(self, request_id: int) -> bool:
        if self._lib is not None:
            return bool(self._lib.aio_is_done(self._h, request_id))
        fut = self._futures.get(request_id)
        return fut is None or fut.done()

    def drain(self) -> int:
        """Block until all outstanding requests complete; returns the number of
        failures among requests not individually waited (counter resets)."""
        if self._lib is not None:
            return self._lib.aio_drain(self._h)
        failures = 0
        for rid in list(self._futures):
            failures += self.wait(rid)
        return failures
