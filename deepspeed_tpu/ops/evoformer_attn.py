"""Evoformer (DS4Science) attention — biased attention for MSA/pair stacks.

Reference analog: ``csrc/deepspeed4science/evoformer_attn/`` (14.9k LoC of
CUTLASS fused kernels) + ``deepspeed/ops/deepspeed4science/evoformer_attn.py``
(``DS4Sci_EvoformerAttention(q, k, v, [bias1, bias2])``).

Semantics: ``softmax(q k^T / sqrt(d) + bias1 + bias2) v`` where q/k/v are
``[*, L, H, D]`` and each bias broadcasts to ``[*, H, L, L]`` (AlphaFold usage:
bias1 is the MSA mask ``[B, N, 1, 1, L]``, bias2 the pair bias
``[B, 1, H, L, L]``).

TPU shape: the reference needs CUTLASS for memory efficiency; here a blockwise
online-softmax ``lax.scan`` over key blocks gives the same O(L) working-set
scaling and XLA autodiff derives the fused backward (including bias gradients)
— no hand-written bwd kernel. Panels land on the MXU as
``[*, H, L, block_k]`` einsums.
"""

from functools import partial
from typing import Sequence

import jax
import jax.numpy as jnp
import numpy as np

NEG_INF = -1e30


def _pad_bias(bias, l_k, pad_k):
    """Pad a bias's last (key) dim in lockstep with k/v so per-block
    dynamic_slices never clamp (broadcast dims of size 1 stay as-is; padded
    columns are masked out by the key-padding mask)."""
    if bias.shape[-1] == 1:
        return bias
    if bias.shape[-1] != l_k:
        raise ValueError(
            f"bias last dim {bias.shape[-1]} must be 1 or key length {l_k}")
    if pad_k:
        bias = jnp.pad(bias, [(0, 0)] * (bias.ndim - 1) + [(0, pad_k)])
    return bias


def _slice_bias(bias, start, size):
    if bias.shape[-1] == 1:
        return bias
    return jax.lax.dynamic_slice_in_dim(bias, start, size, axis=-1)


@partial(jax.jit, static_argnames=("block_k",))
def evoformer_attention(q, k, v, biases: Sequence = (), block_k: int = 512):
    """q, k, v: [*, L, H, D]; biases: up to 2 arrays broadcastable to
    [*, H, Lq, Lk]. Returns [*, L, H, D]."""
    *lead, l_q, h, d = q.shape
    l_k = k.shape[-3]
    scale = 1.0 / np.sqrt(d)
    block_k = min(block_k, l_k)
    pad_k = (-l_k) % block_k
    if pad_k:
        kp = jnp.pad(k, [(0, 0)] * len(lead) + [(0, pad_k), (0, 0), (0, 0)])
        vp = jnp.pad(v, [(0, 0)] * len(lead) + [(0, pad_k), (0, 0), (0, 0)])
    else:
        kp, vp = k, v
    biases = tuple(_pad_bias(b, l_k, pad_k) for b in biases)
    nk = kp.shape[-3] // block_k

    def kv_step(carry, ki):
        m, l, o = carry
        start = ki * block_k
        k_blk = jax.lax.dynamic_slice_in_dim(kp, start, block_k, axis=-3)
        v_blk = jax.lax.dynamic_slice_in_dim(vp, start, block_k, axis=-3)
        s = jnp.einsum("...qhd,...khd->...hqk", q, k_blk,
                       preferred_element_type=jnp.float32) * scale
        for b in biases:
            s = s + _slice_bias(b.astype(jnp.float32), start, block_k)
        # mask key padding
        kpos = start + jnp.arange(block_k)
        s = jnp.where((kpos < l_k)[(None,) * (s.ndim - 1)], s, NEG_INF)
        m_new = jnp.maximum(m, jnp.max(s, axis=-1))
        p = jnp.exp(s - m_new[..., None])
        alpha = jnp.exp(m - m_new)
        l_new = l * alpha + jnp.sum(p, axis=-1)
        o_new = o * alpha[..., None] + jnp.einsum(
            "...hqk,...khd->...hqd", p.astype(v_blk.dtype), v_blk,
            preferred_element_type=jnp.float32)
        return (m_new, l_new, o_new), None

    m0 = jnp.full((*lead, h, l_q), NEG_INF, jnp.float32)
    l0 = jnp.zeros((*lead, h, l_q), jnp.float32)
    o0 = jnp.zeros((*lead, h, l_q, d), jnp.float32)
    (m, l, o), _ = jax.lax.scan(kv_step, (m0, l0, o0), jnp.arange(nk))
    out = o / jnp.maximum(l, 1e-30)[..., None]
    # [*, H, L, D] -> [*, L, H, D]
    return jnp.moveaxis(out, -3, -2).astype(q.dtype)


def DS4Sci_EvoformerAttention(q, k, v, biases: Sequence = ()):  # noqa: N802
    """Reference-named entry point (evoformer_attn.py
    DS4Sci_EvoformerAttention): q/k/v [*, L, H, D], biases list of <= 2.
    On TPU this dispatches to the fused Pallas kernel set (fwd + bwd incl.
    bias gradients — the analog of csrc/deepspeed4science/evoformer_attn);
    elsewhere the blockwise-scan jnp path (same O(L) working set, XLA
    autodiff bwd)."""
    if len(biases) > 2:
        raise ValueError("DS4Sci_EvoformerAttention supports at most 2 biases")
    biases = tuple(b for b in biases if b is not None)
    if jax.default_backend() == "tpu":
        from deepspeed_tpu.ops.pallas.evoformer import (
            UnsupportedBiasLayout, pallas_evoformer_attention)
        try:
            return pallas_evoformer_attention(q, k, v, biases)
        except UnsupportedBiasLayout:
            pass      # bias layout outside the kernel contract -> jnp path
    return evoformer_attention(q, k, v, biases)


def evoformer_attention_reference(q, k, v, biases: Sequence = ()):
    """Naive oracle for tests."""
    d = q.shape[-1]
    s = jnp.einsum("...qhd,...khd->...hqk", q, k).astype(jnp.float32) / \
        np.sqrt(d)
    for b in biases:
        s = s + b.astype(jnp.float32)
    p = jax.nn.softmax(s, axis=-1)
    out = jnp.einsum("...hqk,...khd->...hqd", p.astype(v.dtype), v)
    return jnp.moveaxis(out, -3, -2)
