// Asynchronous file I/O engine for the NVMe offload tier.
//
// Reference analog: csrc/aio (DeepNVMe) — a libaio worker-thread pool with
// work/complete queues (deepspeed_aio_thread.h:20) feeding pinned host
// buffers. Rebuilt TPU-side: a portable POSIX thread pool issuing pread/pwrite
// on per-thread file descriptors (libaio is not guaranteed in this image;
// threaded psync saturates modern NVMe at queue depth = num_threads), with a
// C ABI for ctypes. Buffers are caller-owned (numpy arrays pinned by the
// Python layer); completion is polled or waited via condition variable.

#include <atomic>
#include <condition_variable>
#include <cstdint>
#include <cstring>
#include <deque>
#include <fcntl.h>
#include <mutex>
#include <string>
#include <thread>
#include <unistd.h>
#include <unordered_map>
#include <vector>

namespace {

struct Request {
    int64_t id;
    bool write;
    std::string path;
    void* buffer;
    int64_t nbytes;
    int64_t file_offset;
};

struct Engine {
    std::vector<std::thread> workers;
    std::deque<Request> queue;
    std::mutex mu;
    std::condition_variable cv_work;
    std::condition_variable cv_done;
    std::atomic<int64_t> next_id{1};
    // completed, not-yet-waited requests: id -> ok. Per-request status (not a
    // global sticky counter) so one failed swap never poisons later waits.
    std::unordered_map<int64_t, bool> done;
    int64_t outstanding = 0;              // submitted but not completed
    bool shutdown = false;
    int block_size = 1 << 20;             // 1 MiB pread/pwrite chunks

    explicit Engine(int num_threads) {
        for (int i = 0; i < num_threads; ++i)
            workers.emplace_back([this] { run(); });
    }

    ~Engine() {
        {
            std::lock_guard<std::mutex> l(mu);
            shutdown = true;
        }
        cv_work.notify_all();
        for (auto& t : workers) t.join();
    }

    void run() {
        for (;;) {
            Request req;
            {
                std::unique_lock<std::mutex> l(mu);
                cv_work.wait(l, [this] { return shutdown || !queue.empty(); });
                if (shutdown && queue.empty()) return;
                req = queue.front();
                queue.pop_front();
            }
            bool ok = execute(req);
            {
                std::lock_guard<std::mutex> l(mu);
                done[req.id] = ok;
                outstanding--;
            }
            cv_done.notify_all();
        }
    }

    bool execute(const Request& req) {
        int flags = req.write ? (O_WRONLY | O_CREAT) : O_RDONLY;
        int fd = ::open(req.path.c_str(), flags, 0644);
        if (fd < 0) return false;
        char* p = (char*)req.buffer;
        int64_t remaining = req.nbytes;
        int64_t off = req.file_offset;
        bool ok = true;
        while (remaining > 0) {
            int64_t chunk = remaining < block_size ? remaining : block_size;
            ssize_t r = req.write ? ::pwrite(fd, p, chunk, off)
                                  : ::pread(fd, p, chunk, off);
            if (r <= 0) { ok = false; break; }
            p += r; off += r; remaining -= r;
        }
        ::close(fd);
        return ok;
    }

    int64_t submit(bool write, const char* path, void* buf, int64_t nbytes,
                   int64_t offset) {
        int64_t id = next_id.fetch_add(1);
        {
            std::lock_guard<std::mutex> l(mu);
            queue.push_back({id, write, path, buf, nbytes, offset});
            outstanding++;
        }
        cv_work.notify_one();
        return id;
    }

    bool is_done(int64_t id) {
        std::lock_guard<std::mutex> l(mu);
        return done.count(id) != 0;
    }

    // 0 = success, 1 = this request failed (entry reclaimed either way so the
    // table stays bounded over long runs).
    int wait(int64_t id) {
        std::unique_lock<std::mutex> l(mu);
        cv_done.wait(l, [&] { return done.count(id) != 0; });
        bool ok = done[id];
        done.erase(id);
        return ok ? 0 : 1;
    }

    // Waits for all outstanding requests; returns how many of the completed,
    // not-individually-waited requests failed, then clears the table.
    int drain() {
        std::unique_lock<std::mutex> l(mu);
        cv_done.wait(l, [&] { return outstanding == 0; });
        int failures = 0;
        for (auto& kv : done) if (!kv.second) failures++;
        done.clear();
        return failures;
    }
};

}  // namespace

extern "C" {

void* aio_create(int num_threads) { return new Engine(num_threads); }
void aio_destroy(void* h) { delete (Engine*)h; }

int64_t aio_pwrite(void* h, const char* path, void* buf, int64_t nbytes,
                   int64_t offset) {
    return ((Engine*)h)->submit(true, path, buf, nbytes, offset);
}

int64_t aio_pread(void* h, const char* path, void* buf, int64_t nbytes,
                  int64_t offset) {
    return ((Engine*)h)->submit(false, path, buf, nbytes, offset);
}

int aio_is_done(void* h, int64_t id) { return ((Engine*)h)->is_done(id) ? 1 : 0; }
int aio_wait(void* h, int64_t id) { return ((Engine*)h)->wait(id); }
int aio_drain(void* h) { return ((Engine*)h)->drain(); }

}  // extern "C"
