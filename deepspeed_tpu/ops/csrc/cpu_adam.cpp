// Fused CPU Adam/AdamW for host-offloaded optimizer states.
//
// Reference analog: csrc/adam/cpu_adam_impl.cpp (AVX2/AVX512 Step_1/4/8
// templates with OMP tiling). Rebuilt for the TPU framework's host-offload
// tier: OpenMP `parallel for simd` + __restrict__ aliasing guarantees so
// -O3 -march=native emits the same packed AVX the reference hand-writes
// (Step_8-style unrolling comes from the compiler),
// exposed via a C ABI for ctypes binding (no pybind11 in this image).
//
// Semantics match the framework's in-HBM optax path: bias-corrected Adam with
// decoupled (AdamW) or L2 weight decay, fp32 master params and states, and an
// optional bf16 shadow copy written for the device transfer.

#include <cmath>
#include <cstdint>
#include <cstring>

extern "C" {

// One fused Adam step over a flat fp32 shard.
//   params, grads, exp_avg, exp_avg_sq: length n
//   step: 1-based step count (for bias correction)
//   adamw: 1 = decoupled weight decay, 0 = L2 (grad += wd * param)
void cpu_adam_step(float* __restrict__ params, const float* __restrict__ grads,
                   float* __restrict__ exp_avg,
                   float* __restrict__ exp_avg_sq, int64_t n, float lr, float beta1,
                   float beta2, float eps, float weight_decay, int adamw,
                   int64_t step) {
    const float bc1 = 1.0f - std::pow(beta1, (float)step);
    const float bc2 = 1.0f - std::pow(beta2, (float)step);
    const float step_size = lr / bc1;
    const float inv_sqrt_bc2 = 1.0f / std::sqrt(bc2);

#pragma omp parallel for simd schedule(static)
    for (int64_t i = 0; i < n; ++i) {
        float g = grads[i];
        float p = params[i];
        if (!adamw && weight_decay != 0.0f) g += weight_decay * p;
        float m = beta1 * exp_avg[i] + (1.0f - beta1) * g;
        float v = beta2 * exp_avg_sq[i] + (1.0f - beta2) * g * g;
        exp_avg[i] = m;
        exp_avg_sq[i] = v;
        float denom = std::sqrt(v) * inv_sqrt_bc2 + eps;
        float p_new = p - step_size * (m / denom);
        // decoupled decay scales with lr, not the bias-corrected step size
        if (adamw && weight_decay != 0.0f) p_new -= lr * weight_decay * p;
        params[i] = p_new;
    }
}

// bf16 shadow copy of the fp32 master params (for the host->device transfer;
// reference: param fp16 shard update after CPU step).
void fp32_to_bf16(const float* __restrict__ src, uint16_t* __restrict__ dst,
                  int64_t n) {
#pragma omp parallel for simd schedule(static)
    for (int64_t i = 0; i < n; ++i) {
        uint32_t bits;
        std::memcpy(&bits, &src[i], 4);
        // round-to-nearest-even
        uint32_t rounding_bias = 0x7FFF + ((bits >> 16) & 1);
        dst[i] = (uint16_t)((bits + rounding_bias) >> 16);
    }
}

// Fused CPU Adagrad (reference: csrc/adagrad/cpu_adagrad.cpp)
void cpu_adagrad_step(float* __restrict__ params, const float* __restrict__ grads,
                      float* __restrict__ state_sum, int64_t n, float lr, float eps, float weight_decay) {
#pragma omp parallel for simd schedule(static)
    for (int64_t i = 0; i < n; ++i) {
        float g = grads[i];
        if (weight_decay != 0.0f) g += weight_decay * params[i];
        float s = state_sum[i] + g * g;
        state_sum[i] = s;
        params[i] -= lr * g / (std::sqrt(s) + eps);
    }
}

// Fused CPU Lion (reference: csrc/lion/cpu_lion_impl.cpp)
void cpu_lion_step(float* __restrict__ params, const float* __restrict__ grads,
                   float* __restrict__ exp_avg, int64_t n, float lr, float beta1, float beta2,
                   float weight_decay) {
#pragma omp parallel for simd schedule(static)
    for (int64_t i = 0; i < n; ++i) {
        float g = grads[i];
        float m = exp_avg[i];
        float c = beta1 * m + (1.0f - beta1) * g;
        float update = (c > 0.0f) - (c < 0.0f);  // sign
        float p = params[i];
        p -= lr * (update + weight_decay * p);
        params[i] = p;
        exp_avg[i] = beta2 * m + (1.0f - beta2) * g;
    }
}

}  // extern "C"
