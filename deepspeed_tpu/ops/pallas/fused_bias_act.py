"""Fused bias + activation (+ dropout) Pallas kernels, fwd and bwd.

Reference analog: ``csrc/transformer/gelu_kernels.cu`` (fused_bias_gelu +
d_gelu_bias backward) and ``dropout_kernels.cu`` (``dropout_act``-style fused
variants) — the elementwise tail of the reference's fused transformer layer.

TPU note: XLA fuses a plain ``act(x + b)`` into the producing matmul, so the
un-dropout forms exist mainly for the op-level parity surface; the fused
*dropout* variant is the one XLA cannot reproduce exactly — it fuses the PRNG
(Pallas ``prng_random_bits``, threefry-seeded per block) with bias+activation
in one VMEM pass, like the CUDA kernel's curand-in-kernel design, and its
backward regenerates the same mask from the seed instead of storing it
(memory: zero mask bytes vs B*S*F bools).
"""

import functools

import jax
import jax.numpy as jnp
from jax.experimental import pallas as pl

try:  # TPU-only primitives; interpret-mode fallbacks used off-TPU
    from jax.experimental.pallas import tpu as pltpu
except ImportError:  # pragma: no cover
    pltpu = None

_ACTS = {
    "gelu": jax.nn.gelu,
    "relu": jax.nn.relu,
    "silu": jax.nn.silu,
    "identity": lambda x: x,
}


def _act_grad(name, x):
    return jax.grad(lambda v: jnp.sum(_ACTS[name](v)))(x)


def _bias_act_kernel(x_ref, b_ref, o_ref, *, act):
    x = x_ref[:].astype(jnp.float32) + b_ref[:].astype(jnp.float32)
    o_ref[:] = _ACTS[act](x).astype(o_ref.dtype)


def _bias_act_bwd_kernel(x_ref, b_ref, g_ref, dx_ref, db_ref, *, act):
    x = x_ref[:].astype(jnp.float32) + b_ref[:].astype(jnp.float32)
    dx = _act_grad(act, x) * g_ref[:].astype(jnp.float32)
    dx_ref[:] = dx.astype(dx_ref.dtype)
    # per-block bias-grad partial, fused in the same VMEM pass (reference
    # d_gelu_bias accumulates db in-kernel) — no second HBM sweep over dx
    db_ref[:] = jnp.sum(dx, axis=0, keepdims=True)


def _call_rows(kernel, args, out_dtype, block_rows, interpret):
    """Row-blocked elementwise pallas_call over [N, D] operands (+[D] bias)."""
    n, d = args[0].shape
    pad = (-n) % block_rows
    if pad:
        args = [jnp.pad(a, ((0, pad), (0, 0))) if a.ndim == 2 else a
                for a in args]
    specs = [pl.BlockSpec((block_rows, d), lambda i: (i, 0)) if a.ndim == 2
             else pl.BlockSpec((d,), lambda i: (0,)) for a in args]
    out = pl.pallas_call(
        kernel,
        grid=((n + pad) // block_rows,),
        in_specs=specs,
        out_specs=pl.BlockSpec((block_rows, d), lambda i: (i, 0)),
        out_shape=jax.ShapeDtypeStruct((n + pad, d), out_dtype),
        interpret=interpret,
    )(*args)
    return out[:n]


@functools.partial(jax.custom_vjp, nondiff_argnums=(2, 3, 4))
def fused_bias_act(x, bias, act: str = "gelu", block_rows: int = 256,
                   interpret: bool = False):
    """act(x + bias) in one VMEM pass. x: [..., D]; bias: [D]."""
    shape = x.shape
    out = _call_rows(functools.partial(_bias_act_kernel, act=act),
                     [x.reshape(-1, shape[-1]), bias], x.dtype, block_rows,
                     interpret)
    return out.reshape(shape)


def _fba_fwd(x, bias, act, block_rows, interpret):
    return fused_bias_act(x, bias, act, block_rows, interpret), (x, bias)


def _bwd_call(kernel, x, bias, g, block_rows, interpret, seed=None):
    """Shared bwd scaffolding: pad rows, run the (dx, db-partials) kernel,
    slice, reduce partials. Zero-padded rows contribute nothing — g is padded
    with zeros, so dx=0 there and db is unaffected."""
    shape = x.shape
    d = shape[-1]
    x2, g2 = x.reshape(-1, d), g.reshape(-1, d)
    n = x2.shape[0]
    pad = (-n) % block_rows
    if pad:
        x2 = jnp.pad(x2, ((0, pad), (0, 0)))
        g2 = jnp.pad(g2, ((0, pad), (0, 0)))
    grid = (n + pad) // block_rows
    in_specs = [
        pl.BlockSpec((block_rows, d), lambda i: (i, 0)),
        pl.BlockSpec((d,), lambda i: (0,)),
        pl.BlockSpec((block_rows, d), lambda i: (i, 0)),
    ]
    args = [x2, bias, g2]
    if seed is not None:
        in_specs.insert(0, pl.BlockSpec(memory_space=pltpu.SMEM))
        args.insert(0, seed)
    dx, db_parts = pl.pallas_call(
        kernel,
        grid=(grid,),
        in_specs=in_specs,
        out_specs=[
            pl.BlockSpec((block_rows, d), lambda i: (i, 0)),
            pl.BlockSpec((1, d), lambda i: (i, 0)),
        ],
        out_shape=[
            jax.ShapeDtypeStruct((n + pad, d), x.dtype),
            jax.ShapeDtypeStruct((grid, d), jnp.float32),
        ],
        interpret=interpret,
    )(*args)
    return dx[:n].reshape(shape), \
        jnp.sum(db_parts, axis=0).astype(bias.dtype)


def _fba_bwd(act, block_rows, interpret, res, g):
    x, bias = res
    return _bwd_call(functools.partial(_bias_act_bwd_kernel, act=act),
                     x, bias, g, block_rows, interpret)


fused_bias_act.defvjp(_fba_fwd, _fba_bwd)


# ---------------------------------------------------------------------------
# fused bias + activation + dropout (mask regenerated in backward)
# ---------------------------------------------------------------------------

def _u32_to_unit_float(bits):
    # upper 24 bits -> [0, 1) floats, unbiased
    return (bits >> 8).astype(jnp.float32) * (1.0 / (1 << 24))


def _bias_act_dropout_kernel(seed_ref, x_ref, b_ref, o_ref, *, act, rate):
    i = pl.program_id(0)
    pltpu.prng_seed(seed_ref[0], i)
    bits = pltpu.prng_random_bits(x_ref.shape).astype(jnp.uint32)
    keep = _u32_to_unit_float(bits) >= rate
    scale = 1.0 / (1.0 - rate)
    x = x_ref[:].astype(jnp.float32) + b_ref[:].astype(jnp.float32)
    o_ref[:] = jnp.where(keep, _ACTS[act](x) * scale, 0.0).astype(o_ref.dtype)


def _bias_act_dropout_bwd_kernel(seed_ref, x_ref, b_ref, g_ref, dx_ref, db_ref,
                                 *, act, rate):
    # regenerate the SAME mask as forward: identical seed, grid index, shape
    i = pl.program_id(0)
    pltpu.prng_seed(seed_ref[0], i)
    bits = pltpu.prng_random_bits(x_ref.shape).astype(jnp.uint32)
    keep = _u32_to_unit_float(bits) >= rate
    scale = 1.0 / (1.0 - rate)
    x = x_ref[:].astype(jnp.float32) + b_ref[:].astype(jnp.float32)
    dx = jnp.where(keep, _act_grad(act, x) * scale, 0.0) * \
        g_ref[:].astype(jnp.float32)
    dx_ref[:] = dx.astype(dx_ref.dtype)
    db_ref[:] = jnp.sum(dx, axis=0, keepdims=True)


def _seed_arr(seed):
    return jnp.asarray([seed], jnp.int32) if jnp.ndim(seed) == 0 \
        else seed.reshape(1).astype(jnp.int32)


def _interp_keep(seed, shape, rate):
    # pltpu PRNG primitives have no CPU lowering; the interpret-mode path
    # derives the keep mask from the same seed with jax.random — the
    # fwd/bwd mask-identity contract holds per platform
    return jax.random.uniform(jax.random.PRNGKey(seed[0]), shape) >= rate


def _fbad_impl(x, bias, seed, act, rate, block_rows, interpret):
    shape = x.shape
    d = shape[-1]
    x2 = x.reshape(-1, d)
    seed = _seed_arr(seed)
    if interpret:
        keep = _interp_keep(seed, x2.shape, rate)
        xb = x2.astype(jnp.float32) + bias.astype(jnp.float32)
        out = jnp.where(keep, _ACTS[act](xb) / (1.0 - rate), 0.0) \
            .astype(x2.dtype)
        return out.reshape(shape)
    n = x2.shape[0]
    pad = (-n) % block_rows
    if pad:
        x2 = jnp.pad(x2, ((0, pad), (0, 0)))
    out = pl.pallas_call(
        functools.partial(_bias_act_dropout_kernel, act=act, rate=rate),
        grid=((n + pad) // block_rows,),
        in_specs=[
            pl.BlockSpec(memory_space=pltpu.SMEM),
            pl.BlockSpec((block_rows, d), lambda i: (i, 0)),
            pl.BlockSpec((d,), lambda i: (0,)),
        ],
        out_specs=pl.BlockSpec((block_rows, d), lambda i: (i, 0)),
        out_shape=jax.ShapeDtypeStruct((n + pad, d), x2.dtype),
        interpret=interpret,
    )(seed, x2, bias)
    return out[:n].reshape(shape)


def _fbad_bwd_impl(x, bias, seed, g, act, rate, block_rows, interpret):
    seed = _seed_arr(seed)
    if interpret:
        shape = x.shape
        x2, g2 = x.reshape(-1, shape[-1]), g.reshape(-1, shape[-1])
        keep = _interp_keep(seed, x2.shape, rate)
        xb = x2.astype(jnp.float32) + bias.astype(jnp.float32)
        dx = jnp.where(keep, _act_grad(act, xb) / (1.0 - rate), 0.0) * \
            g2.astype(jnp.float32)
        return dx.astype(x.dtype).reshape(shape), \
            jnp.sum(dx, axis=0).astype(bias.dtype)
    return _bwd_call(
        functools.partial(_bias_act_dropout_bwd_kernel, act=act, rate=rate),
        x, bias, g, block_rows, interpret, seed=seed)


@functools.partial(jax.custom_vjp, nondiff_argnums=(3, 4, 5, 6))
def fused_bias_act_dropout(x, bias, seed, act: str = "gelu",
                           rate: float = 0.1, block_rows: int = 256,
                           interpret: bool = False):
    """dropout(act(x + bias)) with the mask generated in-kernel from ``seed``
    (int32 scalar). The backward re-derives the identical mask from the same
    seed — no mask tensor is ever written to HBM."""
    if not 0.0 <= rate < 1.0:
        raise ValueError(f"dropout rate must be in [0, 1), got {rate}")
    if rate == 0.0:
        return fused_bias_act(x, bias, act, block_rows, interpret)
    return _fbad_impl(x, bias, seed, act, rate, block_rows, interpret)


def _fbad_fwd(x, bias, seed, act, rate, block_rows, interpret):
    return fused_bias_act_dropout(x, bias, seed, act, rate, block_rows,
                                  interpret), (x, bias, seed)


def _fbad_bwd(act, rate, block_rows, interpret, res, g):
    x, bias, seed = res
    if rate == 0.0:
        dx, db = _fba_bwd(act, block_rows, interpret, (x, bias), g)
        return dx, db, None
    dx, db = _fbad_bwd_impl(x, bias, seed, g, act, rate, block_rows,
                            interpret)
    return dx, db, None


fused_bias_act_dropout.defvjp(_fbad_fwd, _fbad_bwd)
