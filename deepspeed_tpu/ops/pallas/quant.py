"""Int8 (de)quantization Pallas kernels.

Reference analog: ``csrc/quantization/{quantize.cu,swizzled_quantize.cu,
quant_reduce.cu}`` — symmetric per-group int8 used by ZeRO++ quantized-weight
allgather (qwZ) and quantized-gradient collectives (qgZ), and
``deepspeed/inference/quantization`` for ZeRO-Inference weight quant.

Layout: per-row (last-dim group) symmetric scales in fp32. The quantize kernel
fuses absmax + scale + round in one VMEM pass; dequantize fuses scale-multiply.
These are the building blocks the quantized-collective layer composes around an
``all_gather``/``psum_scatter`` (int8 on the wire = 4x ICI bandwidth saving vs
fp32, 2x vs bf16 — cf. ZeRO++'s qwZ).
"""

import functools

import jax
import jax.numpy as jnp
from jax.experimental import pallas as pl


def _quant_kernel(x_ref, q_ref, s_ref):
    x = x_ref[:].astype(jnp.float32)
    absmax = jnp.max(jnp.abs(x), axis=-1, keepdims=True)
    scale = jnp.maximum(absmax / 127.0, 1e-12)
    q = jnp.clip(jnp.round(x / scale), -127, 127)
    q_ref[:] = q.astype(jnp.int8)
    s_ref[:] = scale


def _dequant_kernel(q_ref, s_ref, o_ref):
    o_ref[:] = (q_ref[:].astype(jnp.float32) * s_ref[:]).astype(o_ref.dtype)


def _auto_interpret():
    return jax.default_backend() != "tpu"


def rowwise_pallas_op(kernel, inputs, out_shapes, block_rows: int,
                      interpret):
    """Shared scaffolding for per-row (last-dim-group) quantization kernels:
    flatten [..., D] inputs to row-blocks, pad the row count to ``block_rows``,
    run ``kernel`` over a 1-D row-block grid, unpad. ``inputs``: list of
    [N, D_i] arrays (same N); ``out_shapes``: list of (last_dim, dtype).
    Used by the int8 kernels here and the fp8 kernels in ``fp_quant.py``."""
    interpret = _auto_interpret() if interpret is None else interpret
    n = inputs[0].shape[0]
    pad = (-n) % block_rows
    if pad:
        inputs = [jnp.pad(x, ((0, pad), (0, 0))) for x in inputs]
    rows = inputs[0].shape[0]
    outs = pl.pallas_call(
        kernel,
        grid=(rows // block_rows,),
        in_specs=[pl.BlockSpec((block_rows, x.shape[1]), lambda i: (i, 0))
                  for x in inputs],
        out_specs=[pl.BlockSpec((block_rows, d), lambda i: (i, 0))
                   for d, _ in out_shapes],
        out_shape=[jax.ShapeDtypeStruct((rows, d), dt) for d, dt in out_shapes],
        interpret=interpret,
    )(*inputs)
    return [o[:n] for o in outs]


@functools.partial(jax.jit, static_argnames=("block_rows", "interpret"))
def quantize_int8(x, block_rows: int = 256, interpret: bool = None):
    """x: [..., D] -> (int8 values [..., D], fp32 scales [..., 1]) per-row."""
    shape = x.shape
    d = shape[-1]
    qv, sv = rowwise_pallas_op(
        _quant_kernel, [x.reshape(-1, d)],
        [(d, jnp.int8), (1, jnp.float32)], block_rows, interpret)
    return qv.reshape(shape), sv.reshape(*shape[:-1], 1)


@functools.partial(jax.jit, static_argnames=("block_rows", "interpret", "dtype"))
def dequantize_int8(q, scales, dtype=jnp.bfloat16, block_rows: int = 256,
                    interpret: bool = None):
    shape = q.shape
    d = shape[-1]
    (out,) = rowwise_pallas_op(
        _dequant_kernel, [q.reshape(-1, d), scales.reshape(-1, 1)],
        [(d, dtype)], block_rows, interpret)
    return out.reshape(shape)


def quantized_all_gather(x, axis_name: str):
    """qwZ-style collective: quantize locally, all_gather int8 + scales, dequant
    (reference: quantized weights allgather, partition_parameters.py:1664 +
    quantizer kernels). Usable inside shard_map."""
    q, s = quantize_int8(x)
    qg = jax.lax.all_gather(q, axis_name, axis=0, tiled=True)
    sg = jax.lax.all_gather(s, axis_name, axis=0, tiled=True)
    return dequantize_int8(qg, sg, dtype=x.dtype)


def quantized_psum_scatter(x, axis_name: str, mean: bool = False):
    """qgZ building block: reduce-scatter with int8 on the wire. Usable inside
    shard_map. x: [N, D] per-device partial values (N divisible by the axis
    size after padding); returns the local [N/W, D] shard of the sum.

    Implementation is the reference's dequant-reduce scheme
    (``runtime/comm/coalesced_collectives.py:31 all_to_all_quant_reduce`` +
    ``csrc/quantization/quant_reduce.cu``): quantize locally, all-to-all the
    int8 chunks + scales (4x less wire traffic than fp32), dequantize and
    reduce on the receiver.

    When N is not divisible by W the input is zero-padded, so the returned
    shard is [(N + pad)/W, D] and the pad rows surface as trailing zero rows
    in the LAST devices' shards — reassembling over the axis yields the padded
    [N + pad, D] sum; slice to N if exact shape matters.
    """
    w = jax.lax.axis_size(axis_name)
    n, d = x.shape
    pad = (-n) % w
    if pad:
        x = jnp.pad(x, ((0, pad), (0, 0)))
    q, s = quantize_int8(x)
    qs = q.reshape(w, -1, d)
    ss = s.reshape(w, -1, 1)
    qx = jax.lax.all_to_all(qs, axis_name, split_axis=0, concat_axis=0,
                            tiled=False)
    sx = jax.lax.all_to_all(ss, axis_name, split_axis=0, concat_axis=0,
                            tiled=False)
    deq = dequantize_int8(qx.reshape(-1, d), sx.reshape(-1, 1),
                          dtype=jnp.float32).reshape(w, -1, d)
    out = jnp.sum(deq, axis=0)
    if mean:
        out = out / w
    return out.astype(x.dtype)


def all_to_all_quant_reduce(x, axis_name: str, outer_axis_name=None,
                            mean: bool = False):
    """qgZ: hierarchical quantized gradient reduce-scatter (reference:
    ``all_to_all_quant_reduce`` coalesced_collectives.py:31 — int8 all-to-all
    within the node, dequant-reduce, then a second quantized hop across nodes).
    On a TPU mesh the two levels are the inner (ICI-adjacent, e.g. ``fsdp``)
    and outer (e.g. ``fsdp_out`` / DCN) axes. Usable inside shard_map."""
    y = quantized_psum_scatter(x, axis_name, mean=mean)
    if outer_axis_name is not None:
        y = quantized_psum_scatter(y, outer_axis_name, mean=mean)
    return y


@functools.partial(jax.custom_vjp, nondiff_argnums=(1, 2))
def quantized_psum(x, axes, mean: bool = False):
    """All-reduce with int8 on the wire: hierarchical quantized
    reduce-scatter + int8 regather over ``axes`` (given outermost-first; the
    scatter runs innermost-first so the full volume rides the fast/ICI hop
    and only the reduced 1/w shard crosses the outer wire). x: [N, D]; the
    result is replicated across ``axes``. Usable inside shard_map manual
    over (at least) ``axes``. Shared core of the qgZ gradient sync
    (runtime/zero/qgz.py) and the quantized MoE dispatch/combine.

    Differentiable with a straight-through backward: a psum whose output is
    replicated has identity (÷w for mean) as its exact vjp — each device's
    cotangent IS the replicated downstream cotangent — so the backward costs
    zero wire bytes and only the int8 rounding is straight-through'd (same
    contract as qwZ's straight-through weight gather)."""
    return _quantized_psum_core(x, axes, mean)


def _quantized_psum_core(x, axes, mean):
    rows = []
    for ax in reversed(tuple(axes)):
        rows.append(x.shape[0])
        x = quantized_psum_scatter(x, ax, mean=mean)
    for ax, r in zip(tuple(axes), reversed(rows)):
        x = quantized_all_gather(x, ax)[:r]
    return x


def _quantized_psum_fwd(x, axes, mean):
    return _quantized_psum_core(x, axes, mean), None


def _quantized_psum_bwd(axes, mean, _, g):
    # Straight-through the int8 rounding; the backward of the underlying
    # collective (psum / pmean with REPLICATED output) is a pure local
    # rescale of the (replicated) cotangent — zero wire bytes. The scale
    # factor depends on shard_map's cotangent convention: under
    # check_vma=False JAX transposes psum to psum and hands this bwd
    # dL/dy ÷ world, so the local equivalent of psum(replicated g) is g*w;
    # under a VMA/identity-transpose convention it would be g unscaled.
    # Rather than hard-code the convention (ADVICE r3), DERIVE it at trace
    # time: build the jaxpr of lax.psum's own transpose in the current trace
    # context and check whether it binds a psum — a JAX internals change
    # flips the factor here in lockstep, and the final program still
    # contains no collective (the probe jaxpr is inspected, never executed).
    def _collective(x):
        return jax.lax.psum(x, tuple(axes))

    tiny = jax.ShapeDtypeStruct((1,), g.dtype)
    probe = jax.make_jaxpr(
        lambda t: jax.linear_transpose(_collective, tiny)(t))(
            jnp.zeros((1,), g.dtype))
    transposes_to_psum = any(
        "psum" in eqn.primitive.name
        for eqn in probe.jaxpr.eqns)

    w = 1
    for ax in axes:
        w *= jax.lax.axis_size(ax)
    if mean:
        # forward = psum/w; psum-transpose convention makes the two rescales
        # cancel (psum(g/w) over replicated g == g); identity convention
        # leaves the ÷w
        gx = g if transposes_to_psum else g / w
    else:
        gx = g * w if transposes_to_psum else g
    return (gx,)


quantized_psum.defvjp(_quantized_psum_fwd, _quantized_psum_bwd)


def quantized_all_to_all(x, axis_name: str, split_axis: int = 0,
                         concat_axis: int = 0):
    """MoE-dispatch collective with int8 wire format (cf. EQuARX): quantize
    per-row groups, all-to-all codes + scales, dequantize on the receiver —
    4x less ICI traffic than fp32 expert dispatch for the same top-k routing.
    x: [..., D] with the split axis divisible by the axis size. Usable inside
    shard_map."""
    q, s = quantize_int8(x)
    qx = jax.lax.all_to_all(q, axis_name, split_axis=split_axis,
                            concat_axis=concat_axis, tiled=True)
    sx = jax.lax.all_to_all(s, axis_name, split_axis=split_axis,
                            concat_axis=concat_axis, tiled=True)
    return dequantize_int8(qx, sx, dtype=x.dtype)
