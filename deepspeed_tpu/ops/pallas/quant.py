"""Int8 (de)quantization Pallas kernels.

Reference analog: ``csrc/quantization/{quantize.cu,swizzled_quantize.cu,
quant_reduce.cu}`` — symmetric per-group int8 used by ZeRO++ quantized-weight
allgather (qwZ) and quantized-gradient collectives (qgZ), and
``deepspeed/inference/quantization`` for ZeRO-Inference weight quant.

Layout: per-row (last-dim group) symmetric scales in fp32. The quantize kernel
fuses absmax + scale + round in one VMEM pass; dequantize fuses scale-multiply.
These are the building blocks the quantized-collective layer composes around an
``all_gather``/``psum_scatter`` (int8 on the wire = 4x ICI bandwidth saving vs
fp32, 2x vs bf16 — cf. ZeRO++'s qwZ).
"""

import functools

import jax
import jax.numpy as jnp
from jax.experimental import pallas as pl


def _quant_kernel(x_ref, q_ref, s_ref):
    x = x_ref[:].astype(jnp.float32)
    absmax = jnp.max(jnp.abs(x), axis=-1, keepdims=True)
    scale = jnp.maximum(absmax / 127.0, 1e-12)
    q = jnp.clip(jnp.round(x / scale), -127, 127)
    q_ref[:] = q.astype(jnp.int8)
    s_ref[:] = scale


def _dequant_kernel(q_ref, s_ref, o_ref):
    o_ref[:] = (q_ref[:].astype(jnp.float32) * s_ref[:]).astype(o_ref.dtype)


def _auto_interpret():
    return jax.default_backend() != "tpu"


@functools.partial(jax.jit, static_argnames=("block_rows", "interpret"))
def quantize_int8(x, block_rows: int = 256, interpret: bool = None):
    """x: [..., D] -> (int8 values [..., D], fp32 scales [..., 1]) per-row."""
    interpret = _auto_interpret() if interpret is None else interpret
    shape = x.shape
    d = shape[-1]
    x2 = x.reshape(-1, d)
    n = x2.shape[0]
    pad = (-n) % block_rows
    if pad:
        x2 = jnp.pad(x2, ((0, pad), (0, 0)))
    qv, sv = pl.pallas_call(
        _quant_kernel,
        grid=(x2.shape[0] // block_rows,),
        in_specs=[pl.BlockSpec((block_rows, d), lambda i: (i, 0))],
        out_specs=[
            pl.BlockSpec((block_rows, d), lambda i: (i, 0)),
            pl.BlockSpec((block_rows, 1), lambda i: (i, 0)),
        ],
        out_shape=[
            jax.ShapeDtypeStruct(x2.shape, jnp.int8),
            jax.ShapeDtypeStruct((x2.shape[0], 1), jnp.float32),
        ],
        interpret=interpret,
    )(x2)
    return (qv[:n].reshape(shape),
            sv[:n].reshape(*shape[:-1], 1))


@functools.partial(jax.jit, static_argnames=("block_rows", "interpret", "dtype"))
def dequantize_int8(q, scales, dtype=jnp.bfloat16, block_rows: int = 256,
                    interpret: bool = None):
    interpret = _auto_interpret() if interpret is None else interpret
    shape = q.shape
    d = shape[-1]
    q2 = q.reshape(-1, d)
    s2 = scales.reshape(-1, 1)
    n = q2.shape[0]
    pad = (-n) % block_rows
    if pad:
        q2 = jnp.pad(q2, ((0, pad), (0, 0)))
        s2 = jnp.pad(s2, ((0, pad), (0, 0)))
    out = pl.pallas_call(
        _dequant_kernel,
        grid=(q2.shape[0] // block_rows,),
        in_specs=[
            pl.BlockSpec((block_rows, d), lambda i: (i, 0)),
            pl.BlockSpec((block_rows, 1), lambda i: (i, 0)),
        ],
        out_specs=pl.BlockSpec((block_rows, d), lambda i: (i, 0)),
        out_shape=jax.ShapeDtypeStruct(q2.shape, dtype),
        interpret=interpret,
    )(q2, s2)
    return out[:n].reshape(shape)


def quantized_all_gather(x, axis_name: str):
    """qwZ-style collective: quantize locally, all_gather int8 + scales, dequant
    (reference: quantized weights allgather, partition_parameters.py:1664 +
    quantizer kernels). Usable inside shard_map."""
    q, s = quantize_int8(x)
    qg = jax.lax.all_gather(q, axis_name, axis=0, tiled=True)
    sg = jax.lax.all_gather(s, axis_name, axis=0, tiled=True)
    return dequantize_int8(qg, sg, dtype=x.dtype)


def quantized_psum_scatter(x, axis_name: str, mean: bool = False):
    """qgZ building block: reduce-scatter with int8 on the wire. Usable inside
    shard_map. x: [N, D] per-device partial values (N divisible by the axis
    size after padding); returns the local [N/W, D] shard of the sum.

    Implementation is the reference's dequant-reduce scheme
    (``runtime/comm/coalesced_collectives.py:31 all_to_all_quant_reduce`` +
    ``csrc/quantization/quant_reduce.cu``): quantize locally, all-to-all the
    int8 chunks + scales (4x less wire traffic than fp32), dequantize and
    reduce on the receiver.
    """
    w = jax.lax.axis_size(axis_name)
    n, d = x.shape
    pad = (-n) % w
    if pad:
        x = jnp.pad(x, ((0, pad), (0, 0)))
    q, s = quantize_int8(x)
    qs = q.reshape(w, -1, d)
    ss = s.reshape(w, -1, 1)
    qx = jax.lax.all_to_all(qs, axis_name, split_axis=0, concat_axis=0,
                            tiled=False)
    sx = jax.lax.all_to_all(ss, axis_name, split_axis=0, concat_axis=0,
                            tiled=False)
    deq = dequantize_int8(qx.reshape(-1, d), sx.reshape(-1, 1),
                          dtype=jnp.float32).reshape(w, -1, d)
    out = jnp.sum(deq, axis=0)
    if mean:
        out = out / w
    return out.astype(x.dtype)


def all_to_all_quant_reduce(x, axis_name: str, outer_axis_name=None,
                            mean: bool = False):
    """qgZ: hierarchical quantized gradient reduce-scatter (reference:
    ``all_to_all_quant_reduce`` coalesced_collectives.py:31 — int8 all-to-all
    within the node, dequant-reduce, then a second quantized hop across nodes).
    On a TPU mesh the two levels are the inner (ICI-adjacent, e.g. ``fsdp``)
    and outer (e.g. ``fsdp_out`` / DCN) axes. Usable inside shard_map."""
    y = quantized_psum_scatter(x, axis_name, mean=mean)
    if outer_axis_name is not None:
        y = quantized_psum_scatter(y, outer_axis_name, mean=mean)
    return y
