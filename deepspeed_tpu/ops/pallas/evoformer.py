"""Pallas evoformer (DS4Science) attention — fused biased attention kernels.

Reference analog: ``csrc/deepspeed4science/evoformer_attn/`` (14.9k LoC of
CUTLASS kernels: ``attention_cu.cu`` forward, ``attention_back.cu`` backward
incl. bias gradients). Semantics (``DS4Sci_EvoformerAttention``):

    softmax(q k^T / sqrt(d) + bias1 + bias2) v

with q/k/v ``[B, N, L, H, D]`` (AlphaFold MSA/pair stacks: B batch, N rows),
``bias1`` broadcastable ``[B, N, 1, 1, L]`` (row mask, per-key additive) and
``bias2`` ``[B, 1, H, L, L]`` (pair bias, shared across rows).

Kernel set (mirrors the flash-attention family in flash_attention.py):
- fwd: online-softmax over key blocks; biases stream per block (the [L, L]
  panel never materializes in HBM).
- bwd dq / dkv: flash-style recompute-from-(q,k,v,lse) with the bias terms
  re-added; note ``s = qk*scale + b`` so dq/dk carry ``scale`` while the
  bias gradient is the raw ``dS``.
- bwd dbias2: accumulates ``dS`` over the N rows that share a pair-bias
  panel — N is the innermost grid dim so output-block revisits are
  CONSECUTIVE (TPU pallas keeps the block resident between consecutive
  same-index iterations; non-consecutive revisits would be undefined).
- bwd dbias1: per-key column sum of ``dS`` over heads and query blocks.

Gradients flow to q, k, v and both biases (the reference computes dbias1/2
too). GQA is not a thing here (H == Hkv).
"""

import functools

import jax
import jax.numpy as jnp
import numpy as np
from jax.experimental import pallas as pl
from jax.experimental.pallas import tpu as pltpu

NEG_INF = -1e30


def _key_mask(ki, block_k, seq_len_k):
    kpos = ki * block_k + jax.lax.broadcasted_iota(jnp.int32, (1, block_k), 1)
    return kpos < seq_len_k


def _scores(q, k, b1, b2, ki, *, sm_scale, block_k, seq_len_k):
    """s = q k^T * scale + bias1 + bias2, padding keys masked to NEG_INF.
    b1: [1, block_k] or None; b2: [block_q, block_k] or None."""
    s = jax.lax.dot_general(q, k, (((1,), (1,)), ((), ())),
                            preferred_element_type=jnp.float32) * sm_scale
    if b1 is not None:
        s = s + b1.astype(jnp.float32)
    if b2 is not None:
        s = s + b2.astype(jnp.float32)
    mask = _key_mask(ki, block_k, seq_len_k)
    return jnp.where(mask, s, NEG_INF), mask


def _evo_fwd_kernel(q_ref, k_ref, v_ref, b1_ref, b2_ref, o_ref, lse_ref,
                    m_scr, l_scr, acc_scr, *, sm_scale, block_k,
                    num_k_blocks, seq_len_k, has_b1, has_b2):
    ki = pl.program_id(2)

    @pl.when(ki == 0)
    def _init():
        m_scr[:] = jnp.full_like(m_scr, NEG_INF)
        l_scr[:] = jnp.zeros_like(l_scr)
        acc_scr[:] = jnp.zeros_like(acc_scr)

    q, k, v = q_ref[0], k_ref[0], v_ref[0]
    b1 = b1_ref[0] if has_b1 else None               # [1, block_k]
    b2 = b2_ref[0] if has_b2 else None               # [block_q, block_k]
    s, mask = _scores(q, k, b1, b2, ki, sm_scale=sm_scale, block_k=block_k,
                      seq_len_k=seq_len_k)
    m_prev = m_scr[:]
    m_new = jnp.maximum(m_prev, jnp.max(s, axis=1, keepdims=True))
    p = jnp.where(mask, jnp.exp(s - m_new), 0.0)
    alpha = jnp.exp(m_prev - m_new)
    l_new = l_scr[:] * alpha + jnp.sum(p, axis=1, keepdims=True)
    acc_scr[:] = acc_scr[:] * alpha + jax.lax.dot_general(
        p.astype(v.dtype), v, (((1,), (0,)), ((), ())),
        preferred_element_type=jnp.float32)
    m_scr[:] = m_new
    l_scr[:] = l_new

    @pl.when(ki == num_k_blocks - 1)
    def _finalize():
        l = jnp.maximum(l_scr[:], 1e-30)
        o_ref[0] = (acc_scr[:] / l).astype(o_ref.dtype)
        lse_ref[0] = m_scr[:] + jnp.log(l)


def _evo_dq_kernel(q_ref, k_ref, v_ref, b1_ref, b2_ref, do_ref, lse_ref,
                   delta_ref, dq_ref, dq_scr, *, sm_scale, block_k,
                   num_k_blocks, seq_len_k, has_b1, has_b2):
    ki = pl.program_id(2)

    @pl.when(ki == 0)
    def _init():
        dq_scr[:] = jnp.zeros_like(dq_scr)

    q, k, v, do = q_ref[0], k_ref[0], v_ref[0], do_ref[0]
    b1 = b1_ref[0] if has_b1 else None
    b2 = b2_ref[0] if has_b2 else None
    s, mask = _scores(q, k, b1, b2, ki, sm_scale=sm_scale, block_k=block_k,
                      seq_len_k=seq_len_k)
    p = jnp.where(mask, jnp.exp(s - lse_ref[0]), 0.0)
    dp = jax.lax.dot_general(do, v, (((1,), (1,)), ((), ())),
                             preferred_element_type=jnp.float32)
    ds = p * (dp - delta_ref[0])                     # raw dS (bias grad units)
    dq_scr[:] = dq_scr[:] + jax.lax.dot_general(
        (ds * sm_scale).astype(k.dtype), k, (((1,), (0,)), ((), ())),
        preferred_element_type=jnp.float32)

    @pl.when(ki == num_k_blocks - 1)
    def _finalize():
        dq_ref[0] = dq_scr[:].astype(dq_ref.dtype)


def _evo_dkv_kernel(q_ref, k_ref, v_ref, b1_ref, b2_ref, do_ref, lse_ref,
                    delta_ref, dk_ref, dv_ref, dk_scr, dv_scr, *, sm_scale,
                    block_k, num_q_blocks, seq_len_k, has_b1, has_b2):
    qi = pl.program_id(2)
    ki = pl.program_id(1)

    @pl.when(qi == 0)
    def _init():
        dk_scr[:] = jnp.zeros_like(dk_scr)
        dv_scr[:] = jnp.zeros_like(dv_scr)

    q, k, v, do = q_ref[0], k_ref[0], v_ref[0], do_ref[0]
    b1 = b1_ref[0] if has_b1 else None
    b2 = b2_ref[0] if has_b2 else None
    s, mask = _scores(q, k, b1, b2, ki, sm_scale=sm_scale, block_k=block_k,
                      seq_len_k=seq_len_k)
    p = jnp.where(mask, jnp.exp(s - lse_ref[0]), 0.0)  # [bq, bk]
    dv_scr[:] = dv_scr[:] + jax.lax.dot_general(
        p.astype(do.dtype), do, (((0,), (0,)), ((), ())),
        preferred_element_type=jnp.float32)
    dp = jax.lax.dot_general(do, v, (((1,), (1,)), ((), ())),
                             preferred_element_type=jnp.float32)
    ds = p * (dp - delta_ref[0])
    dk_scr[:] = dk_scr[:] + jax.lax.dot_general(
        (ds * sm_scale).astype(q.dtype), q, (((0,), (0,)), ((), ())),
        preferred_element_type=jnp.float32)

    @pl.when(qi == num_q_blocks - 1)
    def _finalize():
        dk_ref[0] = dk_scr[:].astype(dk_ref.dtype)
        dv_ref[0] = dv_scr[:].astype(dv_ref.dtype)


def _evo_dbias2_kernel(q_ref, k_ref, v_ref, b1_ref, b2_ref, do_ref, lse_ref,
                       delta_ref, db2_ref, db2_scr, *, sm_scale, block_k,
                       num_rows, seq_len_k, has_b1, has_b2):
    """Grid (B*H, nq, nk, N): N innermost -> the (bh, qi, ki) output block is
    revisited on consecutive iterations and accumulates dS over rows."""
    n = pl.program_id(3)
    ki = pl.program_id(2)

    @pl.when(n == 0)
    def _init():
        db2_scr[:] = jnp.zeros_like(db2_scr)

    q, k, v, do = q_ref[0], k_ref[0], v_ref[0], do_ref[0]
    b1 = b1_ref[0] if has_b1 else None
    b2 = b2_ref[0] if has_b2 else None
    s, mask = _scores(q, k, b1, b2, ki, sm_scale=sm_scale, block_k=block_k,
                      seq_len_k=seq_len_k)
    p = jnp.where(mask, jnp.exp(s - lse_ref[0]), 0.0)
    dp = jax.lax.dot_general(do, v, (((1,), (1,)), ((), ())),
                             preferred_element_type=jnp.float32)
    db2_scr[:] = db2_scr[:] + p * (dp - delta_ref[0])

    @pl.when(n == num_rows - 1)
    def _finalize():
        db2_ref[0] = db2_scr[:].astype(db2_ref.dtype)


def _evo_dbias1_kernel(q_ref, k_ref, v_ref, b1_ref, b2_ref, do_ref, lse_ref,
                       delta_ref, db1_ref, db1_scr, *, sm_scale, block_k,
                       num_hq_steps, seq_len_k, has_b1, has_b2):
    """Grid (B*N, nk, H*nq): per-key column sum of dS over heads + q blocks."""
    j = pl.program_id(2)

    @pl.when(j == 0)
    def _init():
        db1_scr[:] = jnp.zeros_like(db1_scr)

    q, k, v, do = q_ref[0], k_ref[0], v_ref[0], do_ref[0]
    b1 = b1_ref[0] if has_b1 else None
    b2 = b2_ref[0] if has_b2 else None
    s, mask = _scores(q, k, b1, b2, ki=pl.program_id(1), sm_scale=sm_scale,
                      block_k=block_k, seq_len_k=seq_len_k)
    p = jnp.where(mask, jnp.exp(s - lse_ref[0]), 0.0)
    dp = jax.lax.dot_general(do, v, (((1,), (1,)), ((), ())),
                             preferred_element_type=jnp.float32)
    ds = p * (dp - delta_ref[0])
    db1_scr[:] = db1_scr[:] + jnp.sum(ds, axis=0, keepdims=True)

    @pl.when(j == num_hq_steps - 1)
    def _finalize():
        db1_ref[0] = db1_scr[:].astype(db1_ref.dtype)


# ---------------------------------------------------------------------------
# host-side plumbing
# ---------------------------------------------------------------------------

class UnsupportedBiasLayout(ValueError):
    """Bias shape outside the kernel contract — the caller may fall back to
    the jnp blockwise path (which handles any broadcastable bias)."""


def _bcast(bias, target):
    try:
        return jnp.broadcast_to(bias.astype(jnp.float32), target)
    except ValueError as e:         # folded lead dims the bias can't match
        raise UnsupportedBiasLayout(str(e)) from e


def _canon(q, k, v, biases):
    """[*, L, H, D] -> (q,k,v [B, N, L, H, D], bias1 [B, N, Lk] | None,
    bias2 [B, H, Lq, Lk] | None). Leading dims beyond two fold into B."""
    if q.ndim < 4:
        raise UnsupportedBiasLayout(
            f"evoformer q must be [*, L, H, D], got {q.shape}")
    lead = q.shape[:-3]
    if len(lead) == 1:
        b, n = lead[0], 1
    else:
        b, n = int(np.prod(lead[:-1])), lead[-1]
    l_q, h, d = q.shape[-3:]
    l_k = k.shape[-3]
    q5 = q.reshape(b, n, l_q, h, d)
    k5 = k.reshape(b, n, l_k, h, d)
    v5 = v.reshape(b, n, l_k, h, d)

    b1 = b2 = None
    for bias in biases:
        if bias is None:
            continue
        # classify by broadcast pattern against [B, N, H, Lq, Lk]
        shape = bias.shape
        if bias.ndim >= 1 and shape[-1] not in (l_k, 1):
            raise UnsupportedBiasLayout(
                f"bias last dim {shape[-1]} != key length {l_k}")
        if bias.ndim < 2 or (shape[-2] == 1
                             and (bias.ndim < 3 or shape[-3] == 1)):
            # per-key additive (mask): [B, N, 1, 1, Lk]-like (or 0/1-d)
            if b1 is not None:
                raise UnsupportedBiasLayout("two mask-like biases given")
            b1 = _bcast(bias, (b, n, 1, 1, l_k)).reshape(b, n, l_k)
        else:
            # pair bias: [B, 1, H, Lq, Lk]-like (shared across the N rows —
            # the kernel streams ONE panel per (b, h); a bias that varies by
            # row is outside the reference's contract too)
            if b2 is not None:
                raise UnsupportedBiasLayout("two pair-like biases given")
            if bias.ndim >= 3 and shape[-3] not in (1, h):
                raise UnsupportedBiasLayout(
                    f"bias head dim {shape[-3]} != heads {h}")
            if bias.ndim >= 4 and shape[-4] != 1 and n > 1:
                raise UnsupportedBiasLayout(
                    "pair bias varying over the row (N) dim is unsupported "
                    f"(got row dim {shape[-4]} with N={n})")
            b2 = _bcast(bias, (b, 1, h, l_q, l_k)).reshape(b, h, l_q, l_k)
    return q5, k5, v5, b1, b2


def _fold_bnh(x):
    """[B, N, L, H, D] -> [B*N*H, L, D]."""
    b, n, l, h, d = x.shape
    return x.transpose(0, 1, 3, 2, 4).reshape(b * n * h, l, d)


def _pad_axis(x, block, axis):
    pad = (-x.shape[axis]) % block
    if not pad:
        return x
    widths = [(0, 0)] * x.ndim
    widths[axis] = (0, pad)
    return jnp.pad(x, widths)


def _bias_operands(b1, b2, b, n, h, block_q, block_k, lq_p, lk_p):
    """Padded/folded bias arrays + (q-major) grid index maps, shared by the
    fwd and bwd pallas_calls so their block addressing can never diverge.
    Index maps take grid coords (bh=B*N*H row, i=q block, j=k block); the
    dummy zero operands keep the arg list static when a bias is absent."""
    has_b1, has_b2 = b1 is not None, b2 is not None
    b1a = _pad_axis(b1, block_k, 2).reshape(b * n, 1, lk_p) if has_b1 else \
        jnp.zeros((1, 1, block_k), jnp.float32)
    b2a = _pad_axis(_pad_axis(b2, block_k, 3), block_q, 2) \
        .reshape(b * h, lq_p, lk_p) if has_b2 else \
        jnp.zeros((1, block_q, block_k), jnp.float32)

    def b1_idx(bh, i, j):
        return (bh // h, 0, j) if has_b1 else (0, 0, 0)

    def b2_idx(bh, i, j):
        return ((bh // (n * h)) * h + bh % h, i, j) if has_b2 else (0, 0, 0)
    return b1a, b2a, b1_idx, b2_idx, has_b1, has_b2


def _evo_fwd_impl(q, k, v, b1, b2, block_q, block_k, interpret):
    b, n, l_q, h, d = q.shape
    l_k = k.shape[2]
    sm_scale = 1.0 / np.sqrt(d)
    qf = _fold_bnh(_pad_axis(q, block_q, 2))
    kf = _fold_bnh(_pad_axis(k, block_k, 2))
    vf = _fold_bnh(_pad_axis(v, block_k, 2))
    lq_p, lk_p = qf.shape[1], kf.shape[1]
    nq, nk = lq_p // block_q, lk_p // block_k
    g = b * n * h
    b1a, b2a, b1_idx, b2_idx, has_b1, has_b2 = _bias_operands(
        b1, b2, b, n, h, block_q, block_k, lq_p, lk_p)

    out, lse = pl.pallas_call(
        functools.partial(_evo_fwd_kernel, sm_scale=sm_scale, block_k=block_k,
                          num_k_blocks=nk, seq_len_k=l_k,
                          has_b1=has_b1, has_b2=has_b2),
        grid=(g, nq, nk),
        in_specs=[
            pl.BlockSpec((1, block_q, d), lambda bh, i, j: (bh, i, 0)),
            pl.BlockSpec((1, block_k, d), lambda bh, i, j: (bh, j, 0)),
            pl.BlockSpec((1, block_k, d), lambda bh, i, j: (bh, j, 0)),
            pl.BlockSpec((1, 1, block_k), b1_idx),
            pl.BlockSpec((1, block_q, block_k), b2_idx),
        ],
        out_specs=[
            pl.BlockSpec((1, block_q, d), lambda bh, i, j: (bh, i, 0)),
            pl.BlockSpec((1, block_q, 1), lambda bh, i, j: (bh, i, 0)),
        ],
        out_shape=[
            jax.ShapeDtypeStruct((g, lq_p, d), q.dtype),
            jax.ShapeDtypeStruct((g, lq_p, 1), jnp.float32),
        ],
        scratch_shapes=[
            pltpu.VMEM((block_q, 1), jnp.float32),
            pltpu.VMEM((block_q, 1), jnp.float32),
            pltpu.VMEM((block_q, d), jnp.float32),
        ],
        interpret=interpret,
    )(qf, kf, vf, b1a, b2a)
    o5 = out.reshape(b, n, h, lq_p, d).transpose(0, 1, 3, 2, 4)[:, :, :l_q]
    return o5, (lse, lq_p, lk_p)


def _evo_bwd_impl(q, k, v, b1, b2, out, lse, g_out, block_q, block_k,
                  interpret):
    b, n, l_q, h, d = q.shape
    l_k = k.shape[2]
    sm_scale = 1.0 / np.sqrt(d)
    qf = _fold_bnh(_pad_axis(q, block_q, 2))
    kf = _fold_bnh(_pad_axis(k, block_k, 2))
    vf = _fold_bnh(_pad_axis(v, block_k, 2))
    dof = _fold_bnh(_pad_axis(g_out, block_q, 2))
    of = _fold_bnh(_pad_axis(out, block_q, 2))
    lq_p, lk_p = qf.shape[1], kf.shape[1]
    nq, nk = lq_p // block_q, lk_p // block_k
    gdim = b * n * h
    delta = jnp.sum(dof.astype(jnp.float32) * of.astype(jnp.float32),
                    axis=-1, keepdims=True)
    b1a, b2a, b1_idx, b2_idx, has_b1, has_b2 = _bias_operands(
        b1, b2, b, n, h, block_q, block_k, lq_p, lk_p)

    common = dict(sm_scale=sm_scale, block_k=block_k, seq_len_k=l_k,
                  has_b1=has_b1, has_b2=has_b2)
    row_specs = [
        pl.BlockSpec((1, block_q, d), lambda bh, i, j: (bh, i, 0)),   # q
        pl.BlockSpec((1, block_k, d), lambda bh, i, j: (bh, j, 0)),   # k
        pl.BlockSpec((1, block_k, d), lambda bh, i, j: (bh, j, 0)),   # v
        pl.BlockSpec((1, 1, block_k), b1_idx),
        pl.BlockSpec((1, block_q, block_k), b2_idx),
        pl.BlockSpec((1, block_q, d), lambda bh, i, j: (bh, i, 0)),   # do
        pl.BlockSpec((1, block_q, 1), lambda bh, i, j: (bh, i, 0)),   # lse
        pl.BlockSpec((1, block_q, 1), lambda bh, i, j: (bh, i, 0)),   # delta
    ]
    args = (qf, kf, vf, b1a, b2a, dof, lse, delta)

    dq = pl.pallas_call(
        functools.partial(_evo_dq_kernel, num_k_blocks=nk, **common),
        grid=(gdim, nq, nk),
        in_specs=row_specs,
        out_specs=pl.BlockSpec((1, block_q, d), lambda bh, i, j: (bh, i, 0)),
        out_shape=jax.ShapeDtypeStruct((gdim, lq_p, d), q.dtype),
        scratch_shapes=[pltpu.VMEM((block_q, d), jnp.float32)],
        interpret=interpret,
    )(*args)

    kv_specs = [
        pl.BlockSpec((1, block_q, d), lambda bh, i, j: (bh, j, 0)),   # q
        pl.BlockSpec((1, block_k, d), lambda bh, i, j: (bh, i, 0)),   # k
        pl.BlockSpec((1, block_k, d), lambda bh, i, j: (bh, i, 0)),   # v
        # grid here is (g, nk, nq): swap (i, j) into the shared q-major maps
        pl.BlockSpec((1, 1, block_k), lambda bh, i, j: b1_idx(bh, j, i)),
        pl.BlockSpec((1, block_q, block_k),
                     lambda bh, i, j: b2_idx(bh, j, i)),
        pl.BlockSpec((1, block_q, d), lambda bh, i, j: (bh, j, 0)),   # do
        pl.BlockSpec((1, block_q, 1), lambda bh, i, j: (bh, j, 0)),   # lse
        pl.BlockSpec((1, block_q, 1), lambda bh, i, j: (bh, j, 0)),   # delta
    ]
    dk, dv = pl.pallas_call(
        functools.partial(_evo_dkv_kernel, num_q_blocks=nq, **common),
        grid=(gdim, nk, nq),
        in_specs=kv_specs,
        out_specs=[
            pl.BlockSpec((1, block_k, d), lambda bh, i, j: (bh, i, 0)),
            pl.BlockSpec((1, block_k, d), lambda bh, i, j: (bh, i, 0)),
        ],
        out_shape=[
            jax.ShapeDtypeStruct((gdim, lk_p, d), k.dtype),
            jax.ShapeDtypeStruct((gdim, lk_p, d), v.dtype),
        ],
        scratch_shapes=[
            pltpu.VMEM((block_k, d), jnp.float32),
            pltpu.VMEM((block_k, d), jnp.float32),
        ],
        interpret=interpret,
    )(*args)

    db1 = db2 = None
    if has_b2:
        # grid (B*H, nq, nk, N): q/k/v row index from (bh, n) pair
        def row_of(bh, nn):
            return (bh // h) * (n * h) + nn * h + bh % h

        b2_specs = [
            pl.BlockSpec((1, block_q, d),
                         lambda bh, i, j, nn: (row_of(bh, nn), i, 0)),
            pl.BlockSpec((1, block_k, d),
                         lambda bh, i, j, nn: (row_of(bh, nn), j, 0)),
            pl.BlockSpec((1, block_k, d),
                         lambda bh, i, j, nn: (row_of(bh, nn), j, 0)),
            pl.BlockSpec((1, 1, block_k),
                         (lambda bh, i, j, nn: ((row_of(bh, nn)) // h, 0, j))
                         if has_b1 else (lambda bh, i, j, nn: (0, 0, 0))),
            pl.BlockSpec((1, block_q, block_k),
                         lambda bh, i, j, nn: (bh, i, j)),
            pl.BlockSpec((1, block_q, d),
                         lambda bh, i, j, nn: (row_of(bh, nn), i, 0)),
            pl.BlockSpec((1, block_q, 1),
                         lambda bh, i, j, nn: (row_of(bh, nn), i, 0)),
            pl.BlockSpec((1, block_q, 1),
                         lambda bh, i, j, nn: (row_of(bh, nn), i, 0)),
        ]
        db2 = pl.pallas_call(
            functools.partial(_evo_dbias2_kernel, num_rows=n, **common),
            grid=(b * h, nq, nk, n),
            in_specs=b2_specs,
            out_specs=pl.BlockSpec((1, block_q, block_k),
                                   lambda bh, i, j, nn: (bh, i, j)),
            out_shape=jax.ShapeDtypeStruct((b * h, lq_p, lk_p), jnp.float32),
            scratch_shapes=[pltpu.VMEM((block_q, block_k), jnp.float32)],
            interpret=interpret,
        )(*args)
        db2 = db2.reshape(b, h, lq_p, lk_p)[:, :, :l_q, :l_k]
    if has_b1:
        # grid (B*N, nk, H*nq): row index bn*h + (j // nq), q block j % nq
        def g_of(bn, j):
            return bn * h + j // nq

        b1_specs = [
            pl.BlockSpec((1, block_q, d),
                         lambda bn, i, j: (g_of(bn, j), j % nq, 0)),
            pl.BlockSpec((1, block_k, d),
                         lambda bn, i, j: (g_of(bn, j), i, 0)),
            pl.BlockSpec((1, block_k, d),
                         lambda bn, i, j: (g_of(bn, j), i, 0)),
            pl.BlockSpec((1, 1, block_k), lambda bn, i, j: (bn, 0, i)),
            pl.BlockSpec((1, block_q, block_k),
                         (lambda bn, i, j: ((bn // n) * h + j // nq,
                                            j % nq, i))
                         if has_b2 else (lambda bn, i, j: (0, 0, 0))),
            pl.BlockSpec((1, block_q, d),
                         lambda bn, i, j: (g_of(bn, j), j % nq, 0)),
            pl.BlockSpec((1, block_q, 1),
                         lambda bn, i, j: (g_of(bn, j), j % nq, 0)),
            pl.BlockSpec((1, block_q, 1),
                         lambda bn, i, j: (g_of(bn, j), j % nq, 0)),
        ]
        db1 = pl.pallas_call(
            functools.partial(_evo_dbias1_kernel, num_hq_steps=h * nq,
                              **common),
            grid=(b * n, nk, h * nq),
            in_specs=b1_specs,
            out_specs=pl.BlockSpec((1, 1, block_k),
                                   lambda bn, i, j: (bn, 0, i)),
            out_shape=jax.ShapeDtypeStruct((b * n, 1, lk_p), jnp.float32),
            scratch_shapes=[pltpu.VMEM((1, block_k), jnp.float32)],
            interpret=interpret,
        )(*args)
        db1 = db1.reshape(b, n, lk_p)[:, :, :l_k]

    def unfold(x, l):
        return x.reshape(b, n, h, -1, d).transpose(0, 1, 3, 2, 4)[:, :, :l]
    return unfold(dq, l_q), unfold(dk, l_k), unfold(dv, l_k), db1, db2


# ---------------------------------------------------------------------------
# custom_vjp core (canonical 5D shapes) + public entry
# ---------------------------------------------------------------------------

@functools.partial(jax.custom_vjp, nondiff_argnums=(5, 6, 7))
def _evo_core(q5, k5, v5, b1, b2, block_q, block_k, interpret):
    out, _ = _evo_fwd_impl(q5, k5, v5, b1, b2, block_q, block_k, interpret)
    return out


def _evo_core_fwd(q5, k5, v5, b1, b2, block_q, block_k, interpret):
    out, (lse, _, _) = _evo_fwd_impl(q5, k5, v5, b1, b2, block_q, block_k,
                                     interpret)
    return out, (q5, k5, v5, b1, b2, out, lse)


def _evo_core_bwd(block_q, block_k, interpret, res, g):
    q5, k5, v5, b1, b2, out, lse = res
    dq, dk, dv, db1, db2 = _evo_bwd_impl(q5, k5, v5, b1, b2, out, lse, g,
                                         block_q, block_k, interpret)
    return (dq, dk, dv,
            db1 if b1 is not None else None,
            db2 if b2 is not None else None)


_evo_core.defvjp(_evo_core_fwd, _evo_core_bwd)


def pallas_evoformer_attention(q, k, v, biases=(), block_q: int = 128,
                               block_k: int = 128, interpret: bool = False):
    """Fused evoformer attention (Pallas): q/k/v ``[*, L, H, D]``, biases
    per the module docstring. Differentiable in q/k/v and both biases (the
    bias canonicalization is plain jnp broadcasting, so autodiff sums the
    cotangent back over any broadcast dims of the caller's original shape).
    """
    lead = q.shape[:-3]                      # non-empty: _canon raises on <4d
    q5, k5, v5, b1, b2 = _canon(q, k, v, biases)
    out = _evo_core(q5, k5, v5, b1, b2, block_q, block_k, interpret)
    return out.reshape(*lead, *out.shape[-3:])
