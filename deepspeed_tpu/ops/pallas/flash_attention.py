"""Pallas flash-attention forward kernel.

Reference analog: the fused attention CUDA kernels
(``csrc/transformer/inference/csrc/softmax.cu``, v2 ``blocked_flash``). TPU design:
canonical sequential-grid flash — grid (batch*heads, q_blocks, k_blocks) with the
k dimension innermost (TPU grids execute sequentially, so VMEM scratch accumulators
carry across k steps): online-softmax max/sum/output accumulators in fp32 scratch,
[block_q, block_k] score panels on the MXU, GQA handled by index-mapping q heads
onto shared KV heads (no KV repeat materialized).

Backward: flash-style recompute via the blockwise lax implementation
(``deepspeed_tpu.ops.flash_attention``) under ``jax.custom_vjp`` — same numerics,
O(S) memory.
"""

import functools

import jax
import jax.numpy as jnp
import numpy as np
from jax.experimental import pallas as pl
from jax.experimental.pallas import tpu as pltpu

from deepspeed_tpu.ops.flash_attention import flash_attention as blockwise_reference

NEG_INF = -1e30


def _flash_kernel(q_ref, k_ref, v_ref, o_ref, m_scr, l_scr, acc_scr, *,
                  sm_scale, causal, block_q, block_k, num_k_blocks, seq_len_k):
    ki = pl.program_id(2)
    qi = pl.program_id(1)

    @pl.when(ki == 0)
    def _init():
        m_scr[:] = jnp.full_like(m_scr, NEG_INF)
        l_scr[:] = jnp.zeros_like(l_scr)
        acc_scr[:] = jnp.zeros_like(acc_scr)

    q = q_ref[0]                       # [block_q, D]
    k = k_ref[0]                       # [block_k, D]
    v = v_ref[0]
    s = jax.lax.dot_general(q, k, (((1,), (1,)), ((), ())),
                            preferred_element_type=jnp.float32) * sm_scale

    qpos = qi * block_q + jax.lax.broadcasted_iota(jnp.int32, s.shape, 0)
    kpos = ki * block_k + jax.lax.broadcasted_iota(jnp.int32, s.shape, 1)
    mask = kpos < seq_len_k            # kv padding
    if causal:
        mask = jnp.logical_and(mask, qpos >= kpos)
    s = jnp.where(mask, s, NEG_INF)

    m_prev = m_scr[:]                  # [block_q, 1]
    m_new = jnp.maximum(m_prev, jnp.max(s, axis=1, keepdims=True))
    p = jnp.where(mask, jnp.exp(s - m_new), 0.0)
    alpha = jnp.exp(m_prev - m_new)
    l_new = l_scr[:] * alpha + jnp.sum(p, axis=1, keepdims=True)
    acc = acc_scr[:] * alpha + jax.lax.dot_general(
        p.astype(v.dtype), v, (((1,), (0,)), ((), ())),
        preferred_element_type=jnp.float32)
    m_scr[:] = m_new
    l_scr[:] = l_new
    acc_scr[:] = acc

    @pl.when(ki == num_k_blocks - 1)
    def _finalize():
        o_ref[0] = (acc_scr[:] / jnp.maximum(l_scr[:], 1e-30)).astype(o_ref.dtype)


def _pallas_flash_fwd_impl(q, k, v, causal: bool, block_q: int, block_k: int,
                           interpret: bool):
    """q: [B, Sq, H, D]; k,v: [B, Sk, Hkv, D]."""
    b, sq, h, d = q.shape
    sk, hkv = k.shape[1], k.shape[2]
    rep = h // hkv
    sm_scale = 1.0 / np.sqrt(d)

    pad_q = (-sq) % block_q
    pad_k = (-sk) % block_k
    qp = jnp.pad(q, ((0, 0), (0, pad_q), (0, 0), (0, 0))) if pad_q else q
    kp = jnp.pad(k, ((0, 0), (0, pad_k), (0, 0), (0, 0))) if pad_k else k
    vp = jnp.pad(v, ((0, 0), (0, pad_k), (0, 0), (0, 0))) if pad_k else v

    sq_p, sk_p = qp.shape[1], kp.shape[1]
    # [B*H, S, D] layout: heads fold into the grid's batch dim
    q2 = qp.transpose(0, 2, 1, 3).reshape(b * h, sq_p, d)
    k2 = kp.transpose(0, 2, 1, 3).reshape(b * hkv, sk_p, d)
    v2 = vp.transpose(0, 2, 1, 3).reshape(b * hkv, sk_p, d)

    nq, nk = sq_p // block_q, sk_p // block_k
    grid = (b * h, nq, nk)

    out = pl.pallas_call(
        functools.partial(_flash_kernel, sm_scale=sm_scale, causal=causal,
                          block_q=block_q, block_k=block_k, num_k_blocks=nk,
                          seq_len_k=sk),
        grid=grid,
        in_specs=[
            pl.BlockSpec((1, block_q, d), lambda bh, i, j: (bh, i, 0)),
            pl.BlockSpec((1, block_k, d),
                         lambda bh, i, j, rep=rep: (bh // rep, j, 0)),
            pl.BlockSpec((1, block_k, d),
                         lambda bh, i, j, rep=rep: (bh // rep, j, 0)),
        ],
        out_specs=pl.BlockSpec((1, block_q, d), lambda bh, i, j: (bh, i, 0)),
        out_shape=jax.ShapeDtypeStruct((b * h, sq_p, d), q.dtype),
        scratch_shapes=[
            pltpu.VMEM((block_q, 1), jnp.float32),
            pltpu.VMEM((block_q, 1), jnp.float32),
            pltpu.VMEM((block_q, d), jnp.float32),
        ],
        interpret=interpret,
    )(q2, k2, v2)

    out = out.reshape(b, h, sq_p, d).transpose(0, 2, 1, 3)
    return out[:, :sq]


@functools.partial(jax.custom_vjp, nondiff_argnums=(3, 4, 5, 6))
def pallas_flash_attention(q, k, v, causal: bool = True, block_q: int = 256,
                           block_k: int = 256, interpret: bool = False):
    """Flash attention with a Pallas forward and flash-recompute backward.
    ``interpret=True`` runs the kernel in interpreter mode (CPU CI)."""
    return _pallas_flash_fwd_impl(q, k, v, causal, block_q, block_k, interpret)


def _fwd(q, k, v, causal, block_q, block_k, interpret):
    out = _pallas_flash_fwd_impl(q, k, v, causal, block_q, block_k, interpret)
    return out, (q, k, v)


def _bwd(causal, block_q, block_k, interpret, res, g):
    q, k, v = res
    # flash-style recompute through the blockwise lax implementation
    _, vjp_fn = jax.vjp(
        lambda q_, k_, v_: blockwise_reference(
            q_, k_, v_, causal=causal,
            block_q=min(block_q, q.shape[1]), block_k=min(block_k, k.shape[1])),
        q, k, v)
    return vjp_fn(g)


pallas_flash_attention.defvjp(_fwd, _bwd)


def flash_attention_auto(q, k, v, causal: bool = True):
    """Dispatch: Pallas kernel on TPU, interpret/blockwise elsewhere."""
    on_tpu = jax.default_backend() == "tpu"
    if on_tpu:
        return pallas_flash_attention(q, k, v, causal)
    return blockwise_reference(q, k, v, causal=causal)
