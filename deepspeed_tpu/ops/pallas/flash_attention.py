"""Pallas flash-attention kernels (forward + backward).

Reference analog: the fused attention CUDA kernels
(``csrc/transformer/inference/csrc/softmax.cu``, the training transformer kernel
suite ``csrc/transformer/`` fused fwd+bwd, v2 ``blocked_flash``). TPU design:
canonical sequential-grid flash — grid (batch*heads, q_blocks, k_blocks) with the
k dimension innermost (TPU grids execute sequentially, so VMEM scratch accumulators
carry across k steps): online-softmax max/sum/output accumulators in fp32 scratch,
[block_q, block_k] score panels on the MXU, GQA handled by index-mapping q heads
onto shared KV heads (no KV repeat materialized).

Causal block skipping: score blocks entirely above the diagonal are predicated
out with ``pl.when`` — the MXU work for the ~half of blocks that are fully
masked is skipped (the reference's fused kernels get the same effect from their
triangular launch bounds).

Backward: FlashAttention-2 style two-kernel recompute. The forward additionally
emits the per-row logsumexp; backward precomputes ``delta = rowsum(dO * O)``
with XLA, then
- a dQ kernel over grid (B*H, q_blocks, k_blocks) accumulating
  ``dq += ds @ K`` in fp32 VMEM scratch, and
- a dKV kernel over grid (B*Hkv, k_blocks, q_blocks * group) accumulating
  ``dk += ds^T @ Q`` / ``dv += p^T @ dO`` — the GQA group dimension is folded
  into the innermost grid axis so gradients for KV heads shared by several query
  heads accumulate in-kernel (no rep-times-larger intermediate in HBM).
"""

import functools

import jax
import jax.numpy as jnp
import numpy as np
from jax.experimental import pallas as pl
from jax.experimental.pallas import tpu as pltpu

from deepspeed_tpu.ops.flash_attention import flash_attention as blockwise_reference

NEG_INF = -1e30


def _masked_scores(q, k, qi, ki, *, sm_scale, causal, block_q, block_k,
                   seq_len_k, window=None, causal_shift=0,
                   qseg=None, kseg=None):
    """Shared score-panel + mask construction for the forward and both backward
    kernels — keeps their masking numerically locked together. Returns
    (s[bq,bk] fp32 scores, mask[bq,bk] bool: kv-padding AND causal AND
    mistral-style sliding ``window``: token t sees (t-window, t])."""
    s = jax.lax.dot_general(q, k, (((1,), (1,)), ((), ())),
                            preferred_element_type=jnp.float32) * sm_scale
    qpos = qi * block_q + jax.lax.broadcasted_iota(jnp.int32, s.shape, 0)
    kpos = ki * block_k + jax.lax.broadcasted_iota(jnp.int32, s.shape, 1)
    mask = kpos < seq_len_k
    if causal or window is not None:
        # a window implies the causal band (t-window, t] — same contract as
        # attention_reference/_xla_attention. ``causal_shift=1`` is the
        # STRICT band (qpos > kpos): striped ring attention steps where the
        # KV stripe sits one position ahead of the query stripe.
        mask = jnp.logical_and(mask, qpos >= kpos + causal_shift)
    if window is not None:
        mask = jnp.logical_and(mask, kpos > qpos - window)
    if qseg is not None:
        # packed sequences: tokens attend within their segment only
        mask = jnp.logical_and(mask, qseg == kseg.reshape(1, -1))
    return s, mask


def _block_live(qi, ki, *, causal, block_q, block_k, window):
    """Whether a [block_q, block_k] panel can contain any unmasked entry —
    the pl.when skip shared by all three kernels: blocks entirely above the
    causal diagonal AND blocks entirely below the sliding window are dead."""
    live = None
    if causal or window is not None:   # window implies the causal band
        live = ki * block_k <= qi * block_q + block_q - 1
    if window is not None:
        w_live = (ki + 1) * block_k - 1 > qi * block_q - window
        live = jnp.logical_and(live, w_live)
    return live


def _flash_kernel(q_ref, k_ref, v_ref, qs_ref, ks_ref, o_ref, lse_ref,
                  m_scr, l_scr, acc_scr, *,
                  sm_scale, causal, block_q, block_k, num_k_blocks, seq_len_k,
                  window=None, causal_shift=0, has_seg=False):
    ki = pl.program_id(2)
    qi = pl.program_id(1)

    @pl.when(ki == 0)
    def _init():
        m_scr[:] = jnp.full_like(m_scr, NEG_INF)
        l_scr[:] = jnp.zeros_like(l_scr)
        acc_scr[:] = jnp.zeros_like(acc_scr)

    def _compute():
        q = q_ref[0]                       # [block_q, D]
        k = k_ref[0]                       # [block_k, D]
        v = v_ref[0]
        s, mask = _masked_scores(q, k, qi, ki, sm_scale=sm_scale, causal=causal,
                                 block_q=block_q, block_k=block_k,
                                 seq_len_k=seq_len_k, window=window,
                                 causal_shift=causal_shift,
                                 qseg=qs_ref[0] if has_seg else None,
                                 kseg=ks_ref[0] if has_seg else None)
        s = jnp.where(mask, s, NEG_INF)

        m_prev = m_scr[:]                  # [block_q, 1]
        m_new = jnp.maximum(m_prev, jnp.max(s, axis=1, keepdims=True))
        p = jnp.where(mask, jnp.exp(s - m_new), 0.0)
        alpha = jnp.exp(m_prev - m_new)
        l_new = l_scr[:] * alpha + jnp.sum(p, axis=1, keepdims=True)
        acc = acc_scr[:] * alpha + jax.lax.dot_general(
            p.astype(v.dtype), v, (((1,), (0,)), ((), ())),
            preferred_element_type=jnp.float32)
        m_scr[:] = m_new
        l_scr[:] = l_new
        acc_scr[:] = acc

    live = _block_live(qi, ki, causal=causal, block_q=block_q,
                       block_k=block_k, window=window)
    if live is None:
        _compute()
    else:
        pl.when(live)(_compute)

    @pl.when(ki == num_k_blocks - 1)
    def _finalize():
        l = jnp.maximum(l_scr[:], 1e-30)
        o_ref[0] = (acc_scr[:] / l).astype(o_ref.dtype)
        lse_ref[0] = m_scr[:] + jnp.log(l)


def _fold(x):
    """[B, S, H, D] -> [B*H, S, D]."""
    b, s, h, d = x.shape
    return x.transpose(0, 2, 1, 3).reshape(b * h, s, d)


def _pad_seq(x, block):
    pad = (-x.shape[1]) % block
    return jnp.pad(x, ((0, 0), (0, pad), (0, 0), (0, 0))) if pad else x


def _unfold(x, b, h, s):
    return x.reshape(b, h, x.shape[1], x.shape[2]).transpose(0, 2, 1, 3)[:, :s]


def _seg_operands(segment_ids, sq, sk, block_q, block_k):
    """Padded [B, S, 1] int32 segment arrays (+has_seg). ``segment_ids`` is
    [B, S] shared by q and k, or a ``(q_ids [B, Sq], k_ids [B, Sk])`` pair
    (ring attention: the rotating KV block carries different ids than the
    local queries). Padding uses -1 on the k side so padded keys mismatch
    every real segment (they are also masked by seq_len_k)."""
    if segment_ids is None:
        return (jnp.zeros((1, block_q, 1), jnp.int32),
                jnp.zeros((1, block_k, 1), jnp.int32), False)
    if isinstance(segment_ids, tuple):
        q_ids, k_ids = segment_ids
    else:
        q_ids = k_ids = segment_ids
    qs = jnp.pad(jnp.asarray(q_ids, jnp.int32),
                 ((0, 0), (0, (-sq) % block_q)),
                 constant_values=-1)[..., None]
    ks = jnp.pad(jnp.asarray(k_ids, jnp.int32)[:, :sk],
                 ((0, 0), (0, (-sk) % block_k)),
                 constant_values=-1)[..., None]
    return qs, ks, True


def _seg_specs(has_seg, h_of, block_q, block_k, q_major=True):
    """Block specs for the (q_seg, k_seg) operands: indexed by BATCH
    (grid dim0 // heads). ``q_major``: grid is (g, q_blocks, k_blocks);
    otherwise (g, k_blocks, q_steps) — the dkv layout."""
    if not has_seg:
        z = lambda bh, i, j: (0, 0, 0)
        return [pl.BlockSpec((1, block_q, 1), z),
                pl.BlockSpec((1, block_k, 1), z)]
    if q_major:
        return [pl.BlockSpec((1, block_q, 1),
                             lambda bh, i, j: (h_of(bh), i, 0)),
                pl.BlockSpec((1, block_k, 1),
                             lambda bh, i, j: (h_of(bh), j, 0))]
    return [pl.BlockSpec((1, block_q, 1),
                         lambda bh, i, j: (h_of(bh), j, 0)),
            pl.BlockSpec((1, block_k, 1),
                         lambda bh, i, j: (h_of(bh), i, 0))]


def _pallas_flash_fwd_impl(q, k, v, causal: bool, block_q: int, block_k: int,
                           interpret: bool, window=None, causal_shift=0,
                           segment_ids=None):
    """q: [B, Sq, H, D]; k,v: [B, Sk, Hkv, D] -> (out, lse[B*H, Sq_padded])."""
    b, sq, h, d = q.shape
    sk, hkv = k.shape[1], k.shape[2]
    rep = h // hkv
    sm_scale = 1.0 / np.sqrt(d)

    qp, kp, vp = _pad_seq(q, block_q), _pad_seq(k, block_k), _pad_seq(v, block_k)
    sq_p, sk_p = qp.shape[1], kp.shape[1]
    q2, k2, v2 = _fold(qp), _fold(kp), _fold(vp)
    qs, ks, has_seg = _seg_operands(segment_ids, sq, sk, block_q, block_k)

    nq, nk = sq_p // block_q, sk_p // block_k
    grid = (b * h, nq, nk)

    out, lse = pl.pallas_call(
        functools.partial(_flash_kernel, sm_scale=sm_scale, causal=causal,
                          block_q=block_q, block_k=block_k, num_k_blocks=nk,
                          seq_len_k=sk, window=window,
                          causal_shift=causal_shift, has_seg=has_seg),
        grid=grid,
        in_specs=[
            pl.BlockSpec((1, block_q, d), lambda bh, i, j: (bh, i, 0)),
            pl.BlockSpec((1, block_k, d),
                         lambda bh, i, j, rep=rep: (bh // rep, j, 0)),
            pl.BlockSpec((1, block_k, d),
                         lambda bh, i, j, rep=rep: (bh // rep, j, 0)),
        ] + _seg_specs(has_seg, lambda bh, h=h: bh // h, block_q, block_k),
        out_specs=[
            pl.BlockSpec((1, block_q, d), lambda bh, i, j: (bh, i, 0)),
            # rank-3 [B*H, S, 1]: TPU blocks need sublane %8 == 0 and lane
            # equal to the array dim — a rank-2 (1, block_q) block is rejected
            pl.BlockSpec((1, block_q, 1), lambda bh, i, j: (bh, i, 0)),
        ],
        out_shape=[
            jax.ShapeDtypeStruct((b * h, sq_p, d), q.dtype),
            jax.ShapeDtypeStruct((b * h, sq_p, 1), jnp.float32),
        ],
        scratch_shapes=[
            pltpu.VMEM((block_q, 1), jnp.float32),
            pltpu.VMEM((block_q, 1), jnp.float32),
            pltpu.VMEM((block_q, d), jnp.float32),
        ],
        interpret=interpret,
    )(q2, k2, v2, qs, ks)

    return _unfold(out, b, h, sq), lse


def _dq_kernel(q_ref, k_ref, v_ref, qs_ref, ks_ref, do_ref, lse_ref,
               delta_ref, dq_ref, dq_scr, *,
               sm_scale, causal, block_q, block_k, num_k_blocks, seq_len_k,
               window=None, causal_shift=0, has_seg=False):
    ki = pl.program_id(2)
    qi = pl.program_id(1)

    @pl.when(ki == 0)
    def _init():
        dq_scr[:] = jnp.zeros_like(dq_scr)

    def _compute():
        q, k, v, do = q_ref[0], k_ref[0], v_ref[0], do_ref[0]
        lse = lse_ref[0]                   # [block_q, 1]
        delta = delta_ref[0]               # [block_q, 1]
        s, mask = _masked_scores(q, k, qi, ki, sm_scale=sm_scale, causal=causal,
                                 block_q=block_q, block_k=block_k,
                                 seq_len_k=seq_len_k, window=window,
                                 causal_shift=causal_shift,
                                 qseg=qs_ref[0] if has_seg else None,
                                 kseg=ks_ref[0] if has_seg else None)
        p = jnp.where(mask, jnp.exp(s - lse), 0.0)
        dp = jax.lax.dot_general(do, v, (((1,), (1,)), ((), ())),
                                 preferred_element_type=jnp.float32)
        ds = p * (dp - delta) * sm_scale
        dq_scr[:] = dq_scr[:] + jax.lax.dot_general(
            ds.astype(k.dtype), k, (((1,), (0,)), ((), ())),
            preferred_element_type=jnp.float32)

    live = _block_live(qi, ki, causal=causal, block_q=block_q,
                       block_k=block_k, window=window)
    if live is None:
        _compute()
    else:
        pl.when(live)(_compute)

    @pl.when(ki == num_k_blocks - 1)
    def _finalize():
        dq_ref[0] = dq_scr[:].astype(dq_ref.dtype)


def _dkv_kernel(q_ref, k_ref, v_ref, qs_ref, ks_ref, do_ref, lse_ref,
                delta_ref, dk_ref, dv_ref,
                dk_scr, dv_scr, *, sm_scale, causal, block_q, block_k,
                num_q_blocks, num_q_steps, seq_len_k, window=None,
                causal_shift=0, has_seg=False):
    j = pl.program_id(2)                   # folded (group, q_block) index
    ki = pl.program_id(1)
    qi = j % num_q_blocks

    @pl.when(j == 0)
    def _init():
        dk_scr[:] = jnp.zeros_like(dk_scr)
        dv_scr[:] = jnp.zeros_like(dv_scr)

    def _compute():
        q, k, v, do = q_ref[0], k_ref[0], v_ref[0], do_ref[0]
        lse = lse_ref[0]                   # [block_q, 1]
        delta = delta_ref[0]
        s, mask = _masked_scores(q, k, qi, ki, sm_scale=sm_scale, causal=causal,
                                 block_q=block_q, block_k=block_k,
                                 seq_len_k=seq_len_k, window=window,
                                 causal_shift=causal_shift,
                                 qseg=qs_ref[0] if has_seg else None,
                                 kseg=ks_ref[0] if has_seg else None)
        p = jnp.where(mask, jnp.exp(s - lse), 0.0)  # [bq, bk]
        dv_scr[:] = dv_scr[:] + jax.lax.dot_general(
            p.astype(do.dtype), do, (((0,), (0,)), ((), ())),
            preferred_element_type=jnp.float32)
        dp = jax.lax.dot_general(do, v, (((1,), (1,)), ((), ())),
                                 preferred_element_type=jnp.float32)
        ds = p * (dp - delta) * sm_scale
        dk_scr[:] = dk_scr[:] + jax.lax.dot_general(
            ds.astype(q.dtype), q, (((0,), (0,)), ((), ())),
            preferred_element_type=jnp.float32)

    live = _block_live(qi, ki, causal=causal, block_q=block_q,
                       block_k=block_k, window=window)
    if live is None:
        _compute()
    else:
        pl.when(live)(_compute)

    @pl.when(j == num_q_steps - 1)
    def _finalize():
        dk_ref[0] = dk_scr[:].astype(dk_ref.dtype)
        dv_ref[0] = dv_scr[:].astype(dv_ref.dtype)


def _pallas_flash_bwd_impl(q, k, v, out, lse, g, causal, block_q, block_k,
                           interpret, window=None, causal_shift=0,
                           segment_ids=None):
    b, sq, h, d = q.shape
    sk, hkv = k.shape[1], k.shape[2]
    rep = h // hkv
    sm_scale = 1.0 / np.sqrt(d)

    qp, op, gp = (_pad_seq(a, block_q) for a in (q, out, g))
    kp, vp = _pad_seq(k, block_k), _pad_seq(v, block_k)

    sq_p, sk_p = qp.shape[1], kp.shape[1]
    q2, k2, v2 = _fold(qp), _fold(kp), _fold(vp)
    do2, o2 = _fold(gp), _fold(op)
    qs, ks, has_seg = _seg_operands(segment_ids, sq, sk, block_q, block_k)
    delta = jnp.sum(do2.astype(jnp.float32) * o2.astype(jnp.float32),
                    axis=-1, keepdims=True)

    nq, nk = sq_p // block_q, sk_p // block_k

    dq = pl.pallas_call(
        functools.partial(_dq_kernel, sm_scale=sm_scale, causal=causal,
                          block_q=block_q, block_k=block_k, num_k_blocks=nk,
                          seq_len_k=sk, window=window,
                          causal_shift=causal_shift, has_seg=has_seg),
        grid=(b * h, nq, nk),
        in_specs=[
            pl.BlockSpec((1, block_q, d), lambda bh, i, j: (bh, i, 0)),
            pl.BlockSpec((1, block_k, d),
                         lambda bh, i, j, rep=rep: (bh // rep, j, 0)),
            pl.BlockSpec((1, block_k, d),
                         lambda bh, i, j, rep=rep: (bh // rep, j, 0)),
        ] + _seg_specs(has_seg, lambda bh, h=h: bh // h, block_q, block_k) + [
            pl.BlockSpec((1, block_q, d), lambda bh, i, j: (bh, i, 0)),
            pl.BlockSpec((1, block_q, 1), lambda bh, i, j: (bh, i, 0)),
            pl.BlockSpec((1, block_q, 1), lambda bh, i, j: (bh, i, 0)),
        ],
        out_specs=pl.BlockSpec((1, block_q, d), lambda bh, i, j: (bh, i, 0)),
        out_shape=jax.ShapeDtypeStruct((b * h, sq_p, d), q.dtype),
        scratch_shapes=[pltpu.VMEM((block_q, d), jnp.float32)],
        interpret=interpret,
    )(q2, k2, v2, qs, ks, do2, lse, delta)

    # dKV: GQA group folded into the innermost grid axis → in-kernel accumulation
    nsteps = nq * rep
    dk, dv = pl.pallas_call(
        functools.partial(_dkv_kernel, sm_scale=sm_scale, causal=causal,
                          block_q=block_q, block_k=block_k, num_q_blocks=nq,
                          num_q_steps=nsteps, seq_len_k=sk, window=window,
                          causal_shift=causal_shift, has_seg=has_seg),
        grid=(b * hkv, nk, nsteps),
        in_specs=[
            pl.BlockSpec((1, block_q, d),
                         lambda bh, i, j, rep=rep, nq=nq:
                         (bh * rep + j // nq, j % nq, 0)),
            pl.BlockSpec((1, block_k, d), lambda bh, i, j: (bh, i, 0)),
            pl.BlockSpec((1, block_k, d), lambda bh, i, j: (bh, i, 0)),
        ] + ([
            # seg operands: q block j%nq (batch = bh // hkv), k block i
            pl.BlockSpec((1, block_q, 1),
                         lambda bh, i, j, hkv=hkv, nq=nq:
                         (bh // hkv, j % nq, 0)),
            pl.BlockSpec((1, block_k, 1),
                         lambda bh, i, j, hkv=hkv: (bh // hkv, i, 0)),
        ] if has_seg else [
            pl.BlockSpec((1, block_q, 1), lambda bh, i, j: (0, 0, 0)),
            pl.BlockSpec((1, block_k, 1), lambda bh, i, j: (0, 0, 0)),
        ]) + [
            pl.BlockSpec((1, block_q, d),
                         lambda bh, i, j, rep=rep, nq=nq:
                         (bh * rep + j // nq, j % nq, 0)),
            pl.BlockSpec((1, block_q, 1),
                         lambda bh, i, j, rep=rep, nq=nq:
                         (bh * rep + j // nq, j % nq, 0)),
            pl.BlockSpec((1, block_q, 1),
                         lambda bh, i, j, rep=rep, nq=nq:
                         (bh * rep + j // nq, j % nq, 0)),
        ],
        out_specs=[
            pl.BlockSpec((1, block_k, d), lambda bh, i, j: (bh, i, 0)),
            pl.BlockSpec((1, block_k, d), lambda bh, i, j: (bh, i, 0)),
        ],
        out_shape=[
            jax.ShapeDtypeStruct((b * hkv, sk_p, d), k.dtype),
            jax.ShapeDtypeStruct((b * hkv, sk_p, d), v.dtype),
        ],
        scratch_shapes=[
            pltpu.VMEM((block_k, d), jnp.float32),
            pltpu.VMEM((block_k, d), jnp.float32),
        ],
        interpret=interpret,
    )(q2, k2, v2, qs, ks, do2, lse, delta)

    return (_unfold(dq, b, h, sq), _unfold(dk, b, hkv, sk),
            _unfold(dv, b, hkv, sk))


@functools.partial(jax.custom_vjp, nondiff_argnums=(3, 4, 5, 6, 7))
def pallas_flash_attention(q, k, v, causal: bool = True, block_q: int = 256,
                           block_k: int = 256, interpret: bool = False,
                           window=None, segment_ids=None):
    """Flash attention with Pallas forward and backward kernels.
    ``interpret=True`` runs the kernels in interpreter mode (CPU CI);
    ``window`` adds mistral-style sliding-window masking with below-window
    block skipping (long-context windowed cost is O(S*window));
    ``segment_ids`` [B, S] masks packed sequences in-kernel (tokens attend
    within their segment only)."""
    out, _ = _pallas_flash_fwd_impl(q, k, v, causal, block_q, block_k,
                                    interpret, window,
                                    segment_ids=segment_ids)
    return out


def _fwd(q, k, v, causal, block_q, block_k, interpret, window, segment_ids):
    out, lse = _pallas_flash_fwd_impl(q, k, v, causal, block_q, block_k,
                                      interpret, window,
                                      segment_ids=segment_ids)
    return out, (q, k, v, out, lse, segment_ids)


def _bwd(causal, block_q, block_k, interpret, window, res, g):
    q, k, v, out, lse, segment_ids = res
    dq, dk, dv = _pallas_flash_bwd_impl(q, k, v, out, lse, g, causal, block_q,
                                        block_k, interpret, window,
                                        segment_ids=segment_ids)
    return dq, dk, dv, None


pallas_flash_attention.defvjp(_fwd, _bwd)


def flash_attention_auto(q, k, v, causal: bool = True, window=None,
                         segment_ids=None):
    """Dispatch: Pallas kernel on TPU, interpret/blockwise elsewhere."""
    on_tpu = jax.default_backend() == "tpu"
    if on_tpu:
        # bigger blocks amortize grid overhead (measured on v5e at s=2048,
        # d=128: fwd+bwd 10.9ms @256 / 4.9ms @512 / 4.6ms @1024); 1024-blocks
        # fit VMEM up to d=128 (acc scratch 1024*128*4B = 0.5MB per buffer)
        d = q.shape[-1]
        for blk in ((1024, 512, 256) if d <= 128 else (512, 256)):
            if q.shape[1] % blk == 0 and k.shape[1] % blk == 0:
                return pallas_flash_attention(q, k, v, causal, blk, blk,
                                              False, window, segment_ids)
        return pallas_flash_attention(q, k, v, causal, 256, 256, False,
                                      window, segment_ids)
    if window is not None or segment_ids is not None:
        from deepspeed_tpu.ops.flash_attention import attention_reference
        return attention_reference(q, k, v, causal=causal, window=window,
                                   segment_ids=segment_ids)
    return blockwise_reference(q, k, v, causal=causal)
