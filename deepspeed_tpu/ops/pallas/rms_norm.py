"""Fused RMSNorm Pallas kernel with analytical backward.

Reference analog: ``csrc/transformer/inference/csrc/rms_norm.cu`` (fused rms_norm
+ residual-add variants). One VMEM pass per row block: fp32 mean-of-squares,
rsqrt, scale — what the CUDA kernel does with a block reduction, here on the VPU.
"""

import functools

import jax
import jax.numpy as jnp
from jax.experimental import pallas as pl


def _rms_fwd_kernel(x_ref, scale_ref, o_ref, *, eps):
    x = x_ref[:].astype(jnp.float32)
    var = jnp.mean(jnp.square(x), axis=-1, keepdims=True)
    inv = jax.lax.rsqrt(var + eps)
    o_ref[:] = (x * inv * scale_ref[:].astype(jnp.float32)).astype(o_ref.dtype)


def _rms_impl(x, scale, eps, block_rows, interpret):
    orig_shape = x.shape
    d = orig_shape[-1]
    x2 = x.reshape(-1, d)
    n = x2.shape[0]
    pad = (-n) % block_rows
    if pad:
        x2 = jnp.pad(x2, ((0, pad), (0, 0)))
    out = pl.pallas_call(
        functools.partial(_rms_fwd_kernel, eps=eps),
        grid=(x2.shape[0] // block_rows,),
        in_specs=[
            pl.BlockSpec((block_rows, d), lambda i: (i, 0)),
            pl.BlockSpec((d,), lambda i: (0,)),
        ],
        out_specs=pl.BlockSpec((block_rows, d), lambda i: (i, 0)),
        out_shape=jax.ShapeDtypeStruct(x2.shape, x.dtype),
        interpret=interpret,
    )(x2, scale)
    return out[:n].reshape(orig_shape)


@functools.partial(jax.custom_vjp, nondiff_argnums=(2, 3, 4))
def pallas_rms_norm(x, scale, eps: float = 1e-5, block_rows: int = 256,
                    interpret: bool = False):
    return _rms_impl(x, scale, eps, block_rows, interpret)


def _fwd(x, scale, eps, block_rows, interpret):
    return _rms_impl(x, scale, eps, block_rows, interpret), (x, scale)


def _bwd(eps, block_rows, interpret, res, g):
    x, scale = res
    x32 = x.astype(jnp.float32)
    g32 = g.astype(jnp.float32)
    s32 = scale.astype(jnp.float32)
    var = jnp.mean(jnp.square(x32), axis=-1, keepdims=True)
    inv = jax.lax.rsqrt(var + eps)
    xhat = x32 * inv
    d = x.shape[-1]
    # d/dx of x*inv(x)*s with inv = (mean(x^2)+eps)^-1/2
    gs = g32 * s32
    dx = inv * (gs - xhat * jnp.mean(gs * xhat, axis=-1, keepdims=True))
    dscale = jnp.sum((g32 * xhat).reshape(-1, d), axis=0)
    return dx.astype(x.dtype), dscale.astype(scale.dtype)


pallas_rms_norm.defvjp(_fwd, _bwd)


def rms_norm_reference(x, scale, eps: float = 1e-5):
    x32 = x.astype(jnp.float32)
    var = jnp.mean(jnp.square(x32), axis=-1, keepdims=True)
    return (x32 * jax.lax.rsqrt(var + eps) * scale.astype(jnp.float32)).astype(x.dtype)
