"""FP8 (e4m3/e5m2) quantization Pallas kernels + selective gather.

Reference analog: ``csrc/fp_quantizer/{fp_quantize.cu,fp_quantize.cpp}`` (FP8/
FP6/FP12 group quantize/dequantize with ``selective_dequantize`` for gathering
a row subset) and ``deepspeed/ops/fp_quantizer/fp8_gemm.py``.

TPU shape: native ``float8_e4m3fn`` / ``float8_e5m2`` storage — the MXU and
XLA understand these dtypes directly, so "dequantize" is a cast fused into the
consumer matmul (or a future native fp8 GEMM keeps the operands in fp8). Group
scaling is per-row (last-dim groups) symmetric fp32, like the int8 kernels in
``quant.py``; usable by qwZ-style quantized gathers wherever int8's 256 levels
are overkill and fp8's dynamic range fits better.
"""

import functools

import jax
import jax.numpy as jnp

from deepspeed_tpu.ops.pallas.quant import rowwise_pallas_op

# max finite magnitude per format
FP8_FORMATS = {
    "e4m3": (jnp.float8_e4m3fn, 448.0),
    "e5m2": (jnp.float8_e5m2, 57344.0),
}


def _fp8_quant_kernel(x_ref, q_ref, s_ref, *, fmax):
    x = x_ref[:].astype(jnp.float32)
    absmax = jnp.max(jnp.abs(x), axis=-1, keepdims=True)
    scale = jnp.maximum(absmax / fmax, 1e-12)
    q_ref[:] = (x / scale).astype(q_ref.dtype)
    s_ref[:] = scale


def _fp8_dequant_kernel(q_ref, s_ref, o_ref):
    o_ref[:] = (q_ref[:].astype(jnp.float32) * s_ref[:]).astype(o_ref.dtype)


@functools.partial(jax.jit, static_argnames=("fmt", "block_rows", "interpret"))
def quantize_fp8(x, fmt: str = "e4m3", block_rows: int = 256,
                 interpret: bool = None):
    """x: [..., D] -> (fp8 values [..., D], fp32 scales [..., 1]) per-row."""
    dtype, fmax = FP8_FORMATS[fmt]
    shape = x.shape
    d = shape[-1]
    qv, sv = rowwise_pallas_op(
        functools.partial(_fp8_quant_kernel, fmax=fmax), [x.reshape(-1, d)],
        [(d, dtype), (1, jnp.float32)], block_rows, interpret)
    return qv.reshape(shape), sv.reshape(*shape[:-1], 1)


@functools.partial(jax.jit, static_argnames=("block_rows", "interpret", "dtype"))
def dequantize_fp8(q, scales, dtype=jnp.bfloat16, block_rows: int = 256,
                   interpret: bool = None):
    shape = q.shape
    d = shape[-1]
    (out,) = rowwise_pallas_op(
        _fp8_dequant_kernel, [q.reshape(-1, d), scales.reshape(-1, 1)],
        [(d, dtype)], block_rows, interpret)
    return out.reshape(shape)


def selective_dequantize_fp8(q, scales, rows, dtype=jnp.bfloat16,
                             interpret: bool = None):
    """Gather a subset of quantized rows and dequantize only those
    (reference: ``selective_dequantize`` in fp_quantize.cu — used to fetch
    sub-slices of a quantized parameter without expanding the whole tensor).
    q: [N, D]; scales: [N, 1]; rows: [K] int32 -> [K, D] in ``dtype``."""
    qg = jnp.take(q, rows, axis=0)
    sg = jnp.take(scales, rows, axis=0)
    return dequantize_fp8(qg, sg, dtype=dtype, interpret=interpret)


def quantized_all_gather_fp8(x, axis_name: str, fmt: str = "e4m3"):
    """qwZ-style collective with fp8 wire format (1 byte/elem like int8 but
    wider dynamic range per group). Usable inside shard_map."""
    q, s = quantize_fp8(x, fmt=fmt)
    qg = jax.lax.all_gather(q, axis_name, axis=0, tiled=True)
    sg = jax.lax.all_gather(s, axis_name, axis=0, tiled=True)
    return dequantize_fp8(qg, sg, dtype=x.dtype)
# (collective shape mirrors quant.quantized_all_gather — int8 variant)


def fp8_matmul(a, b_q, b_scales, preferred=jnp.float32):
    """Matmul against an fp8-quantized weight: the dequant scale-multiply is
    applied to the fp32 accumulator per output column group (reference:
    ops/fp_quantizer/fp8_gemm.py matmul_fp8). a: [M, K]; b_q: [K, N] fp8 with
    per-ROW (K) scales [K, 1] — scales fold into ``a`` before the MXU matmul so
    the fp8 operand feeds the MXU directly."""
    # fold the per-K scales into the activation side: a' = a * s_k
    a_scaled = a.astype(jnp.float32) * b_scales.reshape(1, -1)
    return jax.lax.dot_general(
        a_scaled.astype(jnp.bfloat16), b_q.astype(jnp.bfloat16),
        (((1,), (0,)), ((), ())), preferred_element_type=preferred)


# ---------------------------------------------------------------------------
# True fp8 GEMM: operands stay fp8 INTO dot_general, scales fused as a
# rank-1 epilogue on the fp32 accumulator
# ---------------------------------------------------------------------------
def fp8_gemm_quantize(a, b, fmt: str = "e4m3"):
    """Quantize a GEMM pair for :func:`fp8_gemm`: ``a`` [M, K] per-row
    (per-M) scales, ``b`` [K, N] per-COLUMN (per-N) scales — both scale sets
    then apply on the OUTPUT as the rank-1 epilogue ``s_m ⊗ s_n``, so the
    dot itself runs entirely in fp8."""
    a_q, s_m = quantize_fp8(a, fmt=fmt)
    bt_q, s_n = quantize_fp8(b.T, fmt=fmt)       # per-column groups of b
    return a_q, s_m, bt_q.T, s_n


def fp8_gemm(a_q, s_m, b_q, s_n, out_dtype=jnp.bfloat16):
    """y = dequant(a_q) @ dequant(b_q) with the operands staying fp8 through
    ``dot_general`` (reference: ``ops/fp_quantizer/fp8_gemm.py`` — fp8
    operands into the tensor-core GEMM with fused scales). The fp32
    accumulator is scaled by the rank-1 outer product of the row/column
    scales in the epilogue; XLA keeps native-fp8 dots where the hardware has
    them and upcasts inside the fused op elsewhere — either way no
    dequantized copy of the operands ever materializes in HBM.

    a_q: [M, K] fp8; s_m: [M, 1] fp32; b_q: [K, N] fp8; s_n: [N, 1] fp32.
    """
    acc = jax.lax.dot_general(a_q, b_q, (((1,), (0,)), ((), ())),
                              preferred_element_type=jnp.float32)
    return (acc * s_m.reshape(-1, 1) * s_n.reshape(1, -1)).astype(out_dtype)
