"""FP8 (e4m3/e5m2) quantization Pallas kernels + selective gather.

Reference analog: ``csrc/fp_quantizer/{fp_quantize.cu,fp_quantize.cpp}`` (FP8/
FP6/FP12 group quantize/dequantize with ``selective_dequantize`` for gathering
a row subset) and ``deepspeed/ops/fp_quantizer/fp8_gemm.py``.

TPU shape: native ``float8_e4m3fn`` / ``float8_e5m2`` storage — the MXU and
XLA understand these dtypes directly, so "dequantize" is a cast fused into the
consumer matmul (or a future native fp8 GEMM keeps the operands in fp8). Group
scaling is per-row (last-dim groups) symmetric fp32, like the int8 kernels in
``quant.py``; usable by qwZ-style quantized gathers wherever int8's 256 levels
are overkill and fp8's dynamic range fits better.
"""

import functools

import jax
import jax.numpy as jnp

from deepspeed_tpu.ops.pallas.quant import rowwise_pallas_op

# max finite magnitude per format
FP8_FORMATS = {
    "e4m3": (jnp.float8_e4m3fn, 448.0),
    "e5m2": (jnp.float8_e5m2, 57344.0),
}


def _fp8_quant_kernel(x_ref, q_ref, s_ref, *, fmax):
    x = x_ref[:].astype(jnp.float32)
    absmax = jnp.max(jnp.abs(x), axis=-1, keepdims=True)
    scale = jnp.maximum(absmax / fmax, 1e-12)
    q_ref[:] = (x / scale).astype(q_ref.dtype)
    s_ref[:] = scale


def _fp8_dequant_kernel(q_ref, s_ref, o_ref):
    o_ref[:] = (q_ref[:].astype(jnp.float32) * s_ref[:]).astype(o_ref.dtype)


@functools.partial(jax.jit, static_argnames=("fmt", "block_rows", "interpret"))
def quantize_fp8(x, fmt: str = "e4m3", block_rows: int = 256,
                 interpret: bool = None):
    """x: [..., D] -> (fp8 values [..., D], fp32 scales [..., 1]) per-row."""
    dtype, fmax = FP8_FORMATS[fmt]
    shape = x.shape
    d = shape[-1]
    qv, sv = rowwise_pallas_op(
        functools.partial(_fp8_quant_kernel, fmax=fmax), [x.reshape(-1, d)],
        [(d, dtype), (1, jnp.float32)], block_rows, interpret)
    return qv.reshape(shape), sv.reshape(*shape[:-1], 1)


@functools.partial(jax.jit, static_argnames=("block_rows", "interpret", "dtype"))
def dequantize_fp8(q, scales, dtype=jnp.bfloat16, block_rows: int = 256,
                   interpret: bool = None):
    shape = q.shape
    d = shape[-1]
    (out,) = rowwise_pallas_op(
        _fp8_dequant_kernel, [q.reshape(-1, d), scales.reshape(-1, 1)],
        [(d, dtype)], block_rows, interpret)
    return out.reshape(shape)


def selective_dequantize_fp8(q, scales, rows, dtype=jnp.bfloat16,
                             interpret: bool = None):
    """Gather a subset of quantized rows and dequantize only those
    (reference: ``selective_dequantize`` in fp_quantize.cu — used to fetch
    sub-slices of a quantized parameter without expanding the whole tensor).
    q: [N, D]; scales: [N, 1]; rows: [K] int32 -> [K, D] in ``dtype``."""
    qg = jnp.take(q, rows, axis=0)
    sg = jnp.take(scales, rows, axis=0)
    return dequantize_fp8(qg, sg, dtype=dtype, interpret=interpret)


def quantized_all_gather_fp8(x, axis_name: str, fmt: str = "e4m3"):
    """qwZ-style collective with fp8 wire format (1 byte/elem like int8 but
    wider dynamic range per group). Usable inside shard_map."""
    q, s = quantize_fp8(x, fmt=fmt)
    qg = jax.lax.all_gather(q, axis_name, axis=0, tiled=True)
    sg = jax.lax.all_gather(s, axis_name, axis=0, tiled=True)
    return dequantize_fp8(qg, sg, dtype=x.dtype)
# (collective shape mirrors quant.quantized_all_gather — int8 variant)


def fp8_matmul(a, b_q, b_scales, preferred=jnp.float32):
    """Matmul against an fp8-quantized weight: the dequant scale-multiply is
    applied to the fp32 accumulator per output column group (reference:
    ops/fp_quantizer/fp8_gemm.py matmul_fp8). a: [M, K]; b_q: [K, N] fp8 with
    per-ROW (K) scales [K, 1] — scales fold into ``a`` before the MXU matmul so
    the fp8 operand feeds the MXU directly."""
    # fold the per-K scales into the activation side: a' = a * s_k
    a_scaled = a.astype(jnp.float32) * b_scales.reshape(1, -1)
    return jax.lax.dot_general(
        a_scaled.astype(jnp.bfloat16), b_q.astype(jnp.bfloat16),
        (((1,), (0,)), ((), ())), preferred_element_type=preferred)
