"""Pallas paged (blocked) attention over the serving KV cache.

Reference analog: ``deepspeed/inference/v2/kernels/ragged_ops/blocked_flash``
(flash attention over paged KV) + ``atom_builder`` (ragged batch splitting).

TPU design: the block table rides as a **scalar-prefetch** argument
(``pltpu.PrefetchScalarGridSpec``), so the BlockSpec index map dereferences it
and the kernel DMAs each sequence's KV pages *directly out of the paged pool in
HBM* — the gather fallback's [B, MB*bs, H, d] context re-materialization (plus
rep-times KV expansion for GQA) never exists. Grid (batch, kv_head, page) with
the page dimension innermost: online-softmax accumulators live in VMEM scratch
and carry across pages, flash-style.

GQA/T folding: the kernel processes one KV head per grid cell; the q rows for
that cell are the (group × chunk) fold — ``rep`` query heads that share the KV
head times ``T`` chunk tokens — zero-padded to a multiple of 8 sublanes. Decode
is T=1; prefill is B=1, T=chunk. Pages entirely above the causal horizon (or
entirely below the sliding window) are predicated out with ``pl.when``.

Cache layout is head-major ``[Hkv, num_blocks, block_size, d]`` so one page of
one KV head is a contiguous ``(block_size, d)`` tile (legal TPU block shape).
"""

import functools

import jax
import jax.numpy as jnp
import numpy as np
from jax.experimental import pallas as pl
from jax.experimental.pallas import tpu as pltpu

NEG_INF = -1e30


def _paged_kernel(*refs, block_size, num_pages, chunk, rep,
                  window, softcap, num_blocks=0):
    if num_blocks:      # fp8 pages with per-(head, page) scales prefetched
        (tables_ref, start_ref, kscale_ref, vscale_ref, q_ref, k_ref, v_ref,
         o_ref, m_scr, l_scr, acc_scr) = refs
    else:
        (tables_ref, start_ref, q_ref, k_ref, v_ref, o_ref, m_scr, l_scr,
         acc_scr) = refs
        kscale_ref = vscale_ref = None
    b = pl.program_id(0)
    hi = pl.program_id(1)
    j = pl.program_id(2)

    @pl.when(j == 0)
    def _init():
        m_scr[:] = jnp.full_like(m_scr, NEG_INF)
        l_scr[:] = jnp.zeros_like(l_scr)
        acc_scr[:] = jnp.zeros_like(acc_scr)

    start = start_ref[b]
    max_qpos = start + chunk - 1

    def _compute():
        q = q_ref[0, 0]                    # [Gp, d]
        k = k_ref[0, 0]                    # [bs, d] (fp8 pages dequantize
        v = v_ref[0, 0]                    # on load; no-op otherwise)
        if kscale_ref is not None:
            # per-(head, page) scale rides in SMEM next to the block table
            page = tables_ref[b * num_pages + j]
            k = k.astype(jnp.float32) * kscale_ref[hi * num_blocks + page]
            v = v.astype(jnp.float32) * vscale_ref[hi * num_blocks + page]
        k = k.astype(q.dtype)
        v = v.astype(q.dtype)
        s = jax.lax.dot_general(q, k, (((1,), (1,)), ((), ())),
                                preferred_element_type=jnp.float32)
        s = s * (1.0 / np.sqrt(q.shape[-1]))
        if softcap:                        # gemma2 attn_logit_softcapping
            s = softcap * jnp.tanh(s / softcap)
        # row r of the fold is (q-head r // chunk, chunk token r % chunk)
        row = jax.lax.broadcasted_iota(jnp.int32, s.shape, 0)
        qpos = start + row % chunk
        kpos = j * block_size + jax.lax.broadcasted_iota(jnp.int32, s.shape, 1)
        mask = kpos <= qpos                # causal == context-length mask
        if window is not None:
            mask = jnp.logical_and(mask, kpos > qpos - window)
        s = jnp.where(mask, s, NEG_INF)
        m_prev = m_scr[:]
        m_new = jnp.maximum(m_prev, jnp.max(s, axis=1, keepdims=True))
        p = jnp.where(mask, jnp.exp(s - m_new), 0.0)
        alpha = jnp.exp(m_prev - m_new)
        m_scr[:] = m_new
        l_scr[:] = l_scr[:] * alpha + jnp.sum(p, axis=1, keepdims=True)
        acc_scr[:] = acc_scr[:] * alpha + jax.lax.dot_general(
            p.astype(v.dtype), v, (((1,), (0,)), ((), ())),
            preferred_element_type=jnp.float32)

    live = j * block_size <= max_qpos      # page overlaps the causal horizon
    if window is not None:
        live = jnp.logical_and(live, (j + 1) * block_size - 1 > start - window)
    pl.when(live)(_compute)

    @pl.when(j == num_pages - 1)
    def _finalize():
        o_ref[0, 0] = (acc_scr[:] / jnp.maximum(l_scr[:], 1e-30)
                       ).astype(o_ref.dtype)


def paged_attention(q, k_pages, v_pages, block_tables, start_pos,
                    window=None, softcap=None, k_scales=None, v_scales=None,
                    interpret: bool = False):
    """q: [B, T, H, d] (T=1 decode / B=1 prefill chunk);
    k_pages/v_pages: [Hkv, NB, block_size, d]; block_tables: [B, MB] int32
    (trash-padded); start_pos: [B] int32 — global position of q row t=0
    (row t attends kpos <= start+t). ``k_scales``/``v_scales``: optional
    [Hkv, NB] fp32 per-(head, page) dequant scales for fp8 pages (ride as
    scalar prefetch; applied on load in-kernel). Returns [B, T, H, d].

    The KV written for q's own tokens must already be in the pages (the decode/
    prefill step scatters K/V before calling attention); causal masking then
    doubles as the context-length mask, so trash-padded table slots and stale
    tail entries of the last page are never visible.
    """
    b, t, h, d = q.shape
    hkv, nb, bs, _ = k_pages.shape
    rep = h // hkv
    g = rep * t
    gp = -(-g // 8) * 8                    # pad fold rows to sublane multiple
    mb = block_tables.shape[1]
    scaled = k_scales is not None

    qf = q.transpose(0, 2, 1, 3).reshape(b, hkv, g, d)
    if gp != g:
        qf = jnp.pad(qf, ((0, 0), (0, 0), (0, gp - g), (0, 0)))

    grid_spec = pltpu.PrefetchScalarGridSpec(
        num_scalar_prefetch=4 if scaled else 2,
        grid=(b, hkv, mb),
        in_specs=[
            pl.BlockSpec((1, 1, gp, d), lambda bi, hi, j, *pf:
                         (bi, hi, 0, 0)),
            pl.BlockSpec((1, 1, bs, d), lambda bi, hi, j, *pf, mb=mb:
                         (hi, pf[0][bi * mb + j], 0, 0)),
            pl.BlockSpec((1, 1, bs, d), lambda bi, hi, j, *pf, mb=mb:
                         (hi, pf[0][bi * mb + j], 0, 0)),
        ],
        out_specs=pl.BlockSpec((1, 1, gp, d), lambda bi, hi, j, *pf:
                               (bi, hi, 0, 0)),
        scratch_shapes=[
            pltpu.VMEM((gp, 1), jnp.float32),
            pltpu.VMEM((gp, 1), jnp.float32),
            pltpu.VMEM((gp, d), jnp.float32),
        ],
    )
    prefetch = [block_tables.reshape(-1).astype(jnp.int32),
                start_pos.astype(jnp.int32)]
    if scaled:
        prefetch += [k_scales.reshape(-1).astype(jnp.float32),
                     v_scales.reshape(-1).astype(jnp.float32)]
    out = pl.pallas_call(
        functools.partial(_paged_kernel, block_size=bs, num_pages=mb,
                          chunk=t, rep=rep, window=window, softcap=softcap,
                          num_blocks=nb if scaled else 0),
        grid_spec=grid_spec,
        out_shape=jax.ShapeDtypeStruct((b, hkv, gp, d), q.dtype),
        interpret=interpret,
    )(*prefetch, qf, k_pages, v_pages)

    out = out[:, :, :g].reshape(b, hkv, rep, t, d)
    return out.transpose(0, 3, 1, 2, 4).reshape(b, t, h, d)


def paged_attention_reference(q, k_pages, v_pages, block_tables, start_pos,
                              window=None, softcap=None, k_scales=None,
                              v_scales=None):
    """Gather-based jnp reference with identical semantics (numerics oracle for
    kernel tests; also the CPU fallback path). ``softcap`` tanh-caps the
    scaled logits before masking (gemma2 attn_logit_softcapping);
    ``k_scales``/``v_scales``: [Hkv, NB] per-(head, page) fp8 dequant."""
    b, t, h, d = q.shape
    hkv, _, bs, _ = k_pages.shape
    rep = h // hkv
    mb = block_tables.shape[1]
    # [Hkv, B, MB, bs, d] -> [B, MB*bs, Hkv, d]
    gk = k_pages[:, block_tables]
    gv = v_pages[:, block_tables]
    if k_scales is not None:               # dequant before the dtype fold
        gk = gk.astype(jnp.float32) * k_scales[:, block_tables][..., None, None]
        gv = gv.astype(jnp.float32) * v_scales[:, block_tables][..., None, None]
    ctx_k = gk.transpose(1, 2, 3, 0, 4).reshape(b, mb * bs, hkv, d)
    ctx_v = gv.transpose(1, 2, 3, 0, 4).reshape(b, mb * bs, hkv, d)
    if rep > 1:
        ctx_k = jnp.repeat(ctx_k, rep, axis=2)
        ctx_v = jnp.repeat(ctx_v, rep, axis=2)
    ctx_k = ctx_k.astype(q.dtype)          # fp8 pages dequantize on load
    ctx_v = ctx_v.astype(q.dtype)
    s = jnp.einsum("bthd,bkhd->bhtk", q, ctx_k,
                   preferred_element_type=jnp.float32) / np.sqrt(d)
    from deepspeed_tpu.models.llama import softcap_logits
    s = softcap_logits(s, softcap)
    qpos = start_pos[:, None] + jnp.arange(t)[None, :]          # [B, T]
    kpos = jnp.arange(mb * bs)[None, None, :]
    mask = kpos <= qpos[..., None]
    if window is not None:
        mask = jnp.logical_and(mask, kpos > qpos[..., None] - window)
    s = jnp.where(mask[:, None], s, NEG_INF)
    p = jax.nn.softmax(s, axis=-1).astype(ctx_v.dtype)
    return jnp.einsum("bhtk,bkhd->bthd", p, ctx_v)
