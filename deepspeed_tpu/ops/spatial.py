"""Spatial (diffusion UNet) fused ops.

Reference analog: ``csrc/spatial/csrc/opt_bias_add.cu`` + ``pt_binding.cpp``
(``nhwc_bias_add`` / ``nhwc_bias_add_add`` / ``nhwc_bias_add_bias_add`` — the
channels-last fused bias/residual adds on the diffusion UNet hot path) and the
diffusers attention/group-norm glue in
``deepspeed/ops/transformer/inference/``.

TPU shape: these are elementwise chains — exactly what XLA fuses into a single
VPU pass — so the TPU-native implementation is the jnp expression under jit;
the value of this module is the stable reference-named API (and NCHW/NHWC
handling: TPU convolutions prefer NHWC, the reference kernels assume
channels-last memory format of an NCHW tensor, which is the same byte layout).
Group norm rides along since the reference fuses it in the diffusion path.
"""

from functools import partial

import jax
import jax.numpy as jnp


def _bias_for(activations, bias, channel_axis: int):
    shape = [1] * activations.ndim
    shape[channel_axis] = bias.shape[0]
    return bias.reshape(shape)


@partial(jax.jit, static_argnames=("channel_axis",))
def nhwc_bias_add(activations, bias, channel_axis: int = -1):
    """activations: [B, H, W, C] (NHWC; pass channel_axis=1 for NCHW);
    bias: [C]."""
    return activations + _bias_for(activations, bias, channel_axis)


@partial(jax.jit, static_argnames=("channel_axis",))
def nhwc_bias_add_add(activations, bias, other, channel_axis: int = -1):
    """(activations + bias) + other — residual fused in one pass."""
    return activations + _bias_for(activations, bias, channel_axis) + other


@partial(jax.jit, static_argnames=("channel_axis",))
def nhwc_bias_add_bias_add(activations, bias, other, other_bias,
                           channel_axis: int = -1):
    """(activations + bias) + (other + other_bias)."""
    return (activations + _bias_for(activations, bias, channel_axis)
            + other + _bias_for(other, other_bias, channel_axis))


@partial(jax.jit, static_argnames=("num_groups", "eps", "channel_axis"))
def group_norm(x, scale, bias, num_groups: int = 32, eps: float = 1e-5,
               channel_axis: int = -1):
    """GroupNorm over NHWC activations (diffusion UNet norm; the reference
    fuses it via its inference kernel path). scale/bias: [C]."""
    if channel_axis != -1 and channel_axis != x.ndim - 1:
        x = jnp.moveaxis(x, channel_axis, -1)
        out = group_norm(x, scale, bias, num_groups, eps)
        return jnp.moveaxis(out, -1, channel_axis)
    c = x.shape[-1]
    g = x.reshape(x.shape[0], -1, num_groups, c // num_groups)
    x32 = g.astype(jnp.float32)
    mu = jnp.mean(x32, axis=(1, 3), keepdims=True)
    var = jnp.mean(jnp.square(x32 - mu), axis=(1, 3), keepdims=True)
    norm = (x32 - mu) * jax.lax.rsqrt(var + eps)
    norm = norm.reshape(x.shape)
    return (norm * scale + bias).astype(x.dtype)
