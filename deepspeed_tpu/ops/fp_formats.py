"""FP6 / FP12 software minifloat formats with dense bit packing.

Reference analog: ``csrc/fp_quantizer/fp_quantize.cu`` +
``deepspeed/ops/fp_quantizer/__init__.py`` (``FP_Quantize`` with
``q_bits`` ∈ {6, 8, 12}): group-scaled minifloat quantization used for
weight compression (ZeRO-Inference / qwZ breadth beyond int8/fp8).

TPU shape: fp8 has native dtypes (``ops/pallas/fp_quant.py``); fp6/fp12 do
not, so they are software formats — encode/decode are vectorized jnp bit
arithmetic (XLA fuses the integer ops), and the codes pack densely into a
``uint8`` buffer (4×6-bit codes → 3 bytes; 2×12-bit codes → 3 bytes), so
storage/wire really is 0.75 / 1.5 bytes per element:

- **fp6**  = 1 sign + 3 exponent + 2 mantissa (e3m2, bias 3, no inf/nan —
  the top exponent carries data, max normal 28.0)
- **fp12** = 1 sign + 5 exponent + 6 mantissa (e5m6, bias 15, max normal
  ≈ 130k; ~0.8% max relative rounding error)

Like the fp8/int8 kernels, scaling is per-row (last-dim group) symmetric
fp32: the row absmax maps onto the format's max normal.
"""

import functools
from typing import Tuple

import jax
import jax.numpy as jnp

# fmt -> (e_bits, m_bits)
FP_FORMATS = {"fp6": (3, 2), "fp12": (5, 6)}


def format_max(fmt: str) -> float:
    e_bits, m_bits = FP_FORMATS[fmt]
    bias = 2 ** (e_bits - 1) - 1
    emax = 2 ** e_bits - 1 - bias
    return float(2.0 ** emax * (2.0 - 2.0 ** -m_bits))


# ---------------------------------------------------------------------------
# scalar-format encode/decode (vectorized over arrays of fp32)
# ---------------------------------------------------------------------------
def _encode(x, e_bits: int, m_bits: int):
    """fp32 -> integer codes (1+e_bits+m_bits bits, ieee-like layout with
    subnormals, round-to-nearest, saturation, no inf/nan)."""
    bias = 2 ** (e_bits - 1) - 1
    emax = 2 ** e_bits - 1 - bias
    mscale = 2 ** m_bits
    sign = (x < 0).astype(jnp.uint32)
    ax = jnp.abs(x.astype(jnp.float32))
    # exponent bucket; everything below the subnormal range clamps to the
    # e = 1-bias bucket whose step also covers subnormals (ieee property)
    e = jnp.floor(jnp.log2(jnp.maximum(ax, 2.0 ** (1 - bias))))
    e = jnp.clip(e, 1 - bias, emax)
    q = jnp.round(ax * jnp.exp2(m_bits - e)).astype(jnp.int32)
    of = q >= 2 * mscale                       # rounded up into next exponent
    e = jnp.where(of, e + 1, e)
    q = jnp.where(of, mscale, q)
    sat = e > emax                             # saturate at max finite
    e = jnp.where(sat, emax, e)
    q = jnp.where(sat, 2 * mscale - 1, q)
    subnormal = q < mscale                     # only possible at e == 1-bias
    e_idx = jnp.where(subnormal, 0, e + bias).astype(jnp.uint32)
    mant = jnp.where(subnormal, q, q - mscale).astype(jnp.uint32)
    return (sign << (e_bits + m_bits)) | (e_idx << m_bits) | mant


def _decode(code, e_bits: int, m_bits: int):
    """integer codes -> fp32 values."""
    bias = 2 ** (e_bits - 1) - 1
    mscale = 2 ** m_bits
    code = code.astype(jnp.uint32)
    sign = (code >> (e_bits + m_bits)) & 1
    e_idx = (code >> m_bits) & (2 ** e_bits - 1)
    mant = code & (mscale - 1)
    normal = e_idx > 0
    e = jnp.where(normal, e_idx.astype(jnp.int32) - bias, 1 - bias)
    frac = jnp.where(normal, mant + mscale, mant).astype(jnp.float32)
    val = frac * jnp.exp2((e - m_bits).astype(jnp.float32))
    return jnp.where(sign == 1, -val, val)


# ---------------------------------------------------------------------------
# dense packing: 6-bit codes 4->3 bytes, 12-bit codes 2->3 bytes
# ---------------------------------------------------------------------------
def _pack6(codes):                              # [..., D] uint32, D % 4 == 0
    c = codes.astype(jnp.uint32).reshape(*codes.shape[:-1], -1, 4)
    b0 = (c[..., 0] | (c[..., 1] << 6)) & 0xFF
    b1 = ((c[..., 1] >> 2) | (c[..., 2] << 4)) & 0xFF
    b2 = ((c[..., 2] >> 4) | (c[..., 3] << 2)) & 0xFF
    return jnp.stack([b0, b1, b2], axis=-1).reshape(
        *codes.shape[:-1], -1).astype(jnp.uint8)


def _unpack6(packed, d: int):                   # [..., D*3/4] uint8
    b = packed.astype(jnp.uint32).reshape(*packed.shape[:-1], -1, 3)
    c0 = b[..., 0] & 0x3F
    c1 = ((b[..., 0] >> 6) | (b[..., 1] << 2)) & 0x3F
    c2 = ((b[..., 1] >> 4) | (b[..., 2] << 4)) & 0x3F
    c3 = (b[..., 2] >> 2) & 0x3F
    return jnp.stack([c0, c1, c2, c3], axis=-1).reshape(
        *packed.shape[:-1], d)


def _pack12(codes):                             # [..., D] uint32, D % 2 == 0
    c = codes.astype(jnp.uint32).reshape(*codes.shape[:-1], -1, 2)
    b0 = c[..., 0] & 0xFF
    b1 = ((c[..., 0] >> 8) | (c[..., 1] << 4)) & 0xFF
    b2 = (c[..., 1] >> 4) & 0xFF
    return jnp.stack([b0, b1, b2], axis=-1).reshape(
        *codes.shape[:-1], -1).astype(jnp.uint8)


def _unpack12(packed, d: int):
    b = packed.astype(jnp.uint32).reshape(*packed.shape[:-1], -1, 3)
    c0 = b[..., 0] | ((b[..., 1] & 0xF) << 8)
    c1 = (b[..., 1] >> 4) | (b[..., 2] << 4)
    return jnp.stack([c0, c1], axis=-1).reshape(*packed.shape[:-1], d)


_PACK = {"fp6": (_pack6, _unpack6, 4), "fp12": (_pack12, _unpack12, 2)}


# ---------------------------------------------------------------------------
# group-scaled quantize / dequantize (the FP_Quantize-equivalent surface)
# ---------------------------------------------------------------------------
@functools.partial(jax.jit, static_argnames=("fmt",))
def quantize_fp(x, fmt: str = "fp6") -> Tuple[jnp.ndarray, jnp.ndarray]:
    """x: [..., D] -> (packed uint8 [..., D*bits/8], fp32 scales [..., 1]).
    Per-row symmetric scaling onto the format's max normal; D must be
    divisible by the packing group (4 for fp6, 2 for fp12)."""
    e_bits, m_bits = FP_FORMATS[fmt]
    pack, _, group = _PACK[fmt]
    if x.shape[-1] % group:
        raise ValueError(f"{fmt}: last dim {x.shape[-1]} not divisible "
                         f"by the packing group {group}")
    x32 = x.astype(jnp.float32)
    absmax = jnp.max(jnp.abs(x32), axis=-1, keepdims=True)
    scale = jnp.maximum(absmax / format_max(fmt), 1e-12)
    codes = _encode(x32 / scale, e_bits, m_bits)
    return pack(codes), scale


@functools.partial(jax.jit, static_argnames=("fmt", "d", "dtype"))
def dequantize_fp(packed, scales, fmt: str, d: int, dtype=jnp.bfloat16):
    """Inverse of :func:`quantize_fp`; ``d`` is the unpacked last dim."""
    e_bits, m_bits = FP_FORMATS[fmt]
    _, unpack, _ = _PACK[fmt]
    vals = _decode(unpack(packed, d), e_bits, m_bits)
    return (vals * scales).astype(dtype)


def selective_dequantize_fp(packed, scales, rows, fmt: str, d: int,
                            dtype=jnp.bfloat16):
    """Gather a row subset of a packed tensor and dequantize only those
    (reference: ``selective_dequantize``, fp_quantize.cu). packed: [N, Dp];
    scales: [N, 1]; rows: [K] int32 -> [K, d]."""
    return dequantize_fp(jnp.take(packed, rows, axis=0),
                         jnp.take(scales, rows, axis=0), fmt, d, dtype)


class FPQuantizer:
    """API-parity shim for the reference ``FP_Quantize`` (q_bits 6/8/12):
    dispatches to the native-fp8 Pallas kernels for 8 bits and to the packed
    software formats here for 6/12."""

    def __init__(self, q_bits: int = 8, fp8_fmt: str = "e4m3"):
        if q_bits not in (6, 8, 12):
            raise ValueError(f"q_bits must be 6, 8 or 12, got {q_bits}")
        self.q_bits = q_bits
        self.fp8_fmt = fp8_fmt

    def quantize(self, x):
        if self.q_bits == 8:
            from deepspeed_tpu.ops.pallas.fp_quant import quantize_fp8
            return quantize_fp8(x, fmt=self.fp8_fmt)
        return quantize_fp(x, fmt=f"fp{self.q_bits}")

    def dequantize(self, q, scales, d: int = None, dtype=jnp.bfloat16):
        if self.q_bits == 8:
            from deepspeed_tpu.ops.pallas.fp_quant import dequantize_fp8
            return dequantize_fp8(q, scales, dtype=dtype)
        if d is None:
            raise ValueError("packed formats need the unpacked dim d")
        return dequantize_fp(q, scales, f"fp{self.q_bits}", d, dtype)
