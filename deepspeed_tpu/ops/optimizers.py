"""Optimizer registry — the analog of the fused/CPU optimizer zoo.

Reference analogs: ``deepspeed/ops/adam/fused_adam.py:18`` (FusedAdam),
``ops/adam/cpu_adam.py:13`` (DeepSpeedCPUAdam), ``ops/lamb``, ``ops/lion``,
``csrc/adam/multi_tensor_adam.cu`` (multi-tensor-apply kernels), and the engine's
``_configure_basic_optimizer`` (``runtime/engine.py:1322``) name dispatch.

On TPU "fused" is the default, not an op: the whole optimizer update is one XLA
fusion inside the jitted train step — multi-tensor-apply is what XLA does to a pytree
update anyway. The registry keeps the reference's optimizer names (adam, adamw,
fusedadam, cpuadam → all map to the same fused XLA update; lamb, lion, adagrad, sgd,
muon-style skipped) so configs port unchanged. Host-offloaded CPU optimizer steps for
the ZeRO-Offload tier live in deepspeed_tpu/runtime/offload (C++ path) — this module
is the in-HBM path.
"""

from typing import Any, Callable, Dict, Optional, Union

import optax

from deepspeed_tpu.utils.logging import log_dist

ScheduleOrFloat = Union[float, Callable]


def _adam_like(lr: ScheduleOrFloat, params: Dict[str, Any], weight_decay_default: float,
               decoupled: bool) -> optax.GradientTransformation:
    betas = params.get("betas", (0.9, 0.999))
    eps = params.get("eps", 1e-8)
    wd = params.get("weight_decay", weight_decay_default)
    if decoupled:
        return optax.adamw(lr, b1=betas[0], b2=betas[1], eps=eps, weight_decay=wd)
    tx = optax.adam(lr, b1=betas[0], b2=betas[1], eps=eps)
    if wd:
        # non-decoupled (L2) decay: add wd*param to grads before adam
        tx = optax.chain(optax.add_decayed_weights(wd), tx)
    return tx


def build_optimizer(opt_type: str, opt_params: Dict[str, Any],
                    lr_schedule: Optional[Callable] = None) -> optax.GradientTransformation:
    """Map a reference optimizer config onto an optax transformation chain.

    The learning rate is ``lr_schedule`` if provided (engine threads the config
    scheduler here), else the static ``lr`` from optimizer params.
    """
    name = opt_type.lower()
    lr: ScheduleOrFloat = lr_schedule if lr_schedule is not None \
        else opt_params.get("lr", 1e-3)

    if name in ("adam", "fusedadam"):
        adam_w_mode = opt_params.get("adam_w_mode", True)
        tx = _adam_like(lr, opt_params, 0.0, decoupled=adam_w_mode)
    elif name in ("adamw", "deepspeedcpuadam", "cpuadam", "cpu_adam"):
        tx = _adam_like(lr, opt_params, 0.01 if name == "adamw" else 0.0, decoupled=True)
    elif name in ("lamb", "fusedlamb"):
        betas = opt_params.get("betas", (0.9, 0.999))
        tx = optax.lamb(lr, b1=betas[0], b2=betas[1], eps=opt_params.get("eps", 1e-6),
                        weight_decay=opt_params.get("weight_decay", 0.0))
    elif name in ("lion", "fusedlion", "cpulion"):
        betas = opt_params.get("betas", (0.9, 0.99))
        tx = optax.lion(lr, b1=betas[0], b2=betas[1],
                        weight_decay=opt_params.get("weight_decay", 0.0))
    elif name in ("adagrad", "cpuadagrad", "cpu_adagrad"):
        tx = optax.adagrad(lr, eps=opt_params.get("eps", 1e-10))
    elif name in ("sgd", "momentum"):
        tx = optax.sgd(lr, momentum=opt_params.get("momentum", 0.0),
                       nesterov=opt_params.get("nesterov", False))
    elif name in ("rmsprop",):
        tx = optax.rmsprop(lr, decay=opt_params.get("alpha", 0.99),
                           eps=opt_params.get("eps", 1e-8),
                           momentum=opt_params.get("momentum", 0.0))
    elif name in ("onebitadam", "zerooneadam", "onebitlamb"):
        from deepspeed_tpu.ops import onebit
        betas = opt_params.get("betas", (0.9, 0.999))
        common = dict(b1=betas[0], b2=betas[1],
                      weight_decay=opt_params.get("weight_decay", 0.0),
                      world_size=opt_params.get("world_size", 1),
                      axis_name=opt_params.get("axis_name"))
        if name == "onebitadam":
            tx = onebit.onebit_adam(lr, eps=opt_params.get("eps", 1e-8),
                                    freeze_step=opt_params.get("freeze_step", 100000),
                                    **common)
        elif name == "zerooneadam":
            tx = onebit.zero_one_adam(
                lr, eps=opt_params.get("eps", 1e-8),
                var_freeze_step=opt_params.get("var_freeze_step", 100000),
                var_update_scaler=opt_params.get("var_update_scaler", 16), **common)
        else:
            tx = onebit.onebit_lamb(
                lr, eps=opt_params.get("eps", 1e-6),
                freeze_step=opt_params.get("freeze_step", 100000),
                max_coeff=opt_params.get("max_coeff", 10.0),
                min_coeff=opt_params.get("min_coeff", 0.01), **common)
    else:
        raise ValueError(f"unknown optimizer type '{opt_type}'")
    return tx
