"""Host fused optimizers over the native C++ kernels.

Reference analogs: ``deepspeed/ops/adam/cpu_adam.py:13`` (``DeepSpeedCPUAdam``),
``ops/adagrad/cpu_adagrad.py`` and ``ops/lion/cpu_lion.py`` — python wrappers
over the AVX kernels used for ZeRO-Offload optimizer states. Numpy fallback
keeps CI working without a toolchain.
"""

import ctypes
from typing import Optional

import numpy as np

from deepspeed_tpu.utils.logging import warning_once


def _load_sym(name, argtypes):
    from deepspeed_tpu.ops.op_builder import get_op
    lib = get_op("cpu_adam")
    fn = getattr(lib, name)
    fn.argtypes = argtypes
    return fn


def to_bf16(src: np.ndarray) -> np.ndarray:
    """Round-to-nearest-even bf16 shadow of an fp32 array (reference: the fp16
    param-shard update after the CPU step). Uses the C++ kernel when available;
    halves host→device transfer bytes for the offload tier."""
    import ml_dtypes
    src = np.ascontiguousarray(src, dtype=np.float32)
    try:
        fn = _load_sym("fp32_to_bf16", [
            ctypes.POINTER(ctypes.c_float), ctypes.POINTER(ctypes.c_uint16),
            ctypes.c_int64])
    except Exception:
        return src.astype(ml_dtypes.bfloat16)
    out = np.empty(src.shape, dtype=np.uint16)
    fn(src.ctypes.data_as(ctypes.POINTER(ctypes.c_float)),
       out.ctypes.data_as(ctypes.POINTER(ctypes.c_uint16)), src.size)
    return out.view(ml_dtypes.bfloat16)


class CPUAdam:
    """Fused AdamW/Adam over flat fp32 numpy shards (host memory)."""

    num_states = 2  # exp_avg, exp_avg_sq

    def __init__(self, lr: float = 1e-3, betas=(0.9, 0.999), eps: float = 1e-8,
                 weight_decay: float = 0.0, adamw_mode: bool = True, **_ignored):
        self.lr = lr
        self.beta1, self.beta2 = betas
        self.eps = eps
        self.weight_decay = weight_decay
        self.adamw_mode = adamw_mode
        self.step_count = 0
        self._fn = None
        try:
            from deepspeed_tpu.ops.op_builder import get_op
            lib = get_op("cpu_adam")
            fn = lib.cpu_adam_step
            fn.argtypes = [ctypes.POINTER(ctypes.c_float)] * 4 + [
                ctypes.c_int64, ctypes.c_float, ctypes.c_float, ctypes.c_float,
                ctypes.c_float, ctypes.c_float, ctypes.c_int, ctypes.c_int64]
            self._fn = fn
        except Exception as e:
            warning_once(f"cpu_adam native op unavailable ({e}); numpy fallback")

    @staticmethod
    def _ptr(a: np.ndarray):
        return a.ctypes.data_as(ctypes.POINTER(ctypes.c_float))

    def step(self, params: np.ndarray, grads: np.ndarray, exp_avg: np.ndarray,
             exp_avg_sq: np.ndarray, lr: Optional[float] = None):
        """In-place fused update on contiguous fp32 arrays."""
        assert params.dtype == np.float32 and params.flags["C_CONTIGUOUS"]
        self.step_count += 1
        lr = self.lr if lr is None else lr
        if self._fn is not None:
            grads32 = np.ascontiguousarray(grads, dtype=np.float32)
            self._fn(self._ptr(params), self._ptr(grads32), self._ptr(exp_avg),
                     self._ptr(exp_avg_sq), params.size, lr, self.beta1,
                     self.beta2, self.eps, self.weight_decay,
                     int(self.adamw_mode), self.step_count)
            return
        # numpy fallback (same math)
        g = grads.astype(np.float32)
        if not self.adamw_mode and self.weight_decay:
            g = g + self.weight_decay * params
        exp_avg *= self.beta1
        exp_avg += (1 - self.beta1) * g
        exp_avg_sq *= self.beta2
        exp_avg_sq += (1 - self.beta2) * g * g
        bc1 = 1 - self.beta1 ** self.step_count
        bc2 = 1 - self.beta2 ** self.step_count
        update = (exp_avg / bc1) / (np.sqrt(exp_avg_sq / bc2) + self.eps)
        if self.adamw_mode and self.weight_decay:
            update = update + self.weight_decay * params
        params -= lr * update


class CPUAdagrad:
    """Fused Adagrad over flat fp32 numpy shards (reference:
    csrc/adagrad/cpu_adagrad.cpp via ops/adagrad/cpu_adagrad.py)."""

    num_states = 1  # state_sum

    def __init__(self, lr: float = 1e-2, eps: float = 1e-10,
                 weight_decay: float = 0.0, **_ignored):
        self.lr = lr
        self.eps = eps
        self.weight_decay = weight_decay
        self.step_count = 0
        self._fn = None
        try:
            self._fn = _load_sym("cpu_adagrad_step", [
                ctypes.POINTER(ctypes.c_float)] * 3 + [
                ctypes.c_int64, ctypes.c_float, ctypes.c_float, ctypes.c_float])
        except Exception as e:
            warning_once(f"cpu_adagrad native op unavailable ({e}); numpy fallback")

    def step(self, params: np.ndarray, grads: np.ndarray, state_sum: np.ndarray,
             lr: Optional[float] = None):
        assert params.dtype == np.float32 and params.flags["C_CONTIGUOUS"]
        self.step_count += 1
        lr = self.lr if lr is None else lr
        if self._fn is not None:
            g32 = np.ascontiguousarray(grads, dtype=np.float32)
            self._fn(params.ctypes.data_as(ctypes.POINTER(ctypes.c_float)),
                     g32.ctypes.data_as(ctypes.POINTER(ctypes.c_float)),
                     state_sum.ctypes.data_as(ctypes.POINTER(ctypes.c_float)),
                     params.size, lr, self.eps, self.weight_decay)
            return
        g = grads.astype(np.float32)
        if self.weight_decay:
            g = g + self.weight_decay * params
        state_sum += g * g
        params -= lr * g / (np.sqrt(state_sum) + self.eps)


class CPULion:
    """Fused Lion over flat fp32 numpy shards (reference:
    csrc/lion/cpu_lion_impl.cpp via ops/lion/cpu_lion.py)."""

    num_states = 1  # exp_avg

    def __init__(self, lr: float = 1e-4, betas=(0.9, 0.99),
                 weight_decay: float = 0.0, **_ignored):
        self.lr = lr
        self.beta1, self.beta2 = betas
        self.weight_decay = weight_decay
        self.step_count = 0
        self._fn = None
        try:
            self._fn = _load_sym("cpu_lion_step", [
                ctypes.POINTER(ctypes.c_float)] * 3 + [
                ctypes.c_int64, ctypes.c_float, ctypes.c_float, ctypes.c_float,
                ctypes.c_float])
        except Exception as e:
            warning_once(f"cpu_lion native op unavailable ({e}); numpy fallback")

    def step(self, params: np.ndarray, grads: np.ndarray, exp_avg: np.ndarray,
             lr: Optional[float] = None):
        assert params.dtype == np.float32 and params.flags["C_CONTIGUOUS"]
        self.step_count += 1
        lr = self.lr if lr is None else lr
        if self._fn is not None:
            g32 = np.ascontiguousarray(grads, dtype=np.float32)
            self._fn(params.ctypes.data_as(ctypes.POINTER(ctypes.c_float)),
                     g32.ctypes.data_as(ctypes.POINTER(ctypes.c_float)),
                     exp_avg.ctypes.data_as(ctypes.POINTER(ctypes.c_float)),
                     params.size, lr, self.beta1, self.beta2, self.weight_decay)
            return
        g = grads.astype(np.float32)
        c = self.beta1 * exp_avg + (1 - self.beta1) * g
        params -= lr * (np.sign(c) + self.weight_decay * params)
        exp_avg *= self.beta2
        exp_avg += (1 - self.beta2) * g
