"""Host fused optimizers over the native C++ kernels.

Reference analog: ``deepspeed/ops/adam/cpu_adam.py:13`` (``DeepSpeedCPUAdam`` —
python wrapper over the AVX kernel, used for ZeRO-Offload optimizer states).
Numpy fallback keeps CI working without a toolchain.
"""

import ctypes
from typing import Optional

import numpy as np

from deepspeed_tpu.utils.logging import warning_once


class CPUAdam:
    """Fused AdamW/Adam over flat fp32 numpy shards (host memory)."""

    def __init__(self, lr: float = 1e-3, betas=(0.9, 0.999), eps: float = 1e-8,
                 weight_decay: float = 0.0, adamw_mode: bool = True):
        self.lr = lr
        self.beta1, self.beta2 = betas
        self.eps = eps
        self.weight_decay = weight_decay
        self.adamw_mode = adamw_mode
        self.step_count = 0
        self._fn = None
        try:
            from deepspeed_tpu.ops.op_builder import get_op
            lib = get_op("cpu_adam")
            fn = lib.cpu_adam_step
            fn.argtypes = [ctypes.POINTER(ctypes.c_float)] * 4 + [
                ctypes.c_int64, ctypes.c_float, ctypes.c_float, ctypes.c_float,
                ctypes.c_float, ctypes.c_float, ctypes.c_int, ctypes.c_int64]
            self._fn = fn
        except Exception as e:
            warning_once(f"cpu_adam native op unavailable ({e}); numpy fallback")

    @staticmethod
    def _ptr(a: np.ndarray):
        return a.ctypes.data_as(ctypes.POINTER(ctypes.c_float))

    def step(self, params: np.ndarray, grads: np.ndarray, exp_avg: np.ndarray,
             exp_avg_sq: np.ndarray, lr: Optional[float] = None):
        """In-place fused update on contiguous fp32 arrays."""
        assert params.dtype == np.float32 and params.flags["C_CONTIGUOUS"]
        self.step_count += 1
        lr = self.lr if lr is None else lr
        if self._fn is not None:
            grads32 = np.ascontiguousarray(grads, dtype=np.float32)
            self._fn(self._ptr(params), self._ptr(grads32), self._ptr(exp_avg),
                     self._ptr(exp_avg_sq), params.size, lr, self.beta1,
                     self.beta2, self.eps, self.weight_decay,
                     int(self.adamw_mode), self.step_count)
            return
        # numpy fallback (same math)
        g = grads.astype(np.float32)
        if not self.adamw_mode and self.weight_decay:
            g = g + self.weight_decay * params
        exp_avg *= self.beta1
        exp_avg += (1 - self.beta1) * g
        exp_avg_sq *= self.beta2
        exp_avg_sq += (1 - self.beta2) * g * g
        bc1 = 1 - self.beta1 ** self.step_count
        bc2 = 1 - self.beta2 ** self.step_count
        update = (exp_avg / bc1) / (np.sqrt(exp_avg_sq / bc2) + self.eps)
        if self.adamw_mode and self.weight_decay:
            update = update + self.weight_decay * params
        params -= lr * update
