"""1-bit / 0-1 communication-compressed optimizers.

Reference analogs: ``deepspeed/runtime/fp16/onebit/adam.py:14`` (OnebitAdam),
``lamb.py`` (OnebitLamb), ``zoadam.py`` (ZeroOneAdam). Semantics preserved:

- **OneBitAdam** — two stages. Warmup (step < ``freeze_step``): vanilla Adam
  with bias correction. Compressed stage: the *variance is frozen*; the momentum
  is updated with fresh grads and then passed through the error-feedback 1-bit
  compressor (``comm/compressed.py``) — that compressed momentum (not the grads)
  is what crosses the wire; the update is ``m / (√v_frozen + eps)`` with no bias
  correction (reference adam.py:230).
- **ZeroOneAdam** — removes the hard freeze: the variance refreshes at
  exponentially growing intervals (``var_update_scaler``) until
  ``var_freeze_step``; momentum is always sign-compressed with error feedback
  (reference zoadam.py learning-rate/variance freeze policies; the local-step
  policy collapses under SPMD where every step is synchronous).
- **OneBitLamb** — warmup runs vanilla LAMB while recording per-tensor trust
  ratios; the compressed stage reuses the *frozen* trust ratio with 1-bit
  momentum (reference lamb.py scaling-coefficient freezing).

TPU-native shape: optax ``GradientTransformation``s. Under SPMD the engine's
grads arrive already averaged, so the compressor's distributed path
(``axis_name``) matters when the transform runs inside ``shard_map`` over the
data axis (multi-slice DCN, where 32× momentum compression pays); otherwise the
local error-feedback compressor preserves the exact update semantics.
"""

from typing import Callable, NamedTuple, Optional, Union

import jax
import jax.numpy as jnp
import optax

from deepspeed_tpu.comm.compressed import (
    compress_local, compressed_allreduce, error_buffer_shapes)

ScheduleOrFloat = Union[float, Callable]


def _lr_at(lr: ScheduleOrFloat, count):
    return lr(count) if callable(lr) else lr


def _compress_leaf(m, we, se, axis_name):
    """Flatten + pad a momentum leaf, run the (distributed) compressor, restore."""
    flat = m.astype(jnp.float32).ravel()
    pad = we.size - flat.size
    flat = jnp.pad(flat, (0, pad))
    if axis_name is None:
        out, new_we = compress_local(flat, we)
        new_se = se
    else:
        out, new_we, new_se = compressed_allreduce(flat, we, se, axis_name)
    return out[:m.size].reshape(m.shape).astype(m.dtype), new_we, new_se


class OneBitAdamState(NamedTuple):
    count: jnp.ndarray
    exp_avg: optax.Updates
    exp_avg_sq: optax.Updates
    worker_error: optax.Updates
    server_error: optax.Updates


def _error_buffers(params, world_size: int):
    def we(p):
        padded, _ = error_buffer_shapes(p.size, world_size)
        return jnp.zeros((padded,), jnp.float32)

    def se(p):
        _, chunk = error_buffer_shapes(p.size, world_size)
        return jnp.zeros((chunk,), jnp.float32)
    return jax.tree.map(we, params), jax.tree.map(se, params)


def onebit_adam(learning_rate: ScheduleOrFloat,
                b1: float = 0.9, b2: float = 0.999, eps: float = 1e-8,
                weight_decay: float = 0.0,
                freeze_step: int = 100000,
                world_size: int = 1,
                axis_name: Optional[str] = None,
                update_clip: float = 10.0) -> optax.GradientTransformation:
    """reference: runtime/fp16/onebit/adam.py:14 (OnebitAdam).

    ``update_clip`` is a TPU-side stabilization absent in the reference: in the
    compressed stage each coordinate's raw update ``m/(sqrt(v_frozen)+eps)`` is
    clipped elementwise to ±update_clip. Healthy coordinates sit at O(1); only
    near-zero-variance coordinates (which the reference handles with a
    hand-written ``exp_avg_mask``) are affected."""

    def init(params):
        we, se = _error_buffers(params, world_size)
        return OneBitAdamState(
            count=jnp.zeros([], jnp.int32),
            exp_avg=jax.tree.map(lambda p: jnp.zeros_like(p, jnp.float32), params),
            exp_avg_sq=jax.tree.map(lambda p: jnp.zeros_like(p, jnp.float32), params),
            worker_error=we, server_error=se)

    def update(grads, state, params=None):
        count = state.count + 1
        # compression starts at step freeze_step+1: the reference flips
        # adam_freeze_key at the END of the step where step >= freeze_step
        # (adam.py:249-252), so the first compressed step is > freeze_step
        frozen = count > freeze_step

        def warmup(_):
            m = jax.tree.map(lambda m, g: b1 * m + (1 - b1) * g.astype(jnp.float32),
                             state.exp_avg, grads)
            v = jax.tree.map(lambda v, g: b2 * v + (1 - b2) * jnp.square(
                g.astype(jnp.float32)), state.exp_avg_sq, grads)
            bc1 = 1 - b1 ** count.astype(jnp.float32)
            bc2 = 1 - b2 ** count.astype(jnp.float32)
            upd = jax.tree.map(
                lambda m, v: (m / bc1) / (jnp.sqrt(v / bc2) + eps), m, v)
            return upd, m, v, state.worker_error, state.server_error

        def compressed(_):
            m = jax.tree.map(lambda m, g: b1 * m + (1 - b1) * g.astype(jnp.float32),
                             state.exp_avg, grads)
            flat_m, tree = jax.tree.flatten(m)
            flat_we = jax.tree.leaves(state.worker_error)
            flat_se = jax.tree.leaves(state.server_error)
            outs = [_compress_leaf(mm, we, se, axis_name)
                    for mm, we, se in zip(flat_m, flat_we, flat_se)]
            # automatic exp_avg_mask (reference adam.py:218-227): coordinates
            # whose frozen variance is exactly zero saw no gradient during
            # warmup — sign-compression noise there would divide by eps and
            # explode, so mask both the momentum and the update
            mask = jax.tree.map(lambda v: (v > 0).astype(jnp.float32),
                                state.exp_avg_sq)
            m_c = jax.tree.unflatten(tree, [o[0] for o in outs])
            m_c = jax.tree.map(jnp.multiply, m_c, mask)
            new_we = jax.tree.unflatten(tree, [o[1] for o in outs])
            new_se = jax.tree.unflatten(tree, [o[2] for o in outs])
            # frozen variance, no bias correction (reference adam.py:230);
            # elementwise trust clip guards tiny-variance coordinates
            upd = jax.tree.map(
                lambda m, v: jnp.clip(m / (jnp.sqrt(v) + eps),
                                      -update_clip, update_clip),
                m_c, state.exp_avg_sq)
            return upd, m_c, state.exp_avg_sq, new_we, new_se

        upd, m, v, we, se = jax.lax.cond(frozen, compressed, warmup, None)
        # LR schedules are 0-based repo-wide (optax scale_by_schedule and
        # engine.get_lr() read lr_schedule(step) pre-increment)
        lr = _lr_at(learning_rate, state.count)
        if weight_decay and params is not None:
            upd = jax.tree.map(lambda u, p: u + weight_decay * p.astype(jnp.float32),
                               upd, params)
        updates = jax.tree.map(lambda u, g: (-lr * u).astype(g.dtype), upd, grads)
        return updates, OneBitAdamState(count, m, v, we, se)

    return optax.GradientTransformation(init, update)


class ZeroOneAdamState(NamedTuple):
    count: jnp.ndarray
    exp_avg: optax.Updates
    exp_avg_sq: optax.Updates
    worker_error: optax.Updates
    server_error: optax.Updates
    var_interval: jnp.ndarray   # current variance-refresh interval
    var_counter: jnp.ndarray    # refreshes done at this interval


def zero_one_adam(learning_rate: ScheduleOrFloat,
                  b1: float = 0.9, b2: float = 0.999, eps: float = 1e-8,
                  weight_decay: float = 0.0,
                  var_freeze_step: int = 100000,
                  var_update_scaler: int = 16,
                  world_size: int = 1,
                  axis_name: Optional[str] = None,
                  update_clip: float = 10.0) -> optax.GradientTransformation:
    """reference: runtime/fp16/onebit/zoadam.py (ZeroOneAdam). Variance updates
    happen when ``count % var_interval == 0``; after ``var_update_scaler``
    refreshes the interval doubles (exponential policy, zoadam.py:269-277);
    past ``var_freeze_step`` the variance never refreshes again. Momentum is
    1-bit-compressed with error feedback from step one."""

    def init(params):
        we, se = _error_buffers(params, world_size)
        return ZeroOneAdamState(
            count=jnp.zeros([], jnp.int32),
            exp_avg=jax.tree.map(lambda p: jnp.zeros_like(p, jnp.float32), params),
            exp_avg_sq=jax.tree.map(lambda p: jnp.zeros_like(p, jnp.float32), params),
            worker_error=we, server_error=se,
            var_interval=jnp.ones([], jnp.int32),
            var_counter=jnp.zeros([], jnp.int32))

    def update(grads, state, params=None):
        count = state.count + 1
        m = jax.tree.map(lambda m, g: b1 * m + (1 - b1) * g.astype(jnp.float32),
                         state.exp_avg, grads)
        flat_m, tree = jax.tree.flatten(m)
        outs = [_compress_leaf(mm, we, se, axis_name)
                for mm, we, se in zip(flat_m, jax.tree.leaves(state.worker_error),
                                      jax.tree.leaves(state.server_error))]
        m_c = jax.tree.unflatten(tree, [o[0] for o in outs])
        new_we = jax.tree.unflatten(tree, [o[1] for o in outs])
        new_se = jax.tree.unflatten(tree, [o[2] for o in outs])

        refresh = jnp.logical_and(count % state.var_interval == 0,
                                  count <= var_freeze_step)
        v = jax.tree.map(
            lambda v, g: jnp.where(refresh,
                                   b2 * v + (1 - b2) * jnp.square(g.astype(jnp.float32)),
                                   v),
            state.exp_avg_sq, grads)
        # mask zero-variance coordinates (no gradient signal yet) — same guard
        # as onebit_adam's automatic exp_avg_mask
        m_c = jax.tree.map(lambda m, v: m * (v > 0).astype(jnp.float32), m_c, v)
        var_counter = jnp.where(refresh, state.var_counter + 1, state.var_counter)
        grow = var_counter >= var_update_scaler
        var_interval = jnp.where(grow, state.var_interval * 2, state.var_interval)
        var_counter = jnp.where(grow, 0, var_counter)

        upd = jax.tree.map(
            lambda m, v: jnp.clip(m / (jnp.sqrt(v) + eps), -update_clip, update_clip),
            m_c, v)
        # LR schedules are 0-based repo-wide (optax scale_by_schedule and
        # engine.get_lr() read lr_schedule(step) pre-increment)
        lr = _lr_at(learning_rate, state.count)
        if weight_decay and params is not None:
            upd = jax.tree.map(lambda u, p: u + weight_decay * p.astype(jnp.float32),
                               upd, params)
        updates = jax.tree.map(lambda u, g: (-lr * u).astype(g.dtype), upd, grads)
        return updates, ZeroOneAdamState(count, m_c, v, new_we, new_se,
                                         var_interval, var_counter)

    return optax.GradientTransformation(init, update)


class OneBitLambState(NamedTuple):
    count: jnp.ndarray
    exp_avg: optax.Updates
    exp_avg_sq: optax.Updates
    worker_error: optax.Updates
    server_error: optax.Updates
    frozen_ratio: optax.Updates  # per-tensor trust ratio recorded during warmup


def onebit_lamb(learning_rate: ScheduleOrFloat,
                b1: float = 0.9, b2: float = 0.999, eps: float = 1e-6,
                weight_decay: float = 0.0,
                freeze_step: int = 100000,
                max_coeff: float = 10.0, min_coeff: float = 0.01,
                world_size: int = 1,
                axis_name: Optional[str] = None,
                update_clip: float = 10.0) -> optax.GradientTransformation:
    """reference: runtime/fp16/onebit/lamb.py (OnebitLamb). Warmup = LAMB with
    live trust ratios (clipped to [min_coeff, max_coeff]), recorded per tensor;
    compressed stage reuses the frozen ratios with 1-bit momentum."""

    def trust_ratio(p, u):
        pn = jnp.linalg.norm(p.astype(jnp.float32))
        un = jnp.linalg.norm(u)
        raw = jnp.where(un > 0, pn / jnp.maximum(un, 1e-12), 1.0)
        return jnp.clip(jnp.where(pn > 0, raw, 1.0), min_coeff, max_coeff)

    def init(params):
        we, se = _error_buffers(params, world_size)
        return OneBitLambState(
            count=jnp.zeros([], jnp.int32),
            exp_avg=jax.tree.map(lambda p: jnp.zeros_like(p, jnp.float32), params),
            exp_avg_sq=jax.tree.map(lambda p: jnp.zeros_like(p, jnp.float32), params),
            worker_error=we, server_error=se,
            frozen_ratio=jax.tree.map(lambda p: jnp.ones([], jnp.float32), params))

    def update(grads, state, params=None):
        if params is None:
            raise ValueError("onebit_lamb requires params (trust ratio)")
        count = state.count + 1
        # compression starts at step freeze_step+1: the reference flips
        # adam_freeze_key at the END of the step where step >= freeze_step
        # (adam.py:249-252), so the first compressed step is > freeze_step
        frozen = count > freeze_step

        def warmup(_):
            m = jax.tree.map(lambda m, g: b1 * m + (1 - b1) * g.astype(jnp.float32),
                             state.exp_avg, grads)
            v = jax.tree.map(lambda v, g: b2 * v + (1 - b2) * jnp.square(
                g.astype(jnp.float32)), state.exp_avg_sq, grads)
            bc1 = 1 - b1 ** count.astype(jnp.float32)
            bc2 = 1 - b2 ** count.astype(jnp.float32)
            upd = jax.tree.map(
                lambda m, v, p: (m / bc1) / (jnp.sqrt(v / bc2) + eps)
                + weight_decay * p.astype(jnp.float32), m, v, params)
            ratios = jax.tree.map(trust_ratio, params, upd)
            return upd, m, v, state.worker_error, state.server_error, ratios

        def compressed(_):
            m = jax.tree.map(lambda m, g: b1 * m + (1 - b1) * g.astype(jnp.float32),
                             state.exp_avg, grads)
            flat_m, tree = jax.tree.flatten(m)
            outs = [_compress_leaf(mm, we, se, axis_name)
                    for mm, we, se in zip(flat_m, jax.tree.leaves(state.worker_error),
                                          jax.tree.leaves(state.server_error))]
            mask = jax.tree.map(lambda v: (v > 0).astype(jnp.float32),
                                state.exp_avg_sq)
            m_c = jax.tree.unflatten(tree, [o[0] for o in outs])
            m_c = jax.tree.map(jnp.multiply, m_c, mask)
            new_we = jax.tree.unflatten(tree, [o[1] for o in outs])
            new_se = jax.tree.unflatten(tree, [o[2] for o in outs])
            upd = jax.tree.map(
                lambda m, v, p: jnp.clip(m / (jnp.sqrt(v) + eps),
                                         -update_clip, update_clip)
                + weight_decay * p.astype(jnp.float32), m_c, state.exp_avg_sq, params)
            return upd, m_c, state.exp_avg_sq, new_we, new_se, state.frozen_ratio

        upd, m, v, we, se, ratios = jax.lax.cond(frozen, compressed, warmup, None)
        # LR schedules are 0-based repo-wide (optax scale_by_schedule and
        # engine.get_lr() read lr_schedule(step) pre-increment)
        lr = _lr_at(learning_rate, state.count)
        updates = jax.tree.map(lambda u, r, g: (-lr * r * u).astype(g.dtype),
                               upd, ratios, grads)
        return updates, OneBitLambState(count, m, v, we, se, ratios)

    return optax.GradientTransformation(init, update)
