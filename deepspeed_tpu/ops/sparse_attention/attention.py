"""Block-sparse attention compute over a sparsity layout.

Reference analog: ``deepspeed/ops/sparse_attention/{matmul.py:819,
softmax.py:296}`` + ``sparse_self_attention.py`` — Triton block-sparse SDD/DSD
matmuls with a block-masked softmax between them.

TPU shape: two paths over the same [H, nb, nb] layout:

- ``block_sparse_attention`` — blockwise online-softmax in ``lax.scan``
  (flash-style O(S) memory) with the layout folded into the mask; fully
  differentiable, runs anywhere. XLA still executes all block panels (masked),
  so this is the numerics/autodiff path.
- ``pallas_block_sparse_attention`` — the Pallas grid kernel: the layout rides
  as a scalar-prefetch argument and inactive (layout==0) blocks are predicated
  out with ``pl.when``, so the MXU executes only the live blocks — compute
  proportional to the layout density, the Triton kernels' actual win.

Both follow the reference semantics: token (i, j) may attend iff
``layout[h, i//block, j//block] == 1``; layouts already encode causality
(unidirectional configs emit lower-triangular layouts) at *block* granularity,
and ``causal=True`` additionally applies the exact token-level triangle.
"""

import functools
from typing import Optional

import jax
import jax.numpy as jnp
import numpy as np
from jax.experimental import pallas as pl
from jax.experimental.pallas import tpu as pltpu

NEG_INF = -1e30


def dense_mask_from_layout(layout, block: int, seq_len: int):
    """[H, nb, nb] {0,1} -> [H, S, S] boolean token mask (test oracle)."""
    m = np.repeat(np.repeat(np.asarray(layout, bool), block, 1), block, 2)
    return m[:, :seq_len, :seq_len]


def sparse_attention_reference(q, k, v, layout, block: int,
                               causal: bool = False):
    """Naive masked softmax oracle. q,k,v: [B, S, H, D]."""
    s = jnp.einsum("bqhd,bkhd->bhqk", q, k).astype(jnp.float32) / \
        np.sqrt(q.shape[-1])
    mask = jnp.asarray(dense_mask_from_layout(layout, block, q.shape[1]))
    if causal:
        sq = q.shape[1]
        mask = jnp.logical_and(
            mask, (jnp.arange(sq)[:, None] >= jnp.arange(sq)[None, :]))
    s = jnp.where(mask[None], s, NEG_INF)
    p = jax.nn.softmax(s, axis=-1)
    # fully-masked rows (no live block) produce uniform probs; zero them like
    # the blocked implementations (l == 0 -> output 0)
    alive = mask.any(-1)[None, ..., None]
    return jnp.einsum("bhqk,bkhd->bqhd",
                      jnp.where(alive, p, 0.0).astype(v.dtype), v)


@functools.partial(jax.jit, static_argnames=("block", "causal"))
def block_sparse_attention(q, k, v, layout, block: int, causal: bool = False):
    """Blockwise lax path (differentiable). q,k,v: [B, S, H, D];
    layout: [H, nb, nb]."""
    b, sq, h, d = q.shape
    nb = sq // block
    scale = 1.0 / np.sqrt(d)
    qb = q.reshape(b, nb, block, h, d).transpose(1, 0, 3, 2, 4)  # [nb,B,H,blk,D]
    kb = k.reshape(b, nb, block, h, d).transpose(1, 0, 3, 2, 4)
    vb = v.reshape(b, nb, block, h, d).transpose(1, 0, 3, 2, 4)
    lay = jnp.asarray(layout)

    def per_q_block(qi, q_blk):
        def kv_step(carry, inputs):
            m, l, o = carry
            ki, k_blk, v_blk = inputs
            s = jnp.einsum("bhqd,bhkd->bhqk", q_blk, k_blk,
                           preferred_element_type=jnp.float32) * scale
            live = lay[:, qi, ki].astype(bool)          # [H]
            mask = jnp.broadcast_to(live[None, :, None, None], s.shape)
            if causal:
                qpos = qi * block + jnp.arange(block)
                kpos = ki * block + jnp.arange(block)
                mask = jnp.logical_and(
                    mask, (qpos[:, None] >= kpos[None, :])[None, None])
            s = jnp.where(mask, s, NEG_INF)
            m_new = jnp.maximum(m, jnp.max(s, axis=-1))
            p = jnp.where(mask, jnp.exp(s - m_new[..., None]), 0.0)
            alpha = jnp.exp(m - m_new)
            l_new = l * alpha + jnp.sum(p, axis=-1)
            o_new = o * alpha[..., None] + jnp.einsum(
                "bhqk,bhkd->bhqd", p.astype(v_blk.dtype), v_blk,
                preferred_element_type=jnp.float32)
            return (m_new, l_new, o_new), None

        m0 = jnp.full((b, h, block), NEG_INF, jnp.float32)
        l0 = jnp.zeros((b, h, block), jnp.float32)
        o0 = jnp.zeros((b, h, block, d), jnp.float32)
        (m, l, o), _ = jax.lax.scan(kv_step, (m0, l0, o0),
                                    (jnp.arange(nb), kb, vb))
        return o / jnp.maximum(l, 1e-30)[..., None]

    outs = jax.lax.map(lambda args: per_q_block(*args), (jnp.arange(nb), qb))
    return outs.transpose(1, 0, 3, 2, 4).reshape(b, sq, h, d).astype(q.dtype)


def _sparse_kernel(lay_ref, q_ref, k_ref, v_ref, o_ref, m_scr, l_scr, acc_scr,
                   *, sm_scale, causal, block, num_k_blocks, num_heads):
    bh = pl.program_id(0)
    qi = pl.program_id(1)
    ki = pl.program_id(2)
    h = bh % num_heads

    @pl.when(ki == 0)
    def _init():
        m_scr[:] = jnp.full_like(m_scr, NEG_INF)
        l_scr[:] = jnp.zeros_like(l_scr)
        acc_scr[:] = jnp.zeros_like(acc_scr)

    def _compute():
        q, k, v = q_ref[0], k_ref[0], v_ref[0]
        s = jax.lax.dot_general(q, k, (((1,), (1,)), ((), ())),
                                preferred_element_type=jnp.float32) * sm_scale
        if causal:
            qpos = qi * block + jax.lax.broadcasted_iota(jnp.int32, s.shape, 0)
            kpos = ki * block + jax.lax.broadcasted_iota(jnp.int32, s.shape, 1)
            mask = qpos >= kpos
            s = jnp.where(mask, s, NEG_INF)
            p_mask = mask
        else:
            p_mask = jnp.ones(s.shape, bool)
        m_prev = m_scr[:]
        m_new = jnp.maximum(m_prev, jnp.max(s, axis=1, keepdims=True))
        p = jnp.where(p_mask, jnp.exp(s - m_new), 0.0)
        alpha = jnp.exp(m_prev - m_new)
        m_scr[:] = m_new
        l_scr[:] = l_scr[:] * alpha + jnp.sum(p, axis=1, keepdims=True)
        acc_scr[:] = acc_scr[:] * alpha + jax.lax.dot_general(
            p.astype(v.dtype), v, (((1,), (0,)), ((), ())),
            preferred_element_type=jnp.float32)

    # the layout lookup is THE sparsity win: dead blocks never hit the MXU
    live = lay_ref[(h * pl.num_programs(1) + qi) * num_k_blocks + ki] != 0
    pl.when(live)(_compute)

    @pl.when(ki == num_k_blocks - 1)
    def _finalize():
        o_ref[0] = (acc_scr[:] / jnp.maximum(l_scr[:], 1e-30)
                    ).astype(o_ref.dtype)


def _pallas_sparse_fwd(q, k, v, layout, block, causal, interpret):
    b, sq, h, d = q.shape
    nb = sq // block
    q2 = q.transpose(0, 2, 1, 3).reshape(b * h, sq, d)
    k2 = k.transpose(0, 2, 1, 3).reshape(b * h, sq, d)
    v2 = v.transpose(0, 2, 1, 3).reshape(b * h, sq, d)
    lay = jnp.asarray(layout, jnp.int32).reshape(-1)

    grid_spec = pltpu.PrefetchScalarGridSpec(
        num_scalar_prefetch=1,
        grid=(b * h, nb, nb),
        in_specs=[
            pl.BlockSpec((1, block, d), lambda bh, i, j, lay: (bh, i, 0)),
            pl.BlockSpec((1, block, d), lambda bh, i, j, lay: (bh, j, 0)),
            pl.BlockSpec((1, block, d), lambda bh, i, j, lay: (bh, j, 0)),
        ],
        out_specs=pl.BlockSpec((1, block, d), lambda bh, i, j, lay: (bh, i, 0)),
        scratch_shapes=[
            pltpu.VMEM((block, 1), jnp.float32),
            pltpu.VMEM((block, 1), jnp.float32),
            pltpu.VMEM((block, d), jnp.float32),
        ],
    )
    out = pl.pallas_call(
        functools.partial(_sparse_kernel, sm_scale=1.0 / np.sqrt(d),
                          causal=causal, block=block, num_k_blocks=nb,
                          num_heads=h),
        grid_spec=grid_spec,
        out_shape=jax.ShapeDtypeStruct((b * h, sq, d), q.dtype),
        interpret=interpret,
    )(lay, q2, k2, v2)
    return out.reshape(b, h, sq, d).transpose(0, 2, 1, 3)


@functools.partial(jax.custom_vjp, nondiff_argnums=(4, 5, 6))
def pallas_block_sparse_attention(q, k, v, layout, block: int,
                                  causal: bool = False,
                                  interpret: bool = False):
    """Pallas path: dead blocks are skipped on the MXU. Backward recomputes
    through the blockwise lax path (same numerics)."""
    return _pallas_sparse_fwd(q, k, v, layout, block, causal, interpret)


def _sp_fwd(q, k, v, layout, block, causal, interpret):
    out = _pallas_sparse_fwd(q, k, v, layout, block, causal, interpret)
    return out, (q, k, v, layout)


def _sp_bwd(block, causal, interpret, res, g):
    q, k, v, layout = res
    _, vjp_fn = jax.vjp(
        lambda q_, k_, v_: block_sparse_attention(q_, k_, v_, layout, block,
                                                  causal), q, k, v)
    return (*vjp_fn(g), None)


pallas_block_sparse_attention.defvjp(_sp_fwd, _sp_bwd)


class SparseSelfAttention:
    """Config-driven entry point (reference sparse_self_attention.py):
    holds a SparsityConfig, builds/caches the layout per sequence length and
    dispatches to the Pallas kernel on TPU or the lax path elsewhere."""

    def __init__(self, sparsity_config, causal: Optional[bool] = None):
        self.config = sparsity_config
        self.causal = (sparsity_config.attention == "unidirectional"
                       if causal is None and
                       hasattr(sparsity_config, "attention") else bool(causal))
        self._layouts = {}

    def layout(self, seq_len):
        if seq_len not in self._layouts:
            self._layouts[seq_len] = self.config.make_layout(seq_len)
        return self._layouts[seq_len]

    def __call__(self, q, k, v):
        lay = self.layout(q.shape[1])
        if jax.default_backend() == "tpu":
            return pallas_block_sparse_attention(q, k, v, lay,
                                                 self.config.block, self.causal)
        return block_sparse_attention(q, k, v, lay, self.config.block,
                                      self.causal)
