"""Block-sparse attention layout configs.

Reference analog: ``deepspeed/ops/sparse_attention/sparsity_config.py:727`` —
the layout-builder classes (Dense/Fixed/Variable/BigBird/BSLongformer/
LocalSlidingWindow). A *layout* is an int {0,1} array [num_heads, nb, nb]
(nb = seq_len // block) marking which [block x block] score tiles exist.

Same config surface and pattern semantics, rebuilt on numpy (layouts are
host-side static metadata; the kernels consume them as scalar-prefetch args).
Random patterns take an explicit ``seed`` so layouts are reproducible.
"""

import dataclasses
from typing import List, Optional

import numpy as np


class SparsityConfig:
    """Base: shared block/head bookkeeping (reference sparsity_config.py:10)."""

    def __init__(self, num_heads, block=16, different_layout_per_head=False):
        self.num_heads = num_heads
        self.block = block
        self.different_layout_per_head = different_layout_per_head
        self.num_layout_heads = num_heads if different_layout_per_head else 1

    def setup_layout(self, seq_len) -> np.ndarray:
        if seq_len % self.block != 0:
            raise ValueError(
                f"sequence length {seq_len} must be divisible by block "
                f"{self.block}")
        nb = seq_len // self.block
        return np.zeros((self.num_heads, nb, nb), np.int64)

    def check_and_propagate_first_head_layout(self, layout) -> np.ndarray:
        if not self.different_layout_per_head:
            layout[1:] = layout[0]
        return layout

    def make_layout(self, seq_len) -> np.ndarray:
        raise NotImplementedError


class DenseSparsityConfig(SparsityConfig):
    """All blocks present (reference :63 — the dense degenerate case)."""

    def make_layout(self, seq_len):
        layout = self.setup_layout(seq_len)
        layout[:] = 1
        return layout


class FixedSparsityConfig(SparsityConfig):
    """Sparse-Transformer 'fixed' pattern (reference :95; arxiv 1904.10509):
    local windows of ``num_local_blocks`` + per-window global representative
    columns (last ``num_global_blocks`` of each window, rotated per head when
    ``num_different_global_patterns`` > 1)."""

    def __init__(self, num_heads, block=16, different_layout_per_head=False,
                 num_local_blocks=4, num_global_blocks=1,
                 attention="bidirectional", horizontal_global_attention=False,
                 num_different_global_patterns=1):
        super().__init__(num_heads, block, different_layout_per_head)
        if num_local_blocks % num_global_blocks != 0:
            raise ValueError(
                f"num_local_blocks {num_local_blocks} must be divisible by "
                f"num_global_blocks {num_global_blocks}")
        if attention not in ("unidirectional", "bidirectional"):
            raise NotImplementedError(
                "only uni/bi-directional attention is supported")
        if attention != "bidirectional" and horizontal_global_attention:
            raise ValueError(
                "horizontal global attention requires bidirectional attention")
        if num_different_global_patterns > 1 and not different_layout_per_head:
            raise ValueError(
                "multiple global patterns require different_layout_per_head")
        if num_different_global_patterns > num_local_blocks // num_global_blocks:
            raise ValueError("too many global patterns for the local window")
        self.num_local_blocks = num_local_blocks
        self.num_global_blocks = num_global_blocks
        self.attention = attention
        self.horizontal_global_attention = horizontal_global_attention
        self.num_different_global_patterns = num_different_global_patterns

    def _set_local(self, h, layout):
        nb = layout.shape[1]
        for i in range(0, nb, self.num_local_blocks):
            end = min(i + self.num_local_blocks, nb)
            for row in range(i, end):
                cols_end = row + 1 if self.attention == "unidirectional" else end
                layout[h, row, i:cols_end] = 1
        return layout

    def _set_global(self, h, layout):
        nb = layout.shape[1]
        first = self.num_local_blocks - \
            (1 + h % self.num_different_global_patterns) * self.num_global_blocks
        end = nb - (nb % self.num_local_blocks)
        for i in range(first, end, self.num_local_blocks):
            first_row = 0 if self.attention == "bidirectional" else i
            layout[h, first_row:, i:i + self.num_global_blocks] = 1
            if self.horizontal_global_attention:
                layout[h, i:i + self.num_global_blocks, :] = 1
        if end < nb:
            start = min(end + first, nb - self.num_global_blocks)
            stop = start + self.num_global_blocks
            first_row = 0 if self.attention == "bidirectional" else start
            layout[h, first_row:, start:stop] = 1
            if self.horizontal_global_attention:
                layout[h, start:stop, :] = 1
        return layout

    def make_layout(self, seq_len):
        layout = self.setup_layout(seq_len)
        for h in range(self.num_layout_heads):
            layout = self._set_local(h, layout)
            layout = self._set_global(h, layout)
        if self.attention == "unidirectional":
            layout = np.tril(layout)
        return self.check_and_propagate_first_head_layout(layout)


class VariableSparsityConfig(SparsityConfig):
    """'Variable' pattern (reference :239): random blocks + variable-size local
    windows + explicit global block columns/rows."""

    def __init__(self, num_heads, block=16, different_layout_per_head=False,
                 num_random_blocks=0, local_window_blocks=None,
                 global_block_indices=None, global_block_end_indices=None,
                 attention="bidirectional", horizontal_global_attention=False,
                 seed=0):
        super().__init__(num_heads, block, different_layout_per_head)
        if attention not in ("unidirectional", "bidirectional"):
            raise NotImplementedError(
                "only uni/bi-directional attention is supported")
        if attention != "bidirectional" and horizontal_global_attention:
            raise ValueError(
                "horizontal global attention requires bidirectional attention")
        self.num_random_blocks = num_random_blocks
        self.local_window_blocks = local_window_blocks or [4]
        self.global_block_indices = global_block_indices or [0]
        self.global_block_end_indices = global_block_end_indices
        if global_block_end_indices is not None and \
                len(global_block_end_indices) != len(self.global_block_indices):
            raise ValueError("global block start/end index lists must align")
        self.attention = attention
        self.horizontal_global_attention = horizontal_global_attention
        self.seed = seed

    def _set_random(self, h, layout, rng):
        nb = layout.shape[1]
        for row in range(nb):
            hi = nb if self.attention == "bidirectional" else row + 1
            cols = rng.choice(hi, size=min(self.num_random_blocks, hi),
                              replace=False)
            layout[h, row, cols] = 1
        return layout

    def _set_local(self, h, layout):
        nb = layout.shape[1]
        start = 0
        wi = 0
        while start < nb:
            w = self.local_window_blocks[min(wi,
                                             len(self.local_window_blocks) - 1)]
            end = min(start + w, nb)
            for row in range(start, end):
                cols_end = row + 1 if self.attention == "unidirectional" else end
                layout[h, row, start:cols_end] = 1
            start = end
            wi += 1
        return layout

    def _set_global(self, h, layout):
        nb = layout.shape[1]
        if self.global_block_end_indices is None:
            spans = [(i, i + 1) for i in self.global_block_indices]
        else:
            spans = list(zip(self.global_block_indices,
                             self.global_block_end_indices))
        for start, end in spans:
            if start >= nb:
                continue
            end = min(end, nb)
            layout[h, :, start:end] = 1            # vertical
            if self.horizontal_global_attention:
                layout[h, start:end, :] = 1
        return layout

    def make_layout(self, seq_len):
        layout = self.setup_layout(seq_len)
        rng = np.random.default_rng(self.seed)
        for h in range(self.num_layout_heads):
            if self.num_random_blocks:
                layout = self._set_random(h, layout, rng)
            layout = self._set_local(h, layout)
            layout = self._set_global(h, layout)
        if self.attention == "unidirectional":
            layout = np.tril(layout)
        return self.check_and_propagate_first_head_layout(layout)


class BigBirdSparsityConfig(SparsityConfig):
    """BigBird (reference :411; arxiv 2007.14062): random + sliding window +
    ITC global (first ``num_global_blocks`` rows AND columns)."""

    def __init__(self, num_heads, block=16, different_layout_per_head=False,
                 num_random_blocks=1, num_sliding_window_blocks=3,
                 num_global_blocks=1, attention="bidirectional", seed=0):
        super().__init__(num_heads, block, different_layout_per_head)
        if attention not in ("unidirectional", "bidirectional"):
            raise NotImplementedError(
                "only uni/bi-directional attention is supported")
        self.num_random_blocks = num_random_blocks
        self.num_sliding_window_blocks = num_sliding_window_blocks
        self.num_global_blocks = num_global_blocks
        self.attention = attention
        self.seed = seed

    def make_layout(self, seq_len):
        layout = self.setup_layout(seq_len)
        nb = layout.shape[1]
        if nb < max(self.num_random_blocks, self.num_sliding_window_blocks,
                    self.num_global_blocks):
            raise ValueError(
                f"{nb} blocks is too few for the configured pattern")
        rng = np.random.default_rng(self.seed)
        w = self.num_sliding_window_blocks // 2
        for h in range(self.num_layout_heads):
            for row in range(nb):
                hi = nb if self.attention == "bidirectional" else row + 1
                cols = rng.choice(hi, size=min(self.num_random_blocks, hi),
                                  replace=False)
                layout[h, row, cols] = 1
                layout[h, row, max(0, row - w):min(row + w + 1, nb)] = 1
            layout[h, :self.num_global_blocks, :] = 1
            layout[h, :, :self.num_global_blocks] = 1
        if self.attention == "unidirectional":
            layout = np.tril(layout)
        return self.check_and_propagate_first_head_layout(layout)


class BSLongformerSparsityConfig(SparsityConfig):
    """Blocked Longformer (reference :546): sliding window + explicit global
    block indices (rows AND columns)."""

    def __init__(self, num_heads, block=16, different_layout_per_head=False,
                 num_sliding_window_blocks=3, global_block_indices=None,
                 global_block_end_indices=None, attention="bidirectional"):
        super().__init__(num_heads, block, different_layout_per_head)
        self.num_sliding_window_blocks = num_sliding_window_blocks
        self.global_block_indices = global_block_indices or [0]
        self.global_block_end_indices = global_block_end_indices
        if global_block_end_indices is not None and \
                len(global_block_end_indices) != len(self.global_block_indices):
            raise ValueError("global block start/end index lists must align")
        self.attention = attention

    def make_layout(self, seq_len):
        layout = self.setup_layout(seq_len)
        nb = layout.shape[1]
        w = self.num_sliding_window_blocks // 2
        for h in range(self.num_layout_heads):
            for row in range(nb):
                layout[h, row, max(0, row - w):min(row + w + 1, nb)] = 1
            if self.global_block_end_indices is None:
                spans = [(i, i + 1) for i in self.global_block_indices]
            else:
                spans = list(zip(self.global_block_indices,
                                 self.global_block_end_indices))
            for start, end in spans:
                if start >= nb:
                    continue
                end = min(end, nb)
                layout[h, start:end, :] = 1
                layout[h, :, start:end] = 1
        if self.attention == "unidirectional":
            layout = np.tril(layout)
        return self.check_and_propagate_first_head_layout(layout)


class LocalSlidingWindowSparsityConfig(SparsityConfig):
    """Purely local sliding window (reference :678)."""

    def __init__(self, num_heads, block=16, num_sliding_window_blocks=3,
                 attention="unidirectional"):
        super().__init__(num_heads, block)
        self.num_sliding_window_blocks = num_sliding_window_blocks
        self.attention = attention

    def make_layout(self, seq_len):
        layout = self.setup_layout(seq_len)
        nb = layout.shape[1]
        w = self.num_sliding_window_blocks // 2
        for h in range(self.num_layout_heads):
            for row in range(nb):
                layout[h, row, max(0, row - w):min(row + w + 1, nb)] = 1
        if self.attention == "unidirectional":
            layout = np.tril(layout)
        return self.check_and_propagate_first_head_layout(layout)
