"""Block-sparse attention (reference: deepspeed/ops/sparse_attention/)."""
from deepspeed_tpu.ops.sparse_attention.attention import (     # noqa: F401
    SparseSelfAttention, block_sparse_attention,
    dense_mask_from_layout, pallas_block_sparse_attention,
    sparse_attention_reference)
from deepspeed_tpu.ops.sparse_attention.sparsity_configs import (  # noqa: F401
    BigBirdSparsityConfig, BSLongformerSparsityConfig, DenseSparsityConfig,
    FixedSparsityConfig, LocalSlidingWindowSparsityConfig, SparsityConfig,
    VariableSparsityConfig)
