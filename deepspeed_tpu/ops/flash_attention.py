"""Memory-efficient blockwise attention (flash-attention semantics).

Reference analog: the fused attention kernels in
``csrc/transformer/inference/csrc/softmax.cu`` + the training transformer kernel
(``csrc/transformer``), and the v2 ``blocked_flash`` ragged kernels.

TPU-native design: an online-softmax blockwise computation expressed in ``lax.scan``
so XLA tiles the [block_q, block_k] score panels onto the MXU and never materializes
the full [S, S] score matrix; O(S) memory, autodiff for free (the backward pass
recomputes per-block under the scan, flash-style). A hand-written Pallas kernel with
the same interface lives in ``deepspeed_tpu.ops.pallas.flash_attention`` and is used
when shapes meet its tiling constraints; this module is the portable fallback and
the numerics reference for kernel tests.
"""

from functools import partial
from typing import Optional

import jax
import jax.numpy as jnp
import numpy as np

NEG_INF = -1e30


def _repeat_kv(k, v, num_heads: int):
    hkv = k.shape[2]
    if hkv != num_heads:
        rep = num_heads // hkv
        k = jnp.repeat(k, rep, axis=2)
        v = jnp.repeat(v, rep, axis=2)
    return k, v


@partial(jax.jit, static_argnames=("causal", "block_q", "block_k"))
def flash_attention(q, k, v, causal: bool = True, segment_ids=None,
                    block_q: int = 512, block_k: int = 512,
                    q_offset: int = 0, k_offset: int = 0):
    """q: [B, Sq, H, D]; k,v: [B, Sk, Hkv, D] -> [B, Sq, H, D].

    ``q_offset``/``k_offset`` shift global positions (used by ring attention where
    each shard holds a slice of the global sequence).
    """
    b, sq, h, d = q.shape
    sk = k.shape[1]
    k, v = _repeat_kv(k, v, h)
    block_q = min(block_q, sq)
    block_k = min(block_k, sk)
    # pad to multiples
    pad_q = (-sq) % block_q
    pad_k = (-sk) % block_k
    if pad_q:
        q = jnp.pad(q, ((0, 0), (0, pad_q), (0, 0), (0, 0)))
    if pad_k:
        k = jnp.pad(k, ((0, 0), (0, pad_k), (0, 0), (0, 0)))
        v = jnp.pad(v, ((0, 0), (0, pad_k), (0, 0), (0, 0)))
    nq, nk = q.shape[1] // block_q, k.shape[1] // block_k

    scale = 1.0 / np.sqrt(d)
    q_blocks = q.reshape(b, nq, block_q, h, d).transpose(1, 0, 3, 2, 4)  # [nq,B,H,bq,D]
    k_blocks = k.reshape(b, nk, block_k, h, d).transpose(1, 0, 3, 2, 4)
    v_blocks = v.reshape(b, nk, block_k, h, d).transpose(1, 0, 3, 2, 4)

    kv_valid = jnp.arange(nk * block_k) < sk    # mask out k padding

    def per_q_block(qi, q_blk):
        qpos = q_offset + qi * block_q + jnp.arange(block_q)

        def kv_step(carry, inputs):
            m, l, o = carry
            ki, k_blk, v_blk = inputs
            kpos = k_offset + ki * block_k + jnp.arange(block_k)
            s = jnp.einsum("bhqd,bhkd->bhqk", q_blk, k_blk,
                           preferred_element_type=jnp.float32) * scale
            mask = kv_valid[ki * block_k + jnp.arange(block_k)][None, None, None, :]
            if causal:
                mask = jnp.logical_and(mask,
                                       (qpos[:, None] >= kpos[None, :])[None, None])
            s = jnp.where(mask, s, NEG_INF)
            m_new = jnp.maximum(m, jnp.max(s, axis=-1))
            p = jnp.where(mask, jnp.exp(s - m_new[..., None]), 0.0)
            alpha = jnp.exp(m - m_new)
            l_new = l * alpha + jnp.sum(p, axis=-1)
            o_new = o * alpha[..., None] + jnp.einsum(
                "bhqk,bhkd->bhqd", p.astype(v_blk.dtype), v_blk,
                preferred_element_type=jnp.float32)
            return (m_new, l_new, o_new), None

        m0 = jnp.full((b, h, block_q), NEG_INF, jnp.float32)
        l0 = jnp.zeros((b, h, block_q), jnp.float32)
        o0 = jnp.zeros((b, h, block_q, d), jnp.float32)
        (m, l, o), _ = jax.lax.scan(
            kv_step, (m0, l0, o0),
            (jnp.arange(nk), k_blocks, v_blocks))
        out = o / jnp.maximum(l, 1e-30)[..., None]
        return out  # [B, H, bq, D]

    outs = jax.lax.map(lambda args: per_q_block(*args), (jnp.arange(nq), q_blocks))
    out = outs.transpose(1, 0, 3, 2, 4).reshape(b, nq * block_q, h, d)
    return out[:, :sq].astype(q.dtype)


def attention_reference(q, k, v, causal: bool = True, window=None,
                        segment_ids=None):
    """Naive O(S^2)-memory reference for kernel tests (analog of the torch
    reference implementations in tests/unit/ops). ``window`` masks to the
    band (t-window, t] — a window implies causal banding (mistral);
    ``segment_ids`` [B, S] confines attention within packed segments."""
    b, sq, h, d = q.shape
    k, v = _repeat_kv(k, v, h)
    s = jnp.einsum("bqhd,bkhd->bhqk", q, k).astype(jnp.float32) / np.sqrt(d)
    if causal or window is not None:
        sk = k.shape[1]
        qpos = jnp.arange(sq)[:, None] + (sk - sq)
        kpos = jnp.arange(sk)[None, :]
        mask = qpos >= kpos
        if window is not None:
            mask = jnp.logical_and(mask, kpos > qpos - window)
        s = jnp.where(mask[None, None], s, NEG_INF)
    if segment_ids is not None:
        seg = jnp.asarray(segment_ids)
        seg_mask = seg[:, :, None] == seg[:, None, :]
        s = jnp.where(seg_mask[:, None], s, NEG_INF)
    p = jax.nn.softmax(s, axis=-1)
    return jnp.einsum("bhqk,bkhd->bqhd", p.astype(v.dtype), v)
