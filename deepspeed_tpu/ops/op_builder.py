"""JIT build system for native host ops.

Reference analog: ``op_builder/builder.py:109,514,533`` (``OpBuilder.load()`` —
ninja JIT compile + cache of CUDA/C++ extensions, per-accelerator builder dirs).
TPU-side the native surface is host C++ only (device kernels are Pallas), so the
builder reduces to: g++ a .cpp into a cached .so, bind via ctypes (no pybind11 in
this image). Compilation is keyed on source hash; concurrent builds race safely
via atomic rename.
"""

import ctypes
import hashlib
import os
import platform
import subprocess
import tempfile
from typing import List, Optional

from deepspeed_tpu.utils.logging import logger

CSRC_DIR = os.path.join(os.path.dirname(os.path.abspath(__file__)), "csrc")
CACHE_DIR = os.environ.get(
    "DSTPU_OP_CACHE", os.path.join(os.path.expanduser("~"), ".cache", "deepspeed_tpu"))

DEFAULT_FLAGS = ["-O3", "-march=native", "-fopenmp", "-fPIC", "-shared", "-std=c++17"]


def _cpu_identity() -> str:
    """Model name + flags line from /proc/cpuinfo (best effort)."""
    try:
        with open("/proc/cpuinfo") as f:
            for line in f:
                if line.startswith(("model name", "flags")):
                    return line.strip()
    except OSError:
        pass
    return platform.processor() or "unknown-cpu"


class OpBuilder:
    """Build + load one native op library (reference: OpBuilder ABC)."""

    def __init__(self, name: str, sources: List[str],
                 extra_flags: Optional[List[str]] = None):
        self.name = name
        self.sources = [s if os.path.isabs(s) else os.path.join(CSRC_DIR, s)
                        for s in sources]
        self.flags = DEFAULT_FLAGS + (extra_flags or [])
        self._lib: Optional[ctypes.CDLL] = None

    def _cache_key(self) -> str:
        h = hashlib.sha256()
        for s in self.sources:
            with open(s, "rb") as f:
                h.update(f.read())
        h.update(" ".join(self.flags).encode())
        # -march=native binaries are host-specific: key on the CPU identity so a
        # shared (NFS) cache dir across heterogeneous hosts never serves a .so
        # built for another microarchitecture (SIGILL otherwise).
        h.update(platform.machine().encode())
        h.update(_cpu_identity().encode())
        return h.hexdigest()[:16]

    def is_compatible(self) -> bool:
        from shutil import which
        return which("g++") is not None

    def load(self) -> ctypes.CDLL:
        """Compile (cached) and dlopen (reference: OpBuilder.load :533)."""
        if self._lib is not None:
            return self._lib
        # AOT artifact first (DSTPU_BUILD_OPS=1 install pre-compiles next to
        # the sources — reference setup.py ext_modules path). Only trusted
        # when its source-hash sidecar matches the current sources: a stale
        # or foreign-host artifact falls back to the keyed JIT cache.
        aot = os.path.join(CSRC_DIR, f"{self.name}.so")
        sidecar = aot + ".src"
        if os.path.exists(aot) and os.path.exists(sidecar):
            import hashlib
            # hash ALL sources (registration order) plus the compile flags so
            # a stale artifact is rejected when either changes — e.g. an op
            # gaining a flag like -pthread must invalidate installs built
            # without it. Must stay in sync with setup.py:_sidecar_hash.
            want = hashlib.sha256(
                b"".join(open(s, "rb").read() for s in self.sources) +
                b"\0" + " ".join(self.flags).encode()).hexdigest()[:16]
            if open(sidecar).read().strip() == want:
                self._lib = ctypes.CDLL(aot)
                return self._lib
        if not self.is_compatible():
            raise RuntimeError(f"op '{self.name}': no g++ available")
        os.makedirs(CACHE_DIR, exist_ok=True)
        so_path = os.path.join(CACHE_DIR, f"{self.name}_{self._cache_key()}.so")
        if not os.path.exists(so_path):
            fd, tmp = tempfile.mkstemp(suffix=".so", dir=CACHE_DIR)
            os.close(fd)
            cmd = ["g++"] + self.flags + self.sources + ["-o", tmp]
            logger.info(f"building native op '{self.name}': {' '.join(cmd)}")
            try:
                subprocess.run(cmd, check=True, capture_output=True, text=True)
            except subprocess.CalledProcessError as e:
                os.unlink(tmp)
                raise RuntimeError(
                    f"native op '{self.name}' build failed:\n{e.stderr}") from e
            os.replace(tmp, so_path)  # atomic under concurrent builders
        self._lib = ctypes.CDLL(so_path)
        return self._lib


def _make_ops():
    return {
        "cpu_adam": OpBuilder("cpu_adam", ["cpu_adam.cpp"]),
        "aio": OpBuilder("aio", ["aio.cpp"], extra_flags=["-pthread"]),
    }


# Registry of known native ops (reference: op_builder/all_ops.py).
OPS = _make_ops()


def get_op(name: str) -> ctypes.CDLL:
    if name not in OPS:
        raise ValueError(f"unknown native op '{name}'")
    return OPS[name].load()
