"""Abstract accelerator interface.

TPU-native analog of the reference's hardware-abstraction layer
(``accelerator/abstract_accelerator.py:10`` ``DeepSpeedAccelerator`` ABC). Where the
reference abstracts over CUDA/HPU/XPU device runtimes for an eager framework, here the
abstraction is over **JAX platforms** (tpu / cpu / gpu): device enumeration, memory
introspection, dtype support, collective-backend name, and profiler hooks. Streams,
events and per-op allocators do not exist in the XLA execution model — XLA owns
scheduling and memory — so those reference methods map onto async-dispatch /
``block_until_ready`` semantics.
"""

import abc
from typing import Any, List


class Accelerator(abc.ABC):
    """Platform abstraction consumed by every other layer (cf. get_accelerator())."""

    _name: str = "abstract"

    @property
    def name(self) -> str:
        return self._name

    # --- device management -------------------------------------------------
    @abc.abstractmethod
    def devices(self) -> List[Any]:
        """All addressable devices for this process."""

    @abc.abstractmethod
    def device_count(self) -> int:
        ...

    def global_device_count(self) -> int:
        import jax
        return jax.device_count()

    def process_index(self) -> int:
        import jax
        return jax.process_index()

    def process_count(self) -> int:
        import jax
        return jax.process_count()

    @abc.abstractmethod
    def communication_backend_name(self) -> str:
        """Name of the collective fabric ('ici+dcn' on TPU, 'xla-cpu' on CPU)."""

    # --- synchronization ---------------------------------------------------
    def synchronize(self) -> None:
        """Drain the async dispatch queue (the XLA analog of cudaDeviceSynchronize)."""
        import jax
        import jax.numpy as jnp
        jax.block_until_ready(jnp.zeros(()))

    # --- memory ------------------------------------------------------------
    def memory_stats(self) -> dict:
        """Best-effort live/peak bytes per device (reference: memory_allocated etc.)."""
        stats = {}
        for d in self.devices():
            try:
                s = d.memory_stats()
            except Exception:
                s = None
            if s:
                stats[str(d)] = {
                    "bytes_in_use": s.get("bytes_in_use", 0),
                    "peak_bytes_in_use": s.get("peak_bytes_in_use", 0),
                    "bytes_limit": s.get("bytes_limit", 0),
                }
        return stats

    def total_memory(self) -> int:
        total = 0
        for s in self.memory_stats().values():
            total += s.get("bytes_limit", 0)
        return total

    # --- dtype support -----------------------------------------------------
    def is_bf16_supported(self) -> bool:
        return True

    def is_fp16_supported(self) -> bool:
        return True

    def preferred_dtype(self):
        import jax.numpy as jnp
        return jnp.bfloat16

    # --- profiler / tracing ------------------------------------------------
    def range_push(self, name: str):
        """Named trace annotation (reference: nvtx range_push). Routed
        through ``utils.nvtx.annotate`` so the range also lands in the
        dstrace timeline when tracing is on."""
        from deepspeed_tpu.utils.nvtx import annotate
        return annotate(name)

    # --- op-builder dir (kept for API parity; see deepspeed_tpu.ops) -------
    def op_builder_dir(self) -> str:
        return "deepspeed_tpu.ops"

    # --- flops -------------------------------------------------------------
    def peak_tflops(self, dtype: str = "bf16") -> float:
        """Advertised peak TFLOPS per chip for MFU math; 0 when unknown."""
        return 0.0
