"""CPU accelerator — the CI / development backend.

Reference analog: ``accelerator/cpu_accelerator.py:28`` (gloo backend lets the whole
suite run without GPUs). Here the JAX CPU platform plays that role; with
``XLA_FLAGS=--xla_force_host_platform_device_count=N`` it exposes N virtual devices so
multi-chip sharding is exercised on one host.
"""

from typing import Any, List

from deepspeed_tpu.accelerator.abstract_accelerator import Accelerator


class CPUAccelerator(Accelerator):
    _name = "cpu"

    def devices(self) -> List[Any]:
        import jax
        return [d for d in jax.local_devices() if d.platform == "cpu"] or jax.local_devices()

    def device_count(self) -> int:
        return len(self.devices())

    def communication_backend_name(self) -> str:
        return "xla-cpu"

    def memory_stats(self) -> dict:
        return {}
