"""Accelerator auto-detection.

Reference analog: ``accelerator/real_accelerator.py:51`` (env override
``DS_ACCELERATOR`` + probe-based detection). Here detection is by JAX platform;
override with ``DSTPU_ACCELERATOR=cpu|tpu``.
"""

import os
from typing import Optional

from deepspeed_tpu.accelerator.abstract_accelerator import Accelerator

_accelerator: Optional[Accelerator] = None


def _detect() -> Accelerator:
    from deepspeed_tpu.accelerator.cpu_accelerator import CPUAccelerator
    from deepspeed_tpu.accelerator.tpu_accelerator import TPUAccelerator

    override = os.environ.get("DSTPU_ACCELERATOR", "").lower()
    if override == "cpu":
        return CPUAccelerator()
    if override == "tpu":
        return TPUAccelerator()

    try:
        import jax
        platform = jax.local_devices()[0].platform
    except Exception:
        platform = "cpu"
    # Treat any non-cpu XLA platform (tpu, experimental tunnels) as the TPU path.
    if platform != "cpu":
        return TPUAccelerator()
    return CPUAccelerator()


def get_accelerator() -> Accelerator:
    global _accelerator
    if _accelerator is None:
        _accelerator = _detect()
    return _accelerator


def set_accelerator(acc: Accelerator) -> None:
    global _accelerator
    _accelerator = acc
