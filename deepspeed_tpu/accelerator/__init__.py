from deepspeed_tpu.accelerator.abstract_accelerator import Accelerator
from deepspeed_tpu.accelerator.real_accelerator import get_accelerator, set_accelerator

__all__ = ["Accelerator", "get_accelerator", "set_accelerator"]
