"""TPU accelerator (the first-class platform).

Reference analog: ``accelerator/cuda_accelerator.py``. Peak-TFLOPS table is used for
MFU reporting by the throughput timer / flops profiler.
"""

from typing import Any, List

from deepspeed_tpu.accelerator.abstract_accelerator import Accelerator

# chip generation -> peak dense TFLOPS (bf16). Public figures.
_PEAK_TFLOPS_BF16 = {
    "v4": 275.0,
    "v5 lite": 197.0,   # v5e
    "v5e": 197.0,
    "v5p": 459.0,
    "v6 lite": 918.0,   # trillium
    "v6e": 918.0,
}


class TPUAccelerator(Accelerator):
    _name = "tpu"

    def devices(self) -> List[Any]:
        import jax
        return jax.local_devices()

    def device_count(self) -> int:
        return len(self.devices())

    def communication_backend_name(self) -> str:
        return "ici+dcn"

    def peak_tflops(self, dtype: str = "bf16") -> float:
        devs = self.devices()
        if not devs:
            return 0.0
        kind = getattr(devs[0], "device_kind", "").lower()
        for key, tflops in _PEAK_TFLOPS_BF16.items():
            if key in kind:
                scale = 1.0
                if dtype in ("int8", "fp8"):
                    scale = 2.0
                elif dtype == "fp32":
                    scale = 0.5
                return tflops * scale
        return 0.0
