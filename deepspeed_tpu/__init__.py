"""deepspeed_tpu — a TPU-native distributed training & inference framework.

Capability surface of DeepSpeed (see SURVEY.md), re-designed for TPU: named-axis
device meshes + pjit sharding instead of runtime partition hooks, one fused compiled
train step, Pallas kernels for hot ops, XLA collectives over ICI/DCN.

Public API parity (reference: ``deepspeed/__init__.py``):
- ``initialize(...)`` (:69) → (engine, optimizer, dataloader, lr_scheduler)
- ``init_inference(...)`` (:291)
- ``add_config_arguments(...)`` (:268)
"""

from typing import Any, Callable, Optional

import jax

from deepspeed_tpu.utils import jax_compat  # noqa: F401  (aliases drifted jax APIs)

__version__ = "0.2.0"

from deepspeed_tpu.accelerator import get_accelerator  # noqa: F401
from deepspeed_tpu.comm import mesh as _mesh_lib
from deepspeed_tpu.config.config import DeepSpeedTPUConfig
from deepspeed_tpu.runtime.engine import DeepSpeedTPUEngine
from deepspeed_tpu.utils.logging import log_dist, logger  # noqa: F401


def initialize(args=None,
               model=None,
               optimizer=None,
               model_parameters=None,
               training_data=None,
               lr_scheduler=None,
               distributed_port: Optional[int] = None,
               mesh=None,
               mpu=None,
               dist_init_required: Optional[bool] = None,
               collate_fn: Optional[Callable] = None,
               config: Any = None,
               config_params: Any = None,
               loss_fn: Optional[Callable] = None,
               example_batch: Any = None,
               tensor_rules: Optional[Callable] = None,
               seed: int = 0):
    """Build the engine (reference: deepspeed.initialize, deepspeed/__init__.py:69).

    Returns ``(engine, optimizer, training_dataloader, lr_scheduler)`` like the
    reference. ``model`` is a flax Module or a callable
    ``apply_fn(params, batch, rng) -> loss``; ``model_parameters`` is the params
    pytree (or None to init from ``example_batch``).
    """
    if config is None and config_params is not None:
        config = config_params
    if config is None and args is not None:
        config = getattr(args, "deepspeed_config", None)
    # initialize() is THE training entry point: an elastic-agent relaunch's
    # escalated-ladder overrides (DSTPU_ELASTIC_CONFIG_OVERRIDES) apply
    # here and only here
    ds_config = config if isinstance(config, DeepSpeedTPUConfig) \
        else DeepSpeedTPUConfig(config, apply_elastic_overrides=True)

    if dist_init_required is None:
        # auto (reference: deepspeed.initialize always ensures the process
        # group, __init__.py:143): join the multi-process rendezvous when a
        # launcher's env (DSTPU_*/torch-style) announces one and the user
        # hasn't already initialized jax.distributed themselves. Mirrors
        # init_distributed's own trigger (num_processes>1 OR a coordinator
        # address alone — launchers may set a subset); discovery runs once
        # and its kwargs are passed through
        disc = _mesh_lib.discover_cluster_env()
        if (not jax.distributed.is_initialized()
                and (disc.get("num_processes", 1) > 1
                     or disc.get("coordinator_address"))):
            _mesh_lib.init_distributed(**disc)
    elif dist_init_required:
        _mesh_lib.init_distributed()

    if mesh is None and mpu is not None:
        # Megatron-style mpu compat (reference: initialize(..., mpu=) —
        # engine.py:1184 reads the mp/pp world sizes off it): translate the
        # mpu's world sizes into a named-axis mesh
        from deepspeed_tpu.config.config import MeshConfig

        def _ws(*names):
            for n in names:
                fn = getattr(mpu, n, None)
                if fn is not None:
                    return int(fn())
            return 1

        mesh = _mesh_lib.create_mesh(MeshConfig(
            tensor=_ws("get_tensor_model_parallel_world_size",
                       "get_model_parallel_world_size"),
            pipe=_ws("get_pipeline_model_parallel_world_size",
                     "get_pipe_parallel_world_size"),
            sequence=_ws("get_sequence_parallel_world_size"),
            data=-1))

    # pipeline dispatch (reference: deepspeed.initialize returns a
    # PipelineEngine when model is a PipelineModule, deepspeed/__init__.py:69)
    from deepspeed_tpu.runtime.pipe.engine import PipeModule, PipelineEngine
    if isinstance(model, PipeModule):
        if lr_scheduler is not None and not callable(lr_scheduler):
            raise ValueError(
                "pipeline: lr_scheduler must be a callable step -> lr "
                f"(got {type(lr_scheduler).__name__}); stateful scheduler "
                "objects are not supported on the pipeline path")
        pipe_engine = PipelineEngine(
            model, config=ds_config, mesh=mesh,
            client_optimizer=optimizer, lr_scheduler=lr_scheduler)
        pipe_loader = None
        if training_data is not None:
            # resolve_batch_sizes guarantees micro_batch_size >= 1 (default 1
            # when the config gives only the accumulation depth)
            import jax as _jax
            from deepspeed_tpu.runtime.dataloader import DeepSpeedTPUDataLoader
            pipe_loader = DeepSpeedTPUDataLoader(
                training_data,
                batch_size=pipe_engine.micro_batch_size *
                pipe_engine.micro_batches,
                collate_fn=collate_fn,
                process_index=_jax.process_index(),
                process_count=_jax.process_count())
        return pipe_engine, pipe_engine.tx, pipe_loader, None

    engine_kwargs = dict(
        model=model,
        config=ds_config,
        params=model_parameters,
        loss_fn=loss_fn,
        mesh=mesh,
        example_batch=example_batch,
        tensor_rules=tensor_rules,
        seed=seed,
        lr_scheduler=lr_scheduler if callable(lr_scheduler) else None,
        client_optimizer=optimizer,
    )
    hybrid_cfg = ds_config.raw().get("hybrid_engine", {})
    if hybrid_cfg.get("enabled", False):
        # RLHF train<->generate engine (reference: deepspeed.initialize returns
        # DeepSpeedHybridEngine when hybrid_engine.enabled)
        from deepspeed_tpu.runtime.hybrid_engine import DeepSpeedTPUHybridEngine
        engine = DeepSpeedTPUHybridEngine(hybrid_config=hybrid_cfg, **engine_kwargs)
    else:
        engine = DeepSpeedTPUEngine(**engine_kwargs)

    dataloader = None
    if training_data is not None:
        from deepspeed_tpu.runtime.dataloader import DeepSpeedTPUDataLoader
        dataloader = DeepSpeedTPUDataLoader(
            training_data,
            batch_size=engine.micro_batch_size * engine.dp_world_size,
            collate_fn=collate_fn,
            process_index=jax.process_index(),
            process_count=jax.process_count())

    return engine, engine.tx, dataloader, engine.lr_schedule


def init_inference(model=None, config=None, params=None, mesh=None,
                   tensor_rules=None, **kwargs):
    """reference: deepspeed.init_inference (deepspeed/__init__.py:291).

    When ``tensor_rules`` is not given and tp_size > 1, AutoTP resolves a policy
    from the model's architecture (reference: auto-injection via
    ``replace_transformer_layer``/``AutoTP``, module_inject/replace_module.py:183).
    """
    from deepspeed_tpu.inference.engine import InferenceEngine
    from deepspeed_tpu.inference.config import InferenceConfig
    inf_config = config if isinstance(config, InferenceConfig) \
        else InferenceConfig(**(config or {}), **kwargs)
    if tensor_rules is None and inf_config.tp_size > 1:
        from deepspeed_tpu.module_inject.auto_tp import AutoTP
        tensor_rules = AutoTP.infer_rules(model, params=params)
    return InferenceEngine(model, inf_config, params=params, mesh=mesh,
                           tensor_rules=tensor_rules)


def add_config_arguments(parser):
    """reference: deepspeed.add_config_arguments (deepspeed/__init__.py:268)."""
    group = parser.add_argument_group("DeepSpeed-TPU", "DeepSpeed-TPU configurations")
    group.add_argument("--deepspeed", default=False, action="store_true",
                       help="Enable DeepSpeed-TPU (helper flag for config parsing)")
    group.add_argument("--deepspeed_config", default=None, type=str,
                       help="Path to the DeepSpeed-TPU json config file")
    return parser
