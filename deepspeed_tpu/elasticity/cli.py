"""``dstpu_elastic`` CLI (reference: ``bin/ds_elastic`` — inspect a config's
elastic batch/world-size compatibility table)."""

import argparse
import json
import sys

from deepspeed_tpu.elasticity.elasticity import compute_elastic_config


def main(args=None):
    parser = argparse.ArgumentParser(
        description="elastic batch-size compatibility explorer")
    parser.add_argument("-c", "--config", type=str, required=True,
                        help="DeepSpeed-TPU json config with an 'elasticity' block")
    parser.add_argument("-w", "--world-size", type=int, default=0,
                        help="validate a specific world size")
    args = parser.parse_args(args)

    with open(args.config) as f:
        ds_config = json.load(f)

    if args.world_size:
        batch, valid, micro = compute_elastic_config(
            ds_config, world_size=args.world_size, return_microbatch=True)
        gas = batch // (micro * args.world_size)
        print(f"world size {args.world_size} OK: train_batch_size={batch}, "
              f"micro_batch={micro}, gradient_accumulation_steps={gas}")
    else:
        batch, valid = compute_elastic_config(ds_config)
        print(f"train_batch_size: {batch}")
        print(f"compatible world sizes ({len(valid)}): {valid}")
    return 0


if __name__ == "__main__":
    sys.exit(main())
