"""Elasticity: scale-invariant batch configs + worker supervision
(reference: ``deepspeed/elasticity/``)."""

from deepspeed_tpu.elasticity.agent import ElasticAgent, WorkerSpec
from deepspeed_tpu.elasticity.elasticity import (
    ElasticityConfig, ElasticityConfigError, ElasticityError,
    ElasticityIncompatibleWorldSize, compute_elastic_config, elasticity_enabled,
    get_candidate_batch_sizes, get_valid_devices)

__all__ = [
    "ElasticAgent", "WorkerSpec", "ElasticityConfig", "ElasticityError",
    "ElasticityConfigError", "ElasticityIncompatibleWorldSize",
    "compute_elastic_config", "elasticity_enabled",
    "get_candidate_batch_sizes", "get_valid_devices",
]
