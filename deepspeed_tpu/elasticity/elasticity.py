"""Elastic batch configuration (reference: ``deepspeed/elasticity/elasticity.py:233
compute_elastic_config``, ``_get_compatible_gpus_v01:83``, v2 model-parallel-aware
``:126``).

The contract: pick a global ``train_batch_size`` (or a set of acceptable ones)
such that for EVERY world size in an allowed range there exists a
(micro_batch, gradient_accumulation_steps) pair with
``micro_batch × gas × dp_world == train_batch_size``. A preempted TPU job can
then restart at a different slice size with an identical global batch — loss
curves stay comparable across scale changes.
"""

from typing import Dict, List, Optional, Tuple

LATEST_ELASTICITY_VERSION = 0.2
MINIMUM_DEEPSPEED_VERSION = "0.1.0"


class ElasticityError(Exception):
    pass


class ElasticityConfigError(ElasticityError):
    pass


class ElasticityIncompatibleWorldSize(ElasticityError):
    pass


class ElasticityConfig:
    """Parsed 'elasticity' config block (reference: elasticity/config.py).

    Fields mirror the reference JSON schema::

        "elasticity": {
          "enabled": true,
          "max_train_batch_size": 2000,
          "micro_batch_sizes": [2, 4, 6],
          "min_gpus": 1, "max_gpus": 10000,
          "min_time": 20,
          "prefer_larger_batch": true,
          "ignore_non_elastic_batch_info": false,
          "version": 0.2,
          "model_parallel_size": 1,
          "num_gpus_per_node": 4
        }
    """

    def __init__(self, param_dict: Dict):
        self.enabled = param_dict.get("enabled", False)
        if "max_train_batch_size" not in param_dict and self.enabled:
            raise ElasticityConfigError(
                "elasticity config missing 'max_train_batch_size'")
        self.max_acceptable_batch_size = param_dict.get("max_train_batch_size", 0)
        self.micro_batches = param_dict.get("micro_batch_sizes", [2, 4, 6])
        if not isinstance(self.micro_batches, list) or \
                any(m <= 0 for m in self.micro_batches):
            raise ElasticityConfigError(
                f"micro_batch_sizes must be positive ints, got {self.micro_batches}")
        self.min_devices = param_dict.get("min_gpus",
                                          param_dict.get("min_devices", 1))
        self.max_devices = param_dict.get("max_gpus",
                                          param_dict.get("max_devices", 10000))
        if self.min_devices < 1 or self.max_devices < self.min_devices:
            raise ElasticityConfigError(
                f"invalid device range [{self.min_devices}, {self.max_devices}]")
        self.model_parallel_size = param_dict.get("model_parallel_size", 1)
        self.num_devices_per_node = param_dict.get(
            "num_gpus_per_node", param_dict.get("num_devices_per_node", 1))
        self.min_time = param_dict.get("min_time", 0)
        self.version = param_dict.get("version", LATEST_ELASTICITY_VERSION)
        self.prefer_larger_batch_size = param_dict.get("prefer_larger_batch", True)
        self.ignore_non_elastic_batch_info = param_dict.get(
            "ignore_non_elastic_batch_info", False)


def _highly_composite_numbers(limit: int) -> List[int]:
    """Numbers ≤ limit with strictly more divisors than any smaller number.
    A batch of micro×HCN divides evenly at the most world sizes — the core
    trick behind the reference's candidate table (elasticity.py HCN_LIST)."""
    hcns, best = [], 0
    counts = [0] * (limit + 1)
    for d in range(1, limit + 1):          # sieve divisor counts
        for m in range(d, limit + 1, d):
            counts[m] += 1
    for n in range(1, limit + 1):
        if counts[n] > best:
            best = counts[n]
            hcns.append(n)
    return hcns


def get_candidate_batch_sizes(base_list: List[int],
                              max_acceptable_batch_size: int) -> List[int]:
    """For each micro batch, the largest micro×HCN ≤ max — the batch sizes that
    maximize divisor coverage (reference: elasticity.py:40
    get_candidate_batch_sizes over its HCN table)."""
    candidates = set()
    for base in base_list:
        if base >= max_acceptable_batch_size:
            candidates.add(base)
            continue
        budget = max_acceptable_batch_size // base
        hcns = _highly_composite_numbers(budget)
        candidates.add(base * hcns[-1])
    return sorted(candidates)


def get_valid_devices(batch_size: int, micro_batches: List[int],
                      min_valid_devices: int, max_valid_devices: int) -> List[int]:
    """World sizes at which ``batch_size`` divides evenly for some micro batch
    (reference: elasticity.py:63 get_valid_gpus)."""
    valid = set()
    for micro_batch in micro_batches:
        if batch_size % micro_batch != 0:
            continue
        max_devices = batch_size // micro_batch
        for i in range(1, max_devices + 1):
            if batch_size % (micro_batch * i) == 0:
                if min_valid_devices <= i <= max_valid_devices:
                    valid.add(i)
    return sorted(valid)


def _get_compatible_devices_v01(
        micro_batches: List[int], max_acceptable_batch_size: int,
        min_devices: int, max_devices: int,
        prefer_larger: bool) -> Tuple[int, List[int]]:
    """v0.1 search: the candidate batch with the most valid world sizes
    (tie-break toward larger batch if prefer_larger). Reference elasticity.py:83."""
    final_batch_size, valid_devices = 0, []
    for batch_size in get_candidate_batch_sizes(
            micro_batches, max_acceptable_batch_size):
        devices = get_valid_devices(batch_size, micro_batches,
                                    min_devices, max_devices)
        better = (len(devices) > len(valid_devices)
                  or (len(devices) == len(valid_devices)
                      and prefer_larger and batch_size > final_batch_size))
        if devices and better:
            valid_devices = devices
            final_batch_size = batch_size
    if not valid_devices:
        raise ElasticityConfigError(
            f"no valid batch size found for micro batches {micro_batches} with "
            f"max batch {max_acceptable_batch_size} over device range "
            f"[{min_devices}, {max_devices}]")
    return final_batch_size, valid_devices


def _get_compatible_devices_v02(
        micro_batches, max_acceptable_batch_size, current_num_devices,
        min_devices, max_devices, prefer_larger, num_devices_per_node,
        model_parallel_size) -> Tuple[int, List[int], int]:
    """v0.2 adds model parallelism: the data-parallel world is
    world // mp, and mp ranks must pack within nodes (reference elasticity.py:126)."""
    if model_parallel_size > 1 and current_num_devices % num_devices_per_node != 0:
        raise ElasticityConfigError(
            "model-parallel elasticity requires whole nodes: "
            f"{current_num_devices} devices with {num_devices_per_node}/node")
    if model_parallel_size > num_devices_per_node and \
            model_parallel_size % num_devices_per_node != 0:
        raise ElasticityConfigError(
            f"model_parallel_size {model_parallel_size} must divide into nodes "
            f"of {num_devices_per_node}")
    dp_size_per_node = max(1, num_devices_per_node // model_parallel_size)
    final_batch_size, valid_world_sizes = _get_compatible_devices_v01(
        micro_batches,
        max_acceptable_batch_size,
        min_devices=max(1, min_devices // model_parallel_size),
        max_devices=max(1, max_devices // model_parallel_size),
        prefer_larger=prefer_larger)
    current_dp = current_num_devices // model_parallel_size
    if current_dp not in valid_world_sizes:
        raise ElasticityIncompatibleWorldSize(
            f"world size {current_num_devices} (dp={current_dp} at "
            f"mp={model_parallel_size}) is not in the compatible set "
            f"{[w * model_parallel_size for w in valid_world_sizes]}")
    return final_batch_size, valid_world_sizes, current_dp * dp_size_per_node


def compute_elastic_config(ds_config: Dict, target_deepspeed_version: str = "",
                           world_size: int = 0, return_microbatch: bool = False):
    """Main entry (reference: elasticity.py:233 compute_elastic_config).

    Returns ``(final_batch_size, valid_world_sizes[, micro_batch])``; when
    ``world_size`` > 0 also validates it and computes the per-rank micro batch +
    gradient accumulation steps.
    """
    elastic_config = ElasticityConfig(ds_config.get("elasticity", {}))
    if not elastic_config.enabled:
        raise ElasticityConfigError("elasticity is not enabled in config")

    if elastic_config.version >= 0.2 and elastic_config.model_parallel_size > 1:
        final_batch_size, valid_world_sizes, _ = _get_compatible_devices_v02(
            elastic_config.micro_batches,
            elastic_config.max_acceptable_batch_size,
            current_num_devices=world_size or elastic_config.min_devices *
            elastic_config.model_parallel_size,
            min_devices=elastic_config.min_devices,
            max_devices=elastic_config.max_devices,
            prefer_larger=elastic_config.prefer_larger_batch_size,
            num_devices_per_node=elastic_config.num_devices_per_node,
            model_parallel_size=elastic_config.model_parallel_size)
        dp_world = (world_size // elastic_config.model_parallel_size
                    if world_size else 0)
    else:
        final_batch_size, valid_world_sizes = _get_compatible_devices_v01(
            elastic_config.micro_batches,
            elastic_config.max_acceptable_batch_size,
            elastic_config.min_devices, elastic_config.max_devices,
            elastic_config.prefer_larger_batch_size)
        dp_world = world_size

    if world_size > 0:
        if dp_world not in valid_world_sizes:
            raise ElasticityIncompatibleWorldSize(
                f"world size {world_size} not compatible; valid: "
                f"{valid_world_sizes}")
        micro, gas = _compute_micro_and_gas(
            final_batch_size, dp_world, elastic_config.micro_batches,
            elastic_config.prefer_larger_batch_size)
        if return_microbatch:
            return final_batch_size, valid_world_sizes, micro
        return final_batch_size, valid_world_sizes
    if return_microbatch:
        raise ElasticityConfigError("return_microbatch requires world_size > 0")
    return final_batch_size, valid_world_sizes


def _compute_micro_and_gas(batch_size: int, dp_world: int,
                           micro_batches: List[int],
                           prefer_larger: bool) -> Tuple[int, int]:
    per_rank = batch_size // dp_world
    options = [m for m in sorted(micro_batches, reverse=prefer_larger)
               if per_rank % m == 0]
    if not options:
        raise ElasticityIncompatibleWorldSize(
            f"no micro batch in {micro_batches} divides per-rank batch {per_rank}")
    micro = options[0]
    return micro, per_rank // micro


def elasticity_enabled(ds_config: Dict) -> bool:
    return ds_config.get("elasticity", {}).get("enabled", False)
