"""Elastic supervisor (reference: ``deepspeed/elasticity/elastic_agent.py:32
DSElasticAgent`` — monitors the worker group and restarts it within the
rendezvous on failure).

JAX/TPU has no torchelastic, so the supervisor is a real component here: it owns
the worker processes, detects failures (exit codes) and scale changes (host set
callback), recomputes a *compatible* world size from the elastic batch config,
and relaunches workers with fresh DSTPU_* rendezvous env. Checkpoint/resume is
the state-transfer mechanism (workers are expected to resume from the latest
checkpoint tag, as with preempted TPU slices).
"""

import os
import subprocess
import sys
import time
from dataclasses import dataclass, field
from typing import Callable, Dict, List, Optional

from deepspeed_tpu.elasticity.elasticity import (
    ElasticityIncompatibleWorldSize, compute_elastic_config)
from deepspeed_tpu.launcher.constants import (ENV_COORDINATOR, ENV_NUM_PROCESSES,
                                              ENV_PROCESS_ID)
from deepspeed_tpu.utils.logging import logger


@dataclass
class WorkerSpec:
    """What to run on each alive host (reference: torchelastic WorkerSpec)."""
    cmd: List[str]
    max_restarts: int = 100
    monitor_interval_s: float = 1.0
    coordinator_port: int = 8476
    env: Dict[str, str] = field(default_factory=dict)


class ElasticAgent:
    """Run → monitor → (on failure) shrink/regrow → relaunch loop
    (reference: elastic_agent.py:127 _invoke_run)."""

    def __init__(self, spec: WorkerSpec, ds_config: Dict,
                 host_provider: Optional[Callable[[], List[str]]] = None,
                 popen: Callable = subprocess.Popen):
        self.spec = spec
        self.ds_config = ds_config
        # host_provider returns the currently-alive host list; defaults to
        # localhost-only (single-host elasticity = restart-on-crash).
        self.host_provider = host_provider or (lambda: ["localhost"])
        self.popen = popen  # injectable for tests
        self.restart_count = 0
        self.procs: List[subprocess.Popen] = []

    def _validate_world(self, world_size: int) -> int:
        """Check the world size against the elastic config; returns the global
        batch that training must use at this scale."""
        final_batch, valid = compute_elastic_config(
            self.ds_config, world_size=world_size)
        return final_batch

    def _launch(self, hosts: List[str]) -> None:
        world = len(hosts)
        final_batch = self._validate_world(world)
        coordinator = f"{hosts[0]}:{self.spec.coordinator_port}"
        logger.info(f"elastic launch: world={world} batch={final_batch} "
                    f"coordinator={coordinator} (restart #{self.restart_count})")
        self.procs = []
        for pid, host in enumerate(hosts):
            env = dict(os.environ)
            env.update(self.spec.env)
            env[ENV_COORDINATOR] = coordinator
            env[ENV_NUM_PROCESSES] = str(world)
            env[ENV_PROCESS_ID] = str(pid)
            env["DSTPU_ELASTIC_RESTART"] = str(self.restart_count)
            env["DSTPU_ELASTIC_BATCH"] = str(final_batch)
            self.procs.append(self.popen(self.spec.cmd, env=env))

    def _poll(self) -> Optional[int]:
        """None while all healthy; first non-zero exit code on failure; 0 done."""
        codes = [p.poll() for p in self.procs]
        if any(c not in (None, 0) for c in codes):
            return next(c for c in codes if c not in (None, 0))
        if all(c == 0 for c in codes):
            return 0
        return None

    def _terminate_all(self):
        for p in self.procs:
            if p.poll() is None:
                p.terminate()
        for p in self.procs:
            try:
                p.wait(timeout=30)
            except subprocess.TimeoutExpired:
                p.kill()

    def run(self) -> int:
        """Supervise until success or restart budget exhausted."""
        hosts = self.host_provider()
        self._launch(hosts)
        while True:
            time.sleep(self.spec.monitor_interval_s)
            status = self._poll()
            current_hosts = self.host_provider()
            scale_change = set(current_hosts) != set(hosts)
            if status is None and not scale_change:
                continue
            if status == 0 and not scale_change:
                logger.info("elastic agent: all workers finished")
                return 0
            # failure or membership change → restart the group at new scale
            self._terminate_all()
            self.restart_count += 1
            if self.restart_count > self.spec.max_restarts:
                logger.error("elastic agent: restart budget exhausted")
                return status or 1
            hosts = current_hosts
            try:
                self._launch(hosts)
            except ElasticityIncompatibleWorldSize as e:
                logger.error(f"elastic agent: no compatible config at "
                             f"world={len(hosts)}: {e}")
                return 1
