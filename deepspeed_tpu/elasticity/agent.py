"""Elastic supervisor (reference: ``deepspeed/elasticity/elastic_agent.py:32
DSElasticAgent`` — monitors the worker group and restarts it within the
rendezvous on failure).

JAX/TPU has no torchelastic, so the supervisor is a real component here: it owns
the worker processes, detects failures (exit codes) and scale changes (host set
callback), recomputes a *compatible* world size from the elastic batch config,
and relaunches workers with fresh DSTPU_* rendezvous env. Checkpoint/resume is
the state-transfer mechanism (workers are expected to resume from the latest
checkpoint tag, as with preempted TPU slices).

**Shrink-to-survive** (the ``elasticity`` config keys ``shrink_on_peer_loss``
/ ``min_world_size`` / ``rejoin_grace_s``): a permanently dead chip used to
wedge the job in a relaunch loop forever — every generation re-assembled the
SAME world and re-faulted on the same missing rank. With shrink enabled the
agent consults the filesystem membership store on every free-relaunch
generation: ranks whose heartbeat stays stale past ``rejoin_grace_s`` are
excluded, the next generation is planned at the surviving world (floored at
``min_world_size``), a jax-free ``MemoryLedger`` preflight re-plans the
per-chip footprint (auto-escalating the offload ladder and exporting the
escalated config to workers via ``DSTPU_ELASTIC_CONFIG_OVERRIDES``), and the
workers resume from the mesh-portable checkpoint. When an excluded rank's
heartbeat returns, the agent re-grows back toward the target world. Every
transition stamps an ``elastic/`` dstrace instant and updates the
``elastic_status.json`` artifact ``env_report`` renders.
"""

import json
import os
import subprocess
import sys
import time
from dataclasses import dataclass, field
from typing import Callable, Dict, List, Optional

from deepspeed_tpu.config.constants import (COMM_GUARD, ELASTICITY,
                                            ELASTICITY_MIN_WORLD_SIZE,
                                            ELASTICITY_REJOIN_GRACE_S,
                                            ELASTICITY_SHRINK_ON_PEER_LOSS,
                                            MEMORY)
from deepspeed_tpu.elasticity.elasticity import (
    ElasticityIncompatibleWorldSize, compute_elastic_config)
from deepspeed_tpu.launcher.constants import (ENV_CONFIG_OVERRIDES,
                                              ENV_COORDINATOR,
                                              ENV_NUM_PROCESSES,
                                              ENV_PROCESS_ID)
from deepspeed_tpu.utils.logging import logger

#: env var naming the agent's status artifact (read back by env_report)
STATUS_ENV = "DSTPU_ELASTIC_STATUS"
DEFAULT_STATUS_PATH = "elastic_status.json"


@dataclass
class WorkerSpec:
    """What to run on each alive host (reference: torchelastic WorkerSpec)."""
    cmd: List[str]
    max_restarts: int = 100          # CRASH budget (preemptions are free)
    # absolute backstop over ALL relaunches (crashes + preemptions + scale
    # changes): a worker that dies preemption-shaped at startup forever must
    # not spin the agent indefinitely just because no crash was charged
    max_total_restarts: int = 1000
    monitor_interval_s: float = 1.0
    coordinator_port: int = 8476
    env: Dict[str, str] = field(default_factory=dict)
    # shutdown escalation: SIGTERM, wait this long, then SIGKILL — one hung
    # worker must not block the group teardown forever
    term_grace_s: float = 30.0
    # crash-loop backoff: sleep base * 2^(consecutive_crashes - 1) before a
    # crash relaunch, capped; a generation that survives healthy_uptime_s
    # resets the streak. Preemptions/scale changes relaunch immediately.
    restart_backoff_s: float = 1.0
    restart_backoff_max_s: float = 60.0
    healthy_uptime_s: float = 300.0
    # exit statuses that mean "the platform took the node" rather than "the
    # worker crashed": SIGTERM/SIGINT deaths (negative Popen returncodes) and
    # their 128+N shell-convention forms
    preemption_exit_codes: tuple = (-15, -2, 143, 130)
    # classified comm-fault exits (comm.guard.COMM_FAULT_EXIT_CODE): the
    # worker detected a wedged collective / lost peer, autosaved, and exited
    # deliberately — the fabric's fault, so the relaunch is free like a
    # preemption, not budgeted like a crash
    comm_fault_exit_codes: tuple = (75,)
    # relaunches get DSTPU_RESUME=latest so workers resume from the newest
    # committed checkpoint (resilience.resume_from_latest) instead of step 0
    resume_env: bool = True
    # membership store the agent consults for the shrink verdict (exported
    # to workers as DSTPU_MEMBERSHIP_DIR so every generation's heartbeats
    # land in the same place); None = resilience.default_membership_dir()
    membership_dir: Optional[str] = None
    # the workers' checkpoint save_dir: the agent reads the latest tag's
    # ds_meta.json provenance (num_params, observed HBM limit, saved config)
    # to run the ledger preflight for a shrunk world — no devices touched
    ckpt_dir: Optional[str] = None
    # where the per-generation status artifact lands (env_report's elastic
    # rows); None = $DSTPU_ELASTIC_STATUS when set, else no artifact is
    # written (a supervisor must opt in — tests and ad-hoc agents must not
    # litter the cwd). Operators conventionally point it at
    # ./elastic_status.json, which env_report discovers unprompted.
    status_path: Optional[str] = None
    # heartbeat staleness horizon for the agent's own membership view
    # (mirrors comm_guard.lost_after_s; the config group wins when present)
    lost_after_s: float = 10.0


class ElasticAgent:
    """Run → monitor → (on failure) shrink/regrow → relaunch loop
    (reference: elastic_agent.py:127 _invoke_run)."""

    def __init__(self, spec: WorkerSpec, ds_config: Dict,
                 host_provider: Optional[Callable[[], List[str]]] = None,
                 popen: Callable = subprocess.Popen):
        self.spec = spec
        self.ds_config = ds_config
        # host_provider returns the currently-alive host list; defaults to
        # localhost-only (single-host elasticity = restart-on-crash).
        self.host_provider = host_provider or (lambda: ["localhost"])
        self.popen = popen  # injectable for tests
        self.restart_count = 0        # total relaunches (generation counter)
        self.crash_restarts = 0       # relaunches charged to the budget
        self.consecutive_crashes = 0  # crash-loop streak (drives backoff)
        self.procs: List[subprocess.Popen] = []
        self._launch_time = 0.0

        # --- shrink-to-survive state (the "elasticity" group's new keys) --
        ecfg = self.ds_config.get(ELASTICITY) or {}
        self.shrink_on_peer_loss = bool(
            ecfg.get(ELASTICITY_SHRINK_ON_PEER_LOSS, False))
        self.min_world_size = int(ecfg.get(ELASTICITY_MIN_WORLD_SIZE, 1))
        self.rejoin_grace_s = float(ecfg.get(ELASTICITY_REJOIN_GRACE_S, 0.0))
        self.target_world: Optional[int] = None    # world of gen 0
        self.current_world: Optional[int] = None   # world of the live gen
        self.shrink_events: List[Dict] = []        # shrink/regrow history
        self.last_exit: Dict = {}                  # last gen's classification
        self.last_preflight: Optional[Dict] = None
        self._config_overrides: Dict = {}          # ladder escalation result
        self._membership = None
        self._next_regrow_probe = 0.0

    # ------------------------------------------------------------------
    # shrink-to-survive: membership verdict + ledger preflight + status
    # ------------------------------------------------------------------
    def _membership_view(self, world: Optional[int] = None):
        """The agent's read-side view of the workers' heartbeat store. A
        fresh view is anchored at every generation launch with
        ``expected_ranks = range(world)`` — a rank that NEVER publishes
        (booted dead, or chaos-silenced from the start) classifies lost
        once the generation is older than the staleness horizon, exactly
        like one that published and went quiet."""
        if not self.shrink_on_peer_loss:
            return None
        if world is not None or self._membership is None:
            from deepspeed_tpu.resilience.membership import (
                MembershipView, default_membership_dir)
            cg = self.ds_config.get(COMM_GUARD) or {}
            self._membership = MembershipView(
                self.spec.membership_dir or default_membership_dir(),
                lost_after_s=float(cg.get("lost_after_s",
                                          self.spec.lost_after_s)),
                expected_ranks=range(world) if world else None)
        return self._membership

    def _tracer(self):
        from deepspeed_tpu.telemetry.tracer import get_tracer
        return get_tracer()

    def _await_membership_verdict(self) -> List[int]:
        """Ranks of the just-ended generation whose heartbeat is stale AND
        stays stale through the ``rejoin_grace_s`` window — the
        permanently-lost set the shrink is planned around. A rank that
        heartbeats again inside the window drops out (transient blip:
        relaunch at the same world, no shrink). Only ranks stale at FIRST
        observation are eligible — survivors whose files age out while the
        agent waits (they exited cleanly and stopped beating) are never
        shrunk away."""
        view = self._membership_view()
        if view is None or self.current_world is None:
            return []
        # membership staleness is the verdict, but only CAPACITY-SHAPED
        # exits are eligible: a vanished node's local process dies by
        # signal (negative Popen code / 137) or never exits (None), and a
        # dead remote host's ssh wrapper returns 255 — while a software
        # crash exits with a positive status and a deliberate exit (0,
        # comm-fault 75, preemption 143/130) chose its code. Without this
        # filter a deterministic exit-1 bug would "mature" into the lost
        # set as its heartbeat aged and walk the job down the shrink
        # ladder with the crash budget never charged. Survivors are
        # additionally protected by freshness: they beat until they exited
        # ~now, while the rank that CAUSED the failure stopped beating at
        # least one staleness horizon earlier. Operating envelope:
        # lost_after_s must exceed the agent's detection latency
        # (monitor_interval_s).
        # capacity-shaped = externally killed or vanished: SIGKILL (-9 /
        # 137 — the OOM killer and the platform reclaiming a node), a dead
        # remote host's ssh 255, or never-exiting (None). Other signal
        # deaths are NOT eligible — SIGSEGV/SIGABRT/SIGFPE are how native
        # code crashes deterministically (XLA CHECK failures), and
        # reclassifying those as capacity loss would walk the job down the
        # shrink ladder with the crash budget never charged.
        codes = getattr(self, "_last_codes", [])
        eligible = {i for i, c in enumerate(codes)
                    if c is None or c in (-9, 137, 255)}
        if not eligible:
            # every worker chose its exit code (clean/crash/preemption/
            # comm-fault): nothing can mature into the lost set — don't
            # burn a staleness horizon on a verdict that cannot change
            return []

        def lost_now():
            return {r for r in view.lost_peers()
                    if r < self.current_world and r in eligible}
        # a rank that died WITH this generation's failure only turns stale
        # after the staleness horizon — wait it out before concluding
        # nobody was lost (the first to mature is the one that died first)
        initial = lost_now()
        mature = time.monotonic() + view.lost_after_s + 1.0
        while not initial and time.monotonic() < mature:
            time.sleep(0.1)
            initial = lost_now()
        if not initial:
            return []
        self._tracer().instant("elastic/peer_lost", cat="elastic",
                               ranks=sorted(initial),
                               generation=self.restart_count,
                               world=self.current_world)
        logger.warning(f"elastic agent: rank(s) {sorted(initial)} lost "
                       f"(stale heartbeat); waiting "
                       f"{self.rejoin_grace_s:.1f}s for rejoin before "
                       f"shrinking")
        lost = initial
        deadline = time.monotonic() + self.rejoin_grace_s
        while lost and time.monotonic() < deadline:
            time.sleep(min(0.2, max(0.0, deadline - time.monotonic())))
            lost = initial & lost_now()
        return sorted(lost)

    def _read_ckpt_provenance(self) -> Dict:
        """The latest checkpoint tag's ds_meta provenance (stdlib reads
        only — the supervisor never touches orbax/devices). Empty dict when
        no checkpoint or no provenance exists yet. Memoized per tag: the
        block carries the full config + param-tree lines, and this runs on
        every status write inside the supervisor loop."""
        d = self.spec.ckpt_dir
        if not d:
            return {}
        try:
            with open(os.path.join(d, "latest")) as f:
                tag = f.read().strip()
        except OSError:
            return {}
        cached = getattr(self, "_prov_cache", None)
        if cached is not None and cached[0] == tag:
            return cached[1]
        try:
            with open(os.path.join(d, tag, "ds_meta.json")) as f:
                prov = json.load(f).get("provenance") or {}
        except (OSError, ValueError):
            return {}
        self._prov_cache = (tag, prov)
        return prov

    def _preflight_world(self, world: int) -> Optional[Dict]:
        """Ledger preflight for the shrunk world: fewer chips means more
        bytes per chip, so re-plan analytically (MemoryLedger over the
        checkpoint's recorded config/param-count/HBM-limit) and escalate
        the offload ladder until the plan fits. The escalated overrides are
        exported to workers via DSTPU_ELASTIC_CONFIG_OVERRIDES. Returns the
        plan (None when no provenance exists to plan from); raises
        ``ElasticityIncompatibleWorldSize`` when the plan cannot fit and
        the memory group's policy is "refuse"."""
        from deepspeed_tpu.telemetry.memory import plan_from_provenance
        prov = self._read_ckpt_provenance()
        plan = plan_from_provenance(prov, world,
                                    default_config=dict(self.ds_config))
        if plan is None:
            logger.info("elastic agent: no checkpoint provenance to "
                        "preflight the shrunk world against; skipping")
            return None
        self.last_preflight = {
            "world": world, "chips": plan["world_chips"],
            "fits": plan["verdict"]["fits"],
            "required_bytes": plan["verdict"]["required_bytes"],
            "bytes_limit": plan["verdict"]["bytes_limit"],
            "escalations": plan["escalations"],
        }
        policy = (self.ds_config.get(MEMORY) or {}).get("preflight", "warn")
        if plan["escalations"]:
            logger.warning(
                f"elastic agent: shrink to {world} workers needs the "
                f"offload ladder: {plan['escalations']} (exported to "
                f"workers via {ENV_CONFIG_OVERRIDES})")
            self._config_overrides = plan["overrides"]
        if not plan["verdict"]["fits"]:
            msg = (f"shrunk world {world} cannot fit: plan needs "
                   f"{plan['verdict']['required_bytes'] / 1e9:.2f}GB/chip vs "
                   f"limit {plan['verdict']['bytes_limit'] / 1e9:.2f}GB even "
                   f"at the last offload rung")
            if policy == "refuse":
                raise ElasticityIncompatibleWorldSize(
                    f"elastic agent (preflight: refuse): {msg}")
            logger.warning(f"elastic agent: {msg}; launching anyway "
                           f"(memory.preflight={policy})")
        return plan

    def _clean_excluded_heartbeats(self, world: int) -> None:
        """Remove heartbeat files of every rank outside the new world so
        the shrunk generation's membership view (and a single-process
        worker's ad-hoc view, which counts every published rank) never
        wedges on pre-shrink leftovers. Unconditional on freshness: a
        just-terminated healthy survivor's file is still fresh here but
        will go stale in seconds, and that rank is not a member of the new
        generation either way."""
        view = self._membership_view()
        if view is None:
            return
        for rank in view.snapshot():
            if rank >= world:
                try:
                    os.remove(os.path.join(
                        view.directory, f"rank_{rank}.json"))
                except OSError:
                    pass

    def _regrow_candidates(self) -> List[int]:
        """Excluded ranks whose heartbeat came back (capacity returned)."""
        view = self._membership_view()
        if view is None or self.current_world is None or \
                self.target_world is None or \
                self.current_world >= self.target_world:
            return []
        snap = view.snapshot()
        return [r for r, h in snap.items()
                if r >= self.current_world and h.alive]

    def _status_path(self) -> Optional[str]:
        """Where the status artifact lands — spec wins, then env; None
        disables the artifact (the in-memory state still accumulates).
        ``env_report`` looks at $DSTPU_ELASTIC_STATUS then
        ``./DEFAULT_STATUS_PATH`` (the conventional operator choice for
        ``status_path``)."""
        return self.spec.status_path or os.environ.get(STATUS_ENV) or None

    def _write_status(self, event: Optional[Dict] = None) -> None:
        """Persist the supervisor's view for operators/env_report: worlds,
        budget, last exit classification, last shrink/regrow event, last
        preflight. Atomic write; a status failure never kills the agent."""
        if event is not None:
            self.shrink_events.append(event)
        if self._status_path() is None:
            return
        status = {
            "target_world": self.target_world,
            "current_world": self.current_world,
            "checkpoint_world": (self._read_ckpt_provenance().get("world")
                                 or {}).get("process_count"),
            "generation": self.restart_count,
            "crash_restarts": self.crash_restarts,
            "max_restarts": self.spec.max_restarts,
            "total_restarts": self.restart_count,
            "max_total_restarts": self.spec.max_total_restarts,
            "last_exit": self.last_exit or None,
            "last_event": self.shrink_events[-1] if self.shrink_events
            else None,
            "preflight": self.last_preflight,
            "config_overrides": self._config_overrides or None,
            "updated_at": time.time(),
        }
        path = self._status_path()
        try:
            tmp = f"{path}.tmp.{os.getpid()}"
            with open(tmp, "w") as f:
                json.dump(status, f, indent=2)
            os.replace(tmp, path)
        except OSError:
            logger.exception("elastic agent: status artifact write failed")

    def _validate_world(self, world_size: int) -> int:
        """Check the world size against the elastic config; returns the global
        batch that training must use at this scale."""
        final_batch, valid = compute_elastic_config(
            self.ds_config, world_size=world_size)
        return final_batch

    def _launch(self, hosts: List[str], world: Optional[int] = None) -> None:
        """Spawn one worker per world slot (default: one per host; a shrink
        passes an explicit smaller ``world`` and slots cycle over the
        surviving hosts)."""
        world = len(hosts) if world is None else world
        final_batch = self._validate_world(world)
        coordinator = f"{hosts[0]}:{self.spec.coordinator_port}"
        logger.info(f"elastic launch: world={world} batch={final_batch} "
                    f"coordinator={coordinator} (restart #{self.restart_count})")
        # the "comm_guard" group's init budget rides to every worker as env:
        # a relaunched worker's rendezvous honors the configured
        # deadline/retries/backoff (comm.mesh.init_distributed reads these;
        # operator-set env and spec.env win over the config)
        from deepspeed_tpu.comm.guard import (INIT_BACKOFF_ENV,
                                              INIT_DEADLINE_ENV,
                                              INIT_RETRIES_ENV)
        cg = self.ds_config.get(COMM_GUARD) or {}
        init_env = {var: str(cg[key]) for key, var in
                    (("init_deadline_s", INIT_DEADLINE_ENV),
                     ("init_retries", INIT_RETRIES_ENV),
                     ("init_backoff_s", INIT_BACKOFF_ENV)) if key in cg}
        self.procs = []
        for pid in range(world):
            env = dict(os.environ)
            env.update(self.spec.env)
            for var, val in init_env.items():
                env.setdefault(var, val)
            env[ENV_COORDINATOR] = coordinator
            env[ENV_NUM_PROCESSES] = str(world)
            env[ENV_PROCESS_ID] = str(pid)
            env["DSTPU_ELASTIC_RESTART"] = str(self.restart_count)
            env["DSTPU_ELASTIC_BATCH"] = str(final_batch)
            if self.spec.membership_dir:
                # one shared heartbeat store across generations: the agent's
                # shrink verdict and the workers' peer-loss detection read
                # the same files
                env.setdefault("DSTPU_MEMBERSHIP_DIR",
                               self.spec.membership_dir)
            if self._config_overrides:
                # the shrink preflight escalated the offload ladder: workers
                # deep-merge this over their raw config at parse time
                env[ENV_CONFIG_OVERRIDES] = json.dumps(self._config_overrides)
            if self.restart_count > 0 and self.spec.resume_env:
                # relaunch marker: workers call FaultTolerantRunner
                # .maybe_resume() at startup, which resumes from the newest
                # committed checkpoint iff this var is set
                env["DSTPU_RESUME"] = "latest"
            self.procs.append(self.popen(self.spec.cmd, env=env))
        self.current_world = world
        if self.target_world is None:
            self.target_world = world
        if self.shrink_on_peer_loss:
            # fresh view anchored at this generation: never-published
            # members classify lost once the generation outlives the
            # staleness horizon
            self._membership_view(world=world)
        self._launch_time = time.monotonic()
        self._write_status()

    def _poll(self) -> Optional[int]:
        """None while all healthy; first non-zero exit code on failure; 0
        done. The full code vector is kept (``_last_codes``) so the restart
        accounting can distinguish preemption exits from crashes."""
        codes = [p.poll() for p in self.procs]
        self._last_codes = codes
        if any(c not in (None, 0) for c in codes):
            return next(c for c in codes if c not in (None, 0))
        if all(c == 0 for c in codes):
            return 0
        return None

    def _is_preemption(self, status: Optional[int]) -> bool:
        """True when every failed worker died by a preemption-shaped status
        (SIGTERM/SIGINT or their 128+N forms) — the platform reclaimed
        capacity; nobody's code crashed, so the restart budget is untouched.
        A SIGKILL/OOM/traceback in ANY worker makes the generation a crash."""
        return self._all_failed_in(self.spec.preemption_exit_codes, status)

    def _is_comm_fault(self, status: Optional[int]) -> bool:
        """True when every failed worker exited in a free-relaunch class
        (preemption or classified comm fault) and at least one was a comm
        fault — relaunch is free. A comm fault in one worker alongside a
        real crash in another is still a crash generation."""
        free = tuple(self.spec.preemption_exit_codes) + \
            tuple(self.spec.comm_fault_exit_codes)
        bad = [c for c in getattr(self, "_last_codes", [])
               if c not in (None, 0)]
        return (self._all_failed_in(free, status)
                and any(c in self.spec.comm_fault_exit_codes for c in bad))

    def _all_failed_in(self, codes, status: Optional[int]) -> bool:
        bad = [c for c in getattr(self, "_last_codes", [])
               if c not in (None, 0)]
        return (status is not None and status != 0 and bool(bad)
                and all(c in codes for c in bad))

    def _terminate_all(self):
        """SIGTERM the group, give each worker ``term_grace_s`` to autosave
        and exit (the resilience runner's preemption path), then SIGKILL the
        stragglers — one hung worker can't block shutdown."""
        for p in self.procs:
            if p.poll() is None:
                p.terminate()
        deadline = time.monotonic() + self.spec.term_grace_s
        for p in self.procs:
            try:
                p.wait(timeout=max(0.0, deadline - time.monotonic()))
            except subprocess.TimeoutExpired:
                logger.warning("elastic agent: worker ignored SIGTERM for "
                               f"{self.spec.term_grace_s:.0f}s; escalating "
                               "to SIGKILL")
                p.kill()
        for p in self.procs:
            if p.poll() is None:
                try:
                    p.wait(timeout=10)
                except subprocess.TimeoutExpired:
                    logger.error("elastic agent: worker survived SIGKILL "
                                 "wait; abandoning process")

    def _crash_backoff_s(self) -> float:
        """Exponential crash-loop backoff: base * 2^(streak-1), capped."""
        if self.consecutive_crashes <= 0 or self.spec.restart_backoff_s <= 0:
            return 0.0
        return min(
            self.spec.restart_backoff_s * 2 ** (self.consecutive_crashes - 1),
            self.spec.restart_backoff_max_s)

    def run(self) -> int:
        """Supervise until success or the crash-restart budget is exhausted.
        Preemption/comm-fault exits and membership changes relaunch for free
        (the platform's churn is not the workload's fault); crashes consume
        the budget and back off exponentially while the streak lasts.

        With ``shrink_on_peer_loss``: a free-relaunch generation whose
        membership shows ranks permanently lost (stale past
        ``rejoin_grace_s``) relaunches at the SURVIVING world — ledger
        preflight first, offload-ladder escalation exported to workers —
        and re-grows toward the target world when the lost capacity's
        heartbeat returns. Shrink generations never consume the crash
        budget: capacity loss is the platform's fault, even when the dead
        rank's own exit status looks crash-shaped (a killed node cannot
        exit cleanly)."""
        hosts = self.host_provider()
        self._launch(hosts)
        while True:
            time.sleep(self.spec.monitor_interval_s)
            status = self._poll()
            current_hosts = self.host_provider()
            scale_change = set(current_hosts) != set(hosts)
            regrow = self._poll_regrow(status)
            if status is None and not scale_change and not regrow:
                continue
            if status == 0 and not scale_change:
                logger.info("elastic agent: all workers finished")
                self.last_exit = {"codes": list(self._last_codes),
                                  "classification": "completed"}
                self._write_status()
                return 0
            comm_fault = self._is_comm_fault(status)
            crash = (status is not None and status != 0
                     and not self._is_preemption(status) and not comm_fault)
            # membership verdict (shrink enabled, any failed generation):
            # which ranks are REALLY gone, after the rejoin grace window
            lost: List[int] = []
            if status is not None and status != 0 and self.shrink_on_peer_loss:
                lost = self._await_membership_verdict()
            if crash and lost:
                free = tuple(self.spec.preemption_exit_codes) + \
                    tuple(self.spec.comm_fault_exit_codes)
                bad_idx = [i for i, c in enumerate(self._last_codes)
                           if c not in (None, 0) and c not in free]
                if bad_idx and set(bad_idx) <= set(lost):
                    # every crash-shaped exit belongs to a membership-lost
                    # rank: that IS the capacity loss (a reclaimed node's
                    # process never exits preemption-shaped) — the
                    # generation is free, the budget untouched
                    crash = False
                    logger.info(f"elastic agent: crash-shaped exits "
                                f"{bad_idx} all belong to lost rank(s) "
                                f"{lost}; classified as capacity loss")
            uptime = time.monotonic() - self._launch_time
            # failure or membership change → restart the group at new scale
            self._terminate_all()
            self.restart_count += 1
            self.last_exit = {
                "codes": [c for c in getattr(self, "_last_codes", [])],
                "classification": (
                    # status None (all running) or 0 (all finished) can only
                    # reach here via a host-set/regrow change
                    "scale_change" if status in (None, 0) else
                    "capacity_loss" if lost and not crash else
                    "crash" if crash else
                    "comm_fault" if comm_fault else "preemption"),
                "lost_ranks": lost or None,
            }
            if self.restart_count > self.spec.max_total_restarts:
                logger.error("elastic agent: total restart backstop "
                             f"exhausted ({self.spec.max_total_restarts})")
                self._write_status()
                return status or 1
            if crash:
                if uptime >= self.spec.healthy_uptime_s:
                    self.consecutive_crashes = 0    # not a crash LOOP
                self.consecutive_crashes += 1
                self.crash_restarts += 1
                if self.crash_restarts > self.spec.max_restarts:
                    logger.error("elastic agent: crash-restart budget "
                                 f"exhausted ({self.spec.max_restarts})")
                    self._write_status()
                    return status or 1
                backoff = self._crash_backoff_s()
                if backoff:
                    logger.warning(
                        f"elastic agent: crash #{self.consecutive_crashes} "
                        f"(exit {status}, uptime {uptime:.1f}s); backing off "
                        f"{backoff:.1f}s before relaunch")
                    time.sleep(backoff)
            else:
                self.consecutive_crashes = 0
                why = ("scale change" if scale_change or regrow else
                       f"capacity loss (lost ranks {lost})" if lost else
                       f"comm fault (exit {status})" if comm_fault else
                       f"preemption (exit {status})")
                logger.info(f"elastic agent: {why}; relaunching immediately "
                            "(budget untouched)")
            hosts = current_hosts
            try:
                world = self._plan_next_world(hosts, lost, regrow)
                if world is None:            # below min_world_size
                    self._write_status()
                    return status or 1
                self._launch(hosts, world=world)
            except ElasticityIncompatibleWorldSize as e:
                logger.error(f"elastic agent: no compatible config at the "
                             f"planned world: {e}")
                self._write_status()
                return 1

    def _poll_regrow(self, status) -> int:
        """Throttled probe for returned capacity while the group is healthy
        and shrunk below target: a fresh heartbeat from an excluded rank
        triggers a regrow relaunch (same restart-the-group mechanics as a
        host-set scale change)."""
        if status is not None or not self.shrink_on_peer_loss or \
                self.current_world is None or self.target_world is None or \
                self.current_world >= self.target_world:
            return 0
        now = time.monotonic()
        if now < self._next_regrow_probe:
            return 0
        self._next_regrow_probe = now + max(
            self.spec.monitor_interval_s, 1.0)
        back = len(self._regrow_candidates())
        if not back:
            return 0
        # only restart the group when the returned capacity actually buys a
        # LARGER compatible world (one chip back under a {2,4}-only batch
        # config buys nothing at world 2 — don't churn a healthy job)
        grown = self._compatible_world_at_most(
            min(self.target_world, self.current_world + back))
        return back if grown is not None and grown > self.current_world else 0

    def _compatible_world_at_most(self, world: int) -> Optional[int]:
        """The largest elastic-config-compatible world <= ``world`` (the
        global batch is invariant, so not every integer world factors);
        None when nothing <= ``world`` is compatible. In the v0.2
        model-parallel path ``compute_elastic_config`` reports DATA-PARALLEL
        worlds — convert to total worker counts (dp * mp) before comparing,
        or the planner would pick an mp-indivisible world."""
        ecfg = self.ds_config.get(ELASTICITY) or {}
        mp = int(ecfg.get("model_parallel_size", 1) or 1) \
            if float(ecfg.get("version", 0.2) or 0.2) >= 0.2 else 1
        try:
            _, valid = compute_elastic_config(self.ds_config)
        except Exception:
            return world if world >= 1 else None
        if mp > 1:
            valid = [w * mp for w in valid]
        fits = [w for w in valid if w <= world]
        return max(fits) if fits else None

    def _plan_next_world(self, hosts: List[str], lost: List[int],
                         regrow: int) -> Optional[int]:
        """The next generation's world: host-provider count, minus
        membership-lost ranks (shrink, rounded DOWN to the nearest
        batch-compatible world), plus returned capacity (regrow, capped at
        the target world). Returns None when the surviving world would
        fall below ``min_world_size`` (the agent refuses and exits — a
        1-chip remnant grinding a 256-chip job is not survival)."""
        base = self.current_world if self.current_world is not None \
            else len(hosts)
        if not self.shrink_on_peer_loss:
            return len(hosts)
        if self.target_world is not None and \
                len(hosts) != self.target_world and not lost and not regrow:
            # the host provider re-scoped the cluster: it wins, and the
            # shrink baseline re-anchors on the new target
            self.target_world = len(hosts)
            return len(hosts)
        world = base
        if lost:
            surviving = base - len(lost)
            # the elastic invariant bounds the shrink too: relaunch at the
            # LARGEST batch-compatible world <= the surviving capacity
            # (idle spare chips beat an impossible batch factorization)
            world = self._compatible_world_at_most(surviving)
            if world is None or world < self.min_world_size:
                logger.error(
                    f"elastic agent: surviving world {surviving} has no "
                    f"compatible world >= min_world_size="
                    f"{self.min_world_size}; refusing to shrink further")
                self._tracer().instant("elastic/shrink_refused",
                                       cat="elastic", surviving=surviving,
                                       min_world_size=self.min_world_size)
                self.shrink_events.append(
                    {"type": "shrink_refused", "generation":
                     self.restart_count, "from_world": base,
                     "to_world": surviving, "at": time.time()})
                return None
            plan = self._preflight_world(world)
            self._tracer().instant(
                "elastic/shrink_planned", cat="elastic",
                from_world=base, to_world=world, lost_ranks=list(lost),
                generation=self.restart_count,
                preflight_fits=None if plan is None
                else plan["verdict"]["fits"],
                escalations=len(plan["escalations"]) if plan else 0)
            self._write_status(event={
                "type": "shrink", "generation": self.restart_count,
                "from_world": base, "to_world": world,
                "lost_ranks": list(lost), "at": time.time()})
            self._clean_excluded_heartbeats(world)
        elif regrow or (self.target_world is not None
                        and base < self.target_world):
            back = regrow or len(self._regrow_candidates())
            if not back:
                return world
            # regrow rounds DOWN to a batch-compatible world too — planning
            # an incompatible one would kill a healthy shrunk job at launch
            world = self._compatible_world_at_most(
                min(self.target_world, base + back)) or base
            if world > base:
                # capacity is back. Any previously-escalated ladder
                # overrides stay STICKY: the checkpoints saved since the
                # shrink record the escalated config in their provenance,
                # so that is the config the preflight plans from — and the
                # config the regrown workers must actually launch with for
                # the verdict to mean anything. Relaxing the ladder after
                # a regrow is an operator decision (relaunch fresh), not
                # something the agent guesses at.
                self._preflight_world(world)
                self._tracer().instant("elastic/regrow", cat="elastic",
                                       from_world=base, to_world=world,
                                       generation=self.restart_count)
                self._write_status(event={
                    "type": "regrow", "generation": self.restart_count,
                    "from_world": base, "to_world": world,
                    "at": time.time()})
        return world
