"""Elastic supervisor (reference: ``deepspeed/elasticity/elastic_agent.py:32
DSElasticAgent`` — monitors the worker group and restarts it within the
rendezvous on failure).

JAX/TPU has no torchelastic, so the supervisor is a real component here: it owns
the worker processes, detects failures (exit codes) and scale changes (host set
callback), recomputes a *compatible* world size from the elastic batch config,
and relaunches workers with fresh DSTPU_* rendezvous env. Checkpoint/resume is
the state-transfer mechanism (workers are expected to resume from the latest
checkpoint tag, as with preempted TPU slices).
"""

import os
import subprocess
import sys
import time
from dataclasses import dataclass, field
from typing import Callable, Dict, List, Optional

from deepspeed_tpu.elasticity.elasticity import (
    ElasticityIncompatibleWorldSize, compute_elastic_config)
from deepspeed_tpu.launcher.constants import (ENV_COORDINATOR, ENV_NUM_PROCESSES,
                                              ENV_PROCESS_ID)
from deepspeed_tpu.utils.logging import logger


@dataclass
class WorkerSpec:
    """What to run on each alive host (reference: torchelastic WorkerSpec)."""
    cmd: List[str]
    max_restarts: int = 100          # CRASH budget (preemptions are free)
    # absolute backstop over ALL relaunches (crashes + preemptions + scale
    # changes): a worker that dies preemption-shaped at startup forever must
    # not spin the agent indefinitely just because no crash was charged
    max_total_restarts: int = 1000
    monitor_interval_s: float = 1.0
    coordinator_port: int = 8476
    env: Dict[str, str] = field(default_factory=dict)
    # shutdown escalation: SIGTERM, wait this long, then SIGKILL — one hung
    # worker must not block the group teardown forever
    term_grace_s: float = 30.0
    # crash-loop backoff: sleep base * 2^(consecutive_crashes - 1) before a
    # crash relaunch, capped; a generation that survives healthy_uptime_s
    # resets the streak. Preemptions/scale changes relaunch immediately.
    restart_backoff_s: float = 1.0
    restart_backoff_max_s: float = 60.0
    healthy_uptime_s: float = 300.0
    # exit statuses that mean "the platform took the node" rather than "the
    # worker crashed": SIGTERM/SIGINT deaths (negative Popen returncodes) and
    # their 128+N shell-convention forms
    preemption_exit_codes: tuple = (-15, -2, 143, 130)
    # classified comm-fault exits (comm.guard.COMM_FAULT_EXIT_CODE): the
    # worker detected a wedged collective / lost peer, autosaved, and exited
    # deliberately — the fabric's fault, so the relaunch is free like a
    # preemption, not budgeted like a crash
    comm_fault_exit_codes: tuple = (75,)
    # relaunches get DSTPU_RESUME=latest so workers resume from the newest
    # committed checkpoint (resilience.resume_from_latest) instead of step 0
    resume_env: bool = True


class ElasticAgent:
    """Run → monitor → (on failure) shrink/regrow → relaunch loop
    (reference: elastic_agent.py:127 _invoke_run)."""

    def __init__(self, spec: WorkerSpec, ds_config: Dict,
                 host_provider: Optional[Callable[[], List[str]]] = None,
                 popen: Callable = subprocess.Popen):
        self.spec = spec
        self.ds_config = ds_config
        # host_provider returns the currently-alive host list; defaults to
        # localhost-only (single-host elasticity = restart-on-crash).
        self.host_provider = host_provider or (lambda: ["localhost"])
        self.popen = popen  # injectable for tests
        self.restart_count = 0        # total relaunches (generation counter)
        self.crash_restarts = 0       # relaunches charged to the budget
        self.consecutive_crashes = 0  # crash-loop streak (drives backoff)
        self.procs: List[subprocess.Popen] = []
        self._launch_time = 0.0

    def _validate_world(self, world_size: int) -> int:
        """Check the world size against the elastic config; returns the global
        batch that training must use at this scale."""
        final_batch, valid = compute_elastic_config(
            self.ds_config, world_size=world_size)
        return final_batch

    def _launch(self, hosts: List[str]) -> None:
        world = len(hosts)
        final_batch = self._validate_world(world)
        coordinator = f"{hosts[0]}:{self.spec.coordinator_port}"
        logger.info(f"elastic launch: world={world} batch={final_batch} "
                    f"coordinator={coordinator} (restart #{self.restart_count})")
        # the "comm_guard" group's init budget rides to every worker as env:
        # a relaunched worker's rendezvous honors the configured
        # deadline/retries/backoff (comm.mesh.init_distributed reads these;
        # operator-set env and spec.env win over the config)
        from deepspeed_tpu.comm.guard import (INIT_BACKOFF_ENV,
                                              INIT_DEADLINE_ENV,
                                              INIT_RETRIES_ENV)
        from deepspeed_tpu.config.constants import COMM_GUARD
        cg = self.ds_config.get(COMM_GUARD) or {}
        init_env = {var: str(cg[key]) for key, var in
                    (("init_deadline_s", INIT_DEADLINE_ENV),
                     ("init_retries", INIT_RETRIES_ENV),
                     ("init_backoff_s", INIT_BACKOFF_ENV)) if key in cg}
        self.procs = []
        for pid, host in enumerate(hosts):
            env = dict(os.environ)
            env.update(self.spec.env)
            for var, val in init_env.items():
                env.setdefault(var, val)
            env[ENV_COORDINATOR] = coordinator
            env[ENV_NUM_PROCESSES] = str(world)
            env[ENV_PROCESS_ID] = str(pid)
            env["DSTPU_ELASTIC_RESTART"] = str(self.restart_count)
            env["DSTPU_ELASTIC_BATCH"] = str(final_batch)
            if self.restart_count > 0 and self.spec.resume_env:
                # relaunch marker: workers call FaultTolerantRunner
                # .maybe_resume() at startup, which resumes from the newest
                # committed checkpoint iff this var is set
                env["DSTPU_RESUME"] = "latest"
            self.procs.append(self.popen(self.spec.cmd, env=env))
        self._launch_time = time.monotonic()

    def _poll(self) -> Optional[int]:
        """None while all healthy; first non-zero exit code on failure; 0
        done. The full code vector is kept (``_last_codes``) so the restart
        accounting can distinguish preemption exits from crashes."""
        codes = [p.poll() for p in self.procs]
        self._last_codes = codes
        if any(c not in (None, 0) for c in codes):
            return next(c for c in codes if c not in (None, 0))
        if all(c == 0 for c in codes):
            return 0
        return None

    def _is_preemption(self, status: Optional[int]) -> bool:
        """True when every failed worker died by a preemption-shaped status
        (SIGTERM/SIGINT or their 128+N forms) — the platform reclaimed
        capacity; nobody's code crashed, so the restart budget is untouched.
        A SIGKILL/OOM/traceback in ANY worker makes the generation a crash."""
        return self._all_failed_in(self.spec.preemption_exit_codes, status)

    def _is_comm_fault(self, status: Optional[int]) -> bool:
        """True when every failed worker exited in a free-relaunch class
        (preemption or classified comm fault) and at least one was a comm
        fault — relaunch is free. A comm fault in one worker alongside a
        real crash in another is still a crash generation."""
        free = tuple(self.spec.preemption_exit_codes) + \
            tuple(self.spec.comm_fault_exit_codes)
        bad = [c for c in getattr(self, "_last_codes", [])
               if c not in (None, 0)]
        return (self._all_failed_in(free, status)
                and any(c in self.spec.comm_fault_exit_codes for c in bad))

    def _all_failed_in(self, codes, status: Optional[int]) -> bool:
        bad = [c for c in getattr(self, "_last_codes", [])
               if c not in (None, 0)]
        return (status is not None and status != 0 and bool(bad)
                and all(c in codes for c in bad))

    def _terminate_all(self):
        """SIGTERM the group, give each worker ``term_grace_s`` to autosave
        and exit (the resilience runner's preemption path), then SIGKILL the
        stragglers — one hung worker can't block shutdown."""
        for p in self.procs:
            if p.poll() is None:
                p.terminate()
        deadline = time.monotonic() + self.spec.term_grace_s
        for p in self.procs:
            try:
                p.wait(timeout=max(0.0, deadline - time.monotonic()))
            except subprocess.TimeoutExpired:
                logger.warning("elastic agent: worker ignored SIGTERM for "
                               f"{self.spec.term_grace_s:.0f}s; escalating "
                               "to SIGKILL")
                p.kill()
        for p in self.procs:
            if p.poll() is None:
                try:
                    p.wait(timeout=10)
                except subprocess.TimeoutExpired:
                    logger.error("elastic agent: worker survived SIGKILL "
                                 "wait; abandoning process")

    def _crash_backoff_s(self) -> float:
        """Exponential crash-loop backoff: base * 2^(streak-1), capped."""
        if self.consecutive_crashes <= 0 or self.spec.restart_backoff_s <= 0:
            return 0.0
        return min(
            self.spec.restart_backoff_s * 2 ** (self.consecutive_crashes - 1),
            self.spec.restart_backoff_max_s)

    def run(self) -> int:
        """Supervise until success or the crash-restart budget is exhausted.
        Preemption exits and membership changes relaunch for free (the
        platform's churn is not the workload's fault); crashes consume the
        budget and back off exponentially while the streak lasts."""
        hosts = self.host_provider()
        self._launch(hosts)
        while True:
            time.sleep(self.spec.monitor_interval_s)
            status = self._poll()
            current_hosts = self.host_provider()
            scale_change = set(current_hosts) != set(hosts)
            if status is None and not scale_change:
                continue
            if status == 0 and not scale_change:
                logger.info("elastic agent: all workers finished")
                return 0
            comm_fault = self._is_comm_fault(status)
            crash = (status is not None and status != 0
                     and not self._is_preemption(status) and not comm_fault)
            uptime = time.monotonic() - self._launch_time
            # failure or membership change → restart the group at new scale
            self._terminate_all()
            self.restart_count += 1
            if self.restart_count > self.spec.max_total_restarts:
                logger.error("elastic agent: total restart backstop "
                             f"exhausted ({self.spec.max_total_restarts})")
                return status or 1
            if crash:
                if uptime >= self.spec.healthy_uptime_s:
                    self.consecutive_crashes = 0    # not a crash LOOP
                self.consecutive_crashes += 1
                self.crash_restarts += 1
                if self.crash_restarts > self.spec.max_restarts:
                    logger.error("elastic agent: crash-restart budget "
                                 f"exhausted ({self.spec.max_restarts})")
                    return status or 1
                backoff = self._crash_backoff_s()
                if backoff:
                    logger.warning(
                        f"elastic agent: crash #{self.consecutive_crashes} "
                        f"(exit {status}, uptime {uptime:.1f}s); backing off "
                        f"{backoff:.1f}s before relaunch")
                    time.sleep(backoff)
            else:
                self.consecutive_crashes = 0
                why = ("scale change" if scale_change else
                       f"comm fault (exit {status})" if comm_fault else
                       f"preemption (exit {status})")
                logger.info(f"elastic agent: {why}; relaunching immediately "
                            "(budget untouched)")
            hosts = current_hosts
            try:
                self._launch(hosts)
            except ElasticityIncompatibleWorldSize as e:
                logger.error(f"elastic agent: no compatible config at "
                             f"world={len(hosts)}: {e}")
                return 1
