"""Multi-process distributed test harness.

Reference analog: ``tests/unit/common.py:416`` (``DistributedTest``) — the
reference's key testing trick: every distributed test spawns ``world_size``
*real processes* on one host (``_launch_daemonic_procs:170``), rendezvous over
TCP, runs the test body in every rank (``_dist_run:279``), and propagates
failures/skips back through the pool with a timeout kill.

TPU redesign: single-process multi-device SPMD already covers sharding
semantics (tests/conftest.py), so this harness exists for what that cannot
exercise — the *multi-host* path: ``jax.distributed.initialize`` rendezvous,
cross-process global meshes, and gloo-backed CPU collectives standing in for
ICI/DCN (the same substitution the reference makes with gloo for NCCL).
``run_distributed`` launches N python processes, each contributing
``devices_per_process`` virtual CPU devices to one global mesh; the target
function must be importable (``module:qualname``) and runs in every rank.
"""

import os
import socket
import subprocess
import sys
import time
from typing import Callable, Optional, Sequence, Union

DEFAULT_TIMEOUT = 240

_BOOTSTRAP = r"""
import importlib, os, sys
for p in os.environ.get("DSTPU_TEST_PATH", "").split(os.pathsep):
    if p and p not in sys.path:
        sys.path.insert(0, p)
# fresh interpreter: env-var device forcing still works here, and doubles as
# the fallback for jax versions without the jax_num_cpu_devices option (the
# parent pytest env carries conftest's =8 flag — replace it with this rank's)
ndev = os.environ["DSTPU_TEST_LOCAL_DEVICES"]
os.environ["JAX_PLATFORMS"] = "cpu"
flags = [f for f in os.environ.get("XLA_FLAGS", "").split()
         if "xla_force_host_platform_device_count" not in f]
flags.append("--xla_force_host_platform_device_count=" + ndev)
os.environ["XLA_FLAGS"] = " ".join(flags)
import jax
jax.config.update("jax_platforms", "cpu")
try:
    jax.config.update("jax_num_cpu_devices", int(ndev))
except AttributeError:
    pass   # older jax: XLA_FLAGS above already forced the device count
jax.config.update("jax_cpu_collectives_implementation", "gloo")
from deepspeed_tpu.comm.mesh import init_distributed
# the wedge-proof rendezvous: deadline + transient-retry (comm/guard.py
# bounded_init) — a dead coordinator fails the rank with CommWedgeError
# inside the deadline instead of hanging the whole harness to its timeout
init_distributed(
    coordinator_address=os.environ["DSTPU_TEST_COORD"],
    num_processes=int(os.environ["DSTPU_TEST_NPROC"]),
    process_id=int(os.environ["DSTPU_TEST_RANK"]))
mod_name, _, qual = os.environ["DSTPU_TEST_FN"].partition(":")
fn = importlib.import_module(mod_name)
for part in qual.split("."):
    fn = getattr(fn, part)
fn()
"""


def free_port() -> int:
    with socket.socket() as s:
        s.bind(("127.0.0.1", 0))
        return s.getsockname()[1]


def run_distributed(fn: Union[Callable, str], world_size: int = 2,
                    devices_per_process: int = 2,
                    timeout: float = DEFAULT_TIMEOUT,
                    env: Optional[dict] = None) -> Sequence[str]:
    """Run ``fn`` in ``world_size`` fresh processes under one jax.distributed
    rendezvous. ``fn`` is a module-level callable or an ``"module:qualname"``
    string. Returns per-rank stdout; raises RuntimeError with the failing
    rank's output on any nonzero exit (reference ``_dist_run`` failure
    propagation) or TimeoutError after ``timeout`` (reference
    ``DS_UNITTEST_TIMEOUT`` kill)."""
    repo_root = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))
    extra_paths = [repo_root]
    if callable(fn):
        mod = getattr(fn, "__module__", None)
        qual = getattr(fn, "__qualname__", None)
        if not mod or not qual or "<locals>" in qual:
            raise ValueError("fn must be importable (module-level) to run in "
                             "spawned ranks")
        if "." in mod:
            # dotted (package) module: import it by its real name in the child
            # — re-importing under a stripped name would double-import it and
            # put package internals on sys.path
            import importlib.util
            try:
                if importlib.util.find_spec(mod) is None:
                    raise ValueError(f"module {mod!r} is not importable from "
                                     "a spawned rank")
            except ImportError:
                raise ValueError(f"module {mod!r} is not importable from a "
                                 "spawned rank") from None
        else:
            # top-level module (e.g. a pytest-loaded test file): make its own
            # directory importable in the child
            mod_file = getattr(sys.modules.get(mod), "__file__", None)
            if mod_file:
                extra_paths.append(os.path.dirname(os.path.abspath(mod_file)))
        fn = f"{mod}:{qual}"

    coord = f"127.0.0.1:{free_port()}"
    procs = []
    for rank in range(world_size):
        rank_env = dict(os.environ,
                        DSTPU_TEST_COORD=coord,
                        DSTPU_TEST_NPROC=str(world_size),
                        DSTPU_TEST_RANK=str(rank),
                        DSTPU_TEST_LOCAL_DEVICES=str(devices_per_process),
                        DSTPU_TEST_FN=fn,
                        DSTPU_TEST_PATH=os.pathsep.join(extra_paths),
                        **(env or {}))
        procs.append(subprocess.Popen(
            [sys.executable, "-c", _BOOTSTRAP], env=rank_env,
            stdout=subprocess.PIPE, stderr=subprocess.STDOUT, text=True,
            cwd=repo_root))

    # drain all ranks concurrently: a rank blocking on a full stdout pipe would
    # stall its collectives and masquerade as a hang of its peers
    import threading
    outs = [None] * world_size

    def drain(rank, p):
        outs[rank], _ = p.communicate()

    readers = [threading.Thread(target=drain, args=(r, p), daemon=True)
               for r, p in enumerate(procs)]
    for t in readers:
        t.start()
    deadline = time.time() + timeout
    try:
        for t in readers:
            t.join(max(0.0, deadline - time.time()))
        timed_out = [r for r, t in enumerate(readers) if t.is_alive()]
    finally:
        for p in procs:
            if p.poll() is None:
                p.kill()
    for t in readers:
        t.join(10)
    # a rank that crashed while its peers hung in a collective is the root
    # cause — report its traceback, not the peers' timeout
    for rank, p in enumerate(procs):
        if p.returncode not in (0, None) and rank not in timed_out:
            raise RuntimeError(
                f"rank {rank} exited {p.returncode}:\n{outs[rank]}")
    if timed_out:
        raise TimeoutError(f"ranks {timed_out} timed out ({timeout}s)")
    return outs


class DistributedTest:
    """Class-style sugar matching the reference spelling: subclass, set
    ``world_size``, point ``run = staticmethod(body_fn)`` at a module-level
    body, call ``self.launch()`` from a normal pytest test."""

    world_size: int = 2
    devices_per_process: int = 2
    timeout: float = DEFAULT_TIMEOUT
    run: Callable = None

    def launch(self):
        return run_distributed(type(self).run, self.world_size,
                               self.devices_per_process, self.timeout)
