"""Compression scheduling.

Reference analog: ``deepspeed/compression/scheduler.py`` (``compression_scheduler``
— flips per-module enable flags once ``training_steps`` passes each technique's
``schedule_offset``) plus the MoQ-style bit annealing (``start_bits`` →
``target_bits`` stepped every ``quantization_period`` steps).

Because the train step is a compiled XLA program, the schedule lives on the host:
``state(step)`` returns a hashable snapshot (which techniques are active + current
bits per group). The engine keys its compiled-step cache on that snapshot, so a
schedule transition triggers exactly one recompile — annealing bits one at a time
bounds the number of programs to ``start_bits - target_bits + 1`` per group.
"""

from typing import Any, Dict, Tuple

QUANT_METHODS = ("weight_quantization", "activation_quantization")
PRUNE_METHODS = ("sparse_pruning", "row_pruning", "head_pruning", "channel_pruning")


class CompressionScheduler:

    def __init__(self, compression_config: Dict[str, Any]):
        self.config = compression_config
        self.training_steps = 0

    def step(self, increment: int = 1) -> None:
        self.training_steps += increment

    def _method_active(self, method: str) -> bool:
        mcfg = self.config.get(method)
        if not mcfg:
            return False
        shared = mcfg.get("shared_parameters", {})
        if not shared.get("enabled", False):
            return False
        offset = shared.get("schedule_offset", 0)
        end = shared.get("schedule_offset_end", None)
        if self.training_steps < offset:
            return False
        if end is not None and self.training_steps > end:
            return False
        return True

    def current_bits(self, group_params: Dict[str, Any]) -> int:
        """Annealed bit width for a weight-quantization group: start_bits drops by
        one every ``quantization_period`` steps until target_bits. The anneal
        clock starts at the technique's ``schedule_offset`` (activation step),
        so the first quantized steps really run at start_bits."""
        start = int(group_params.get("start_bits", group_params.get("bits", 8)))
        target = int(group_params.get("target_bits", start))
        period = int(group_params.get("quantization_period", 0))
        if period <= 0 or start <= target:
            return target
        offset = int(group_params.get("schedule_offset", 0))
        active_steps = max(0, self.training_steps - offset)
        return max(target, start - active_steps // period)

    def state(self, step: int = None) -> Tuple:
        """Hashable snapshot of everything *static* about compression at ``step``
        (active methods + per-group bits). Changes ⇒ the engine recompiles."""
        if step is not None:
            self.training_steps = step
        snap = []
        for method in QUANT_METHODS + PRUNE_METHODS:
            if not self._method_active(method):
                continue
            mcfg = self.config.get(method, {})
            shared = mcfg.get("shared_parameters", {})
            gsnap = []
            for gname, g in sorted(mcfg.get("different_groups", {}).items()):
                params = {**shared, **g.get("params", {})}
                bits = self.current_bits(params) if method == "weight_quantization" \
                    else int(params.get("bits", 8))
                gsnap.append((gname, bits))
            snap.append((method, tuple(gsnap)))
        return tuple(snap)
