"""Compression entry points.

Reference analog: ``deepspeed/compression/compress.py:100`` (``init_compression``
— regex-matches module names per technique group and swaps in compression-aware
layers; ``redundancy_clean`` bakes masks in; ``student_initialization`` copies
teacher layers for layer reduction/distillation).

TPU-native shape: ``init_compression(params, config)`` returns a ``Compressor``
holding (a) the per-leaf technique assignment resolved from the same
``compression_training`` JSON schema, and (b) a ``CompressionScheduler``. Inside
the jitted loss, call ``compressor.transform(params)`` — a pure function of the
matched leaves under the *current* host-side schedule snapshot; the engine keys
its compiled step on ``compressor.schedule_key()`` so schedule transitions
recompile exactly once. Pruning masks are frozen from the weights the first time
a pruning technique activates (reference: masks computed at enable time and kept).
"""

import re
from typing import Any, Callable, Dict, List, Optional, Tuple

import jax
import jax.numpy as jnp

from deepspeed_tpu.compression import ops
from deepspeed_tpu.compression.scheduler import (
    CompressionScheduler, PRUNE_METHODS, QUANT_METHODS)
from deepspeed_tpu.utils.logging import logger

COMPRESSION_KEY = "compression_training"
LAYER_REDUCTION_KEY = "layer_reduction"


def _path_name(path) -> str:
    """Canonical 'a/b/kernel' name for a tree_util key path."""
    return "/".join(str(getattr(k, "key", getattr(k, "idx", k))) for k in path)


def _leaf_paths(params) -> List[Tuple[str, Any]]:
    """Flatten a params pytree to ('a/b/kernel', leaf) pairs."""
    flat, _ = jax.tree_util.tree_flatten_with_path(params)
    return [(_path_name(path), leaf) for path, leaf in flat]


class Compressor:

    def __init__(self, params, config: Dict[str, Any],
                 num_heads: Optional[int] = None):
        self.config = config.get(COMPRESSION_KEY, config) or {}
        self.scheduler = CompressionScheduler(self.config)
        self.num_heads = num_heads
        self._masks: Dict[str, Dict[str, jnp.ndarray]] = {m: {} for m in PRUNE_METHODS}
        self._mask_frozen: Dict[str, bool] = {m: False for m in PRUNE_METHODS}
        # technique -> list of (leaf_path, group_params) resolved once at init
        self.assignments: Dict[str, List[Tuple[str, Dict[str, Any]]]] = {}
        names = [n for n, leaf in _leaf_paths(params)
                 if hasattr(leaf, "ndim") and leaf.ndim >= 2]
        for method in QUANT_METHODS + PRUNE_METHODS:
            mcfg = self.config.get(method)
            if not mcfg or not mcfg.get("shared_parameters", {}).get("enabled", False):
                continue
            taken = set()
            matched: List[Tuple[str, Dict[str, Any]]] = []
            for gname, group in sorted(mcfg.get("different_groups", {}).items()):
                gparams = dict(group.get("params", {}))
                gparams.update({k: v for k, v in mcfg.get("shared_parameters", {}).items()
                                if k not in gparams})
                for pattern in group.get("modules", [".*"]):
                    for name in names:
                        if re.search(pattern, name) and name not in taken:
                            taken.add(name)
                            matched.append((name, gparams))
            if matched:
                self.assignments[method] = matched
                logger.info(f"compression: {method} on {len(matched)} tensors")

    # -- host-side schedule ------------------------------------------------
    def set_step(self, step: int) -> None:
        self.scheduler.training_steps = step
        # freeze pruning masks from current weights the first time each
        # pruning technique becomes active (requires caller to pass params then)

    def schedule_key(self) -> Tuple:
        """Hashable snapshot of the static compression structure: active methods
        + per-tensor bits from the *merged* (shared + group) params — the same
        values transform() traces with. Changes ⇒ the engine recompiles."""
        snap = []
        for method in QUANT_METHODS + PRUNE_METHODS:
            if method not in self.assignments or not self.scheduler._method_active(method):
                continue
            gsnap = []
            for name, gparams in self.assignments[method]:
                bits = self.scheduler.current_bits(gparams) \
                    if method == "weight_quantization" else int(gparams.get("bits", 8))
                gsnap.append((name, bits))
            snap.append((method, tuple(gsnap)))
        return tuple(snap)

    def maybe_freeze_masks(self, params) -> None:
        """Compute pruning masks once when each pruning method first activates
        (reference: enable_*_pruning computes the mask from live weights)."""
        pending = [m for m in PRUNE_METHODS
                   if not self._mask_frozen[m] and m in self.assignments
                   and self.scheduler._method_active(m)]
        if not pending:
            return
        leaves = dict(_leaf_paths(params))
        for method in pending:
            for name, gparams in self.assignments[method]:
                w = leaves[name]
                ratio = float(gparams.get("dense_ratio", 0.5))
                mth = gparams.get("method", "l1")
                if method == "sparse_pruning":
                    m = ops.sparse_mask(w, ratio, mth)
                elif method == "row_pruning":
                    m = ops.row_mask(w, ratio, mth)
                elif method == "head_pruning":
                    heads = int(gparams.get("num_heads", self.num_heads or 0))
                    if heads <= 0:
                        raise ValueError("head_pruning requires num_heads")
                    m = ops.head_mask(w, ratio, heads, mth)
                else:
                    m = ops.channel_mask(w, ratio, mth)
                self._masks[method][name] = jax.device_get(m)
            self._mask_frozen[method] = True
            logger.info(f"compression: froze {method} masks at step "
                        f"{self.scheduler.training_steps}")

    def state_dict(self) -> Dict[str, Any]:
        """Persistable compression state: frozen pruning masks + schedule step.
        Masks MUST survive resume — refreezing from restored (or worse, fresh
        random) weights would change the sparsity pattern mid-training."""
        return {
            "training_steps": self.scheduler.training_steps,
            "mask_frozen": dict(self._mask_frozen),
            "masks": {m: {k: jax.device_get(v) for k, v in d.items()}
                      for m, d in self._masks.items()},
        }

    def load_state_dict(self, state: Dict[str, Any]) -> None:
        self.scheduler.training_steps = int(state["training_steps"])
        self._mask_frozen = dict(state["mask_frozen"])
        self._masks = {m: dict(d) for m, d in state["masks"].items()}

    # -- traced transform --------------------------------------------------
    def transform(self, params):
        """Pure function applied to params inside the jitted loss. Uses the
        host-side schedule snapshot as static structure."""
        active = dict(self.schedule_key())
        if not active:
            return params
        leaves = dict(_leaf_paths(params))
        replaced: Dict[str, jnp.ndarray] = {}

        if "weight_quantization" in active:
            shared = self.config["weight_quantization"].get("shared_parameters", {})
            sym = shared.get("quantization_type", "symmetric") == "symmetric"
            for name, gparams in self.assignments.get("weight_quantization", []):
                bits = self.scheduler.current_bits(gparams)
                groups = int(gparams.get("quantize_groups", 1))
                w = replaced.get(name, leaves[name])
                replaced[name] = ops.quantize_weight(w, bits, symmetric=sym,
                                                     num_groups=groups)
        for method in PRUNE_METHODS:
            if method not in active:
                continue
            for name, _ in self.assignments.get(method, []):
                mask = self._masks[method].get(name)
                if mask is None:
                    continue  # activates on the step maybe_freeze_masks runs
                w = replaced.get(name, leaves[name])
                replaced[name] = w * jnp.asarray(mask, dtype=w.dtype)

        if not replaced:
            return params

        def sub(path, leaf):
            return replaced.get(_path_name(path), leaf)
        return jax.tree_util.tree_map_with_path(sub, params)

    def quantize_activations(self, x: jnp.ndarray, layer_name: str) -> jnp.ndarray:
        """For models that opt in per-layer (reference QuantAct usage): quantize
        iff ``layer_name`` matches a configured activation-quantization group's
        module patterns. No match (including an empty name) → unchanged."""
        active = dict(self.schedule_key())
        if "activation_quantization" not in active:
            return x
        shared = self.config["activation_quantization"].get("shared_parameters", {})
        sym = shared.get("quantization_type", "symmetric") == "symmetric"
        groups = self.config["activation_quantization"].get("different_groups", {})
        for _, group in sorted(groups.items()):
            for pattern in group.get("modules", [".*"]):
                if layer_name and re.search(pattern, layer_name):
                    gparams = {**shared, **group.get("params", {})}
                    return ops.quantize_activation(x, int(gparams.get("bits", 8)),
                                                   symmetric=sym)
        return x


def init_compression(params, config: Dict[str, Any],
                     teacher_params=None, num_heads: Optional[int] = None,
                     layer_map: Optional[Dict[int, int]] = None) -> "Compressor":
    """Build a Compressor (reference compress.py:100 init_compression). When the
    config enables layer_reduction, ``teacher_params`` + the layer mapping seed
    the student (reference student_initialization)."""
    comp_cfg = config.get(COMPRESSION_KEY, config) or {}
    lr_cfg = comp_cfg.get(LAYER_REDUCTION_KEY, {})
    if lr_cfg.get("enabled", False):
        if teacher_params is None:
            raise ValueError("layer_reduction requires teacher_params")
        params = student_initialization(params, teacher_params, lr_cfg,
                                        layer_map=layer_map)
    c = Compressor(params, comp_cfg, num_heads=num_heads)
    c.initialized_params = params
    return c


def student_initialization(student_params, teacher_params, lr_cfg: Dict[str, Any],
                           layer_map: Optional[Dict[int, int]] = None):
    """Copy selected teacher layers into the student (reference
    ``compress.py student_initialization``): ``teacher_layer[i]`` is the teacher
    layer index whose weights initialize student layer i. Layer indices are
    rewritten in leaf paths under ``module_name_prefix`` (e.g. 'layers/3/...').
    """
    prefix = lr_cfg.get("module_name_prefix", "layers")
    teacher_layers = lr_cfg.get("teacher_layer", [])
    mapping = layer_map or {i: int(t) for i, t in enumerate(teacher_layers)}
    teacher_leaves = dict(_leaf_paths(teacher_params))
    pat = re.compile(rf"(^|/){re.escape(prefix)}[_/](\d+)(/|$)")

    def pick(path, leaf):
        name = _path_name(path)
        m = pat.search(name)
        if m:
            student_idx = int(m.group(2))
            if student_idx in mapping:
                tname = name[:m.start(2)] + str(mapping[student_idx]) + name[m.end(2):]
                if tname in teacher_leaves:
                    return jnp.asarray(teacher_leaves[tname], dtype=leaf.dtype)
            return leaf
        # non-layer leaves (embeddings, final norm, head) copy straight across
        return jnp.asarray(teacher_leaves[name], dtype=leaf.dtype) \
            if name in teacher_leaves and teacher_leaves[name].shape == leaf.shape else leaf

    return jax.tree_util.tree_map_with_path(pick, student_params)


def redundancy_clean(params, compressor: "Compressor"):
    """Bake compression into the weights for export (reference
    ``compress.py redundancy_clean`` / per-layer ``fix_compression``): apply the
    final quantization + masks once, outside any STE."""
    baked = compressor.transform(params)
    return jax.tree.map(jax.lax.stop_gradient, baked)
