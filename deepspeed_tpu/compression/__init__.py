"""Compression suite (reference: ``deepspeed/compression/``).

Capability parity with the reference's QAT + pruning + layer-reduction stack,
re-designed functionally for TPU/XLA: the reference swaps ``nn.Linear`` for
mask-carrying ``LinearLayer_Compress`` modules; here compression is a *pure
transform over the params pytree* applied inside the jitted step —
``compressor.transform(params, step)`` fake-quantizes and masks the matched
leaves with straight-through gradients, and ``redundancy_clean`` bakes the
compression in at export time (reference ``fix_compression``).
"""

from deepspeed_tpu.compression.compress import (
    Compressor, init_compression, redundancy_clean, student_initialization)
from deepspeed_tpu.compression.scheduler import CompressionScheduler
from deepspeed_tpu.compression.ops import (
    quantize_weight, quantize_activation, sparse_mask, row_mask, head_mask,
    channel_mask)

__all__ = [
    "Compressor", "init_compression", "redundancy_clean", "student_initialization",
    "CompressionScheduler", "quantize_weight", "quantize_activation",
    "sparse_mask", "row_mask", "head_mask", "channel_mask",
]
