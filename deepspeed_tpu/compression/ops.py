"""Pure-JAX compression primitives: STE fake quantization + pruning masks.

Reference analog: ``deepspeed/compression/utils.py`` (SymQuantizer, AsymQuantizer,
TernaryQuantizer, BinaryQuantizer — autograd Functions with straight-through
backward) and the mask helpers inside ``basic_layer.py``. Here each quantizer is a
pure function; the straight-through estimator is ``w + stop_gradient(q(w) - w)``,
which XLA folds into the surrounding computation (no custom VJP needed).

Convention: weights are flax-style ``[in_features, out_features]`` — the *output*
feature axis is the last one, so "row pruning" (reference: torch weight rows =
output neurons) masks the last axis here, and head pruning groups the last axis
into ``num_heads`` blocks.
"""

from typing import Optional

import jax
import jax.numpy as jnp


def _ste(w: jnp.ndarray, q: jnp.ndarray) -> jnp.ndarray:
    """Straight-through estimator: forward q, gradient of identity."""
    return w + jax.lax.stop_gradient(q - w)


def _grouped(w: jnp.ndarray, num_groups: int):
    """Reshape to (num_groups, -1) for per-group scales (reference quantizers
    view(num_groups, -1))."""
    return w.reshape(num_groups, -1)


def quantize_weight(w: jnp.ndarray, bits: int, symmetric: bool = True,
                    num_groups: int = 1) -> jnp.ndarray:
    """Fake-quantize with STE. bits>=3 → uniform sym/asym; 2 → ternary; 1 → binary
    (reference utils.py quantizer dispatch in basic_layer.py:319)."""
    orig_shape = w.shape
    g = _grouped(w, num_groups)
    if bits == 1:
        # binary: sign(w) * E|w| per group (XNOR-style scaling)
        scale = jnp.mean(jnp.abs(g), axis=1, keepdims=True)
        q = jnp.sign(g) * scale
    elif bits == 2:
        # ternary: threshold 0.7*E|w|; kept values get the mean magnitude of kept
        thresh = 0.7 * jnp.mean(jnp.abs(g), axis=1, keepdims=True)
        mask = (jnp.abs(g) > thresh).astype(g.dtype)
        alpha = jnp.sum(jnp.abs(g) * mask, axis=1, keepdims=True) / \
            jnp.maximum(jnp.sum(mask, axis=1, keepdims=True), 1.0)
        q = jnp.sign(g) * alpha * mask
    elif symmetric:
        qmax = 2.0 ** (bits - 1) - 1
        scale = jnp.max(jnp.abs(g), axis=1, keepdims=True) / qmax
        scale = jnp.maximum(scale, 1e-10)
        q = jnp.round(g / scale).clip(-qmax - 1, qmax) * scale
    else:
        levels = 2.0 ** bits - 1
        lo = jnp.min(g, axis=1, keepdims=True)
        hi = jnp.max(g, axis=1, keepdims=True)
        scale = jnp.maximum(hi - lo, 1e-10) / levels
        q = jnp.round((g - lo) / scale).clip(0, levels) * scale + lo
    return _ste(w, q.reshape(orig_shape).astype(w.dtype))


def quantize_activation(x: jnp.ndarray, bits: int, symmetric: bool = True,
                        static_range: Optional[jnp.ndarray] = None) -> jnp.ndarray:
    """Activation fake-quant (reference QuantAct basic_layer.py:17). Dynamic range
    by default (per-tensor max of the current batch); ``static_range`` supplies a
    calibrated max instead."""
    if symmetric:
        qmax = 2.0 ** (bits - 1) - 1
        amax = jnp.max(jnp.abs(x)) if static_range is None else static_range
        scale = jnp.maximum(amax, 1e-10) / qmax
        q = jnp.round(x / scale).clip(-qmax - 1, qmax) * scale
    else:
        levels = 2.0 ** bits - 1
        lo = jnp.min(x) if static_range is None else -static_range
        hi = jnp.max(x) if static_range is None else static_range
        scale = jnp.maximum(hi - lo, 1e-10) / levels
        q = jnp.round((x - lo) / scale).clip(0, levels) * scale + lo
    return _ste(x, q.astype(x.dtype))


def sparse_mask(w: jnp.ndarray, dense_ratio: float, method: str = "l1") -> jnp.ndarray:
    """Unstructured magnitude mask keeping the top ``dense_ratio`` fraction
    (reference enable_sparse_pruning l1/topk)."""
    k = max(1, int(round(dense_ratio * w.size)))
    flat = jnp.abs(w).ravel()
    if method not in ("l1", "topk"):
        raise ValueError(f"unknown sparse pruning method {method!r}")
    thresh = jnp.sort(flat)[-k]
    return (jnp.abs(w) >= thresh).astype(w.dtype)


def row_mask(w: jnp.ndarray, dense_ratio: float, method: str = "l1") -> jnp.ndarray:
    """Structured mask over output features (last axis), scored by L1 norm
    (reference enable_row_pruning). Returns shape [..., out] broadcastable mask."""
    if method != "l1":
        raise ValueError(f"unknown row pruning method {method!r}")
    scores = jnp.sum(jnp.abs(w).reshape(-1, w.shape[-1]), axis=0)
    k = max(1, int(round(dense_ratio * w.shape[-1])))
    thresh = jnp.sort(scores)[-k]
    return (scores >= thresh).astype(w.dtype)


def head_mask(w: jnp.ndarray, dense_ratio: float, num_heads: int,
              method: str = "l1") -> jnp.ndarray:
    """Per-head mask over the output axis grouped into ``num_heads`` blocks
    (reference enable_head_pruning on attention output projections)."""
    if method != "l1":
        raise ValueError(f"unknown head pruning method {method!r}")
    out = w.shape[-1]
    if out % num_heads:
        raise ValueError(f"output dim {out} not divisible by num_heads {num_heads}")
    head_dim = out // num_heads
    scores = jnp.sum(jnp.abs(w).reshape(-1, num_heads, head_dim), axis=(0, 2))
    k = max(1, int(round(dense_ratio * num_heads)))
    thresh = jnp.sort(scores)[-k]
    keep = (scores >= thresh).astype(w.dtype)
    return jnp.repeat(keep, head_dim)


def channel_mask(w: jnp.ndarray, dense_ratio: float, method: str = "l1") -> jnp.ndarray:
    """Conv channel mask (reference enable_channel_pruning): scores over all axes
    but the output-channel axis (last, HWIO convention)."""
    if method != "l1":
        raise ValueError(f"unknown channel pruning method {method!r}")
    axes = tuple(range(w.ndim - 1))
    scores = jnp.sum(jnp.abs(w), axis=axes)
    k = max(1, int(round(dense_ratio * w.shape[-1])))
    thresh = jnp.sort(scores)[-k]
    return (scores >= thresh).astype(w.dtype)
