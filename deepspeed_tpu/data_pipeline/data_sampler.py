"""Curriculum-aware batch sampler.

Reference analog: ``deepspeed/runtime/data_pipeline/data_sampling/data_sampler.py:36``
(``DeepSpeedDataSampler``). Semantics preserved:

- one or more *metrics*, each a per-sample difficulty array plus its own
  ``CurriculumScheduler``;
- ``difficulty_type`` "value" (samples admitted when metric <= difficulty) or
  "percentile" (admitted when metric's percentile rank <= difficulty);
- per global batch: update every scheduler, intersect the admitted pools, draw the
  batch without replacement from the not-yet-consumed admitted pool (re-admitting
  everything once exhausted — an epoch within the current difficulty);
- deterministic under a seed, resumable via ``state_dict``.

The reference builds on-disk difficulty "clusters" with mmap files so multi-node
workers share them; on TPU hosts we hold the index arrays in host RAM (they are
tiny relative to the token data) and every process draws the same global batch
from the shared seed, slicing its own shard — same invariant as the reference's
``get_start_end_idx``.
"""

from typing import Any, Dict, Iterator, List, Optional

import numpy as np

from deepspeed_tpu.data_pipeline.curriculum_scheduler import CurriculumScheduler

DIFFICULTY_VALUE = "value"
DIFFICULTY_PERCENTILE = "percentile"


class CurriculumDataSampler:
    """Yields global batches of dataset indices honoring difficulty schedules."""

    def __init__(self,
                 metric_values: Dict[str, np.ndarray],
                 metric_configs: Dict[str, Dict[str, Any]],
                 total_samples: int,
                 global_batch_size: int,
                 seed: int = 1234,
                 drop_last: bool = True):
        self.total_samples = int(total_samples)
        self.global_batch_size = int(global_batch_size)
        self.seed = seed
        self.drop_last = drop_last
        self.global_step = 0
        self.consumed = np.zeros(self.total_samples, dtype=bool)

        self.schedulers: Dict[str, CurriculumScheduler] = {}
        self.difficulty_types: Dict[str, str] = {}
        self.values: Dict[str, np.ndarray] = {}
        self.percentiles: Dict[str, np.ndarray] = {}
        for name, cfg in metric_configs.items():
            vals = np.asarray(metric_values[name])
            if vals.shape[0] != self.total_samples:
                raise ValueError(f"metric '{name}' has {vals.shape[0]} values for "
                                 f"{self.total_samples} samples")
            self.schedulers[name] = CurriculumScheduler(cfg)
            dtype = cfg.get("difficulty_type", DIFFICULTY_VALUE)
            if dtype not in (DIFFICULTY_VALUE, DIFFICULTY_PERCENTILE):
                raise ValueError(f"unknown difficulty_type {dtype!r}")
            self.difficulty_types[name] = dtype
            self.values[name] = vals
            if dtype == DIFFICULTY_PERCENTILE:
                # percentile rank in [0, 100] of each sample's metric value
                order = np.argsort(vals, kind="stable")
                ranks = np.empty(self.total_samples, dtype=np.float64)
                ranks[order] = (np.arange(self.total_samples) + 1) / self.total_samples * 100.0
                self.percentiles[name] = ranks

    def _admitted_mask(self) -> np.ndarray:
        # cache keyed on the difficulty tuple: quantized schedules hold a level for
        # many steps, and a full-corpus comparison per step would dominate input
        # latency (the reference builds on-disk clusters once per level for the
        # same reason)
        key = tuple(s.get_current_difficulty() for s in self.schedulers.values())
        if getattr(self, "_mask_key", None) == key:
            return self._mask_cache
        mask = np.ones(self.total_samples, dtype=bool)
        for name, sched in self.schedulers.items():
            diff = sched.get_current_difficulty()
            if self.difficulty_types[name] == DIFFICULTY_VALUE:
                mask &= self.values[name] <= diff
            else:
                mask &= self.percentiles[name] <= diff
        self._mask_key, self._mask_cache = key, mask
        return mask

    def get_next_global_batch(self) -> np.ndarray:
        """One global batch of sample indices at the current step's difficulty."""
        for sched in self.schedulers.values():
            sched.update_difficulty(self.global_step)
        admitted = self._admitted_mask()
        if not bool(admitted.any()):
            # Degenerate config (min difficulty below every sample): admit all, like
            # the reference's fallback to the first cluster.
            admitted = np.ones(self.total_samples, dtype=bool)
        pool = np.flatnonzero(admitted & ~self.consumed)
        rng = np.random.default_rng(self.seed + self.global_step)
        batch: List[np.ndarray] = []
        need = self.global_batch_size
        while need > 0:
            if pool.size == 0:
                # difficulty-epoch boundary: everything admitted becomes fresh
                # again — except indices already drawn into THIS batch, so a
                # global batch never contains duplicates
                self.consumed[admitted] = False
                pool = np.flatnonzero(admitted)
                if batch:
                    drawn = np.concatenate(batch)
                    self.consumed[drawn] = True
                    pool = np.setdiff1d(pool, drawn, assume_unique=False)
                    if pool.size == 0:
                        # batch larger than the admitted pool: duplicates are
                        # unavoidable, fall back to the full pool
                        pool = np.flatnonzero(admitted)
            take = min(need, pool.size)
            chosen = rng.choice(pool, size=take, replace=False)
            self.consumed[chosen] = True
            batch.append(chosen)
            pool = np.setdiff1d(pool, chosen, assume_unique=False)
            need -= take
        self.global_step += 1
        return np.concatenate(batch)

    def __iter__(self) -> Iterator[np.ndarray]:
        while True:
            yield self.get_next_global_batch()

    def get_start_end_idx(self, process_index: int, process_count: int,
                          batch_len: Optional[int] = None):
        """Each process's contiguous slice of the global batch (reference
        ``data_sampler.py:122``). Rounded boundaries so the slices cover the whole
        batch even when it doesn't divide evenly."""
        n = batch_len if batch_len is not None else self.global_batch_size
        start = (process_index * n + process_count - 1) // process_count
        end = ((process_index + 1) * n + process_count - 1) // process_count
        return start, end

    def state_dict(self) -> Dict[str, Any]:
        return {
            "global_step": self.global_step,
            "consumed": self.consumed.copy(),
            "seed": self.seed,
            "schedulers": {k: s.state_dict() for k, s in self.schedulers.items()},
        }

    def load_state_dict(self, state: Dict[str, Any]) -> None:
        self.global_step = state["global_step"]
        self.consumed = np.asarray(state["consumed"]).copy()
        self.seed = state["seed"]
        for k, s in state["schedulers"].items():
            self.schedulers[k].load_state_dict(s)
