"""Curriculum-learning difficulty scheduler.

Reference analog: ``deepspeed/runtime/data_pipeline/curriculum_scheduler.py:11``
(``CurriculumScheduler``). Same JSON schema and schedule families:

- ``fixed_linear``   — difficulty grows linearly from min to max over
  ``total_curriculum_step`` steps, quantized to ``difficulty_step``.
- ``fixed_root``     — grows as ``(step/total)^(1/root_degree)``.
- ``fixed_discrete`` — explicit ``difficulty`` list with ``max_step`` boundaries.
- ``custom``         — user-supplied ``fn(global_step) -> difficulty``.

On TPU, ``difficulty_step`` quantization matters for a different reason than the
reference's tensor-core alignment: when difficulty is a sequence length, every
distinct value is a distinct XLA program — coarse steps bound recompilation.
"""

import math
from typing import Any, Callable, Dict, Optional

FIXED_LINEAR = "fixed_linear"
FIXED_ROOT = "fixed_root"
FIXED_DISCRETE = "fixed_discrete"
CUSTOM = "custom"


class CurriculumScheduler:
    """Stateful difficulty schedule keyed by global step."""

    def __init__(self, config: Dict[str, Any]):
        self.schedule_type: str = config["schedule_type"]
        self.min_difficulty: int = int(config.get("min_difficulty", 1))
        self.max_difficulty: int = int(config.get("max_difficulty", self.min_difficulty))
        self.current_difficulty: int = self.min_difficulty
        self.schedule_config: Dict[str, Any] = dict(config.get("schedule_config", {}))
        self.custom_get_difficulty: Optional[Callable[[int], int]] = None

        if self.schedule_type == FIXED_DISCRETE:
            diffs = self.schedule_config["difficulty"]
            steps = self.schedule_config["max_step"]
            if len(diffs) != len(steps) + 1:
                raise ValueError(
                    "fixed_discrete needs len(difficulty) == len(max_step)+1 "
                    f"(got {len(diffs)} vs {len(steps)})")
        elif self.schedule_type in (FIXED_LINEAR, FIXED_ROOT):
            if "total_curriculum_step" not in self.schedule_config:
                raise ValueError(f"{self.schedule_type} needs 'total_curriculum_step'")
            self.schedule_config.setdefault("difficulty_step", 8)
            if self.schedule_type == FIXED_ROOT:
                self.schedule_config.setdefault("root_degree", 2)
        elif self.schedule_type != CUSTOM:
            raise ValueError(f"unknown curriculum schedule_type {self.schedule_type!r}")

    def set_custom_get_difficulty(self, fn: Callable[[int], int]) -> None:
        self.custom_get_difficulty = fn

    def _root_difficulty(self, global_step: int, degree: float) -> int:
        sc = self.schedule_config
        frac = min(1.0, max(0.0, global_step / sc["total_curriculum_step"]))
        if frac >= 1.0:
            # exact max at completion even when it isn't a multiple of the step
            return self.max_difficulty
        raw = self.min_difficulty + (self.max_difficulty - self.min_difficulty) * \
            (frac ** (1.0 / degree))
        dstep = sc["difficulty_step"]
        quantized = int(math.floor(raw / dstep)) * dstep
        return min(self.max_difficulty, max(self.min_difficulty, quantized))

    def get_difficulty(self, global_step: int) -> int:
        if self.schedule_type == FIXED_LINEAR:
            return self._root_difficulty(global_step, 1.0)
        if self.schedule_type == FIXED_ROOT:
            return self._root_difficulty(global_step, self.schedule_config["root_degree"])
        if self.schedule_type == FIXED_DISCRETE:
            diffs = self.schedule_config["difficulty"]
            for d, boundary in zip(diffs, self.schedule_config["max_step"]):
                if global_step < boundary:
                    return d
            return diffs[-1]
        if self.custom_get_difficulty is None:
            raise RuntimeError("custom schedule requires set_custom_get_difficulty()")
        return self.custom_get_difficulty(global_step)

    def update_difficulty(self, global_step: int) -> int:
        self.current_difficulty = self.get_difficulty(global_step)
        return self.current_difficulty

    def get_current_difficulty(self) -> int:
        return self.current_difficulty

    def state_dict(self) -> Dict[str, Any]:
        return {"current_difficulty": self.current_difficulty}

    def load_state_dict(self, state: Dict[str, Any]) -> None:
        self.current_difficulty = state["current_difficulty"]
