"""Random layerwise token dropping (random-LTD).

Reference analog: ``deepspeed/runtime/data_pipeline/data_routing/``
(``RandomLayerTokenDrop`` basic_layer.py:14, ``RandomLTDScheduler`` scheduler.py:38,
token gather/scatter CUDA kernels ``csrc/random_ltd/``). Capability: during
training, each wrapped transformer layer processes only a random subset of
``reserved_length`` tokens; the rest skip the layer (identity). The reserved
length anneals from ``min_value`` to ``max_value``, cutting layer FLOPs early in
training.

TPU-native design: instead of CUDA gather/scatter kernels + autograd Functions,
the drop is expressed functionally (``jnp.take_along_axis`` gather, ``.at[].set``
scatter) inside the jitted step — XLA fuses these into cheap dynamic-slice ops.
``reserved_length`` is a *static* shape under jit, so each distinct value compiles
once; the scheduler quantizes to ``difficulty_step`` (via the shared fixed-linear /
fixed-root schedule math) to bound the number of compiles. For decoder models the
sampled indices are sorted to preserve causal order (reference
``gpt_sample_tokens``).
"""

from typing import Any, Callable, Dict

import jax
import jax.numpy as jnp

from deepspeed_tpu.data_pipeline.curriculum_scheduler import CurriculumScheduler


class RandomLTDScheduler:
    """Anneals reserved token count; tracks consumed layer-tokens.

    Reference: ``data_routing/scheduler.py:38``. The schedule reuses the
    curriculum fixed_linear/fixed_root math (the reference duplicates it in
    ``BaseScheduler``).
    """

    def __init__(self, config: Dict[str, Any]):
        self.model_layer_num = int(config["total_layer_num"])
        self.random_ltd_layer_num = int(config["random_ltd_layer_num"])
        self.global_batch_size = int(config.get("global_batch_size", 1))
        sched = config["random_ltd_schedule"]
        self._curriculum = CurriculumScheduler({
            "schedule_type": sched.get("schedule_type", "fixed_linear"),
            "min_difficulty": sched["min_value"],
            "max_difficulty": sched["max_value"],
            "schedule_config": sched.get("schedule_config", {}),
        })
        self.min_value = int(sched["min_value"])
        self.max_value = int(sched["max_value"])
        self.current_value = self.min_value
        self.consumed_layer_tokens = 0
        self.curr_step = -1

    def get_current_seq(self) -> int:
        return self.current_value

    def update_seq(self, global_step: int) -> int:
        if self.current_value < self.max_value:
            self.current_value = self._curriculum.update_difficulty(global_step)
        if global_step != self.curr_step:
            # layer-token accounting (reference scheduler.py:85): dropped layers see
            # current_value tokens, the rest see the full sequence
            self.consumed_layer_tokens += self.global_batch_size * (
                self.current_value * self.random_ltd_layer_num
                + self.max_value * (self.model_layer_num - self.random_ltd_layer_num))
            self.curr_step = global_step
        return self.current_value

    def get_total_layer_tokens(self, train_iters: int) -> int:
        """Projection of layer-tokens over ``train_iters`` steps; pure query (the
        live schedule state is untouched)."""
        total, value = 0, self.min_value
        for step in range(train_iters):
            if value < self.max_value:
                value = self._curriculum.get_difficulty(step)
            total += self.global_batch_size * (
                value * self.random_ltd_layer_num
                + self.max_value * (self.model_layer_num - self.random_ltd_layer_num))
        return total

    def state_dict(self) -> Dict[str, Any]:
        return {"current_value": self.current_value, "curr_step": self.curr_step,
                "consumed_layer_tokens": self.consumed_layer_tokens,
                "min_value": self.min_value, "max_value": self.max_value}

    def load_state_dict(self, state: Dict[str, Any]) -> None:
        self.current_value = state["current_value"]
        self.curr_step = state["curr_step"]
        self.consumed_layer_tokens = state["consumed_layer_tokens"]
        self.min_value = state["min_value"]
        self.max_value = state["max_value"]


def sample_token_indices(rng: jax.Array, batch: int, seq_len: int,
                         reserved: int, decoder: bool = True) -> jnp.ndarray:
    """Per-example random subset of ``reserved`` token positions.

    Reference: ``data_routing/helper.py`` ``gpt_sample_tokens``/``bert_sample_tokens``
    (backed by ``csrc/random_ltd/token_sort.cu``). Decoder models sort indices so
    relative causal order is preserved.
    """
    keys = jax.random.split(rng, batch)
    idx = jax.vmap(
        lambda k: jax.random.permutation(k, seq_len)[:reserved])(keys)
    if decoder:
        idx = jnp.sort(idx, axis=-1)
    return idx  # [batch, reserved] int32


def gather_tokens(hidden: jnp.ndarray, indices: jnp.ndarray) -> jnp.ndarray:
    """[B,S,H] x [B,R] -> [B,R,H] (reference GatherTokens / gather_scatter.cu)."""
    return jnp.take_along_axis(hidden, indices[..., None], axis=1)


def scatter_tokens(hidden: jnp.ndarray, part: jnp.ndarray,
                   indices: jnp.ndarray) -> jnp.ndarray:
    """Write [B,R,H] back into [B,S,H] at ``indices`` (reference ScatterTokens)."""
    batch = jnp.arange(hidden.shape[0])[:, None]
    return hidden.at[batch, indices].set(part)


def random_ltd_layer(layer_fn: Callable, hidden: jnp.ndarray, rng: jax.Array,
                     reserved: int, decoder: bool = True,
                     indices: jnp.ndarray = None) -> jnp.ndarray:
    """Run ``layer_fn`` on a random token subset; other tokens pass through.

    ``reserved`` must be a Python int (static under jit). When ``indices`` is
    given, reuse it (the reference samples once at layer 0 and shares indices
    across all LTD layers).
    """
    b, s, _ = hidden.shape
    if reserved >= s:
        return layer_fn(hidden)
    if indices is None:
        indices = sample_token_indices(rng, b, s, reserved, decoder=decoder)
    part = gather_tokens(hidden, indices)
    out = layer_fn(part)
    return scatter_tokens(hidden, out, indices)
