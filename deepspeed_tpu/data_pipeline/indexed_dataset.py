"""Memory-mapped indexed token dataset.

Reference analog: ``deepspeed/runtime/data_pipeline/data_sampling/indexed_dataset.py``
(the Megatron-style ``MMapIndexedDataset``). Same capability — a two-file format
(``.bin`` raw token stream + ``.idx`` sizes/offsets) read through ``np.memmap``
so billion-token corpora load lazily — with a simplified index layout:

``<prefix>.idx`` (little-endian)::

    magic     8 bytes  b"DSTPUIDX"
    version   u32      1
    dtype     u32      numpy type code (see _DTYPES)
    count     u64      number of sequences
    sizes     u32[count]
    offsets   u64[count]   element (not byte) offset of each sequence

``<prefix>.bin``: the concatenated token sequences, dtype as recorded.
"""

import os
import struct
from typing import Sequence, Union

import numpy as np

_MAGIC = b"DSTPUIDX"
_VERSION = 1
_DTYPES = {1: np.uint8, 2: np.int8, 3: np.int16, 4: np.int32,
           5: np.int64, 6: np.float32, 7: np.float64, 8: np.uint16, 9: np.uint32}
_DTYPE_CODES = {np.dtype(v): k for k, v in _DTYPES.items()}


def data_file_path(prefix: str) -> str:
    return prefix + ".bin"


def index_file_path(prefix: str) -> str:
    return prefix + ".idx"


class MMapIndexedDatasetBuilder:
    """Streaming writer: ``add_item`` sequences, then ``finalize``."""

    def __init__(self, prefix: str, dtype: Union[type, np.dtype] = np.int32):
        self.prefix = prefix
        self.dtype = np.dtype(dtype)
        if self.dtype not in _DTYPE_CODES:
            raise TypeError(f"unsupported dtype {dtype}")
        self._data = open(data_file_path(prefix), "wb")
        self._sizes = []
        self._offsets = []
        self._elements = 0

    def add_item(self, tokens: Sequence) -> None:
        arr = np.asarray(tokens, dtype=self.dtype)
        self._data.write(arr.tobytes(order="C"))
        self._sizes.append(arr.size)
        self._offsets.append(self._elements)
        self._elements += arr.size

    def merge_file(self, other_prefix: str) -> None:
        """Append another dataset with the same dtype (reference builder's
        ``merge_file_``): block-copy the raw ``.bin`` and shift the index — no
        per-sequence Python loop."""
        other = MMapIndexedDataset(other_prefix)
        if other.dtype != self.dtype:
            raise TypeError(f"dtype mismatch: {other.dtype} vs {self.dtype}")
        base = self._elements
        self._sizes.extend(int(s) for s in other.sizes)
        self._offsets.extend(base + int(o) for o in other.offsets)
        self._elements += int(other._bin.size)
        del other  # close the memmap before streaming the raw bytes
        with open(data_file_path(other_prefix), "rb") as src:
            while True:
                chunk = src.read(1 << 24)
                if not chunk:
                    break
                self._data.write(chunk)

    def finalize(self) -> None:
        self._data.close()
        with open(index_file_path(self.prefix), "wb") as idx:
            idx.write(_MAGIC)
            idx.write(struct.pack("<II", _VERSION, _DTYPE_CODES[self.dtype]))
            idx.write(struct.pack("<Q", len(self._sizes)))
            idx.write(np.asarray(self._sizes, dtype=np.uint32).tobytes())
            idx.write(np.asarray(self._offsets, dtype=np.uint64).tobytes())


class MMapIndexedDataset:
    """Lazy reader; ``ds[i]`` returns sequence i as a numpy view."""

    def __init__(self, prefix: str):
        self.prefix = prefix
        with open(index_file_path(prefix), "rb") as f:
            if f.read(8) != _MAGIC:
                raise ValueError(f"{index_file_path(prefix)}: bad magic")
            version, dtype_code = struct.unpack("<II", f.read(8))
            if version != _VERSION:
                raise ValueError(f"unsupported index version {version}")
            self.dtype = np.dtype(_DTYPES[dtype_code])
            (count,) = struct.unpack("<Q", f.read(8))
            self.sizes = np.frombuffer(f.read(4 * count), dtype=np.uint32)
            self.offsets = np.frombuffer(f.read(8 * count), dtype=np.uint64)
        self._bin = np.memmap(data_file_path(prefix), dtype=self.dtype, mode="r")

    def __len__(self) -> int:
        return len(self.sizes)

    def __getitem__(self, i: int) -> np.ndarray:
        if isinstance(i, slice):
            return [self[j] for j in range(*i.indices(len(self)))]
        off, size = int(self.offsets[i]), int(self.sizes[i])
        return self._bin[off:off + size]

    def get(self, i: int, offset: int = 0, length: int = None) -> np.ndarray:
        seq = self[i]
        return seq[offset:offset + length if length is not None else None]

    @staticmethod
    def exists(prefix: str) -> bool:
        return (os.path.exists(index_file_path(prefix))
                and os.path.exists(data_file_path(prefix)))
