"""Data-efficiency suite (reference: ``deepspeed/runtime/data_pipeline/``).

Two halves, mirroring the reference split:
- **data sampling** — curriculum learning: a difficulty scheduler
  (``curriculum_scheduler.py``) driving a difficulty-aware batch sampler
  (``data_sampler.py``), plus the offline metric analyzer (``data_analyzer.py``)
  and an mmap token dataset (``indexed_dataset.py``).
- **data routing** — random layerwise token dropping (random-LTD,
  ``random_ltd.py``): per-layer token subsampling with a token-budget schedule.
"""

from deepspeed_tpu.data_pipeline.curriculum_scheduler import CurriculumScheduler
from deepspeed_tpu.data_pipeline.data_sampler import CurriculumDataSampler
from deepspeed_tpu.data_pipeline.data_analyzer import DataAnalyzer
from deepspeed_tpu.data_pipeline.indexed_dataset import (
    MMapIndexedDataset, MMapIndexedDatasetBuilder)
from deepspeed_tpu.data_pipeline.random_ltd import (
    RandomLTDScheduler, gather_tokens, sample_token_indices, scatter_tokens,
    random_ltd_layer)

__all__ = [
    "CurriculumScheduler", "CurriculumDataSampler", "DataAnalyzer",
    "MMapIndexedDataset", "MMapIndexedDatasetBuilder", "RandomLTDScheduler",
    "gather_tokens", "scatter_tokens", "sample_token_indices", "random_ltd_layer",
]
from deepspeed_tpu.data_pipeline.packing import (packing_efficiency,  # noqa: F401,E501
                                                 pack_sequences)
